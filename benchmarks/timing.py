"""Device-true timing for the axon-tunnelled TPU.

`jax.block_until_ready` does not synchronise through the axon loopback
relay (a 8192^3 matmul appears to run at 30 PFLOP/s), so wall-clock
around dispatches measures nothing.  The only reliable fence is a
device->host transfer.  This harness chains ``iters`` applications of
the op inside one jitted `lax.scan`, fetches a single scalar, and
subtracts the 1-iteration run to cancel the tunnel round-trip and
dispatch overhead:

    per_iter = (t(iters) - t(1)) / (iters - 1)
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp


def time_op(step_fn, x0, iters: int = 64, repeats: int = 3) -> float:
    """Median per-iteration seconds of ``step_fn`` (x -> x-like).

    Adaptive: if the chained run is not clearly above the 1-iteration
    baseline (per-iter cost below the tunnel's ms-scale jitter), the
    chain length is grown until it is, so sub-0.1 ms ops still resolve.
    """
    iters = max(iters, 2)  # the t(1) subtraction needs iters - 1 >= 1

    def chained(n):
        def body(c, _):
            return step_fn(c), None

        # sum the FULL carry: slicing it lets XLA narrow the whole
        # loop's dependency cone to the sliced elements for
        # elementwise bodies, timing nothing
        f = jax.jit(lambda x: jnp.sum(
            jnp.abs(jax.lax.scan(body, x, None, length=n)[0])))
        float(f(x0))  # compile + warm
        best = float("inf")
        for _ in range(repeats):
            t0 = time.perf_counter()
            float(f(x0))
            best = min(best, time.perf_counter() - t0)
        return best

    t1 = chained(1)
    for attempt in range(7):
        tn = chained(iters)
        if tn - t1 > max(0.5 * t1, 5e-3) or attempt == 6:
            break  # clearly above jitter (or give up at this length)
        iters *= 4
    return max(tn - t1, 1e-12) / (iters - 1)
