"""Production-scale end-to-end benchmark (VERDICT r1 item 2).

SURVEY-scale workload: 2^23 samples x 1024 channels x 8-bit, 500 DM
trials, >= 20 acceleration trials per DM — the scale the reference
handles through libdedisp + per-GPU streaming
(`src/pipeline_multi.cu:145-157`, `include/transforms/dedisperser.hpp:104-112`)
— run through the bounded-HBM chunked mesh search on one real chip.

A synthetic pulsar (P=7.7 ms, DM=300) is injected so the run also
validates end-to-end recovery at scale, not just wall-clock.

Writes benchmarks/production_bench.json with the stage timers and a
micro-benchmark-derived device-time model for the roofline comparison.

Run on the real chip:  python benchmarks/production.py [--quick]
(--quick drops to 2^21 samples / 128 DMs for a fast smoke pass.)
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

DISPERSION_MS = 4.148808e6  # ms; DM * (f_MHz^-2 - f_ref^-2) scaling


def make_filterbank(nsamps, nchans, tsamp, fch1, foff,
                    period_s, dm, amp, seed=0):
    """Build a synthetic 8-bit filterbank with a dispersed pulse train,
    generated in bounded-memory chunks (kept in RAM: writing + reading
    an 8.6 GB file would only time the disk)."""
    from peasoup_tpu.io.sigproc import Filterbank, SigprocHeader

    rng = np.random.default_rng(seed)
    freqs = fch1 + foff * np.arange(nchans)
    # dispersion delay per channel relative to fch1, in samples
    delay_s = (DISPERSION_MS / 1e3) * dm * (freqs ** -2 - fch1 ** -2)
    delay_samp = np.round(delay_s / tsamp).astype(np.int64)
    data = np.empty((nsamps, nchans), np.uint8)
    chunk = 1 << 21
    for s0 in range(0, nsamps, chunk):
        s1 = min(s0 + chunk, nsamps)
        data[s0:s1] = rng.integers(0, 64, size=(s1 - s0, nchans),
                                   dtype=np.uint8)
    # pulse train: one-sample pulses at t = n*P + channel delay
    npulses = int(nsamps * tsamp / period_s) + 2
    base = np.round(np.arange(npulses) * period_s / tsamp).astype(np.int64)
    for c in range(nchans):
        idx = base + delay_samp[c]
        idx = idx[(idx >= 0) & (idx < nsamps)]
        col = data[idx, c].astype(np.int64) + amp
        data[idx, c] = np.minimum(col, 255).astype(np.uint8)
    hdr = SigprocHeader()
    hdr.source_name = "SYNTH_PROD"
    hdr.data_type = 1
    hdr.nchans = nchans
    hdr.nbits = 8
    hdr.tsamp = tsamp
    hdr.fch1 = fch1
    hdr.foff = foff
    hdr.nifs = 1
    hdr.tstart = 60000.0
    hdr.nsamples = nsamps
    return Filterbank(header=hdr, data=data)


def main(argv=None):
    args = argv if argv is not None else sys.argv[1:]
    quick = "--quick" in args

    nsamps = (1 << 21) + 6000 if quick else (1 << 23) + 18000
    nchans = 256 if quick else 1024
    ndm = 128 if quick else 500
    tsamp = 6.4e-5
    fch1, foff = 1500.0, -0.29296875  # 300 MHz band
    # amp 6/chan over 1024 chans is still a blazing detection (coherent
    # over channels) without flooding the peak buffers the way a
    # 30/chan signal does
    period_s, dm_inj, amp = 0.0077, 300.0, 6

    t0 = time.time()
    fil = make_filterbank(nsamps, nchans, tsamp, fch1, foff,
                          period_s, dm_inj, amp)
    t_gen = time.time() - t0
    print(f"generated {fil.data.nbytes/1e9:.2f} GB filterbank in {t_gen:.0f}s")
    t_read = 0.0

    from peasoup_tpu.search.plan import SearchConfig
    from peasoup_tpu.parallel.mesh import MeshPulsarSearch

    # At tobs ~537 s the tolerance-stepped accel grid would hold 68k
    # trials per DM at +-500 m/s^2 (step ~0.0015); the benchmark uses a
    # fixed 21-trial grid over the full +-500 range instead — the
    # VERDICT-prescribed >=20 trials, at accelerations that exercise
    # the high-shift resample tables (max_shift ~940).
    naccel = 21
    acc_max = 500.0
    cfg = SearchConfig(
        dm_list=np.linspace(0.0, 600.0, ndm).astype(np.float32),
        acc_start=-acc_max, acc_end=acc_max,
        npdmp=10, limit=1000, verbose=True,
        compact_capacity=1 << 22,
        # tunnel stalls can wedge a multi-minute run (observed: a
        # chunk fetch hanging indefinitely mid-benchmark); per-chunk
        # checkpointing makes a kill+rerun resume instead of restart
        checkpoint_file=os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            f"prod_ckpt{'_quick' if quick else ''}.jsonl"),
        checkpoint_interval=1,
        # persistent buffer tuning (search/tuning.py): run 1 observes
        # the true peak-count high-waters; run 2+ sizes buffers so no
        # row clips (re-search phase disappears) and transfers shrink.
        # The emitted JSON records whether this run was tuned.
        tune_file=os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            f"prod_tune{'_quick' if quick else ''}.json"),
    )
    from peasoup_tpu.utils import enable_compile_cache

    enable_compile_cache()

    t0 = time.time()
    search = MeshPulsarSearch(fil, cfg, max_devices=1)

    # artifact flags are KEY-VALIDATED, not existence-checked: a stale
    # sidecar from a different benchmark config is ignored by the
    # search and must not mislabel this run as tuned/resumed.  The
    # checkpoint is probed with the REAL loader (same key + row + torn-
    # tail validation the resume itself applies).
    from peasoup_tpu.search.checkpoint import SearchCheckpoint, search_key
    from peasoup_tpu.search.tuning import load_tuning

    tuned = load_tuning(
        cfg.tune_file, search._tune_scoped_key("chunked")) is not None
    resumed_rows = 0
    if os.path.exists(cfg.checkpoint_file):
        import warnings as _w

        with _w.catch_warnings():
            _w.simplefilter("ignore")
            done = SearchCheckpoint(
                cfg.checkpoint_file,
                search_key(cfg.infilename, fil, cfg)).load()
        resumed_rows = len(done or {})
        if resumed_rows:
            print(f"NOTE: resuming from checkpoint with "
                  f"{resumed_rows} completed rows; timings cover the "
                  f"residual work only (delete {cfg.checkpoint_file} "
                  f"for a fresh capture)")

    class _FixedAccelPlan:
        def __init__(self, accs):
            self._accs = np.asarray(accs, np.float32)

        def generate_accel_list(self, dm):
            return self._accs.copy()

    search.acc_plan = _FixedAccelPlan(
        np.linspace(-acc_max, acc_max, naccel))
    acc0 = search.acc_plan.generate_accel_list(0.0)
    print(f"size={search.size} ndm={len(search.dm_list)} "
          f"naccel(dm=0)={len(acc0)} max_shift={search.max_shift} "
          f"block={search.resample_block}")
    result = search.run()
    t_search = time.time() - t0

    cands = result.candidates.cands
    hit = None
    for c in cands:
        if abs(c.freq - 1.0 / period_s) < 0.01 and abs(c.dm - dm_inj) < 20:
            hit = c
            break
    print(f"wall: gen {t_gen:.0f}s  read {t_read:.0f}s  "
          f"search+fold {t_search:.0f}s")
    print("timers:", {k: round(v, 2) for k, v in result.timers.items()})
    if hit:
        print(f"RECOVERED: P={1.0/hit.freq*1e3:.4f} ms DM={hit.dm:.1f} "
              f"snr={hit.snr:.1f} folded={hit.folded_snr:.1f}")
    else:
        top = max(cands, key=lambda c: c.snr) if cands else None
        print(f"NOT RECOVERED; top cand: {top!r}")

    # device-time model from the committed micro numbers (ms/trial):
    # per accel trial = resample(tables) + rfft + interbin + hsum +
    # peaks; per DM trial = whiten rfft+irfft + median chain
    micro_path = os.path.join(os.path.dirname(__file__),
                              "micro_results.json")
    model = None
    if os.path.exists(micro_path) and not quick:
        micro = {r["metric"]: r["value"]
                 for r in json.load(open(micro_path))["results"]}
        acc_lists = [search.acc_plan.generate_accel_list(float(d))
                     for d in search.dm_list]
        n_trials = sum(len(a) for a in acc_lists)
        # hsum/peaks at the SIZE the search actually runs them (2^22
        # spectrum bins for a 2^23-sample series), re-measured r5 on
        # v5e: fused Pallas harmonic sum 1.52 ms, by-value exact
        # two-stage peak extraction 2.71 ms across the 5 levels at
        # cap=1024 (1.07 ms at cap=320)
        per_accel = (micro.get("resample2_tables_2e23_accel500", 0)
                     + micro.get("fft_r2c_2e23", 0) + 1.52 + 2.71)
        per_dm = micro.get("fft_r2c_c2r_2e23_roundtrip", 0) + 2.0
        # whole-pipeline terms the per-trial sums omit: the Pallas
        # dedispersion sweep (VPU-bound, ~0.7 s per 9-row chunk at
        # 2^23 x 1024 chans) and shipping each chunk's packed peak
        # buffer over the ~35 MB/s tunnel
        plan = getattr(search, "_chunk_plan", None)
        dedisp_s = transfer_s = 0.0
        if plan:
            n_chunks = -(-ndm // plan["dm_chunk"])
            # the Pallas dedisp kernel is VPU-bound: ~78 ms per DM row
            # at 2^23 x 1024 chans (0.7 s per 9-row chunk measured),
            # i.e. proportional to rows, independent of chunking
            dedisp_s = 0.078 * ndm * (nsamps / (1 << 23)) * (nchans / 1024)
            nspec = (plan["dm_chunk"] * plan["namax_p"]
                     * (cfg.nharmonics + 1))
            _, ckq = getattr(
                search, "_chunk_buffer_shapes",
                (cfg.peak_capacity, nspec * cfg.peak_capacity))
            # packed layout: 3*compact_k + 4*nspec + 2 f32 per shard
            transfer_s = n_chunks * ((3 * ckq + 4 * nspec) * 4) / 35e6
        model = {
            "n_accel_trials": n_trials,
            "per_accel_trial_ms": round(per_accel, 2),
            "per_dm_trial_ms": round(per_dm, 2),
            "dedisp_model_s": round(dedisp_s, 1),
            "transfer_model_s": round(transfer_s, 1),
            "device_model_s": round(
                (n_trials * per_accel + len(search.dm_list) * per_dm)
                / 1e3 + dedisp_s + transfer_s, 1),
        }
        # VERDICT r2 item 2: the wall/model gap must be attributable —
        # the chunk phases (upload/compile/fetch/decode/distill/
        # research) in timers_s are the breakdown; summarise the ratio
        # both ways (the h2d upload and remote XLA compile are
        # tunnel/relay costs a local TPU deployment would not pay)
        t = result.timers
        steady = (t.get("chunk_fetch", 0.0) + t.get("chunk_dispatch", 0.0)
                  + t.get("chunk_decode", 0.0) + t.get("chunk_distill", 0.0)
                  + t.get("chunk_research", 0.0))
        model["vs_model_total"] = round(
            t["searching_device"] / model["device_model_s"], 2)
        model["vs_model_excl_upload_compile"] = round(
            steady / model["device_model_s"], 2)
        print("device-time model:", model)

    out = {
        "config": {"nsamps": nsamps, "nchans": nchans, "ndm": ndm,
                   "acc_range": [-acc_max, acc_max], "naccel": naccel,
                   "tsamp": tsamp,
                   "nbits": 8, "quick": quick,
                   "injected": {"period_s": period_s, "dm": dm_inj}},
        "resumed": resumed_rows > 0,
        "resumed_rows": resumed_rows,
        "tuned": tuned,
        "device": None,
        "wall_s": {"generate": round(t_gen, 1), "read": round(t_read, 1),
                   "search_total": round(t_search, 1)},
        "timers_s": {k: round(v, 2) for k, v in result.timers.items()},
        "recovered": None if hit is None else {
            "period_ms": round(1.0 / hit.freq * 1e3, 4),
            "dm": round(hit.dm, 1), "snr": round(hit.snr, 1),
            "folded_snr": round(float(hit.folded_snr or 0), 1)},
        "model": model,
    }
    import jax

    out["device"] = str(jax.devices()[0])
    suffix = "_quick" if quick else ""
    path = os.path.join(os.path.dirname(__file__),
                        f"production_bench{suffix}.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(f"wrote {path}")
    # same-schema ledger record as bench.py/micro.py (the ad-hoc
    # production_bench*.json above keeps its shape unchanged)
    from peasoup_tpu.obs.history import append_history, make_history_record
    from peasoup_tpu.obs.metrics import REGISTRY

    metrics = {"search_total_s": round(t_search, 2),
               "generate_s": round(t_gen, 2)}
    if hit is not None:
        metrics["recovered_snr"] = round(float(hit.snr), 2)
        metrics["recovered_folded_snr"] = round(
            float(hit.folded_snr or 0.0), 2)
    from peasoup_tpu.obs.history import stage_device_seconds

    append_history(make_history_record(
        "production" + ("_quick" if quick else ""),
        metrics=metrics,
        timers={k: round(v, 3) for k, v in result.timers.items()},
        stage_device_s=stage_device_seconds(REGISTRY.snapshot()),
        parity="recovered" if hit is not None else "NOT RECOVERED",
        config=out["config"],
        extra={"resumed": resumed_rows > 0, "tuned": tuned},
    ))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
