"""Micro-benchmarks mirroring the reference's ad-hoc perf harnesses.

* fft:      2^23-point R2C+C2R round trip (`src/hcfft.cpp:14-42`),
            plus the R2C half alone (the search loop's per-trial FFT)
* hsum:     10^7-bin spectrum, 4 harmonic-sum levels
            (`src/harmonic_sum_test.cpp:13,35-36`)
* resample: 2^23-point kernel-II resample at accel=500 m/s^2
            (`src/kernels.cu:335-362`) — host-table path vs raw gather
* peaks:    thresholded peak extraction per lowering (sort /
            two_stage / pallas compaction, ops/peaks.py), measured
            BOTH standalone and inside a vmapped spectrum-forming
            program — the in-program delta is the figure the tuner's
            cost table wants (the r5 attribution gap: in-program
            sorts serialise against surrounding fused ops and run
            slower than standalone).  Optional third argv: a tune
            sidecar path to record the measured costs into
            (search/tuning.py ``extraction`` section)
* copy:     HBM/VMEM copy bound (roll; the roofline all of the above
            are judged against)

Run: python benchmarks/micro.py [fft|hsum|resample|copy|peaks|all] [iters]
Prints one JSON line per benchmark and (for `all`) writes
benchmarks/micro_results.json.

Timing uses benchmarks/timing.py's scan-chained harness: on the
remote-attached TPU both lazy dispatch AND ``block_until_ready`` lie
(a 8192^3 matmul appears to run at 30 PFLOP/s), so each op is chained
``iters`` times inside one jitted ``lax.scan``, fenced by a scalar
fetch, with the 1-iteration run subtracted to cancel tunnel latency.
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def _gbps(nbytes, secs):
    return nbytes / secs / 1e9


def _peak_gbps() -> float:
    """Peak HBM bandwidth of the attached device for the utilisation
    column — from the cost model's peak table (PSL007: the single
    source of truth), not a hand-written headline number."""
    from peasoup_tpu.obs.costmodel import device_peak

    return device_peak()["bytes_per_s"] / 1e9


def bench_fft(iters):
    import jax
    import jax.numpy as jnp
    from benchmarks.timing import time_op

    n = 1 << 23
    x = jax.device_put(
        np.random.default_rng(0).normal(size=n).astype(np.float32)
    )
    rt = time_op(
        lambda v: jnp.fft.irfft(jnp.fft.rfft(v), n=n).astype(jnp.float32),
        x, iters=iters)
    fwd = time_op(
        lambda v: jnp.pad(
            jnp.abs(jnp.fft.rfft(v)).astype(jnp.float32),
            (0, n - (n // 2 + 1))),
        x, iters=iters)
    return [
        {"metric": "fft_r2c_c2r_2e23_roundtrip", "value": round(rt * 1e3, 3),
         "unit": "ms"},
        {"metric": "fft_r2c_2e23", "value": round(fwd * 1e3, 3),
         "unit": "ms"},
    ]


def bench_hsum(iters):
    import jax
    import jax.numpy as jnp
    from benchmarks.timing import time_op

    from peasoup_tpu.ops import harmonic_sums

    from peasoup_tpu.obs.costmodel import harmonics_cost

    n = 10_000_000
    spec = jax.device_put(
        np.random.default_rng(0).normal(size=n).astype(np.float32)
    )
    peak_gbps = _peak_gbps()
    out = []
    for nh in (4, 5):
        def step(s, nh=nh):
            h = harmonic_sums(s, nh)
            return s + 1e-12 * sum(h)
        t = time_op(step, spec, iters=iters)
        # nh levels read the spectrum at stretched indices + write sums
        traffic = harmonics_cost(n, nh).bytes_total
        out.append({"metric": f"harmonic_sum_1e7_{nh}levels",
                    "value": round(t * 1e3, 3), "unit": "ms",
                    "GBps": round(_gbps(traffic, t), 1),
                    "hbm_util_pct": round(
                        100 * _gbps(traffic, t) / peak_gbps, 1)})
    return out


def bench_resample(iters):
    import jax
    import jax.numpy as jnp
    from benchmarks.timing import time_op

    from peasoup_tpu.ops.resample import (
        resample2,
        resample2_from_tables,
        resample2_max_shift,
        resample2_tables,
    )

    n = 1 << 23
    tsamp = 6.4e-5
    accel = 500.0
    block = 16384
    ms = resample2_max_shift(accel, tsamp, n)
    tim = jax.device_put(
        np.random.default_rng(0).normal(size=n).astype(np.float32)
    )
    d0, pos, step = (jnp.asarray(t[0]) for t in
                     resample2_tables([accel], tsamp, n, ms, block=block))
    t_tab = time_op(
        lambda v: resample2_from_tables(v, d0, pos, step, ms, block=block),
        tim, iters=iters)
    t_gather = time_op(
        lambda v: resample2(v, accel, tsamp, max_shift=None), tim,
        iters=max(4, iters // 4))
    traffic = 2 * n * 4  # one read + one write pass over the series
    return [
        {"metric": "resample2_tables_2e23_accel500",
         "value": round(t_tab * 1e3, 3), "unit": "ms",
         "GBps": round(_gbps(traffic, t_tab), 1),
         "hbm_util_pct": round(100 * _gbps(traffic, t_tab) / _peak_gbps(),
                               1)},
        {"metric": "resample2_gather_2e23_accel500",
         "value": round(t_gather * 1e3, 3), "unit": "ms"},
    ]


#: (searched prefix, capacity) cells the peaks bench measures — the
#: tutorial's dominant harmonic-level shapes plus the small-cap cell
#: where the narrow two-stage wins (benchmarks/peaks_sweep.json)
PEAKS_CELLS = ((36909, 320), (65537, 320), (65537, 64))

#: trial batch per measurement (vmapped, like the fused program)
PEAKS_BATCH = 16


def bench_peaks(iters, sidecar: str | None = None):
    import jax
    import jax.numpy as jnp
    from benchmarks.timing import time_op

    from peasoup_tpu.ops.peaks import EXTRACTION_METHODS, extract_top_peaks

    on_tpu = jax.devices()[0].platform == "tpu"
    # interpret-mode pallas is a correctness vehicle, ~100x compiled:
    # timing it would poison the tuner's cost table
    methods = [m for m in EXTRACTION_METHODS if m != "pallas" or on_tpu]
    rng = np.random.default_rng(0)
    out = []
    for stop, cap in PEAKS_CELLS:
        n = stop + 111  # non-multiple-of-row-width tail on purpose
        spec = np.abs(rng.normal(size=(PEAKS_BATCH, n))) * 3
        spec[:, ::601] += 9.5  # sparse guaranteed hits
        spec = jax.device_put(spec.astype(np.float32))
        tim = jax.device_put(rng.normal(
            size=(PEAKS_BATCH, 2 * (n - 1))).astype(np.float32))
        for m in methods:
            def extract(s, m=m, stop=stop, cap=cap):
                return extract_top_peaks(s, 9.0, 100, stop, cap,
                                         method=m)

            # standalone: the extraction alone, vmapped over trials
            def alone(s, m=m):
                _i, sn, _c = jax.vmap(extract)(s)
                return s + 1e-12 * jnp.sum(sn)

            t_alone = time_op(alone, spec, iters=iters)

            # in-program: spectrum formation (rfft + normalise) feeding
            # the extraction, vs the same program with the extraction
            # replaced by a cheap reduce — the DELTA attributes the
            # extraction's cost inside a fused dispatch
            def formed(t, with_extract, m=m):
                sp = jnp.abs(jnp.fft.rfft(t, axis=-1)).astype(
                    jnp.float32)[:, : spec.shape[1]]
                if with_extract:
                    _i, sn, _c = jax.vmap(extract)(sp)
                    probe = jnp.sum(sn)
                else:
                    probe = jnp.sum(sp[:, :8])
                return t + 1e-12 * probe

            t_with = time_op(lambda t: formed(t, True), tim, iters=iters)
            t_without = time_op(lambda t: formed(t, False), tim,
                                iters=iters)
            t_prog = max(t_with - t_without, 0.0)
            per_call = t_prog / PEAKS_BATCH
            out.append({
                "metric": f"peaks_{m}_{stop}x{cap}_standalone",
                "value": round(t_alone * 1e3, 4), "unit": "ms"})
            out.append({
                "metric": f"peaks_{m}_{stop}x{cap}_inprog",
                "value": round(t_prog * 1e3, 4), "unit": "ms",
                "per_spectrum_us": round(per_call * 1e6, 3)})
            if sidecar:
                from peasoup_tpu.search.tuning import update_extraction

                update_extraction(
                    sidecar, str(jax.devices()[0].device_kind), stop,
                    cap, costs={m: per_call})
    return out


def bench_copy(iters):
    import jax
    import jax.numpy as jnp
    from benchmarks.timing import time_op

    n = 1 << 23
    x = jax.device_put(
        np.random.default_rng(0).normal(size=n).astype(np.float32)
    )
    # the nonlinear |v| term defeats XLA's composition of rolled/scaled
    # linear chains across scan iterations
    t = time_op(lambda v: jnp.roll(v, 12345) + jnp.abs(v) * 1e-20, x,
                iters=max(iters, 64))
    return [{"metric": "copy_roll_2e23", "value": round(t * 1e3, 4),
             "unit": "ms", "GBps": round(_gbps(2 * n * 4, t), 1)}]


BENCHES = {"fft": bench_fft, "hsum": bench_hsum,
           "resample": bench_resample, "peaks": bench_peaks,
           "copy": bench_copy}


def main(argv=None):
    args = argv if argv is not None else sys.argv[1:]
    which = args[0] if args else "all"
    iters = int(args[1]) if len(args) > 1 else 32
    sidecar = args[2] if len(args) > 2 else None
    if which != "all" and which not in BENCHES:
        print(f"unknown benchmark '{which}'; choose from: "
              f"{', '.join(BENCHES)}, all", file=sys.stderr)
        return 1
    names = list(BENCHES) if which == "all" else [which]
    results = []
    for name in names:
        rows = (BENCHES[name](iters, sidecar=sidecar)
                if name == "peaks" else BENCHES[name](iters))
        for row in rows:
            results.append(row)
            print(json.dumps(row))
    if which == "all":
        import jax

        out = {"device": str(jax.devices()[0]), "results": results}
        path = os.path.join(os.path.dirname(__file__),
                            "micro_results.json")
        with open(path, "w") as f:
            json.dump(out, f, indent=1)
    # same-schema ledger record as bench.py/production.py (the ad-hoc
    # per-bench JSON above keeps its stdout/file shape unchanged)
    from peasoup_tpu.obs.history import append_history, make_history_record

    append_history(make_history_record(
        "micro",
        metrics={r["metric"]: r["value"] for r in results
                 if isinstance(r.get("value"), (int, float))},
        config={"which": which, "iters": iters},
    ))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
