"""Micro-benchmarks mirroring the reference's ad-hoc perf harnesses.

* fft:  2^23-point R2C+C2R round trip, mean over N iters
  (`src/hcfft.cpp:14-42`)
* hsum: 10^7-bin spectrum, 4 harmonic-sum levels, N reps
  (`src/harmonic_sum_test.cpp:13,35-36`)
* resample: 2^23-point accel resample (select path), N reps

Run: python benchmarks/micro.py [fft|hsum|resample|all] [iters]
Prints one JSON line per benchmark.  Timing is taken at the host fetch
of a scalar reduction — on remote-attached TPUs dispatch is lazy and
``block_until_ready`` can return before execution.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def _time(fn, iters):
    fn()  # compile
    t0 = time.time()
    for _ in range(iters):
        fn()
    return (time.time() - t0) / iters


def bench_fft(iters):
    import jax
    import jax.numpy as jnp

    n = 1 << 23
    x = jax.device_put(
        np.random.default_rng(0).normal(size=n).astype(np.float32)
    )
    f = jax.jit(lambda v: jnp.fft.irfft(jnp.fft.rfft(v), n=n).sum())
    return {"metric": "fft_r2c_c2r_2e23_roundtrip",
            "value": round(_time(lambda: float(f(x)), iters) * 1e3, 3),
            "unit": "ms"}


def bench_hsum(iters):
    import jax
    import jax.numpy as jnp

    from peasoup_tpu.ops import harmonic_sums

    n = 10_000_000
    spec = jax.device_put(
        np.random.default_rng(0).normal(size=n).astype(np.float32)
    )
    f = jax.jit(lambda s: sum(h.sum() for h in harmonic_sums(s, 4)))
    return {"metric": "harmonic_sum_1e7_4levels",
            "value": round(_time(lambda: float(f(spec)), iters) * 1e3, 3),
            "unit": "ms"}


def bench_resample(iters):
    import jax
    import jax.numpy as jnp

    from peasoup_tpu.ops.resample import resample2, resample2_max_shift

    n = 1 << 23
    tsamp = 6.4e-5
    ms = resample2_max_shift(5.0, tsamp, n)
    tim = jax.device_put(
        np.random.default_rng(0).normal(size=n).astype(np.float32)
    )
    f = jax.jit(lambda t: resample2(t, 5.0, tsamp, ms).sum())
    return {"metric": "resample2_2e23",
            "value": round(_time(lambda: float(f(tim)), iters) * 1e3, 3),
            "unit": "ms"}


BENCHES = {"fft": bench_fft, "hsum": bench_hsum, "resample": bench_resample}


def main(argv=None):
    args = argv if argv is not None else sys.argv[1:]
    which = args[0] if args else "all"
    iters = int(args[1]) if len(args) > 1 else 20
    if which != "all" and which not in BENCHES:
        print(f"unknown benchmark '{which}'; choose from: "
              f"{', '.join(BENCHES)}, all", file=sys.stderr)
        return 1
    names = list(BENCHES) if which == "all" else [which]
    for name in names:
        print(json.dumps(BENCHES[name](iters)))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
