"""Micro-benchmark: kernel-II acceleration resampling at production scale.

VERDICT r1 item 4: measure the 2^23-point paths at realistic high
accelerations (max_shift >> 64, where the select path is unavailable)
against the copy roofline.  Reference kernel: `src/kernels.cu:335-362`
(getAcceleratedIndexII).

Uses benchmarks/timing.time_op — wall-clock around dispatches measures
nothing through the async relay (see timing.py docstring).

Run on the real chip:  python benchmarks/resample_bench.py
"""

from __future__ import annotations

import importlib
import json

import jax
import jax.numpy as jnp

rs = importlib.import_module("peasoup_tpu.ops.resample")


def main():
    from benchmarks.timing import time_op

    n = 1 << 23
    tsamp = 6.4e-5  # 64 us: typical survey sampling => tobs ~ 537 s
    accel = 500.0  # m/s^2, top of the realistic search range
    max_shift = rs.resample2_max_shift(accel, tsamp, n)
    print(f"n={n}  accel={accel}  tsamp={tsamp}  max_shift={max_shift}")

    key = jax.random.PRNGKey(0)
    tim = jax.random.normal(key, (n,), dtype=jnp.float32)

    results = {"n": n, "accel": accel, "tsamp": tsamp,
               "max_shift": max_shift, "device": str(jax.devices()[0]),
               "cases": {}}

    def record(name, t, extra=None):
        row = {"ms": round(t * 1e3, 3),
               "GBps": round(2 * n * 4 / t / 1e9, 1)}
        row.update(extra or {})
        results["cases"][name] = row
        print(f"{name:20s} {t*1e3:8.3f} ms   {row['GBps']:7.1f} GB/s")

    # copy roofline (nonlinear term defeats scan-chain folding)
    record("copy", time_op(
        lambda x: jnp.roll(x, 12345) + jnp.abs(x) * 1e-20, tim))

    # the gather fallback (what high accel used to hit)
    record("gather", time_op(
        lambda x: rs.resample2(x, accel, tsamp, max_shift=None), tim,
        iters=8))

    # host-exact table path at several block sizes
    gather_ref = jax.jit(
        lambda x: rs.resample2(x, accel, tsamp, max_shift=None))(tim)
    for bs in (4096, 8192, 16384, 32768):
        d0, pos, step = rs.resample2_tables(
            [accel], tsamp, n, max_shift, block=bs)
        d0j, posj, stepj = (jnp.asarray(a[0]) for a in (d0, pos, step))
        fn = lambda x, a=d0j, b=posj, c=stepj, blk=bs: (
            rs.resample2_from_tables(x, a, b, c, max_shift, block=blk))
        exact = bool(jnp.array_equal(jax.jit(fn)(tim), gather_ref))
        record(f"tables_b{bs}", time_op(fn, tim, iters=16),
               {"matches_gather": exact})

    with open("benchmarks/resample_bench.json", "w") as f:
        json.dump(results, f, indent=1)
    print("wrote benchmarks/resample_bench.json")


if __name__ == "__main__":
    main()
