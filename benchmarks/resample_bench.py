"""Micro-benchmark: kernel-II acceleration resampling at production scale.

VERDICT r1 item 4: measure the 2^23-point gather path at realistic high
accelerations (max_shift >> 64, i.e. the regime where `resample2`'s
select path is unavailable) and compare candidate implementations
against plain-copy HBM bandwidth.  Reference kernel:
`src/kernels.cu:335-362` (getAcceleratedIndexII).

Run on the real chip:  python benchmarks/resample_bench.py
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

import importlib

rs = importlib.import_module("peasoup_tpu.ops.resample")


def timeit(fn, *args, n_iter=20, warmup=3):
    for _ in range(warmup):
        jax.block_until_ready(fn(*args))
    t0 = time.perf_counter()
    for _ in range(n_iter):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / n_iter


def main():
    n = 1 << 23
    tsamp = 6.4e-5  # 64 us: typical survey sampling => tobs ~ 537 s
    accel = 500.0  # m/s^2, top of the realistic search range
    max_shift = rs.resample2_max_shift(accel, tsamp, n)
    print(f"n={n}  accel={accel}  tsamp={tsamp}  max_shift={max_shift}")

    key = jax.random.PRNGKey(0)
    tim = jax.random.normal(key, (n,), dtype=jnp.float32)

    results = {"n": n, "accel": accel, "tsamp": tsamp,
               "max_shift": max_shift, "device": str(jax.devices()[0]),
               "cases": {}}

    # plain copy: the bandwidth roofline for any resampler (read n + write n)
    copy = jax.jit(lambda x: x * 1.0)
    t = timeit(copy, tim)
    bw = 2 * n * 4 / t / 1e9
    results["cases"]["copy"] = {"ms": t * 1e3, "GBps": bw}
    print(f"copy               {t*1e3:8.3f} ms   {bw:7.1f} GB/s")

    # gather path (what resample2 falls back to at high accel)
    gather = jax.jit(lambda x: rs.resample2(x, accel, tsamp, max_shift=None))
    t = timeit(gather, tim)
    bw = 2 * n * 4 / t / 1e9
    results["cases"]["gather"] = {"ms": t * 1e3, "GBps": bw}
    print(f"gather             {t*1e3:8.3f} ms   {bw:7.1f} GB/s")

    # blockwise path (candidate fix), several block sizes
    for bs in (1024, 4096, 16384):
        fn = jax.jit(lambda x, b=bs: rs.resample2_blockwise(
            x, accel, tsamp, max_shift, block=b))
        out = fn(tim)
        ref = gather(tim)
        ok = bool(jnp.array_equal(out, ref))
        t = timeit(fn, tim)
        bw = 2 * n * 4 / t / 1e9
        results["cases"][f"blockwise_{bs}"] = {
            "ms": t * 1e3, "GBps": bw, "matches_gather": ok}
        print(f"blockwise b={bs:<6} {t*1e3:8.3f} ms   {bw:7.1f} GB/s   "
              f"exact={ok}")

    with open("benchmarks/resample_bench.json", "w") as f:
        json.dump(results, f, indent=1)
    print("wrote benchmarks/resample_bench.json")


if __name__ == "__main__":
    main()
