"""Shape-stability + timing sweep for the peak-extraction lowerings.

The r5 session measured the narrow-row two-stage top_k faster than the
approx_max_k sorts at some shapes, but its C=64 variant at stop=65537
CRASHED the v5e worker mid-sweep, killing the whole process — so the
two-stage path stayed unshipped behind a PERF NOTE.  This tool is the
sweep that unblocked it (ISSUE 6 tier A): every (C, stop, cap) cell
runs in its OWN subprocess, so a backend crash is recorded as an
unsafe cell instead of killing the sweep, and the committed artifact
(``benchmarks/peaks_sweep.json``) is the safety table the tuner
consults — :data:`peasoup_tpu.search.tuning.TWO_STAGE_UNSAFE` mirrors
its unsafe cells, and ``resolve_peaks_methods`` never picks one.

Each cell checks EXACTNESS first (two-stage — and pallas compaction,
where available — against the single-top_k ground truth on random +
adversarial one-hit-per-row patterns), then times all available
lowerings with the scan-chained harness (``benchmarks/timing.py``).

Usage::

    python benchmarks/peaks_sweep.py                  # full grid
    python benchmarks/peaks_sweep.py --quick          # 1-cell smoke
    python benchmarks/peaks_sweep.py --out sweep.json --sidecar tune.json
    python benchmarks/peaks_sweep.py --cell 128 36909 320   # one cell
                                                      # (internal)

Grid: C in {64, 128, 256} x stop in {9216, 18432, 36909, 65537,
131072} x cap in {64, 256, 320, 1024, 2048} — the ISSUE-6 ranges.
Cells marked unsafe in an existing artifact are SKIPPED (their
verdict is carried forward) unless ``--include-unsafe``: re-running a
known worker-killer needs an explicit ask.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

ROW_WIDTHS = (64, 128, 256)
STOPS = (9216, 18432, 36909, 65537, 131072)
CAPS = (64, 256, 320, 1024, 2048)

DEFAULT_OUT = os.path.join(os.path.dirname(__file__), "peaks_sweep.json")

#: per-cell subprocess budget: a hung backend counts as unsafe too
CELL_TIMEOUT_S = 240


def run_cell(row_width: int, stop: int, cap: int, iters: int) -> dict:
    """Executed INSIDE the per-cell subprocess: exactness then timing.
    Prints one JSON object on stdout."""
    import numpy as np
    import jax
    import jax.numpy as jnp

    from benchmarks.timing import time_op
    from peasoup_tpu.ops.peaks import extract_top_peaks
    from peasoup_tpu.ops.peaks_pallas import (
        pallas_peaks_supported,
    )

    on_tpu = jax.devices()[0].platform == "tpu"
    rng = np.random.default_rng(7)
    start = 100
    thresh = 9.0

    def truth(spec):
        i = np.arange(len(spec))
        m = (i >= start) & (i < stop) & (spec > thresh)
        return i[m], int(m.sum())

    def check(spec, method):
        iv, sv, cv = extract_top_peaks(
            jnp.asarray(spec), thresh, start, stop, cap, method=method,
            row_width=row_width if method == "two_stage" else 0)
        iv, sv = np.asarray(iv), np.asarray(sv)
        hits, cnt = truth(spec)
        got = np.sort(iv[iv >= 0])
        if int(cv) != cnt:
            return f"{method}: count {int(cv)} != {cnt}"
        want = hits if cnt <= cap else None
        if want is not None and not np.array_equal(got, want):
            return f"{method}: hit set mismatch ({len(got)}/{len(want)})"
        if not np.allclose(np.sort(sv[iv >= 0]),
                           np.sort(spec[iv[iv >= 0]]), rtol=1e-6):
            return f"{method}: (index, value) pairing broken"
        return None

    # adversarial patterns: dense random, one-hit-per-row (the case
    # the row-selection proof has to cover), empty, over-capacity
    specs = []
    dense = np.abs(rng.normal(size=stop + 137)).astype(np.float32) * 3
    dense[::515] += 9.5
    specs.append(dense)
    sparse = np.abs(rng.normal(size=stop + 137)).astype(np.float32)
    sparse[::row_width + 1] += 11.0
    specs.append(sparse)
    flood = np.abs(rng.normal(size=stop + 137)).astype(np.float32) + 10.0
    specs.append(flood)

    methods = ["sort", "two_stage"]
    if pallas_peaks_supported()[0]:
        methods.append("pallas")
    errors = []
    for spec in specs:
        for m in methods:
            err = check(spec, m)
            if err:
                errors.append(err)
    cell = {
        "row_width": row_width, "stop": stop, "cap": cap,
        "device": str(jax.devices()[0].device_kind),
        "safe": not errors,
        "exact": not errors,
    }
    if errors:
        cell["errors"] = errors[:8]
        return cell

    spec_b = np.stack([dense[: stop + 137]] * 8)
    spec_d = jax.device_put(jnp.asarray(spec_b))
    times = {}
    for m in methods:
        if m == "pallas" and not on_tpu:
            continue  # interpret timing would poison the table

        def step(s, m=m):
            _i, sn, _c = jax.vmap(
                lambda v: extract_top_peaks(
                    v, thresh, start, stop, cap, method=m,
                    row_width=row_width if m == "two_stage" else 0)
            )(s)
            return s + 1e-12 * jnp.sum(sn)

        times[m] = round(time_op(step, spec_d, iters=iters) * 1e3, 4)
    cell["ms_per_batch8"] = times
    return cell


def cell_key(row_width: int, stop: int, cap: int) -> str:
    return f"C{row_width}/stop{stop}/cap{cap}"


def load_artifact(path: str) -> dict:
    if not path or not os.path.exists(path):
        return {}
    try:
        with open(path) as f:
            doc = json.load(f)
        return doc if isinstance(doc, dict) else {}
    except (OSError, ValueError):
        return {}


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--out", default=DEFAULT_OUT)
    p.add_argument("--sidecar", default="",
                   help="tune sidecar to record safety/timing into "
                        "(search/tuning.py extraction section)")
    p.add_argument("--iters", type=int, default=16)
    p.add_argument("--quick", action="store_true",
                   help="one safe cell only (CI smoke)")
    p.add_argument("--include-unsafe", action="store_true",
                   help="re-run cells the existing artifact marks "
                        "unsafe (may crash a TPU worker)")
    p.add_argument("--cell", nargs=3, type=int, metavar=("C", "STOP",
                                                         "CAP"),
                   help="internal: run ONE cell in-process and print "
                        "its JSON")
    args = p.parse_args(argv)

    if args.cell:
        print(json.dumps(run_cell(*args.cell, iters=args.iters)))
        return 0

    grid = ([(128, 9216, 64)] if args.quick else
            [(c, s, k) for c in ROW_WIDTHS for s in STOPS for k in CAPS])
    prior = load_artifact(args.out).get("cells", {})
    cells: dict[str, dict] = {}
    for c, s, k in grid:
        key = cell_key(c, s, k)
        old = prior.get(key)
        if (old is not None and old.get("safe") is False
                and not args.include_unsafe):
            # carry the unsafe verdict forward; re-running a known
            # worker-killer needs --include-unsafe
            old = dict(old)
            old["skipped"] = "unsafe in prior artifact"
            cells[key] = old
            print(json.dumps({"cell": key, **old}))
            continue
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--cell", str(c), str(s), str(k),
             "--iters", str(args.iters)],
            capture_output=True, text=True, timeout=CELL_TIMEOUT_S * 2,
            cwd=os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))),
        )
        line = (proc.stdout.strip().splitlines() or [""])[-1]
        try:
            cell = json.loads(line)
        except ValueError:
            cell = None
        if proc.returncode != 0 or cell is None:
            # the subprocess died (the C=64/stop>=65537 v5e failure
            # mode) — THAT is the datum the sweep exists to record
            cell = {
                "row_width": c, "stop": s, "cap": k, "safe": False,
                "exact": False,
                "errors": [f"subprocess rc={proc.returncode}: "
                           + (proc.stderr or "")[-300:].strip()],
            }
        cells[key] = cell
        print(json.dumps({"cell": key, **cell}))
        if args.sidecar:
            from peasoup_tpu.search.tuning import update_extraction

            update_extraction(
                args.sidecar, cell.get("device", "unknown"), s, k,
                safe=bool(cell.get("safe")))

    doc = {
        "grid": {"row_widths": list(ROW_WIDTHS), "stops": list(STOPS),
                 "caps": list(CAPS)},
        "cells": cells,
        "n_unsafe": sum(1 for v in cells.values() if not v.get("safe")),
    }
    with open(args.out, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
    print(f"wrote {args.out} ({len(cells)} cells, "
          f"{doc['n_unsafe']} unsafe)")

    # same-schema ledger record as every other benchmarks/ harness
    from peasoup_tpu.obs.history import append_history, make_history_record

    append_history(make_history_record(
        "micro",
        metrics={"peaks_sweep_cells": len(cells),
                 "peaks_sweep_unsafe": doc["n_unsafe"]},
        config={"quick": bool(args.quick), "iters": args.iters},
    ))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
