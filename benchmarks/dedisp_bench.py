"""Dedispersion benchmark: direct Pallas sweep vs two-stage sub-band.

VERDICT r3 item 2: the sub-band scheme must beat the direct kernel
>= 4x at 1024 channels on a realistic survey grid, with a 4096-channel
entry.  The grid is the PRODUCT's own tolerance-stepped DM list
(`generate_dm_list`, the dedisp recurrence) — dense at low DM, which is
exactly where anchor sharing compresses.

Per (nchans, nsamps) case this measures, on the real chip:

* direct: one `dedisperse_pallas_flat` dispatch per chunk of
  ``dm_chunk`` fine rows (the chunked driver's exact hot-path call);
* subband: the driver's `dedisperse_subband_flat` assembly for sampled
  chunks spanning the anchor-count range, with a linear fit
  ``t = a * n_anchors + b`` extrapolating the total.

Writes benchmarks/dedisp_bench.json.  Run: python benchmarks/dedisp_bench.py
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def time_calls(fn, n=4, repeats=3):
    """Median wall of n chained async dispatches fenced by one fetch."""
    import jax.numpy as jnp

    best = []
    for _ in range(repeats):
        t0 = time.time()
        for _i in range(n):
            out = fn()
        float(jnp.sum(out[:1, :128]))  # fence: forces real execution
        best.append((time.time() - t0) / n)
    return float(np.median(best))


def bench_case(nchans, nsamps, dm_chunk=32):
    import jax
    import jax.numpy as jnp

    from peasoup_tpu.ops.dedisperse import (
        delay_table,
        delays_in_samples,
        generate_dm_list,
        max_delay,
        split_flat_channels,
        subband_chunk_plan,
        subband_stage2_layout,
    )
    from peasoup_tpu.ops.dedisperse_pallas import (
        dedisperse_flat_pad_to,
        dedisperse_pallas_flat,
        dedisperse_pallas_flat_subband,
        dedisperse_window_slack,
    )

    tsamp, fch1 = 6.4e-5, 1500.0
    foff = -300.0 / nchans  # fixed 300 MHz band
    tab = delay_table(nchans, tsamp, fch1, foff)
    dm_list = generate_dm_list(0.0, 600.0, tsamp, 64.0, fch1, foff,
                               nchans, 1.10)
    delays = delays_in_samples(dm_list, tab)
    ndm = len(dm_list)
    md = max_delay(dm_list, tab)
    out_nsamps = nsamps
    G, T = 16, 15360
    dm_tile = dm_chunk
    n_chunks = ndm // dm_chunk  # drop the ragged tail: bench only
    cells = [np.arange(ci * dm_chunk, (ci + 1) * dm_chunk)
             for ci in range(n_chunks)]
    plan = subband_chunk_plan(dm_list.astype(np.float64), delays, tab,
                              cells, chan_align=2 * G, eps=0.5)
    assert plan is not None
    n_anchor_p = plan["n_anchor_p"]
    L1 = out_nsamps + plan["shift_max"]

    slack_d = dedisperse_window_slack(delays, dm_tile, G)
    anchor_tables = np.concatenate(
        [delays[c[0]] for c in plan["per_cell"]])
    slack_s = dedisperse_window_slack(anchor_tables, n_anchor_p, G)
    print(f"  plan: ndm={ndm} n_chunks={n_chunks} "
          f"anchors_total={int(np.sum([len(np.unique(c[0])) for c in plan['per_cell']]))} "
          f"n_anchor_p={n_anchor_p} nsub={plan['nsub']}", flush=True)
    # stage-1 kernel geometry: K time tiles per window DMA, bounded by
    # the double-buffered per-channel window scratch (~9 MB)
    csub = plan["bounds"][0][1] - plan["bounds"][0][0]
    nsub = plan["nsub"]
    k_sub = int(max(1, min(4, (9 << 20) // (2 * csub * T))))
    # L1: a K*T multiple covering out + shift_max AND the stage-2
    # window reach (stage 2 = ONE direct-kernel launch over the flat
    # partials with synthetic delays assign*nsub*L1 + shift)
    dm_tile2, G2 = 8, 16 if nsub % 32 == 0 else 8
    KT = k_sub * T
    # slack2 is L1-independent (anchor-pure tiles: the anchor stride
    # cancels in every block spread), so probe it with L1=0, then fix
    # L1 to cover out + shift + the stage-2 window reach, then build
    # the real layout
    _, cells2p = subband_stage2_layout(plan["per_cell"], 0, dm_tile2)
    slack2 = max(dedisperse_window_slack(c[0], dm_tile2, G2)
                 for c in cells2p)
    need2 = (-(-out_nsamps // T) * T - T + plan["shift_max"]
             + (-(-(T + slack2 + 1024) // 1024) * 1024))
    L1 = -(-max(out_nsamps + plan["shift_max"], need2) // KT) * KT
    R2, cells2 = subband_stage2_layout(plan["per_cell"], L1, dm_tile2)
    assert (n_anchor_p - 1) * nsub * L1 + plan["shift_max"] < 2**31
    pad_to = max(
        dedisperse_flat_pad_to(out_nsamps, md, slack_d, T),
        # +1024: the sb kernel's per-kk aligned slices round its window
        # one alignment unit past the plain K*T formula
        dedisperse_flat_pad_to(L1, md, slack_s + 1024, k_sub * T),
    )
    rng = np.random.default_rng(0)
    data = rng.integers(0, 64, (nchans, pad_to), dtype=np.uint8)
    parts = [jax.device_put(p)
             for p in split_flat_channels(data, align=max(2 * G, csub))]
    nsamps_dev = pad_to

    def direct(ci):
        dj = jnp.asarray(delays[cells[ci]])
        return lambda: dedisperse_pallas_flat(
            parts, dj, nsamps_dev, out_nsamps, window_slack=slack_d,
            dm_tile=dm_tile, time_tile=T, chan_group=G, max_delay=md)

    # ONE jitted program shared by every sampled chunk (shapes are
    # padded equal across cells).  parts are ARGUMENTS: a stage1
    # closure over device arrays would bake them into the executable
    # as multi-GB captured constants
    def _sub(parts_, ad_, d2_, unpad_):
        partials = dedisperse_pallas_flat_subband(
            parts_, ad_, nsamps_dev, L1, csub=csub,
            window_slack=slack_s, dm_tile=n_anchor_p,
            time_tile=T, k_tiles=k_sub, chan_group=G,
            max_delay=md)
        # stage 2 AS a dedispersion: flat partials = the synthetic
        # nsub-channel filterbank, per-row delays carry the anchor
        # stride; one direct-kernel launch replaces ndm*nsub XLA
        # dynamic slices (~0.19 s/chunk, the dominant sub-band cost)
        out2 = dedisperse_pallas_flat(
            [partials.reshape(-1)], d2_, L1, out_nsamps,
            window_slack=slack2, max_delay=plan["shift_max"],
            dm_tile=dm_tile2, time_tile=T, chan_group=G2,
            data_tail_ok=True)
        return jnp.take(out2, unpad_, axis=0)

    sub_fn = jax.jit(_sub)

    def subband(ci):
        anchor_rows, _assign, _shifts = plan["per_cell"][ci]
        ad = jnp.asarray(delays[anchor_rows])
        d2 = jnp.asarray(cells2[ci][0])
        up = jnp.asarray(cells2[ci][1])
        return lambda: sub_fn(parts, ad, d2, up)

    # anchor counts per cell (pre-padding)
    n_anch = np.array([
        len(np.unique(c[0])) for c in plan["per_cell"]])
    t_direct = time_calls(direct(n_chunks // 2))
    # sample chunks across the anchor-count range for the linear fit
    order = np.argsort(n_anch)
    sample_cis = sorted({int(order[0]), int(order[len(order) // 3]),
                         int(order[2 * len(order) // 3]),
                         int(order[-1])})
    t_sub = {ci: time_calls(subband(ci)) for ci in sample_cis}
    xs = np.array([n_anch[ci] for ci in sample_cis], float)
    ys = np.array([t_sub[ci] for ci in sample_cis])
    if len(set(xs)) > 1:
        a, b = np.polyfit(xs, ys, 1)
    else:
        a, b = 0.0, float(ys.mean())
    total_direct = t_direct * n_chunks
    total_sub = float(a * n_anch.sum() + b * n_chunks)
    return {
        "nchans": nchans, "nsamps": nsamps, "ndm": ndm,
        "dm_chunk": dm_chunk, "n_chunks": n_chunks,
        "nsub": plan["nsub"], "n_anchor_p": n_anchor_p,
        "anchors_total": int(n_anch.sum()),
        "cost_ratio_model": round(plan["cost_ratio"], 4),
        "max_err_samples": plan["max_err"],
        "t_direct_per_chunk_s": round(t_direct, 4),
        "t_subband_sampled_s": {str(k): round(v, 4)
                                for k, v in t_sub.items()},
        "total_direct_s": round(total_direct, 2),
        "total_subband_s": round(total_sub, 2),
        "speedup": round(total_direct / total_sub, 2),
    }


def main():
    import jax

    results = []
    # sample counts sized so the (35 MB/s tunnel) upload fits the run:
    # per-row cost scales linearly in nsamps, the direct/sub-band
    # ratio does not depend on it
    for nchans, nsamps in ((1024, 1 << 21), (4096, 1 << 20)):
        print(f"case {nchans} chans x {nsamps} samples...", flush=True)
        r = bench_case(nchans, nsamps)
        print(json.dumps(r), flush=True)
        results.append(r)
    out = {"device": str(jax.devices()[0]), "results": results}
    path = os.path.join(os.path.dirname(__file__), "dedisp_bench.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(f"wrote {path}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
