"""Multibeam coincidencer tests (reference: `src/coincidencer.cpp`,
`include/transforms/coincidencer.hpp`, `src/kernels.cu:1073-1100`)."""

import numpy as np
import jax.numpy as jnp
import pytest

from peasoup_tpu.io.sigproc import Filterbank, SigprocHeader, write_filterbank
from peasoup_tpu.ops.coincidence import (
    birdie_list_from_mask,
    coincidence_mask,
    write_birdie_list,
    write_samp_mask,
)
from peasoup_tpu.search.coincidence import (
    CoincidencerConfig,
    run_coincidencer,
)


def _reference_birdie_walk(mask, bin_width):
    """Direct port of the reference's run-length scan
    (`coincidencer.hpp:59-72`), bounds-checked."""
    out = []
    ii = 0
    size = len(mask)
    while ii < size:
        if mask[ii] == 0:
            count = 0
            while ii < size and mask[ii] == 0:
                count += 1
                ii += 1
            out.append((((ii - 1) - count / 2.0) * bin_width,
                        count * bin_width))
        else:
            ii += 1
    return np.array(out).reshape(-1, 2)


def test_coincidence_mask_counts_beams():
    arrays = jnp.asarray(np.array([
        [5.0, 1.0, 5.0, 5.0],
        [5.0, 5.0, 1.0, 5.0],
        [5.0, 1.0, 5.0, 1.0],
    ], np.float32))
    # thresh 4, beam_thresh 2: bin is masked (0) when >=2 beams exceed
    mask = np.asarray(coincidence_mask(arrays, 4.0, 2))
    np.testing.assert_array_equal(mask, [0.0, 1.0, 0.0, 0.0])


@pytest.mark.parametrize("mask", [
    np.array([1, 1, 0, 0, 0, 1, 1, 0, 1], np.float32),
    np.array([0, 0, 1, 1], np.float32),
    np.array([1, 1, 1], np.float32),
    np.array([0, 0, 0], np.float32),
    np.array([1, 0], np.float32),
])
def test_birdie_list_matches_reference_walk(mask):
    got = birdie_list_from_mask(mask, 0.125)
    want = _reference_birdie_walk(mask, 0.125)
    np.testing.assert_allclose(got, want)


def _make_beam(rng, nsamps, nchans, tsamp, signal=None, spikes=None):
    data = rng.normal(96.0, 10.0, size=(nsamps, nchans))
    t = np.arange(nsamps) * tsamp
    if signal is not None:
        freq, amp = signal
        data += amp * np.sin(2 * np.pi * freq * t)[:, None]
    if spikes is not None:
        data[spikes] += 120.0
    return np.clip(data, 0, 255).astype(np.uint8)


def test_coincidencer_end_to_end(tmp_path):
    rng = np.random.default_rng(42)
    nsamps, nchans, tsamp = 4096, 8, 0.000512
    nbeams = 6
    birdie_freq = 120.0
    spike_samples = [1000, 1001, 2500]
    files = []
    for b in range(nbeams):
        # birdie + spikes in 5 of 6 beams (>= beam_thresh of 4);
        # beam 5 gets a different, single-beam signal that must NOT
        # be masked
        if b < 5:
            data = _make_beam(rng, nsamps, nchans, tsamp,
                              signal=(birdie_freq, 30.0),
                              spikes=spike_samples)
        else:
            data = _make_beam(rng, nsamps, nchans, tsamp,
                              signal=(33.0, 30.0))
        hdr = SigprocHeader(nbits=8, nchans=nchans, tsamp=tsamp,
                            fch1=1510.0, foff=-10.0, nsamples=nsamps)
        path = str(tmp_path / f"beam{b}.fil")
        write_filterbank(path, Filterbank(header=hdr, data=data))
        files.append(path)

    # drive through the CLI subcommand so arg wiring is exercised
    from peasoup_tpu.cli import main as cli_main

    samp_out = str(tmp_path / "rfi.eb_mask")
    spec_out = str(tmp_path / "birdies.txt")
    rc = cli_main(["coincidencer", *files, "--o", samp_out,
                   "--o2", spec_out])
    assert rc == 0

    cfg = CoincidencerConfig(
        samp_outfilename=samp_out, spec_outfilename=spec_out,
    )
    samp_mask, spec_mask, bin_width = run_coincidencer(files, cfg)

    # multibeam spikes are masked in the sample mask
    assert samp_mask[1000] == 0.0
    assert samp_mask[2500] == 0.0
    # the whitened+normalised series should be mostly unmasked
    assert samp_mask.mean() > 0.99

    # the common birdie is masked in the spectral mask...
    bbin = int(round(birdie_freq / bin_width))
    assert spec_mask[bbin - 2 : bbin + 3].min() == 0.0
    # ...but the single-beam signal is not
    sbin = int(round(33.0 / bin_width))
    assert spec_mask[sbin - 2 : sbin + 3].min() == 1.0

    # output files: sample mask header + one line per sample
    lines = open(cfg.samp_outfilename).read().splitlines()
    assert lines[0] == "#0 1"
    assert len(lines) == 1 + nsamps
    assert set(lines[1:]) <= {"0", "1"}
    # birdie list covers the birdie frequency
    birdies = np.loadtxt(cfg.spec_outfilename).reshape(-1, 2)
    assert len(birdies) >= 1
    assert np.any(np.abs(birdies[:, 0] - birdie_freq) < 2.0)


def test_coincidencer_rejects_mismatched_lengths(tmp_path):
    rng = np.random.default_rng(0)
    files = []
    for b, nsamps in enumerate([1024, 2048]):
        hdr = SigprocHeader(nbits=8, nchans=4, tsamp=0.001, fch1=1510.0,
                            foff=-10.0, nsamples=nsamps)
        data = rng.integers(0, 255, size=(nsamps, 4), dtype=np.uint8)
        path = str(tmp_path / f"b{b}.fil")
        write_filterbank(path, Filterbank(header=hdr, data=data))
        files.append(path)
    with pytest.raises(ValueError, match="same length"):
        run_coincidencer(files, CoincidencerConfig(
            samp_outfilename=str(tmp_path / "m"),
            spec_outfilename=str(tmp_path / "b"),
        ))
