"""Candidate provenance (ISSUE 19): stable ids, the lineage ledger,
exact funnel conservation, the `why` chain, and the distillation
baselines — plus the assoc-count round-trip pinning ``<nassoc>`` to
the binary writer's pre-order flatten."""

import json
import time
import xml.etree.ElementTree as ET

import pytest

from peasoup_tpu.data import Candidate
from peasoup_tpu.obs import lineage
from peasoup_tpu.obs.baseline import funnel_anomalies
from peasoup_tpu.obs.warehouse import Warehouse, lineage_rows
from peasoup_tpu.output import (
    CandidateFileParser,
    OutputFileWriter,
    write_candidate_binary,
)
from peasoup_tpu.serve.health import HealthContext, rule_distill_collapse


# -------------------------------------------------------------------------
# stable candidate ids
# -------------------------------------------------------------------------

def test_uid_stable_and_json_roundtrip():
    c = Candidate(dm=12.5, dm_idx=7, acc=-3.25, jerk=0.5, nh=2,
                  snr=9.0, freq=123.456789)
    uid = lineage.candidate_uid("run-a", c)
    assert len(uid) == 16 and int(uid, 16) >= 0
    # same fields -> same id, however they arrive
    assert uid == lineage.uid_from_fields(
        "run-a", c.dm_idx, c.acc, c.jerk, c.nh, c.freq)
    # json round-trip (store record / overview.xml) reproduces the id:
    # repr(float) is the shortest exact round-trip
    fields = json.loads(json.dumps(
        {"dm_idx": c.dm_idx, "acc": c.acc, "jerk": c.jerk,
         "nh": c.nh, "freq": c.freq}))
    assert uid == lineage.uid_from_fields("run-a", **fields)
    # mutating what folding mutates must NOT move the id
    c.folded_snr, c.opt_period = 42.0, 0.1
    assert uid == lineage.candidate_uid("run-a", c)
    # but run and any trial coordinate must
    assert uid != lineage.candidate_uid("run-b", c)
    c2 = Candidate(dm=12.5, dm_idx=8, acc=-3.25, jerk=0.5, nh=2,
                   snr=9.0, freq=123.456789)
    assert uid != lineage.candidate_uid("run-a", c2)


# -------------------------------------------------------------------------
# the recorder: rotation, torn lines, failure latch
# -------------------------------------------------------------------------

def test_recorder_writes_and_reader_filters(tmp_path):
    path = str(tmp_path / "lineage.jsonl")
    rec = lineage.LineageRecorder(path, run="r1")
    rec.mark("decoded", ids=["a", "b"])
    rec.mark("emitted", id="a", rank=0)
    rec.mark("absorbed", run="r2", id="x", absorber="y",
             rule="harmonic", margin=1e-4)
    rec.close()
    with open(path, "a") as f:
        f.write('{"v": 1, "run": "r1", "kind": "torn')  # crashed tail
        f.write("\n")
        f.write(json.dumps({"v": 99, "run": "r1",
                            "kind": "future"}) + "\n")
    marks = lineage.read_lineage(path)
    assert [m["kind"] for m in marks] == ["decoded", "emitted",
                                          "absorbed"]
    assert all(m["v"] == lineage.LINEAGE_VERSION for m in marks)
    only_r1 = lineage.read_lineage(path, run="r1")
    assert [m["kind"] for m in only_r1] == ["decoded", "emitted"]
    # None-valued fields are elided, not serialised
    assert "rank" in marks[1] and "margin" in marks[2]


def test_recorder_rotates_and_reader_spans_generations(tmp_path):
    path = str(tmp_path / "lineage.jsonl")
    rec = lineage.LineageRecorder(path, run="r", max_bytes=256)
    for i in range(40):
        rec.mark("decoded", ids=[f"c{i}"])
    rec.close()
    assert (tmp_path / "lineage.jsonl.1").exists()
    marks = lineage.read_lineage(path)
    # one sealed generation is retained: the reader sees a contiguous
    # TAIL of the append order ending at the newest mark
    got = [m["ids"][0] for m in marks]
    want = [f"c{i}" for i in range(40)]
    assert 0 < len(got) < 40
    assert got == want[-len(got):]


def test_recorder_io_failure_latches_never_raises(tmp_path):
    blocker = tmp_path / "dir"
    blocker.write_text("a file where the ledger dir should be")
    rec = lineage.LineageRecorder(str(blocker / "lineage.jsonl"))
    before = lineage.overhead()
    rec.mark("decoded", ids=["a"])  # must not raise
    rec.mark("emitted", id="a")
    after = lineage.overhead()
    assert after["errors"] >= before["errors"] + 1
    assert after["marks"] >= before["marks"] + 2


def test_module_level_configure_and_noop_when_off(tmp_path):
    path = str(tmp_path / "lineage.jsonl")
    try:
        lineage.configure_lineage(path, run="r9")
        assert lineage.enabled()
        lineage.mark("decoded", ids=["z"])
        lineage.configure_lineage("")  # the --no-lineage escape hatch
        assert not lineage.enabled()
        lineage.mark("decoded", ids=["never-written"])
    finally:
        lineage.configure_lineage("")
    marks = lineage.read_lineage(path)
    assert len(marks) == 1 and marks[0]["run"] == "r9"


# -------------------------------------------------------------------------
# funnel accounting + the conservation proof
# -------------------------------------------------------------------------

def _marks_ok(run="r"):
    # ids embed the run like real candidate_uid ids do, so a
    # multi-run ledger never collides
    a, b, c, d = (f"{run}-{x}" for x in "abcd")
    return [
        {"v": 1, "run": run, "kind": "decoded",
         "ids": [a, b, c, d]},
        {"v": 1, "run": run, "kind": "clipped", "n": 3},
        {"v": 1, "run": run, "kind": "absorbed", "id": a,
         "absorber": b, "rule": "harmonic", "margin": 1e-4},
        {"v": 1, "run": run, "kind": "cut", "id": c, "stage": "limit"},
        {"v": 1, "run": run, "kind": "scored", "id": b},
        {"v": 1, "run": run, "kind": "emitted", "id": b, "rank": 0},
        {"v": 1, "run": run, "kind": "emitted", "id": d, "rank": 1},
    ]


def test_funnel_conserves_exactly():
    fn = lineage.funnel(_marks_ok())
    assert fn["decoded"] == 4
    assert fn["decoded"] == fn["absorbed"] + fn["cut"] + fn["emitted"]
    assert (fn["absorbed"], fn["cut"], fn["emitted"]) == (1, 1, 2)
    assert fn["clipped"] == 3  # aggregate: counted, outside invariant
    assert fn["pass_frac"] == pytest.approx(0.5)
    assert fn["absorbed_frac"] == pytest.approx(0.25)
    assert lineage.check_conservation(_marks_ok()) == []


def test_funnel_filters_by_run():
    marks = _marks_ok("r1") + _marks_ok("r2")
    assert lineage.funnel(marks, runs=["r1"])["decoded"] == 4
    assert lineage.funnel(marks)["decoded"] == 8
    assert lineage.check_conservation(marks) == []


def test_conservation_detects_each_violation():
    leaked = _marks_ok()[:-1]  # d decoded, no terminal
    assert any("no terminal" in p
               for p in lineage.check_conservation(leaked))
    double = _marks_ok() + [
        {"v": 1, "run": "r", "kind": "cut", "id": "r-d"}]
    assert any("2 terminal states" in p
               for p in lineage.check_conservation(double))
    orphan = _marks_ok() + [
        {"v": 1, "run": "r", "kind": "emitted", "id": "ghost"}]
    assert any("never decoded" in p
               for p in lineage.check_conservation(orphan))


def test_why_chain_recurses_into_absorbed_children():
    marks = _marks_ok() + [
        # an earlier stage: "r-a" had itself absorbed "r-z"
        {"v": 1, "run": "r", "kind": "decoded", "ids": ["r-z"]},
        {"v": 1, "run": "r", "kind": "absorbed", "id": "r-z",
         "absorber": "r-a", "rule": "dm", "margin": 0.5},
    ]
    chain = lineage.why_chain(marks, "r-b")
    assert chain["decoded"] and chain["run"] == "r"
    assert chain["terminal"]["kind"] == "emitted"
    assert [m["kind"] for m in chain["annotations"]] == ["scored"]
    kid = chain["children"][0]
    assert kid["id"] == "r-a" and kid["absorbed_into"] == "r-b"
    assert kid["terminal"]["rule"] == "harmonic"
    grandkid = kid["children"][0]
    assert (grandkid["id"] == "r-z"
            and grandkid["terminal"]["rule"] == "dm")
    # depth limit stops the recursion, never errors
    shallow = lineage.why_chain(marks, "r-b", max_depth=1)
    assert shallow["children"][0]["children"] == []


# -------------------------------------------------------------------------
# warehouse ingest + funnel baselines + the health rule
# -------------------------------------------------------------------------

def test_lineage_rows_and_ingest(tmp_path):
    path = str(tmp_path / "lineage.jsonl")
    rec = lineage.LineageRecorder(path, run="r1")
    for m in _marks_ok("r1"):
        rec.mark(m["kind"], **{k: v for k, v in m.items()
                               if k not in ("v", "run", "kind")})
    rec.close()
    rows = lineage_rows(lineage.read_lineage(path))
    by_metric = {}
    for r in rows:
        by_metric.setdefault(r["metric"], 0.0)
        by_metric[r["metric"]] += r["value"]
    assert by_metric["lineage.decoded"] == 4.0
    assert by_metric["lineage.clipped"] == 3.0  # aggregate uses n
    assert by_metric["lineage.emitted"] == 2.0

    wh = Warehouse(str(tmp_path / "wh"))
    n = wh.ingest_lineage(path)
    assert n == len(rows) + 3  # + pass_frac / absorbed_frac / decoded
    funnel_rows = wh.rows(stage="funnel")
    got = {r["metric"]: r["value"] for r in funnel_rows}
    assert got["lineage.pass_frac"] == pytest.approx(0.5)
    assert got["lineage.absorbed_frac"] == pytest.approx(0.25)
    assert got["lineage.decoded"] == 4.0


def _serve_rec(i, pass_frac, absorbed_frac, decoded=100):
    return {"kind": "serve", "utc": 1000.0 + 60.0 * i,
            "config": {"worker": "w0"},
            "metrics": {"lineage_decoded": decoded,
                        "lineage_pass_frac": pass_frac,
                        "lineage_absorbed_frac": absorbed_frac}}


def _collapse_ctx(ledger):
    return HealthContext(now=time.time(), samples=[], recent=[],
                         latest={}, queue={}, running=[],
                         ledger=list(ledger))


def test_distill_collapse_needs_baseline():
    recs = [_serve_rec(i, 0.3, 0.6) for i in range(2)]
    (f,) = rule_distill_collapse(_collapse_ctx(recs))
    assert f.severity == "ok" and "baseline" in f.message
    # funnel-free serve records don't count toward the baseline
    recs += [_serve_rec(9, 0.0, 0.0, decoded=0)] * 5
    (f,) = rule_distill_collapse(_collapse_ctx(recs))
    assert f.severity == "ok" and f.data["records"] == 2


def test_distill_collapse_bands():
    steady = [_serve_rec(i, 0.30, 0.60) for i in range(4)]
    (f,) = rule_distill_collapse(_collapse_ctx(steady))
    assert f.severity == "ok"
    shifted = steady[:-1] + [_serve_rec(9, 0.10, 0.90)]
    (f,) = rule_distill_collapse(_collapse_ctx(shifted))
    assert f.severity == "warn"
    collapsed = steady[:-1] + [_serve_rec(9, 0.005, 0.62)]
    (f,) = rule_distill_collapse(_collapse_ctx(collapsed))
    assert f.severity == "crit" and "why" in f.message


def test_funnel_anomalies_attribute_the_shift():
    steady = [_serve_rec(i, 0.30, 0.60) for i in range(5)]
    assert funnel_anomalies(steady) == []
    shifted = steady + [_serve_rec(9, 0.05, 0.95)]
    anoms = funnel_anomalies(shifted)
    metrics = {a["metric"] for a in anoms}
    assert metrics == {"lineage_pass_frac", "lineage_absorbed_frac"}
    for a in anoms:
        assert a["kind"] == "anomaly"
        assert a["key"]["stage"] == "distill"
        assert a["key"]["host"] == "w0"
    # funnel-free records alone -> nothing to judge
    assert funnel_anomalies(
        [_serve_rec(i, 0.0, 0.0, decoded=0) for i in range(6)]) == []


# -------------------------------------------------------------------------
# satellite: count_assoc == binary pre-order flatten == <nassoc>
# -------------------------------------------------------------------------

def _assoc_tree():
    """root absorbs two candidates, one of which absorbed another —
    the nested shape the distillers actually produce."""
    leaf = Candidate(dm=1.0, dm_idx=1, acc=0.5, jerk=0.25, nh=1,
                     snr=5.0, freq=200.0)
    mid = Candidate(dm=2.0, dm_idx=2, acc=1.0, jerk=-0.5, nh=2,
                    snr=7.0, freq=100.0, assoc=[leaf])
    sib = Candidate(dm=3.0, dm_idx=3, acc=-1.0, jerk=0.0, nh=1,
                    snr=6.0, freq=50.0)
    root = Candidate(dm=4.0, dm_idx=4, acc=2.0, jerk=1.5, nh=4,
                     snr=9.0, freq=25.0, assoc=[mid, sib])
    lone = Candidate(dm=5.0, dm_idx=5, acc=0.0, jerk=0.0, nh=1,
                     snr=4.0, freq=10.0)
    return [root, lone]


def test_nassoc_pins_preorder_flatten_and_xml(tmp_path):
    cands = _assoc_tree()
    root = cands[0]
    assert root.count_assoc() == 3  # mid + leaf + sib
    # count_assoc is exactly the flattened tree minus the candidate
    for c in cands:
        assert c.count_assoc() == len(c.collect()) - 1

    # binary layout: ndets per candidate == 1 + count_assoc, rows in
    # the same pre-order collect() walks, jerk column intact
    path = str(tmp_path / "candidates.peasoup")
    mapping = write_candidate_binary(cands, path)
    with CandidateFileParser(path) as parser:
        for ii, c in enumerate(cands):
            _, hits = parser.cand_from_offset(mapping[ii])
            flat = c.collect()
            assert len(hits) == 1 + c.count_assoc() == len(flat)
            for row, d in zip(hits, flat):
                assert row["dm_idx"] == d.dm_idx
                assert row["freq"] == pytest.approx(d.freq)
                assert row["jerk"] == pytest.approx(d.jerk)

    # XML: <nassoc> must agree with the binary block it points into
    w = OutputFileWriter()
    w.add_candidates(cands, mapping,
                     cand_ids=[lineage.candidate_uid("r", c)
                               for c in cands])
    tree = ET.fromstring(w.to_string())
    els = tree.findall(".//candidate")
    assert len(els) == len(cands)
    for el, c in zip(els, cands):
        assert int(float(el.findtext("nassoc"))) == c.count_assoc()
        assert el.findtext("candidate_id") == lineage.candidate_uid(
            "r", c)
