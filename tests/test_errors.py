"""Typed-exception layer (`peasoup_tpu/errors.py`) — one test per
class, raised by the real guard sites (the reference's ErrorChecker
pattern, `include/utils/exceptions.hpp:13-153`)."""

import io
import os

import numpy as np
import pytest

from peasoup_tpu.errors import (
    CheckpointError,
    ConfigError,
    DomainError,
    HBMBudgetError,
    InputFileError,
    PeasoupError,
)


def test_config_error_on_empty_dm_list(tutorial_fil):
    from peasoup_tpu.io.sigproc import read_filterbank
    from peasoup_tpu.search.pipeline import PulsarSearch
    from peasoup_tpu.search.plan import SearchConfig

    fil = read_filterbank(tutorial_fil)
    cfg = SearchConfig(dm_list=np.zeros((0,), np.float32))
    with pytest.raises(ConfigError):
        PulsarSearch(fil, cfg)


def test_input_file_error_on_non_sigproc_bytes():
    from peasoup_tpu.io.sigproc import read_sigproc_header

    with pytest.raises(InputFileError):
        read_sigproc_header(io.BytesIO(b"this is not a sigproc header"))


def test_hbm_budget_error_when_filterbank_exceeds_budget(tutorial_fil):
    from peasoup_tpu.io.sigproc import read_filterbank
    from peasoup_tpu.parallel.mesh import MeshPulsarSearch
    from peasoup_tpu.search.plan import SearchConfig

    fil = read_filterbank(tutorial_fil)
    cfg = SearchConfig(
        dm_list=np.array([0.0, 10.0], np.float32), hbm_budget_gb=1e-9,
    )
    search = MeshPulsarSearch(fil, cfg)
    with pytest.raises(HBMBudgetError):
        search._plan_chunking(search.acc_plan.max_trials(search.dm_list))


def test_domain_error_on_out_of_domain_resample_shift():
    from peasoup_tpu.ops.resample import resample2_tables

    # 4*max_shift >= n: the staircase bisection's validity bound
    with pytest.raises(DomainError):
        resample2_tables(
            np.array([500.0], np.float64), tsamp=6.4e-5, n=1024,
            max_shift=512, block=128,
        )


def test_checkpoint_error_classified_as_torn(tmp_path):
    """A newline-less header is torn: load() must treat the file as
    unusable (warn + None) — the torn classification is the typed
    CheckpointError raised internally."""
    from peasoup_tpu.search.checkpoint import SearchCheckpoint

    path = os.path.join(tmp_path, "ckpt.jsonl")
    with open(path, "w") as f:
        f.write('{"version": 3, "key": "k"}')  # no trailing newline
    ck = SearchCheckpoint(path, key="k")
    with pytest.warns(UserWarning, match="unterminated header"):
        assert ck.load() is None


def test_hierarchy_and_builtin_compat():
    # every class is catchable as PeasoupError AND as the builtin its
    # guard historically raised
    assert issubclass(ConfigError, (PeasoupError, ValueError))
    assert issubclass(DomainError, (PeasoupError, ValueError))
    assert issubclass(HBMBudgetError, (PeasoupError, ValueError))
    assert issubclass(CheckpointError, (PeasoupError, ValueError))
    assert issubclass(InputFileError, PeasoupError)
    assert issubclass(InputFileError, OSError)
    assert issubclass(InputFileError, ValueError)
