"""ISSUE 11: depth-2 async dispatch pipeline + on-device fold fusion.

Covers the DispatchPipeline scheduling contract, the trace-proven
dispatch/fetch overlap on the chunked driver (and its absence at
depth 1), bit-identical candidates across pipeline depths, the fused
fold program against the resident-trials fold, the bounded
FoldInputCache, classified prefetch misses, and the device_duty_cycle
ledger gauge.
"""

import copy

import numpy as np
import pytest

from peasoup_tpu.parallel.dispatch import DispatchPipeline
from peasoup_tpu.search.plan import SearchConfig


# ---------------------------------------------------------------------------
# DispatchPipeline unit contract (pure python, no jax)
# ---------------------------------------------------------------------------


def _instrumented(events, depth, items, start_fetch=False):
    def dispatch(item):
        events.append(("d", item))
        return f"tok{item}"

    def retire(token, item):
        events.append(("r", item))
        assert token == f"tok{item}"
        return item * 10

    sf = None
    if start_fetch:
        def sf(token):  # noqa: E306
            events.append(("f", token))
    pipe = DispatchPipeline(dispatch, retire, depth=depth, start_fetch=sf)
    return pipe, pipe.run(items)


def test_pipeline_depth1_is_serial():
    events = []
    _, results = _instrumented(events, 1, [0, 1, 2])
    assert events == [("d", 0), ("r", 0), ("d", 1), ("r", 1),
                      ("d", 2), ("r", 2)]
    assert results == [0, 10, 20]


def test_pipeline_depth2_keeps_one_chunk_in_flight():
    """The historical double-buffer order: dispatch N+1 is enqueued
    BEFORE chunk N is retired, so the device computes while the host
    decodes."""
    events = []
    pipe, results = _instrumented(events, 2, [0, 1, 2])
    assert events == [("d", 0), ("d", 1), ("r", 0), ("d", 2),
                      ("r", 1), ("r", 2)]
    assert results == [0, 10, 20]
    assert pipe.max_inflight == 2


def test_pipeline_depth3_window():
    events = []
    pipe, results = _instrumented(events, 3, list(range(5)))
    assert events == [("d", 0), ("d", 1), ("d", 2), ("r", 0),
                      ("d", 3), ("r", 1), ("d", 4), ("r", 2),
                      ("r", 3), ("r", 4)]
    assert results == [0, 10, 20, 30, 40]
    assert pipe.max_inflight == 3


def test_pipeline_start_fetch_runs_at_dispatch_time():
    """start_fetch(token) must fire immediately after each dispatch —
    before ANY retire of that token — so the d2h copy overlaps the
    next chunk's compute."""
    events = []
    _, _ = _instrumented(events, 2, [0, 1], start_fetch=True)
    assert events == [("d", 0), ("f", "tok0"), ("d", 1), ("f", "tok1"),
                      ("r", 0), ("r", 1)]


def test_pipeline_fewer_items_than_depth():
    events = []
    pipe, results = _instrumented(events, 4, [0, 1])
    assert events == [("d", 0), ("d", 1), ("r", 0), ("r", 1)]
    assert results == [0, 10]
    assert pipe.max_inflight == 2


def test_pipeline_empty_and_bad_depth():
    from peasoup_tpu.errors import ConfigError

    assert DispatchPipeline(lambda i: i, lambda t, i: i).run([]) == []
    with pytest.raises(ConfigError):
        DispatchPipeline(lambda i: i, lambda t, i: i, depth=0)


# ---------------------------------------------------------------------------
# Chunked-driver overlap + depth parity (small synthetic observation)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def synth_fil(tmp_path_factory):
    """Small 8-bit observation with a pulse train (batch_smoke recipe)."""
    from peasoup_tpu.io import read_filterbank
    from peasoup_tpu.io.sigproc import (
        Filterbank, SigprocHeader, write_filterbank,
    )

    rng = np.random.default_rng(7)
    nsamps, nchans = 4096, 16
    data = rng.integers(0, 32, size=(nsamps, nchans), dtype=np.uint8)
    data[::16] += 60
    hdr = SigprocHeader(nbits=8, nchans=nchans, tsamp=0.000256,
                        fch1=1510.0, foff=-10.0, nsamples=nsamps)
    path = tmp_path_factory.mktemp("pipeline") / "synth.fil"
    write_filterbank(str(path), Filterbank(header=hdr, data=data))
    return read_filterbank(str(path))


def _chunked_cfg(depth, **kw):
    # dm_chunk=1 over the 8-device test mesh -> 3 chunks per device,
    # enough pipeline stages to observe (or rule out) overlap
    return SearchConfig(dm_start=0.0, dm_end=20.0, acc_start=-5.0,
                        acc_end=5.0, acc_pulse_width=64000.0, npdmp=0,
                        limit=10, min_snr=6.0, dm_chunk=1, accel_block=1,
                        pipeline_depth=depth, **kw)


def _cand_tuples(result):
    return [(float(c.freq), float(c.snr), float(c.dm), float(c.acc),
             int(c.nh), float(c.folded_snr))
            for c in result.candidates]


def _run_traced(fil, depth, path):
    from peasoup_tpu.obs.trace import get_tracer, write_merged_trace
    from peasoup_tpu.parallel.mesh import MeshPulsarSearch
    from peasoup_tpu.tools.trace_report import load_events, rebuild_spans

    get_tracer().reset()
    result = MeshPulsarSearch(fil, _chunked_cfg(depth)).run()
    write_merged_trace(str(path), tracer=get_tracer(),
                       gather=lambda b: [b], process_index=0)
    return result, rebuild_spans(load_events(str(path)))


def _chunk_spans(spans):
    dispatches = {s["args"]["chunk"]: s for s in spans
                  if s["name"].startswith("Chunked-Search-")}
    fetches = {s["args"]["chunk"]: s for s in spans
               if s["name"] == "Chunk-Fetch"}
    assert set(dispatches) == set(fetches)
    return dispatches, fetches


def test_chunked_depth2_overlaps_depth1_does_not(synth_fil, tmp_path):
    """The ledger proof of ISSUE 11's tentpole: at depth 2 the trace
    shows dispatch N+1 enqueued before fetch N completes; at depth 1
    every fetch strictly precedes the next dispatch.  And the pipeline
    is pure scheduling — candidates are bit-identical across depths."""
    r2, spans2 = _run_traced(synth_fil, 2, tmp_path / "d2.trace.json")
    r1, spans1 = _run_traced(synth_fil, 1, tmp_path / "d1.trace.json")

    assert _cand_tuples(r1) == _cand_tuples(r2)
    assert len(r2.candidates) > 0

    d2, f2 = _chunk_spans(spans2)
    assert len(d2) >= 2, "need >=2 chunks to observe pipelining"
    for ci in sorted(d2)[:-1]:
        fetch_end = f2[ci]["ts"] + f2[ci]["dur_ms"] * 1e3
        assert d2[ci + 1]["ts"] < fetch_end, (
            f"depth 2 must dispatch chunk {ci + 1} before fetch "
            f"{ci} completes")

    d1, f1 = _chunk_spans(spans1)
    for ci in sorted(d1)[:-1]:
        assert d1[ci + 1]["ts"] >= f1[ci]["ts"] + f1[ci]["dur_ms"] * 1e3, (
            f"depth 1 must retire chunk {ci} before dispatching "
            f"{ci + 1}")


def test_chunked_run_reports_duty_cycle_and_depth(synth_fil, tmp_path):
    from peasoup_tpu.obs.metrics import REGISTRY
    from peasoup_tpu.parallel.mesh import MeshPulsarSearch

    MeshPulsarSearch(synth_fil, _chunked_cfg(2)).run()
    gauges = REGISTRY.snapshot()["gauges"]
    assert gauges.get("chunk.pipeline_depth") == 2
    assert "device_duty_cycle" in gauges
    assert 0.0 <= gauges["device_duty_cycle"] <= 1.5


# ---------------------------------------------------------------------------
# On-device fold fusion: fused program == resident-trials fold
# ---------------------------------------------------------------------------


def test_fused_fold_matches_resident_trials_fold(synth_fil):
    """_fused_fold_provider's one-dispatch unpack->dedisperse->fold
    must reproduce the resident-trials fold bit for bit (same device
    ops on the same rows, only the materialisation point moves)."""
    from peasoup_tpu.parallel.mesh import MeshPulsarSearch
    from peasoup_tpu.search.pipeline import fold_candidates

    cfg = SearchConfig(dm_start=0.0, dm_end=20.0, acc_start=-5.0,
                       acc_end=5.0, acc_pulse_width=64000.0, npdmp=0,
                       limit=10, min_snr=6.0)
    search = MeshPulsarSearch(synth_fil, cfg)
    result = search.run()
    assert len(result.candidates) >= 2
    npdmp = min(4, len(result.candidates))
    hdr = synth_fil.header

    host = [copy.deepcopy(c) for c in result.candidates]
    trials = search._maybe_quantise(search.dedisperse_sharded())
    fold_candidates(host, trials, search.out_nsamps, hdr.tsamp, npdmp)

    fused = [copy.deepcopy(c) for c in result.candidates]
    dm_idxs = sorted({c.dm_idx for c in fused[:npdmp]})
    fp, row_map = search._fused_fold_provider(dm_idxs)
    fold_candidates(fused, None, search.out_nsamps, hdr.tsamp, npdmp,
                    dm_row_lookup=row_map, fold_program=fp)

    assert [c.folded_snr for c in fused] == [c.folded_snr for c in host]
    assert [c.opt_period for c in fused] == [c.opt_period for c in host]
    assert _cand_tuples_like(fused) == _cand_tuples_like(host)


def _cand_tuples_like(cands):
    return [(float(c.freq), float(c.snr), float(c.folded_snr))
            for c in cands]


# ---------------------------------------------------------------------------
# FoldInputCache bound + eviction counter
# ---------------------------------------------------------------------------


def test_fold_input_cache_is_bounded_lru():
    from peasoup_tpu.obs.metrics import REGISTRY
    from peasoup_tpu.search.pipeline import FoldInputCache

    before = REGISTRY.snapshot()["counters"].get("fold.cache_evicted", 0)
    cache = FoldInputCache(maxsize=2)
    cache["a"] = 1
    cache["b"] = 2
    assert cache.get("a") == 1  # refresh: "a" is now most-recent
    cache["c"] = 3  # evicts "b", the least-recently-used
    assert list(cache) == ["a", "c"]
    assert cache.get("b") is None
    after = REGISTRY.snapshot()["counters"].get("fold.cache_evicted", 0)
    assert after == before + 1


# ---------------------------------------------------------------------------
# Prefetch miss classification
# ---------------------------------------------------------------------------


def test_prefetch_miss_records_classified_kind(tmp_path):
    from peasoup_tpu.obs.metrics import REGISTRY
    from peasoup_tpu.serve.worker import ObservationPrefetcher

    bad = tmp_path / "garbage.fil"
    bad.write_bytes(b"this is not a filterbank")
    pf = ObservationPrefetcher(slots=1)
    pf.start(str(bad))
    before = REGISTRY.snapshot()["counters"]
    assert pf.take(str(bad)) is None
    after = REGISTRY.snapshot()["counters"]
    assert (after.get("scheduler.prefetch_misses", 0)
            == before.get("scheduler.prefetch_misses", 0) + 1)
    kinds = {k for k in after
             if k.startswith("scheduler.prefetch_miss.")
             and after[k] > before.get(k, 0)}
    assert len(kinds) == 1, "exactly one classified miss kind"


def test_prefetch_never_started_is_a_silent_miss(tmp_path):
    from peasoup_tpu.obs.metrics import REGISTRY
    from peasoup_tpu.serve.worker import ObservationPrefetcher

    pf = ObservationPrefetcher(slots=1)
    before = REGISTRY.snapshot()["counters"]
    assert pf.take(str(tmp_path / "never_started.fil")) is None
    after = REGISTRY.snapshot()["counters"]
    assert (after.get("scheduler.prefetch_misses", 0)
            == before.get("scheduler.prefetch_misses", 0) + 1)
    assert not any(k.startswith("scheduler.prefetch_miss.")
                   and after[k] > before.get(k, 0) for k in after)
