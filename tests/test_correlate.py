"""DelayFinder tests (`include/transforms/correlator.hpp:33-92`)."""

import numpy as np
import pytest

from peasoup_tpu.ops.correlate import distance_to_lag, find_delays


def _delayed(base, lag):
    """x_j(t) = x_i(t - lag) -> correlation peaks at +lag."""
    return np.roll(base, lag)


def test_recovers_known_delays():
    rng = np.random.default_rng(0)
    size, md = 4096, 64
    base = rng.normal(size=size)
    arrays = np.stack([
        base,
        _delayed(base, 5),
        _delayed(base, -17),
    ]).astype(np.complex64)
    out = find_delays(arrays, md)
    assert len(out) == 3  # baselines (0,1), (0,2), (1,2)
    by_pair = {(r["i"], r["j"]): r for r in out}
    assert by_pair[(0, 1)]["lag"] == 5
    assert by_pair[(0, 2)]["lag"] == -17
    assert by_pair[(1, 2)]["lag"] == -22

    # distance is the raw window index the reference prints
    assert by_pair[(0, 1)]["distance"] == 5
    assert by_pair[(0, 2)]["distance"] == 2 * md - 17


def test_distance_to_lag_window_mapping():
    assert distance_to_lag(0, 32) == 0
    assert distance_to_lag(31, 32) == 31
    assert distance_to_lag(32, 32) == -32
    assert distance_to_lag(63, 32) == -1


def test_matches_numpy_reference():
    """Window power must equal a direct numpy correlation."""
    rng = np.random.default_rng(3)
    size, md = 1024, 16
    a = rng.normal(size=size) + 1j * rng.normal(size=size)
    b = rng.normal(size=size) + 1j * rng.normal(size=size)
    corr = np.fft.ifft(np.conj(np.fft.fft(a)) * np.fft.fft(b))
    window = np.concatenate([corr[:md], corr[-md:]])
    want = int(np.argmax(np.abs(window) ** 2))
    out = find_delays(np.stack([a, b]).astype(np.complex64), md)
    assert out[0]["distance"] == want


def test_no_baselines_for_single_antenna():
    assert find_delays(np.zeros((1, 128), np.complex64), 8) == []


def test_accmap_cli(tmp_path, capsys):
    """`peasoup-tpu accmap` recovers a known inter-antenna delay from a
    raw complex8 file (the reference accmap.cpp payload layout)."""
    from peasoup_tpu.cli import main

    rng = np.random.default_rng(7)
    size, lag = 4096, 37
    base = rng.integers(-60, 60, size + lag)
    a = base[:size]
    b = base[lag : size + lag]  # antenna 1 sees the signal `lag` early
    raw = np.zeros((2, size, 2), np.int8)
    raw[0, :, 0] = a
    raw[1, :, 0] = b
    path = tmp_path / "antennas.bin"
    raw.tofile(path)
    rc = main(["accmap", str(path), "--nant", "2", "--size", str(size),
               "--max_delay", "128"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "baseline 0-1" in out
    assert f"lag {lag} " in out or f"lag {-lag} " in out
