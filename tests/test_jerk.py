"""Jerk-search axis + quantized trial lattice tests (ISSUE 13).

Covers the grid plumbing (JerkPlan, combine_trials, 3-axis geometry),
the resampler's cubic index ramp (zero-jerk bit-identity, numpy
reference parity, host-exact (accel, jerk) pair tables), the
trial-lattice parity gate (sidecar round-trip, refusal on failed
verdicts, forced overrides), checkpoint v4 -> v5 migration, the
JerkDistiller, and a synthetic end-to-end zero-jerk bit-identity run
through the fused mesh driver."""

import json
import os

import jax.numpy as jnp
import numpy as np
import pytest

from peasoup_tpu.data.candidates import Candidate
from peasoup_tpu.errors import ConfigError
from peasoup_tpu.ops.resample import (
    resample2,
    resample2_from_tables,
    resample2_max_shift,
    resample2_unique_tables,
)
from peasoup_tpu.search.plan import (
    JerkPlan,
    SearchConfig,
    combine_trials,
    trial_grid_geometry,
)

rng = np.random.default_rng(7)

SPEED_OF_LIGHT = 299792458.0


# --------------------------------------------------------------------------
# trial grid plumbing
# --------------------------------------------------------------------------

def test_jerk_plan_grid():
    p = JerkPlan(-10.0, 10.0, 5.0)
    np.testing.assert_array_equal(
        p.jerk_list(), np.array([-10, -5, 0, 5, 10], np.float32))
    assert p.njerk == 5 and p.max_abs == 10.0
    # forced zero when the range straddles it off-grid
    assert 0.0 in JerkPlan(-7.0, 7.0, 5.0).jerk_list()
    # collapse to one trial
    one = JerkPlan(3.0, 3.0, 0.0)
    assert one.njerk == 1 and one.jerk_list()[0] == 3.0
    zero = JerkPlan(0.0, 0.0, 0.0)
    assert zero.njerk == 1 and zero.max_abs == 0.0


def test_jerk_plan_errors():
    with pytest.raises(ConfigError):
        JerkPlan(5.0, -5.0, 1.0)
    with pytest.raises(ConfigError):
        JerkPlan(-5.0, 5.0, 0.0)


def test_combine_trials_ordering():
    acc = np.array([0.0, 1.0, 2.0], np.float32)
    jerks = np.array([-5.0, 0.0, 5.0], np.float32)
    accs, js = combine_trials(acc, jerks)
    assert len(accs) == len(js) == 9
    # accel varies fastest: slot k -> acc[k % na], jerk[k // na]
    na = len(acc)
    for k in range(9):
        assert accs[k] == acc[k % na]
        assert js[k] == jerks[k // na]


def test_combine_trials_zero_jerk_is_identity():
    """The single-zero-jerk combine returns the SAME accel array object
    (structural bit-identity for the accel-only path)."""
    acc = np.array([0.0, 1.0], np.float32)
    accs, js = combine_trials(acc, np.array([0.0], np.float32))
    assert accs is acc
    assert js.dtype == np.float32 and not js.any()


def test_trial_grid_geometry_jerk_axis():
    from peasoup_tpu.search.plan import AccelerationPlan

    plan = AccelerationPlan(-5.0, 5.0, 1.10, 64000.0, 1 << 17,
                            6.4e-5, 1510.0, -10.0)
    dms = np.asarray([0.0, 50.0], np.float32)
    flat = trial_grid_geometry(dms, plan)
    jp = JerkPlan(-10.0, 10.0, 5.0)
    cubed = trial_grid_geometry(dms, plan, jerk_plan=jp)
    assert cubed.njerk == 5
    assert cubed.n_trials_total == 5 * flat.n_trials_total
    assert cubed.n_dm == flat.n_dm and cubed.namax == flat.namax


def test_search_config_jerk_defaults():
    cfg = SearchConfig()
    assert cfg.jerk_start == cfg.jerk_end == cfg.jerk_step == 0.0
    assert cfg.trial_lattice == "auto"


# --------------------------------------------------------------------------
# cubic index ramp
# --------------------------------------------------------------------------

def _ref_jerk_numpy(tim, accel, jerk, tsamp):
    """Plain-gather kernel-II reference with the cubic jerk term."""
    n = len(tim)
    af = accel * tsamp / (2.0 * SPEED_OF_LIGHT)
    jf = jerk * tsamp * tsamp / (6.0 * SPEED_OF_LIGHT)
    i = np.arange(n, dtype=np.float64)
    idx = np.rint(i + i * af * (i - float(n))
                  + i * jf * (i - float(n)) * (i + float(n)))
    return tim[np.clip(idx.astype(np.int64), 0, n - 1)]


@pytest.mark.parametrize("accel,jerk", [
    (0.0, 2e6), (125.5, -2e6), (-125.5, 5e5), (5.0, 0.0),
])
def test_resample2_jerk_matches_numpy(accel, jerk):
    n = 1 << 14
    tsamp = 0.000064
    tim = rng.normal(size=n).astype(np.float32)
    got = np.asarray(resample2(jnp.asarray(tim), accel, tsamp,
                               jerk=jerk))
    np.testing.assert_array_equal(
        got, _ref_jerk_numpy(tim, accel, jerk, tsamp))


def test_resample2_zero_jerk_bit_identical():
    """jerk=0.0 must be the PRE-JERK program: identical jaxpr (the
    static-zero gate keeps the cubic term out of the trace entirely)
    and identical output."""
    import jax

    n = 1 << 12
    tsamp = 0.000064
    tim = jnp.asarray(rng.normal(size=n).astype(np.float32))
    old = jax.make_jaxpr(
        lambda t: resample2(t, 125.5, tsamp))(tim)
    new = jax.make_jaxpr(
        lambda t: resample2(t, 125.5, tsamp, jerk=0.0))(tim)
    assert str(old) == str(new)
    np.testing.assert_array_equal(
        np.asarray(resample2(tim, 125.5, tsamp)),
        np.asarray(resample2(tim, 125.5, tsamp, jerk=0.0)))


def test_resample2_max_shift_jerk_bound():
    """The static bound covers the true peak displacement of the cubic
    ramp (an under-bound would silently clip device slices)."""
    n = 1 << 14
    tsamp = 0.000064
    for accel, jerk in ((125.5, 2e6), (0.0, 5e6), (500.0, 0.0)):
        ms = resample2_max_shift(accel, tsamp, n, max_jerk=jerk)
        af = accel * tsamp / (2.0 * SPEED_OF_LIGHT)
        jf = jerk * tsamp * tsamp / (6.0 * SPEED_OF_LIGHT)
        i = np.arange(n, dtype=np.float64)
        shift = i * af * (i - float(n)) + i * jf * (i - n) * (i + n)
        assert ms >= np.abs(shift).max()


def test_resample2_unique_pair_tables_exact():
    """(accel, jerk) pair tables are bit-exact with the on-device
    gather ramp for every grid slot, and dedup by PAIR (same accel
    under two jerks must not alias)."""
    from peasoup_tpu.ops.resample import residual_width_jerk

    n, tsamp, block = 1 << 14, 0.000064, 1024
    accs = np.array([[0.0, 50.0, np.nan],
                     [0.0, -50.0, 50.0]], np.float32)
    jerks = np.array([[1e6, 1e6, np.nan],
                      [-1e6, 1e6, 1e6]], np.float32)
    ms = resample2_max_shift(50.0, tsamp, n, max_jerk=1e6)
    width = residual_width_jerk(50.0, 1e6, tsamp, block, n)
    d0, pos, step, uidx = resample2_unique_tables(
        accs, tsamp, n, ms, block=block, jerks_grid=jerks, width=width)
    # unique pairs: (-50,1e6) (0,-1e6) (0,0 <- NaN pad) (0,1e6)
    # (50,1e6) -> 5 rows
    assert d0.shape[0] == 5
    assert uidx[0, 0] != uidx[1, 0]  # same accel, different jerk
    tim = rng.normal(size=n).astype(np.float32)
    for (r, c), acc in np.ndenumerate(accs):
        if np.isnan(acc):
            continue
        u = int(uidx[r, c])
        got = np.asarray(resample2_from_tables(
            jnp.asarray(tim), jnp.asarray(d0[u]), jnp.asarray(pos[u]),
            jnp.asarray(step[u]), ms, block=block))
        np.testing.assert_array_equal(
            got, _ref_jerk_numpy(tim, float(acc), float(jerks[r, c]),
                                 tsamp))


# --------------------------------------------------------------------------
# trial lattice: quantisers + parity-gated tuner sidecar
# --------------------------------------------------------------------------

def test_quantise_trials_bf16_properties():
    from peasoup_tpu.ops.dedisperse import quantise_trials_bf16

    trials = jnp.asarray(
        rng.normal(size=(4, 256)).astype(np.float32) * 100.0)
    q = quantise_trials_bf16(trials)
    assert q.dtype == jnp.float32  # widened back for the FFT chain
    err = np.abs(np.asarray(q) - np.asarray(trials))
    # bf16 keeps 8 significand bits: relative error < 2^-8
    assert (err <= np.abs(np.asarray(trials)) * 2.0 ** -8 + 1e-12).all()
    # idempotent: a bf16 lattice re-quantises to itself
    np.testing.assert_array_equal(np.asarray(quantise_trials_bf16(q)),
                                  np.asarray(q))


def test_lattice_sidecar_roundtrip(tmp_path):
    from peasoup_tpu.search.tuning import (
        load_lattice,
        resolve_trial_lattice,
        update_lattice,
    )

    path = str(tmp_path / "tune.json")
    good = {"ok": True, "max_snr_delta": 0.01, "candidates_moved": 0}
    update_lattice(path, "TPU v5 lite", "dedisperse", 1 << 21,
                   costs={"f32": 2.0, "u8": 0.8, "bf16": 1.2},
                   picked="u8", parity={"u8": good, "bf16": good})
    sec = load_lattice(path)
    assert "TPU v5 lite" in sec
    got = resolve_trial_lattice(
        "auto", device_kind="TPU v5 lite", sidecar=path,
        stage="dedisperse", nsamps=1 << 21)
    assert got == "u8"
    # other cells / devices fall back to f32
    assert resolve_trial_lattice(
        "auto", device_kind="TPU v4", sidecar=path,
        stage="dedisperse", nsamps=1 << 21) == "f32"
    assert resolve_trial_lattice(
        "auto", device_kind="TPU v5 lite", sidecar=path,
        stage="dedisperse", nsamps=1 << 10) == "f32"


def test_lattice_parity_gate_refuses(tmp_path):
    """A pick whose parity verdict failed (or moved a candidate) must
    NOT engage — quantisation never engages silently."""
    from peasoup_tpu.search.tuning import (
        resolve_trial_lattice,
        update_lattice,
    )

    path = str(tmp_path / "tune.json")
    update_lattice(
        path, "cpu", "dedisperse", 1 << 20,
        costs={"f32": 2.0, "u8": 0.5},
        picked="u8",
        parity={"u8": {"ok": True, "max_snr_delta": 0.4,
                       "candidates_moved": 2}})
    assert resolve_trial_lattice(
        "auto", device_kind="cpu", sidecar=path,
        stage="dedisperse", nsamps=1 << 20) == "f32"
    # a cheap dtype with NO parity entry is equally refused
    path2 = str(tmp_path / "tune2.json")
    update_lattice(path2, "cpu", "dedisperse", 1 << 20,
                   costs={"f32": 2.0, "bf16": 0.5})
    assert resolve_trial_lattice(
        "auto", device_kind="cpu", sidecar=path2,
        stage="dedisperse", nsamps=1 << 20) == "f32"


def test_lattice_forced_override_and_validation():
    from peasoup_tpu.search.tuning import resolve_trial_lattice

    # a concrete force wins with no sidecar at all
    assert resolve_trial_lattice("bf16") == "bf16"
    assert resolve_trial_lattice("f32") == "f32"
    with pytest.raises(ConfigError):
        resolve_trial_lattice("f16")


# --------------------------------------------------------------------------
# checkpoint migration
# --------------------------------------------------------------------------

def _synthetic_fil(tmp_path):
    from peasoup_tpu.io import read_filterbank
    from peasoup_tpu.tools.batch_smoke import _write_synthetic

    path = _write_synthetic(str(tmp_path / "obs.fil"), seed=3)
    return path, read_filterbank(path)


def test_checkpoint_v4_migration(tmp_path):
    """A v4 (pre-jerk) checkpoint resumes under v5 iff the search is
    jerk-free with an f32/auto lattice; its rows deserialise with
    jerk=0.0."""
    from peasoup_tpu.search.checkpoint import (
        SearchCheckpoint,
        _cand_to_obj,
        legacy_search_keys,
        search_key,
    )

    path, fil = _synthetic_fil(tmp_path)
    cfg = SearchConfig(dm_end=20.0)
    key5 = search_key(path, fil, cfg)
    legacy = legacy_search_keys(path, fil, cfg)
    assert set(legacy) == {4}
    assert legacy[4] != key5
    # simulate the v4 writer: version-4 header + a row without jerk
    row = _cand_to_obj(Candidate(dm=1.0, dm_idx=0, acc=2.0, nh=3,
                                 snr=11.0, freq=7.0))
    row.pop("jerk")
    ck = str(tmp_path / "resume.ckpt")
    with open(ck, "w") as f:
        f.write(json.dumps({"version": 4, "key": legacy[4]}) + "\n")
        f.write(json.dumps({"dm_idx": 0, "cands": [row]}) + "\n")
    with pytest.warns(UserWarning, match="resuming v4 checkpoint"):
        out = SearchCheckpoint(ck, key5, legacy=legacy).load()
    assert out is not None and list(out) == [0]
    assert out[0][0].jerk == 0.0 and out[0][0].acc == 2.0


def test_checkpoint_v4_refused_for_jerk_search(tmp_path):
    """The SAME v4 file must NOT resume a search that grew a jerk axis
    or a non-f32 lattice — different trial grid, different results."""
    from peasoup_tpu.search.checkpoint import (
        SearchCheckpoint,
        legacy_search_keys,
        search_key,
    )

    path, fil = _synthetic_fil(tmp_path)
    flat_cfg = SearchConfig(dm_end=20.0)
    flat_legacy = legacy_search_keys(path, fil, flat_cfg)
    ck = str(tmp_path / "resume.ckpt")
    with open(ck, "w") as f:
        f.write(json.dumps({"version": 4,
                            "key": flat_legacy[4]}) + "\n")
    for cfg in (SearchConfig(dm_end=20.0, jerk_start=-5e6,
                             jerk_end=5e6, jerk_step=5e6),
                SearchConfig(dm_end=20.0, trial_lattice="bf16")):
        assert legacy_search_keys(path, fil, cfg) == {}
        key = search_key(path, fil, cfg)
        with pytest.warns(UserWarning, match="format version 4"):
            out = SearchCheckpoint(
                ck, key, legacy=legacy_search_keys(path, fil, cfg)
            ).load()
        assert out is None


def test_checkpoint_v5_roundtrip_preserves_jerk(tmp_path):
    from peasoup_tpu.search.checkpoint import SearchCheckpoint

    ck = str(tmp_path / "v5.ckpt")
    cands = {2: [Candidate(dm=1.0, dm_idx=2, acc=-3.0, jerk=5e6,
                           nh=2, snr=12.0, freq=50.0)]}
    cp = SearchCheckpoint(ck, "key")
    cp.save(cands)
    out = SearchCheckpoint(ck, "key").load()
    assert out[2][0].jerk == 5e6


def test_jerk_fields_change_search_key(tmp_path):
    from peasoup_tpu.search.checkpoint import search_key

    path, fil = _synthetic_fil(tmp_path)
    base = search_key(path, fil, SearchConfig(dm_end=20.0))
    jerked = search_key(path, fil, SearchConfig(
        dm_end=20.0, jerk_start=-5e6, jerk_end=5e6, jerk_step=5e6))
    latticed = search_key(path, fil, SearchConfig(
        dm_end=20.0, trial_lattice="u8"))
    assert len({base, jerked, latticed}) == 3


# --------------------------------------------------------------------------
# jerk-adjacent distillation
# --------------------------------------------------------------------------

def test_jerk_distiller_absorbs_drift_window():
    from peasoup_tpu.search.distill import JerkDistiller

    tobs = 40.0
    f0 = 50.0
    dj = 2e6
    drift = f0 * dj * tobs * tobs / (6.0 * SPEED_OF_LIGHT)
    assert drift > 0
    cands = [
        Candidate(freq=f0, snr=30.0, jerk=0.0),
        # inside the (signed) drift window of a dj jerk mismatch:
        # delta_jerk = 0 - dj < 0 pulls the window BELOW f0
        Candidate(freq=f0 - 0.5 * drift, snr=20.0, jerk=dj),
        # far outside any window
        Candidate(freq=f0 * 1.5, snr=10.0, jerk=dj),
    ]
    out = JerkDistiller(tobs, 1e-4, keep_related=True).distill(cands)
    assert len(out) == 2
    assert out[0].freq == f0 and out[0].count_assoc() == 1
    # zero jerk spread -> window collapses to the tolerance edge
    tight = [
        Candidate(freq=f0, snr=30.0, jerk=dj),
        Candidate(freq=f0 + 0.5 * drift, snr=20.0, jerk=dj),
    ]
    out2 = JerkDistiller(tobs, 1e-4, keep_related=False).distill(tight)
    assert len(out2) == 2


# --------------------------------------------------------------------------
# end-to-end zero-jerk bit-identity (fused mesh driver, synthetic)
# --------------------------------------------------------------------------

def _run_mesh(path, **overrides):
    from peasoup_tpu.io import read_filterbank
    from peasoup_tpu.parallel.mesh import MeshPulsarSearch

    cfg = SearchConfig(**dict(
        dict(dm_end=20.0, min_snr=6.0, npdmp=0, limit=10), **overrides))
    return MeshPulsarSearch(read_filterbank(path), cfg).run()


def test_mesh_zero_jerk_bit_identity(tmp_path):
    """An explicit zero jerk grid + forced f32 lattice through the
    fused mesh driver returns candidates BIT-identical to the
    accel-only default (the new axis costs nothing when unused)."""
    from peasoup_tpu.tools.batch_smoke import _write_synthetic

    path = _write_synthetic(str(tmp_path / "obs.fil"), seed=5)
    ref = _run_mesh(path)
    zero = _run_mesh(path, jerk_start=0.0, jerk_end=0.0,
                     jerk_step=0.0, trial_lattice="f32")
    fp = lambda res: sorted(
        (c.freq, c.dm, c.acc, c.jerk, c.snr, c.nh)
        for c in res.candidates)
    assert fp(ref) == fp(zero)
    assert all(c.jerk == 0.0 for c in ref.candidates)


def test_tutorial_zero_jerk_bit_identity(tutorial_fil):
    """Same invariant against the reference's shipped tutorial data
    (the golden-parity observation): the jerk-free config spelled
    through the new machinery must reproduce the accel-only
    candidates bit-for-bit."""
    from peasoup_tpu.io import read_filterbank
    from peasoup_tpu.search.pipeline import PulsarSearch

    fil = read_filterbank(tutorial_fil)
    base = dict(dm_start=0.0, dm_end=60.0, acc_start=-5.0,
                acc_end=5.0, acc_pulse_width=64000.0, npdmp=0,
                limit=50)
    ref = PulsarSearch(fil, SearchConfig(**base)).run()
    zero = PulsarSearch(fil, SearchConfig(
        **base, jerk_start=0.0, jerk_end=0.0, jerk_step=0.0,
        trial_lattice="f32")).run()
    fp = lambda res: sorted(
        (c.freq, c.dm, c.acc, c.jerk, c.snr, c.nh)
        for c in res.candidates)
    assert fp(ref) == fp(zero)
