"""ISSUE-6 vertical: tuner-driven peak-extraction method selection.

Covers the measured-cost sidecar (search/tuning.py ``extraction``
section), the per-level resolution and its safety/availability rules,
the picked-path audit trail, the costmodel's per-method peaks formula,
the perf gate's new stage device-time columns, the sweep harness, and
end-to-end forced-method candidate parity on both drivers.
"""

import json
import os

import numpy as np
import pytest

from peasoup_tpu.search import tuning


BOUNDS = ((1, 9228, 0.1), (2, 18456, 0.05), (4, 36909, 0.025),
          (8, 65537, 0.0125), (16, 65537, 0.00625))


# --------------------------------------------------------------------------
# tuning-layer unit tests
# --------------------------------------------------------------------------

def test_stop_bucket_powers_of_two():
    assert tuning.stop_bucket(1) == 1
    assert tuning.stop_bucket(9216) == 16384
    assert tuning.stop_bucket(16384) == 16384
    assert tuning.stop_bucket(36909) == 65536
    assert tuning.stop_bucket(65537) == 131072


def test_update_extraction_roundtrip_and_save_tuning_preserves(tmp_path):
    side = str(tmp_path / "tune.json")
    tuning.update_extraction(side, "TPU v5 lite", 65537, 320,
                             costs={"sort": 5.4e-5, "pallas": 6.2e-6})
    tuning.update_extraction(side, "TPU v5 lite", 65537, 320,
                             picked="pallas")
    sec = tuning.load_extraction(side)
    cell = sec["TPU v5 lite"]["131072/320"]
    assert cell["pallas"] == pytest.approx(6.2e-6)
    assert cell["picked"] == "pallas"
    # the buffer-tuning writer must carry the section across rewrites
    tuning.save_tuning(side, "some-search-key", 100, 2000)
    assert tuning.load_tuning(side, "some-search-key")["cap_hw"] == 100
    sec2 = tuning.load_extraction(side)
    assert sec2 == sec
    # and a search-key MISMATCH still exposes the extraction section
    assert tuning.load_tuning(side, "other-key") is None
    assert tuning.load_extraction(side)["TPU v5 lite"]


def test_resolve_forced_method_wins_everywhere(tmp_path):
    for forced in ("sort", "two_stage", "pallas"):
        got = tuning.resolve_peaks_methods(
            BOUNDS, 320, forced=forced, device_kind="TPU v5 lite")
        assert got == (forced,) * len(BOUNDS)


def test_resolve_rejects_unknown_method():
    from peasoup_tpu.errors import ConfigError

    with pytest.raises(ConfigError, match="peaks_method"):
        tuning.resolve_peaks_methods(BOUNDS, 320, forced="quantum")


def test_resolve_uses_measured_sidecar_argmin(tmp_path):
    side = str(tmp_path / "tune.json")
    # measured: two_stage cheapest at this cell, pallas cheapest but
    # NOT available (pallas_ok=None) -> two_stage must win
    tuning.update_extraction(side, "cpu", 9228, 64,
                             costs={"sort": 5e-5, "two_stage": 1e-5,
                                    "pallas": 1e-6})
    got = tuning.resolve_peaks_methods(
        ((1, 9228, 0.1),), 64, device_kind="cpu", sidecar=side,
        pallas_ok=None)
    assert got == ("two_stage",)
    got = tuning.resolve_peaks_methods(
        ((1, 9228, 0.1),), 64, device_kind="cpu", sidecar=side,
        pallas_ok="compiled")
    assert got == ("pallas",)


def test_resolve_escalated_capacity_reuses_nearest_cell():
    """A clipped row's escalated capacity (e.g. 320 -> 4096) never has
    an exact sweep cell; the nearest-capacity cell in the same stop
    bucket must carry the tuner's verdict so the re-search does not
    recompile the legacy heuristic's sort."""
    bounds = ((0, 131000, 1.0),)  # bucket 131072, below 2^17 heuristic
    # nearest cell to capacity 128 is 131072/64: two_stage measured
    # ~3x cheaper than sort on v5e
    got = tuning.resolve_peaks_methods(
        bounds, 128, device_kind="TPU v5 lite", pallas_ok=None)
    assert got == ("two_stage",)
    # with the kernel available the donor cell's argmin is pallas
    got = tuning.resolve_peaks_methods(
        bounds, 4096, device_kind="TPU v5 lite", pallas_ok="compiled")
    assert got == ("pallas",)


def test_resolve_sidecar_nearest_capacity_and_exact_priority(tmp_path):
    side = str(tmp_path / "tune.json")
    tuning.update_extraction(side, "cpu", 9228, 320,
                             costs={"sort": 5e-5, "two_stage": 1e-5})
    # capacity 4096 has no exact cell: the 320 cell's verdict applies
    got = tuning.resolve_peaks_methods(
        ((1, 9228, 0.1),), 4096, device_kind="cpu", sidecar=side,
        pallas_ok=None)
    assert got == ("two_stage",)
    # an exact cell at the escalated capacity still wins over nearest
    tuning.update_extraction(side, "cpu", 9228, 4096,
                             costs={"sort": 1e-5, "two_stage": 5e-5})
    got = tuning.resolve_peaks_methods(
        ((1, 9228, 0.1),), 4096, device_kind="cpu", sidecar=side,
        pallas_ok=None)
    assert got == ("sort",)
    # other stop buckets never donate cells
    got = tuning.resolve_peaks_methods(
        ((0, 40000, 1.0),), 4096, device_kind="cpu", sidecar=side,
        pallas_ok=None)
    assert got == ("sort",)  # bucket 65536 empty -> heuristic


def test_cell_for_tie_prefers_smaller_capacity():
    table = {"16384/64": {"sort": 1.0}, "16384/576": {"sort": 2.0},
             "32768/320": {"sort": 9.0}, "junk": 3, "a/b": {"sort": 1}}
    cell = tuning._cell_for(table, 16384, 320)  # both 256 away
    assert cell == {"sort": 1.0}
    assert tuning._cell_for(table, 8192, 320) is None


def test_resolve_skips_unsafe_two_stage_cells(tmp_path):
    side = str(tmp_path / "tune.json")
    tuning.update_extraction(side, "cpu", 9228, 64,
                             costs={"sort": 5e-5, "two_stage": 1e-5},
                             safe=False)
    got = tuning.resolve_peaks_methods(
        ((1, 9228, 0.1),), 64, device_kind="cpu", sidecar=side,
        pallas_ok=None)
    assert got == ("sort",)


def test_resolve_default_table_picks_pallas_on_v5e():
    """The committed v5e sweep numbers make the compaction kernel the
    tuned pick at the tutorial's dominant cells when compiled pallas
    is available."""
    got = tuning.resolve_peaks_methods(
        BOUNDS, 320, device_kind="TPU v5 lite", pallas_ok="compiled")
    assert set(got) == {"pallas"}
    # without the kernel, the small-cap cells fall to two_stage where
    # the sweep measured it faster, sort otherwise
    got64 = tuning.resolve_peaks_methods(
        ((1, 9228, 0.1), (8, 65537, 0.0125)), 64,
        device_kind="TPU v5 lite", pallas_ok=None)
    assert got64 == ("two_stage", "two_stage")
    got320 = tuning.resolve_peaks_methods(
        ((8, 65537, 0.0125),), 320,
        device_kind="TPU v5 lite", pallas_ok=None)
    assert got320 == ("sort",)


def test_resolve_heuristic_matches_legacy_on_unknown_device():
    from peasoup_tpu.ops.peaks import _TWO_STAGE_MIN_SIZE

    bounds = ((0, 9228, 1.0), (0, _TWO_STAGE_MIN_SIZE + 1, 1.0))
    got = tuning.resolve_peaks_methods(
        bounds, 320, device_kind="weird-device-9000", pallas_ok=None)
    assert got == ("sort", "two_stage")
    # a TPU generation with no table entry prefers the compiled kernel
    got = tuning.resolve_peaks_methods(
        bounds, 320, device_kind="weird-device-9000",
        pallas_ok="compiled")
    assert got == ("pallas", "pallas")


def test_record_peaks_choices_audit_trail(tmp_path):
    side = str(tmp_path / "tune.json")
    methods = ("sort", "sort", "two_stage", "pallas", "pallas")
    tuning.record_peaks_choices(side, BOUNDS, 320, methods,
                                device_kind="cpu")
    sec = tuning.load_extraction(side)["cpu"]
    assert sec["16384/320"]["picked"] == "sort"
    assert sec["65536/320"]["picked"] == "two_stage"
    assert sec["131072/320"]["picked"] == "pallas"


# --------------------------------------------------------------------------
# costmodel: the compaction formula
# --------------------------------------------------------------------------

def test_peaks_cost_per_method_formulas():
    from peasoup_tpu.obs import costmodel as cm

    nb, cap = 1 << 20, 320
    sort = cm.peaks_cost(nb, cap, "sort")
    two = cm.peaks_cost(nb, cap, "two_stage")
    pal = cm.peaks_cost(nb, cap, "pallas")
    # the compaction is O(n + survivors): far fewer flops than the
    # sort's n log k selection network at large n
    assert pal.flops < two.flops < sort.flops
    # identical traffic model: all three stream the prefix once and
    # write the same fixed-capacity buffers
    for c in (sort, two, pal):
        assert c.bytes_read == nb * 4
        assert c.bytes_written == cap * 8
    # compaction intensity ~2 flops/byte -> memory-roof bound
    peak = cm.device_peak("TPU v5 lite")
    assert pal.dominant(peak) == "memory"
    assert cm.peaks_cost(nb, cap).flops == sort.flops  # default=sort


def test_pipeline_geometry_carries_peaks_method():
    from peasoup_tpu.obs import costmodel as cm

    geom = cm.PipelineGeometry(
        n_dm=4, nchans=16, out_nsamps=1 << 18, in_itemsize=1,
        size=1 << 18, nharmonics=2, peak_capacity=64, n_trials_total=12,
        npdmp=0, fold_nsamps=1 << 17, fold_nbins=64, fold_nints=16,
        peaks_method="pallas")
    js = geom.to_json()
    assert js["peaks_method"] == "pallas"
    costs = cm.pipeline_costs(geom)
    geom_sort = cm.PipelineGeometry(**{**js, "peaks_method": "sort"})
    costs_sort = cm.pipeline_costs(geom_sort)
    assert costs["peaks"].flops < costs_sort["peaks"].flops


# --------------------------------------------------------------------------
# perf gate: stage device-time columns
# --------------------------------------------------------------------------

def _ledger(tmp_path, rows):
    path = str(tmp_path / "history.jsonl")
    with open(path, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")
    return path


def _bench_rec(e2e, peaks=None):
    rec = {"v": 1, "kind": "bench", "metrics": {"e2e_s": e2e}}
    if peaks is not None:
        rec["metrics"]["peaks_device_s"] = peaks
    return rec


def test_gate_trips_on_peaks_device_time_regression(tmp_path):
    from peasoup_tpu.tools.perf_report import main as pr_main

    rows = [_bench_rec(0.37, 0.007) for _ in range(6)]
    rows.append(_bench_rec(0.37, 0.064))  # sort wall came back
    path = _ledger(tmp_path, rows)
    rc = pr_main(["--gate", "--ledger", path, "--legacy-glob", ""])
    assert rc == 1
    # wall-clock alone would NOT have caught it
    rc = pr_main(["--gate", "--ledger", path, "--legacy-glob", "",
                  "--stage-metrics", ""])
    assert rc == 0


def test_gate_passes_without_stage_columns(tmp_path):
    from peasoup_tpu.tools.perf_report import main as pr_main

    rows = [_bench_rec(0.37) for _ in range(5)]
    path = _ledger(tmp_path, rows)
    assert pr_main(["--gate", "--ledger", path,
                    "--legacy-glob", ""]) == 0


def test_gate_clean_stage_columns_pass(tmp_path):
    from peasoup_tpu.tools.perf_report import main as pr_main

    rows = [_bench_rec(0.37, 0.06) for _ in range(5)]
    rows.append(_bench_rec(0.33, 0.007))  # the ISSUE-6 improvement
    path = _ledger(tmp_path, rows)
    assert pr_main(["--gate", "--ledger", path,
                    "--legacy-glob", ""]) == 0


# --------------------------------------------------------------------------
# sweep harness
# --------------------------------------------------------------------------

def test_sweep_cell_in_process_structure():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "peaks_sweep", os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "benchmarks", "peaks_sweep.py"))
    sweep = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(sweep)
    cell = sweep.run_cell(128, 9216, 64, iters=2)
    assert cell["safe"] is True and cell["exact"] is True
    assert cell["row_width"] == 128 and cell["stop"] == 9216
    assert "sort" in cell["ms_per_batch8"]
    assert "two_stage" in cell["ms_per_batch8"]


def test_sweep_carries_unsafe_cells_forward(tmp_path):
    """A cell the artifact marks unsafe is NEVER re-executed by
    default — the r5 C=64/stop=65537 v5e crash must not be
    reproducible by an innocent re-run."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "peaks_sweep2", os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "benchmarks", "peaks_sweep.py"))
    sweep = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(sweep)
    out = str(tmp_path / "sweep.json")
    prior = {
        "cells": {
            sweep.cell_key(c, s, k): {
                "row_width": c, "stop": s, "cap": k, "safe": False,
                "errors": ["prior worker crash"],
            }
            for c in sweep.ROW_WIDTHS for s in sweep.STOPS
            for k in sweep.CAPS
        }
    }
    with open(out, "w") as f:
        json.dump(prior, f)
    # every cell carried forward -> no subprocesses, near-instant
    rc = sweep.main(["--out", out])
    assert rc == 0
    doc = json.load(open(out))
    assert doc["n_unsafe"] == len(doc["cells"])
    assert all(v.get("skipped") for v in doc["cells"].values())


def test_committed_sweep_artifact_matches_tuner_safety():
    """The committed v5e sweep artifact and the tuner's built-in
    unsafe-cell table must agree: every unsafe artifact cell is a
    C=64 / stop >= 2^16 cell (the r5 crash signature)."""
    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "benchmarks", "peaks_sweep.json")
    doc = json.load(open(path))
    unsafe = [v for v in doc["cells"].values() if not v.get("safe")]
    assert unsafe, "the r5 crash cells must be recorded"
    for cell in unsafe:
        assert cell["row_width"] == 64 and cell["stop"] >= 65536
    # and every safe two-stage cell was exactness-verified
    for v in doc["cells"].values():
        if v.get("safe"):
            assert v.get("exact") is True


# --------------------------------------------------------------------------
# end-to-end: forced methods produce identical candidates
# --------------------------------------------------------------------------

def _synthetic_fil(tmp_path, nsamps=8192, nchans=16):
    from peasoup_tpu.tools.serve_smoke import _write_synthetic

    return _write_synthetic(str(tmp_path / "obs.fil"), nsamps=nsamps,
                            nchans=nchans)


def _run_search(fil_path, method, mesh=False, tune_file=""):
    from peasoup_tpu.io import read_filterbank
    from peasoup_tpu.parallel.mesh import MeshPulsarSearch
    from peasoup_tpu.search.pipeline import PulsarSearch
    from peasoup_tpu.search.plan import SearchConfig

    fil = read_filterbank(fil_path)
    cfg = SearchConfig(
        dm_start=0.0, dm_end=30.0, acc_start=-2.0, acc_end=2.0,
        acc_pulse_width=64000.0, nharmonics=2, npdmp=0, min_snr=6.0,
        peaks_method=method, tune_file=tune_file,
    )
    search = (MeshPulsarSearch(fil, cfg, max_devices=2) if mesh
              else PulsarSearch(fil, cfg))
    result = search.run()
    return sorted((round(c.freq, 9), round(c.snr, 5), c.dm_idx, c.nh)
                  for c in result.candidates)


def test_forced_methods_host_loop_parity(tmp_path):
    fil_path = _synthetic_fil(tmp_path)
    base = _run_search(fil_path, "auto")
    assert base, "synthetic pulse train must yield candidates"
    for method in ("sort", "two_stage"):
        assert _run_search(fil_path, method) == base, method


def test_forced_pallas_host_loop_parity(tmp_path, peaks_pallas_interpret):
    fil_path = _synthetic_fil(tmp_path)
    base = _run_search(fil_path, "auto")
    assert _run_search(fil_path, "pallas") == base


def test_forced_methods_mesh_parity_and_sidecar(tmp_path):
    fil_path = _synthetic_fil(tmp_path)
    tune = str(tmp_path / "tune.json")
    base = _run_search(fil_path, "auto", mesh=True, tune_file=tune)
    assert base
    # the audit trail recorded a picked path per (bucket, capacity)
    sec = tuning.load_extraction(tune)
    assert sec, "mesh run must record its picked extraction paths"
    kinds = list(sec)
    cells = sec[kinds[0]]
    assert cells and all("picked" in c for c in cells.values())
    for method in ("sort", "two_stage"):
        assert _run_search(fil_path, method, mesh=True) == base, method


def test_run_report_reflects_peaks_method(tmp_path):
    """The costmodel geometry (run_report perf section input) carries
    the resolved lowering of the deepest level."""
    from peasoup_tpu.io import read_filterbank
    from peasoup_tpu.obs.costmodel import get_run_costs
    from peasoup_tpu.search.pipeline import PulsarSearch
    from peasoup_tpu.search.plan import SearchConfig

    fil_path = _synthetic_fil(tmp_path)
    fil = read_filterbank(fil_path)
    cfg = SearchConfig(dm_start=0.0, dm_end=10.0, nharmonics=1,
                       npdmp=0, min_snr=6.0, peaks_method="two_stage")
    PulsarSearch(fil, cfg).run()
    geom = get_run_costs()["geometry"]
    assert geom.peaks_method == "two_stage"
