"""Post-processing tool tests (`tools/peasoup_tools.py` equivalents)."""

import os

import numpy as np
import pytest

from peasoup_tpu.cli import main
from peasoup_tpu.tools import PeasoupOutput, as_text, radec_to_str


@pytest.fixture(scope="module")
def outdir(tutorial_fil, tmp_path_factory):
    """A real (small) search output directory."""
    d = str(tmp_path_factory.mktemp("tools") / "out")
    rc = main([
        "-i", tutorial_fil, "-o", d,
        "--dm_start", "0", "--dm_end", "40",
        "--acc_start", "-5", "--acc_end", "5",
        "--acc_pulse_width", "64000", "--npdmp", "2", "--limit", "10",
    ])
    assert rc == 0
    return d


def test_radec_to_str():
    # SIGPROC packed hhmmss.s: 12h 34m 56.7s
    assert radec_to_str(123456.7) == "12:34:56.7000"
    assert radec_to_str(-23456.7) == "-2:34:56.7000"


def test_joined_candidate_and_predictor(outdir):
    out = PeasoupOutput(os.path.join(outdir, "overview.xml"))
    assert out.ncands > 0
    cand = out.get_candidate(0)
    # folded candidate: fold present and hit list consistent with nassoc
    assert cand.fold is not None and cand.fold.shape == (16, 64)
    assert len(cand.hits) == cand.nassoc + 1
    assert cand.hits[0]["snr"] == pytest.approx(float(cand.snr), rel=1e-5)
    pred = out.make_predictor(0)
    assert pred.splitlines()[1].startswith("PERIOD: ")
    assert "DM: %.3f" % cand.dm in pred


def test_as_text_table(outdir):
    text = as_text(os.path.join(outdir, "overview.xml"))
    lines = text.splitlines()
    assert lines[0].split()[0] == "cand_num"
    out = PeasoupOutput(os.path.join(outdir, "overview.xml"))
    assert len(lines) == 1 + out.ncands
    # sorted by period ascending by default
    periods = [float(l.split()[1]) for l in lines[1:]]
    assert periods == sorted(periods)


def test_candidate_plotter_writes_page(outdir, tmp_path):
    pytest.importorskip("matplotlib")
    from peasoup_tpu.tools import CandidatePlotter

    out = PeasoupOutput(os.path.join(outdir, "overview.xml"))
    plotter = CandidatePlotter(out)
    png = str(tmp_path / "cand0.png")
    plotter.plot_cand(0, png)
    assert os.path.getsize(png) > 10000  # a real rendered page
