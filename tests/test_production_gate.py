"""Production-scale regression gate (VERDICT r3 item 8), CPU-runnable.

A miniature of benchmarks/production.py: synthetic 8-bit filterbank
with an injected pulsar, searched through the bounded-HBM CHUNKED mesh
driver (forced chunking) with checkpointing and tuning enabled.
Asserts the things the full benchmark asserts by eye:

* the injected pulsar is recovered (period + DM + a folded profile),
* no DM row clips its peak buffers at the default capacity,
* the per-phase chunk timers are present and non-degenerate.

The reference's only acceptance artefact is the tutorial golden pair
(SURVEY.md section 4); this gate exceeds it by checking end-to-end
recovery at the production *configuration shape* on every test run.
"""

import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from benchmarks.production import make_filterbank  # noqa: E402
from peasoup_tpu.parallel.mesh import MeshPulsarSearch  # noqa: E402
from peasoup_tpu.search.plan import SearchConfig  # noqa: E402


@pytest.fixture(scope="module")
def gate_result(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("prod_gate")
    nsamps, nchans, ndm = (1 << 17) + 600, 64, 32
    tsamp, fch1, foff = 6.4e-5, 1500.0, -4.6875  # 300 MHz band
    period_s, dm_inj = 0.0077, 120.0
    fil = make_filterbank(nsamps, nchans, tsamp, fch1, foff,
                          period_s, dm_inj, amp=20)
    cfg = SearchConfig(
        dm_list=np.linspace(0.0, 240.0, ndm).astype(np.float32),
        acc_start=-50.0, acc_end=50.0, acc_step=25.0,
        npdmp=4, limit=100,
        dm_chunk=4, accel_block=2,  # force the chunked driver
        checkpoint_file=str(tmp / "gate_ckpt.jsonl"),
        checkpoint_interval=1,
        tune_file=str(tmp / "gate_tune.json"),
    )
    search = MeshPulsarSearch(fil, cfg, max_devices=4)
    result = search.run()
    return result, period_s, dm_inj


def test_gate_recovers_injected_pulsar(gate_result):
    result, period_s, dm_inj = gate_result
    hit = next(
        (c for c in result.candidates.cands
         if abs(c.freq - 1.0 / period_s) < 0.01
         and abs(c.dm - dm_inj) < 20.0),
        None,
    )
    assert hit is not None, "injected pulsar not recovered"
    assert hit.snr > 20.0
    assert hit.folded_snr > 0.0 and hit.fold is not None
    assert hit.opt_period == pytest.approx(period_s, rel=1e-3)


def test_gate_zero_clipped_rows(gate_result):
    result, _, _ = gate_result
    assert result.timers["chunk_n_clipped_rows"] == 0
    assert result.timers["chunk_research"] < 1.0


def test_gate_stage_budget_breakdown(gate_result):
    result, _, _ = gate_result
    for phase in ("chunk_upload", "chunk_compile", "chunk_fetch",
                  "chunk_decode", "chunk_distill", "chunk_checkpoint"):
        assert phase in result.timers
    assert result.timers["chunk_fetch"] > 0.0
    assert result.timers["searching_device"] > 0.0
    # the search completed, so the checkpoint must have been removed
    # and the tune sidecar recorded
    assert not os.path.exists(result.config.checkpoint_file)
    assert os.path.exists(result.config.tune_file)
