"""Telemetry-plane tests: MetricsCursor delta snapshots (including
under concurrent increments), event-log flood suppression, the
TelemetrySampler's shard lifecycle (immediate first sample, extras
seam, overhead accounting, rotation), the merged torn-tail-tolerant
reader across skewed host clocks, and the tuning sidecar's measured
calibration round-trip."""

import json
import os
import threading

import pytest

from peasoup_tpu.obs.events import EventLog
from peasoup_tpu.obs.metrics import REGISTRY, MetricsCursor, MetricsRegistry
from peasoup_tpu.obs.telemetry import (
    TelemetrySampler,
    latest_by_host,
    read_samples,
    safe_host,
    shard_hosts,
    shard_path,
)


@pytest.fixture(autouse=True)
def _fresh_registry():
    REGISTRY.reset()
    yield
    REGISTRY.reset()


# --------------------------------------------------------------------------
# MetricsCursor deltas
# --------------------------------------------------------------------------

def test_cursor_deltas_are_per_interval_not_totals():
    r = MetricsRegistry()
    cur = MetricsCursor()
    r.inc("jobs", 3)
    with r.timer("span"):
        pass
    s1 = r.snapshot(cur)
    assert s1["deltas"]["counters"] == {"jobs": 3}
    assert s1["deltas"]["timers"]["span"]["count"] == 1
    # no activity between snapshots -> empty deltas, totals unchanged
    s2 = r.snapshot(cur)
    assert s2["deltas"] == {"counters": {}, "timers": {}}
    assert s2["counters"]["jobs"] == 3
    r.inc("jobs", 2)
    assert r.snapshot(cur)["deltas"]["counters"] == {"jobs": 2}


def test_cursor_independent_per_consumer():
    r = MetricsRegistry()
    a, b = MetricsCursor(), MetricsCursor()
    r.inc("x")
    assert r.snapshot(a)["deltas"]["counters"] == {"x": 1}
    # b never snapshotted before: sees the full history as one delta
    r.inc("x")
    assert r.snapshot(b)["deltas"]["counters"] == {"x": 2}
    assert r.snapshot(a)["deltas"]["counters"] == {"x": 1}


def test_cursor_rebases_after_registry_reset():
    r = MetricsRegistry()
    cur = MetricsCursor()
    r.inc("x", 5)
    r.snapshot(cur)
    r.reset()  # totals rewind below the cursor
    r.inc("x", 2)
    # clamped at zero, re-based: no negative delta, next delta clean
    assert r.snapshot(cur)["deltas"]["counters"] == {}
    r.inc("x", 3)
    assert r.snapshot(cur)["deltas"]["counters"] == {"x": 3}


def test_cursor_concurrent_increments_land_in_exactly_one_delta():
    """Hammer one counter from 4 threads while a sampler thread takes
    delta snapshots: the deltas must sum to the final total — no
    increment lost to or double-counted across a sampling boundary."""
    r = MetricsRegistry()
    cur = MetricsCursor()
    per_thread, threads = 2000, 4
    stop = threading.Event()
    seen = []

    def _inc():
        for _ in range(per_thread):
            r.inc("hammer")

    def _sample():
        while not stop.is_set():
            seen.append(r.snapshot(cur)["deltas"]["counters"].get(
                "hammer", 0))

    ts = [threading.Thread(target=_inc) for _ in range(threads)]
    sampler = threading.Thread(target=_sample)
    sampler.start()
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    stop.set()
    sampler.join()
    seen.append(r.snapshot(cur)["deltas"]["counters"].get("hammer", 0))
    assert sum(seen) == per_thread * threads
    assert r.snapshot()["counters"]["hammer"] == per_thread * threads


# --------------------------------------------------------------------------
# event-log flood suppression
# --------------------------------------------------------------------------

def test_event_flood_bounds_disk_lines_not_counters(tmp_path):
    path = str(tmp_path / "events.jsonl")
    t = [1000.0]
    log = EventLog(path, flood_limit=3, flood_window_s=60.0,
                   clock=lambda: t[0])
    for _ in range(10):
        log.emit("overflow", "buffer overflowed")
    # counters and the in-memory summary see all 10...
    assert log.summary()["overflow"] == 10
    assert REGISTRY.snapshot()["counters"]["events.overflow"] == 10
    assert REGISTRY.snapshot()["counters"][
        "events.flood_suppressed"] == 7
    # ...but only flood_limit lines persist inside the window
    lines = [json.loads(x) for x in open(path)]
    assert [l["kind"] for l in lines] == ["overflow"] * 3
    # window rollover emits ONE summary stating what was dropped
    t[0] += 61.0
    log.emit("overflow", "again")
    lines = [json.loads(x) for x in open(path)]
    kinds = [l["kind"] for l in lines]
    assert kinds == ["overflow"] * 3 + ["event_flood", "overflow"]
    flood = lines[3]
    assert flood["data"] == {"kind": "overflow", "suppressed": 7,
                             "window_s": 60.0}
    log.close()


def test_event_flood_close_flushes_pending_summary(tmp_path):
    path = str(tmp_path / "events.jsonl")
    log = EventLog(path, flood_limit=1, flood_window_s=3600.0)
    log.emit("spam", "x")
    log.emit("spam", "x")
    log.emit("spam", "x")
    log.close()  # window never rolled over; close states the drop
    lines = [json.loads(x) for x in open(path)]
    assert [l["kind"] for l in lines] == ["spam", "event_flood"]
    assert lines[1]["data"]["suppressed"] == 2


def test_event_flood_distinct_kinds_have_independent_budgets(tmp_path):
    path = str(tmp_path / "events.jsonl")
    log = EventLog(path, flood_limit=2, flood_window_s=60.0)
    for _ in range(4):
        log.emit("a", "m")
        log.emit("b", "m")
    kinds = [json.loads(x)["kind"] for x in open(path)]
    assert kinds.count("a") == 2 and kinds.count("b") == 2
    log.close()


# --------------------------------------------------------------------------
# TelemetrySampler
# --------------------------------------------------------------------------

def test_sampler_writes_schema_versioned_deltas_and_extras(tmp_path):
    ts_dir = str(tmp_path / "fleet")
    r = MetricsRegistry()
    r.inc("scheduler.succeeded", 2)
    r.gauge("scheduler.jobs_per_hour", 42.0)
    s = TelemetrySampler(
        shard_path(ts_dir, "host-0"), "host-0", 30.0, registry=r,
        extras=lambda: {"queue": {"pending": 3, "done": 1}})
    s.start()  # immediate first sample
    r.inc("scheduler.succeeded", 5)
    s.stop()  # final sample
    samples = read_samples(ts_dir)
    assert len(samples) == 2 == s.samples_written
    first, last = samples
    assert first["v"] == 1 and first["host"] == "host-0"
    assert first["seq"] == 1 and last["seq"] == 2
    # per-interval deltas, not totals
    assert first["counters"]["scheduler.succeeded"] == 2
    assert last["counters"]["scheduler.succeeded"] == 5
    assert last["gauges"]["scheduler.jobs_per_hour"] == 42.0
    assert last["queue"] == {"pending": 3, "done": 1}
    assert last["overhead_s"] >= first["overhead_s"] >= 0.0
    assert s.overhead_s > 0.0


def test_sampler_ticks_on_interval_and_extras_failure_is_recorded(
        tmp_path):
    ts_dir = str(tmp_path / "fleet")
    calls = [0]

    def _extras():
        calls[0] += 1
        if calls[0] == 2:
            raise RuntimeError("spool vanished")
        return {"queue": {"pending": 0}}

    with TelemetrySampler(shard_path(ts_dir, "h"), "h", 0.05,
                          registry=MetricsRegistry(),
                          extras=_extras) as s:
        deadline = threading.Event()
        while s.samples_written < 4:
            deadline.wait(0.01)  # avoid bare sleep (PSL008)
    samples = read_samples(ts_dir)
    assert len(samples) >= 4
    # the one failing extras call tainted exactly its own sample
    errs = [x for x in samples if "extras_error" in x]
    assert len(errs) == 1 and "spool vanished" in errs[0]["extras_error"]
    assert all("queue" in x for x in samples if "extras_error" not in x)


def test_sampler_rotation_bounds_shards_and_reader_merges(tmp_path):
    ts_dir = str(tmp_path / "fleet")
    path = shard_path(ts_dir, "h")
    s = TelemetrySampler(path, "h", 30.0, registry=MetricsRegistry(),
                         max_shard_bytes=400)
    n = 0
    while not os.path.exists(path + ".1"):
        s.sample_now()
        n += 1
        assert n < 100  # a ~150-byte line must rotate a 400B shard
    s.sample_now()
    # bounded: exactly two generations, never a .2
    assert os.path.exists(path) and os.path.exists(path + ".1")
    assert not os.path.exists(path + ".2")
    merged = read_samples(ts_dir)
    # no sample lost across the rotation boundary, order preserved
    assert [x["seq"] for x in merged] == list(range(1, n + 2))


def test_reader_skips_torn_tail_and_corrupt_lines(tmp_path):
    ts_dir = str(tmp_path / "fleet")
    s = TelemetrySampler(shard_path(ts_dir, "h"), "h", 30.0,
                         registry=MetricsRegistry())
    s.sample_now()
    s.sample_now()
    with open(s.path, "a") as f:
        f.write("not json at all\n")
        f.write('{"v": 1, "no_ts": true}\n')  # dict without ts: dropped
        f.write('{"v": 1, "ts": 12')  # SIGKILL mid-append
    samples = read_samples(ts_dir)
    assert [x["seq"] for x in samples] == [1, 2]
    # the torn tail must not hide the host from latest_by_host either
    assert latest_by_host(ts_dir)["h"]["seq"] == 2


def test_reader_merges_skewed_host_clocks(tmp_path):
    """host-b's clock runs 100s ahead: the merge is ts-sorted (so
    cross-host order follows the skewed clocks) but each host's own
    samples stay in seq order — the documented contract."""
    ts_dir = str(tmp_path / "fleet")
    ta, tb = [1000.0], [1100.0]
    ra, rb = MetricsRegistry(), MetricsRegistry()
    sa = TelemetrySampler(shard_path(ts_dir, "a"), "a", 30.0,
                          registry=ra, clock=lambda: ta[0])
    sb = TelemetrySampler(shard_path(ts_dir, "b"), "b", 30.0,
                          registry=rb, clock=lambda: tb[0])
    for _ in range(3):
        sa.sample_now()
        sb.sample_now()
        ta[0] += 10.0
        tb[0] += 10.0
    assert shard_hosts(ts_dir) == ["a", "b"]
    merged = read_samples(ts_dir)
    assert [x["ts"] for x in merged] == sorted(x["ts"] for x in merged)
    for host in ("a", "b"):
        seqs = [x["seq"] for x in merged if x["host"] == host]
        assert seqs == [1, 2, 3]
    # all of a sorts before any of b (the skew is visible, not fatal)
    assert [x["host"] for x in merged] == ["a"] * 3 + ["b"] * 3
    latest = latest_by_host(ts_dir)
    assert latest["a"]["ts"] == 1020.0 and latest["b"]["ts"] == 1120.0
    # since= filters on the merged timeline
    assert len(read_samples(ts_dir, since=1100.0)) == 3
    assert len(read_samples(ts_dir, hosts=["a"])) == 3


def test_sampler_io_failure_latches_instead_of_raising(tmp_path):
    target = tmp_path / "fleet"
    target.mkdir()
    shard = target / "ts-h.jsonl"
    shard.mkdir()  # open() for append will fail with EISDIR
    s = TelemetrySampler(str(shard), "h", 30.0,
                         registry=MetricsRegistry())
    s.sample_now()  # must not raise
    s.sample_now()
    assert s.samples_written == 0 and s._io_failed


def test_safe_host_sanitises_labels():
    assert safe_host("pod a/slice:3") == "pod_a_slice_3"
    assert safe_host("  ") == "host"
    assert safe_host("host-0") == "host-0"


# --------------------------------------------------------------------------
# tuning calibration round-trip
# --------------------------------------------------------------------------

def test_calibration_roundtrip_survives_save_tuning(tmp_path):
    from peasoup_tpu.search.tuning import (
        DEFAULT_COMPILE_S,
        DEFAULT_RESEARCH_S,
        DEFAULT_SLOT_S,
        calibration_constants,
        save_tuning,
        update_calibration,
    )

    path = str(tmp_path / "tune.json")
    # no sidecar yet: hardcoded v5e-class fallbacks, flagged unmeasured
    c = calibration_constants(path)
    assert not c["measured"]
    assert (c["slot_s"], c["research_s"], c["compile_s"]) == (
        DEFAULT_SLOT_S, DEFAULT_RESEARCH_S, DEFAULT_COMPILE_S)

    update_calibration(path, "tpu-v5e", slot_s=4e-6, research_s=1.0,
                       compile_s=12.0)
    c1 = calibration_constants(path, "tpu-v5e")
    assert c1["measured"] and c1["slot_s"] == pytest.approx(4e-6)
    # EWMA merge (alpha=0.5), not last-write-wins
    update_calibration(path, "tpu-v5e", slot_s=2e-6)
    assert calibration_constants(path, "tpu-v5e")["slot_s"] == \
        pytest.approx(3e-6)
    # a later capacity-tuning rewrite must not drop the calibration
    save_tuning(path, "some|search|key", 256, 32)
    c2 = calibration_constants(path, "tpu-v5e")
    assert c2["measured"] and c2["slot_s"] == pytest.approx(3e-6)
    doc = json.load(open(path))
    assert doc["cap_hw"] == 256 and "calibration" in doc


def test_record_run_calibration_uses_compile_timer(tmp_path):
    from peasoup_tpu.search.tuning import (
        calibration_constants,
        record_run_calibration,
    )

    path = str(tmp_path / "tune.json")
    r = MetricsRegistry()
    with r.timer("jit_compile"):
        pass
    record_run_calibration(path, "cpu", research_s=0.5, registry=r)
    c = calibration_constants(path, "cpu")
    assert c["measured"]
    assert c["research_s"] == pytest.approx(0.5)
    assert c["compile_s"] < 21.0  # merged toward the tiny measurement


def test_pick_row_capacity_honours_measured_constants():
    import numpy as np

    from peasoup_tpu.search.tuning import pick_row_capacity

    row_hw = np.array([40] * 63 + [100000], np.int64)
    # expensive re-search: cover even the pathological row
    cap_slow = pick_row_capacity(row_hw, 1000, research_s=500.0,
                                 compile_s=500.0, slot_s=1e-9)
    # near-free re-search: leave the loud row to the re-search path
    cap_fast = pick_row_capacity(row_hw, 1000, research_s=1e-6,
                                 compile_s=0.0, slot_s=1.0)
    assert cap_fast < cap_slow
