import io

import numpy as np
import pytest

from peasoup_tpu.io import (
    Filterbank,
    SigprocHeader,
    pack_bits,
    read_filterbank,
    read_sigproc_header,
    unpack_bits,
    write_filterbank,
    write_sigproc_header,
)
from peasoup_tpu.io.unpack import _lut


@pytest.mark.parametrize("nbits", [1, 2, 4, 8])
def test_pack_unpack_roundtrip(nbits):
    rng = np.random.default_rng(0)
    samples = rng.integers(0, 1 << nbits, size=4096, dtype=np.uint8)
    packed = pack_bits(samples, nbits)
    assert packed.size == samples.size * nbits // 8
    unpacked = unpack_bits(packed, nbits)
    np.testing.assert_array_equal(unpacked, samples)


@pytest.mark.parametrize("nbits", [1, 2, 4])
def test_native_matches_numpy(nbits):
    from peasoup_tpu import native

    if native.lib is None:
        pytest.skip("native helpers unavailable")
    rng = np.random.default_rng(1)
    raw = rng.integers(0, 256, size=1000, dtype=np.uint8)
    np.testing.assert_array_equal(
        native.lib.unpack_bits(raw, nbits), _lut(nbits)[raw].ravel()
    )


def test_header_roundtrip():
    hdr = SigprocHeader(
        source_name="TESTPSR",
        tstart=55000.0,
        tsamp=6.4e-5,
        fch1=1510.0,
        foff=-1.09,
        nchans=64,
        nbits=8,
        nifs=1,
        data_type=1,
        nsamples=1024,
    )
    buf = io.BytesIO()
    write_sigproc_header(buf, hdr, include_nsamples=True)
    buf.seek(0)
    parsed = read_sigproc_header(buf)
    for key in ("source_name", "tstart", "tsamp", "fch1", "foff", "nchans",
                "nbits", "nsamples"):
        assert getattr(parsed, key) == getattr(hdr, key)


def test_filterbank_roundtrip(tmp_path):
    rng = np.random.default_rng(2)
    data = rng.integers(0, 4, size=(512, 16), dtype=np.uint8)
    hdr = SigprocHeader(tsamp=1e-4, fch1=1400.0, foff=-0.5, nchans=16,
                        nbits=2, nifs=1, data_type=1, nsamples=512)
    path = str(tmp_path / "test.fil")
    write_filterbank(path, Filterbank(header=hdr, data=data))
    fil = read_filterbank(path)
    assert fil.nsamps == 512 and fil.nchans == 16
    np.testing.assert_array_equal(fil.data, data)


def test_read_tutorial_header(tutorial_fil):
    # Golden values from example_output/overview.xml <header_parameters>
    fil = read_filterbank(tutorial_fil)
    h = fil.header
    assert h.nchans == 64
    assert h.nbits == 2
    assert h.nsamples == 187520
    assert h.tsamp == pytest.approx(0.00032)
    assert h.fch1 == pytest.approx(1510.0)
    assert h.foff == pytest.approx(-1.09)
    assert h.tstart == pytest.approx(50000.0)
    assert h.source_name.startswith("P: 250")
    assert fil.data.shape == (187520, 64)
    assert fil.data.max() <= 3
    # centre frequency as used by AccelerationPlan / scorer
    assert h.cfreq == pytest.approx(1510.0 - 1.09 * 32)
