import io

import numpy as np
import pytest

from peasoup_tpu.io import (
    Filterbank,
    SigprocHeader,
    pack_bits,
    read_filterbank,
    read_sigproc_header,
    unpack_bits,
    write_filterbank,
    write_sigproc_header,
)
from peasoup_tpu.io.unpack import _lut


@pytest.mark.parametrize("nbits", [1, 2, 4, 8])
def test_pack_unpack_roundtrip(nbits):
    rng = np.random.default_rng(0)
    samples = rng.integers(0, 1 << nbits, size=4096, dtype=np.uint8)
    packed = pack_bits(samples, nbits)
    assert packed.size == samples.size * nbits // 8
    unpacked = unpack_bits(packed, nbits)
    np.testing.assert_array_equal(unpacked, samples)


@pytest.mark.parametrize("nbits", [1, 2, 4])
def test_native_matches_numpy(nbits):
    from peasoup_tpu import native

    if native.lib is None:
        pytest.skip("native helpers unavailable")
    rng = np.random.default_rng(1)
    raw = rng.integers(0, 256, size=1000, dtype=np.uint8)
    np.testing.assert_array_equal(
        native.lib.unpack_bits(raw, nbits), _lut(nbits)[raw].ravel()
    )


def test_header_roundtrip():
    hdr = SigprocHeader(
        source_name="TESTPSR",
        tstart=55000.0,
        tsamp=6.4e-5,
        fch1=1510.0,
        foff=-1.09,
        nchans=64,
        nbits=8,
        nifs=1,
        data_type=1,
        nsamples=1024,
    )
    buf = io.BytesIO()
    write_sigproc_header(buf, hdr, include_nsamples=True)
    buf.seek(0)
    parsed = read_sigproc_header(buf)
    for key in ("source_name", "tstart", "tsamp", "fch1", "foff", "nchans",
                "nbits", "nsamples"):
        assert getattr(parsed, key) == getattr(hdr, key)


@pytest.mark.parametrize("nbits", [8, 32])
def test_truncated_filterbank_raises_input_file_error(tmp_path, nbits):
    """A short read must surface as a typed InputFileError WITH the
    byte counts (the survey scheduler quarantines on it), not as a
    numpy reshape error deep inside unpack."""
    from peasoup_tpu.errors import InputFileError

    rng = np.random.default_rng(3)
    nsamps, nchans = 256, 8
    if nbits == 32:
        data = rng.normal(size=(nsamps, nchans)).astype(np.float32)
    else:
        data = rng.integers(0, 255, size=(nsamps, nchans),
                            dtype=np.uint8)
    hdr = SigprocHeader(tsamp=1e-4, fch1=1400.0, foff=-0.5,
                        nchans=nchans, nbits=nbits, nifs=1,
                        data_type=1, nsamples=nsamps)
    path = str(tmp_path / "trunc.fil")
    # header written WITH nsamples: the promise the data must honour
    with open(path, "wb") as f:
        write_sigproc_header(f, hdr, include_nsamples=True)
        f.write(data.tobytes()[:-100])
    with pytest.raises(InputFileError) as exc_info:
        read_filterbank(path)
    msg = str(exc_info.value)
    expected = nsamps * nchans * nbits // 8
    assert "truncated" in msg
    assert str(expected) in msg            # promised byte count
    assert str(expected - 100) in msg      # actual byte count


def test_zero_nchans_header_rejected(tmp_path):
    """nchans/nbits of 0 must be a typed error, not a ZeroDivision
    in the nsamples inference."""
    from peasoup_tpu.errors import InputFileError

    hdr = SigprocHeader(tsamp=1e-4, fch1=1400.0, nchans=0, nbits=8)
    buf = io.BytesIO()
    write_sigproc_header(buf, hdr)
    buf.seek(0)
    with pytest.raises(InputFileError, match="nchans"):
        read_sigproc_header(buf)


def test_filterbank_roundtrip(tmp_path):
    rng = np.random.default_rng(2)
    data = rng.integers(0, 4, size=(512, 16), dtype=np.uint8)
    hdr = SigprocHeader(tsamp=1e-4, fch1=1400.0, foff=-0.5, nchans=16,
                        nbits=2, nifs=1, data_type=1, nsamples=512)
    path = str(tmp_path / "test.fil")
    write_filterbank(path, Filterbank(header=hdr, data=data))
    fil = read_filterbank(path)
    assert fil.nsamps == 512 and fil.nchans == 16
    np.testing.assert_array_equal(fil.data, data)


def test_read_tutorial_header(tutorial_fil):
    # Golden values from example_output/overview.xml <header_parameters>
    fil = read_filterbank(tutorial_fil)
    h = fil.header
    assert h.nchans == 64
    assert h.nbits == 2
    assert h.nsamples == 187520
    assert h.tsamp == pytest.approx(0.00032)
    assert h.fch1 == pytest.approx(1510.0)
    assert h.foff == pytest.approx(-1.09)
    assert h.tstart == pytest.approx(50000.0)
    assert h.source_name.startswith("P: 250")
    assert fil.data.shape == (187520, 64)
    assert fil.data.max() <= 3
    # centre frequency as used by AccelerationPlan / scorer
    assert h.cfreq == pytest.approx(1510.0 - 1.09 * 32)
