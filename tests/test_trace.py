"""Span-tracing tests: nesting/ids, Chrome trace-event schema
validity, host/device split, HBM sampler behaviour, multihost merge
with a faked 2-process gather, the trace_report tool, and the e2e
tutorial run whose trace must cover the five pipeline stages."""

import json
import os
import threading

import numpy as np
import pytest

from peasoup_tpu.obs.metrics import MetricsRegistry
from peasoup_tpu.obs.trace import (
    Tracer,
    chrome_events,
    get_tracer,
    local_trace_payload,
    span_table,
    write_merged_trace,
)


def _balance_check(events):
    """Every B has its E, LIFO per (pid, tid); ts never runs backwards
    per tid.  Returns the B/E event count checked."""
    stacks = {}
    last_ts = {}
    n = 0
    for e in events:
        ph = e.get("ph")
        if ph not in ("B", "E"):
            continue
        n += 1
        key = (e.get("pid", 0), e.get("tid", 0))
        assert e["ts"] >= last_ts.get(key, float("-inf")), (
            f"ts ran backwards on {key}: {e}")
        last_ts[key] = e["ts"]
        if ph == "B":
            stacks.setdefault(key, []).append(e["name"])
        else:
            assert stacks.get(key), f"E without B: {e}"
            assert stacks[key].pop() == e["name"]
    for key, st in stacks.items():
        assert st == [], f"unclosed spans on {key}: {st}"
    return n


# --------------------------------------------------------------------------
# span nesting, ids, attributes
# --------------------------------------------------------------------------

def test_span_nesting_parent_ids():
    t = Tracer(registry=MetricsRegistry())
    with t.span("outer", n_dm_trials=3) as o:
        with t.span("inner") as i:
            pass
        with t.span("inner2") as i2:
            pass
    with t.span("sibling") as s:
        pass
    recs = {r.name: r for r in t.records()}
    assert len(recs) == 4
    assert recs["outer"].parent_id is None
    assert recs["sibling"].parent_id is None
    assert recs["inner"].parent_id == recs["outer"].span_id
    assert recs["inner2"].parent_id == recs["outer"].span_id
    ids = [r.span_id for r in t.records()]
    assert len(set(ids)) == 4
    # children close before (or when) the parent does
    assert recs["inner"].t_end <= recs["outer"].t_end
    assert recs["outer"].attrs["n_dm_trials"] == 3
    assert o.span_id == recs["outer"].span_id
    assert i.span_id != i2.span_id != s.span_id


def test_span_metric_feeds_stage_timer_registry():
    reg = MetricsRegistry()
    t = Tracer(registry=reg)
    with t.span("Dedisperse", metric="dedispersion"):
        pass
    with t.span("no-metric"):
        pass
    timers = reg.snapshot()["timers"]
    assert list(timers) == ["dedispersion"]
    assert timers["dedispersion"]["count"] == 1
    assert timers["dedispersion"]["host_s"] >= 0.0


def test_span_set_attrs_and_error_capture():
    t = Tracer(registry=MetricsRegistry())
    with pytest.raises(RuntimeError):
        with t.span("boom") as sp:
            sp.set(rows=7)
            raise RuntimeError("x")
    (rec,) = t.records()
    assert rec.attrs["rows"] == 7
    assert rec.attrs["error"] == "RuntimeError"


def test_device_host_split_sanity():
    import jax.numpy as jnp

    t = Tracer(registry=MetricsRegistry())
    with t.span("compute") as sp:
        arr = jnp.arange(4096) * 3
        out = sp.block(arr)
    assert out is arr
    (rec,) = t.records()
    assert 0.0 <= rec.device_s <= (rec.t_end - rec.t_start)


def test_span_threads_get_distinct_tids():
    t = Tracer(registry=MetricsRegistry())

    def work():
        with t.span("worker"):
            pass

    th = threading.Thread(target=work)
    with t.span("main"):
        th.start()
        th.join()
    recs = {r.name: r for r in t.records()}
    assert recs["main"].tid != recs["worker"].tid
    # a thread's root span has no parent (stacks are per-thread)
    assert recs["worker"].parent_id is None


def test_span_cap_drops_not_grows():
    reg = MetricsRegistry()
    t = Tracer(registry=reg, max_spans=3)
    for _ in range(5):
        with t.span("s"):
            pass
    assert len(t.records()) == 3
    assert t.dropped == 2
    assert reg.counter("trace.spans_dropped") == 2


# --------------------------------------------------------------------------
# Chrome trace-event schema
# --------------------------------------------------------------------------

def test_chrome_events_balanced_and_monotonic():
    t = Tracer(registry=MetricsRegistry())
    with t.span("a", k=1):
        with t.span("b"):
            with t.span("c"):
                pass
        with t.span("d"):
            pass
    with t.span("e"):
        pass
    events = chrome_events(t.records(), process_index=0, epoch=t.epoch)
    assert _balance_check(events) == 10  # 5 spans x (B + E)
    b = next(e for e in events if e.get("ph") == "B" and e["name"] == "a")
    assert b["args"]["k"] == 1
    assert "span_id" in b["args"] and "device_ms" in b["args"]
    bb = next(e for e in events if e.get("ph") == "B" and e["name"] == "b")
    assert bb["args"]["parent_id"] == b["args"]["span_id"]
    # JSON round-trips
    json.loads(json.dumps(events))


def test_write_merged_trace_single_process(tmp_path):
    t = Tracer(registry=MetricsRegistry())
    with t.span("root"):
        with t.span("leaf"):
            pass
    path = str(tmp_path / "trace.json")
    out = write_merged_trace(path, tracer=t, gather=lambda b: [b],
                             process_index=0)
    assert out == path
    doc = json.load(open(path))
    assert doc["metadata"]["n_processes"] == 1
    evs = doc["traceEvents"]
    _balance_check(evs)
    # timestamps are normalised to the earliest span
    ts = [e["ts"] for e in evs if "ts" in e and e.get("ph") != "M"]
    assert min(ts) == 0.0


# --------------------------------------------------------------------------
# HBM watermark sampler
# --------------------------------------------------------------------------

def test_hbm_sampler_noop_on_cpu():
    """CPU devices report no memory stats: spans carry no hbm attrs,
    no high-water gauge appears, and nothing raises."""
    reg = MetricsRegistry()
    t = Tracer(registry=reg)
    with t.span("s"):
        pass
    (rec,) = t.records()
    assert "hbm_bytes_in_use" not in rec.attrs
    assert "hbm.high_water_bytes" not in reg.snapshot()["gauges"]
    # the unsupported probe result is cached — later spans skip polling
    assert t._hbm_supported is False


def test_hbm_sampler_records_watermarks_when_supported(monkeypatch):
    from peasoup_tpu.obs import trace as tr

    stats = iter([
        {"bytes_in_use": 100, "peak_bytes_in_use": 800},
        {"bytes_in_use": 50, "peak_bytes_in_use": 1200},
    ])
    monkeypatch.setattr(tr, "hbm_watermark", lambda: next(stats))
    reg = MetricsRegistry()
    t = Tracer(registry=reg)
    with t.span("s1"):
        pass
    with t.span("s2"):
        pass
    r1, r2 = t.records()
    assert r1.attrs["hbm_bytes_in_use"] == 100
    assert r1.attrs["hbm_peak_bytes"] == 800
    assert r2.attrs["hbm_peak_bytes"] == 1200
    # run-level high-water gauge tracks the max peak seen
    assert reg.snapshot()["gauges"]["hbm.high_water_bytes"] == 1200


# --------------------------------------------------------------------------
# span table
# --------------------------------------------------------------------------

def test_span_table_self_vs_total():
    import time

    t = Tracer(registry=MetricsRegistry())
    with t.span("outer"):
        with t.span("inner"):
            time.sleep(0.02)
    table = span_table(t.records())
    assert set(table) == {"outer", "inner"}
    assert table["inner"]["total_s"] >= 0.02
    # outer's self time excludes its child
    assert table["outer"]["self_s"] <= (
        table["outer"]["total_s"] - table["inner"]["total_s"] + 1e-3)
    for rec in table.values():
        assert {"count", "total_s", "self_s", "device_s"} <= set(rec)


# --------------------------------------------------------------------------
# multihost merge (faked 2-process gather)
# --------------------------------------------------------------------------

def test_multihost_merge_faked_two_process_gather(tmp_path):
    """Host 0 gathers both processes' payloads and writes ONE merged
    trace whose events keep their per-process pid tags; host 1 joins
    the gather but writes nothing."""
    t = Tracer(registry=MetricsRegistry())
    with t.span("Fused-Search", n_dm_trials=59):
        with t.span("Peak-Decode"):
            pass
    payload0 = local_trace_payload(t)
    # fake the second process: same spans, pid-tagged 1
    doc1 = json.loads(payload0)
    doc1["process_index"] = 1
    for e in doc1["events"]:
        e["pid"] = 1
    payload1 = json.dumps(doc1).encode()

    def fake_gather(payload):
        assert payload == payload0
        return [payload0, payload1]

    path = str(tmp_path / "merged.json")
    out = write_merged_trace(path, tracer=t, gather=fake_gather,
                             process_index=0)
    assert out == path
    doc = json.load(open(path))
    assert doc["metadata"]["n_processes"] == 2
    pids = {e.get("pid") for e in doc["traceEvents"]
            if e.get("ph") in ("B", "E")}
    assert pids == {0, 1}
    _balance_check(doc["traceEvents"])
    # process 1 participates but does not write
    other = str(tmp_path / "other.json")
    assert write_merged_trace(other, tracer=t, gather=fake_gather,
                              process_index=1) is None
    assert not os.path.exists(other)


def test_gather_host_payloads_single_process():
    from peasoup_tpu.parallel.multihost import gather_host_payloads

    assert gather_host_payloads(b"abc") == [b"abc"]


# --------------------------------------------------------------------------
# trace_report tool
# --------------------------------------------------------------------------

@pytest.fixture()
def sample_trace(tmp_path):
    import time

    t = Tracer(registry=MetricsRegistry())
    with t.span("Fused-Search"):
        with t.span("Peak-Decode"):
            time.sleep(0.01)
    with t.span("Folding"):
        pass
    path = str(tmp_path / "trace.json")
    write_merged_trace(path, tracer=t, gather=lambda b: [b],
                       process_index=0)
    return path


def test_trace_report_table_and_critical_path(sample_trace, capsys):
    from peasoup_tpu.tools.trace_report import main

    rc = main([sample_trace, "--top", "10"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "Fused-Search" in out and "Peak-Decode" in out
    assert "critical path" in out
    # the critical path descends Fused-Search -> Peak-Decode
    cp = out[out.index("critical path"):]
    assert cp.index("Fused-Search") < cp.index("Peak-Decode")


def test_trace_report_require_gate(sample_trace, capsys):
    from peasoup_tpu.tools.trace_report import main

    assert main([sample_trace, "--require", "Fused-Search",
                 "Folding"]) == 0
    capsys.readouterr()
    assert main([sample_trace, "--require", "Dedisperse"]) == 1
    assert "Dedisperse" in capsys.readouterr().err


def test_trace_report_rejects_unbalanced(tmp_path, capsys):
    from peasoup_tpu.tools.trace_report import main

    path = str(tmp_path / "bad.json")
    json.dump({"traceEvents": [
        {"name": "x", "ph": "B", "ts": 0, "pid": 0, "tid": 0},
    ]}, open(path, "w"))
    assert main([path]) == 2
    assert "unbalanced" in capsys.readouterr().err


# --------------------------------------------------------------------------
# driver integration: per-chunk spans with DM/trial attributes
# --------------------------------------------------------------------------

def test_chunked_driver_emits_per_chunk_spans(tutorial_fil):
    from peasoup_tpu.io import read_filterbank
    from peasoup_tpu.parallel.mesh import MeshPulsarSearch
    from peasoup_tpu.search.plan import SearchConfig

    get_tracer().reset()
    fil = read_filterbank(tutorial_fil)
    cfg = SearchConfig(
        dm_start=0.0, dm_end=30.0, acc_start=-5.0, acc_end=5.0,
        acc_pulse_width=64000.0, npdmp=0, limit=20,
        dm_chunk=2, accel_block=2,
    )
    result = MeshPulsarSearch(fil, cfg).run()
    assert len(result.candidates) > 0
    recs = get_tracer().records()
    chunks = [r for r in recs if r.name.startswith("Chunked-Search-")]
    assert chunks, "chunked driver must open per-chunk spans"
    for r in chunks:
        assert r.attrs["chunk"] >= 0
        assert r.attrs["n_dm_rows"] >= 0
        if r.attrs["n_dm_rows"]:
            assert r.attrs["dm_lo"] <= r.attrs["dm_hi"]
            assert r.attrs["n_trials"] > 0
    # chunk ids are distinct and dense from 0
    ids = sorted(r.attrs["chunk"] for r in chunks)
    assert ids == list(range(len(chunks)))
    names = {r.name for r in recs}
    assert {"Peak-Decode", "Distill"} <= names


def test_measure_dedispersion_stage_reports_nonzero(tutorial_fil):
    """The bench blind spot: the fused mesh path fuses dedispersion
    into the search program and reported 0.0; the dedicated
    measurement dispatch must return a real figure."""
    from peasoup_tpu.io import read_filterbank
    from peasoup_tpu.parallel.mesh import MeshPulsarSearch
    from peasoup_tpu.search.plan import SearchConfig

    fil = read_filterbank(tutorial_fil)
    cfg = SearchConfig(dm_start=0.0, dm_end=30.0, npdmp=0, limit=20)
    search = MeshPulsarSearch(fil, cfg)
    get_tracer().reset()
    dt = search.measure_dedispersion_stage()
    assert dt > 0.0
    recs = [r for r in get_tracer().records() if r.name == "Dedisperse"]
    assert recs and recs[-1].attrs.get("measured") is True


# --------------------------------------------------------------------------
# e2e: tutorial CLI run covers the five pipeline stages
# --------------------------------------------------------------------------

FIVE_STAGES = {"Dedisperse", "DM-Loop", "Accel-Search", "Distill",
               "Folding"}


def test_tutorial_cli_trace_covers_five_stages(tutorial_fil, tmp_path):
    import warnings

    from peasoup_tpu.cli import main
    from peasoup_tpu.obs.metrics import REGISTRY
    from peasoup_tpu.tools.trace_report import (
        critical_path,
        rebuild_spans,
    )

    REGISTRY.reset()  # stage-timer counts must describe THIS run
    outdir = str(tmp_path / "out")
    trace_path = str(tmp_path / "trace.json")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        rc = main([
            "-i", tutorial_fil, "-o", outdir,
            "--dm_start", "0", "--dm_end", "60",
            "--acc_start", "-5", "--acc_end", "5",
            "--acc_pulse_width", "64000",
            "--npdmp", "2", "--limit", "50",
            "--single_device", "--trace_json", trace_path,
        ])
    assert rc == 0
    doc = json.load(open(trace_path))
    events = doc["traceEvents"]
    _balance_check(events)
    spans = rebuild_spans(events)
    names = {s["name"] for s in spans}
    assert FIVE_STAGES <= names, f"missing: {FIVE_STAGES - names}"
    # per-trial attribution on the accel-search spans
    accel = [s for s in spans if s["name"] == "Accel-Search"]
    assert len(accel) >= 10  # one per (DM trial, accel chunk)
    dms = {s["args"]["dm_trial"] for s in accel}
    assert len(dms) > 1
    for s in accel[:5]:
        assert "dm" in s["args"] and "n_trials" in s["args"]
    # spans nest: Accel-Search sits under DM-Loop
    assert accel[0]["parent"] is not None
    assert accel[0]["parent"]["name"] == "DM-Loop"
    assert critical_path(spans), "critical path must be derivable"
    # the run report carries the span table and a real dedispersion time
    report = json.load(open(os.path.join(outdir, "run_report.json")))
    assert "spans" in report
    assert "DM-Loop" in report["spans"]
    assert report["timers"]["dedispersion"] > 0.0
    assert report["stage_timers"]["accel_search"]["count"] == len(accel)
