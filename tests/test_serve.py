"""Survey scheduler tests: spool atomicity, retry/quarantine, priority
ordering, end-to-end drain with candidate-store assertions, crashed-
worker recovery, and checkpoint resume across a retry."""

import json
import os
import threading

import numpy as np
import pytest

from peasoup_tpu.errors import ConfigError, InputFileError
from peasoup_tpu.obs.metrics import REGISTRY
from peasoup_tpu.serve import (
    QUARANTINE,
    RETRY,
    BackoffPolicy,
    CandidateStore,
    JobSpool,
    SurveyWorker,
    classify_failure,
)


@pytest.fixture(autouse=True)
def _fresh_registry():
    REGISTRY.reset()
    yield
    REGISTRY.reset()


def _write_fil(path, nsamps=4096, nchans=16, seed=0, pulse=True):
    from peasoup_tpu.io.sigproc import (
        Filterbank, SigprocHeader, write_filterbank,
    )

    rng = np.random.default_rng(seed)
    data = rng.integers(0, 32, size=(nsamps, nchans), dtype=np.uint8)
    if pulse:
        data[::16] += 60
    hdr = SigprocHeader(nbits=8, nchans=nchans, tsamp=0.000256,
                        fch1=1510.0, foff=-10.0, nsamples=nsamps)
    write_filterbank(str(path), Filterbank(header=hdr, data=data))
    return str(path)


def _write_truncated_fil(path, nsamps=4096, nchans=16, seed=0):
    """Header promises ``nsamps`` but 1024 data bytes are missing."""
    from peasoup_tpu.io.sigproc import (
        SigprocHeader, write_sigproc_header,
    )

    rng = np.random.default_rng(seed)
    data = rng.integers(0, 32, size=(nsamps, nchans), dtype=np.uint8)
    hdr = SigprocHeader(nbits=8, nchans=nchans, tsamp=0.000256,
                        fch1=1510.0, foff=-10.0, nsamples=nsamps)
    with open(str(path), "wb") as f:
        write_sigproc_header(f, hdr, include_nsamples=True)
        f.write(data.tobytes()[:-1024])
    return str(path)


#: fast search overrides shared by the end-to-end tests
FAST = {"dm_end": 20.0, "min_snr": 6.0, "npdmp": 0, "limit": 10}


# --------------------------------------------------------------------------
# spool mechanics
# --------------------------------------------------------------------------

def test_submit_claim_priority_order(tmp_path):
    spool = JobSpool(str(tmp_path / "jobs"))
    lo = spool.submit("/tmp/lo.fil", priority=0)
    hi = spool.submit("/tmp/hi.fil", priority=9)
    mid = spool.submit("/tmp/mid.fil", priority=5)
    lo2 = spool.submit("/tmp/lo2.fil", priority=0)
    order = []
    while True:
        job = spool.claim("w")
        if job is None:
            break
        order.append(job.job_id)
        spool.mark_done(job)
    # priority descending, FIFO within a band
    assert order == [hi.job_id, mid.job_id, lo.job_id, lo2.job_id]


def test_atomic_claim_under_concurrent_workers(tmp_path):
    """Two workers hammering one spool: every job claimed exactly
    once (the rename is the arbiter)."""
    spool = JobSpool(str(tmp_path / "jobs"))
    submitted = {spool.submit(f"/tmp/{i}.fil").job_id
                 for i in range(24)}
    claimed: dict[str, list] = {"a": [], "b": []}
    barrier = threading.Barrier(2)

    def _worker(name):
        barrier.wait()
        while True:
            job = spool.claim(name)
            if job is None:
                return
            claimed[name].append(job.job_id)

    ts = [threading.Thread(target=_worker, args=(n,)) for n in "ab"]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    ids_a, ids_b = set(claimed["a"]), set(claimed["b"])
    assert ids_a | ids_b == submitted
    assert ids_a & ids_b == set()  # no double claim
    assert spool.counts()["pending"] == 0
    assert spool.counts()["running"] == 24


def test_requeue_recovers_crashed_worker_job(tmp_path):
    """A job stuck in running/ after a worker crash goes back to
    pending via requeue, keeping its attempt count and record."""
    spool = JobSpool(str(tmp_path / "jobs"))
    rec = spool.submit("/tmp/x.fil", {"dm_end": 30.0}, priority=2)
    job = spool.claim("doomed-worker")
    assert job.attempts == 1
    # the worker dies here; nothing releases the job
    assert spool.counts()["running"] == 1
    back = spool.requeue(job.job_id)
    assert back.attempts == 1 and back.worker == ""
    assert spool.counts() == {"pending": 1, "running": 0, "done": 0,
                              "failed": 0}
    again = spool.claim("w2")
    assert again.job_id == rec.job_id
    assert again.attempts == 2
    assert again.overrides == {"dm_end": 30.0}
    # unknown job ids are a typed error
    with pytest.raises(ConfigError):
        spool.requeue("no-such-job")


def test_job_record_roundtrip_and_corrupt_record(tmp_path):
    spool = JobSpool(str(tmp_path / "jobs"))
    rec = spool.submit("/tmp/x.fil", {"npdmp": 4}, priority=1)
    state, loaded = spool.get(rec.job_id)
    assert state == "pending"
    assert loaded.overrides == {"npdmp": 4}
    # corrupt record: warned and skipped, not a crash
    bad = os.path.join(spool.root, "pending", "zzzz.json")
    with open(bad, "w") as f:
        f.write("{torn")
    with pytest.warns(UserWarning, match="unreadable job record"):
        jobs = spool.pending_jobs()
    assert [j.job_id for j in jobs] == [rec.job_id]


# --------------------------------------------------------------------------
# retry / classification
# --------------------------------------------------------------------------

def test_classification_table():
    assert classify_failure(InputFileError("bad")) == QUARANTINE
    assert classify_failure(ConfigError("bad")) == QUARANTINE
    assert classify_failure(FileNotFoundError("gone")) == QUARANTINE
    assert classify_failure(RuntimeError("flaky")) == RETRY
    assert classify_failure(OSError("io blip")) == RETRY
    from peasoup_tpu.serve.retry import JobTimeoutError

    assert classify_failure(JobTimeoutError("slow")) == RETRY


def test_backoff_retry_then_exhaustion(tmp_path):
    """A transiently-failing job is re-queued with exponential backoff
    until max_attempts, then lands in failed/ with the full log."""
    spool = JobSpool(str(tmp_path / "jobs"))
    spool.submit("/tmp/flaky.fil")
    delays = []
    worker = SurveyWorker(
        spool,
        backoff=BackoffPolicy(max_attempts=3, base_s=1.0, factor=2.0),
        run_job_fn=lambda job: (_ for _ in ()).throw(
            RuntimeError("flaky device")),
        sleeper=delays.append,
        history_path=str(tmp_path / "h.jsonl"),
    )
    with pytest.warns(UserWarning):
        summary = worker.drain()
    assert (summary["claimed"], summary["succeeded"],
            summary["failed"]) == (3, 0, 3)
    assert delays == [1.0, 2.0]  # backoff doubled, none after the last
    counts = spool.counts()
    assert counts["failed"] == 1 and counts["pending"] == 0
    failed = spool.jobs("failed")[0]
    assert failed.attempts == 3
    assert [f["classification"] for f in failed.failures] == [RETRY] * 3
    assert all("flaky device" in f["error"] for f in failed.failures)
    assert all("RuntimeError" in f["traceback"]
               for f in failed.failures)
    counters = REGISTRY.snapshot()["counters"]
    assert counters["scheduler.retried"] == 2
    assert counters["scheduler.exhausted"] == 1


def test_quarantine_skips_retries(tmp_path):
    spool = JobSpool(str(tmp_path / "jobs"))
    spool.submit("/tmp/corrupt.fil")
    delays = []
    worker = SurveyWorker(
        spool,
        backoff=BackoffPolicy(max_attempts=5),
        run_job_fn=lambda job: (_ for _ in ()).throw(
            InputFileError("truncated filterbank: 64 of 128 bytes")),
        sleeper=delays.append,
        history_path=str(tmp_path / "h.jsonl"),
    )
    with pytest.warns(UserWarning, match="quarantined"):
        worker.drain()
    assert delays == []  # no backoff burned on a deterministic failure
    failed = spool.jobs("failed")[0]
    assert failed.attempts == 1
    assert failed.failures[0]["classification"] == QUARANTINE
    assert REGISTRY.snapshot()["counters"]["scheduler.quarantined"] == 1


def test_per_job_timeout_classified_transient(tmp_path):
    import time as _time

    spool = JobSpool(str(tmp_path / "jobs"))
    spool.submit("/tmp/slow.fil")
    worker = SurveyWorker(
        spool, timeout_s=0.1,
        backoff=BackoffPolicy(max_attempts=1),
        run_job_fn=lambda job: _time.sleep(5.0),
        sleeper=lambda s: None,
        history_path=str(tmp_path / "h.jsonl"),
    )
    with pytest.warns(UserWarning):
        worker.drain()
    failed = spool.jobs("failed")[0]
    assert failed.failures[0]["classification"] == RETRY
    assert "budget" in failed.failures[0]["error"]


# --------------------------------------------------------------------------
# candidate store
# --------------------------------------------------------------------------

class _C:
    def __init__(self, freq, snr, dm=10.0):
        self.freq = freq
        self.snr = snr
        self.dm = dm
        self.acc = 0.0
        self.folded_snr = 0.0
        self.nh = 0


def test_store_ingest_query_and_coincidence(tmp_path):
    store = CandidateStore(str(tmp_path / "cands.jsonl"))
    # the same 10 Hz signal in two observations, plus unrelated noise
    store.ingest("j1", "beamA.fil", [_C(10.0, 12.0), _C(3.3, 9.0)])
    store.ingest("j2", "beamB.fil", [_C(10.0004, 11.0)])
    store.ingest("j3", "beamC.fil", [_C(77.7, 9.5)])
    assert store.count() == 4
    assert store.sources() == ["beamA.fil", "beamB.fil", "beamC.fil"]

    hits = store.query(10.0, freq_tol=1e-3)
    assert sorted(r["source"] for r in hits) == ["beamA.fil",
                                                "beamB.fil"]
    # harmonic-aware: 20 Hz record matches a 10 Hz lookup at max_harm 2
    store.ingest("j4", "beamD.fil", [_C(20.0, 8.0)])
    hits = store.query(10.0, freq_tol=1e-3, max_harm=2)
    assert "beamD.fil" in {r["source"] for r in hits}

    groups = store.coincident_groups(freq_tol=1e-3, min_sources=2)
    assert len(groups) == 1
    grp = groups[0]
    assert {r["source"] for r in grp} >= {"beamA.fil", "beamB.fil"}
    # strongest detection leads the group (distiller ordering)
    assert grp[0]["snr"] == 12.0


def test_store_tolerates_torn_tail(tmp_path):
    store = CandidateStore(str(tmp_path / "cands.jsonl"))
    store.ingest("j1", "a.fil", [_C(5.0, 10.0)])
    with open(store.path, "a") as f:
        f.write('{"v": 1, "job_id": "torn"')  # killed mid-append
    assert store.count() == 1


# --------------------------------------------------------------------------
# end-to-end: drain a real spool through the real pipeline
# --------------------------------------------------------------------------

def test_worker_drain_end_to_end(tmp_path):
    """Three synthetic observations (one truncated) through the CLI
    worker: 2 done with candidates in the store, 1 quarantined with
    the byte counts, scheduler counters + throughput ledger record."""
    from peasoup_tpu.serve.cli import main

    spool_dir = str(tmp_path / "jobs")
    ledger = str(tmp_path / "history.jsonl")
    good1 = _write_fil(tmp_path / "obs1.fil", seed=1)
    good2 = _write_fil(tmp_path / "obs2.fil", seed=2)
    bad = _write_truncated_fil(tmp_path / "obs3.fil", seed=3)

    rc = main(["--spool", spool_dir, "submit", good1, bad,
               "--set", "dm_end=20.0", "--set", "min_snr=6.0",
               "--set", "npdmp=0", "--set", "limit=10"])
    assert rc == 0
    rc = main(["--spool", spool_dir, "submit", good2, "--priority", "5",
               "--set", "dm_end=20.0", "--set", "min_snr=6.0",
               "--set", "npdmp=0", "--set", "limit=10"])
    assert rc == 0

    with pytest.warns(UserWarning, match="quarantined"):
        rc = main(["--spool", spool_dir, "worker", "--drain",
                   "--single_device", "--max-attempts", "2",
                   "--backoff-base", "0", "--history", ledger])
    assert rc == 1  # nonzero: a job failed

    spool = JobSpool(spool_dir)
    counts = spool.counts()
    assert counts["done"] == 2 and counts["failed"] == 1
    # the high-priority job ran first despite submitting last
    done = sorted(spool.jobs("done"), key=lambda r: r.claimed_utc)
    assert done[0].input == good2
    for rec in done:
        assert rec.summary["candidates"] >= 1
        report = os.path.join(rec.summary["outdir"], "run_report.json")
        assert json.load(open(report))["candidates"]["count"] >= 1

    failed = spool.jobs("failed")[0]
    assert failed.input == bad
    assert failed.failures[0]["classification"] == QUARANTINE
    assert "truncated filterbank" in failed.failures[0]["error"]
    assert failed.attempts == 1  # quarantine is immediate

    store = CandidateStore(os.path.join(spool_dir, "candidates.jsonl"))
    assert store.count() >= 2
    assert set(store.sources()) == {good1, good2}
    for rec in store.records():
        assert rec["job_id"] and rec["snr"] >= 6.0

    counters = REGISTRY.snapshot()["counters"]
    assert counters["scheduler.submitted"] == 3
    assert counters["scheduler.claimed"] == 3
    assert counters["scheduler.succeeded"] == 2
    assert counters["scheduler.quarantined"] == 1
    # the second good observation was prefetched while the first ran
    assert counters.get("scheduler.prefetch_hits", 0) >= 1
    # identical geometry -> one plan bucket, programs reused
    assert counters.get("scheduler.plan_reuse", 0) >= 1

    from peasoup_tpu.obs.history import load_history

    recs = load_history(ledger, kinds=["serve"])
    assert len(recs) == 1
    assert recs[0]["metrics"]["jobs_succeeded"] == 2
    assert recs[0]["metrics"]["jobs_per_hour"] > 0
    assert recs[0]["config"]["geometry_buckets"] >= 1

    # status verb renders without blowing up
    rc = main(["--spool", spool_dir, "status", "--jobs"])
    assert rc == 0


def test_crashed_job_resumes_from_checkpoint(tmp_path, monkeypatch):
    """A job that dies mid-search is re-queued; the retry must RESUME
    the checkpointed DM rows, not recompute them."""
    from peasoup_tpu.search.pipeline import PulsarSearch

    spool = JobSpool(str(tmp_path / "jobs"))
    fil = _write_fil(tmp_path / "obs.fil", seed=7)
    spool.submit(fil, {**FAST, "checkpoint_interval": 1})

    orig = PulsarSearch.search_dm_trial
    seen: dict[str, list] = {"first": [], "second": []}

    def _crashing(self, trials, idx):
        phase = "first" if not seen["second"] and \
            len(seen["first"]) <= 5 else "second"
        if phase == "first":
            seen["first"].append(idx)
            if len(seen["first"]) > 5:
                raise RuntimeError("injected crash")
        else:
            seen["second"].append(idx)
        return orig(self, trials, idx)

    monkeypatch.setattr(PulsarSearch, "search_dm_trial", _crashing)
    worker = SurveyWorker(
        spool, single_device=True,
        backoff=BackoffPolicy(max_attempts=2, base_s=0.0),
        history_path=str(tmp_path / "h.jsonl"),
        sleeper=lambda s: None,
    )
    with pytest.warns(UserWarning, match="re-queueing"):
        summary = worker.drain()

    assert summary["succeeded"] == 1
    assert spool.counts()["done"] == 1
    counters = REGISTRY.snapshot()["counters"]
    assert counters["scheduler.retried"] == 1
    assert counters["checkpoint.rows_resumed"] >= 5
    # the resumed attempt never re-searched the checkpointed rows
    assert not set(seen["second"]) & set(seen["first"][:-1])


def test_worker_rejects_unknown_override_as_quarantine(tmp_path):
    spool = JobSpool(str(tmp_path / "jobs"))
    fil = _write_fil(tmp_path / "obs.fil")
    spool.submit(fil, {"not_a_knob": 1})
    worker = SurveyWorker(spool, single_device=True,
                          sleeper=lambda s: None,
                          history_path=str(tmp_path / "h.jsonl"))
    with pytest.warns(UserWarning, match="quarantined"):
        worker.drain()
    failed = spool.jobs("failed")[0]
    assert failed.failures[0]["classification"] == QUARANTINE
    assert "not_a_knob" in failed.failures[0]["error"]


def test_geometry_bucketing_is_lossless(tmp_path):
    """Two observations whose sample counts share an FFT bucket must
    land in ONE geometry bucket, and trimming must not change the
    candidates (same data prefix => same results as the full read)."""
    from peasoup_tpu.io import read_filterbank
    from peasoup_tpu.search.pipeline import PulsarSearch
    from peasoup_tpu.search.plan import SearchConfig

    rng = np.random.default_rng(11)
    base = rng.integers(0, 32, size=(4500, 16), dtype=np.uint8)
    base[::16] += 60

    def _write(path, nsamps):
        from peasoup_tpu.io.sigproc import (
            Filterbank, SigprocHeader, write_filterbank,
        )

        hdr = SigprocHeader(nbits=8, nchans=16, tsamp=0.000256,
                            fch1=1510.0, foff=-10.0, nsamples=nsamps)
        write_filterbank(str(path),
                         Filterbank(header=hdr, data=base[:nsamps]))
        return str(path)

    a = _write(tmp_path / "a.fil", 4400)
    b = _write(tmp_path / "b.fil", 4500)
    spool = JobSpool(str(tmp_path / "jobs"))
    for path in (a, b):
        spool.submit(path, FAST)
    worker = SurveyWorker(spool, single_device=True,
                          prefetch=False, sleeper=lambda s: None,
                          history_path=str(tmp_path / "h.jsonl"))
    summary = worker.drain()
    assert summary["succeeded"] == 2
    assert summary["geometry_buckets"] == 1
    assert REGISTRY.snapshot()["counters"]["scheduler.plan_reuse"] == 1

    # parity: the trimmed search returns exactly the full search's
    # candidates for observation a
    cfg = SearchConfig(**FAST)
    full = PulsarSearch(read_filterbank(a), cfg).run()
    store = CandidateStore(os.path.join(spool.root,
                                        "candidates.jsonl"))
    got = [(round(r["freq"], 6), round(r["snr"], 3))
           for r in store.records(source=a)]
    want = [(round(float(c.freq), 6), round(float(c.snr), 3))
            for c in full.candidates]
    assert got == want


# --------------------------------------------------------------------------
# batched multi-observation dispatch (ISSUE 9)
# --------------------------------------------------------------------------


def _drain_spool(tmp_path, name, obs, batch):
    """Spool ``obs`` and drain with a ``batch``-wide mesh worker;
    returns (spool, drain summary)."""
    spool = JobSpool(str(tmp_path / name))
    for path in obs:
        spool.submit(path, FAST)
    worker = SurveyWorker(
        spool, batch=batch, sleeper=lambda s: None,
        history_path=str(tmp_path / f"{name}.jsonl"))
    return spool, worker.drain()


def _per_source_outputs(spool, sources):
    """{source: (store tuples, candidates.peasoup bytes)} — the
    bit-identity fingerprint of a drained spool."""
    store = CandidateStore(os.path.join(spool.root, "candidates.jsonl"))
    by_input = {rec.input: rec for rec in spool.jobs("done")}
    out = {}
    for src in sources:
        cands = sorted(
            (r["dm"], r["acc"], r["freq"], r["snr"], r["folded_snr"],
             r["nh"])
            for r in store.records(source=src)
        )
        binary = open(os.path.join(
            by_input[src].summary["outdir"], "candidates.peasoup"),
            "rb").read()
        out[os.path.basename(src)] = (cands, binary)
    return out


def test_batched_drain_bit_identical_to_sequential(tmp_path):
    """Three same-geometry observations drained as ONE batched dispatch
    must produce byte-for-byte the candidates of three sequential
    dispatches: store records AND candidates.peasoup binaries."""
    obs = [_write_fil(tmp_path / f"obs{i}.fil", seed=i)
           for i in range(3)]

    seq_spool, seq_sum = _drain_spool(tmp_path, "seq", obs, batch=1)
    assert seq_sum["succeeded"] == 3
    seq_counters = REGISTRY.snapshot()["counters"]
    assert seq_counters.get("scheduler.batched_dispatches", 0) == 0
    seq_dispatches = seq_counters["runs.mesh_fused"]

    REGISTRY.reset()
    bat_spool, bat_sum = _drain_spool(tmp_path, "bat", obs, batch=3)
    assert bat_sum["succeeded"] == 3 and bat_sum["batch"] == 3
    counters = REGISTRY.snapshot()["counters"]
    assert counters["scheduler.batched_dispatches"] == 1
    assert counters["scheduler.batch_fill"] == 3
    # the point of batching: fewer fused device dispatches
    assert counters["runs.mesh_fused"] < seq_dispatches
    for rec in bat_spool.jobs("done"):
        assert rec.summary["batch"] == 3

    assert (_per_source_outputs(bat_spool, obs)
            == _per_source_outputs(seq_spool, obs))


def test_batched_drain_quarantines_failing_beam(tmp_path):
    """A truncated observation claimed into a batch must quarantine via
    the typed-failure path WITHOUT poisoning its batch-mates: the good
    beams complete with candidates, the bad one carries the
    InputFileError byte counts, and no checkpoint files leak."""
    good = [_write_fil(tmp_path / f"obs{i}.fil", seed=i)
            for i in range(2)]
    bad = _write_truncated_fil(tmp_path / "obs_bad.fil", seed=9)

    spool = JobSpool(str(tmp_path / "jobs"))
    # submit the bad beam between the good ones: batch-mate claiming
    # must not depend on queue position
    for path in (good[0], bad, good[1]):
        spool.submit(path, FAST)
    worker = SurveyWorker(
        spool, batch=3, sleeper=lambda s: None,
        backoff=BackoffPolicy(max_attempts=2, base_s=0.0),
        history_path=str(tmp_path / "h.jsonl"))
    with pytest.warns(UserWarning, match="quarantined"):
        summary = worker.drain()

    assert summary["succeeded"] == 2 and summary["failed"] == 1
    counts = spool.counts()
    assert counts["done"] == 2 and counts["failed"] == 1
    counters = REGISTRY.snapshot()["counters"]
    # the two surviving beams still went out as ONE batched dispatch
    assert counters["scheduler.batched_dispatches"] == 1
    assert counters["scheduler.batch_fill"] == 2
    assert counters["scheduler.quarantined"] == 1

    failed = spool.jobs("failed")[0]
    assert failed.input == bad
    assert failed.failures[0]["classification"] == QUARANTINE
    assert "truncated filterbank" in failed.failures[0]["error"]
    assert failed.attempts == 1  # quarantine is immediate

    store = CandidateStore(os.path.join(spool.root, "candidates.jsonl"))
    assert set(store.sources()) == set(good)
    for rec in spool.jobs("done"):
        assert rec.summary["candidates"] >= 1
        # per-beam checkpoints were consumed on success, not leaked
        assert not os.path.exists(
            os.path.join(spool.work_dir(rec.job_id), "search.ckpt"))


# --------------------------------------------------------------------------
# load observatory (ISSUE 12): dual-clock failure history and the
# drain ledger's latency percentiles
# --------------------------------------------------------------------------


def test_failure_history_carries_monotonic_clock(tmp_path):
    """Every failure entry records BOTH clocks: ``utc`` (wall, for
    humans and cross-host merging) and ``t_mono`` (monotonic, so
    per-process failure spacing survives NTP steps)."""
    spool = JobSpool(str(tmp_path / "jobs"))
    spool.submit(_write_fil(tmp_path / "obs.fil"), FAST)

    def _explode(job):
        raise ConfigError("injected config failure")

    worker = SurveyWorker(spool, single_device=True, prefetch=False,
                          run_job_fn=_explode, sleeper=lambda s: None,
                          history_path=str(tmp_path / "h.jsonl"))
    with pytest.warns(UserWarning, match="quarantined"):
        worker.drain()

    entry = spool.jobs("failed")[0].failures[-1]
    assert entry["classification"] == QUARANTINE
    assert "utc" in entry
    assert isinstance(entry["t_mono"], float) and entry["t_mono"] > 0


def test_drain_ledger_records_sojourn_percentiles(tmp_path):
    """A drain's serve ledger record carries the end-to-end latency of
    the jobs it finished (sojourn/queue-wait p95 from the per-job
    timelines) plus the timeline's own bookkeeping cost."""
    from peasoup_tpu.obs.history import load_history

    spool = JobSpool(str(tmp_path / "jobs"))
    for i in range(3):
        spool.submit(_write_fil(tmp_path / f"obs{i}.fil", seed=i), FAST)
    history = str(tmp_path / "h.jsonl")
    worker = SurveyWorker(spool, single_device=True, prefetch=False,
                          run_job_fn=lambda job: {"candidates": 0},
                          sleeper=lambda s: None, history_path=history)
    summary = worker.drain()
    assert summary["succeeded"] == 3

    (rec,) = load_history(history, kinds=["serve"])
    m = rec["metrics"]
    for key in ("sojourn_p50", "sojourn_p95",
                "queue_wait_p50", "queue_wait_p95"):
        assert isinstance(m[key], float), key
    # sojourn includes the queue wait, so the p95s must be ordered
    assert m["sojourn_p95"] >= m["queue_wait_p95"] >= 0.0
    assert m["sojourn_p95"] > 0.0
    # the worker self-accounts its OWN marks (claim + done per job;
    # the submit mark belongs to the submitter's ledger)
    assert m["timeline_marks"] >= 6
    assert m["timeline_overhead_s"] >= 0.0
