"""Survey scheduler tests: spool atomicity, retry/quarantine, priority
ordering, end-to-end drain with candidate-store assertions, crashed-
worker recovery, and checkpoint resume across a retry."""

import json
import os
import threading

import numpy as np
import pytest

from peasoup_tpu.errors import ConfigError, InputFileError
from peasoup_tpu.obs.metrics import REGISTRY
from peasoup_tpu.serve import (
    QUARANTINE,
    RETRY,
    BackoffPolicy,
    CandidateStore,
    JobSpool,
    SurveyWorker,
    classify_failure,
)


@pytest.fixture(autouse=True)
def _fresh_registry():
    REGISTRY.reset()
    yield
    REGISTRY.reset()


def _write_fil(path, nsamps=4096, nchans=16, seed=0, pulse=True):
    from peasoup_tpu.io.sigproc import (
        Filterbank, SigprocHeader, write_filterbank,
    )

    rng = np.random.default_rng(seed)
    data = rng.integers(0, 32, size=(nsamps, nchans), dtype=np.uint8)
    if pulse:
        data[::16] += 60
    hdr = SigprocHeader(nbits=8, nchans=nchans, tsamp=0.000256,
                        fch1=1510.0, foff=-10.0, nsamples=nsamps)
    write_filterbank(str(path), Filterbank(header=hdr, data=data))
    return str(path)


def _write_truncated_fil(path, nsamps=4096, nchans=16, seed=0):
    """Header promises ``nsamps`` but 1024 data bytes are missing."""
    from peasoup_tpu.io.sigproc import (
        SigprocHeader, write_sigproc_header,
    )

    rng = np.random.default_rng(seed)
    data = rng.integers(0, 32, size=(nsamps, nchans), dtype=np.uint8)
    hdr = SigprocHeader(nbits=8, nchans=nchans, tsamp=0.000256,
                        fch1=1510.0, foff=-10.0, nsamples=nsamps)
    with open(str(path), "wb") as f:
        write_sigproc_header(f, hdr, include_nsamples=True)
        f.write(data.tobytes()[:-1024])
    return str(path)


#: fast search overrides shared by the end-to-end tests
FAST = {"dm_end": 20.0, "min_snr": 6.0, "npdmp": 0, "limit": 10}


# --------------------------------------------------------------------------
# spool mechanics
# --------------------------------------------------------------------------

def test_submit_claim_priority_order(tmp_path):
    spool = JobSpool(str(tmp_path / "jobs"))
    lo = spool.submit("/tmp/lo.fil", priority=0)
    hi = spool.submit("/tmp/hi.fil", priority=9)
    mid = spool.submit("/tmp/mid.fil", priority=5)
    lo2 = spool.submit("/tmp/lo2.fil", priority=0)
    order = []
    while True:
        job = spool.claim("w")
        if job is None:
            break
        order.append(job.job_id)
        spool.mark_done(job)
    # priority descending, FIFO within a band
    assert order == [hi.job_id, mid.job_id, lo.job_id, lo2.job_id]


def test_atomic_claim_under_concurrent_workers(tmp_path):
    """Two workers hammering one spool: every job claimed exactly
    once (the rename is the arbiter)."""
    spool = JobSpool(str(tmp_path / "jobs"))
    submitted = {spool.submit(f"/tmp/{i}.fil").job_id
                 for i in range(24)}
    claimed: dict[str, list] = {"a": [], "b": []}
    barrier = threading.Barrier(2)

    def _worker(name):
        barrier.wait()
        while True:
            job = spool.claim(name)
            if job is None:
                return
            claimed[name].append(job.job_id)

    ts = [threading.Thread(target=_worker, args=(n,)) for n in "ab"]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    ids_a, ids_b = set(claimed["a"]), set(claimed["b"])
    assert ids_a | ids_b == submitted
    assert ids_a & ids_b == set()  # no double claim
    assert spool.counts()["pending"] == 0
    assert spool.counts()["running"] == 24


def test_requeue_recovers_crashed_worker_job(tmp_path):
    """A job stuck in running/ after a worker crash goes back to
    pending via requeue, keeping its attempt count and record."""
    spool = JobSpool(str(tmp_path / "jobs"))
    rec = spool.submit("/tmp/x.fil", {"dm_end": 30.0}, priority=2)
    job = spool.claim("doomed-worker")
    assert job.attempts == 1
    # the worker dies here; nothing releases the job
    assert spool.counts()["running"] == 1
    back = spool.requeue(job.job_id)
    assert back.attempts == 1 and back.worker == ""
    assert spool.counts() == {"pending": 1, "running": 0, "done": 0,
                              "failed": 0}
    again = spool.claim("w2")
    assert again.job_id == rec.job_id
    assert again.attempts == 2
    assert again.overrides == {"dm_end": 30.0}
    # unknown job ids are a typed error
    with pytest.raises(ConfigError):
        spool.requeue("no-such-job")


def test_job_record_roundtrip_and_corrupt_record(tmp_path):
    spool = JobSpool(str(tmp_path / "jobs"))
    rec = spool.submit("/tmp/x.fil", {"npdmp": 4}, priority=1)
    state, loaded = spool.get(rec.job_id)
    assert state == "pending"
    assert loaded.overrides == {"npdmp": 4}
    # corrupt record: warned and skipped, not a crash
    bad = os.path.join(spool.root, "pending", "zzzz.json")
    with open(bad, "w") as f:
        f.write("{torn")
    with pytest.warns(UserWarning, match="unreadable job record"):
        jobs = spool.pending_jobs()
    assert [j.job_id for j in jobs] == [rec.job_id]


# --------------------------------------------------------------------------
# retry / classification
# --------------------------------------------------------------------------

def test_classification_table():
    assert classify_failure(InputFileError("bad")) == QUARANTINE
    assert classify_failure(ConfigError("bad")) == QUARANTINE
    assert classify_failure(FileNotFoundError("gone")) == QUARANTINE
    assert classify_failure(RuntimeError("flaky")) == RETRY
    assert classify_failure(OSError("io blip")) == RETRY
    from peasoup_tpu.serve.retry import JobTimeoutError

    assert classify_failure(JobTimeoutError("slow")) == RETRY


def test_backoff_retry_then_exhaustion(tmp_path):
    """A transiently-failing job is re-queued with exponential backoff
    until max_attempts, then lands in failed/ with the full log."""
    spool = JobSpool(str(tmp_path / "jobs"))
    spool.submit("/tmp/flaky.fil")
    delays = []
    worker = SurveyWorker(
        spool,
        backoff=BackoffPolicy(max_attempts=3, base_s=1.0, factor=2.0),
        run_job_fn=lambda job: (_ for _ in ()).throw(
            RuntimeError("flaky device")),
        sleeper=delays.append,
        history_path=str(tmp_path / "h.jsonl"),
    )
    with pytest.warns(UserWarning):
        summary = worker.drain()
    assert (summary["claimed"], summary["succeeded"],
            summary["failed"]) == (3, 0, 3)
    assert delays == [1.0, 2.0]  # backoff doubled, none after the last
    counts = spool.counts()
    assert counts["failed"] == 1 and counts["pending"] == 0
    failed = spool.jobs("failed")[0]
    assert failed.attempts == 3
    assert [f["classification"] for f in failed.failures] == [RETRY] * 3
    assert all("flaky device" in f["error"] for f in failed.failures)
    assert all("RuntimeError" in f["traceback"]
               for f in failed.failures)
    counters = REGISTRY.snapshot()["counters"]
    assert counters["scheduler.retried"] == 2
    assert counters["scheduler.exhausted"] == 1


def test_quarantine_skips_retries(tmp_path):
    spool = JobSpool(str(tmp_path / "jobs"))
    spool.submit("/tmp/corrupt.fil")
    delays = []
    worker = SurveyWorker(
        spool,
        backoff=BackoffPolicy(max_attempts=5),
        run_job_fn=lambda job: (_ for _ in ()).throw(
            InputFileError("truncated filterbank: 64 of 128 bytes")),
        sleeper=delays.append,
        history_path=str(tmp_path / "h.jsonl"),
    )
    with pytest.warns(UserWarning, match="quarantined"):
        worker.drain()
    assert delays == []  # no backoff burned on a deterministic failure
    failed = spool.jobs("failed")[0]
    assert failed.attempts == 1
    assert failed.failures[0]["classification"] == QUARANTINE
    assert REGISTRY.snapshot()["counters"]["scheduler.quarantined"] == 1


def test_per_job_timeout_classified_transient(tmp_path):
    import time as _time

    spool = JobSpool(str(tmp_path / "jobs"))
    spool.submit("/tmp/slow.fil")
    worker = SurveyWorker(
        spool, timeout_s=0.1,
        backoff=BackoffPolicy(max_attempts=1),
        run_job_fn=lambda job: _time.sleep(5.0),
        sleeper=lambda s: None,
        history_path=str(tmp_path / "h.jsonl"),
    )
    with pytest.warns(UserWarning):
        worker.drain()
    failed = spool.jobs("failed")[0]
    assert failed.failures[0]["classification"] == RETRY
    assert "budget" in failed.failures[0]["error"]


# --------------------------------------------------------------------------
# candidate store
# --------------------------------------------------------------------------

class _C:
    def __init__(self, freq, snr, dm=10.0):
        self.freq = freq
        self.snr = snr
        self.dm = dm
        self.acc = 0.0
        self.folded_snr = 0.0
        self.nh = 0


def test_store_ingest_query_and_coincidence(tmp_path):
    store = CandidateStore(str(tmp_path / "cands.jsonl"))
    # the same 10 Hz signal in two observations, plus unrelated noise
    store.ingest("j1", "beamA.fil", [_C(10.0, 12.0), _C(3.3, 9.0)])
    store.ingest("j2", "beamB.fil", [_C(10.0004, 11.0)])
    store.ingest("j3", "beamC.fil", [_C(77.7, 9.5)])
    assert store.count() == 4
    assert store.sources() == ["beamA.fil", "beamB.fil", "beamC.fil"]

    hits = store.query(10.0, freq_tol=1e-3)
    assert sorted(r["source"] for r in hits) == ["beamA.fil",
                                                "beamB.fil"]
    # harmonic-aware: 20 Hz record matches a 10 Hz lookup at max_harm 2
    store.ingest("j4", "beamD.fil", [_C(20.0, 8.0)])
    hits = store.query(10.0, freq_tol=1e-3, max_harm=2)
    assert "beamD.fil" in {r["source"] for r in hits}

    groups = store.coincident_groups(freq_tol=1e-3, min_sources=2)
    assert len(groups) == 1
    grp = groups[0]
    assert {r["source"] for r in grp} >= {"beamA.fil", "beamB.fil"}
    # strongest detection leads the group (distiller ordering)
    assert grp[0]["snr"] == 12.0


def test_store_tolerates_torn_tail(tmp_path):
    store = CandidateStore(str(tmp_path / "cands.jsonl"))
    store.ingest("j1", "a.fil", [_C(5.0, 10.0)])
    with open(store.path, "a") as f:
        f.write('{"v": 1, "job_id": "torn"')  # killed mid-append
    assert store.count() == 1


# --------------------------------------------------------------------------
# end-to-end: drain a real spool through the real pipeline
# --------------------------------------------------------------------------

def test_worker_drain_end_to_end(tmp_path):
    """Three synthetic observations (one truncated) through the CLI
    worker: 2 done with candidates in the store, 1 quarantined with
    the byte counts, scheduler counters + throughput ledger record."""
    from peasoup_tpu.serve.cli import main

    spool_dir = str(tmp_path / "jobs")
    ledger = str(tmp_path / "history.jsonl")
    good1 = _write_fil(tmp_path / "obs1.fil", seed=1)
    good2 = _write_fil(tmp_path / "obs2.fil", seed=2)
    bad = _write_truncated_fil(tmp_path / "obs3.fil", seed=3)

    rc = main(["--spool", spool_dir, "submit", good1, bad,
               "--set", "dm_end=20.0", "--set", "min_snr=6.0",
               "--set", "npdmp=0", "--set", "limit=10"])
    assert rc == 0
    rc = main(["--spool", spool_dir, "submit", good2, "--priority", "5",
               "--set", "dm_end=20.0", "--set", "min_snr=6.0",
               "--set", "npdmp=0", "--set", "limit=10"])
    assert rc == 0

    with pytest.warns(UserWarning, match="quarantined"):
        rc = main(["--spool", spool_dir, "worker", "--drain",
                   "--single_device", "--max-attempts", "2",
                   "--backoff-base", "0", "--history", ledger])
    assert rc == 1  # nonzero: a job failed

    spool = JobSpool(spool_dir)
    counts = spool.counts()
    assert counts["done"] == 2 and counts["failed"] == 1
    # the high-priority job ran first despite submitting last
    done = sorted(spool.jobs("done"), key=lambda r: r.claimed_utc)
    assert done[0].input == good2
    for rec in done:
        assert rec.summary["candidates"] >= 1
        report = os.path.join(rec.summary["outdir"], "run_report.json")
        assert json.load(open(report))["candidates"]["count"] >= 1

    failed = spool.jobs("failed")[0]
    assert failed.input == bad
    assert failed.failures[0]["classification"] == QUARANTINE
    assert "truncated filterbank" in failed.failures[0]["error"]
    assert failed.attempts == 1  # quarantine is immediate

    store = CandidateStore(os.path.join(spool_dir, "candidates.jsonl"))
    assert store.count() >= 2
    assert set(store.sources()) == {good1, good2}
    for rec in store.records():
        assert rec["job_id"] and rec["snr"] >= 6.0

    counters = REGISTRY.snapshot()["counters"]
    assert counters["scheduler.submitted"] == 3
    assert counters["scheduler.claimed"] == 3
    assert counters["scheduler.succeeded"] == 2
    assert counters["scheduler.quarantined"] == 1
    # the second good observation was prefetched while the first ran
    assert counters.get("scheduler.prefetch_hits", 0) >= 1
    # identical geometry -> one plan bucket, programs reused
    assert counters.get("scheduler.plan_reuse", 0) >= 1

    from peasoup_tpu.obs.history import load_history

    recs = load_history(ledger, kinds=["serve"])
    assert len(recs) == 1
    assert recs[0]["metrics"]["jobs_succeeded"] == 2
    assert recs[0]["metrics"]["jobs_per_hour"] > 0
    assert recs[0]["config"]["geometry_buckets"] >= 1

    # status verb renders without blowing up
    rc = main(["--spool", spool_dir, "status", "--jobs"])
    assert rc == 0


def test_crashed_job_resumes_from_checkpoint(tmp_path, monkeypatch):
    """A job that dies mid-search is re-queued; the retry must RESUME
    the checkpointed DM rows, not recompute them."""
    from peasoup_tpu.search.pipeline import PulsarSearch

    spool = JobSpool(str(tmp_path / "jobs"))
    fil = _write_fil(tmp_path / "obs.fil", seed=7)
    spool.submit(fil, {**FAST, "checkpoint_interval": 1})

    orig = PulsarSearch.search_dm_trial
    seen: dict[str, list] = {"first": [], "second": []}

    def _crashing(self, trials, idx):
        phase = "first" if not seen["second"] and \
            len(seen["first"]) <= 5 else "second"
        if phase == "first":
            seen["first"].append(idx)
            if len(seen["first"]) > 5:
                raise RuntimeError("injected crash")
        else:
            seen["second"].append(idx)
        return orig(self, trials, idx)

    monkeypatch.setattr(PulsarSearch, "search_dm_trial", _crashing)
    worker = SurveyWorker(
        spool, single_device=True,
        backoff=BackoffPolicy(max_attempts=2, base_s=0.0),
        history_path=str(tmp_path / "h.jsonl"),
        sleeper=lambda s: None,
    )
    with pytest.warns(UserWarning, match="re-queueing"):
        summary = worker.drain()

    assert summary["succeeded"] == 1
    assert spool.counts()["done"] == 1
    counters = REGISTRY.snapshot()["counters"]
    assert counters["scheduler.retried"] == 1
    assert counters["checkpoint.rows_resumed"] >= 5
    # the resumed attempt never re-searched the checkpointed rows
    assert not set(seen["second"]) & set(seen["first"][:-1])


def test_worker_rejects_unknown_override_as_quarantine(tmp_path):
    spool = JobSpool(str(tmp_path / "jobs"))
    fil = _write_fil(tmp_path / "obs.fil")
    spool.submit(fil, {"not_a_knob": 1})
    worker = SurveyWorker(spool, single_device=True,
                          sleeper=lambda s: None,
                          history_path=str(tmp_path / "h.jsonl"))
    with pytest.warns(UserWarning, match="quarantined"):
        worker.drain()
    failed = spool.jobs("failed")[0]
    assert failed.failures[0]["classification"] == QUARANTINE
    assert "not_a_knob" in failed.failures[0]["error"]


def test_geometry_bucketing_is_lossless(tmp_path):
    """Two observations whose sample counts share an FFT bucket must
    land in ONE geometry bucket, and trimming must not change the
    candidates (same data prefix => same results as the full read)."""
    from peasoup_tpu.io import read_filterbank
    from peasoup_tpu.search.pipeline import PulsarSearch
    from peasoup_tpu.search.plan import SearchConfig

    rng = np.random.default_rng(11)
    base = rng.integers(0, 32, size=(4500, 16), dtype=np.uint8)
    base[::16] += 60

    def _write(path, nsamps):
        from peasoup_tpu.io.sigproc import (
            Filterbank, SigprocHeader, write_filterbank,
        )

        hdr = SigprocHeader(nbits=8, nchans=16, tsamp=0.000256,
                            fch1=1510.0, foff=-10.0, nsamples=nsamps)
        write_filterbank(str(path),
                         Filterbank(header=hdr, data=base[:nsamps]))
        return str(path)

    a = _write(tmp_path / "a.fil", 4400)
    b = _write(tmp_path / "b.fil", 4500)
    spool = JobSpool(str(tmp_path / "jobs"))
    for path in (a, b):
        spool.submit(path, FAST)
    worker = SurveyWorker(spool, single_device=True,
                          prefetch=False, sleeper=lambda s: None,
                          history_path=str(tmp_path / "h.jsonl"))
    summary = worker.drain()
    assert summary["succeeded"] == 2
    assert summary["geometry_buckets"] == 1
    assert REGISTRY.snapshot()["counters"]["scheduler.plan_reuse"] == 1

    # parity: the trimmed search returns exactly the full search's
    # candidates for observation a
    cfg = SearchConfig(**FAST)
    full = PulsarSearch(read_filterbank(a), cfg).run()
    store = CandidateStore(os.path.join(spool.root,
                                        "candidates.jsonl"))
    got = [(round(r["freq"], 6), round(r["snr"], 3))
           for r in store.records(source=a)]
    want = [(round(float(c.freq), 6), round(float(c.snr), 3))
            for c in full.candidates]
    assert got == want


# --------------------------------------------------------------------------
# batched multi-observation dispatch (ISSUE 9)
# --------------------------------------------------------------------------


def _drain_spool(tmp_path, name, obs, batch):
    """Spool ``obs`` and drain with a ``batch``-wide mesh worker;
    returns (spool, drain summary)."""
    spool = JobSpool(str(tmp_path / name))
    for path in obs:
        spool.submit(path, FAST)
    worker = SurveyWorker(
        spool, batch=batch, sleeper=lambda s: None,
        history_path=str(tmp_path / f"{name}.jsonl"))
    return spool, worker.drain()


def _per_source_outputs(spool, sources):
    """{source: (store tuples, candidates.peasoup bytes)} — the
    bit-identity fingerprint of a drained spool."""
    store = CandidateStore(os.path.join(spool.root, "candidates.jsonl"))
    by_input = {rec.input: rec for rec in spool.jobs("done")}
    out = {}
    for src in sources:
        cands = sorted(
            (r["dm"], r["acc"], r["freq"], r["snr"], r["folded_snr"],
             r["nh"])
            for r in store.records(source=src)
        )
        binary = open(os.path.join(
            by_input[src].summary["outdir"], "candidates.peasoup"),
            "rb").read()
        out[os.path.basename(src)] = (cands, binary)
    return out


def test_batched_drain_bit_identical_to_sequential(tmp_path):
    """Three same-geometry observations drained as ONE batched dispatch
    must produce byte-for-byte the candidates of three sequential
    dispatches: store records AND candidates.peasoup binaries."""
    obs = [_write_fil(tmp_path / f"obs{i}.fil", seed=i)
           for i in range(3)]

    seq_spool, seq_sum = _drain_spool(tmp_path, "seq", obs, batch=1)
    assert seq_sum["succeeded"] == 3
    seq_counters = REGISTRY.snapshot()["counters"]
    assert seq_counters.get("scheduler.batched_dispatches", 0) == 0
    seq_dispatches = seq_counters["runs.mesh_fused"]

    REGISTRY.reset()
    bat_spool, bat_sum = _drain_spool(tmp_path, "bat", obs, batch=3)
    assert bat_sum["succeeded"] == 3 and bat_sum["batch"] == 3
    counters = REGISTRY.snapshot()["counters"]
    assert counters["scheduler.batched_dispatches"] == 1
    assert counters["scheduler.batch_fill"] == 3
    # the point of batching: fewer fused device dispatches
    assert counters["runs.mesh_fused"] < seq_dispatches
    for rec in bat_spool.jobs("done"):
        assert rec.summary["batch"] == 3

    assert (_per_source_outputs(bat_spool, obs)
            == _per_source_outputs(seq_spool, obs))


def test_batched_drain_quarantines_failing_beam(tmp_path):
    """A truncated observation claimed into a batch must quarantine via
    the typed-failure path WITHOUT poisoning its batch-mates: the good
    beams complete with candidates, the bad one carries the
    InputFileError byte counts, and no checkpoint files leak."""
    good = [_write_fil(tmp_path / f"obs{i}.fil", seed=i)
            for i in range(2)]
    bad = _write_truncated_fil(tmp_path / "obs_bad.fil", seed=9)

    spool = JobSpool(str(tmp_path / "jobs"))
    # submit the bad beam between the good ones: batch-mate claiming
    # must not depend on queue position
    for path in (good[0], bad, good[1]):
        spool.submit(path, FAST)
    worker = SurveyWorker(
        spool, batch=3, sleeper=lambda s: None,
        backoff=BackoffPolicy(max_attempts=2, base_s=0.0),
        history_path=str(tmp_path / "h.jsonl"))
    with pytest.warns(UserWarning, match="quarantined"):
        summary = worker.drain()

    assert summary["succeeded"] == 2 and summary["failed"] == 1
    counts = spool.counts()
    assert counts["done"] == 2 and counts["failed"] == 1
    counters = REGISTRY.snapshot()["counters"]
    # the two surviving beams still went out as ONE batched dispatch
    assert counters["scheduler.batched_dispatches"] == 1
    assert counters["scheduler.batch_fill"] == 2
    assert counters["scheduler.quarantined"] == 1

    failed = spool.jobs("failed")[0]
    assert failed.input == bad
    assert failed.failures[0]["classification"] == QUARANTINE
    assert "truncated filterbank" in failed.failures[0]["error"]
    assert failed.attempts == 1  # quarantine is immediate

    store = CandidateStore(os.path.join(spool.root, "candidates.jsonl"))
    assert set(store.sources()) == set(good)
    for rec in spool.jobs("done"):
        assert rec.summary["candidates"] >= 1
        # per-beam checkpoints were consumed on success, not leaked
        assert not os.path.exists(
            os.path.join(spool.work_dir(rec.job_id), "search.ckpt"))


# --------------------------------------------------------------------------
# load observatory (ISSUE 12): dual-clock failure history and the
# drain ledger's latency percentiles
# --------------------------------------------------------------------------


def test_failure_history_carries_monotonic_clock(tmp_path):
    """Every failure entry records BOTH clocks: ``utc`` (wall, for
    humans and cross-host merging) and ``t_mono`` (monotonic, so
    per-process failure spacing survives NTP steps)."""
    spool = JobSpool(str(tmp_path / "jobs"))
    spool.submit(_write_fil(tmp_path / "obs.fil"), FAST)

    def _explode(job):
        raise ConfigError("injected config failure")

    worker = SurveyWorker(spool, single_device=True, prefetch=False,
                          run_job_fn=_explode, sleeper=lambda s: None,
                          history_path=str(tmp_path / "h.jsonl"))
    with pytest.warns(UserWarning, match="quarantined"):
        worker.drain()

    entry = spool.jobs("failed")[0].failures[-1]
    assert entry["classification"] == QUARANTINE
    assert "utc" in entry
    assert isinstance(entry["t_mono"], float) and entry["t_mono"] > 0


def test_drain_ledger_records_sojourn_percentiles(tmp_path):
    """A drain's serve ledger record carries the end-to-end latency of
    the jobs it finished (sojourn/queue-wait p95 from the per-job
    timelines) plus the timeline's own bookkeeping cost."""
    from peasoup_tpu.obs.history import load_history

    spool = JobSpool(str(tmp_path / "jobs"))
    for i in range(3):
        spool.submit(_write_fil(tmp_path / f"obs{i}.fil", seed=i), FAST)
    history = str(tmp_path / "h.jsonl")
    worker = SurveyWorker(spool, single_device=True, prefetch=False,
                          run_job_fn=lambda job: {"candidates": 0},
                          sleeper=lambda s: None, history_path=history)
    summary = worker.drain()
    assert summary["succeeded"] == 3

    (rec,) = load_history(history, kinds=["serve"])
    m = rec["metrics"]
    for key in ("sojourn_p50", "sojourn_p95",
                "queue_wait_p50", "queue_wait_p95"):
        assert isinstance(m[key], float), key
    # sojourn includes the queue wait, so the p95s must be ordered
    assert m["sojourn_p95"] >= m["queue_wait_p95"] >= 0.0
    assert m["sojourn_p95"] > 0.0
    # the worker self-accounts its OWN marks (claim + done per job;
    # the submit mark belongs to the submitter's ledger)
    assert m["timeline_marks"] >= 6
    assert m["timeline_overhead_s"] >= 0.0


# --------------------------------------------------------------------------
# admission control + fair share (ISSUE 15)
# --------------------------------------------------------------------------


def test_admission_knee_raises_typed_error(tmp_path):
    """Past the backlog knee the spool refuses submits with a typed
    AdmissionError (reason "backlog") instead of letting the queue
    grow without bound."""
    from peasoup_tpu.errors import AdmissionError
    from peasoup_tpu.serve import AdmissionPolicy

    spool = JobSpool(str(tmp_path / "jobs"),
                     admission=AdmissionPolicy(max_pending=3))
    for i in range(3):
        spool.submit(f"/tmp/{i}.fil")
    with pytest.warns(UserWarning, match="backlog"):
        with pytest.raises(AdmissionError) as err:
            spool.submit("/tmp/over.fil")
    assert err.value.reason == "backlog"
    assert spool.counts()["pending"] == 3  # the refused job never landed
    counters = REGISTRY.snapshot()["counters"]
    assert counters["scheduler.admission_deferred"] == 1
    # draining below the knee re-opens admission
    spool.claim("w")
    spool.submit("/tmp/ok-again.fil")


def test_admission_token_bucket_injectable_clock(tmp_path):
    """Per-tenant rate limit: burst tokens spend down, refill follows
    the injected clock, and the typed error carries retry_after_s."""
    from peasoup_tpu.errors import AdmissionError
    from peasoup_tpu.serve import AdmissionPolicy, TenantPolicy

    t = {"now": 1000.0}
    spool = JobSpool(
        str(tmp_path / "jobs"),
        admission=AdmissionPolicy(tenants={
            "flood": TenantPolicy(rate_per_s=1.0, burst=2.0),
        }),
        clock=lambda: t["now"])
    spool.submit("/tmp/a.fil", tenant="flood")
    spool.submit("/tmp/b.fil", tenant="flood")
    with pytest.warns(UserWarning, match="token bucket"):
        with pytest.raises(AdmissionError) as err:
            spool.submit("/tmp/c.fil", tenant="flood")
    assert err.value.reason == "rate_limit"
    assert err.value.tenant == "flood"
    assert err.value.retry_after_s > 0.0
    # an unlimited tenant is never rate-limited
    spool.submit("/tmp/science.fil", tenant="science")
    # the bucket refills with the clock
    t["now"] += 1.5
    spool.submit("/tmp/c.fil", tenant="flood")
    assert REGISTRY.snapshot()["counters"][
        "scheduler.admission_rejected"] == 1


def test_legacy_job_record_defaults_tenant(tmp_path):
    """Records written before the tenant field loads as the default
    tenant (rolling upgrade: old pending jobs stay claimable)."""
    from peasoup_tpu.serve import DEFAULT_TENANT

    spool = JobSpool(str(tmp_path / "jobs"))
    rec = spool.submit("/tmp/x.fil")
    path = os.path.join(spool.root, "pending", f"{rec.job_id}.json")
    obj = json.load(open(path))
    del obj["tenant"]
    obj["some_future_field"] = "ignored"  # additions tolerated too
    with open(path, "w") as f:
        json.dump(obj, f)
    (loaded,) = spool.pending_jobs()
    assert loaded.tenant == DEFAULT_TENANT
    assert spool.tenant_counts() == {
        DEFAULT_TENANT: {"pending": 1, "running": 0, "done": 0,
                         "failed": 0}}


def test_fair_share_interleave_and_single_tenant_fifo(tmp_path):
    """Weight-2 science gets two claims per flood claim within the
    tier; a spool with one tenant keeps the historical FIFO order."""
    from peasoup_tpu.serve import AdmissionPolicy, TenantPolicy

    spool = JobSpool(
        str(tmp_path / "jobs"),
        admission=AdmissionPolicy(tenants={
            "science": TenantPolicy(weight=2.0),
            "flood": TenantPolicy(weight=1.0),
        }))
    sci = [spool.submit(f"/tmp/s{i}.fil", tenant="science")
           for i in range(6)]
    fld = [spool.submit(f"/tmp/f{i}.fil", tenant="flood")
           for i in range(3)]
    names = {r.job_id: r.tenant for r in sci + fld}
    # science ranks (i+1)/2, flood (i+1)/1; rank ties go to the
    # earlier submit (science here) -> two science claims per flood
    order = [names[r.job_id] for r in spool.claim_order()]
    assert order == ["science", "science", "flood",
                     "science", "science", "flood",
                     "science", "science", "flood"]

    solo = JobSpool(str(tmp_path / "solo"))
    subs = [solo.submit(f"/tmp/{i}.fil") for i in range(4)]
    assert [r.job_id for r in solo.claim_order()] \
        == [r.job_id for r in subs]


# --------------------------------------------------------------------------
# backoff jitter + abandoned-timeout accounting (ISSUE 15)
# --------------------------------------------------------------------------


def test_backoff_jitter_spreads_deterministically():
    """Jittered delays stay inside [d*(1-j), d*(1+j)] (capped at
    max_s), actually SPREAD (not constant), and reproduce exactly from
    an injected rng.  jitter=0 keeps the exact legacy sequence."""
    import random as _random

    exact = BackoffPolicy(base_s=1.0, factor=2.0, max_s=60.0)
    assert [exact.delay_for(k) for k in (1, 2, 3)] == [1.0, 2.0, 4.0]

    def delays(seed):
        pol = BackoffPolicy(base_s=1.0, factor=2.0, max_s=5.0,
                            jitter=0.25, rng=_random.Random(seed))
        return [pol.delay_for(k) for k in range(1, 6)]

    a, b = delays(7), delays(7)
    assert a == b  # reproducible from the seed
    assert delays(8) != a  # different seed decorrelates
    for k, d in enumerate(a, start=1):
        nominal = min(1.0 * 2.0 ** (k - 1), 5.0)
        assert nominal * 0.75 <= d <= min(nominal * 1.25, 5.0)
    assert len(set(a)) > 1  # the jitter actually moves the delays


def test_run_with_timeout_accounts_abandoned_thread():
    """A timed-out attempt's thread cannot be cancelled, but it must
    be visible: counter + typed event + a live count that prunes once
    the zombie finishes."""
    from peasoup_tpu.serve import abandoned_count
    from peasoup_tpu.serve.retry import (
        JobTimeoutError, run_with_timeout,
    )

    release = threading.Event()
    with pytest.warns(UserWarning, match="timed out"):
        with pytest.raises(JobTimeoutError, match="budget"):
            run_with_timeout(lambda: release.wait(30.0), 0.05,
                             label="job zombie-1")
    assert abandoned_count() >= 1
    counters = REGISTRY.snapshot()["counters"]
    assert counters["scheduler.timeout_abandoned"] == 1
    assert counters["events.job_timeout_abandoned"] == 1

    release.set()  # let the zombie finish; the count must prune
    deadline = 50
    while abandoned_count() > 0 and deadline > 0:
        threading.Event().wait(0.02)
        deadline -= 1
    assert abandoned_count() == 0


# --------------------------------------------------------------------------
# spool crash-consistency (ISSUE 15: fsync + torn records)
# --------------------------------------------------------------------------


def test_spool_durability_flag_and_env_escape_hatch(tmp_path,
                                                    monkeypatch):
    spool = JobSpool(str(tmp_path / "a"), durable=True)
    assert spool.durable is True
    rec = spool.submit("/tmp/x.fil")  # exercises the fsync path
    assert spool.claim("w").job_id == rec.job_id
    monkeypatch.setenv("PEASOUP_SPOOL_FSYNC", "0")
    assert JobSpool(str(tmp_path / "b")).durable is False
    monkeypatch.delenv("PEASOUP_SPOOL_FSYNC")
    assert JobSpool(str(tmp_path / "c")).durable is True


def test_torn_tmp_write_never_corrupts_state(tmp_path):
    """A crash between the record tmp-write and its rename must leave
    the spool consistent: the half-written tmp is invisible to every
    lister and claimer, and the original record (when the crash was a
    rewrite) survives untouched."""
    spool = JobSpool(str(tmp_path / "jobs"), durable=True)
    rec = spool.submit("/tmp/x.fil", {"dm_end": 25.0})

    # crash mid-rewrite: a torn tmp next to the real record
    pend = os.path.join(spool.root, "pending")
    with open(os.path.join(pend, f"{rec.job_id}.json.tmp999"),
              "w") as f:
        f.write('{"v": 1, "job_id": "torn-half-wri')
    # crash mid-submit: a torn tmp for a record that never landed
    with open(os.path.join(pend, "neverborn.json.tmp42"), "w") as f:
        f.write("{")

    (only,) = spool.pending_jobs()
    assert only.job_id == rec.job_id
    assert only.overrides == {"dm_end": 25.0}
    assert spool.counts()["pending"] == 1  # tmps are not records
    job = spool.claim("w")
    assert job.job_id == rec.job_id and job.attempts == 1
    state, loaded = spool.get(rec.job_id)
    assert state == "running" and loaded.overrides == {"dm_end": 25.0}
