"""Fleet control-plane tests: fake membership, lease lifecycle +
reaper recovery, simulated multi-host drains with no double-runs,
sharded-store merge equality, and the fleet verbs — all WITHOUT real
multihost (FleetMembership.fake simulates N hosts in one process;
see CONTRIBUTING.md)."""

import json
import os
import threading
import time

import pytest

from peasoup_tpu.errors import ConfigError
from peasoup_tpu.obs.metrics import REGISTRY
from peasoup_tpu.serve import (
    LEASE_EXPIRED,
    BackoffPolicy,
    CandidateStore,
    FleetMembership,
    FleetWorker,
    JobSpool,
    LeaseHeartbeat,
    ShardedCandidateStore,
    fleet_report,
    write_fleet_report,
)


@pytest.fixture(autouse=True)
def _fresh_registry():
    REGISTRY.reset()
    yield
    REGISTRY.reset()


def _fleet_worker(spool, host_id, host_count, run_job_fn,
                  tmp_path, **kw):
    """A FleetWorker with fake membership and an injected job body —
    the whole claim/lease/retry machinery stays live."""
    kw.setdefault("backoff", BackoffPolicy(max_attempts=2, base_s=0.0))
    kw.setdefault("history_path", str(tmp_path / "h.jsonl"))
    kw.setdefault("sleeper", lambda s: None)
    return FleetWorker(
        spool, FleetMembership.fake(host_id, host_count),
        run_job_fn=run_job_fn, **kw)


# --------------------------------------------------------------------------
# membership
# --------------------------------------------------------------------------

def test_fake_membership_identity_and_validation():
    m = FleetMembership.fake(2, 4)
    assert (m.host_id, m.host_count, m.label) == (2, 4, "host-2")
    assert FleetMembership.fake(0, 1, "pod a/slice:3").label == \
        "pod_a_slice_3"  # sanitised for file names
    for bad in ((3, 3), (-1, 2), (0, 0)):
        with pytest.raises(ConfigError):
            FleetMembership.fake(*bad)


def test_detect_single_process_is_one_host_fleet():
    """Off-pod (no coordinator env) detect() must come back as the
    1-host fleet — every fleet verb works on a laptop."""
    m = FleetMembership.detect(label="solo")
    assert (m.host_id, m.host_count, m.label) == (0, 1, "solo")


# --------------------------------------------------------------------------
# leases: claim -> heartbeat -> done/reap
# --------------------------------------------------------------------------

def test_claim_drops_lease_and_done_clears_it(tmp_path):
    spool = JobSpool(str(tmp_path / "jobs"))
    spool.submit("/tmp/x.fil")
    job = spool.claim("w0", host="host-0")
    lease = spool.lease_info(job.job_id)
    assert lease["host"] == "host-0" and lease["attempt"] == 1
    assert job.host == "host-0"
    spool.mark_done(job)
    assert spool.lease_info(job.job_id) is None


def test_reaper_recovers_dead_host_job(tmp_path):
    """An expired lease sends the job back to pending with the attempt
    history intact and a LEASE_EXPIRED entry naming the dead host."""
    spool = JobSpool(str(tmp_path / "jobs"))
    rec = spool.submit("/tmp/x.fil", {"dm_end": 30.0})
    job = spool.claim("w-dead", host="host-9")
    assert job.attempts == 1
    # fresh lease: nothing to reap at the default TTL
    assert spool.reap_expired(120.0) == []
    # the host dies; its lease goes stale past the TTL
    with pytest.warns(UserWarning, match="reaped"):
        reaped = spool.reap_expired(120.0, now=time.time() + 121.0)
    assert [r.job_id for r in reaped] == [rec.job_id]
    assert spool.counts()["pending"] == 1
    assert spool.lease_info(rec.job_id) is None
    again = spool.claim("w-live", host="host-0")
    assert again.job_id == rec.job_id
    assert again.attempts == 2  # history intact, like requeue
    assert again.overrides == {"dm_end": 30.0}
    exp = again.failures[-1]
    assert exp["classification"] == LEASE_EXPIRED
    assert "host-9" in exp["error"]
    counters = REGISTRY.snapshot()["counters"]
    assert counters["scheduler.lease_reaped"] == 1


def test_heartbeat_keeps_lease_fresh(tmp_path):
    """A LeaseHeartbeat thread refreshes the lease faster than the
    TTL, so a slow-but-alive job is never reaped."""
    spool = JobSpool(str(tmp_path / "jobs"))
    spool.submit("/tmp/slow.fil")
    job = spool.claim("w0", host="host-0")
    first = spool.lease_info(job.job_id)["utc"]
    with LeaseHeartbeat(spool, job, interval_s=0.05) as hb:
        deadline = time.time() + 5.0
        while hb.beats < 3 and time.time() < deadline:
            hb._stop.wait(0.01)  # avoid bare sleep (PSL008)
        assert hb.beats >= 3
        # a reaper sweeping NOW sees a fresh beat, not the claim time
        assert spool.reap_expired(1.0, now=first + 0.9) == []
        assert spool.lease_info(job.job_id)["utc"] >= first
    # heartbeat stopped: the same TTL eventually expires the lease
    last = spool.lease_info(job.job_id)["utc"]
    with pytest.warns(UserWarning, match="reaped"):
        assert len(spool.reap_expired(1.0, now=last + 1.1)) == 1


# --------------------------------------------------------------------------
# simulated multi-host drains
# --------------------------------------------------------------------------

def test_three_host_drain_no_double_runs(tmp_path):
    """Three fake hosts drain one spool concurrently: every job runs
    exactly once, each host ingests into its own shard, and the
    merged store sees everything."""
    spool = JobSpool(str(tmp_path / "jobs"))
    submitted = {spool.submit(f"/tmp/{i}.fil").job_id
                 for i in range(18)}
    runs: list[tuple[str, str]] = []
    lock = threading.Lock()
    barrier = threading.Barrier(3)

    def _make_runner(label):
        def _run(job):
            with lock:
                runs.append((label, job.job_id))
            return {"candidates": 0}
        return _run

    workers = [
        _fleet_worker(spool, i, 3, _make_runner(f"host-{i}"), tmp_path,
                      lease_ttl_s=60.0)
        for i in range(3)
    ]
    summaries = [None] * 3

    def _drain(i):
        barrier.wait()
        summaries[i] = workers[i].drain()

    ts = [threading.Thread(target=_drain, args=(i,)) for i in range(3)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()

    ran = [job_id for _, job_id in runs]
    assert sorted(ran) == sorted(submitted)  # all jobs ran
    assert len(ran) == len(set(ran))  # ...exactly once
    assert spool.counts()["done"] == 18
    assert sum(s["claimed"] for s in summaries) == 18
    assert {s["host"] for s in summaries} == {"host-0", "host-1",
                                              "host-2"}
    # no lease survives a drained queue
    assert not os.listdir(os.path.join(spool.root, "leases"))
    # every host wrote its status snapshot for `status --fleet`
    report = fleet_report(spool)
    assert set(report["hosts"]) == {"host-0", "host-1", "host-2"}
    assert report["totals"]["claimed"] == 18
    assert report["totals"]["succeeded"] == 18
    assert report["queue"]["done"] == 18


def test_fleet_drain_adopts_dead_hosts_job(tmp_path):
    """Host A claims a job and dies (no heartbeat); host B's drain
    reaps the stale lease up front and runs the job itself."""
    spool = JobSpool(str(tmp_path / "jobs"))
    rec = spool.submit("/tmp/orphan.fil")
    dead = spool.claim("host-0:pid1", host="host-0")
    assert dead.job_id == rec.job_id
    # age the lease past a tiny TTL: rewrite it with an old beat
    path = spool._lease_path(rec.job_id)
    lease = json.load(open(path))
    lease["utc"] = time.time() - 60.0
    json.dump(lease, open(path, "w"))

    ran = []
    worker = _fleet_worker(spool, 1, 2, lambda job: ran.append(
        job.job_id) or {"candidates": 0}, tmp_path, lease_ttl_s=5.0)
    with pytest.warns(UserWarning, match="reaped"):
        summary = worker.drain()
    assert ran == [rec.job_id]
    assert summary["succeeded"] == 1
    done = spool.jobs("done")[0]
    assert done.attempts == 2
    assert done.failures[-1]["classification"] == LEASE_EXPIRED


# --------------------------------------------------------------------------
# sharded store
# --------------------------------------------------------------------------

class _C:
    def __init__(self, freq, snr, dm=10.0):
        self.freq = freq
        self.snr = snr
        self.dm = dm
        self.acc = 0.0
        self.folded_snr = 0.0
        self.nh = 0


def _populate(shard_a, shard_b):
    # the same 10 Hz signal seen from two hosts' observations, plus
    # per-host noise candidates
    shard_a.ingest("j1", "beamA.fil", [_C(10.0, 12.0), _C(3.3, 9.0)])
    shard_b.ingest("j2", "beamB.fil", [_C(10.0004, 11.0)])
    shard_b.ingest("j3", "beamC.fil", [_C(77.7, 9.5)])


def test_sharded_merge_equals_single_store(tmp_path):
    """query/coincident_groups over the shard merge must equal a
    single store holding the same records."""
    root = str(tmp_path / "fleet")
    _populate(ShardedCandidateStore(root, "host-0"),
              ShardedCandidateStore(root, "host-1"))
    single = CandidateStore(str(tmp_path / "single.jsonl"))
    _populate(single, single)

    merged = ShardedCandidateStore(root)  # pure reader: no label
    assert merged.count() == single.count() == 4
    assert merged.sources() == single.sources()
    assert merged.shard_counts() == {"store-host-0.jsonl": 2,
                                     "store-host-1.jsonl": 2}

    q_m = merged.query(10.0, freq_tol=1e-3)
    q_s = single.query(10.0, freq_tol=1e-3)
    strip = lambda recs: sorted(
        (r["source"], r["freq"], r["snr"]) for r in recs)
    assert strip(q_m) == strip(q_s)

    g_m = merged.coincident_groups(freq_tol=1e-3, min_sources=2)
    g_s = single.coincident_groups(freq_tol=1e-3, min_sources=2)
    assert [strip(g) for g in g_m] == [strip(g) for g in g_s]
    assert len(g_m) == 1
    assert {r["source"] for r in g_m[0]} == {"beamA.fil", "beamB.fil"}


def test_sharded_store_tolerates_torn_shard_tail(tmp_path):
    """One host killed mid-append tears only its own shard's tail;
    the merge loses that one line, nothing else."""
    root = str(tmp_path / "fleet")
    a = ShardedCandidateStore(root, "host-0")
    b = ShardedCandidateStore(root, "host-1")
    _populate(a, b)
    with open(b.path, "a") as f:
        f.write('{"v": 1, "freq": 5.5, "job_id": "to')  # SIGKILL here
    merged = ShardedCandidateStore(root)
    assert merged.count() == 4
    assert len(merged.coincident_groups(freq_tol=1e-3)) == 1


def test_sharded_store_merges_legacy_single_file(tmp_path):
    """A spool upgraded to fleet mode keeps its pre-fleet
    candidates.jsonl visible in every merged query."""
    root = str(tmp_path / "fleet")
    os.makedirs(root)
    legacy = CandidateStore(os.path.join(root, "candidates.jsonl"))
    legacy.ingest("j0", "beamZ.fil", [_C(10.0002, 8.0)])
    ShardedCandidateStore(root, "host-0").ingest(
        "j1", "beamA.fil", [_C(10.0, 12.0)])
    merged = ShardedCandidateStore(root)
    assert merged.count() == 2
    groups = merged.coincident_groups(freq_tol=1e-3, min_sources=2)
    assert {r["source"] for r in groups[0]} == {"beamA.fil",
                                                "beamZ.fil"}


# --------------------------------------------------------------------------
# fleet verbs
# --------------------------------------------------------------------------

def test_fleet_worker_verb_and_status_fleet(tmp_path, capsys):
    """The CLI path end-to-end on fake membership: fleet-worker drains
    with its label in the summary line, status --fleet renders the
    per-host table and writes fleet_report.json."""
    from peasoup_tpu.serve.cli import main

    spool_dir = str(tmp_path / "jobs")
    spool = JobSpool(spool_dir)
    spool.submit("/tmp/a.fil")
    # the real pipeline would quarantine /tmp/a.fil; inject instead
    worker = _fleet_worker(spool, 0, 2,
                           lambda job: {"candidates": 0}, tmp_path)
    assert worker.drain()["succeeded"] == 1

    rc = main(["--spool", spool_dir, "status", "--fleet"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "host-0" in out and "TOTAL" in out
    assert "store-host-0.jsonl" in out
    report = json.load(open(os.path.join(spool_dir,
                                         "fleet_report.json")))
    assert report["totals"]["hosts"] == 1
    assert report["totals"]["claimed"] == 1
    assert report["hosts"]["host-0"]["summary"]["succeeded"] == 1
    assert "jobs_per_hour" in report["hosts"]["host-0"]["summary"]

    # membership flags must come as a pair
    with pytest.raises(ConfigError, match="together"):
        main(["--spool", spool_dir, "fleet-worker", "--host-id", "0",
              "--drain"])


def test_coincidence_verb_over_shards(tmp_path, capsys):
    from peasoup_tpu.serve.cli import main

    spool_dir = str(tmp_path / "jobs")
    JobSpool(spool_dir)  # creates the root
    _populate(ShardedCandidateStore(spool_dir, "host-0"),
              ShardedCandidateStore(spool_dir, "host-1"))
    out_json = str(tmp_path / "groups.json")
    rc = main(["--spool", spool_dir, "coincidence",
               "--freq-tol", "1e-3", "--json", out_json])
    out = capsys.readouterr().out
    assert rc == 0
    assert "1 coincident group(s)" in out
    doc = json.load(open(out_json))
    assert len(doc["groups"]) == 1
    assert {r["source"] for r in doc["groups"][0]} == {"beamA.fil",
                                                       "beamB.fil"}


def test_requeue_expired_verb(tmp_path, capsys):
    from peasoup_tpu.serve.cli import main

    spool_dir = str(tmp_path / "jobs")
    spool = JobSpool(spool_dir)
    rec = spool.submit("/tmp/x.fil")
    spool.claim("w-dead", host="host-3")
    # healthy fleet: zero reaped is rc 0, not an error
    rc = main(["--spool", spool_dir, "requeue", "--expired"])
    assert rc == 0
    assert "0 lease-expired" in capsys.readouterr().out
    # stale lease: --lease-ttl 0 reaps it immediately
    with pytest.warns(UserWarning, match="reaped"):
        rc = main(["--spool", spool_dir, "requeue", "--expired",
                   "--lease-ttl", "0"])
    out = capsys.readouterr().out
    assert rc == 0
    assert f"reaped {rec.job_id}" in out
    assert spool.counts()["pending"] == 1


def test_write_fleet_report_is_atomic_and_stale_leases_flagged(
        tmp_path):
    spool = JobSpool(str(tmp_path / "jobs"))
    spool.submit("/tmp/x.fil")
    spool.claim("w0", host="host-0")
    # a lease older than the TTL shows up as stale in the report
    path = spool._lease_path(spool.jobs("running")[0].job_id)
    lease = json.load(open(path))
    lease["utc"] = time.time() - 999.0
    json.dump(lease, open(path, "w"))
    report = fleet_report(spool, lease_ttl_s=10.0)
    assert report["leases"] == {"running": 1, "stale": 1,
                                "ttl_s": 10.0}
    out = write_fleet_report(spool, report)
    assert os.path.basename(out) == "fleet_report.json"
    assert json.load(open(out))["leases"]["stale"] == 1
    assert not [p for p in os.listdir(spool.root)
                if p.startswith("fleet_report.json.tmp")]
