"""End-to-end acceptance test: candidate parity with the reference's
shipped example output on tutorial.fil.

Golden values from /root/reference/example_output/overview.xml (search:
dm 0-250 tol 1.10, acc -5..5 with the 2014 3-trial grid, 4 harmonic
sums, min_snr 9, npdmp 10).  SNRs agree to ~0.1% (we keep dedispersed
trials in float32 where the reference quantises to uint8); association
counts are exact.
"""

import numpy as np
import pytest

from peasoup_tpu.io import read_filterbank
from peasoup_tpu.search.pipeline import PulsarSearch
from peasoup_tpu.search.plan import SearchConfig


@pytest.fixture(scope="module")
def result(tutorial_fil):
    fil = read_filterbank(tutorial_fil)
    cfg = SearchConfig(
        dm_start=0.0, dm_end=250.0, acc_start=-5.0, acc_end=5.0,
        acc_pulse_width=64000.0,  # reproduces the golden [0,-5,5] accel grid
        nharmonics=4, npdmp=10, limit=1000,
    )
    return PulsarSearch(fil, cfg).run()


# (period, dm, nh, snr, nassoc) of the golden candidates that are
# uniquely identified by period+dm
GOLDEN = [
    (0.249939903165736, 19.7624092102051, 4, 86.9626083374023, 155),
    (0.25003302533532, 23.0475635528564, 3, 73.9640884399414, 164),
    (0.249846850335071, 168.867050170898, 3, 53.5081558227539, 38),
    (0.499693700670141, 9.90831470489502, 4, 52.5980796813965, 47),
    (0.249660952380952, 239.375610351562, 2, 42.9121894836426, 176),
    (0.124993235238934, 36.2595176696777, 4, 48.5954704284668, 104),
    (0.083302959285005, 23.0475635528564, 1, 38.9516983032227, 176),
]

GOLDEN_FOLDED = {
    # period -> (opt_period, folded_snr)
    0.249939903165736: (0.249986439943314, 71.4956665039062),
    0.25003302533532: (0.249986439943314, 72.5594100952148),
    0.249846850335071: (0.250009626150131, 50.7492218017578),
    0.499693700670141: (0.500065743923187, 9.89522075653076),
}


def _find(cands, period, dm):
    for c in cands:
        if abs(1.0 / c.freq - period) / period < 1e-6 and abs(c.dm - dm) < 0.01:
            return c
    return None


def test_dm_trial_count(result):
    assert len(result.dm_list) == 59


def test_accel_grid_matches_golden(result):
    np.testing.assert_allclose(result.acc_list_dm0, [0.0, -5.0, 5.0])


def test_candidate_parity(result):
    cands = result.candidates
    assert len(cands) >= 10
    for period, dm, nh, snr, nassoc in GOLDEN:
        c = _find(cands, period, dm)
        assert c is not None, f"missing golden candidate P={period} dm={dm}"
        assert c.nh == nh
        assert c.snr == pytest.approx(snr, rel=2e-3)
        assert c.count_assoc() == nassoc


def test_top_candidate_is_fundamental_family(result):
    top = result.candidates[0]
    assert 1.0 / top.freq == pytest.approx(0.24994, rel=1e-3)
    assert top.snr == pytest.approx(86.9626, rel=2e-3)


def test_folded_snr_parity(result):
    for period, (opt_period, fsnr) in GOLDEN_FOLDED.items():
        c = _find(result.candidates, period, dm=-1) or next(
            (c for c in result.candidates
             if abs(1.0 / c.freq - period) / period < 1e-6), None
        )
        assert c is not None
        assert c.opt_period == pytest.approx(opt_period, rel=1e-4)
        # measured agreement with f32 trials is <= 0.5% on every golden
        # candidate (r5 session) — the historical 3% bar blamed the
        # reference's uint8 trial quantisation, but the f32 pipeline
        # matches its folded S/N to well under 1%, so 1% it is
        assert c.folded_snr == pytest.approx(fsnr, rel=0.01)


def test_scoring_flags(result):
    top = result.candidates[0]
    assert top.is_physical and top.is_adjacent
    assert top.ddm_count_ratio == pytest.approx(1.0)
    assert top.ddm_snr_ratio == pytest.approx(1.0)
