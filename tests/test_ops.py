"""Unit tests of the kernel library against plain-NumPy golden models.

The NumPy models below transcribe the formulas documented in SURVEY.md
section 2.1 (reference: src/kernels.cu) and act as the spec.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from peasoup_tpu.ops import (
    deredden,
    extract_above_threshold,
    form_interpolated,
    form_power,
    harmonic_sums,
    identify_unique_peaks,
    linear_stretch,
    mean_rms_std,
    median_scrunch5,
    normalise,
    resample,
    resample2,
    running_median,
    spectrum_search_bounds,
    zap_birdies,
)

rng = np.random.default_rng(42)


# ---------------- spectrum forming ----------------

def test_form_power():
    x = (rng.normal(size=128) + 1j * rng.normal(size=128)).astype(np.complex64)
    out = np.asarray(form_power(jnp.asarray(x)))
    np.testing.assert_allclose(out, np.abs(x), rtol=1e-6)


def test_form_interpolated():
    x = (rng.normal(size=128) + 1j * rng.normal(size=128)).astype(np.complex64)
    xl = np.concatenate([[0.0 + 0j], x[:-1]])
    expected = np.sqrt(np.maximum(np.abs(x) ** 2, 0.5 * np.abs(x - xl) ** 2))
    out = np.asarray(form_interpolated(jnp.asarray(x)))
    np.testing.assert_allclose(out, expected, rtol=1e-5)


# ---------------- rednoise ----------------

def test_median_scrunch5():
    x = rng.normal(size=103).astype(np.float32)
    out = np.asarray(median_scrunch5(jnp.asarray(x)))
    expected = np.median(x[:100].reshape(20, 5), axis=1)
    np.testing.assert_allclose(out, expected)


def test_median_scrunch5_short():
    for n, expected in [
        (1, lambda x: x[0]),
        (2, lambda x: 0.5 * (x[0] + x[1])),
        (3, lambda x: np.median(x)),
        (4, lambda x: np.mean(np.sort(x)[1:3])),
    ]:
        x = rng.normal(size=n).astype(np.float32)
        out = np.asarray(median_scrunch5(jnp.asarray(x)))
        assert out.shape == (1,)
        np.testing.assert_allclose(out[0], expected(x), rtol=1e-6)


def test_linear_stretch():
    x = np.array([0.0, 1.0, 4.0, 9.0], dtype=np.float32)
    out = np.asarray(linear_stretch(jnp.asarray(x), 7))
    step = np.float32(3) / np.float32(6)
    xi = np.arange(7, dtype=np.float32) * step
    j = xi.astype(np.int32)
    frac = xi - j
    jn = np.minimum(j + 1, 3)
    expected = np.where(frac > 1e-5, x[j] + frac * (x[jn] - x[j]), x[j])
    np.testing.assert_allclose(out, expected, rtol=1e-6)


def test_running_median_flat_spectrum():
    # On a constant spectrum every median level is the constant, so the
    # spliced curve is flat too.
    size = 4097
    powers = jnp.full((size,), 7.0, dtype=jnp.float32)
    med = np.asarray(running_median(powers, bin_width=0.01))
    np.testing.assert_allclose(med, 7.0, rtol=1e-6)


def test_deredden_zeroes_low_bins():
    f = (rng.normal(size=64) + 1j * rng.normal(size=64)).astype(np.complex64)
    med = np.full(64, 2.0, dtype=np.float32)
    out = np.asarray(deredden(jnp.asarray(f), jnp.asarray(med)))
    assert np.all(out[:5] == 0)
    np.testing.assert_allclose(out[5:], f[5:] / 2.0, rtol=1e-6)


# ---------------- zapping ----------------

def test_zap_birdies():
    size = 1024
    bw = 0.5  # Hz per bin
    f = np.ones(size, dtype=np.complex64) * (3 + 4j)
    birdies = jnp.asarray(np.array([50.0, 400.0], dtype=np.float32))
    widths = jnp.asarray(np.array([1.0, 0.6], dtype=np.float32))
    out = np.asarray(zap_birdies(jnp.asarray(f), birdies, widths, bw))
    for freq, width in [(50.0, 1.0), (400.0, 0.6)]:
        low = int(np.floor((freq - width) / bw))
        high = int(np.ceil((freq + width) / bw))
        high = min(high, size - 1)
        assert np.all(out[low:high] == 1.0 + 0j)
        assert out[low - 1] == 3 + 4j
        assert out[high] == 3 + 4j


def test_zap_birdies_clamping():
    size = 64
    f = np.zeros(size, dtype=np.complex64)
    # birdie below DC and birdie beyond nyquist
    birdies = jnp.asarray(np.array([0.0, 1e6], dtype=np.float32))
    widths = jnp.asarray(np.array([2.0, 1.0], dtype=np.float32))
    out = np.asarray(zap_birdies(jnp.asarray(f), birdies, widths, 1.0))
    assert np.all(out[0:2] == 1.0)
    assert np.all(out[3:] == 0.0)


# ---------------- stats ----------------

def test_stats_and_normalise():
    x = rng.normal(loc=3.0, scale=2.0, size=10000).astype(np.float32)
    mean, rms, std = mean_rms_std(jnp.asarray(x))
    assert float(mean) == pytest.approx(x.mean(), rel=1e-4)
    assert float(rms) == pytest.approx(np.sqrt((x.astype(np.float64) ** 2).mean()), rel=1e-4)
    assert float(std) == pytest.approx(x.std(), rel=1e-3)
    normed = np.asarray(normalise(jnp.asarray(x), mean, std))
    assert normed.mean() == pytest.approx(0.0, abs=1e-3)
    assert normed.std() == pytest.approx(1.0, rel=1e-3)


# ---------------- resampling ----------------

def _resample_numpy(tim, accel, tsamp, kernel):
    n = len(tim)
    af = accel * tsamp / (2 * 299792458.0)
    i = np.arange(n, dtype=np.float64)
    if kernel == 1:
        half = n / 2.0
        idx = np.rint(i + af * ((i - half) ** 2 - half * half)).astype(np.int64)
    else:
        idx = np.rint(i + i * af * (i - float(n))).astype(np.int64)
    return tim[np.clip(idx, 0, n - 1)]


@pytest.mark.parametrize("accel", [125.5, -125.5, 0.0])
def test_resample_kernels_match_numpy(accel):
    n = 1 << 16
    tim = (np.arange(n) % 451).astype(np.float32)  # ramp from resampling_test.cpp
    tsamp = 0.000064
    out1 = np.asarray(resample(jnp.asarray(tim), accel, tsamp))
    out2 = np.asarray(resample2(jnp.asarray(tim), accel, tsamp))
    np.testing.assert_array_equal(out1, _resample_numpy(tim, accel, tsamp, 1))
    np.testing.assert_array_equal(out2, _resample_numpy(tim, accel, tsamp, 2))


@pytest.mark.parametrize("accel", [125.5, -125.5, 5.0, 0.0])
def test_resample2_select_path_matches_gather(accel):
    from peasoup_tpu.ops.resample import resample2_max_shift

    n = 1 << 16
    tim = rng.normal(size=n).astype(np.float32)
    tsamp = 0.000064
    ms = resample2_max_shift(accel, tsamp, n)
    gathered = np.asarray(resample2(jnp.asarray(tim), accel, tsamp))
    if ms <= 64:
        selected = np.asarray(
            resample2(jnp.asarray(tim), accel, tsamp, ms)
        )
        np.testing.assert_array_equal(selected, gathered)


@pytest.mark.parametrize("accel", [500.0, -500.0, 137.3, -0.31, 12345.0])
@pytest.mark.parametrize("block", [1024, 4096])
def test_resample2_table_paths_exact(accel, block):
    """Blockwise (device bisection) and table (host-exact) paths must be
    bit-identical with the plain-gather reference formula."""
    from peasoup_tpu.ops.resample import (
        resample2_blockwise,
        resample2_from_tables,
        resample2_max_shift,
        resample2_tables,
    )

    n = 1 << 16
    tsamp = 0.00016
    tim = rng.normal(size=n).astype(np.float32)
    ms = max(resample2_max_shift(accel, tsamp, n), 1)
    ref = _resample_numpy(tim, accel, tsamp, 2)
    got_bw = np.asarray(
        resample2_blockwise(jnp.asarray(tim), accel, tsamp, ms, block=block)
    )
    np.testing.assert_array_equal(got_bw, ref)
    d0, pos, step = resample2_tables([accel], tsamp, n, ms, block=block)
    got_tab = np.asarray(resample2_from_tables(
        jnp.asarray(tim), jnp.asarray(d0[0]), jnp.asarray(pos[0]),
        jnp.asarray(step[0]), ms, block=block,
    ))
    np.testing.assert_array_equal(got_tab, ref)


def test_resample2_unique_tables_grid():
    """NaN-padded accel grids dedupe correctly and round-trip."""
    from peasoup_tpu.ops.resample import (
        resample2_from_tables,
        resample2_max_shift,
        resample2_unique_tables,
    )

    n, tsamp = 1 << 14, 0.000064
    grid = np.array([[0.0, 50.0, np.nan], [0.0, -50.0, 50.0]], np.float32)
    ms = max(resample2_max_shift(50.0, tsamp, n), 1)
    d0, pos, step, uidx = resample2_unique_tables(grid, tsamp, n, ms,
                                                  block=1024)
    assert d0.shape[0] == 3  # unique: -50, 0, 50
    tim = rng.normal(size=n).astype(np.float32)
    for (r, c), acc in np.ndenumerate(grid):
        if np.isnan(acc):
            continue
        u = int(uidx[r, c])
        got = np.asarray(resample2_from_tables(
            jnp.asarray(tim), jnp.asarray(d0[u]), jnp.asarray(pos[u]),
            jnp.asarray(step[u]), ms, block=1024,
        ))
        np.testing.assert_array_equal(
            got, _resample_numpy(tim, float(acc), tsamp, 2))


@pytest.mark.parametrize("accel", [500.0, -217.0])
def test_resample2_index_exactness_2e23(accel):
    """SURVEY hard-part: read-index exactness at 2^23 samples (f64
    index ramp reaches ~2^45, `src/kernels.cu:335-362`).  The x64 CPU
    backend computes true IEEE f64, so equality with the NumPy golden
    is exact; the table path must agree bit-for-bit too."""
    from peasoup_tpu.ops.resample import (
        resample2_from_tables,
        resample2_max_shift,
        resample2_tables,
    )

    n = 1 << 23
    tsamp = 0.000064
    # values = bin index mod p: any index error changes the output value
    tim = (np.arange(n) % 8191).astype(np.float32)
    ms = resample2_max_shift(accel, tsamp, n)
    assert ms > 64  # genuinely in the high-accel regime
    ref = _resample_numpy(tim, accel, tsamp, 2)
    got = np.asarray(resample2(jnp.asarray(tim), accel, tsamp))
    np.testing.assert_array_equal(got, ref)
    block = 16384
    d0, pos, step = resample2_tables([accel], tsamp, n, ms, block=block)
    got_tab = np.asarray(resample2_from_tables(
        jnp.asarray(tim), jnp.asarray(d0[0]), jnp.asarray(pos[0]),
        jnp.asarray(step[0]), ms, block=block,
    ))
    np.testing.assert_array_equal(got_tab, ref)


def test_resample1_kernel_exactness_2e23():
    """Kernel-I (folding path) exactness at 2^23 samples."""
    n = 1 << 23
    tsamp, accel = 0.000064, 350.0
    tim = (np.arange(n) % 8191).astype(np.float32)
    ref = _resample_numpy(tim, accel, tsamp, 1)
    got = np.asarray(resample(jnp.asarray(tim), accel, tsamp))
    np.testing.assert_array_equal(got, ref)


@pytest.mark.parametrize("accel", [350.0, -125.5, 17.2])
def test_resample1_tables_exact(accel):
    """Kernel-I host tables match the kernel-I golden bit-for-bit
    (distinct fp evaluation order from kernel II, so its boundaries
    must be bisected on its own expression)."""
    from peasoup_tpu.ops.resample import (
        resample1_tables,
        resample2_from_tables,
        resample2_max_shift,
    )

    n, tsamp, block = 1 << 16, 0.00016, 1024
    tim = rng.normal(size=n).astype(np.float32)
    ms = max(resample2_max_shift(accel, tsamp, n), 1)
    d0, pos, step = resample1_tables([accel], tsamp, n, ms, block=block)
    got = np.asarray(resample2_from_tables(
        jnp.asarray(tim), jnp.asarray(d0[0]), jnp.asarray(pos[0]),
        jnp.asarray(step[0]), ms, block=block,
    ))
    np.testing.assert_array_equal(got, _resample_numpy(tim, accel, tsamp, 1))


def test_fold_phase_bins_exactness_2e23():
    """Fold phase-bin assignment at 2^23 samples matches the NumPy f64
    golden (`src/kernels.cu:597-651` computes phase in f64)."""
    from peasoup_tpu.ops.fold import phase_bins

    n = 1 << 23
    tsamp, period, nbins = 0.000064, 0.0042573, 64
    got = np.asarray(phase_bins(n, period, tsamp, nbins))
    j = np.arange(n, dtype=np.float64)
    tbp = np.float64(tsamp) / np.float64(period)  # reference precomputes
    frac, _ = np.modf(j * tbp)
    want = np.floor(frac * nbins).astype(np.int64)
    np.testing.assert_array_equal(got, want)


def test_normalise_spectrum_legacy():
    from peasoup_tpu.ops import normalise_spectrum

    x = rng.normal(loc=5.0, scale=2.0, size=4096).astype(np.float32)
    out = np.asarray(normalise_spectrum(jnp.asarray(x)))
    _, _, std = mean_rms_std(jnp.asarray(x))
    np.testing.assert_allclose(out, x / float(std), rtol=1e-6)
    out2 = np.asarray(normalise_spectrum(jnp.asarray(x), sigma=2.0))
    np.testing.assert_allclose(out2, x / 2.0, rtol=1e-6)


def test_transpose_op():
    from peasoup_tpu.ops import transpose

    x = rng.normal(size=(17, 33)).astype(np.float32)
    np.testing.assert_array_equal(np.asarray(transpose(jnp.asarray(x))), x.T)


def test_resample_zero_accel_is_identity():
    n = 4096
    tim = rng.normal(size=n).astype(np.float32)
    np.testing.assert_array_equal(np.asarray(resample2(jnp.asarray(tim), 0.0, 1e-4)), tim)


def test_resample_kernels_shift_symmetry():
    # Kernel I is symmetric about the midpoint: zero shift at i=0 and i=n
    # happens only for kernel II; kernel I pins i=0 and i=n.
    n = 1 << 14
    tim = np.arange(n, dtype=np.float32)
    out = np.asarray(resample2(jnp.asarray(tim), 250.0, 1e-3))
    assert out[0] == 0.0
    assert abs(float(out[-1]) - (n - 1)) <= 1.0


# ---------------- harmonic summing ----------------

def _harmonic_sums_numpy(spec, nharms):
    size = len(spec)
    i = np.arange(size, dtype=np.int64)
    out = []
    val = spec.astype(np.float64).copy()
    scales = [2, 4, 8, 16, 32]
    for k in range(1, nharms + 1):
        for m in range(1, 2 ** k, 2):
            idx = ((i * m + 2 ** (k - 1)) >> k).clip(0, size - 1)
            val = val + spec[idx]
        out.append((val / np.sqrt(scales[k - 1])).astype(np.float32))
    return out


def test_harmonic_sums_match_numpy():
    spec = rng.normal(size=4096).astype(np.float32) ** 2
    ours = harmonic_sums(jnp.asarray(spec), 4)
    golden = _harmonic_sums_numpy(spec, 4)
    assert len(ours) == 4
    for a, b in zip(ours, golden):
        np.testing.assert_allclose(np.asarray(a), b, rtol=1e-5)


def test_harmonic_sums_impulse_train():
    # Impulse train with fundamental every 32 bins: the 2^k-harmonic sum
    # at the fundamental bin grows as 2^k / sqrt(2^k) = sqrt(2^k).
    size = 8192
    spec = np.zeros(size, dtype=np.float32)
    spec[32::32] = 1.0
    sums = harmonic_sums(jnp.asarray(spec), 4)
    # bin of the 16th harmonic index: idx*m/16 lands on multiples of 32
    val = float(np.asarray(sums[3])[512 * 16 // 16])  # fundamental at bin 512
    # all 16 stretched reads at bin 512 hit multiples of 32 -> 1 each
    assert val == pytest.approx((1 + 16) / 4.0, abs=1e-5) or val > 1.0


def test_harmonic_sums_lane_aligned_path_exact():
    """The large-spectrum (stride-slice + one-hot einsum) path must be
    bit-identical with the gather formulation across the dispatch
    threshold."""
    from peasoup_tpu.ops.harmonics import (
        _GATHER_MAX_SIZE,
        _harmonic_sums_gather,
    )

    n = _GATHER_MAX_SIZE + 1017  # odd, just past the dispatch threshold
    spec = rng.normal(size=n).astype(np.float32)
    big = harmonic_sums(jnp.asarray(spec), 4)
    small = _harmonic_sums_gather(jnp.asarray(spec), 4)
    for k, (a, b) in enumerate(zip(big, small), 1):
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b),
            err_msg=f"level {k} mismatch between einsum and gather paths")


def test_harmonic_index_integer_equals_float():
    # (i*m + 2^(k-1)) >> k  ==  int(i * m/2^k + 0.5) for the float64 math
    # the reference uses.
    i = np.arange(1 << 20, dtype=np.int64)
    for k in range(1, 6):
        for m in range(1, 2 ** k, 2):
            int_idx = (i * m + (1 << (k - 1))) >> k
            float_idx = (i.astype(np.float64) * (m / 2 ** k) + 0.5).astype(np.int64)
            np.testing.assert_array_equal(int_idx, float_idx)


# ---------------- peak finding ----------------

def test_extract_above_threshold():
    spec = np.zeros(1000, dtype=np.float32)
    spec[[5, 100, 101, 500, 900]] = [10, 12, 11, 20, 15]
    idxs, snrs, count = extract_above_threshold(
        jnp.asarray(spec), 9.0, start_idx=10, stop_idx=950, capacity=8
    )
    idxs, snrs = np.asarray(idxs), np.asarray(snrs)
    assert int(count) == 4  # bin 5 below start, bin 900 within stop
    np.testing.assert_array_equal(idxs[:4], [100, 101, 500, 900])
    np.testing.assert_allclose(snrs[:4], [12, 11, 20, 15])
    assert np.all(idxs[4:] == -1)


@pytest.mark.parametrize("thresh", [0.5, 2.0, 9.0])
def test_extract_two_stage_matches_reference(thresh):
    """The large-spectrum two-stage extraction must return exactly the
    first `capacity` qualifying indices, like the single top_k path —
    including when hits are spread one-per-row (the case the row
    selection argument has to cover)."""
    from peasoup_tpu.ops.peaks import _TWO_STAGE_MIN_SIZE

    n = _TWO_STAGE_MIN_SIZE + 4097
    cap = 64
    spec = np.abs(rng.normal(size=n)).astype(np.float32)
    # sprinkle guaranteed hits one per 600 bins (one per row-ish)
    spec[::600] += 12.0
    start, stop = 100, n - 50
    idxs, snrs, count = extract_above_threshold(
        jnp.asarray(spec), thresh, start, stop, cap)
    i = np.arange(n)
    m = (i >= start) & (i < stop) & (spec > thresh)
    want = i[m][:cap]
    got = np.asarray(idxs)[np.asarray(idxs) >= 0]
    np.testing.assert_array_equal(np.sort(got), np.sort(want))
    assert int(count) == int(m.sum())
    np.testing.assert_allclose(
        np.sort(np.asarray(snrs)[np.asarray(idxs) >= 0]),
        np.sort(spec[want]), rtol=1e-6)


def test_identify_unique_peaks():
    # Two clusters within min_gap, one isolated peak.
    idxs = np.array([100, 105, 120, 200, 500])
    snrs = np.array([10.0, 15.0, 11.0, 9.5, 30.0])
    pidx, psnr = identify_unique_peaks(idxs, snrs, min_gap=30)
    # walk: 100 group absorbs 105 (better, lastidx->105), 120 (within 30,
    # worse), 200 is within 30 of ... 200-105=95 >= 30 -> new group
    np.testing.assert_array_equal(pidx, [105, 200, 500])
    np.testing.assert_allclose(psnr, [15.0, 9.5, 30.0])


def test_spectrum_search_bounds():
    size, bin_width = 65537, 1.0 / 41.94304
    start0, stop0, f0 = spectrum_search_bounds(size, bin_width, 0, 0.1, 1100.0)
    assert stop0 == min(size, int(1100.0 / bin_width))
    assert start0 == int(2.0 * (size - 1) * (0.1 / (bin_width * size)))
    assert f0 == pytest.approx(bin_width * size / size, rel=1e-5)
    start2, stop2, f2 = spectrum_search_bounds(size, bin_width, 2, 0.1, 1100.0)
    assert start2 == pytest.approx(4 * start0, abs=4)
    assert stop2 == size  # max_bin exceeds size
    assert f2 == pytest.approx(f0 / 4)


def test_median_scrunch5_lane_path_exact():
    """The lane-aligned scrunch (matmul selection + sorting network)
    must match the reshape+sort formulation bit-for-bit across the
    dispatch threshold."""
    from peasoup_tpu.ops.rednoise import (
        _LANE_SCRUNCH_MIN,
        _median_scrunch5_lanes,
    )

    for n in (_LANE_SCRUNCH_MIN + 1013, _LANE_SCRUNCH_MIN + 640 * 7):
        x = rng.normal(size=n).astype(np.float32)
        want = np.sort(
            x[: (n // 5) * 5].reshape(-1, 5), axis=1)[:, 2]
        got = np.asarray(_median_scrunch5_lanes(jnp.asarray(x)))
        np.testing.assert_array_equal(got, want)


def test_linear_stretch_lane_path_exact():
    """Windowed-select stretch must be bit-identical with the gather
    formulation above the dispatch threshold (identical f32 index
    expressions)."""
    from peasoup_tpu.ops.rednoise import (
        _LANE_STRETCH_MIN,
        _linear_stretch_lanes,
    )

    out_count = _LANE_STRETCH_MIN + 12345
    for ratio in (5, 25, 125):
        x = (rng.normal(size=out_count // ratio) ** 2).astype(np.float32)
        in_count = x.shape[0]
        step = np.float32(in_count - 1) / np.float32(out_count - 1)
        xi = np.arange(out_count, dtype=np.float32) * step
        j = xi.astype(np.int32)
        frac = xi - j.astype(np.float32)
        jn = np.minimum(j + 1, in_count - 1)
        want = np.where(frac > 1e-5, x[j] + frac * (x[jn] - x[j]), x[j])
        got = np.asarray(_linear_stretch_lanes(jnp.asarray(x), out_count))
        np.testing.assert_array_equal(got, want, err_msg=f"ratio {ratio}")


@pytest.mark.parametrize("seed", range(4))
def test_extract_top_peaks_matches_reference_semantics(seed):
    """Fuzz the value-ordered extractor against the index-ordered one:
    identical true counts; identical hit SETS when count <= capacity;
    when clipped, the kept subset is the largest-SNR one (any subset is
    acceptable — clipped rows are re-searched — but the contract is
    pinned here)."""
    from peasoup_tpu.ops.peaks import extract_above_threshold, extract_top_peaks

    rng = np.random.default_rng(seed)
    n = 4096 + 17
    spec = np.abs(rng.normal(size=n)).astype(np.float32) * 3
    for cap, thresh, start, stop in [(64, 2.0, 5, n), (8, 4.0, 0, n - 9),
                                     (256, 9.0, 100, 3000)]:
        ia, sa, ca = extract_above_threshold(
            jnp.asarray(spec), thresh, start, stop, cap)
        iv, sv, cv = extract_top_peaks(
            jnp.asarray(spec), thresh, start, stop, cap)
        ia, sa, iv, sv = map(np.asarray, (ia, sa, iv, sv))
        assert int(ca) == int(cv)
        hits_v = iv[iv >= 0]
        vals_v = sv[iv >= 0]
        # value-ordered: descending SNR prefix, correctly PAIRED with
        # its indices (catches index-reconstruction mispairing)
        assert np.all(np.diff(vals_v) <= 0)
        np.testing.assert_allclose(vals_v, spec[hits_v], rtol=1e-6)
        i = np.arange(n)
        m = (i >= start) & (i < min(stop, n)) & (spec > thresh)
        if int(ca) <= cap:
            np.testing.assert_array_equal(np.sort(hits_v), i[m])
            # and the same hit SET as the index-ordered extractor
            np.testing.assert_array_equal(
                np.sort(hits_v), np.sort(ia[ia >= 0]))
            np.testing.assert_allclose(
                np.sort(vals_v), np.sort(sa[ia >= 0]), rtol=1e-6)
        else:
            # largest-SNR subset of size cap
            want = np.sort(spec[m])[-cap:]
            np.testing.assert_allclose(np.sort(vals_v), want, rtol=1e-6)


def test_extract_top_peaks_two_stage_branch():
    """Production-scale sizes take the two-stage row-max top_k branch
    (engaged when stop > max(2^17, cap*512)); its row-selection /
    index-reconstruction math must reproduce the ground truth exactly,
    including correct (index, value) pairing."""
    from peasoup_tpu.ops.peaks import extract_top_peaks

    n = (1 << 17) + 4097
    cap = 128  # cap*512 = 2^16 < n and n > 2^17 -> two-stage
    spec = np.abs(rng.normal(size=n)).astype(np.float32)
    spec[::1201] += 11.0  # ~112 sparse hits (< cap) incl. both ends
    start, stop = 77, n - 33
    iv, sv, cv = extract_top_peaks(jnp.asarray(spec), 9.0, start, stop, cap)
    iv, sv = np.asarray(iv), np.asarray(sv)
    i = np.arange(n)
    m = (i >= start) & (i < stop) & (spec > 9.0)
    hits_v = iv[iv >= 0]
    assert int(cv) == int(m.sum())
    assert int(m.sum()) <= cap
    np.testing.assert_array_equal(np.sort(hits_v), i[m])
    np.testing.assert_allclose(sv[iv >= 0], spec[hits_v], rtol=1e-6)
    assert np.all(np.diff(sv[iv >= 0]) <= 0)


def _extract_ref(spec, thresh, start, stop, cap):
    """numpy ground truth for extract_above_threshold's contract: the
    cap smallest qualifying indices ascending, -1 padding, true count."""
    n = len(spec)
    i = np.arange(n)
    m = (i >= start) & (i < min(stop, n)) & (spec > thresh)
    hits = i[m]
    k = min(cap, len(hits))
    out_i = np.full(cap, -1, np.int64)
    out_s = np.zeros(cap, np.float32)
    out_i[:k] = hits[:k]
    out_s[:k] = spec[hits[:k]]
    return out_i, out_s, int(m.sum())


def _edge_shape_cases():
    """ISSUE-6 satellite: stop_idx at _TWO_STAGE_MIN_SIZE +- 1 (and
    exactly), count > capacity, zero survivors, start_idx > 0, and
    non-multiple-of-row-width stops."""
    from peasoup_tpu.ops.peaks import _TWO_STAGE_MIN_SIZE as M

    # (name, n, start, stop, cap, thresh, hit_stride)
    return [
        ("two_stage_min_minus_1", M + 64, 0, M - 1, 64, 9.0, 997),
        ("two_stage_min_exact", M + 64, 0, M, 64, 9.0, 997),
        ("two_stage_min_plus_1", M + 64, 0, M + 1, 64, 9.0, 997),
        ("count_over_capacity", 40000, 0, 39999, 16, 9.0, 101),
        ("zero_survivors", 30000, 10, 29999, 32, 1e9, 0),
        ("start_idx_positive", 50000, 12345, 49999, 64, 9.0, 509),
        ("non_multiple_row_width", 36909 + 7, 100, 36909, 320, 9.0, 601),
        ("stop_past_size", 20000, 0, 25000, 64, 9.0, 701),
        ("cap_exceeds_stop", 600, 0, 500, 2048, 9.0, 7),
    ]


@pytest.mark.parametrize(
    "case", _edge_shape_cases(), ids=lambda c: c[0])
def test_extract_above_threshold_edge_shapes_xla_methods(case):
    """Bit-exact agreement of the sort and two-stage lowerings with
    the numpy reference over the ISSUE-6 edge shapes (the pallas leg
    runs in test_extract_above_threshold_edge_shapes_pallas — it
    needs the interpret-mode fixture)."""
    _name, n, start, stop, cap, thresh, stride = case
    spec = np.abs(rng.normal(size=n)).astype(np.float32)
    if stride:
        spec[::stride] += 11.0
    want = _extract_ref(spec, thresh, start, stop, cap)
    for method in ("sort", "two_stage"):
        gi, gs, gc = extract_above_threshold(
            jnp.asarray(spec), thresh, start, stop, cap, method=method)
        np.testing.assert_array_equal(np.asarray(gi), want[0],
                                      err_msg=method)
        np.testing.assert_array_equal(np.asarray(gs), want[1],
                                      err_msg=method)
        assert int(gc) == want[2], method
    # narrow row widths must not change the result either
    for rw in (64, 128, 256):
        gi, gs, gc = extract_above_threshold(
            jnp.asarray(spec), thresh, start, stop, cap,
            method="two_stage", row_width=rw)
        np.testing.assert_array_equal(np.asarray(gi), want[0],
                                      err_msg=f"row_width={rw}")
        assert int(gc) == want[2]


@pytest.mark.parametrize(
    "case", _edge_shape_cases(), ids=lambda c: c[0])
def test_extract_above_threshold_edge_shapes_pallas(
        case, peaks_pallas_interpret):
    """The threshold-compaction kernel (real kernel, interpret mode)
    must agree bit-for-bit with the numpy reference — and therefore
    with the other two lowerings — on every edge shape."""
    _name, n, start, stop, cap, thresh, stride = case
    spec = np.abs(rng.normal(size=n)).astype(np.float32)
    if stride:
        spec[::stride] += 11.0
    want = _extract_ref(spec, thresh, start, stop, cap)
    gi, gs, gc = extract_above_threshold(
        jnp.asarray(spec), thresh, start, stop, cap, method="pallas")
    np.testing.assert_array_equal(np.asarray(gi), want[0])
    np.testing.assert_array_equal(np.asarray(gs), want[1])
    assert int(gc) == want[2]


def test_extract_pallas_kernel_vmap(peaks_pallas_interpret):
    """The hot paths vmap the extraction over accel batches: the
    kernel's running-offset scratch must reset per spectrum (the
    batch axis lands as a leading grid axis)."""
    import jax

    from peasoup_tpu.ops.peaks_pallas import (
        extract_above_threshold_pallas,
    )

    B, n, cap = 6, 9000, 64
    specs = np.abs(rng.normal(size=(B, n))).astype(np.float32) * 3
    specs[:, ::611] += 9.5
    f = jax.jit(jax.vmap(
        lambda s: extract_above_threshold_pallas(
            s, 2.0, 10, n - 1, cap, block=1024, interpret=True)
    ))
    bi, bs, bc = f(jnp.asarray(specs))
    for b in range(B):
        wi, ws, wc = _extract_ref(specs[b], 2.0, 10, n - 1, cap)
        np.testing.assert_array_equal(np.asarray(bi[b]), wi)
        np.testing.assert_array_equal(np.asarray(bs[b]), ws)
        assert int(bc[b]) == wc


def test_scatter_chunk_for_vmem_bound():
    """The one-hot scatter tile must stay within the VMEM ceiling at
    any lane-padded capacity (the whole-buffer compaction reuse pushes
    cap_p to 8192)."""
    from peasoup_tpu.ops.peaks_pallas import (
        _SCATTER_TILE_BYTES,
        _scatter_chunk_for,
    )

    assert _scatter_chunk_for(128) == 512
    assert _scatter_chunk_for(2048) == 512
    assert _scatter_chunk_for(4096) == 256
    assert _scatter_chunk_for(8192) == 128
    for cap_p in (128, 1024, 8192, 65536):
        chunk = _scatter_chunk_for(cap_p)
        assert chunk >= 128 and chunk & (chunk - 1) == 0
        assert (cap_p * chunk * 4 <= _SCATTER_TILE_BYTES
                or chunk == 128)


def _compact_ref(flat_idx, flat_val, ck):
    """Numpy model of the cumsum+scatter compaction: first ``ck``
    valid slots in flat order, -1/0.0 padded, plus the TRUE count."""
    keep = np.flatnonzero(flat_idx >= 0)
    sel_i = np.full(ck, -1, flat_idx.dtype)
    sel_v = np.zeros(ck, np.float32)
    took = keep[:ck]
    sel_i[: took.size] = flat_idx[took]
    sel_v[: took.size] = flat_val[took]
    return sel_i, sel_v, keep.size


def test_compact_valid_slots_pallas_matches_reference(
        peaks_pallas_interpret):
    from peasoup_tpu.ops.peaks_pallas import compact_valid_slots_pallas

    rng_ = np.random.default_rng(11)
    for n, ck, p_valid in ((512, 64, 0.3), (2048, 128, 0.02),
                           (1024, 64, 0.5),   # overflow: nvalid > ck
                           (640, 128, 0.0),   # all invalid
                           (256, 256, 1.0)):  # exactly full
        idx = np.where(rng_.random(n) < p_valid,
                       rng_.integers(0, 1 << 22, n),
                       -1).astype(np.int32)
        val = rng_.normal(size=n).astype(np.float32)
        gi, gv, gc = compact_valid_slots_pallas(
            jnp.asarray(idx), jnp.asarray(val), ck, interpret=True)
        wi, wv, wc = _compact_ref(idx, val, ck)
        np.testing.assert_array_equal(np.asarray(gi), wi)
        np.testing.assert_array_equal(
            np.asarray(gv).view(np.uint32), wv.view(np.uint32))
        assert int(gc) == wc


def test_compact_peaks_pallas_bit_equivalence(peaks_pallas_interpret):
    """The whole-buffer compaction's pallas lowering must produce a
    bit-identical packed buffer to the cumsum+scatter path — including
    the overflow (nvalid > compact_k), all-invalid and exactly-full
    cases, and adversarially scattered validity patterns (the XLA
    contract only relies on flat slot order, not prefix packing)."""
    from peasoup_tpu.parallel.mesh import _compact_peaks

    rng_ = np.random.default_rng(5)
    cases = [
        (4, 3, 16, 64, 0.2),    # sparse, ck > nvalid
        (6, 2, 32, 128, 0.9),   # overflow: nvalid > ck
        (3, 2, 64, 384, 1.0),   # exactly full buffers
        (5, 4, 8, 96, 0.0),     # no survivors at all
    ]
    for ntr, nl, cap, ck, p_valid in cases:
        idxs = np.where(
            rng_.random((ntr, nl, cap)) < p_valid,
            rng_.integers(0, 1 << 22, (ntr, nl, cap)),
            -1).astype(np.int32)
        snrs = np.where(idxs >= 0,
                        rng_.normal(size=idxs.shape) * 30,
                        0.0).astype(np.float32)
        counts = (idxs >= 0).sum(axis=2).astype(np.int32)
        args = (jnp.asarray(idxs), jnp.asarray(snrs),
                jnp.asarray(counts), ck)
        want = np.asarray(_compact_peaks(*args, "xla"))
        got = np.asarray(_compact_peaks(*args, "pallas"))
        assert got.dtype == want.dtype == np.float32
        np.testing.assert_array_equal(
            got.view(np.uint32), want.view(np.uint32),
            err_msg=f"case ntr={ntr} nl={nl} cap={cap} ck={ck} "
                    f"p={p_valid}")


def test_extract_top_peaks_method_parity():
    """All lowerings of the value-ordered extractor deliver the SAME
    hit set/pairing when count <= capacity (slot order differs by
    contract: SNR-descending for sort/two_stage, index-ascending for
    pallas's XLA fallback — consumers sort either way)."""
    from peasoup_tpu.ops.peaks import extract_top_peaks

    n = 20000
    spec = np.abs(rng.normal(size=n)).astype(np.float32)
    spec[::997] += 10.0
    i = np.arange(n)
    m = (i >= 50) & (i < n - 13) & (spec > 9.0)
    assert m.sum() <= 64
    for method in ("sort", "two_stage"):
        iv, sv, cv = extract_top_peaks(
            jnp.asarray(spec), 9.0, 50, n - 13, 64, method=method)
        iv, sv = np.asarray(iv), np.asarray(sv)
        assert int(cv) == int(m.sum()), method
        np.testing.assert_array_equal(np.sort(iv[iv >= 0]), i[m],
                                      err_msg=method)
        np.testing.assert_allclose(sv[iv >= 0], spec[iv[iv >= 0]],
                                   rtol=1e-6, err_msg=method)


def test_extract_method_validation():
    from peasoup_tpu.ops.peaks import extract_top_peaks

    spec = jnp.zeros(100, jnp.float32)
    with pytest.raises(ValueError, match="peaks method"):
        extract_top_peaks(spec, 1.0, 0, 100, 8, method="bogus")


def test_harmonic_sums_pallas_exact_interpret(pallas_interpret):
    """The fused Pallas TPU kernel (interpret mode on CPU) must be
    bit-identical with the gather formulation, plain and under vmap
    (the hot paths vmap harmonic_sums over accel batches)."""
    import jax

    from peasoup_tpu.ops.harmonics import (
        _harmonic_sums_gather,
        _pallas_hsum_fn,
    )

    n = (1 << 19) + 1017
    spec = rng.normal(size=n).astype(np.float32)
    fn = _pallas_hsum_fn(4, interpret=True)
    ours = fn(jnp.asarray(spec))
    golden = _harmonic_sums_gather(jnp.asarray(spec), 4)
    for k, (a, b) in enumerate(zip(ours, golden), 1):
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b),
            err_msg=f"level {k}: pallas vs gather mismatch")

    specs = rng.normal(size=(3, n)).astype(np.float32)
    batched = jax.vmap(fn)(jnp.asarray(specs))
    for k in range(4):
        want = np.stack([
            np.asarray(_harmonic_sums_gather(jnp.asarray(s), 4)[k])
            for s in specs
        ])
        np.testing.assert_array_equal(
            np.asarray(batched[k]), want,
            err_msg=f"level {k+1}: vmapped pallas mismatch")


def test_harmonic_sums_pallas_nharms5_exact_interpret(pallas_interpret):
    """nharms=5 on the kernel path (level 5's 16 odd stretches share
    the level-4 accumulator, 32 residue classes per stretch) must be
    bit-identical with the gather formulation."""
    from peasoup_tpu.ops.harmonics import (
        _harmonic_sums_gather,
        _pallas_hsum_fn,
    )

    n = (1 << 19) + 1017
    spec = rng.normal(size=n).astype(np.float32)
    ours = _pallas_hsum_fn(5, interpret=True)(jnp.asarray(spec))
    golden = _harmonic_sums_gather(jnp.asarray(spec), 5)
    assert len(ours) == 5
    for k, (a, b) in enumerate(zip(ours, golden), 1):
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b),
            err_msg=f"level {k}: pallas vs gather mismatch")


def test_fold_onehot_matches_scatter():
    """The TPU one-hot matmul fold must match the segment_sum
    formulation to f32 summation-order tolerance (counts exactly),
    across non-round periods and sizes."""
    from peasoup_tpu.ops.fold import _fold_onehot, phase_bins

    n, nbins, nints = 1 << 15, 64, 16
    nper = n // nints
    tsamp = 6.4e-5
    for period in (0.12503, 0.0042573, 1.7):
        tim = rng.normal(size=n).astype(np.float32)
        binidx = np.asarray(phase_bins(n, period, tsamp, nbins))
        got = np.asarray(_fold_onehot(
            jnp.asarray(tim), jnp.asarray(binidx), nbins, nints))
        # sequential-order numpy golden of the scatter formulation
        # (built inline so the comparison is backend-independent: on a
        # TPU runner fold_time_series_core itself takes the one-hot
        # branch)
        flat = (np.arange(n) // nper) * nbins + binidx
        sums = np.zeros(nints * nbins, np.float32)
        np.add.at(sums, flat, tim)
        counts = np.bincount(flat, minlength=nints * nbins)
        want = (sums / (counts + 1.0)).reshape(nints, nbins)
        np.testing.assert_allclose(got, want, atol=2e-5, rtol=1e-5)
