"""Aux subsystem tests: checkpoint/resume, progress bar, tracing."""

import io
import os

import numpy as np
import pytest

from peasoup_tpu.io import read_filterbank
from peasoup_tpu.search.checkpoint import SearchCheckpoint, search_key
from peasoup_tpu.search.pipeline import PulsarSearch
from peasoup_tpu.search.plan import SearchConfig
from peasoup_tpu.utils import ProgressBar, trace_range


CFG = dict(
    dm_start=0.0, dm_end=30.0, acc_start=-5.0, acc_end=5.0,
    acc_pulse_width=64000.0, npdmp=0, limit=20,
)


def _result_sig(result):
    return [
        (c.freq, c.snr, c.dm, c.acc, c.count_assoc())
        for c in result.candidates
    ]


def test_checkpoint_resume_host_loop(tutorial_fil, tmp_path):
    fil = read_filterbank(tutorial_fil)
    ck = str(tmp_path / "search.ckpt")

    baseline = PulsarSearch(fil, SearchConfig(**CFG)).run()

    # simulate a crash: checkpoint every trial, abort after 4 trials
    cfg = SearchConfig(checkpoint_file=ck, checkpoint_interval=1, **CFG)
    search = PulsarSearch(fil, cfg)
    ckpt, done = search._make_checkpoint()
    assert done == {}
    trials = search.dedisperse()
    for ii in range(4):
        done[ii] = search.search_dm_trial(trials, ii)
        ckpt.maybe_save(done)
    assert os.path.exists(ck)

    # resume: a fresh run must produce identical output and clean up
    calls = []
    resumed = PulsarSearch(fil, cfg)
    orig = resumed.search_dm_trial
    resumed.search_dm_trial = lambda t, ii: calls.append(ii) or orig(t, ii)
    result = resumed.run()
    assert 0 not in calls and 3 not in calls  # checkpointed trials skipped
    assert 4 in calls
    assert _result_sig(result) == _result_sig(baseline)
    assert not os.path.exists(ck)  # removed after success


def test_checkpoint_resume_mesh(tutorial_fil, tmp_path):
    from peasoup_tpu.parallel.mesh import MeshPulsarSearch

    fil = read_filterbank(tutorial_fil)
    ck = str(tmp_path / "mesh.ckpt")
    cfg = SearchConfig(checkpoint_file=ck, **CFG)

    first = MeshPulsarSearch(fil, cfg).run()
    assert not os.path.exists(ck)  # success -> removed

    # craft a complete checkpoint, then resume without searching
    search = MeshPulsarSearch(fil, cfg)
    full = search.run()  # populates nothing persistent; rerun to get cands
    ckpt, _ = search._make_checkpoint()
    done = {}
    for ii in range(len(search.dm_list)):
        done[ii] = [
            c for c in full.candidates if c.dm_idx == ii
        ]
        for c in done[ii]:
            c.assoc = []
    ckpt.save(done)
    resumed = MeshPulsarSearch(fil, cfg).run()
    assert resumed.timers["searching"] == 0.0
    assert len(resumed.candidates) > 0


def test_checkpoint_key_invalidation(tutorial_fil, tmp_path):
    fil = read_filterbank(tutorial_fil)
    ck = str(tmp_path / "k.ckpt")
    cfg_a = SearchConfig(checkpoint_file=ck, **CFG)
    key_a = search_key("", fil, cfg_a)
    c = SearchCheckpoint(ck, key_a)
    c.save({0: []})
    assert c.load() == {0: []}
    # different search params -> different key -> stale checkpoint
    # ignored, LOUDLY (a silent reject would look like a fresh run)
    cfg_b = SearchConfig(checkpoint_file=ck, **{**CFG, "dm_end": 60.0})
    key_b = search_key("", fil, cfg_b)
    assert key_a != key_b
    with pytest.warns(UserWarning, match="different search"):
        assert SearchCheckpoint(ck, key_b).load() is None
    # a corrupt (non-JSON) file is rejected with a warning, not an error
    with open(ck, "w") as f:
        f.write("\x00garbage")
    with pytest.warns(UserWarning, match="unreadable"):
        assert SearchCheckpoint(ck, key_a).load() is None
    # presentation-only knobs do not invalidate
    cfg_c = SearchConfig(checkpoint_file=ck, verbose=True, **CFG)
    assert search_key("", fil, cfg_c) == key_a
    # result-affecting TPU knobs DO invalidate
    cfg_d = SearchConfig(checkpoint_file=ck, compact_capacity=999, **CFG)
    assert search_key("", fil, cfg_d) != key_a


def _synth_fil(path, tsamp=0.000256, nsamps=1024, nchans=8, seed=0):
    from peasoup_tpu.io.sigproc import (
        Filterbank, SigprocHeader, write_filterbank,
    )

    rng = np.random.default_rng(seed)
    data = rng.integers(0, 32, size=(nsamps, nchans), dtype=np.uint8)
    hdr = SigprocHeader(nbits=8, nchans=nchans, tsamp=tsamp,
                        fch1=1510.0, foff=-10.0, nsamples=nsamps)
    write_filterbank(str(path), Filterbank(header=hdr, data=data))
    return str(path)


def test_checkpoint_key_survives_relocation(tmp_path):
    """Migration (v4 keys): the key is the observation's header/
    geometry fingerprint, NOT its path — relocating a spool directory
    (or the file itself) must not invalidate a resume."""
    import shutil

    dir_a = tmp_path / "spool_a"
    dir_b = tmp_path / "relocated"
    dir_a.mkdir()
    dir_b.mkdir()
    path_a = _synth_fil(dir_a / "obs.fil")
    path_b = str(dir_b / "renamed.fil")
    shutil.copy(path_a, path_b)

    fil_a = read_filterbank(path_a)
    fil_b = read_filterbank(path_b)
    cfg_a = SearchConfig(infilename=path_a, **CFG)
    cfg_b = SearchConfig(infilename=path_b, **CFG)
    key_a = search_key(path_a, fil_a, cfg_a)
    key_b = search_key(path_b, fil_b, cfg_b)
    # the path (argument AND config field) is advisory only
    assert key_a == key_b

    # a checkpoint written against the original location loads after
    # the move — the actual resume migration
    ck = str(tmp_path / "moved.ckpt")
    c = SearchCheckpoint(ck, key_a, advisory={"input": path_a})
    c.save({0: []})
    assert SearchCheckpoint(ck, key_b).load() == {0: []}

    # content changes still invalidate: a different observation (other
    # header geometry) must not alias the key
    path_c = _synth_fil(dir_a / "other.fil", tsamp=0.000512)
    fil_c = read_filterbank(path_c)
    assert search_key(path_c, fil_c,
                      SearchConfig(infilename=path_c, **CFG)) != key_a


def test_checkpoint_header_carries_advisory_path(tmp_path):
    """The input path is kept on the checkpoint header line for
    operators, but never compared on load."""
    import json

    path = _synth_fil(tmp_path / "obs.fil")
    fil = read_filterbank(path)
    key = search_key(path, fil, SearchConfig(**CFG))
    ck = str(tmp_path / "adv.ckpt")
    c = SearchCheckpoint(ck, key, advisory={"input": path})
    c.save({0: []})
    with open(ck) as f:
        header = json.loads(f.readline())
    assert header["input"] == path
    assert SearchCheckpoint(ck, key).load() == {0: []}


def test_checkpoint_key_tracks_sidecar_contents(tutorial_fil, tmp_path):
    fil = read_filterbank(tutorial_fil)
    zap = tmp_path / "z.txt"
    zap.write_text("50.0 0.1\n")
    cfg = SearchConfig(zapfilename=str(zap), **CFG)
    key_before = search_key("", fil, cfg)
    zap.write_text("60.0 0.2\n")  # edited between crash and resume
    assert search_key("", fil, cfg) != key_before


def test_progress_bar_output():
    buf = io.StringIO()
    p = ProgressBar(10, "x ", stream=buf, width=10)
    p.start()
    p.update(5)
    p.finish()
    text = buf.getvalue()
    assert "50.0%" in text
    assert "100.0%" in text
    assert "ETA" in text


def test_progress_bar_disabled_writes_nothing():
    buf = io.StringIO()
    p = ProgressBar(10, stream=buf, enabled=False)
    p.start()
    p.update(5)
    p.finish()
    assert buf.getvalue() == ""


def test_trace_range_is_harmless_without_capture():
    with trace_range("UnitTest-Range"):
        x = np.arange(3).sum()
    assert x == 3


def test_checkpoint_append_only_and_torn_tail(tutorial_fil, tmp_path):
    """v3 JSONL: saves append only NEW rows (O(1) per save, VERDICT r2
    item 6), and a torn tail line from a crash mid-append is dropped
    and truncated on load."""
    from peasoup_tpu.data import Candidate

    fil = read_filterbank(tutorial_fil)
    ck = str(tmp_path / "ap.ckpt")
    key = search_key("", fil, SearchConfig(checkpoint_file=ck, **CFG))

    c = SearchCheckpoint(ck, key, interval=1)
    done = {}
    sizes = []
    for ii in range(6):
        done[ii] = [Candidate(dm=float(ii), dm_idx=ii, snr=10.0 + ii,
                              freq=1.0 + ii)]
        c.maybe_save(done)
        sizes.append(os.path.getsize(ck))
    # append-only: every save grows the file by ~one row, not by the
    # whole accumulated dict (O(ndm) total, not O(ndm^2))
    deltas = np.diff(sizes)
    assert all(abs(d - deltas[0]) < 32 for d in deltas)
    with open(ck) as f:
        lines = f.readlines()
    assert len(lines) == 1 + 6  # header + one line per DM row

    # torn tail: simulate a crash mid-append
    with open(ck, "a") as f:
        f.write('{"dm_idx": 6, "cands": [{"dm": trunc')
    c2 = SearchCheckpoint(ck, key, interval=1)
    with pytest.warns(UserWarning, match="corrupt data"):
        got = c2.load()
    assert sorted(got) == list(range(6))
    assert got[3][0].snr == 13.0 and got[3][0].dm_idx == 3
    # the torn line was truncated away; appends resume cleanly
    done[6] = [Candidate(dm=6.0, dm_idx=6, snr=16.0, freq=7.0)]
    c2.save(done)
    got3 = SearchCheckpoint(ck, key).load()
    assert sorted(got3) == list(range(7))
    assert got3[6][0].snr == 16.0
