import re

import numpy as np
import jax.numpy as jnp
import pytest

from peasoup_tpu.io import read_filterbank
from peasoup_tpu.ops.dedisperse import (
    dedisperse,
    dedisperse_numpy,
    delay_table,
    delays_in_samples,
    generate_dm_list,
    max_delay,
)


def golden_dm_list(overview_path):
    with open(overview_path) as f:
        text = f.read()
    block = text.split("<dedispersion_trials", 1)[1].split("</dedispersion_trials>")[0]
    return np.array(
        [float(m) for m in re.findall(r"<trial id='\d+'>([^<]+)</trial>", block)],
        dtype=np.float64,
    )


def test_dm_list_matches_golden(golden_overview):
    # tutorial.fil: tsamp=0.00032, fch1=1510, foff=-1.09, nchans=64
    dms = generate_dm_list(0.0, 250.0, 0.00032, 64.0, 1510.0, -1.09, 64, 1.10)
    golden = golden_dm_list(golden_overview)
    assert len(dms) == len(golden) == 59
    np.testing.assert_allclose(dms, golden, rtol=2e-5)


def test_dm_list_trivial_range():
    dms = generate_dm_list(5.0, 5.0, 0.00032, 64.0, 1510.0, -1.09, 64, 1.10)
    assert len(dms) == 1 and dms[0] == pytest.approx(5.0)


def test_delay_table_signs():
    tab = delay_table(64, 0.00032, 1510.0, -1.09)
    assert tab[0] == 0.0
    assert np.all(np.diff(tab) > 0)  # lower freq -> larger delay
    # analytic check on last channel
    f0, f63 = 1510.0, 1510.0 - 63 * 1.09
    expected = 4.15e3 / 0.00032 * (1.0 / f63**2 - 1.0 / f0**2)
    assert tab[63] == pytest.approx(expected, rel=1e-6)


def test_dedisperse_recovers_pulse():
    # Synthetic filterbank with one dispersed pulse: at the right DM the
    # channel sum is perfectly aligned.
    nchans, nsamps, dm = 16, 4096, 50.0
    tab = delay_table(nchans, 0.00032, 1510.0, -1.09)
    dm_list = np.array([0.0, dm, 100.0], dtype=np.float32)
    delays = delays_in_samples(dm_list, tab)
    data = np.zeros((nchans, nsamps), dtype=np.float32)
    t0 = 1000
    for c in range(nchans):
        data[c, t0 + delays[1, c]] = 1.0
    out_nsamps = nsamps - max_delay(dm_list, tab)
    out = np.asarray(dedisperse(jnp.asarray(data), jnp.asarray(delays), out_nsamps))
    assert out.shape == (3, out_nsamps)
    assert out[1, t0] == pytest.approx(nchans)  # aligned
    assert out[0].max() < nchans  # misaligned at DM=0
    np.testing.assert_allclose(
        out, dedisperse_numpy(data, delays, out_nsamps), rtol=1e-6
    )


def test_dedisperse_killmask():
    nchans, nsamps = 8, 256
    data = np.ones((nchans, nsamps), dtype=np.float32)
    delays = np.zeros((1, nchans), dtype=np.int32)
    mask = np.array([1, 1, 0, 1, 0, 1, 1, 1], dtype=np.float32)
    out = np.asarray(
        dedisperse(jnp.asarray(data), jnp.asarray(delays), nsamps, jnp.asarray(mask))
    )
    assert np.all(out == 6.0)


def test_tutorial_max_delay(tutorial_fil):
    fil = read_filterbank(tutorial_fil)
    dms = generate_dm_list(0.0, 250.0, fil.tsamp, 64.0, fil.fch1, fil.foff,
                           fil.nchans, 1.10)
    tab = delay_table(fil.nchans, fil.tsamp, fil.fch1, fil.foff)
    md = max_delay(dms, tab)
    # ~140 samples at DM 252.98 for the tutorial setup
    assert 100 < md < 200
    assert fil.nsamps - md > 131072  # search still uses a 2**17 FFT


# ---------------------------------------------------------------------------
# two-stage sub-band dedispersion (dedisp's internal algorithm class)
# ---------------------------------------------------------------------------

from peasoup_tpu.ops.dedisperse import (  # noqa: E402
    dedisperse_subband,
    dedisperse_subband_numpy,
    subband_plan,
)


def _dense_case(rng, nchans=32, nsamps=4096, step=0.5, dm_end=150.0):
    tab = delay_table(nchans, 0.00032, 1510.0, -1.09)
    dm_list = np.arange(0.0, dm_end, step, dtype=np.float32)
    delays = delays_in_samples(dm_list, tab)
    data = rng.integers(0, 4, (nchans, nsamps)).astype(np.uint8)
    out_nsamps = nsamps - max_delay(dm_list, tab)
    return tab, dm_list, delays, data, out_nsamps


def test_subband_eps_zero_is_exact():
    """eps=0 degenerates to anchors == trials: bit-identical to the
    direct channel sweep for integer inputs."""
    rng = np.random.default_rng(11)
    tab, dm_list, delays, data, out_nsamps = _dense_case(
        rng, step=4.0)  # 38 trials: also covers the unrolled stage 2
    plan = subband_plan(dm_list, delays, tab, nsub=8, eps=0.0)
    assert plan["n_anchors"] == len(dm_list)
    assert plan["max_err"] == 0
    out = np.asarray(dedisperse_subband(
        jnp.asarray(data.astype(np.float32)), jnp.asarray(delays), plan,
        out_nsamps))
    want = dedisperse_numpy(data.astype(np.float32), delays, out_nsamps)
    np.testing.assert_array_equal(out, want)


def test_subband_dense_grid_compresses_and_bounds_error():
    """On a delay-resolution-dense DM grid the plan must compress the
    stage-1 anchor set substantially, keep the per-channel effective
    delay error within eps+1 samples, and the device op must equal its
    numpy model bit-for-bit (integer inputs)."""
    rng = np.random.default_rng(12)
    tab, dm_list, delays, data, out_nsamps = _dense_case(rng, step=0.5)
    ndm = len(dm_list)
    plan = subband_plan(dm_list, delays, tab, nsub=8, eps=0.5)
    assert plan["n_anchors"] < ndm // 4  # the tree actually compresses
    assert plan["max_err"] <= 2  # eps + rounding
    out = np.asarray(dedisperse_subband(
        jnp.asarray(data.astype(np.float32)), jnp.asarray(delays), plan,
        out_nsamps))
    model = dedisperse_subband_numpy(data, delays, plan, out_nsamps)
    np.testing.assert_array_equal(out, model)


def test_subband_recovers_dispersed_pulse():
    """A dispersed unit pulse must still collect ALL nchans of its
    energy within +-max_err samples of its true position at the true
    DM trial (sub-sample smearing, no energy loss)."""
    nchans, nsamps = 32, 4096
    tab = delay_table(nchans, 0.00032, 1510.0, -1.09)
    dm_list = np.arange(0.0, 150.0, 0.5, dtype=np.float32)
    delays = delays_in_samples(dm_list, tab)
    i_true = 200  # dm = 100.0
    data = np.zeros((nchans, nsamps), np.float32)
    t0 = 1000
    for c in range(nchans):
        data[c, t0 + delays[i_true, c]] = 1.0
    out_nsamps = nsamps - max_delay(dm_list, tab)
    plan = subband_plan(dm_list, delays, tab, nsub=8, eps=0.5)
    out = np.asarray(dedisperse_subband(
        jnp.asarray(data), jnp.asarray(delays), plan, out_nsamps))
    e = plan["max_err"]
    window = out[i_true, t0 - e : t0 + e + 1]
    assert window.sum() == pytest.approx(nchans)


def test_subband_driver_wiring(tutorial_fil):
    """Opt-in config wiring: ``subband_dedisp='auto'`` must engage the
    two-stage path on a compressible grid and produce trials that
    agree with the exact sweep up to the plan's sub-sample smearing
    (default 'never' keeps the exact sweep — covered by every other
    driver test)."""
    from peasoup_tpu.io import read_filterbank
    from peasoup_tpu.search.pipeline import PulsarSearch
    from peasoup_tpu.search.plan import SearchConfig

    fil = read_filterbank(tutorial_fil)
    base = dict(dm_start=0.0, dm_end=60.0, npdmp=0)
    auto = PulsarSearch(fil, SearchConfig(**base, subband_dedisp="auto"))
    plan = auto._subband_plan()
    assert plan is not None
    assert plan["n_anchors"] < len(auto.dm_list)
    exact = PulsarSearch(fil, SearchConfig(**base))
    assert exact._subband_plan() is None
    t_auto = np.asarray(auto.dedisperse())
    t_exact = np.asarray(exact.dedisperse())
    assert t_auto.shape == t_exact.shape
    # the driver's output must be exactly the planned sub-band sum
    # (2-bit integer data: f32 sums are exact), and the plan's delay
    # smearing must stay within the documented eps+1 bound
    assert plan["max_err"] <= 2
    model = dedisperse_subband_numpy(
        fil.data.T, np.asarray(auto.delays), plan, auto.out_nsamps)
    np.testing.assert_array_equal(t_auto, model)


# ---------------------------------------------------------------------------
# Pallas tiled kernel (interpret mode on CPU; compiled on TPU)
# ---------------------------------------------------------------------------

from peasoup_tpu.ops.dedisperse_pallas import (  # noqa: E402
    dedisperse_pallas,
    dedisperse_window_slack,
)


def _random_case(rng, nchans, nsamps, ndm, dtype):
    tab = delay_table(nchans, 0.00032, 1510.0, -1.09)
    dm_list = np.linspace(0.0, 150.0, ndm).astype(np.float32)
    delays = delays_in_samples(dm_list, tab)
    if dtype == np.uint8:
        data = rng.integers(0, 4, (nchans, nsamps)).astype(np.uint8)
    else:
        data = rng.normal(size=(nchans, nsamps)).astype(np.float32)
    out_nsamps = nsamps - max_delay(dm_list, tab)
    return data, delays, out_nsamps


@pytest.mark.parametrize("dtype", [np.float32, np.uint8])
def test_dedisperse_pallas_parity(dtype, pallas_interpret):
    """Tile/pad/clamp paths: ndm not a tile multiple, out_nsamps not a
    time-tile multiple, windows clamped at the array end."""
    rng = np.random.default_rng(3)
    nchans, nsamps, ndm = 32, 4096, 21
    data, delays, out_nsamps = _random_case(rng, nchans, nsamps, ndm, dtype)
    dm_tile, chan_group, time_tile = 8, 8, 1024
    slack = dedisperse_window_slack(delays, dm_tile, chan_group)
    out = np.asarray(dedisperse_pallas(
        jnp.asarray(data), jnp.asarray(delays), out_nsamps,
        window_slack=slack, dm_tile=dm_tile, time_tile=time_tile,
        chan_group=chan_group, interpret=True,
    ))
    golden = dedisperse_numpy(data.astype(np.float32), delays, out_nsamps)
    assert out.shape == golden.shape
    np.testing.assert_allclose(out, golden, rtol=1e-6, atol=1e-5)


def test_dedisperse_pallas_matches_scan_path(pallas_interpret):
    """Pallas kernel == the XLA scan path on the same inputs."""
    rng = np.random.default_rng(4)
    data, delays, out_nsamps = _random_case(rng, 16, 2048, 12, np.float32)
    slack = dedisperse_window_slack(delays, 4, 4)
    a = np.asarray(dedisperse_pallas(
        jnp.asarray(data), jnp.asarray(delays), out_nsamps,
        window_slack=slack, dm_tile=4, time_tile=1024, chan_group=4,
        interpret=True,
    ))
    b = np.asarray(dedisperse(jnp.asarray(data), jnp.asarray(delays),
                              out_nsamps))
    np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-5)


def test_dedisperse_pallas_rejects_short_input():
    data = jnp.zeros((8, 128), jnp.float32)
    delays = jnp.zeros((4, 8), jnp.int32)
    with pytest.raises(ValueError, match="too short"):
        dedisperse_pallas(data, delays, 64, window_slack=128,
                          time_tile=1024, chan_group=8, interpret=True)


@pytest.mark.parametrize("dtype", [np.float32, np.uint8])
@pytest.mark.parametrize("nparts", [1, 2])
def test_dedisperse_pallas_flat_parity(dtype, nparts, pallas_interpret):
    """Flat-input kernel (the production hot path, VERDICT r2 item 3):
    bit-parity with the numpy reference over single- and multi-part
    flat inputs, u8 and f32, with tile-aligned caller padding."""
    from peasoup_tpu.ops.dedisperse import split_flat_channels
    from peasoup_tpu.ops.dedisperse_pallas import (
        dedisperse_flat_pad_to,
        dedisperse_pallas_flat,
    )

    rng = np.random.default_rng(7)
    nchans, ndm = 64, 12
    T, G, dm_tile = 7168, 16, 12
    out_nsamps = T + 300
    tab = delay_table(nchans, 0.00032, 1510.0, -1.09)
    dm_list = np.linspace(0.0, 150.0, ndm).astype(np.float32)
    delays = delays_in_samples(dm_list, tab)
    md = max_delay(dm_list, tab)
    slack = dedisperse_window_slack(delays, dm_tile, G)
    nsamps = dedisperse_flat_pad_to(out_nsamps, md, slack, T,
                                    uint8=dtype == np.uint8)
    if dtype == np.uint8:
        data = rng.integers(0, 4, (nchans, nsamps)).astype(np.uint8)
    else:
        data = rng.normal(size=(nchans, nsamps)).astype(np.float32)
    if nparts == 2:
        import sys

        dd = sys.modules["peasoup_tpu.ops.dedisperse"]
        old = dd._FLAT_PART_LIMIT
        dd._FLAT_PART_LIMIT = 32 * nsamps + 5
        try:
            parts = split_flat_channels(data, align=2 * G)
        finally:
            dd._FLAT_PART_LIMIT = old
        assert len(parts) == 2
    else:
        parts = split_flat_channels(data, align=2 * G)
    got = np.asarray(dedisperse_pallas_flat(
        [jnp.asarray(p) for p in parts], jnp.asarray(delays), nsamps,
        out_nsamps, window_slack=slack, max_delay=md, dm_tile=dm_tile,
        time_tile=T, chan_group=G, interpret=True,
    ))
    want = dedisperse_numpy(data.astype(np.float32), delays, out_nsamps)
    if dtype == np.uint8:
        # integer inputs: sums are exact in f32 regardless of order
        np.testing.assert_array_equal(got, want)
    else:
        # f32 inputs: the kernel accumulates each chan_group in a
        # vector register before touching the output, a different
        # (last-ulp) rounding order than numpy's sequential channel sum
        np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-5)


# ---------------------------------------------------------------------------
# sub-band on the flat/chunked hot path (chan_range + two-stage assembly)
# ---------------------------------------------------------------------------

def test_dedisperse_flat_chan_range_partials():
    """chan_range partials must sum to the full sweep (integer data, so
    f32 add order cannot matter)."""
    from peasoup_tpu.ops.dedisperse import dedisperse_flat

    rng = np.random.default_rng(11)
    nchans, nsamps, ndm = 32, 2048, 9
    tab = delay_table(nchans, 0.00032, 1510.0, -1.09)
    dm_list = np.linspace(0.0, 120.0, ndm).astype(np.float32)
    delays = delays_in_samples(dm_list, tab)
    out_nsamps = nsamps - max_delay(dm_list, tab)
    data = rng.integers(0, 4, (nchans, nsamps)).astype(np.uint8)
    flat = jnp.asarray(data.reshape(-1))
    dj = jnp.asarray(delays)
    full = np.asarray(dedisperse_flat([flat], dj, nsamps, out_nsamps))
    pieces = sum(
        np.asarray(dedisperse_flat([flat], dj, nsamps, out_nsamps,
                                   chan_range=(lo, lo + 8)))
        for lo in range(0, nchans, 8)
    )
    np.testing.assert_array_equal(full, pieces)


def test_dedisperse_pallas_flat_chan_range(pallas_interpret):
    """Pallas flat kernel with chan_range == numpy over that channel
    slice only (sub-band stage 1)."""
    from peasoup_tpu.ops.dedisperse import split_flat_channels
    from peasoup_tpu.ops.dedisperse_pallas import (
        dedisperse_flat_pad_to,
        dedisperse_pallas_flat,
    )

    rng = np.random.default_rng(12)
    nchans, ndm = 64, 6
    T, G, dm_tile = 7168, 8, 6
    out_nsamps = T + 100
    tab = delay_table(nchans, 0.00032, 1510.0, -1.09)
    dm_list = np.linspace(0.0, 150.0, ndm).astype(np.float32)
    delays = delays_in_samples(dm_list, tab)
    md = max_delay(dm_list, tab)
    slack = dedisperse_window_slack(delays, dm_tile, G)
    nsamps = dedisperse_flat_pad_to(out_nsamps, md, slack, T)
    data = rng.integers(0, 4, (nchans, nsamps)).astype(np.uint8)
    parts = [jnp.asarray(p) for p in split_flat_channels(data, align=2 * G)]
    for lo, hi in ((0, 16), (16, 48), (48, 64)):
        got = np.asarray(dedisperse_pallas_flat(
            parts, jnp.asarray(delays), nsamps, out_nsamps,
            window_slack=slack, max_delay=md, dm_tile=dm_tile,
            time_tile=T, chan_group=G, interpret=True,
            chan_range=(lo, hi),
        ))
        mask = np.zeros(nchans, np.float32)
        mask[lo:hi] = 1.0
        want = dedisperse_numpy(data.astype(np.float32), delays,
                                out_nsamps, killmask=mask)
        np.testing.assert_array_equal(got, want)


def test_subband_chunk_plan_and_flat_assembly_exact():
    """eps=0 chunked sub-band plan: anchors compress across
    duplicate-DM trials and the two-stage flat assembly is
    bit-identical to the direct sweep."""
    from peasoup_tpu.ops.dedisperse import (
        dedisperse_flat,
        dedisperse_subband_flat,
        subband_chunk_plan,
    )

    rng = np.random.default_rng(13)
    nchans, nsamps = 32, 4096
    tab = delay_table(nchans, 0.00032, 1510.0, -1.09)
    # pairs of identical DMs: anchors must halve with zero error
    base = np.repeat(np.linspace(0.0, 120.0, 4), 2)
    delays = delays_in_samples(base.astype(np.float32), tab)
    out_nsamps = nsamps - int(delays.max())
    data = rng.integers(0, 4, (nchans, nsamps)).astype(np.uint8)
    cells = [np.arange(0, 4), np.arange(4, 8)]
    plan = subband_chunk_plan(base, delays, tab, cells, chan_align=1,
                              eps=0.0)
    assert plan is not None
    assert plan["max_err"] == 0
    assert plan["n_anchor_p"] == 2  # 2 distinct DMs per 4-row cell
    flat = jnp.asarray(data.reshape(-1))

    def stage1_factory(anchor_rows):
        ad = jnp.asarray(delays[anchor_rows])
        return lambda cr, ad_in: dedisperse_flat(
            [flat], ad_in, nsamps, out_nsamps + plan["shift_max"],
            chan_range=cr)

    direct = np.asarray(dedisperse_flat(
        [flat], jnp.asarray(delays), nsamps, out_nsamps))
    for ci, rows in enumerate(cells):
        anchor_rows, assign, shifts = plan["per_cell"][ci]
        got = np.asarray(dedisperse_subband_flat(
            jnp.asarray(delays[anchor_rows]), jnp.asarray(assign),
            jnp.asarray(shifts), out_nsamps,
            bounds=plan["bounds"],
            L1=out_nsamps + plan["shift_max"],
            stage1=stage1_factory(anchor_rows),
        ))
        np.testing.assert_array_equal(got, direct[rows])


def test_chunked_subband_e2e_matches_direct(tutorial_fil):
    """Chunked mesh driver with subband_dedisp='always', eps=0 must
    reproduce the direct chunked driver's candidates exactly (2-bit
    integer data: all sums exact in f32)."""
    from peasoup_tpu.io.sigproc import read_filterbank
    from peasoup_tpu.parallel.mesh import MeshPulsarSearch
    from peasoup_tpu.search.plan import SearchConfig

    fil = read_filterbank(tutorial_fil)
    # paired DMs so eps=0 still compresses anchors (n_anchor < rows)
    dms = np.repeat(np.linspace(0.0, 60.0, 6), 2).astype(np.float32)
    base = dict(
        dm_list=dms, acc_start=-5.0, acc_end=5.0,
        acc_pulse_width=64000.0, nharmonics=4, npdmp=2, limit=50,
        dm_chunk=4, accel_block=2,
    )
    direct = MeshPulsarSearch(fil, SearchConfig(**base)).run()
    sub = MeshPulsarSearch(
        fil, SearchConfig(**base, subband_dedisp="always",
                          subband_eps=0.0)
    ).run()
    assert len(direct.candidates) == len(sub.candidates)
    for a, b in zip(direct.candidates, sub.candidates):
        assert a.freq == b.freq
        assert a.snr == pytest.approx(b.snr, rel=1e-6)
        assert a.dm == b.dm and a.acc == b.acc


def test_dedisperse_pallas_flat_subband_kernel(pallas_interpret):
    """One-launch sub-band stage 1 (grid over sub-bands, K-tile
    windows, cross-step double buffering): every sub-band's partials
    must equal numpy over that channel slice (integer data => exact)."""
    from peasoup_tpu.ops.dedisperse import split_flat_channels
    from peasoup_tpu.ops.dedisperse_pallas import (
        dedisperse_flat_pad_to,
        dedisperse_pallas_flat_subband,
    )

    rng = np.random.default_rng(14)
    nchans, ndm = 64, 4
    T, G, dm_tile, K, csub = 1024, 8, 4, 2, 16
    out_nsamps = K * T * 2 + 100  # > one K-window: exercises njk > 1
    tab = delay_table(nchans, 0.00032, 1510.0, -1.09)
    dm_list = np.linspace(0.0, 150.0, ndm).astype(np.float32)
    delays = delays_in_samples(dm_list, tab)
    md = max_delay(dm_list, tab)
    slack = dedisperse_window_slack(delays, dm_tile, G)
    nsamps = dedisperse_flat_pad_to(out_nsamps, md, slack, K * T)
    data = rng.integers(0, 4, (nchans, nsamps)).astype(np.uint8)
    parts = [jnp.asarray(p) for p in split_flat_channels(data, align=csub)]
    got = np.asarray(dedisperse_pallas_flat_subband(
        parts, jnp.asarray(delays), nsamps, out_nsamps, csub=csub,
        window_slack=slack, max_delay=md, dm_tile=dm_tile,
        time_tile=T, k_tiles=K, chan_group=G, interpret=True,
    ))
    assert got.shape == (ndm, nchans // csub, out_nsamps)
    for s in range(nchans // csub):
        mask = np.zeros(nchans, np.float32)
        mask[s * csub : (s + 1) * csub] = 1.0
        want = dedisperse_numpy(data.astype(np.float32), delays,
                                out_nsamps, killmask=mask)
        np.testing.assert_array_equal(got[:, s], want,
                                      err_msg=f"sub-band {s}")


def test_quantise_trials_u8_lattice():
    """dedisp out_nbits=8 reconstruction: scale to the output range,
    clip, truncate (`dedisperser.hpp:104-112`)."""
    from peasoup_tpu.ops.dedisperse import quantise_trials_u8

    trials = jnp.asarray([[0.0, 96.0, 192.0, 500.0]], jnp.float32)
    got = np.asarray(quantise_trials_u8(trials, 2, 64))
    # scale = 255 / (3 * 64): 96 -> 127.5 -> 127; 192 -> 255; clip 500
    np.testing.assert_array_equal(got, [[0.0, 127.0, 255.0, 255.0]])
    assert got.dtype == np.float32


def test_trial_nbits8_e2e_recovers_fundamental(tutorial_fil):
    """The opt-in uint8 trial lattice must still recover the strongest
    golden family (quantisation legitimately perturbs near-tie DM
    associations, so only the physics is asserted, not the exact
    golden rows — see ops/dedisperse.py)."""
    from peasoup_tpu.io.sigproc import read_filterbank
    from peasoup_tpu.parallel.mesh import MeshPulsarSearch
    from peasoup_tpu.search.plan import SearchConfig

    fil = read_filterbank(tutorial_fil)
    cfg = SearchConfig(
        dm_start=0.0, dm_end=250.0, acc_start=-5.0, acc_end=5.0,
        acc_pulse_width=64000.0, nharmonics=4, npdmp=2, limit=50,
        trial_nbits=8,
    )
    r = MeshPulsarSearch(fil, cfg).run()
    top = r.candidates[0]
    assert 1.0 / top.freq == pytest.approx(0.24994, rel=1e-3)
    assert top.snr == pytest.approx(86.96, rel=0.02)
    assert top.folded_snr > 30


def test_trial_nbits8_requires_integer_input(tutorial_fil):
    from peasoup_tpu.errors import ConfigError
    from peasoup_tpu.io.sigproc import read_filterbank
    from peasoup_tpu.search.pipeline import PulsarSearch
    from peasoup_tpu.search.plan import SearchConfig

    fil = read_filterbank(tutorial_fil)
    fil.header.nbits = 32
    with pytest.raises(ConfigError):
        PulsarSearch(fil, SearchConfig(trial_nbits=8))
    with pytest.raises(ConfigError):
        PulsarSearch(fil, SearchConfig(trial_nbits=16))


def test_subband_stage2_kernel_assembly_exact(pallas_interpret):
    """The Pallas stage-2-as-dedispersion path (flat f32 partials as a
    synthetic nsub-channel filterbank + one-hot row selection, the
    chunked driver's kernel2 mode) must be bit-identical to the direct
    sweep (interpret mode, integer data => exact)."""
    from peasoup_tpu.ops.dedisperse import (
        dedisperse_flat,
        subband_chunk_plan,
        subband_stage2_layout,
    )
    from peasoup_tpu.ops.dedisperse_pallas import (
        dedisperse_flat_pad_to,
        dedisperse_pallas_flat,
        dedisperse_window_slack,
    )

    rng = np.random.default_rng(17)
    nchans = 32
    T = 1024  # small kernel tile for the interpret run
    tab = delay_table(nchans, 0.00032, 1510.0, -1.09)
    base = np.repeat(np.linspace(0.0, 120.0, 4), 2)
    delays = delays_in_samples(base.astype(np.float32), tab)
    md = int(delays.max())
    out_nsamps = 2 * T + 100
    nsamps0 = out_nsamps + md
    cells = [np.arange(0, 4), np.arange(4, 8)]
    plan = subband_chunk_plan(base, delays, tab, cells, chan_align=1,
                              eps=0.0)
    assert plan is not None
    nsub = plan["nsub"]
    dm_tile2 = 8
    G2 = next(g for g in (16, 8, 4, 2, 1) if nsub % (2 * g) == 0)
    _, cells2p = subband_stage2_layout(plan["per_cell"], 0, dm_tile2)
    slack2 = max(int(dedisperse_window_slack(c[0], dm_tile2, G2))
                 for c in cells2p)
    L1 = dedisperse_flat_pad_to(out_nsamps, plan["shift_max"], slack2, T)
    R2, cells2 = subband_stage2_layout(plan["per_cell"], L1, dm_tile2)
    nsamps0 = L1 + md  # stage-1 windows reach L1 output samples
    data = rng.integers(0, 4, (nchans, nsamps0)).astype(np.uint8)
    flat = jnp.asarray(data.reshape(-1))
    direct = np.asarray(dedisperse_flat(
        [flat], jnp.asarray(delays), nsamps0, out_nsamps))

    for ci, rows in enumerate(cells):
        anchor_rows, _assign, _shifts = plan["per_cell"][ci]
        # stage 1 partials via the XLA path (the stage-1 kernel has
        # its own exactness test); stage 2 through the REAL flat
        # kernel in interpret mode
        parts = []
        for lo, hi in plan["bounds"]:
            p = np.asarray(dedisperse_flat(
                [flat], jnp.asarray(delays[anchor_rows]), nsamps0, L1,
                chan_range=(lo, hi)))
            parts.append(p)
        partials = np.stack(parts, axis=1)  # (n_anchor, nsub, L1)
        d2, unpad = cells2[ci]
        out2 = np.asarray(dedisperse_pallas_flat(
            [jnp.asarray(partials.reshape(-1).astype(np.float32))],
            jnp.asarray(d2), L1, out_nsamps, window_slack=slack2,
            max_delay=plan["shift_max"], dm_tile=dm_tile2,
            time_tile=T, chan_group=G2, data_tail_ok=True,
            interpret=True))
        onehot = (unpad[:, None] == np.arange(R2)[None, :])
        got = np.einsum("rp,pl->rl", onehot.astype(np.float32), out2)
        np.testing.assert_array_equal(got, direct[rows])
