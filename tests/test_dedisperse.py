import re

import numpy as np
import jax.numpy as jnp
import pytest

from peasoup_tpu.io import read_filterbank
from peasoup_tpu.ops.dedisperse import (
    dedisperse,
    dedisperse_numpy,
    delay_table,
    delays_in_samples,
    generate_dm_list,
    max_delay,
)


def golden_dm_list(overview_path):
    with open(overview_path) as f:
        text = f.read()
    block = text.split("<dedispersion_trials", 1)[1].split("</dedispersion_trials>")[0]
    return np.array(
        [float(m) for m in re.findall(r"<trial id='\d+'>([^<]+)</trial>", block)],
        dtype=np.float64,
    )


def test_dm_list_matches_golden(golden_overview):
    # tutorial.fil: tsamp=0.00032, fch1=1510, foff=-1.09, nchans=64
    dms = generate_dm_list(0.0, 250.0, 0.00032, 64.0, 1510.0, -1.09, 64, 1.10)
    golden = golden_dm_list(golden_overview)
    assert len(dms) == len(golden) == 59
    np.testing.assert_allclose(dms, golden, rtol=2e-5)


def test_dm_list_trivial_range():
    dms = generate_dm_list(5.0, 5.0, 0.00032, 64.0, 1510.0, -1.09, 64, 1.10)
    assert len(dms) == 1 and dms[0] == pytest.approx(5.0)


def test_delay_table_signs():
    tab = delay_table(64, 0.00032, 1510.0, -1.09)
    assert tab[0] == 0.0
    assert np.all(np.diff(tab) > 0)  # lower freq -> larger delay
    # analytic check on last channel
    f0, f63 = 1510.0, 1510.0 - 63 * 1.09
    expected = 4.15e3 / 0.00032 * (1.0 / f63**2 - 1.0 / f0**2)
    assert tab[63] == pytest.approx(expected, rel=1e-6)


def test_dedisperse_recovers_pulse():
    # Synthetic filterbank with one dispersed pulse: at the right DM the
    # channel sum is perfectly aligned.
    nchans, nsamps, dm = 16, 4096, 50.0
    tab = delay_table(nchans, 0.00032, 1510.0, -1.09)
    dm_list = np.array([0.0, dm, 100.0], dtype=np.float32)
    delays = delays_in_samples(dm_list, tab)
    data = np.zeros((nchans, nsamps), dtype=np.float32)
    t0 = 1000
    for c in range(nchans):
        data[c, t0 + delays[1, c]] = 1.0
    out_nsamps = nsamps - max_delay(dm_list, tab)
    out = np.asarray(dedisperse(jnp.asarray(data), jnp.asarray(delays), out_nsamps))
    assert out.shape == (3, out_nsamps)
    assert out[1, t0] == pytest.approx(nchans)  # aligned
    assert out[0].max() < nchans  # misaligned at DM=0
    np.testing.assert_allclose(
        out, dedisperse_numpy(data, delays, out_nsamps), rtol=1e-6
    )


def test_dedisperse_killmask():
    nchans, nsamps = 8, 256
    data = np.ones((nchans, nsamps), dtype=np.float32)
    delays = np.zeros((1, nchans), dtype=np.int32)
    mask = np.array([1, 1, 0, 1, 0, 1, 1, 1], dtype=np.float32)
    out = np.asarray(
        dedisperse(jnp.asarray(data), jnp.asarray(delays), nsamps, jnp.asarray(mask))
    )
    assert np.all(out == 6.0)


def test_tutorial_max_delay(tutorial_fil):
    fil = read_filterbank(tutorial_fil)
    dms = generate_dm_list(0.0, 250.0, fil.tsamp, 64.0, fil.fch1, fil.foff,
                           fil.nchans, 1.10)
    tab = delay_table(fil.nchans, fil.tsamp, fil.fch1, fil.foff)
    md = max_delay(dms, tab)
    # ~140 samples at DM 252.98 for the tutorial setup
    assert 100 < md < 200
    assert fil.nsamps - md > 131072  # search still uses a 2**17 FFT
