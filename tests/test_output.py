import os

import numpy as np
import pytest

from peasoup_tpu.cli import build_parser, args_to_config, write_search_output, main
from peasoup_tpu.data import Candidate
from peasoup_tpu.output import (
    CandidateFileParser,
    OutputFileWriter,
    OverviewFile,
    XMLElement,
    write_candidate_binary,
)


def mk_cand(freq=4.0, dm=30.0, snr=50.0, with_fold=False, nassoc=0):
    c = Candidate(dm=dm, dm_idx=9, acc=0.0, nh=2, snr=snr, freq=freq,
                  opt_period=1.0 / freq)
    for i in range(nassoc):
        c.append(Candidate(dm=dm + i, dm_idx=9 + i, snr=snr / 2, freq=freq * 2))
    if with_fold:
        c.fold = np.arange(64 * 16, dtype=np.float32).reshape(16, 64)
        c.nbins, c.nints = 64, 16
    return c


def test_xml_element_formatting():
    el = XMLElement("trial", 3.3133590221405)
    el.add_attribute("id", 1)
    assert el.to_string() == "<trial id='1'>3.3133590221405</trial>\n"
    root = XMLElement("root")
    root.append(XMLElement("child", 0.10000000149011612))
    out = root.to_string(header=True)
    assert out.startswith("<?xml version='1.0' encoding='ISO-8859-1'?>\n")
    assert "<child>0.100000001490116</child>" in out  # 15 sig digits


def test_binary_roundtrip(tmp_path):
    cands = [mk_cand(with_fold=True, nassoc=3), mk_cand(freq=7.0, nassoc=0)]
    path = str(tmp_path / "candidates.peasoup")
    mapping = write_candidate_binary(cands, path)
    assert mapping[0] == 0
    with CandidateFileParser(path) as parser:
        fold, hits = parser.cand_from_offset(mapping[0])
        assert fold.shape == (16, 64)
        np.testing.assert_array_equal(fold, cands[0].fold)
        assert len(hits) == 4  # candidate + 3 assoc
        assert hits[0]["dm"] == pytest.approx(30.0)
        assert hits[0]["snr"] == pytest.approx(50.0)
        fold2, hits2 = parser.cand_from_offset(mapping[1])
        assert fold2 is None
        assert len(hits2) == 1
        assert hits2[0]["freq"] == pytest.approx(7.0)


def test_golden_overview_parses(golden_overview):
    ov = OverviewFile(golden_overview)
    assert ov.ncands == 10
    assert len(ov.dm_list()) == 59
    arr = ov.as_array()
    assert arr["snr"][0] == pytest.approx(86.9626083374023)


def test_cli_end_to_end(tutorial_fil, tmp_path):
    outdir = str(tmp_path / "out")
    rc = main([
        "-i", tutorial_fil, "-o", outdir,
        "--dm_start", "0", "--dm_end", "20",
        "--acc_start", "-5", "--acc_end", "5",
        "--acc_pulse_width", "64000", "--npdmp", "2", "--limit", "10",
        "--single_device",
    ])
    assert rc == 0
    ov = OverviewFile(os.path.join(outdir, "overview.xml"))
    arr = ov.as_array()
    assert ov.ncands > 0
    # binary offsets must be consistent with the XML
    with CandidateFileParser(os.path.join(outdir, "candidates.peasoup")) as p:
        for rec in arr:
            fold, hits = p.cand_from_offset(int(rec["byte_offset"]))
            assert hits[0]["snr"] == pytest.approx(float(rec["snr"]), rel=1e-5)
            assert 1 + rec["nassoc"] == len(hits)
    # sections present
    assert "tsamp" in ov.section("header_parameters")
    assert "dm_start" in ov.section("search_parameters")
    assert "total" in ov.section("execution_times")
    # the run must leave a compile ledger, and every backend compile it
    # ledgered must be attributed to a program + geometry fingerprint
    # (ISSUE 18 — count may be 0 if this process already compiled the
    # tutorial geometry, but an anonymous compile is never acceptable)
    from peasoup_tpu.obs.compilation import read_compiles
    ledger = os.path.join(outdir, "compiles.jsonl")
    assert os.path.exists(ledger)
    for rec in read_compiles(ledger, kinds=("compile",)):
        assert rec["program"] == "pipeline.search"
        assert rec["geometry"] and rec["device_kind"]


def test_cli_defaults_match_reference():
    args = build_parser().parse_args(["-i", "x.fil"])
    cfg = args_to_config(args)
    assert cfg.dm_end == 100.0
    assert cfg.dm_tol == pytest.approx(1.10)
    assert cfg.nharmonics == 4
    assert cfg.min_snr == 9.0
    assert cfg.max_freq == 1100.0
    assert cfg.max_harm == 16
    assert cfg.freq_tol == pytest.approx(1e-4)
    assert cfg.limit == 1000
    assert cfg.outdir.endswith("_peasoup/")
