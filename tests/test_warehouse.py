"""Flight-recorder tier-1 tests (ISSUE 16): the unified warehouse
(robustness: torn lines, schema skew, clock skew, segment sealing),
the statistical baseline plane (determinism from the checked-in
fixture ledger, the one-anomaly acceptance case), the run-to-run
structural diff (reproducing the checked-in ``trace_summary_r6.md``
mechanically) and the ``obs`` CLI verb family."""

import copy
import json
import os

import pytest

from peasoup_tpu.obs.baseline import (
    baseline_band,
    baseline_table,
    detect_point,
    fleet_presence_anomalies,
    history_anomalies,
    robust_stats,
    write_anomalies,
)
from peasoup_tpu.obs.diff import (
    diff_bench_records,
    diff_reports,
    load_report,
    render_markdown,
)
from peasoup_tpu.obs.history import load_history
from peasoup_tpu.obs.warehouse import (
    Warehouse,
    geometry_fingerprint,
    host_rollup,
    make_row,
    row_key,
    sparkline,
)

FIXTURES = os.path.join(os.path.dirname(__file__), "..",
                        "benchmarks", "fixtures")
REPORT_R5 = os.path.join(FIXTURES, "run_report_r5.json")
REPORT_R6 = os.path.join(FIXTURES, "run_report_r6.json")
HISTORY_FIXTURE = os.path.join(FIXTURES, "history_fixture.jsonl")
TRACE_SUMMARY_R6 = os.path.join(FIXTURES, "..", "trace_summary_r6.md")


def _rows(n, *, host="h0", t0=1000.0):
    return [make_row(ts=t0 + i, run="r1", source="report",
                     metric=f"timer.t{i}", value=float(i), host=host)
            for i in range(n)]


# --------------------------------------------------------------------------
# warehouse store: append/read round-trip, filters, index
# --------------------------------------------------------------------------

def test_roundtrip_and_filters(tmp_path):
    wh = Warehouse(str(tmp_path / "wh"))
    rows = [
        make_row(ts=1.0, run="a", source="report", stage="peaks",
                 metric="stage.device_s", value=0.5, host="h0"),
        make_row(ts=2.0, run="a", source="span", stage="sort",
                 metric="span.device_s", value=0.7, host="h0"),
        make_row(ts=3.0, run="b", source="report", stage="peaks",
                 metric="stage.device_s", value=0.6, host="h1"),
    ]
    assert wh.append_rows(rows) == 3
    assert len(wh.rows()) == 3
    assert [r["run"] for r in wh.rows(run="a")] == ["a", "a"]
    assert [r["value"] for r in wh.rows(stage="peaks")] == [0.5, 0.6]
    assert [r["value"] for r in wh.rows(host="h1")] == [0.6]
    # metric filter is a prefix match (one family, many fields)
    assert len(wh.rows(metric="stage.")) == 2
    assert [r["value"] for r in wh.rows(source="span")] == [0.7]
    assert [r["value"] for r in wh.rows(since=2.5)] == [0.6]
    idx = wh.index()
    assert idx["rows_total"] == 3
    assert idx["runs"]["a"]["rows"] == 2
    assert idx["runs"]["a"]["sources"] == ["report", "span"]


def test_top_and_tail(tmp_path):
    wh = Warehouse(str(tmp_path / "wh"))
    wh.append_rows(_rows(5))
    assert [r["value"] for r in wh.top(2)] == [4.0, 3.0]
    assert [r["value"] for r in wh.tail(2)] == [3.0, 4.0]


def test_row_key_excludes_run():
    row = make_row(ts=1.0, run="r9", source="report", stage="peaks",
                   metric="m", value=1.0, geometry="g", host="h",
                   device_kind="cpu")
    assert row_key(row) == ("peaks", "g", "cpu", "h")


# --------------------------------------------------------------------------
# robustness: torn lines, schema skew, clock skew, sealing
# --------------------------------------------------------------------------

def test_torn_lines_skipped_silently(tmp_path):
    wh = Warehouse(str(tmp_path / "wh"))
    wh.append_rows(_rows(3))
    with open(wh.segment_path, "a") as f:
        f.write('{"v": 1, "ts": 99, "torn truncat')
        f.write("\nnot json at all\n")
        f.write('["a", "list", "not", "a", "row"]\n')
    assert len(wh.rows()) == 3
    assert wh.last_skipped["torn"] == 3
    assert wh.last_skipped["skew"] == 0


def test_newer_schema_rows_skipped_with_counted_warning(tmp_path):
    """v+1 rows (a newer writer sharing the store) are skipped, the
    count is tracked, and exactly one typed warn_event fires."""
    wh = Warehouse(str(tmp_path / "wh"))
    wh.append_rows(_rows(2))
    future = make_row(ts=5000.0, run="r1", source="report",
                      metric="timer.future", value=1.0)
    future["v"] = 2
    with open(wh.segment_path, "a") as f:
        f.write(json.dumps(future) + "\n")
        f.write(json.dumps(dict(future, metric="timer.future2"))
                + "\n")
    with pytest.warns(UserWarning, match="schema v1"):
        rows = wh.rows()
    assert len(rows) == 2
    assert wh.last_skipped == {"torn": 0, "skew": 2}


def test_cross_host_clock_skew_merges_by_row_ts(tmp_path):
    """A host with a skewed (earlier) clock appends *later* — reads
    still interleave by the rows' own timestamps, with a
    deterministic (ts, host, source, metric) tiebreak."""
    wh = Warehouse(str(tmp_path / "wh"))
    wh.append_rows(_rows(3, host="h-ahead", t0=2000.0))
    wh.append_rows(_rows(3, host="h-behind", t0=1000.0))
    out = wh.rows()
    assert [r["host"] for r in out] == ["h-behind"] * 3 + ["h-ahead"] * 3
    assert [r["ts"] for r in out] == sorted(r["ts"] for r in out)
    # same-ts rows tiebreak deterministically
    wh2 = Warehouse(str(tmp_path / "wh2"))
    a = make_row(ts=1.0, run="r", source="report", metric="m",
                 value=1.0, host="zz")
    b = make_row(ts=1.0, run="r", source="report", metric="m",
                 value=2.0, host="aa")
    wh2.append_rows([a, b])
    assert [r["host"] for r in wh2.rows()] == ["aa", "zz"]


def test_segment_seals_past_budget_and_reads_span_generations(tmp_path):
    """Past the byte budget the live segment rotates to ``.1`` (the
    telemetry-shard scheme): reads span both generations, and at most
    one sealed generation is retained so disk stays bounded."""
    wh = Warehouse(str(tmp_path / "wh"), max_segment_bytes=600)
    for i in range(12):
        wh.append_rows([make_row(ts=float(i), run="r", source="report",
                                 metric=f"timer.t{i}", value=1.0)])
    assert os.path.exists(wh.segment_path + ".1")
    assert not os.path.exists(wh.segment_path + ".2")
    # reads span the sealed + live generations, newest row included
    rows = wh.rows()
    assert rows[-1]["metric"] == "timer.t11"
    assert len(rows) > len(open(wh.segment_path).readlines())
    # keep writing: the oldest generation is eventually dropped, but
    # the live + one sealed segment keep the store bounded
    for i in range(12, 40):
        wh.append_rows([make_row(ts=float(i), run="r", source="report",
                                 metric=f"timer.t{i}", value=1.0)])
    sizes = [os.path.getsize(p) for p in
             (wh.segment_path, wh.segment_path + ".1")
             if os.path.exists(p)]
    assert sum(sizes) < 4 * 600
    assert wh.rows()[-1]["metric"] == "timer.t39"


def test_io_failure_latches_with_typed_event(tmp_path):
    """An unwritable root warns once (typed) and latches off — the
    warehouse must never kill the run that feeds it."""
    path = tmp_path / "not-a-dir"
    path.write_text("a file where the warehouse dir should be")
    wh = Warehouse(str(path))
    with pytest.warns(UserWarning, match="warehouse disabled"):
        assert wh.append_rows(_rows(1)) == 0
    # latched: no second warning, still refusing quietly
    assert wh.append_rows(_rows(1)) == 0


def test_reindex_rebuilds_from_segments(tmp_path):
    wh = Warehouse(str(tmp_path / "wh"))
    wh.append_rows(_rows(4))
    os.remove(wh.index_path)
    idx = wh.index()
    assert idx["rows_total"] == 4
    assert idx["runs"]["r1"]["rows"] == 4


# --------------------------------------------------------------------------
# ingest flatteners
# --------------------------------------------------------------------------

def test_ingest_run_report_flattens_all_streams(tmp_path):
    wh = Warehouse(str(tmp_path / "wh"))
    report = load_report(REPORT_R5)
    assert wh.ingest_run_report(report, run="r5") > 0
    spans = wh.rows(source="span", metric="span.device_s")
    by_stage = {r["stage"]: r["value"] for r in spans}
    assert by_stage["sort"] == pytest.approx(0.0642)
    assert by_stage["jit_shard_fn"] == pytest.approx(0.0999)
    # every row carries the geometry fingerprint + device kind key
    assert all(r["geometry"] and r["device_kind"] for r in spans)
    util = wh.rows(source="roofline", stage="peaks",
                   metric="roofline.utilization")
    assert [r["value"] for r in util] == [pytest.approx(0.31)]
    assert wh.rows(metric="jit.backend_compiles")[0]["value"] == 41
    assert wh.rows(metric="candidates.count")[0]["value"] == 42


def test_ingest_history_and_geometry_fingerprint(tmp_path):
    wh = Warehouse(str(tmp_path / "wh"))
    records = load_history(HISTORY_FIXTURE, kinds=("bench",))
    assert wh.ingest_history(records) > 0
    stage_rows = wh.rows(metric="stage.device_s", stage="peaks")
    assert len(stage_rows) == len(records)
    fps = {r["geometry"] for r in stage_rows}
    assert fps == {geometry_fingerprint(
        records[0]["config"]["geometry"])}
    # distinct geometry -> distinct fingerprint (the attribution key)
    other = dict(records[0]["config"]["geometry"], n_dm_trials=999)
    assert geometry_fingerprint(other) not in fps


def test_ingest_telemetry_shards(tmp_path):
    ts_dir = str(tmp_path / "fleet")
    os.makedirs(ts_dir)
    sample = {"v": 1, "ts": 100.0, "host": "h0", "pid": 1, "seq": 0,
              "interval_s": 5.0,
              "counters": {"scheduler.claimed": 2},
              "timers": {"peaks": {"device_s": 0.25}},
              "gauges": {"scheduler.jobs_per_hour": 120.0}}
    with open(os.path.join(ts_dir, "ts-h0.jsonl"), "w") as f:
        f.write(json.dumps(sample) + "\n")
    wh = Warehouse(str(tmp_path / "wh"))
    assert wh.ingest_telemetry(ts_dir) == 3
    assert wh.rows(metric="counter.scheduler.claimed")[0]["value"] == 2
    assert wh.rows(metric="stage.device_s")[0]["stage"] == "peaks"
    assert wh.rows(metric="gauge.")[0]["value"] == 120.0


# --------------------------------------------------------------------------
# baseline plane: robust stats, determinism, the acceptance case
# --------------------------------------------------------------------------

def test_robust_stats_and_band():
    med, mad = robust_stats([1.0, 1.1, 0.9, 1.0, 5.0])
    assert med == 1.0
    assert mad == pytest.approx(0.1)  # the outlier does not poison it
    med, half = baseline_band([1.0] * 6, z=4.0, floor_frac=0.4)
    assert (med, half) == (1.0, pytest.approx(0.4))  # MAD=0 -> floor


def test_detect_point_directions():
    window = [1.0, 1.01, 0.99, 1.0, 1.02, 0.98]
    key = {"stage": "peaks", "geometry": "g", "device_kind": "cpu",
           "host": ""}
    anom = detect_point(2.0, window, ts=9.0, key=key,
                        metric="stage.device_s", z=4.0,
                        floor_frac=0.4)
    assert anom["kind"] == "anomaly"
    assert anom["direction"] == "high"
    assert anom["ts"] == 9.0  # the offending point's ts, not "now"
    assert detect_point(1.05, window, ts=9.0, key=key,
                        metric="stage.device_s", z=4.0,
                        floor_frac=0.4) is None
    # higher_is_better inverts the offending direction
    assert detect_point(2.0, window, ts=9.0, key=key, metric="m",
                        z=4.0, floor_frac=0.4,
                        higher_is_better=True) is None
    low = detect_point(0.2, window, ts=9.0, key=key, metric="m",
                       z=4.0, floor_frac=0.4, higher_is_better=True)
    assert low["direction"] == "low"


def test_fixture_history_is_clean_and_deterministic():
    """The checked-in ledger yields no anomalies, and two independent
    evaluations are byte-identical — the gate's verdict is a pure
    function of checked-in history."""
    records = load_history(HISTORY_FIXTURE, kinds=("bench",))
    assert len(records) == 8
    first = history_anomalies(records)
    second = history_anomalies(
        load_history(HISTORY_FIXTURE, kinds=("bench",)))
    assert first == []
    assert json.dumps(first, sort_keys=True) == \
        json.dumps(second, sort_keys=True)
    table = baseline_table(records)
    assert [r["stage"] for r in table] == \
        ["dedisperse", "fold", "harmonics", "peaks", "spectrum"]
    assert json.dumps(table, sort_keys=True) == json.dumps(
        baseline_table(load_history(HISTORY_FIXTURE,
                                    kinds=("bench",))),
        sort_keys=True)


def _slowed_history(factor=2.0, stage="peaks"):
    records = load_history(HISTORY_FIXTURE, kinds=("bench",))
    head = copy.deepcopy(records[-1])
    head["stage_device_s"][stage] *= factor
    head["metrics"]["peaks_device_s"] = head["stage_device_s"][stage]
    return records[:-1] + [head]


def test_synthetic_slowdown_yields_exactly_one_attributed_anomaly():
    """The ISSUE 16 acceptance case: double ONE stage's device time in
    the newest round — exactly one anomaly, attributed to that
    (stage, geometry, device-kind) key, severity crit (>2 bands out),
    while every other stage stays clean."""
    records = _slowed_history(2.0, "peaks")
    anomalies = history_anomalies(records)
    assert len(anomalies) == 1
    (anom,) = anomalies
    assert anom["kind"] == "anomaly"
    assert anom["key"]["stage"] == "peaks"
    assert anom["key"]["geometry"] == geometry_fingerprint(
        records[0]["config"]["geometry"])
    assert anom["key"]["device_kind"] == "cpu"
    assert anom["metric"] == "stage_device_s"
    assert anom["severity"] == "crit"
    assert anom["value"] > anom["median"] + anom["band"]
    assert anom["ts"] == records[-1]["ts"]


def test_slowdown_trips_gate_but_fixture_history_passes():
    """The baseline-aware perf gate on the same evidence: unmodified
    checked-in history passes; the 2x head trips it."""
    from peasoup_tpu.tools.perf_report import regression_gate

    clean = load_history(HISTORY_FIXTURE, kinds=("bench",))
    code, msg = regression_gate(clean, metric="peaks_device_s")
    assert code == 0 and "OK gate" in msg
    code, msg = regression_gate(_slowed_history(2.0, "peaks"),
                                metric="peaks_device_s")
    assert code == 1 and "REGRESSION" in msg


def test_write_anomalies_round_trips_through_ledger(tmp_path):
    ledger = str(tmp_path / "h.jsonl")
    anomalies = history_anomalies(_slowed_history(2.0, "peaks"))
    assert write_anomalies(anomalies, ledger) == 1
    (rec,) = load_history(ledger, kinds=("anomaly",))
    assert rec == anomalies[0]  # verbatim: ts preserved, no restamp


def test_fleet_presence_anomalies_emitted_then_cleared(tmp_path):
    """The chaos harness's signal, offline: two hosts sample steadily,
    one goes silent mid-window (SIGKILL), capacity returns — the
    silent bins are flagged, the recovered tail is clean."""
    ts_dir = str(tmp_path / "fleet")
    os.makedirs(ts_dir)
    t0 = 1000.0
    for host in ("h0", "h1"):
        with open(os.path.join(ts_dir, f"ts-{host}.jsonl"), "w") as f:
            for i in range(40):
                ts = t0 + i * 0.5
                if host == "h1" and 10.0 <= ts - t0 < 14.0:
                    continue  # the kill window: h1's shard is silent
                f.write(json.dumps(
                    {"v": 1, "ts": ts, "host": host, "pid": 1,
                     "seq": i, "interval_s": 0.5, "counters": {},
                     "timers": {}, "gauges": {}}) + "\n")
    anomalies = fleet_presence_anomalies(ts_dir, t_start=t0,
                                         t_end=t0 + 20.0)
    assert anomalies, "kill window must be flagged"
    assert all(10.0 <= a["ts"] - t0 < 14.0 for a in anomalies)
    assert all(a["key"]["stage"] == "presence"
               and a["key"]["host"] == "fleet" for a in anomalies)
    assert all(a["direction"] == "low" for a in anomalies)
    # the recovered tail (both hosts sampling again) is clean — the
    # emitted-then-cleared lifecycle the chaos harness asserts live


# --------------------------------------------------------------------------
# structural diff: the checked-in trace summary is reproducible
# --------------------------------------------------------------------------

def test_diff_reproduces_checked_in_trace_summary():
    """`obs diff` over the two checked-in fixture reports REGENERATES
    benchmarks/trace_summary_r6.md byte-for-byte — run-to-run
    attribution is mechanical, not hand-written prose."""
    diff = diff_reports(load_report(REPORT_R5), load_report(REPORT_R6),
                        label_a="benchmarks/fixtures/run_report_r5"
                                ".json",
                        label_b="benchmarks/fixtures/run_report_r6"
                                ".json")
    with open(TRACE_SUMMARY_R6) as f:
        assert render_markdown(diff) == f.read()


def test_diff_headline_figures():
    diff = diff_reports(load_report(REPORT_R5), load_report(REPORT_R6))
    assert diff["e2e_s"]["a"] == pytest.approx(0.370)
    assert diff["e2e_s"]["b"] == pytest.approx(0.317)
    assert diff["compiles"]["delta"] == -4
    assert diff["geometry"]["same"] is True
    spans = diff["spans"]
    assert spans["sort"]["delta"] == pytest.approx(-0.0642)
    assert spans["sort"]["count_b"] == 0
    assert spans["jit_shard_fn"]["delta"] == pytest.approx(-0.0581)
    assert spans["peaks_compact"]["new"] is True
    # movers are ranked by |delta|: the sort elimination leads
    text = render_markdown(diff)
    first_mover = [ln for ln in text.splitlines()
                   if ln.startswith("|") and "sort" in ln][0]
    assert "-64.2" in first_mover
    assert "0.370 s -> 0.317 s" in text
    assert "41 -> 37 (-4)" in text


def test_diff_bench_records_same_shape():
    a, b = load_history(HISTORY_FIXTURE, kinds=("bench",))[-2:]
    b = copy.deepcopy(b)
    b["stage_device_s"]["peaks"] *= 2
    diff = diff_bench_records(a, b, label_a="r1", label_b="r2")
    assert diff["labels"] == ["r1", "r2"]
    assert diff["stages"]["peaks"]["ratio"] == pytest.approx(2.0,
                                                             rel=0.1)
    assert "peaks" in render_markdown(diff)


# --------------------------------------------------------------------------
# host rollup + sparkline (status --watch columns)
# --------------------------------------------------------------------------

def test_host_rollup_duty_util_and_trend(tmp_path):
    ts_dir = str(tmp_path / "fleet")
    os.makedirs(ts_dir)
    with open(os.path.join(ts_dir, "ts-h0.jsonl"), "w") as f:
        for i in range(5):
            f.write(json.dumps(
                {"v": 1, "ts": 100.0 + i, "host": "h0", "pid": 1,
                 "seq": i, "interval_s": 1.0, "counters": {},
                 "timers": {"peaks": {"device_s": 0.5}},
                 "gauges": {"scheduler.jobs_per_hour": 60.0 + i,
                            "hbm.budget_bytes": 100.0,
                            "hbm.high_water_bytes": 25.0}}) + "\n")
    rollup = host_rollup(ts_dir, now=105.0)
    ent = rollup["h0"]
    assert ent["duty"] == pytest.approx(2.5 / 4.0)
    assert ent["util"] == pytest.approx(0.25)
    assert ent["jobs_per_hour"] == [60.0, 61.0, 62.0, 63.0, 64.0]
    assert ent["last_ts"] == 104.0
    assert len(sparkline(ent["jobs_per_hour"])) == 5


def test_sparkline_shapes():
    assert sparkline([]) == ""
    assert sparkline([1.0]) == "▁"
    line = sparkline([0, 1, 2, 3, 4, 5, 6, 7])
    assert line[0] == "▁" and line[-1] == "█"
    assert len(sparkline(list(range(100)), width=24)) == 24


def test_status_watch_renders_rollup_columns(tmp_path, capsys):
    """``status --watch`` joins the fleet table with the warehouse
    rollup: duty/util/jobs-h-trend columns appear per host."""
    import time as _time

    from peasoup_tpu.serve import FleetMembership, FleetWorker, JobSpool
    from peasoup_tpu.serve.cli import build_parser, cmd_status

    spool_dir = str(tmp_path / "jobs")
    spool = JobSpool(spool_dir)
    w = FleetWorker(spool, FleetMembership.fake(0, 1, "host-0"))
    w.write_host_status({"claimed": 1, "succeeded": 1, "failed": 0})
    ts_dir = os.path.join(spool_dir, "fleet")
    now = _time.time()
    with open(os.path.join(ts_dir, "ts-host-0.jsonl"), "w") as f:
        for i in range(3):
            f.write(json.dumps(
                {"v": 1, "ts": now - 3 + i, "host": "host-0",
                 "pid": 1, "seq": i, "interval_s": 1.0,
                 "counters": {}, "timers": {},
                 "gauges": {"scheduler.jobs_per_hour": 10.0 * i}})
                + "\n")
    args = build_parser().parse_args(
        ["--spool", spool_dir, "status", "--watch",
         "--interval", "0.01", "--iterations", "1"])
    rc = cmd_status(spool, args, sleeper=lambda s: None,
                    clock=lambda: now)
    out = capsys.readouterr().out
    assert rc == 0
    assert "duty" in out and "util" in out and "jobs/h trend" in out
    (line,) = [ln for ln in out.splitlines()
               if ln.startswith("host-0")]
    assert "▁" in line  # the sparkline rendered


# --------------------------------------------------------------------------
# the obs CLI verb family
# --------------------------------------------------------------------------

def _obs(argv):
    from peasoup_tpu.cli import main

    return main(["obs"] + argv)


def test_cli_ingest_query_top_tail(tmp_path, capsys):
    wh_dir = str(tmp_path / "wh")
    rc = _obs(["ingest", "--dir", wh_dir, "--report", REPORT_R5,
               "--report", REPORT_R6, "--ledger", HISTORY_FIXTURE])
    assert rc == 0
    assert "ingested" in capsys.readouterr().out
    rc = _obs(["query", "--dir", wh_dir, "--metric", "span.device_s",
               "--stage", "sort", "--json"])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out)
    # only r5 has a sort span (r6 eliminated it entirely)
    assert [r["value"] for r in doc["rows"]] == [0.0642]
    rc = _obs(["top", "--dir", wh_dir, "-n", "1",
               "--metric", "span.device_s", "--json"])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["rows"][0]["stage"] == "jit_shard_fn"
    rc = _obs(["tail", "--dir", wh_dir, "-n", "3"])
    assert rc == 0
    assert "(3 row(s))" in capsys.readouterr().out


def test_cli_diff_writes_markdown(tmp_path, capsys):
    out = str(tmp_path / "summary.md")
    rc = _obs(["diff", REPORT_R5, REPORT_R6, "--out", out])
    assert rc == 0
    with open(out) as f:
        text = f.read()
    assert "0.370 s -> 0.317 s" in text
    assert "| 64.2 | 0.0 | -64.2 | 0.00x | 885->0 | sort |" in text
    rc = _obs(["diff", REPORT_R5])
    assert rc == 2  # one path is unusable input


def test_cli_baseline_exit_codes(tmp_path, capsys):
    rc = _obs(["baseline", "--ledger", HISTORY_FIXTURE])
    assert rc == 0
    assert "ANOMALY" not in capsys.readouterr().out
    # a doctored copy with a 2x head must exit 1 and name the stage
    doctored = str(tmp_path / "h.jsonl")
    records = _slowed_history(2.0, "peaks")
    with open(doctored, "w") as f:
        for rec in records:
            f.write(json.dumps(rec, sort_keys=True) + "\n")
    rc = _obs(["baseline", "--ledger", doctored, "--write-ledger"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "ANOMALY peaks" in out
    assert load_history(doctored, kinds=("anomaly",))


# --------------------------------------------------------------------------
# events.jsonl rotation (satellite: bounded per-job event logs)
# --------------------------------------------------------------------------

def test_event_log_rotates_past_byte_budget(tmp_path):
    from peasoup_tpu.obs.events import EventLog

    path = str(tmp_path / "events.jsonl")
    log = EventLog(path, max_log_bytes=400, flood_limit=10_000)
    for i in range(50):
        log.emit("spin", f"event {i}")
    log.close()
    assert os.path.exists(path + ".1")
    assert os.path.getsize(path) < 400
    # both generations hold only intact JSON lines
    kept = 0
    for gen in (path + ".1", path):
        with open(gen) as f:
            for line in f:
                assert json.loads(line)["kind"] == "spin"
                kept += 1
    assert 0 < kept < 50  # bounded: older generations were dropped


def test_event_log_rotation_disabled_with_zero_budget(tmp_path):
    from peasoup_tpu.obs.events import EventLog

    path = str(tmp_path / "events.jsonl")
    log = EventLog(path, max_log_bytes=0, flood_limit=10_000)
    for i in range(50):
        log.emit("spin", f"event {i}")
    log.close()
    assert not os.path.exists(path + ".1")
    with open(path) as f:
        assert sum(1 for _ in f) == 50
