"""Health-plane tests: every rule's ok/warn/crit fixtures over
literal-dict contexts, the SLO summary's banding, crash-isolated rule
evaluation, the ``health`` / ``status --watch`` verbs, fleet_report v2
embedding, and a faked 3-host fleet draining with live telemetry —
all WITHOUT real multihost (FleetMembership.fake)."""

import json
import os

import pytest

from peasoup_tpu.obs.history import append_history, make_history_record
from peasoup_tpu.obs.metrics import REGISTRY
from peasoup_tpu.serve import (
    BackoffPolicy,
    FleetMembership,
    FleetWorker,
    HealthContext,
    HealthFinding,
    JobSpool,
    build_context,
    evaluate,
    evaluate_spool,
    fleet_report,
)
from peasoup_tpu.serve.health import (
    CRIT,
    OK,
    RULES,
    WARN,
    format_findings,
    rule_anomaly,
    rule_compile_storm,
    rule_device_duty_cycle,
    rule_hbm_watermark,
    rule_lease_reap_burst,
    rule_loadgen_saturation,
    rule_queue_backlog,
    rule_retry_spike,
    rule_stale_host,
    rule_throughput_regression,
    slo_summary,
    worst_severity,
)

NOW = 100000.0


@pytest.fixture(autouse=True)
def _fresh_registry():
    REGISTRY.reset()
    yield
    REGISTRY.reset()


def _sample(host, ts, *, counters=None, gauges=None, timers=None,
            queue=None, interval_s=5.0):
    rec = {"v": 1, "ts": ts, "host": host, "pid": 1, "seq": 1,
           "interval_s": interval_s, "counters": counters or {},
           "timers": timers or {}, "gauges": gauges or {}}
    if queue is not None:
        rec["queue"] = queue
    return rec


def _ctx(samples=(), *, queue=None, running=(), ledger=(),
         now=NOW, **kw):
    samples = sorted(samples, key=lambda s: s["ts"])
    latest = {}
    for s in samples:
        latest[s["host"]] = s
    return HealthContext(
        now=now, samples=samples,
        recent=[s for s in samples if s["ts"] >= now - 300.0],
        latest=latest,
        queue=queue or {"pending": 0, "running": 0, "done": 0,
                        "failed": 0},
        running=list(running), ledger=list(ledger), **kw)


def _by_sev(findings):
    return worst_severity(f.severity for f in findings)


# --------------------------------------------------------------------------
# rule: stale_host
# --------------------------------------------------------------------------

def test_stale_host_ok_when_fresh():
    ctx = _ctx([_sample("h0", NOW - 3.0)])
    assert _by_sev(rule_stale_host(ctx)) == OK


def test_stale_host_crit_while_holding_leases():
    ctx = _ctx([_sample("h0", NOW - 120.0)],
               running=[{"job_id": "j1", "host": "h0"}])
    (f,) = rule_stale_host(ctx)
    assert (f.severity, f.host) == (CRIT, "h0")
    assert "requeue --expired" in f.message
    assert f.data["leases"] == 1


def test_stale_host_crit_for_leaseholder_without_any_shard():
    """A host that died before its first sample still trips crit via
    its lease (age is infinite, threshold finite)."""
    ctx = _ctx([], running=[{"job_id": "j1", "host": "ghost"}])
    found = {f.host: f for f in rule_stale_host(ctx)}
    assert found["ghost"].severity == CRIT
    assert found["ghost"].data["age_s"] is None


def test_stale_host_warn_with_pending_work_waiting():
    ctx = _ctx([_sample("h0", NOW - 120.0)],
               queue={"pending": 4, "running": 0, "done": 0,
                      "failed": 0})
    (f,) = rule_stale_host(ctx)
    assert f.severity == WARN and "4 pending" in f.message


def test_stale_host_ok_after_clean_departure():
    """Silent + no leases + empty queue = drained worker exited; the
    fleet must report healthy again after recovery."""
    ctx = _ctx([_sample("h0", NOW - 120.0)])
    (f,) = rule_stale_host(ctx)
    assert f.severity == OK and "departed cleanly" in f.message


def test_stale_host_threshold_scales_with_sample_interval():
    # 60s-old sample at interval 30 is fresh (threshold 5*30=150s)...
    ctx = _ctx([_sample("h0", NOW - 60.0, interval_s=30.0)],
               running=[{"job_id": "j", "host": "h0"}])
    assert _by_sev(rule_stale_host(ctx)) == OK
    # ...the same age at interval 5 is stale (threshold 25s)
    ctx = _ctx([_sample("h0", NOW - 60.0, interval_s=5.0)],
               running=[{"job_id": "j", "host": "h0"}])
    assert _by_sev(rule_stale_host(ctx)) == CRIT


def test_stale_host_no_shards_no_leases_is_vacuous_ok():
    (f,) = rule_stale_host(_ctx([]))
    assert f.severity == OK and "no telemetry" in f.message


# --------------------------------------------------------------------------
# rule: queue_backlog
# --------------------------------------------------------------------------

def _queue_series(depths, counters=None):
    return [_sample("h0", NOW - 300.0 + 10.0 * i,
                    queue={"pending": d}, counters=counters)
            for i, d in enumerate(depths)]


def test_queue_backlog_ok_when_stable_or_shrinking():
    assert _by_sev(rule_queue_backlog(_ctx(_queue_series(
        [5, 3, 1, 0])))) == OK
    assert _by_sev(rule_queue_backlog(_ctx(_queue_series(
        [2, 2, 2])))) == OK


def test_queue_backlog_insufficient_samples_is_ok():
    (f,) = rule_queue_backlog(_ctx(_queue_series([1, 9])))
    assert f.severity == OK and "insufficient" in f.message


def test_queue_backlog_warn_while_jobs_still_drain():
    samples = _queue_series([1, 4, 8],
                            counters={"scheduler.succeeded": 1})
    (f,) = rule_queue_backlog(_ctx(samples))
    assert f.severity == WARN and f.data["grew"] == 7


def test_queue_backlog_crit_when_nothing_drains():
    (f,) = rule_queue_backlog(_ctx(_queue_series([1, 4, 8])))
    assert f.severity == CRIT and "ZERO" in f.message


# --------------------------------------------------------------------------
# rule: retry_spike
# --------------------------------------------------------------------------

def test_retry_spike_bands():
    ok = _ctx([_sample("h0", NOW, counters={"scheduler.retried": 1})])
    assert _by_sev(rule_retry_spike(ok)) == OK
    warn = _ctx([_sample("h0", NOW,
                         counters={"scheduler.retried": 3})])
    assert _by_sev(rule_retry_spike(warn)) == WARN
    warn2 = _ctx([_sample("h0", NOW,
                          counters={"scheduler.quarantined": 1})])
    assert _by_sev(rule_retry_spike(warn2)) == WARN
    crit = _ctx([_sample("h0", NOW,
                         counters={"scheduler.quarantined": 2,
                                   "scheduler.exhausted": 1})])
    assert _by_sev(rule_retry_spike(crit)) == CRIT
    crit2 = _ctx([_sample("h0", NOW,
                          counters={"scheduler.retried": 10})])
    assert _by_sev(rule_retry_spike(crit2)) == CRIT


def test_retry_spike_sums_across_hosts_and_window():
    samples = [
        _sample("h0", NOW - 10.0, counters={"scheduler.retried": 2}),
        _sample("h1", NOW - 5.0, counters={"scheduler.retried": 1}),
        # outside the 300s window: ignored
        _sample("h0", NOW - 400.0,
                counters={"scheduler.retried": 50}),
    ]
    (f,) = rule_retry_spike(_ctx(samples))
    assert f.severity == WARN and f.data["retried"] == 3


# --------------------------------------------------------------------------
# rule: compile_storm (ISSUE 18)
# --------------------------------------------------------------------------

def test_compile_storm_ok_without_samples_or_counters():
    assert _by_sev(rule_compile_storm(_ctx())) == OK
    fresh = _ctx([_sample("h0", NOW,
                          counters={"jit.compiles_attributed": 40})])
    # cold compiles of NEW geometry are expected work, not a storm
    (f,) = rule_compile_storm(fresh)
    assert f.severity == OK
    assert f.data["compiles_attributed"] == 40
    assert f.data["recompiles_seen_geometry"] == 0


def test_compile_storm_bands():
    ok = _ctx([_sample("h0", NOW,
                       counters={"jit.recompiles_seen_geometry": 2})])
    assert _by_sev(rule_compile_storm(ok)) == OK
    warn = _ctx([_sample("h0", NOW,
                         counters={"jit.recompiles_seen_geometry": 3})])
    (f,) = rule_compile_storm(warn)
    assert f.severity == WARN
    crit = _ctx([_sample("h0", NOW,
                         counters={"jit.recompiles_seen_geometry": 10})])
    (f,) = rule_compile_storm(crit)
    assert f.severity == CRIT
    assert "obs compiles" in f.message


def test_compile_storm_sums_hosts_and_ages_out():
    samples = [
        _sample("h0", NOW - 10.0,
                counters={"jit.recompiles_seen_geometry": 2}),
        _sample("h1", NOW - 5.0,
                counters={"jit.recompiles_seen_geometry": 1}),
        # outside the 300s window: a storm that already blew over
        _sample("h0", NOW - 400.0,
                counters={"jit.recompiles_seen_geometry": 50}),
    ]
    (f,) = rule_compile_storm(_ctx(samples))
    assert f.severity == WARN
    assert f.data["recompiles_seen_geometry"] == 3


def test_compile_storm_registered_in_rule_set():
    assert rule_compile_storm in RULES


# --------------------------------------------------------------------------
# rule: throughput_regression
# --------------------------------------------------------------------------

def _ledger(values):
    return [{"kind": "serve", "metrics": {"jobs_per_hour": v}}
            for v in values]


def test_throughput_vacuous_ok_without_baseline():
    ctx = _ctx([_sample("h0", NOW, gauges={
        "scheduler.jobs_per_hour": 1.0})], ledger=_ledger([10.0, 12.0]))
    (f,) = rule_throughput_regression(ctx)
    assert f.severity == OK and "not enough" in f.message


def test_throughput_ok_without_live_gauge():
    ctx = _ctx([_sample("h0", NOW)],
               ledger=_ledger([10.0, 12.0, 14.0]))
    (f,) = rule_throughput_regression(ctx)
    assert f.severity == OK and "no live" in f.message


def test_throughput_bands_vs_ledger_median():
    ledger = _ledger([10.0, 12.0, 14.0])  # median 12
    mk = lambda jph: _ctx(
        [_sample("h0", NOW, gauges={"scheduler.jobs_per_hour": jph})],
        ledger=ledger)
    assert _by_sev(rule_throughput_regression(mk(11.0))) == OK
    assert _by_sev(rule_throughput_regression(mk(4.0))) == WARN
    assert _by_sev(rule_throughput_regression(mk(2.0))) == CRIT


def test_throughput_sums_fleet_hosts():
    """Per-host gauges are summed: two hosts at 2 jobs/h each make a
    4 jobs/h fleet, under half the 12 jobs/h ledger median -> warn."""
    ledger = _ledger([10.0, 12.0, 14.0])
    ctx = _ctx([
        _sample("h0", NOW, gauges={"scheduler.jobs_per_hour": 2.0}),
        _sample("h1", NOW, gauges={"scheduler.jobs_per_hour": 2.0}),
    ], ledger=ledger)
    (f,) = rule_throughput_regression(ctx)
    assert f.severity == WARN
    assert f.data["current_jobs_per_hour"] == 4.0


# --------------------------------------------------------------------------
# rule: hbm_watermark
# --------------------------------------------------------------------------

def _hbm_ctx(frac):
    return _ctx([_sample("h0", NOW, gauges={
        "hbm.high_water_bytes": frac * 1000.0,
        "hbm.budget_bytes": 1000.0})])


def test_hbm_watermark_bands():
    assert _by_sev(rule_hbm_watermark(_hbm_ctx(0.5))) == OK
    assert _by_sev(rule_hbm_watermark(_hbm_ctx(0.95))) == WARN
    assert _by_sev(rule_hbm_watermark(_hbm_ctx(0.99))) == CRIT


def test_hbm_watermark_unknown_is_not_unhealthy():
    (f,) = rule_hbm_watermark(_ctx([_sample("h0", NOW)]))
    assert f.severity == OK and "no HBM budget" in f.message


# --------------------------------------------------------------------------
# rule: lease_reap_burst
# --------------------------------------------------------------------------

def test_lease_reap_bands():
    mk = lambda n: _ctx([_sample("h0", NOW, counters={
        "scheduler.lease_reaped": n})] if n else [_sample("h0", NOW)])
    assert _by_sev(rule_lease_reap_burst(mk(0))) == OK
    assert _by_sev(rule_lease_reap_burst(mk(1))) == WARN
    assert _by_sev(rule_lease_reap_burst(mk(3))) == CRIT


# --------------------------------------------------------------------------
# rule: device_duty_cycle
# --------------------------------------------------------------------------

def _duty_ctx(duty, pending):
    return _ctx(
        [_sample("h0", NOW, gauges={"device_duty_cycle": duty})],
        queue={"pending": pending, "running": 0, "done": 0,
               "failed": 0})


def test_device_duty_cycle_bands_with_queued_work():
    assert _by_sev(rule_device_duty_cycle(_duty_ctx(0.8, 5))) == OK
    assert _by_sev(rule_device_duty_cycle(_duty_ctx(0.35, 5))) == WARN
    assert _by_sev(rule_device_duty_cycle(_duty_ctx(0.05, 5))) == CRIT


def test_device_duty_cycle_idle_queue_is_ok():
    # a starved gauge with NOTHING queued is idle by design, not a
    # stall — the rule must not page on a drained fleet
    (f,) = rule_device_duty_cycle(_duty_ctx(0.0, 0))
    assert f.severity == OK and "empty queue" in f.message


def test_device_duty_cycle_unknown_is_not_unhealthy():
    (f,) = rule_device_duty_cycle(
        _ctx([_sample("h0", NOW)],
             queue={"pending": 3, "running": 0, "done": 0,
                    "failed": 0}))
    assert f.severity == OK and "no device_duty_cycle" in f.message


# --------------------------------------------------------------------------
# SLO summary
# --------------------------------------------------------------------------

def _slo_ctx(queue_wait_mean, n=4, job_mean=1.0):
    timers = {
        "queue_wait": {"count": n, "host_s": queue_wait_mean * n,
                       "device_s": 0.0},
        "job": {"count": n, "host_s": job_mean * n, "device_s": 0.0},
    }
    return _ctx([_sample("h0", NOW, timers=timers)])


def test_slo_no_data_counts_as_ok():
    s = slo_summary(_ctx([_sample("h0", NOW)]))
    assert s["status"] == OK
    assert s["metrics"]["queue_wait"]["status"] == "no_data"


def test_slo_bands_against_targets():
    ok = slo_summary(_slo_ctx(1.0))
    assert ok["status"] == OK
    assert ok["metrics"]["queue_wait"]["p50_s"] == pytest.approx(1.0)
    warn = slo_summary(_slo_ctx(90.0))  # > 60s p50 target
    assert warn["status"] == WARN
    crit = slo_summary(_slo_ctx(200.0))  # > 2x target
    assert crit["status"] == CRIT


def test_slo_custom_targets_and_weighted_percentiles():
    # two samples: 10 fast claims at 1s, 1 slow at 100s
    timers_fast = {"queue_wait": {"count": 10, "host_s": 10.0,
                                  "device_s": 0.0}}
    timers_slow = {"queue_wait": {"count": 1, "host_s": 100.0,
                                  "device_s": 0.0}}
    ctx = _ctx([_sample("h0", NOW - 10, timers=timers_fast),
                _sample("h1", NOW - 5, timers=timers_slow)],
               slo={"queue_wait_p50_s": 0.5, "queue_wait_p95_s": 600.0,
                    "job_p50_s": 900.0, "job_p95_s": 3600.0})
    s = slo_summary(ctx)
    m = s["metrics"]["queue_wait"]
    assert m["p50_s"] == pytest.approx(1.0)  # weight-dominant mean
    assert m["p95_s"] == pytest.approx(100.0)
    assert m["n"] == 11
    assert m["status"] == WARN  # over the 0.5s target, under 2x it


# --------------------------------------------------------------------------
# evaluate: rule isolation, report schema, breach folding
# --------------------------------------------------------------------------

def test_evaluate_report_schema_and_ok_fleet():
    report = evaluate(_ctx([_sample("h0", NOW - 1.0)]))
    assert report["v"] == 1 and report["severity"] == OK
    assert report["hosts"] == ["h0"]
    rules = {f["rule"] for f in report["findings"]}
    assert {"stale_host", "queue_backlog", "retry_spike",
            "throughput_regression", "hbm_watermark",
            "lease_reap_burst"} <= rules
    text = format_findings(report)
    assert "fleet severity: ok" in text
    assert "[SLO ]" in text


def test_evaluate_folds_slo_breach_into_findings():
    report = evaluate(_slo_ctx(200.0))
    breach = [f for f in report["findings"]
              if f["rule"] == "slo_breach"]
    assert len(breach) == 1 and breach[0]["severity"] == CRIT
    assert report["severity"] == CRIT


def test_crashing_rule_degrades_to_warn_finding():
    def _bad_rule(ctx):
        raise RuntimeError("kaboom")

    RULES.append(_bad_rule)
    try:
        report = evaluate(_ctx([_sample("h0", NOW)]))
    finally:
        RULES.remove(_bad_rule)
    errs = [f for f in report["findings"] if f["rule"] == "rule_error"]
    assert len(errs) == 1 and errs[0]["severity"] == WARN
    assert "kaboom" in errs[0]["message"]
    # one bad rule never masks the others
    assert any(f["rule"] == "stale_host" for f in report["findings"])


def test_finding_is_json_serialisable():
    f = HealthFinding("r", WARN, "m", host="h", data={"n": 1})
    assert json.loads(json.dumps(f.to_obj()))["host"] == "h"


# --------------------------------------------------------------------------
# rule: anomaly (the flight recorder's baseline plane, ISSUE 16)
# --------------------------------------------------------------------------

def _anomaly(ts, *, stage="peaks", host="", severity="warn"):
    return {"v": 1, "kind": "anomaly", "ts": ts,
            "key": {"stage": stage, "geometry": "abc123",
                    "device_kind": "cpu", "host": host},
            "metric": "stage.device_s", "value": 0.1, "median": 0.05,
            "mad": 0.001, "band": 0.02, "severity": severity}


def test_anomaly_ok_without_records():
    (f,) = rule_anomaly(_ctx())
    assert f.severity == OK
    assert f.data == {"recent": 0, "total": 0}


def test_anomaly_recent_record_warns_with_key():
    (f,) = rule_anomaly(_ctx(ledger=[_anomaly(NOW - 10.0)]))
    assert f.severity == WARN
    assert f.data["keys"] == ["peaks@fleet"]


def test_anomaly_crit_on_count_or_severity():
    burst = [_anomaly(NOW - 5.0 - i) for i in range(3)]
    (f,) = rule_anomaly(_ctx(ledger=burst))
    assert f.severity == CRIT
    (f,) = rule_anomaly(
        _ctx(ledger=[_anomaly(NOW - 5.0, severity="crit")]))
    assert f.severity == CRIT


def test_anomaly_ages_out_of_the_window():
    """Old anomaly records clear on their own — the emitted-then-
    cleared lifecycle the chaos harness asserts end to end."""
    (f,) = rule_anomaly(_ctx(ledger=[_anomaly(NOW - 301.0)]))
    assert f.severity == OK
    assert f.data == {"recent": 0, "total": 1}


def test_build_context_surfaces_anomaly_records(tmp_path):
    """The ledger loader keeps ``kind:"anomaly"`` records so the rule
    sees what ``obs.baseline.write_anomalies`` appended."""
    ledger = str(tmp_path / "h.jsonl")
    append_history(_anomaly(NOW - 1.0), ledger)
    spool = JobSpool(str(tmp_path / "jobs"))
    ctx = build_context(spool, ledger_path=ledger, now=NOW)
    (f,) = rule_anomaly(ctx)
    assert f.severity == WARN


# --------------------------------------------------------------------------
# build_context from a real spool
# --------------------------------------------------------------------------

def test_build_context_reads_spool_shards_and_ledger(tmp_path):
    spool = JobSpool(str(tmp_path / "jobs"))
    spool.submit("/tmp/a.fil")
    spool.claim("w0", host="host-0")
    ledger = str(tmp_path / "h.jsonl")
    append_history(make_history_record(
        "serve", {"jobs_per_hour": 33.0}), ledger)
    append_history(make_history_record("bench", {"e2e_s": 1.0}),
                   ledger)
    from peasoup_tpu.obs.telemetry import TelemetrySampler, shard_path
    s = TelemetrySampler(
        shard_path(os.path.join(spool.root, "fleet"), "host-0"),
        "host-0", 30.0)
    s.sample_now()
    ctx = build_context(spool, ledger_path=ledger, now=NOW,
                        window_s=1e9, slo={"job_p50_s": 7.0})
    assert ctx.queue["running"] == 1
    assert ctx.running == [{"job_id": spool.jobs("running")[0].job_id,
                            "host": "host-0"}]
    assert [r["metrics"]["jobs_per_hour"] for r in ctx.ledger] == \
        [33.0]  # kind-filtered
    assert "host-0" in ctx.latest
    assert ctx.slo["job_p50_s"] == 7.0
    assert ctx.slo["queue_wait_p50_s"] == 60.0  # defaults kept


# --------------------------------------------------------------------------
# CLI verbs: health, status --watch
# --------------------------------------------------------------------------

def test_health_verb_ok_fleet_exits_zero(tmp_path, capsys):
    from peasoup_tpu.serve.cli import main

    spool_dir = str(tmp_path / "jobs")
    JobSpool(spool_dir)
    rc = main(["--spool", spool_dir, "health",
               "--ledger", str(tmp_path / "h.jsonl")])
    out = capsys.readouterr().out
    assert rc == 0
    assert "fleet severity: ok" in out


def test_health_verb_crit_exits_nonzero_and_writes_json(tmp_path,
                                                        capsys):
    from peasoup_tpu.obs.telemetry import TelemetrySampler, shard_path
    from peasoup_tpu.serve.cli import main

    spool_dir = str(tmp_path / "jobs")
    spool = JobSpool(spool_dir)
    spool.submit("/tmp/a.fil")
    spool.claim("w0", host="host-0")  # lease held...
    s = TelemetrySampler(
        shard_path(os.path.join(spool_dir, "fleet"), "host-0"),
        "host-0", 0.05, clock=lambda: 1.0)  # ...by a long-dead host
    s.sample_now()
    out_json = str(tmp_path / "health.json")
    rc = main(["--spool", spool_dir, "health", "--json", out_json,
               "--ledger", str(tmp_path / "h.jsonl")])
    out = capsys.readouterr().out
    assert rc == 1
    assert "[CRIT] stale_host host-0" in out
    doc = json.load(open(out_json))
    assert doc["severity"] == "crit"
    assert any(f["rule"] == "stale_host" and f["severity"] == "crit"
               for f in doc["findings"])


def test_health_verb_rejects_non_numeric_slo(tmp_path):
    from peasoup_tpu.errors import ConfigError
    from peasoup_tpu.serve.cli import main

    spool_dir = str(tmp_path / "jobs")
    JobSpool(spool_dir)
    with pytest.raises(ConfigError, match="number of seconds"):
        main(["--spool", spool_dir, "health", "--slo",
              "queue_wait_p50_s=fast"])


def test_health_verb_custom_slo_trips_breach(tmp_path, capsys):
    from peasoup_tpu.obs.telemetry import TelemetrySampler, shard_path
    from peasoup_tpu.serve.cli import main

    spool_dir = str(tmp_path / "jobs")
    JobSpool(spool_dir)
    s = TelemetrySampler(
        shard_path(os.path.join(spool_dir, "fleet"), "host-0"),
        "host-0", 0.05)
    with REGISTRY.timer("queue_wait"):
        pass  # ~0s wait, but any positive wait beats a zero target
    s.sample_now()
    rc = main(["--spool", spool_dir, "health",
               "--slo", "queue_wait_p50_s=0", "--slo",
               "queue_wait_p95_s=0",
               "--ledger", str(tmp_path / "h.jsonl")])
    out = capsys.readouterr().out
    assert rc == 1  # 2x a zero target is a crit breach
    assert "slo_breach" in out


def test_status_watch_renders_table_and_health(tmp_path, capsys):
    """--watch with an injected sleeper runs N iterations without
    wall-clock waits and prints the health footer each frame."""
    from peasoup_tpu.serve.cli import build_parser, cmd_status

    spool_dir = str(tmp_path / "jobs")
    spool = JobSpool(spool_dir)
    spool.submit("/tmp/a.fil")
    worker = FleetWorker(
        spool, FleetMembership.fake(0, 1),
        run_job_fn=lambda job: {"candidates": 0},
        backoff=BackoffPolicy(max_attempts=2, base_s=0.0),
        history_path=str(tmp_path / "h.jsonl"),
        sleeper=lambda s: None, telemetry_interval_s=30.0)
    assert worker.drain()["succeeded"] == 1

    args = build_parser().parse_args(
        ["--spool", spool_dir, "status", "--watch",
         "--interval", "0.01", "--iterations", "3"])
    slept = []
    rc = cmd_status(spool, args, sleeper=slept.append,
                    clock=lambda: NOW)
    out = capsys.readouterr().out
    assert rc == 0
    assert len(slept) == 2  # N-1 pauses for N frames
    assert out.count("host-0") >= 3  # table re-rendered each frame
    assert "health:" in out
    assert "queue:" in out


def test_status_watch_stops_on_keyboard_interrupt(tmp_path, capsys):
    from peasoup_tpu.serve.cli import build_parser, cmd_status

    spool_dir = str(tmp_path / "jobs")
    spool = JobSpool(spool_dir)
    args = build_parser().parse_args(
        ["--spool", spool_dir, "status", "--watch",
         "--interval", "0.01"])  # no --iterations: forever

    def _interrupt(seconds):
        raise KeyboardInterrupt

    rc = cmd_status(spool, args, sleeper=_interrupt)
    assert rc == 0  # ctrl-c is a clean exit, not a traceback


# --------------------------------------------------------------------------
# fleet_report v2 + fake 3-host fleet end-to-end
# --------------------------------------------------------------------------

def test_fleet_report_v2_embeds_health(tmp_path):
    spool = JobSpool(str(tmp_path / "jobs"))
    report = fleet_report(spool)
    assert report["v"] == 2
    assert report["health"]["severity"] == OK
    assert {"severity", "findings", "slo"} <= set(report["health"])


def test_three_fake_hosts_drain_with_live_telemetry(tmp_path):
    """The ISSUE's e2e: a faked 3-host fleet drains with samplers on,
    every host leaves a ts- shard behind, the merged series carries
    queue depths + per-interval deltas, and the health verdict on the
    drained fleet is ok (hosts departed cleanly)."""
    import threading

    spool = JobSpool(str(tmp_path / "jobs"))
    for i in range(9):
        spool.submit(f"/tmp/{i}.fil")
    workers = [
        FleetWorker(
            spool, FleetMembership.fake(i, 3),
            run_job_fn=lambda job: {"candidates": 0},
            backoff=BackoffPolicy(max_attempts=2, base_s=0.0),
            history_path=str(tmp_path / "h.jsonl"),
            sleeper=lambda s: None, lease_ttl_s=60.0,
            telemetry_interval_s=0.05)
        for i in range(3)
    ]
    summaries = [None] * 3

    def _drain(i):
        summaries[i] = workers[i].drain()

    ts = [threading.Thread(target=_drain, args=(i,)) for i in range(3)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert sum(s["succeeded"] for s in summaries) == 9
    # every host's drain summary reports its sampler's work
    for s in summaries:
        assert s["telemetry"]["samples"] >= 2
        assert s["telemetry"]["overhead_s"] < 1.0
        assert os.path.exists(s["telemetry"]["shard"])

    from peasoup_tpu.obs.telemetry import read_samples, shard_hosts
    ts_dir = os.path.join(spool.root, "fleet")
    assert shard_hosts(ts_dir) == ["host-0", "host-1", "host-2"]
    samples = read_samples(ts_dir)
    assert all("queue" in s for s in samples)
    # every completion lands in the deltas (the fake fleet shares one
    # in-process registry, so each host's cursor also sees the other
    # hosts' increments; a real fleet is one process per host and each
    # shard then carries exactly its own — see the cursor tests)
    done = sum(s["counters"].get("scheduler.succeeded", 0)
               for s in samples)
    assert done >= 9
    # final samples carry the jobs_per_hour gauge set before stop()
    final = {s["host"]: s for s in samples}
    assert all(v["gauges"].get("scheduler.jobs_per_hour", 0) > 0
               for v in final.values())

    report = evaluate_spool(
        spool, ledger_path=str(tmp_path / "no-ledger.jsonl"))
    assert report["severity"] == OK
    assert sorted(report["hosts"]) == ["host-0", "host-1", "host-2"]
    # fleet_report v2 embeds the same verdict
    fr = fleet_report(spool)
    assert fr["v"] == 2 and fr["health"]["severity"] == OK


# --------------------------------------------------------------------------
# rule: loadgen_saturation (ISSUE 12)
# --------------------------------------------------------------------------

def _loadgen_rec(knee):
    return {"kind": "loadgen",
            "metrics": {"knee_throughput_per_s": knee}}


def _arrival_ctx(submits_per_sample, *, ledger):
    """Two shard samples spanning 100 s with per-sample submit deltas:
    arrival rate = 2 * submits_per_sample / 100."""
    return _ctx(
        [_sample("h0", NOW - 100.0,
                 counters={"scheduler.submitted": submits_per_sample}),
         _sample("h0", NOW,
                 counters={"scheduler.submitted": submits_per_sample})],
        ledger=ledger)


def test_loadgen_saturation_ok_without_baseline():
    """No loadgen sweep in the ledger is normal, not unhealthy."""
    (f,) = rule_loadgen_saturation(_arrival_ctx(50, ledger=[]))
    assert f.severity == OK
    assert f.data["knee_throughput_per_s"] is None
    assert "loadgen-smoke" in f.message


def test_loadgen_saturation_ok_under_the_knee():
    # 2 * 50 / 100s = 1.0/s against a 2.0/s knee -> ratio 0.5
    ctx = _arrival_ctx(50, ledger=[_loadgen_rec(2.0)])
    (f,) = rule_loadgen_saturation(ctx)
    assert f.severity == OK
    assert f.data["ratio"] == pytest.approx(0.5)


def test_loadgen_saturation_warn_above_knee():
    # 2 * 125 / 100s = 2.5/s -> ratio 1.25: growing, not yet runaway
    ctx = _arrival_ctx(125, ledger=[_loadgen_rec(2.0)])
    (f,) = rule_loadgen_saturation(ctx)
    assert f.severity == WARN
    assert f.data["arrival_rate_per_s"] == pytest.approx(2.5)


def test_loadgen_saturation_crit_and_newest_sweep_wins():
    # 2 * 200 / 100s = 4.0/s -> ratio 2.0 against the NEWEST knee;
    # the stale 100/s sweep earlier in the ledger must be ignored
    ctx = _arrival_ctx(200, ledger=[_loadgen_rec(100.0),
                                    _loadgen_rec(2.0)])
    (f,) = rule_loadgen_saturation(ctx)
    assert f.severity == CRIT
    assert f.data["knee_throughput_per_s"] == pytest.approx(2.0)
    assert "shed load" in f.message


def test_loadgen_saturation_nonpositive_knee_is_ok():
    """A sweep that never found a sustainable rate carries knee 0.0 —
    no usable baseline, so the rule stays quiet rather than dividing
    by zero."""
    ctx = _arrival_ctx(200, ledger=[_loadgen_rec(0.0)])
    (f,) = rule_loadgen_saturation(ctx)
    assert f.severity == OK
    assert f.data["knee_throughput_per_s"] == 0.0


def test_loadgen_saturation_registered_in_rule_set():
    assert rule_loadgen_saturation in RULES


# --------------------------------------------------------------------------
# rule: batch_mix (ISSUE 15 — the retune_batch action's sensor)
# --------------------------------------------------------------------------

def test_batch_mix_ok_without_pending_work():
    from peasoup_tpu.serve.health import rule_batch_mix

    ctx = _ctx([_sample("h0", NOW - 5.0)], pending_buckets={})
    (f,) = rule_batch_mix(ctx)
    assert f.severity == OK


def test_batch_mix_warns_on_dominant_bucket_with_suggestion():
    """A deep same-geometry bucket against batch=1 workers: warn with
    the retune hint the supervisor's retune_batch action applies
    (clamped to 8)."""
    from peasoup_tpu.serve.health import rule_batch_mix

    ctx = _ctx(
        [_sample("h0", NOW - 5.0, gauges={"search.batch": 1})],
        pending_buckets={"dm_end=20.0": 6, "dm_end=60.0": 1})
    (f,) = rule_batch_mix(ctx)
    assert f.severity == WARN
    assert f.data["suggest_batch"] == 6
    assert f.data["dominant_bucket"] == 6

    # a 20-deep bucket suggests at most 8
    ctx = _ctx(
        [_sample("h0", NOW - 5.0, gauges={"search.batch": 1})],
        pending_buckets={"dm_end=20.0": 20})
    (f,) = rule_batch_mix(ctx)
    assert f.severity == WARN and f.data["suggest_batch"] == 8


def test_batch_mix_warns_on_fragmented_underfill():
    """batch > 1 whose windowed mean fill collapsed: the batch wait is
    pure overhead, suggest stepping down toward the measured fill."""
    from peasoup_tpu.serve.health import rule_batch_mix

    ctx = _ctx(
        [_sample("h0", NOW - 5.0, gauges={"search.batch": 4},
                 counters={"scheduler.batched_dispatches": 4,
                           "scheduler.batch_fill": 4})],
        pending_buckets={"a": 2, "b": 1})
    (f,) = rule_batch_mix(ctx)
    assert f.severity == WARN
    assert f.data["suggest_batch"] == 1

    # healthy fill at the same batch: ok
    ctx = _ctx(
        [_sample("h0", NOW - 5.0, gauges={"search.batch": 4},
                 counters={"scheduler.batched_dispatches": 4,
                           "scheduler.batch_fill": 14})],
        pending_buckets={"a": 2, "b": 1})
    (f,) = rule_batch_mix(ctx)
    assert f.severity == OK
