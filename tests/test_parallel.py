"""Multi-device sharding tests on the virtual 8-device CPU mesh."""

import numpy as np
import jax
import pytest

from peasoup_tpu.io import read_filterbank
from peasoup_tpu.parallel.mesh import MeshPulsarSearch, make_mesh
from peasoup_tpu.search.pipeline import PulsarSearch
from peasoup_tpu.search.plan import SearchConfig


def test_virtual_mesh_has_8_devices():
    assert len(jax.devices()) == 8


def test_mesh_search_matches_single_device(tutorial_fil):
    fil = read_filterbank(tutorial_fil)
    # small config to keep runtime down: restricted DM range
    cfg = SearchConfig(
        dm_start=0.0, dm_end=60.0, acc_start=-5.0, acc_end=5.0,
        acc_pulse_width=64000.0, nharmonics=4, npdmp=0, limit=50,
    )
    single = PulsarSearch(fil, cfg).run()
    mesh = MeshPulsarSearch(fil, cfg).run()
    assert len(single.candidates) == len(mesh.candidates)
    for a, b in zip(single.candidates, mesh.candidates):
        assert a.freq == pytest.approx(b.freq, rel=1e-6)
        assert a.snr == pytest.approx(b.snr, rel=1e-5)
        assert a.dm == b.dm
        assert a.acc == b.acc
        assert a.count_assoc() == b.count_assoc()


def test_mesh_search_accepts_float32_filterbank(tmp_path):
    """The fused program's pack/unpack path must pass 32-bit (float)
    filterbanks straight through."""
    from peasoup_tpu.io.sigproc import (
        Filterbank, SigprocHeader, write_filterbank,
    )

    rng = np.random.default_rng(0)
    hdr = SigprocHeader(nbits=32, nchans=16, tsamp=0.000256, fch1=1510.0,
                        foff=-10.0, nsamples=4096)
    data = rng.normal(size=(4096, 16)).astype(np.float32)
    path = str(tmp_path / "f32.fil")
    write_filterbank(path, Filterbank(header=hdr, data=data))
    fil = read_filterbank(path)
    cfg = SearchConfig(dm_start=0.0, dm_end=20.0, npdmp=0, min_snr=6.0)
    single = PulsarSearch(fil, cfg).run()
    mesh = MeshPulsarSearch(fil, cfg).run()
    assert len(single.candidates) == len(mesh.candidates)
    for a, b in zip(single.candidates, mesh.candidates):
        assert a.freq == pytest.approx(b.freq, rel=1e-6)
        assert a.snr == pytest.approx(b.snr, rel=1e-5)


def test_sharded_dedispersion_matches(tutorial_fil):
    fil = read_filterbank(tutorial_fil)
    cfg = SearchConfig(dm_start=0.0, dm_end=30.0)
    single = PulsarSearch(fil, cfg)
    mesh = MeshPulsarSearch(fil, cfg)
    t_single = np.asarray(single.dedisperse())
    t_mesh = np.asarray(mesh.dedisperse_sharded())[: len(mesh.dm_list)]
    np.testing.assert_allclose(t_single, t_mesh, rtol=1e-6)


def test_chunked_search_matches_full_path(tutorial_fil):
    """Bounded-HBM chunked program (scan over DM chunks x accel blocks,
    peaks-only output, fold via candidate-row re-dedispersion) must
    reproduce the full-materialisation fused path exactly — including
    folded_snr/opt_period through the trials_provider."""
    fil = read_filterbank(tutorial_fil)
    cfg = SearchConfig(
        dm_start=0.0, dm_end=60.0, acc_start=-5.0, acc_end=5.0,
        acc_pulse_width=64000.0, nharmonics=4, npdmp=4, limit=50,
    )
    full = MeshPulsarSearch(fil, cfg).run()
    cfg_chunked = SearchConfig(
        dm_start=0.0, dm_end=60.0, acc_start=-5.0, acc_end=5.0,
        acc_pulse_width=64000.0, nharmonics=4, npdmp=4, limit=50,
        dm_chunk=2, accel_block=2,  # force chunking + ragged padding
        measure_stages=True,
    )
    chunked = MeshPulsarSearch(fil, cfg_chunked).run()
    # VERDICT r2 item 8: execution_times must be non-degenerate — the
    # chunked path reports its per-phase breakdown and a measured
    # dedispersion stage time
    assert chunked.timers["dedispersion"] > 0.0
    assert chunked.timers["chunk_fetch"] > 0.0
    assert len(full.candidates) == len(chunked.candidates)
    for a, b in zip(full.candidates, chunked.candidates):
        assert a.freq == pytest.approx(b.freq, rel=1e-9)
        assert a.snr == pytest.approx(b.snr, rel=1e-6)
        assert a.dm == b.dm and a.acc == b.acc
        assert a.count_assoc() == b.count_assoc()
        assert a.folded_snr == pytest.approx(b.folded_snr, rel=1e-4)
        assert a.opt_period == pytest.approx(b.opt_period, rel=1e-9)


def test_overflow_auto_escalation(tutorial_fil):
    """Forcing tiny peak buffers must auto-escalate (re-run with bigger
    buffers), not silently drop candidates: results at capacity 8 must
    equal results at the default 1024 (VERDICT: the reference never
    drops, it sizes at 100000, peakfinder.hpp:17,61)."""
    import warnings as w

    fil = read_filterbank(tutorial_fil)
    base = dict(
        dm_start=0.0, dm_end=60.0, acc_start=-5.0, acc_end=5.0,
        acc_pulse_width=64000.0, nharmonics=4, npdmp=0, limit=50,
    )
    ref = MeshPulsarSearch(fil, SearchConfig(**base)).run()
    with w.catch_warnings():
        w.simplefilter("ignore")
        tiny = MeshPulsarSearch(
            fil, SearchConfig(**base, peak_capacity=8, compact_capacity=64)
        ).run()
        tiny_chunked = MeshPulsarSearch(
            fil,
            SearchConfig(**base, peak_capacity=8, compact_capacity=64,
                         dm_chunk=2, accel_block=2),
        ).run()
        single = PulsarSearch(
            fil, SearchConfig(**base, peak_capacity=8)
        ).run()
    for other in (tiny, tiny_chunked, single):
        assert len(ref.candidates) == len(other.candidates)
        for a, b in zip(ref.candidates, other.candidates):
            assert a.freq == pytest.approx(b.freq, rel=1e-9)
            assert a.snr == pytest.approx(b.snr, rel=1e-6)
            assert a.dm == b.dm and a.acc == b.acc


def test_mesh_search_above_2e24_bins():
    """FFT sizes beyond 2^25 samples (spectra > 2^24 bins) must run on
    the mesh paths with exact peak transport (VERDICT r3 missing #3:
    the old f32 packing rejected them; the reference has no ceiling,
    `src/pipeline_multi.cu:326-331`).  A 977 Hz pulse train at 2^26
    samples puts its level-2 harmonic peak at bin ~1.7e7 > 2^24, so
    this fails if bin indices lose exactness anywhere in transport."""
    from peasoup_tpu.io.sigproc import Filterbank, SigprocHeader

    nsamps = (1 << 26) + 4096  # size = prev_power_of_two -> 2^26
    rng = np.random.default_rng(7)
    data = rng.integers(0, 32, size=(nsamps, 2), dtype=np.uint8)
    data[::16] += 40  # P = 16 samples = 1.024 ms -> 976.6 Hz
    hdr = SigprocHeader(nbits=8, nchans=2, tsamp=6.4e-5, fch1=1500.0,
                        foff=-100.0, nsamples=nsamps)
    fil = Filterbank(header=hdr, data=data)
    cfg = SearchConfig(dm_list=[0.0], acc_start=0.0, acc_end=0.0,
                       nharmonics=2, npdmp=0, limit=20)
    single = PulsarSearch(fil, cfg).run()
    mesh = MeshPulsarSearch(fil, cfg, max_devices=2).run()
    assert len(single.candidates) > 0
    # the harmonic family of the injected train must include a peak
    # whose level-2 bin index exceeds 2^24
    top = max(single.candidates, key=lambda c: c.snr)
    assert abs(top.freq - 1.0 / (16 * 6.4e-5)) < 0.01
    assert len(single.candidates) == len(mesh.candidates)
    for a, b in zip(single.candidates, mesh.candidates):
        assert a.freq == pytest.approx(b.freq, rel=1e-9)
        assert a.snr == pytest.approx(b.snr, rel=1e-6)


def test_chunked_tuning_persistence(tutorial_fil, tmp_path):
    """Persistent buffer tuning (search/tuning.py): run 1 records its
    peak-count high-waters; run 2 of the same search must produce the
    IDENTICAL candidate set with zero clipped rows — even when run 1
    was forced to clip and re-search by a tiny capacity."""
    import os
    import warnings as w

    fil = read_filterbank(tutorial_fil)
    base = dict(
        dm_start=0.0, dm_end=60.0, acc_start=-5.0, acc_end=5.0,
        acc_pulse_width=64000.0, nharmonics=4, npdmp=0, limit=50,
        dm_chunk=2, accel_block=2,
    )
    tune = str(tmp_path / "tune.json")
    r1 = MeshPulsarSearch(
        fil, SearchConfig(**base, tune_file=tune)).run()
    assert os.path.exists(tune)
    r2 = MeshPulsarSearch(
        fil, SearchConfig(**base, tune_file=tune)).run()
    assert r2.timers["chunk_n_clipped_rows"] == 0
    assert len(r1.candidates) == len(r2.candidates)
    for a, b in zip(r1.candidates, r2.candidates):
        assert a.freq == b.freq and a.snr == b.snr
        assert a.dm == b.dm and a.acc == b.acc

    # clip-inducing capacity: run 1 re-searches rows, run 2 is sized
    # from the recorded high-waters and must not clip at all
    tune2 = str(tmp_path / "tune2.json")
    tiny = dict(base, peak_capacity=8, compact_capacity=64,
                tune_file=tune2)
    with w.catch_warnings():
        w.simplefilter("ignore")
        t1 = MeshPulsarSearch(fil, SearchConfig(**tiny)).run()
    assert t1.timers["chunk_n_clipped_rows"] > 0
    t2 = MeshPulsarSearch(fil, SearchConfig(**tiny)).run()
    assert t2.timers["chunk_n_clipped_rows"] == 0
    assert len(t1.candidates) == len(t2.candidates)
    for a, b in zip(t1.candidates, t2.candidates):
        assert a.freq == b.freq and a.snr == b.snr


@pytest.mark.parametrize("mode", ["fused", "chunked"])
def test_two_process_distributed_search(tutorial_fil, mode):
    """2-process jax.distributed run on a 4-device global CPU mesh
    (VERDICT r2 item 5): exercises ``multihost.initialize``,
    ``multihost.global_mesh`` and ``fetch_to_host``'s
    ``process_allgather`` branch — the only parallel code single-process
    tests cannot reach.  Both processes must produce the identical
    candidate set, matching the single-process reference."""
    import json
    import os
    import socket
    import subprocess
    import sys

    worker = os.path.join(os.path.dirname(__file__), "mh_worker.py")
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(i), str(port), tutorial_fil,
             mode],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env,
        )
        for i in range(2)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=600)
        assert p.returncode == 0, out[-3000:]
        outs.append(out)
    sigs = []
    for out in outs:
        line = next(ln for ln in out.splitlines() if ln.startswith("SIG:"))
        sigs.append(json.loads(line[4:]))
    # deterministic distillation: every host computes the same answer
    assert sigs[0] == sigs[1]
    assert len(sigs[0]) > 0

    # and it is the same answer a single-process search produces
    fil = read_filterbank(tutorial_fil)
    cfg = SearchConfig(
        dm_start=0.0, dm_end=30.0, acc_start=-5.0, acc_end=5.0,
        acc_pulse_width=64000.0, npdmp=0, limit=20,
    )
    ref = PulsarSearch(fil, cfg).run()
    ref_sig = [
        [c.freq, c.snr, c.dm, c.acc, c.count_assoc()]
        for c in ref.candidates
    ]
    for got, want in zip(sigs[0], ref_sig):
        assert got[0] == pytest.approx(want[0], rel=1e-6)  # freq
        assert got[1] == pytest.approx(want[1], rel=1e-5)  # snr
        assert got[2:] == want[2:]                         # dm, acc, assoc
    assert len(sigs[0]) == len(ref_sig)


def test_pick_row_capacity_ignores_pathological_rows():
    """A single blazing row (10x everyone's count) must not set the
    global capacity; bulk rows pick the capacity, loud rows re-search."""
    from peasoup_tpu.search.tuning import pick_row_capacity

    row_hw = [100] * 490 + [900] * 9 + [13143]
    cap = pick_row_capacity(row_hw, n_accel_trials=10500)
    assert 900 < cap < 2048  # covers the 900s, not the 13k row
    # with many rows near the top the big capacity wins
    row_hw2 = [1300] * 400 + [100] * 100
    cap2 = pick_row_capacity(row_hw2, n_accel_trials=2688)
    assert cap2 >= 1332


def test_onehot_selection_exact_on_device():
    """ADVICE round-5 closeout: the kernel2 stage-2 bf16 one-hot row
    selection must be proven bit-identical to a jnp.take gather ON
    DEVICE (the prior test only compared a host f32 np.einsum).  The
    helper caches per backend, so the second call is free."""
    from peasoup_tpu.parallel.mesh import (
        _onehot_exact_checked,
        assert_onehot_selection_exact,
    )

    assert_onehot_selection_exact()  # must not raise on this backend
    assert any(k[1] == "bfloat16" and k[2] == "float32"
               for k in _onehot_exact_checked)
    assert_onehot_selection_exact()  # cached second call


def test_onehot_selection_assert_trips_on_inexact_dtype():
    """The assert must actually DETECT inexactness: pushing the values
    operand through bfloat16 truncates full-precision mantissas and
    has to raise, proving the checker would catch a backend whose
    HIGHEST precision is not an exact limb decomposition."""
    import jax.numpy as jnp

    from peasoup_tpu.errors import DomainError
    from peasoup_tpu.parallel.mesh import assert_onehot_selection_exact

    with pytest.raises(DomainError, match="NOT bit-exact"):
        assert_onehot_selection_exact(value_dtype=jnp.bfloat16)


def test_compact_method_for_gating(monkeypatch):
    """The pallas whole-buffer compaction only applies where the
    compiled kernel exists AND the buffer fits the kernel's VMEM gate
    (exactly the tuned 8192-quantum compact_k range); forced XLA-side
    extraction methods pin the XLA lowering."""
    from types import SimpleNamespace

    from peasoup_tpu.ops.peaks_pallas import COMPACT_PALLAS_MAX_K
    from peasoup_tpu.search import pipeline as pipeline_mod

    method_for = MeshPulsarSearch.compact_method_for

    def stub(peaks_method="auto"):
        return SimpleNamespace(
            config=SimpleNamespace(peaks_method=peaks_method))

    monkeypatch.setattr(pipeline_mod, "_pallas_mode",
                        lambda: "compiled")
    assert method_for(stub(), COMPACT_PALLAS_MAX_K) == "pallas"
    assert method_for(stub(), COMPACT_PALLAS_MAX_K + 1) == "xla"
    assert method_for(stub("pallas"), 4096) == "pallas"
    assert method_for(stub("sort"), 4096) == "xla"
    assert method_for(stub("two_stage"), 4096) == "xla"
    # off-TPU (no compiled kernel) the compaction always stays XLA —
    # an interpret-mode compaction would serialise the fused program
    monkeypatch.setattr(pipeline_mod, "_pallas_mode", lambda: None)
    assert method_for(stub(), 4096) == "xla"
    assert method_for(stub("pallas"), 4096) == "xla"
