"""Subprocess worker for the 2-process multi-host test.

Each process owns 2 virtual CPU devices; the 4-device GLOBAL mesh spans
both processes, so the packed peak buffer is a global array spanning
non-addressable devices and ``fetch_to_host`` must take its
``process_allgather`` branch (SURVEY section 2.8's DCN path).

Usage: python mh_worker.py <process_id> <coordinator_port> <tutorial.fil>
Prints one line ``SIG:<json candidate signature>`` on success.
"""

import json
import os
import sys

pid = int(sys.argv[1])
port = sys.argv[2]
tutorial = sys.argv[3]
mode = sys.argv[4] if len(sys.argv) > 4 else "fused"

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

try:
    import jax.extend

    # the host sitecustomize may have initialised a TPU plugin backend;
    # distributed init must precede (re-)backend creation
    jax.extend.backend.clear_backends()
except Exception:
    pass
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 2)

from peasoup_tpu.parallel import multihost  # noqa: E402

multihost.initialize(coordinator_address=f"localhost:{port}",
                     num_processes=2, process_id=pid)
assert jax.process_count() == 2, jax.process_count()
mesh = multihost.global_mesh()
assert mesh.devices.size == 4

from peasoup_tpu.io import read_filterbank  # noqa: E402
from peasoup_tpu.parallel.mesh import MeshPulsarSearch  # noqa: E402
from peasoup_tpu.search.plan import SearchConfig  # noqa: E402

fil = read_filterbank(tutorial)
extra = {}
if mode == "chunked":
    # force the bounded-HBM path: per-chunk put_global uploads and a
    # fetch_to_host allgather per chunk across both processes
    extra = dict(dm_chunk=2, accel_block=2)
cfg = SearchConfig(
    dm_start=0.0, dm_end=30.0, acc_start=-5.0, acc_end=5.0,
    acc_pulse_width=64000.0, npdmp=0, limit=20, **extra,
)
result = MeshPulsarSearch(fil, cfg, mesh=mesh).run()
sig = [
    [c.freq, c.snr, c.dm, c.acc, c.count_assoc()]
    for c in result.candidates
]
print("SIG:" + json.dumps(sig), flush=True)
