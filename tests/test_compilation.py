"""Cold-start observatory tests (ISSUE 18): the geometry-keyed
compile ledger (record/read round-trip, rotation, live attribution of
real backend compiles), its warehouse ingest + baseline-band
round-trip, the measured-HBM closure of every registered pipeline
program against the cost model, the shared ``memory_stats`` helper's
CPU no-op, the worker's cold-start decomposition, and the perf
report's coldstart table."""

import json
import os

import pytest

from peasoup_tpu.obs.compilation import (
    COMPILES_VERSION,
    CompileLedger,
    compile_context,
    configure_compile_ledger,
    install_compile_ledger,
    read_compiles,
    record_cache_event,
    record_profile,
    reset_seen_geometries,
    summarize_compiles,
)
from peasoup_tpu.obs.metrics import MetricsRegistry, REGISTRY


@pytest.fixture(autouse=True)
def _fresh_registry():
    REGISTRY.reset()
    yield
    REGISTRY.reset()


@pytest.fixture()
def ledger_path(tmp_path):
    """Point the process-wide ledger at a scratch file for the test,
    then park it back on disabled so other tests never write here."""
    path = str(tmp_path / "compiles.jsonl")
    configure_compile_ledger(path)
    yield path
    configure_compile_ledger("")


# --------------------------------------------------------------------------
# ledger round-trip
# --------------------------------------------------------------------------

def test_ledger_record_read_round_trip(tmp_path):
    path = str(tmp_path / "c.jsonl")
    led = CompileLedger(path)
    led.record("compile", program="p", geometry="abc123",
               device_kind="cpu", duration_s=0.5, seen_before=False,
               span="Dedisperse")
    led.record("cache", enabled=True, dir="/tmp/x")
    led.record("profile", path="/tmp/prof")
    recs = read_compiles(path)
    assert [r["kind"] for r in recs] == ["compile", "cache", "profile"]
    for r in recs:
        assert r["v"] == COMPILES_VERSION
        assert r["host"] and r["pid"] > 0 and r["ts"] > 0
    assert recs[0]["program"] == "p"
    assert recs[0]["geometry"] == "abc123"
    assert recs[0]["duration_s"] == 0.5
    assert recs[1]["enabled"] is True
    assert recs[2]["path"] == "/tmp/prof"
    assert [r["kind"] for r in read_compiles(path, kinds=("compile",))] \
        == ["compile"]


def test_read_compiles_skips_torn_and_future(tmp_path):
    path = str(tmp_path / "c.jsonl")
    good = {"v": COMPILES_VERSION, "ts": 1.0, "host": "h", "pid": 1,
            "kind": "compile", "duration_s": 0.1}
    future = dict(good, v=COMPILES_VERSION + 1)
    with open(path, "w") as f:
        f.write(json.dumps(good) + "\n")
        f.write(json.dumps(future) + "\n")
        f.write('{"torn": tr\n')  # crash mid-write
    assert read_compiles(path) == [good]
    assert read_compiles(str(tmp_path / "missing.jsonl")) == []


def test_ledger_rotates_at_byte_budget(tmp_path):
    path = str(tmp_path / "c.jsonl")
    led = CompileLedger(path, max_ledger_bytes=512)
    for i in range(50):
        led.record("compile", program=f"p{i}", duration_s=0.1)
    assert os.path.exists(path + ".1")
    # both generations hold valid records; neither is ever lost whole
    assert read_compiles(path) and read_compiles(path + ".1")


def test_summarize_groups_by_program_geometry():
    recs = [
        {"kind": "compile", "program": "a", "geometry": "g1",
         "device_kind": "cpu", "duration_s": 0.2, "seen_before": False},
        {"kind": "compile", "program": "a", "geometry": "g1",
         "device_kind": "cpu", "duration_s": 0.3, "seen_before": True},
        {"kind": "compile", "program": "b", "geometry": "g2",
         "device_kind": "cpu", "duration_s": 0.1, "seen_before": False},
    ]
    rows = summarize_compiles(recs)
    assert [r["program"] for r in rows] == ["a", "b"]  # total_s desc
    assert rows[0]["compiles"] == 2 and rows[0]["recompiles"] == 1
    assert rows[0]["total_s"] == pytest.approx(0.5)
    assert rows[0]["max_s"] == pytest.approx(0.3)


# --------------------------------------------------------------------------
# live attribution of real backend compiles
# --------------------------------------------------------------------------

def test_attribution_names_program_and_geometry(ledger_path):
    jax = pytest.importorskip("jax")
    import jax.numpy as jnp

    install_compile_ledger()
    reset_seen_geometries()
    with compile_context("unit.test", {"size": 131}):
        jax.jit(lambda x: x * 2.0 + 1.0)(
            jnp.ones((131,), jnp.float32)).block_until_ready()
    recs = read_compiles(ledger_path, kinds=("compile",))
    assert recs, "a fresh jit must ledger at least one backend compile"
    for r in recs:
        assert r["program"] == "unit.test"
        assert r["geometry"] and r["device_kind"]
        assert r["duration_s"] > 0.0
    fingerprint = recs[0]["geometry"]
    counters = REGISTRY.snapshot()["counters"]
    assert counters.get("jit.compiles_attributed", 0) >= len(recs)

    # a second, DIFFERENT program at the same declared geometry is a
    # recompile of a seen key: flagged in the record and the counter
    with compile_context("unit.test", {"size": 131}):
        jax.jit(lambda x: x - 3.0)(
            jnp.ones((131,), jnp.float32)).block_until_ready()
    recs = read_compiles(ledger_path, kinds=("compile",))
    assert any(r["seen_before"] for r in recs)
    assert all(r["geometry"] == fingerprint for r in recs)
    counters = REGISTRY.snapshot()["counters"]
    assert counters.get("jit.recompiles_seen_geometry", 0) >= 1
    rows = summarize_compiles(recs)
    assert rows[0]["program"] == "unit.test"
    assert rows[0]["recompiles"] >= 1


def test_cache_and_profile_events(ledger_path):
    reg = MetricsRegistry()
    record_cache_event(True, "/tmp/jax-cache", registry=reg)
    record_cache_event(False, "", registry=reg)
    record_profile("/tmp/profiles/job-1", registry=reg)
    recs = read_compiles(ledger_path)
    cache = [r for r in recs if r["kind"] == "cache"]
    assert [r["enabled"] for r in cache] == [True, False]
    assert cache[0]["dir"] == "/tmp/jax-cache"
    prof = [r for r in recs if r["kind"] == "profile"]
    assert prof[0]["path"] == "/tmp/profiles/job-1"
    counters = reg.snapshot()["counters"]
    assert counters.get("compile_cache.enabled") == 1
    assert counters.get("profile.captures") == 1


# --------------------------------------------------------------------------
# warehouse ingest + baseline band round-trip
# --------------------------------------------------------------------------

def _compile_rec(ts, dur, *, program="mesh.search", geometry="g1",
                 seen=False):
    return {"v": COMPILES_VERSION, "ts": ts, "host": "h0", "pid": 7,
            "kind": "compile", "program": program, "geometry": geometry,
            "device_kind": "cpu", "duration_s": dur,
            "seen_before": seen, "span": ""}


def test_warehouse_ingest_compiles(tmp_path):
    from peasoup_tpu.obs.warehouse import Warehouse, compile_rows

    rows = compile_rows(_compile_rec(10.0, 0.4, seen=True), run="r1")
    assert [r["metric"] for r in rows] == ["compile.duration_s",
                                           "compile.recompile"]
    assert rows[0]["stage"] == "mesh.search"
    assert rows[0]["geometry"] == "g1"
    assert rows[0]["device_kind"] == "cpu"
    assert rows[0]["value"] == pytest.approx(0.4)
    cache_row = compile_rows(
        {"kind": "cache", "ts": 1.0, "pid": 7, "enabled": True,
         "dir": "/c"})[0]
    assert cache_row["metric"] == "compile.cache_enabled"
    assert cache_row["value"] == 1.0 and cache_row["run"] == "pid:7"
    prof_row = compile_rows(
        {"kind": "profile", "ts": 1.0, "pid": 7, "path": "/p"})[0]
    assert prof_row["metric"] == "profile.capture"
    assert prof_row["data"]["path"] == "/p"

    path = str(tmp_path / "compiles.jsonl")
    with open(path, "w") as f:
        for i in range(3):
            f.write(json.dumps(_compile_rec(float(i), 0.1 * (i + 1),
                                            seen=i > 0)) + "\n")
    wh = Warehouse(str(tmp_path / "wh"))
    n = wh.ingest_compiles(path, run="r2")
    assert n == 5  # 3 durations + 2 recompile markers
    got = wh.rows(metric="compile.duration_s")
    assert len(got) == 3
    assert {r["run"] for r in got} == {"r2"}
    assert {r["geometry"] for r in got} == {"g1"}


def test_compile_anomalies_band_round_trip():
    from peasoup_tpu.obs.baseline import compile_anomalies

    stable = [_compile_rec(float(i), 0.1 + 0.001 * (i % 3))
              for i in range(9)]
    assert compile_anomalies(stable) == []
    spike = stable + [_compile_rec(99.0, 10.0)]
    anomalies = compile_anomalies(spike)
    assert len(anomalies) == 1
    a = anomalies[0]
    assert a["kind"] == "anomaly"
    assert a["metric"] == "compile_duration_s"
    assert a["key"]["stage"] == "mesh.search"
    assert a["key"]["geometry"] == "g1"
    assert a["value"] == pytest.approx(10.0)
    # a different geometry is a different baseline group: three
    # samples of a NEW fingerprint are its own (short) history, and
    # with min_n unmet they never borrow g1's band
    other = stable + [_compile_rec(100.0 + i, 5.0, geometry="g2")
                      for i in range(2)]
    assert compile_anomalies(other) == []


# --------------------------------------------------------------------------
# measured HBM footprints vs the cost model
# --------------------------------------------------------------------------

def test_memory_closure_all_registered_programs():
    pytest.importorskip("jax")
    from peasoup_tpu.obs.memprof import (
        MEMORY_CLOSURE_FACTOR, memory_join, memory_report,
        program_footprints,
    )

    rows = memory_join(program_footprints())
    assert [r["program"] for r in rows] == [
        "dedisperse", "spectrum", "harmonics", "peaks", "fold"]
    measured = [r for r in rows if r["measured"] is not None]
    if not measured:
        pytest.skip("memory_analysis() unavailable on this backend")
    for r in measured:
        assert r["model_bytes"] > 0 and r["measured_bytes"] > 0
        assert r["ok"], (
            f"{r['program']}: measured/model ratio {r['ratio']} "
            f"outside the documented x{MEMORY_CLOSURE_FACTOR} band")
        assert 1.0 / MEMORY_CLOSURE_FACTOR <= r["ratio"] \
            <= MEMORY_CLOSURE_FACTOR
    rep = memory_report(probe=False)  # footprints cached above
    assert rep["closure_factor"] == MEMORY_CLOSURE_FACTOR
    assert [r["program"] for r in rep["programs"]] == \
        [r["program"] for r in rows]


def test_memory_section_rides_run_report():
    pytest.importorskip("jax")
    from peasoup_tpu.obs.memprof import program_footprints
    from peasoup_tpu.obs.report import build_run_report

    program_footprints()  # ensure the process cache is warm
    report = build_run_report()
    assert "memory" in report
    assert report["memory"]["programs"]


def test_device_memory_stats_helper_cpu_noop():
    jax = pytest.importorskip("jax")
    from peasoup_tpu.obs.memprof import (
        device_memory_stats, hbm_watermark, probed_bytes_per,
    )

    dev = jax.devices()[0]
    stats = device_memory_stats(dev)
    if dev.platform == "cpu":
        assert stats is None
        assert hbm_watermark() is None
        # no probe off-TPU unless forced: capacity planners fall back
        # to their hand-measured constants without paying a compile
        assert probed_bytes_per("spectrum") is None
    else:  # pragma: no cover - accelerator-only
        assert stats["bytes_in_use"] >= 0


def test_probed_bytes_per_forced_slope_and_gauge():
    pytest.importorskip("jax")
    from peasoup_tpu.obs.memprof import probed_bytes_per

    slope = probed_bytes_per("row", force=True)
    assert slope is not None and slope > 0.0
    gauges = REGISTRY.snapshot()["gauges"]
    assert gauges.get("hbm.probed_row_bytes") == pytest.approx(slope)
    with pytest.raises(ValueError):
        probed_bytes_per("nonsense", force=True)


# --------------------------------------------------------------------------
# worker cold-start decomposition + perf report surfacing
# --------------------------------------------------------------------------

def test_worker_coldstart_partitions_total(tmp_path):
    from peasoup_tpu.serve import JobSpool, SurveyWorker

    spool = JobSpool(str(tmp_path / "jobs"))
    for i in range(2):
        spool.submit(f"/tmp/obs{i}.fil")
    worker = SurveyWorker(
        spool, run_job_fn=lambda job: {"candidates": 0},
        history_path=str(tmp_path / "h.jsonl"),
        telemetry_interval_s=0.0, sleeper=lambda s: None)
    summary = worker.drain()
    assert summary["succeeded"] == 2
    cold = summary["coldstart"]
    total = cold["cold_to_first_candidate_s"]
    assert total >= 0.0
    assert (cold["read_s"] + cold["trace_s"] + cold["compile_s"]
            + cold["execute_s"]) == pytest.approx(total, abs=1e-3)
    gauges = REGISTRY.snapshot()["gauges"]
    assert gauges.get("coldstart.cold_to_first_candidate_s") == \
        pytest.approx(total)


def test_coldstart_table_and_gate_metric(tmp_path):
    from peasoup_tpu.obs.history import append_history, \
        make_history_record
    from peasoup_tpu.tools.perf_report import (
        STAGE_GATE_METRICS, coldstart_table,
    )

    assert "cold_to_first_candidate_s" in STAGE_GATE_METRICS
    ledger = str(tmp_path / "history.jsonl")
    append_history(make_history_record("coldstart", {
        "cold_to_first_candidate_s": 12.5,
        "coldstart_read_s": 1.0, "coldstart_trace_s": 2.5,
        "coldstart_compile_s": 8.0, "coldstart_execute_s": 1.0,
        "warm_to_first_candidate_s": 1.5, "coldstart_compiles": 7,
    }), path=ledger)
    table = coldstart_table(ledger)
    assert "cold start (1 record(s)" in table
    assert "12.5" in table and "cold-start trend" in table
    assert coldstart_table(str(tmp_path / "empty.jsonl")) == ""
