"""peasoup-lint tier-1 tests: the rule engine on fixture snippets, the
suppression/baseline machinery, the repo-wide clean gate (ISSUE 2
acceptance) and the jaxpr-level program checks."""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from peasoup_tpu.analysis.engine import (
    Baseline,
    SourceFile,
    repo_root,
    run_rules,
)
from peasoup_tpu.analysis.rules import ALL_RULES, rules_by_id

REPO = repo_root()


def _lint_snippet(tmp_path, code, relpath="peasoup_tpu/ops/fixture.py",
                  rules=None):
    """Write ``code`` under a fixture tree and run the rules exactly as
    the CLI would, returning (violations, suppressed)."""
    path = tmp_path / relpath
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(code))
    violations, suppressed, errors = run_rules(
        rules or ALL_RULES, [str(path)], root=str(tmp_path))
    assert not errors, errors
    return violations, suppressed


# --------------------------------------------------------------------------
# per-rule fixtures: each known-bad snippet must be flagged
# --------------------------------------------------------------------------

def test_psl001_bare_warn_flagged(tmp_path):
    vs, _ = _lint_snippet(tmp_path, """
        import warnings

        def f():
            warnings.warn("boo")
    """, relpath="peasoup_tpu/utils/fixture.py")
    assert [v.rule for v in vs] == ["PSL001"]
    assert "warn_event" in vs[0].message


def test_psl001_from_import_flagged(tmp_path):
    vs, _ = _lint_snippet(tmp_path, """
        from warnings import warn
    """, relpath="peasoup_tpu/search/fixture.py")
    assert [v.rule for v in vs] == ["PSL001"]


def test_psl001_exempt_under_obs(tmp_path):
    vs, _ = _lint_snippet(tmp_path, """
        import warnings

        def f():
            warnings.warn("the telemetry bridge itself")
    """, relpath="peasoup_tpu/obs/fixture.py")
    assert vs == []


def test_psl002_host_syncs_flagged(tmp_path):
    vs, _ = _lint_snippet(tmp_path, """
        import jax
        import jax.numpy as jnp
        import numpy as np

        @jax.jit
        def f(x):
            a = float(x)
            b = np.asarray(x)
            x.block_until_ready()
            c = x.sum().item()
            return a, b, c
    """)
    assert [v.rule for v in vs] == ["PSL002"] * 4
    assert [v.line for v in vs] == [8, 9, 10, 11]


def test_psl002_static_and_structure_not_flagged(tmp_path):
    """Statics, shape probes and shape-derived locals are Python
    values — float()/branching on them must not be flagged."""
    vs, _ = _lint_snippet(tmp_path, """
        import jax
        from functools import partial

        @partial(jax.jit, static_argnames=("k",))
        def f(x, k):
            n = x.shape[0]
            scale = float(k) / float(n)
            if n > 4 and k:
                return x * scale
            return x
    """)
    assert vs == []


def test_psl002_jit_wrapper_assignment_detected(tmp_path):
    """`name = jax.jit(core, static_argnames=...)` marks `core` jitted
    — the pipeline's whiten_trial spelling."""
    vs, _ = _lint_snippet(tmp_path, """
        import jax

        def core(x, n):
            return int(x) + n

        core_jit = jax.jit(core, static_argnames=("n",))
    """)
    assert [v.rule for v in vs] == ["PSL002"]
    assert "core" in vs[0].message


def test_psl003_device_f64_flagged_host_f64_not(tmp_path):
    vs, _ = _lint_snippet(tmp_path, """
        import jax.numpy as jnp
        import numpy as np

        def f(x):
            table = np.arange(8, dtype=np.float64)  # host math: fine
            bad1 = jnp.asarray(x, jnp.float64)
            bad2 = jnp.arange(8, dtype="float64")
            return table, bad1, bad2
    """)
    assert [v.rule for v in vs] == ["PSL003", "PSL003"]
    assert [v.line for v in vs] == [7, 8]


def test_psl003_only_under_ops(tmp_path):
    vs, _ = _lint_snippet(tmp_path, """
        import jax.numpy as jnp

        def f(x):
            return jnp.asarray(x, jnp.float64)
    """, relpath="peasoup_tpu/search/fixture.py")
    assert vs == []


def test_psl004_traced_branch_flagged(tmp_path):
    vs, _ = _lint_snippet(tmp_path, """
        import jax

        @jax.jit
        def f(x):
            y = x * 2
            if y.sum() > 0:
                return y
            while x.max() > 1:
                x = x - 1
            return x
    """)
    assert [v.rule for v in vs] == ["PSL004", "PSL004"]


def test_psl004_structure_branches_not_flagged(tmp_path):
    vs, _ = _lint_snippet(tmp_path, """
        import jax

        @jax.jit
        def f(x, mask=None):
            if mask is None:
                return x
            if isinstance(x, (list, tuple)):
                x = x[0]
            if x.shape[0] % 8:
                return x * 2
            return x * mask
    """)
    assert vs == []


def test_psl005_raw_raise_flagged_typed_not(tmp_path):
    vs, _ = _lint_snippet(tmp_path, """
        from peasoup_tpu.errors import ConfigError

        def f(n):
            if n == 1:
                raise ValueError("untyped")
            if n == 2:
                raise RuntimeError("untyped")
            raise ConfigError("typed is fine")
    """, relpath="peasoup_tpu/parallel/fixture.py")
    assert [v.rule for v in vs] == ["PSL005", "PSL005"]


def test_psl005_not_applied_to_ops(tmp_path):
    vs, _ = _lint_snippet(tmp_path, """
        def f():
            raise ValueError("ops guards keep builtin raises")
    """, relpath="peasoup_tpu/ops/fixture.py")
    assert vs == []


def test_psl006_raw_timer_and_trace_range_flagged(tmp_path):
    vs, _ = _lint_snippet(tmp_path, """
        from peasoup_tpu.obs.metrics import REGISTRY as METRICS
        from peasoup_tpu.utils import trace_range

        def f():
            with trace_range("Dedisperse"):
                pass
            with METRICS.timer("dedispersion") as tm:
                pass
            with METRICS.timer("x"), trace_range("y"):
                pass
    """, relpath="peasoup_tpu/search/fixture.py")
    assert [v.rule for v in vs] == ["PSL006"] * 4
    assert all("span" in v.message for v in vs)


def test_psl006_span_api_and_obs_exempt(tmp_path):
    # the replacement API itself is clean
    vs, _ = _lint_snippet(tmp_path, """
        from peasoup_tpu.obs.trace import span

        def f():
            with span("Dedisperse", metric="dedispersion",
                      n_rows=8) as sp:
                sp.block(None)
    """, relpath="peasoup_tpu/search/fixture.py")
    assert vs == []
    # obs/ (where the registry and span() are implemented) is exempt
    vs, _ = _lint_snippet(tmp_path, """
        def f(METRICS):
            with METRICS.timer("jit_compile"):
                pass
    """, relpath="peasoup_tpu/obs/fixture.py")
    assert vs == []


def test_psl006_pragma_escape(tmp_path):
    vs, suppressed = _lint_snippet(tmp_path, """
        from peasoup_tpu.obs.metrics import REGISTRY as METRICS

        def f():
            with METRICS.timer("micro"):  # psl: disable=PSL006 -- benchmark-only scratch timer
                pass
    """, relpath="peasoup_tpu/search/fixture.py")
    assert vs == []
    assert suppressed == 1


def test_psl007_perf_constant_flagged(tmp_path):
    vs, _ = _lint_snippet(tmp_path, """
        V5E_HBM_GBPS = 819.0
        PEAK_BW = 1 << 30
        COPY_BYTES_PER_SAMP = 96 + 32
        FFT_FLOPS = 2.5e7
    """, relpath="benchmarks/fixture.py")
    assert [v.rule for v in vs] == ["PSL007"] * 4
    assert all("costmodel" in v.message for v in vs)


def test_psl007_applies_to_ops_and_bench_entry(tmp_path):
    vs, _ = _lint_snippet(tmp_path, """
        DEDISP_FLOPS = 1.0e9
    """, relpath="peasoup_tpu/ops/fixture.py")
    assert [v.rule for v in vs] == ["PSL007"]
    vs, _ = _lint_snippet(tmp_path, """
        HBM_GBPS = 819.0
    """, relpath="bench.py")
    assert [v.rule for v in vs] == ["PSL007"]


def test_psl007_clean_sites_not_flagged(tmp_path):
    """Lowercase locals, non-perf CONSTANT_CASE names, and values
    derived from the cost model (non-literal) are all clean."""
    vs, _ = _lint_snippet(tmp_path, """
        from peasoup_tpu.obs.costmodel import device_peak

        MAX_SPANS = 100_000
        BASELINE_TOTAL_S = 0.7699
        peak_gbps = 819.0
        DERIVED_GBPS = device_peak()["bytes_per_s"] / 1e9
    """, relpath="benchmarks/fixture.py")
    assert vs == []


def test_psl007_costmodel_is_the_exempt_home(tmp_path):
    vs, _ = _lint_snippet(tmp_path, """
        V5E_HBM_GBPS = 819.0
    """, relpath="peasoup_tpu/obs/costmodel.py")
    assert vs == []


def test_psl007_pragma_escape(tmp_path):
    vs, suppressed = _lint_snippet(tmp_path, """
        LINK_GBPS = 0.035  # psl: disable=PSL007 -- tunnel link budget, not a device peak
    """, relpath="benchmarks/fixture.py")
    assert vs == []
    assert suppressed == 1


def test_psl008_bare_sleep_flagged(tmp_path):
    vs, _ = _lint_snippet(tmp_path, """
        import time

        def poll():
            while True:
                time.sleep(5)
    """, relpath="peasoup_tpu/serve/fixture.py")
    assert [v.rule for v in vs] == ["PSL008"]
    assert "BackoffPolicy" in vs[0].message or "retry" in vs[0].message


def test_psl008_from_import_flagged(tmp_path):
    vs, _ = _lint_snippet(tmp_path, """
        from time import sleep
    """, relpath="peasoup_tpu/utils/fixture.py")
    assert [v.rule for v in vs] == ["PSL008"]


def test_psl008_retry_is_the_exempt_home(tmp_path):
    vs, _ = _lint_snippet(tmp_path, """
        import time

        def pause(seconds):
            time.sleep(seconds)
    """, relpath="peasoup_tpu/serve/retry.py")
    assert vs == []


def test_psl008_pragma_escape(tmp_path):
    vs, suppressed = _lint_snippet(tmp_path, """
        import time

        def settle():
            time.sleep(0.01)  # psl: disable=PSL008 -- hardware settle, not a retry loop
    """, relpath="benchmarks/fixture.py")
    assert vs == []
    assert suppressed == 1


def test_psl009_uncatalogued_metric_flagged(tmp_path):
    vs, _ = _lint_snippet(tmp_path, """
        from ..obs.metrics import REGISTRY as METRICS

        def f():
            METRICS.inc("totally.bogus_counter")
            METRICS.gauge("also.bogus_gauge", 1.0)
    """, relpath="peasoup_tpu/serve/fixture.py")
    assert [v.rule for v in vs] == ["PSL009", "PSL009"]
    assert "catalog" in vs[0].message


def test_psl009_cataloged_and_dynamic_clean(tmp_path):
    """Catalogued literals, documented dynamic-prefix literals and
    f-string names (the prefix is the contract) all pass; so do
    ``.inc`` calls on receivers that are not a metrics registry."""
    vs, _ = _lint_snippet(tmp_path, """
        from ..obs.metrics import REGISTRY as METRICS

        def f(self, reg, kind):
            METRICS.inc("scheduler.claimed")
            METRICS.gauge("hbm.budget_bytes", 2.0)
            reg.inc("supervisor.action.scale_up")
            self._registry.inc(f"events.{kind}")
            counter.inc("not.a.metric.registry")
    """, relpath="peasoup_tpu/serve/fixture.py")
    assert vs == []


def test_psl009_registry_receiver_spellings_flagged(tmp_path):
    """The rule audits every registry spelling the tree uses:
    ``self._registry``, a ``reg`` local, a ``*registry`` attribute."""
    vs, _ = _lint_snippet(tmp_path, """
        def f(self, reg):
            self._registry.inc("bogus.one")
            reg.gauge("bogus.two", 0.0)
    """, relpath="peasoup_tpu/obs/fixture.py")
    assert [v.rule for v in vs] == ["PSL009", "PSL009"]


def test_psl009_catalog_module_is_exempt(tmp_path):
    vs, _ = _lint_snippet(tmp_path, """
        METRICS.inc("names.defined.here.are.the.catalog")
    """, relpath="peasoup_tpu/obs/catalog.py")
    assert vs == []


def test_psl009_every_catalog_name_has_description():
    """The catalog itself stays honest: every entry carries a
    non-empty description and every dynamic prefix ends with a
    separator (it is a family, not a name)."""
    from peasoup_tpu.obs.catalog import CATALOG, DYNAMIC_PREFIXES

    assert all(desc.strip() for desc in CATALOG.values())
    assert all(p.endswith((".", "_")) for p in DYNAMIC_PREFIXES)


# --------------------------------------------------------------------------
# suppressions
# --------------------------------------------------------------------------

def test_inline_suppression(tmp_path):
    vs, suppressed = _lint_snippet(tmp_path, """
        import jax.numpy as jnp

        def f(x):
            return jnp.asarray(x, jnp.float64)  # psl: disable=PSL003 -- reference-exact f64
    """)
    assert vs == []
    assert suppressed == 1


def test_inline_suppression_wrong_id_does_not_silence(tmp_path):
    vs, suppressed = _lint_snippet(tmp_path, """
        import jax.numpy as jnp

        def f(x):
            return jnp.asarray(x, jnp.float64)  # psl: disable=PSL001
    """)
    assert [v.rule for v in vs] == ["PSL003"]
    assert suppressed == 0


def test_file_level_suppression(tmp_path):
    vs, suppressed = _lint_snippet(tmp_path, """
        # psl: disable-file=PSL003 -- emulated-f64 test fixture
        import jax.numpy as jnp

        def f(x):
            return jnp.asarray(x, jnp.float64), jnp.float64(0)
    """)
    assert vs == []
    assert suppressed == 2


def test_multiple_ids_one_pragma(tmp_path):
    vs, suppressed = _lint_snippet(tmp_path, """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x):
            return float(jnp.float64(1) * x)  # psl: disable=PSL002,PSL003 -- fixture
    """)
    assert vs == []
    assert suppressed == 2


# --------------------------------------------------------------------------
# baseline add / expire round-trip
# --------------------------------------------------------------------------

def test_baseline_round_trip(tmp_path):
    code = """
        import warnings

        def f():
            warnings.warn("legacy site one")

        def g():
            warnings.warn("legacy site two")
    """
    vs, _ = _lint_snippet(tmp_path, code,
                          relpath="peasoup_tpu/utils/fixture.py")
    assert len(vs) == 2

    # add: grandfather everything, reload, nothing is "new"
    bl_path = str(tmp_path / "baseline.json")
    Baseline.from_violations(vs, reason="pre-PSL001 sites").save(bl_path)
    bl = Baseline.load(bl_path)
    new, old, expired = bl.split(vs)
    assert new == [] and len(old) == 2 and expired == []

    # expire: fix one site; its entry is reported expired, and an
    # unrelated line shift must NOT expire the other (key is
    # line-free)
    fixed = code.replace('warnings.warn("legacy site one")', "pass")
    fixed = fixed.replace(
        "import warnings",
        "# a new leading comment shifts every line\n        "
        "import warnings")
    vs2, _ = _lint_snippet(tmp_path, fixed,
                           relpath="peasoup_tpu/utils/fixture.py")
    assert len(vs2) == 1
    new, old, expired = bl.split(vs2)
    assert new == [] and len(old) == 1
    assert len(expired) == 1
    assert "site one" in expired[0]["snippet"]

    # re-write drops the expired entry
    Baseline.from_violations(vs2).save(bl_path)
    assert len(Baseline.load(bl_path).entries) == 1


def test_baseline_version_mismatch_rejected(tmp_path):
    p = tmp_path / "bl.json"
    p.write_text(json.dumps({"version": 99, "entries": []}))
    with pytest.raises(ValueError):
        Baseline.load(str(p))


# --------------------------------------------------------------------------
# repo-wide gates (ISSUE 2 acceptance)
# --------------------------------------------------------------------------

def test_repo_is_clean_under_all_rules():
    """`python -m peasoup_tpu.analysis` must exit 0 on the repo: every
    violation fixed, pragma-suppressed with a reason, or baselined."""
    violations, _suppressed, errors = run_rules(ALL_RULES)
    assert not errors, errors
    bl = Baseline.load(os.path.join(REPO, "lint_baseline.json"))
    new, _old, _expired = bl.split(violations)
    assert new == [], "new lint violations:\n" + "\n".join(
        v.format() for v in new)


def test_baseline_is_near_empty():
    """Grandfathering is for emergencies; this PR fixed the real
    violations instead.  Hold the line."""
    bl = Baseline.load(os.path.join(REPO, "lint_baseline.json"))
    assert len(bl.entries) <= 3, (
        "baseline is growing — fix violations instead of baselining: "
        + json.dumps(bl.entries, indent=2)
    )


def test_cli_exits_zero_on_repo():
    proc = subprocess.run(
        [sys.executable, "-m", "peasoup_tpu.analysis", "--no-jaxpr",
         "--json"],
        capture_output=True, text=True, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["ok"] is True
    assert payload["violations"] == []


def test_cli_exits_nonzero_on_injected_violation(tmp_path):
    bad = tmp_path / "peasoup_tpu" / "search" / "bad.py"
    bad.parent.mkdir(parents=True)
    bad.write_text("import warnings\nwarnings.warn('injected')\n")
    proc = subprocess.run(
        [sys.executable, "-m", "peasoup_tpu.analysis", "--no-jaxpr",
         "--json", "--root", str(tmp_path),
         "--baseline", str(tmp_path / "bl.json"), str(bad)],
        capture_output=True, text=True, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["ok"] is False
    assert [v["rule"] for v in payload["violations"]] == ["PSL001"]


def test_rules_by_id_selects_and_rejects():
    assert [r.id for r in rules_by_id(["PSL001"])] == ["PSL001"]
    with pytest.raises(ValueError):
        rules_by_id(["PSL999"])


# --------------------------------------------------------------------------
# jaxpr-level checks
# --------------------------------------------------------------------------

def test_jaxpr_registered_programs_clean():
    """The five registered pipeline programs hold the device-program
    invariants (fold's documented f64 allowance aside)."""
    from peasoup_tpu.analysis.jaxpr_check import (
        check_registered_programs,
        registered_programs,
    )

    names = {s.name for s in registered_programs()}
    assert names == {"dedisperse", "spectrum", "harmonics", "peaks",
                     "fold"}
    findings = check_registered_programs()
    assert findings == [], "\n".join(f.format() for f in findings)


def test_jaxpr_fold_allowance_is_documented():
    from peasoup_tpu.analysis.jaxpr_check import registered_programs

    fold = next(s for s in registered_programs() if s.name == "fold")
    assert fold.allow_f64 and "phase_bins" in fold.allow_reason


def test_jaxpr_fails_on_injected_f64():
    """ISSUE 2 acceptance: an f64 intermediate smuggled into a
    registered program must be caught."""
    import jax.numpy as jnp

    from peasoup_tpu.analysis.jaxpr_check import (
        ProgramSpec,
        check_program,
    )
    from peasoup_tpu.search import pipeline as pl

    def build():
        from functools import partial

        tim = jnp.zeros((2048,), jnp.float32)
        none = jnp.zeros((0,), jnp.float32)
        core = partial(pl.whiten_core, bin_width=1.0 / 2048.0,
                       b5=0.05, b25=0.5, use_zap=False)

        def leaky(tim, birdies, widths):
            tim = (tim.astype(jnp.float64) * 2.0).astype(jnp.float32)
            return core(tim, birdies, widths)

        return leaky, (tim, none, none)

    findings = check_program(ProgramSpec("spectrum-injected", build))
    assert any(f.check == "f64-intermediate" for f in findings)


def test_jaxpr_fails_on_injected_host_callback():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from peasoup_tpu.analysis.jaxpr_check import (
        ProgramSpec,
        check_program,
    )

    def build():
        def f(x):
            return jax.pure_callback(
                lambda a: np.asarray(a),
                jax.ShapeDtypeStruct(x.shape, x.dtype), x)

        return f, (jnp.zeros((8,), jnp.float32),)

    findings = check_program(ProgramSpec("injected-callback", build))
    assert any(f.check == "host-primitive" for f in findings)


def test_jaxpr_trace_error_is_reported_not_raised():
    from peasoup_tpu.analysis.jaxpr_check import (
        ProgramSpec,
        check_program,
    )

    def build():
        def f(x):
            raise RuntimeError("broken build")

        return f, (0,)

    findings = check_program(ProgramSpec("broken", build))
    assert [f.check for f in findings] == ["trace-error"]
    assert "broken build" in findings[0].detail


def test_jaxpr_signature_stability():
    """Repeat calls at identical shapes must not compile new
    signatures (production runs would recompile per DM trial), and
    the pipeline-registered programs stay under the signature bound
    via the PR-1 cache probes."""
    from peasoup_tpu.analysis.jaxpr_check import check_signatures

    findings = check_signatures()
    assert findings == [], "\n".join(f.format() for f in findings)


def test_peaks_pallas_kernel_is_lint_clean():
    """ISSUE-6 satellite: the new threshold-compaction kernel module
    must be clean under every rule WITHOUT any baseline entry — no
    grandfathering for new code."""
    violations, _suppressed, errors = run_rules(
        ALL_RULES, paths=[os.path.join(
            REPO, "peasoup_tpu", "ops", "peaks_pallas.py")])
    assert not errors, errors
    assert violations == [], "\n".join(v.format() for v in violations)
