"""Perf accounting tier-1 tests (ISSUE 4): the closed-form cost model
vs XLA's own cost_analysis, roofline utilization on a synthetic run,
the bench history ledger round-trip, the noise-aware regression gate,
and trace_report --compare."""

import json
import os

import numpy as np
import pytest

from peasoup_tpu.obs import costmodel as cm
from peasoup_tpu.obs.history import (
    append_history,
    load_history,
    make_history_record,
)
from peasoup_tpu.tools import perf_report, trace_report

# --------------------------------------------------------------------------
# cost model closed forms
# --------------------------------------------------------------------------

def _geometry(**over):
    base = dict(
        n_dm=16, nchans=32, out_nsamps=4000, in_itemsize=1, size=2048,
        nharmonics=4, peak_capacity=64, n_trials_total=48, npdmp=4,
        fold_nsamps=2048, fold_nbins=64, fold_nints=16,
    )
    base.update(over)
    return cm.PipelineGeometry(**base)


def test_pipeline_costs_cover_all_five_stages():
    costs = cm.pipeline_costs(_geometry())
    assert set(costs) == set(cm.STAGES)
    for name, cost in costs.items():
        assert cost.flops > 0, name
        assert cost.bytes_read > 0 and cost.bytes_written > 0, name
        assert cost.intensity > 0, name


def test_costs_scale_with_geometry():
    """Doubling the trial grid doubles the per-trial stages; doubling
    the DM count doubles dedispersion — the closed forms track the
    plan, which is the whole point."""
    a = cm.pipeline_costs(_geometry())
    b = cm.pipeline_costs(_geometry(n_trials_total=96))
    assert b["harmonics"].flops == pytest.approx(2 * a["harmonics"].flops)
    assert b["peaks"].flops == pytest.approx(2 * a["peaks"].flops)
    assert b["dedisperse"].flops == a["dedisperse"].flops
    c = cm.pipeline_costs(_geometry(n_dm=32))
    assert c["dedisperse"].flops == pytest.approx(
        2 * a["dedisperse"].flops)


def test_lattice_itemsize_scales_trial_bytes():
    """The trial-lattice dtype reaches the closed forms (ISSUE 13):
    dedispersed trial bytes written and spectrum trial bytes read both
    scale with ``trial_itemsize`` per LATTICE_ITEMSIZE, while flops are
    untouched (quantisation changes traffic, not arithmetic)."""
    from peasoup_tpu.search.tuning import LATTICE_ITEMSIZE

    f32 = cm.pipeline_costs(
        _geometry(trial_itemsize=LATTICE_ITEMSIZE["f32"]))
    for dtype in ("u8", "bf16"):
        isz = LATTICE_ITEMSIZE[dtype]
        q = cm.pipeline_costs(_geometry(trial_itemsize=isz))
        assert q["dedisperse"].bytes_written == pytest.approx(
            f32["dedisperse"].bytes_written * isz / 4.0)
        # spectrum reads trials at the lattice dtype plus f32/f64
        # side inputs: the delta is exactly the trial-array shrink
        g = _geometry()
        assert (f32["spectrum"].bytes_read - q["spectrum"].bytes_read
                == g.n_trials_total * g.size * (4 - isz))
        assert q["spectrum"].flops == f32["spectrum"].flops
        assert q["dedisperse"].flops == f32["dedisperse"].flops


def test_jerk_axis_multiplies_trial_grid_geometry():
    """``from_search`` folds the jerk plan in through
    trial_grid_geometry: n_trials_total picks up the njerk factor, so
    every per-trial closed form scales with it automatically."""
    from peasoup_tpu.search.plan import (
        AccelerationPlan,
        JerkPlan,
        trial_grid_geometry,
    )

    plan = AccelerationPlan(-5.0, 5.0, 1.10, 64000.0, 1 << 17,
                            6.4e-5, 1510.0, -10.0)
    dms = np.asarray([0.0, 50.0], np.float32)
    flat = trial_grid_geometry(dms, plan)
    jp = JerkPlan(-10.0, 10.0, 10.0)
    cubed = trial_grid_geometry(dms, plan, jerk_plan=jp)
    a = cm.pipeline_costs(_geometry(
        n_trials_total=flat.n_trials_total))
    b = cm.pipeline_costs(_geometry(
        n_trials_total=cubed.n_trials_total, njerk=jp.njerk))
    assert b["harmonics"].flops == pytest.approx(
        jp.njerk * a["harmonics"].flops)
    assert b["peaks"].flops == pytest.approx(
        jp.njerk * a["peaks"].flops)
    assert b["dedisperse"].flops == a["dedisperse"].flops


def test_geometry_json_carries_lattice_fields():
    g = _geometry(njerk=3, trial_itemsize=2)
    blob = g.to_json()
    assert blob["njerk"] == 3 and blob["trial_itemsize"] == 2
    # defaults keep the pre-jerk accounting bit-for-bit
    d = _geometry().to_json()
    assert d["njerk"] == 1 and d["trial_itemsize"] == 4


def test_dominant_classification():
    peak = {"flops_per_s": 1e12, "bytes_per_s": 100e9}
    assert cm.StageCost(1e12, 1e9, 1e9).dominant(peak) == "compute"
    assert cm.StageCost(1e6, 1e12, 1e12).dominant(peak) == "memory"


def test_device_peak_lookup_and_fallback():
    v5e = cm.device_peak("TPU v5 lite", n_devices=1)
    assert v5e["matched"] is True
    four = cm.device_peak("TPU v5 lite", n_devices=4)
    assert four["flops_per_s"] == pytest.approx(4 * v5e["flops_per_s"])
    unknown = cm.device_peak("FancyAccel 9000")
    assert unknown["matched"] is False
    assert unknown["flops_per_s"] > 0


def test_geometry_accessors():
    from peasoup_tpu.search.plan import (
        AccelerationPlan,
        SearchConfig,
        trial_grid_geometry,
    )

    cfg = SearchConfig(nharmonics=4, size=0)
    assert cfg.nlevels == 5
    assert cfg.fft_size_for(5000) == 4096
    assert SearchConfig(size=1 << 14).fft_size_for(5000) == 1 << 14

    plan = AccelerationPlan(-5.0, 5.0, 1.10, 64000.0, 1 << 17,
                            6.4e-5, 1510.0, -10.0)
    dms = np.asarray([0.0, 50.0, 100.0], np.float32)
    geom = trial_grid_geometry(dms, plan)
    assert geom.n_dm == 3
    assert geom.n_trials_total == sum(
        len(plan.generate_accel_list(d)) for d in dms)
    assert geom.namax >= 1
    # precomputed acc_lists short-circuit agrees
    lists = [plan.generate_accel_list(float(d)) for d in dms]
    assert trial_grid_geometry(dms, plan, lists) == geom


# --------------------------------------------------------------------------
# closed form vs XLA cost_analysis
# --------------------------------------------------------------------------

def test_crosscheck_shapes_match_registered_programs():
    """The cross-check's model shapes must track the jaxpr checker's
    program registry — same five names."""
    from peasoup_tpu.analysis.jaxpr_check import registered_programs

    assert set(cm._crosscheck_shapes()) == {
        s.name for s in registered_programs()}


def test_crosscheck_agreement_within_documented_factor():
    """Every registered program's closed-form flops agree with
    ``jax.jit(...).lower().compile().cost_analysis()`` within
    CROSSCHECK_FACTOR (programs where the backend reports no flop
    count — FFT custom calls — are skipped by the check itself)."""
    rows = cm.crosscheck_registered_programs()
    assert {r["program"] for r in rows} == set(cm.STAGES)
    if all(r["xla_flops"] is None for r in rows):
        pytest.skip("cost_analysis unavailable on this jax/backend")
    bad = [r for r in rows if not r["ok"]]
    assert bad == [], f"model drifted from traced programs: {bad}"


# --------------------------------------------------------------------------
# utilization on a synthetic end-to-end run
# --------------------------------------------------------------------------

@pytest.fixture(scope="module")
def synth_run_report(tmp_path_factory):
    """One small host-loop search -> its run report (with perf)."""
    from peasoup_tpu.io.sigproc import Filterbank, SigprocHeader
    from peasoup_tpu.obs.metrics import REGISTRY
    from peasoup_tpu.obs.report import build_run_report
    from peasoup_tpu.search.pipeline import PulsarSearch
    from peasoup_tpu.search.plan import SearchConfig

    rng = np.random.default_rng(0)
    nsamps, nchans = 4096, 16
    data = rng.integers(0, 32, size=(nsamps, nchans), dtype=np.uint8)
    data[::16] += 60
    hdr = SigprocHeader(nbits=8, nchans=nchans, tsamp=0.000256,
                        fch1=1510.0, foff=-10.0, nsamples=nsamps)
    fil = Filterbank(header=hdr, data=data)
    REGISTRY.reset()
    cfg = SearchConfig(dm_start=0.0, dm_end=20.0, min_snr=6.0,
                       npdmp=2, limit=10)
    result = PulsarSearch(fil, cfg).run()
    return build_run_report(result)


def test_report_schema_version_bumped(synth_run_report):
    assert synth_run_report["schema_version"] == 2
    assert synth_run_report["version"] == 2


def test_perf_section_all_five_stages(synth_run_report):
    """ISSUE acceptance: per-stage flops, bytes, achieved FLOP/s and
    utilization for all five pipeline stages."""
    perf = synth_run_report["perf"]
    stages = perf["stages"]
    assert set(stages) == set(cm.STAGES)
    for name, row in stages.items():
        assert row["flops"] > 0, name
        assert row["bytes_read"] > 0 and row["bytes_written"] > 0, name
        assert row["dominant"] in ("compute", "memory"), name
        assert row["achieved_flops_per_s"] > 0, name
        assert 0.0 < row["utilization"] <= 1.0, name
        assert row["attribution"] in ("measured", "modeled-share"), name
    assert perf["peak"]["flops_per_s"] > 0
    assert perf["geometry"]["n_dm"] >= 1
    # no nulls anywhere in the section
    assert "null" not in json.dumps(perf)


def test_perf_section_absent_without_cost_data():
    """A bare-telemetry report (no search ran -> no recorded costs)
    omits the perf section entirely rather than emitting nulls."""
    from peasoup_tpu.obs.metrics import MetricsRegistry
    from peasoup_tpu.obs.report import build_run_report

    saved = cm.get_run_costs()
    try:
        cm.reset_run_costs()
        report = build_run_report(registry=MetricsRegistry())
        assert "perf" not in report
        assert "null" not in json.dumps(report.get("perf", {}))
    finally:
        if saved is not None:
            cm._RUN_COSTS = saved


def test_verbose_table_includes_perf(synth_run_report):
    from peasoup_tpu.obs.report import format_stage_table

    table = format_stage_table(synth_run_report)
    assert "util" in table
    assert "dedisperse" in table


def test_span_gflops_attributes(synth_run_report):
    """The drivers attach the modelled Gflops to their existing spans
    so trace viewers can read achieved rates off any slice."""
    from peasoup_tpu.obs.trace import get_tracer

    by_name = {}
    for rec in get_tracer().records():
        by_name.setdefault(rec.name, []).append(rec)
    assert any("gflops" in r.attrs for r in by_name.get("Dedisperse", []))
    assert any("gflops" in r.attrs
               for r in by_name.get("Accel-Search", []))


# --------------------------------------------------------------------------
# history ledger
# --------------------------------------------------------------------------

def test_history_append_load_round_trip(tmp_path):
    path = str(tmp_path / "history.jsonl")
    rec = make_history_record(
        "bench", metrics={"e2e_s": 0.42, "skipme": None},
        timers={"total": 0.5}, utilization={"spectrum": 0.12},
        parity="ok")
    assert rec["v"] == 1
    assert "ts" in rec and "git" in rec and "device" in rec
    assert "skipme" not in rec["metrics"]  # no nulls in the ledger
    assert append_history(rec, path) == path
    assert append_history(make_history_record(
        "micro", metrics={"fft_ms": 1.0}), path) == path
    # a torn tail (killed run) must not poison the history
    with open(path, "a") as f:
        f.write('{"v": 1, "kind": "bench", "metr')
    loaded = load_history(path)
    assert len(loaded) == 2
    assert loaded[0]["metrics"]["e2e_s"] == 0.42
    assert [r["kind"] for r in load_history(path, kinds=("micro",))] \
        == ["micro"]
    assert load_history(str(tmp_path / "missing.jsonl")) == []


def test_legacy_bench_artifacts_load(tmp_path):
    legacy = tmp_path / "BENCH_r01.json"
    legacy.write_text(json.dumps({
        "n": 1, "rc": 0,
        "parsed": {"metric": "tutorial_fil_e2e_wallclock",
                   "value": 0.7087, "unit": "s",
                   "timers": {"total": 0.71}},
    }))
    (tmp_path / "BENCH_r02.json").write_text("not json")
    recs = perf_report.load_legacy_bench(str(tmp_path / "BENCH_r0*.json"))
    assert len(recs) == 1
    assert recs[0]["legacy"] is True
    assert recs[0]["metrics"]["e2e_s"] == 0.7087


# --------------------------------------------------------------------------
# regression gate
# --------------------------------------------------------------------------

def _ledger_with(tmp_path, values, metric="e2e_s"):
    path = str(tmp_path / "history.jsonl")
    for v in values:
        append_history(make_history_record(
            "bench", metrics={metric: v}), path)
    return path


def test_gate_quiet_on_noise_jitter(tmp_path, capsys):
    # +-5 % jitter around 1.0 s: far below the 1.4x threshold
    rng = np.random.default_rng(7)
    vals = list(1.0 + 0.05 * rng.uniform(-1, 1, size=10))
    path = _ledger_with(tmp_path, vals)
    rc = perf_report.main(
        ["--ledger", path, "--legacy-glob", "", "--gate"])
    assert rc == 0
    assert "OK gate" in capsys.readouterr().out


def test_gate_trips_on_injected_3x_regression(tmp_path, capsys):
    """ISSUE acceptance: a synthetic 3x slowdown record appended to an
    otherwise steady ledger makes the gate exit nonzero."""
    rng = np.random.default_rng(7)
    vals = list(1.0 + 0.05 * rng.uniform(-1, 1, size=10)) + [3.0]
    path = _ledger_with(tmp_path, vals)
    rc = perf_report.main(
        ["--ledger", path, "--legacy-glob", "", "--gate"])
    assert rc == 1
    assert "REGRESSION" in capsys.readouterr().out


def test_gate_passes_with_insufficient_history(tmp_path, capsys):
    path = _ledger_with(tmp_path, [1.0])
    rc = perf_report.main(
        ["--ledger", path, "--legacy-glob", "", "--gate"])
    assert rc == 0
    assert "not enough history" in capsys.readouterr().out


def test_gate_median_rejects_single_outlier_in_window(tmp_path):
    """One historic outlier must not poison the baseline median."""
    vals = [1.0, 1.02, 5.0, 0.98, 1.01, 1.0, 0.99, 1.03, 1.0]
    code, msg = perf_report.regression_gate(
        [{"metrics": {"e2e_s": v}} for v in vals])
    assert code == 0, msg


def test_gate_json_mode(tmp_path, capsys):
    path = _ledger_with(tmp_path, [1.0, 1.0, 1.0, 3.1])
    rc = perf_report.main(
        ["--ledger", path, "--legacy-glob", "", "--gate", "--json"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert doc["gate"]["ok"] is False
    assert doc["metrics"]["e2e_s"]["n"] == 4


def test_sparkline_shape():
    s = perf_report.sparkline([1, 2, 3, 4])
    assert len(s) == 4
    assert s[0] == perf_report.SPARK_BLOCKS[0]
    assert s[-1] == perf_report.SPARK_BLOCKS[-1]
    assert perf_report.sparkline([2.0, 2.0]) == \
        perf_report.SPARK_BLOCKS[0] * 2
    assert perf_report.sparkline([]) == ""


def test_trend_table_lists_metrics(tmp_path, capsys):
    path = _ledger_with(tmp_path, [0.5, 0.4, 0.45])
    rc = perf_report.main(["--ledger", path, "--legacy-glob", ""])
    out = capsys.readouterr().out
    assert rc == 0
    assert "e2e_s" in out
    assert "3" in out  # record count


# --------------------------------------------------------------------------
# trace_report --compare
# --------------------------------------------------------------------------

def _write_trace(path, scale, extra_stage=False):
    events, t = [], 0.0
    stages = [("DM-Loop", 100.0 * scale), ("Folding", 30.0)]
    if extra_stage:
        stages.append(("Rednoise", 5.0))
    for name, dur_ms in stages:
        events.append({"ph": "B", "name": name, "ts": t, "pid": 0,
                       "tid": 0, "args": {}})
        events.append({"ph": "E", "name": name, "ts": t + dur_ms * 1e3,
                       "pid": 0, "tid": 0})
        t += dur_ms * 1e3 + 10
    with open(path, "w") as f:
        json.dump({"traceEvents": events}, f)


def test_trace_compare_delta_table(tmp_path, capsys):
    a, b = str(tmp_path / "a.json"), str(tmp_path / "b.json")
    _write_trace(a, 1.0)
    _write_trace(b, 2.0, extra_stage=True)
    rc = trace_report.main(["--compare", a, b])
    out = capsys.readouterr().out
    assert rc == 0
    assert "DM-Loop" in out and "+100.00" in out and "2.00x" in out
    assert "Folding" in out and "+0.00" in out
    assert "Rednoise" in out and "new" in out  # B-only stage
    assert "TOTAL" in out


def test_trace_report_still_requires_a_trace(capsys):
    with pytest.raises(SystemExit) as exc:
        trace_report.main([])
    assert exc.value.code == 2


def test_trace_compare_rejects_bad_file(tmp_path, capsys):
    a = str(tmp_path / "a.json")
    _write_trace(a, 1.0)
    rc = trace_report.main(
        ["--compare", a, str(tmp_path / "missing.json")])
    assert rc == 2


# --------------------------------------------------------------------------
# shared ledger writer (micro/production route through it)
# --------------------------------------------------------------------------

def test_benchmark_harnesses_use_shared_writer():
    """The satellite fix: benchmarks/micro.py and production.py must
    route their ledger records through obs.history (one schema), not
    ad-hoc json.dump calls."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for name in ("micro.py", "production.py"):
        src = open(os.path.join(root, "benchmarks", name)).read()
        assert "make_history_record" in src, name
        assert "append_history" in src, name
    src = open(os.path.join(root, "bench.py")).read()
    assert "make_history_record" in src and "append_history" in src
