"""Load-observatory tests: seeded loadgen determinism, timeline mark
schema round-trip + torn-tail tolerance, the clock-skew-tolerant
two-host merge, the exact waterfall partition invariant, the
``timeline`` serve verb, and a two-rate in-process saturation sweep
with knee detection and monotone sojourn growth."""

import json
import os

import numpy as np
import pytest

from peasoup_tpu.obs import timeline
from peasoup_tpu.obs.history import load_history
from peasoup_tpu.obs.metrics import REGISTRY
from peasoup_tpu.serve import JobSpool
from peasoup_tpu.tools import loadgen


@pytest.fixture(autouse=True)
def _fresh_registry():
    REGISTRY.reset()
    yield
    REGISTRY.reset()


# --------------------------------------------------------------------------
# deterministic mix + schedule
# --------------------------------------------------------------------------

def test_arrival_offsets_seeded_and_monotone():
    a = loadgen.arrival_offsets(4.0, 32, np.random.default_rng(11))
    b = loadgen.arrival_offsets(4.0, 32, np.random.default_rng(11))
    assert a == b  # same seed -> identical schedule
    assert all(y >= x for x, y in zip(a, a[1:]))
    assert len(a) == 32
    # mean inter-arrival ~ 1/rate (loose: 32 samples)
    assert 0.1 < a[-1] / 32 < 0.6
    c = loadgen.arrival_offsets(4.0, 8, np.random.default_rng(12))
    assert a[:8] != c  # different seed -> different schedule


def test_arrival_offsets_zero_rate_is_instant_burst():
    assert loadgen.arrival_offsets(0.0, 5,
                                   np.random.default_rng(0)) == [0.0] * 5


def test_job_mix_deterministic_with_buckets_and_poison():
    kw = dict(buckets=(2048, 4096), priorities=(0, 5),
              poison_fraction=0.25)
    a = loadgen.job_mix(16, np.random.default_rng(7), **kw)
    b = loadgen.job_mix(16, np.random.default_rng(7), **kw)
    assert a == b
    assert [s["i"] for s in a] == list(range(16))
    assert sum(s["poison"] for s in a) == 4  # round(0.25 * 16)
    assert {s["nsamps"] for s in a} <= {2048, 4096}
    assert {s["priority"] for s in a} <= {0, 5}


def test_job_mix_poison_capped_at_n():
    specs = loadgen.job_mix(3, np.random.default_rng(0),
                            poison_fraction=5.0)
    assert sum(s["poison"] for s in specs) == 3


# --------------------------------------------------------------------------
# timeline: schema round-trip, torn tail, skewed merge, partition
# --------------------------------------------------------------------------

def test_mark_roundtrip_schema(tmp_path):
    wd = str(tmp_path / "work" / "j1")
    rec = timeline.mark(wd, "submit", host="h0", attempt=0,
                        t_wall=1000.0, priority=3)
    assert rec["v"] == timeline.TIMELINE_VERSION
    (m,) = timeline.read_timeline(wd)
    assert m["phase"] == "submit"
    assert m["t_wall"] == 1000.0
    assert isinstance(m["t_mono"], float)
    assert (m["host"], m["pid"], m["attempt"]) == ("h0", os.getpid(), 0)
    assert m["priority"] == 3  # attrs ride along
    ov = timeline.overhead()
    assert ov["marks"] >= 1 and ov["seconds"] > 0


def test_read_timeline_skips_torn_tail_and_garbage(tmp_path):
    wd = str(tmp_path / "j")
    timeline.mark(wd, "submit", t_wall=1.0)
    timeline.mark(wd, "claim", t_wall=2.0)
    with open(timeline.timeline_path(wd), "a") as f:
        f.write("not json\n")
        f.write('{"phase": "bad-no-clocks"}\n')
        f.write('{"phase": "done", "t_wall": 3.0, "t_mo')  # torn tail
    phases = [m["phase"] for m in timeline.read_timeline(wd)]
    assert phases == ["submit", "claim"]


def test_read_timeline_missing_file_is_empty(tmp_path):
    assert timeline.read_timeline(str(tmp_path / "nope")) == []


def _two_host_marks(wd, *, claim_wall):
    """submit on host a; claim+done on host b whose wall clock is
    ``claim_wall`` (5 monotonic seconds of service either way)."""
    timeline.mark(wd, "submit", host="a", t_wall=1000.0, t_mono=50.0)
    timeline.mark(wd, "claim", host="b", t_wall=claim_wall,
                  t_mono=10.0)
    timeline.mark(wd, "done", host="b", t_wall=claim_wall + 5.0,
                  t_mono=15.0)
    return timeline.read_timeline(wd)


def test_stitch_two_hosts_aligned_by_wall_delta(tmp_path):
    # host b's clock agrees: claimed 2s after submit
    marks = _two_host_marks(str(tmp_path / "j"), claim_wall=1002.0)
    stitched = timeline.stitch(marks)
    assert [(m["phase"], m["t"]) for m in stitched] == [
        ("submit", 0.0), ("claim", 2.0), ("done", 7.0)]


def test_stitch_skewed_host_clamps_never_time_travels(tmp_path):
    # host b's wall clock runs 3s BEHIND: raw delta would put the
    # claim before the submit; the clamp pins it at the submit instead
    marks = _two_host_marks(str(tmp_path / "j"), claim_wall=997.0)
    stitched = timeline.stitch(marks)
    assert [(m["phase"], m["t"]) for m in stitched] == [
        ("submit", 0.0), ("claim", 0.0), ("done", 5.0)]
    assert all(m["t"] >= 0 for m in stitched)


def test_waterfall_phase_sum_equals_sojourn_exactly(tmp_path):
    wd = str(tmp_path / "j")
    marks = _two_host_marks(wd, claim_wall=1002.0)
    doc = timeline.waterfall(marks, job_id="j")
    assert doc["sojourn_s"] == pytest.approx(7.0)
    assert sum(doc["phase_s"].values()) == pytest.approx(
        doc["sojourn_s"], abs=1e-9)  # exact partition, not approx
    assert doc["phase_s"]["claim"] == pytest.approx(2.0)  # queue wait
    assert doc["phase_s"]["done"] == pytest.approx(5.0)   # service
    assert doc["outcome"] == "done"
    assert {"host": "a", "pid": os.getpid()} in doc["writers"]


def test_queue_wait_from_clamps_backwards_wall(tmp_path):
    wd = str(tmp_path / "j")
    # submit stamped by a host whose clock is AHEAD of the claimer's
    timeline.mark(wd, "submit", host="a", t_wall=2000.0, t_mono=1.0)
    wait = timeline.queue_wait_from(wd, host="b", t_wall=1990.0)
    assert wait == 0.0  # clock step cannot produce a negative wait
    assert timeline.queue_wait_from(str(tmp_path / "empty")) is None


def test_chrome_trace_events_lifecycle_and_span_rows(tmp_path):
    wd = str(tmp_path / "j")
    timeline.mark(wd, "submit", host="a", t_wall=1000.0, t_mono=1.0)
    timeline.mark(wd, "dispatch", host="a", t_wall=1002.0, t_mono=3.0,
                  dur_s=1.5, device_s=0.5)
    doc = timeline.waterfall(timeline.read_timeline(wd), job_id="j")
    events = timeline.chrome_trace_events(doc)
    tids = {e.get("tid") for e in events if e.get("ph") == "X"}
    assert {0, 1} <= tids  # lifecycle row + span-derived row


def test_span_phase_mapping():
    assert timeline.phase_for_span("Folding") == "fold"
    assert timeline.phase_for_span("Chunked-Search-3") == "dispatch"
    assert timeline.phase_for_span("Job-abc123") is None


# --------------------------------------------------------------------------
# spool integration + the timeline verb
# --------------------------------------------------------------------------

def _drain_one(tmp_path, sojourn_sleeper=None):
    from peasoup_tpu.serve import SurveyWorker

    spool = JobSpool(str(tmp_path / "jobs"))
    rec = spool.submit("/tmp/obs.fil", priority=2)
    worker = SurveyWorker(
        spool, prefetch=False,
        run_job_fn=lambda job: {"candidates": 0},
        history_path=str(tmp_path / "h.jsonl"),
        telemetry_interval_s=0.0, sleeper=lambda s: None)
    worker.drain()
    return spool, rec


def test_spool_transitions_write_marks_and_queue_wait(tmp_path):
    spool, rec = _drain_one(tmp_path)
    marks = timeline.read_timeline(spool.work_dir(rec.job_id))
    phases = [m["phase"] for m in marks]
    assert phases[0] == "submit" and "claim" in phases
    assert phases[-1] == "done"
    assert marks[0]["priority"] == 2
    done = spool.jobs("done")[0]
    assert done.queue_wait_s >= 0.0
    soj = timeline.sojourn_for(spool.work_dir(rec.job_id))
    assert soj is not None and soj >= 0.0


def test_timeline_verb_renders_waterfall(tmp_path, capsys):
    from peasoup_tpu.serve.cli import main

    spool, rec = _drain_one(tmp_path)
    wf_json = str(tmp_path / "wf.json")
    trace_json = str(tmp_path / "trace.json")
    code = main(["--spool", spool.root, "timeline", rec.job_id,
                 "--json", wf_json, "--trace_json", trace_json])
    out = capsys.readouterr().out
    assert code == 0
    assert rec.job_id in out and "sojourn" in out
    assert "phase totals:" in out
    doc = json.load(open(wf_json))
    assert sum(doc["phase_s"].values()) == pytest.approx(
        doc["sojourn_s"], abs=1e-6)
    assert doc["state"] == "done"
    trace = json.load(open(trace_json))
    assert any(e.get("ph") == "X" for e in trace["traceEvents"])


def test_timeline_verb_unknown_job_exits_nonzero(tmp_path, capsys):
    from peasoup_tpu.serve.cli import main

    JobSpool(str(tmp_path / "jobs"))
    code = main(["--spool", str(tmp_path / "jobs"), "timeline",
                 "no-such-job"])
    assert code == 1
    assert "no timeline marks" in capsys.readouterr().err


# --------------------------------------------------------------------------
# saturation sweep (in-process stub workers)
# --------------------------------------------------------------------------

def test_detect_knee_orders_and_thresholds():
    points = [
        {"offered_rate_per_s": 1.0, "realized_rate_per_s": 1.0,
         "achieved_per_s": 0.99},
        {"offered_rate_per_s": 4.0, "realized_rate_per_s": 4.0,
         "achieved_per_s": 3.8},
        {"offered_rate_per_s": 16.0, "realized_rate_per_s": 16.0,
         "achieved_per_s": 5.0},
    ]
    knee = loadgen.detect_knee(points)
    assert knee["rate_per_s"] == 4.0
    assert knee["throughput_per_s"] == 3.8
    assert knee["saturated"] is True


def test_detect_knee_all_saturated_reports_first_point_capacity():
    points = [{"offered_rate_per_s": 8.0, "realized_rate_per_s": 8.0,
               "achieved_per_s": 2.0}]
    knee = loadgen.detect_knee(points)
    assert knee["rate_per_s"] == 8.0
    assert knee["throughput_per_s"] == 2.0
    assert knee["saturated"] is True


def test_two_rate_inprocess_sweep_knee_and_monotone_sojourn(tmp_path):
    """One rate well under the stub capacity (1/service_s = 50/s),
    one far over: the sweep must keep up at the low rate, saturate at
    the high one, and show sojourn growing with offered load."""
    history = str(tmp_path / "history.jsonl")
    doc = loadgen.sweep(
        str(tmp_path / "sweep"), rates=[8.0, 200.0], jobs=12, seed=5,
        history=history, timeout_s=60.0, inprocess=True,
        service_s=0.02, verbose=False)
    lo, hi = doc["points"]
    assert lo["done"] == 12 and hi["done"] == 12
    assert not lo["timed_out"] and not hi["timed_out"]
    # the saturated point's sojourn dominates the underloaded one's
    assert hi["sojourn"]["p50_s"] > lo["sojourn"]["p50_s"]
    assert hi["sojourn"]["p95_s"] > lo["sojourn"]["p95_s"]
    # knee = the low rate point (the high one can't keep up)
    assert doc["knee"]["rate_per_s"] == 8.0
    assert doc["knee"]["saturated"] is True
    assert doc["knee"]["throughput_per_s"] == lo["achieved_per_s"]
    # percentile ordering within every point
    for p in (lo, hi):
        s = p["sojourn"]
        assert s["p50_s"] <= s["p95_s"] <= s["p99_s"]
        assert p["phases"]  # phase decomposition present
        assert sum(ph["mean_s"] * s["n"] for ph in
                   p["phases"].values()) == pytest.approx(
            s["mean_s"] * s["n"], rel=1e-3)
    # report written + ledger record with the knee
    report = json.load(open(os.path.join(str(tmp_path / "sweep"),
                                         loadgen.REPORT_BASENAME)))
    assert len(report["points"]) == 2
    (rec,) = load_history(history, kinds=["loadgen"])
    assert rec["metrics"]["knee_throughput_per_s"] == \
        doc["knee"]["throughput_per_s"]
    assert rec["metrics"]["jobs_total"] == 24
    assert len(rec["rates"]) == 2


def test_sweep_is_seed_deterministic_in_schedule(tmp_path):
    """Same seed -> same specs and offsets (the timing measurements
    differ run to run; the INPUTS must not)."""
    rng1 = np.random.default_rng(21)
    rng2 = np.random.default_rng(21)
    specs1 = loadgen.job_mix(20, rng1, buckets=(2048, 4096),
                             poison_fraction=0.1)
    specs2 = loadgen.job_mix(20, rng2, buckets=(2048, 4096),
                             poison_fraction=0.1)
    assert specs1 == specs2
    assert loadgen.arrival_offsets(3.0, 20, rng1) == \
        loadgen.arrival_offsets(3.0, 20, rng2)
