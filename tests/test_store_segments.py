"""Log-structured candidate store tests (ISSUE 20): sealed-segment
compaction equivalence, the pinned merge order, cand_id retention /
dedup, compactor crash drills (killed at every fault stage), seeded
coincidence vs the full distill, the query-service inbox + latency
ledger, the new health rules, the supervisor's ``compact_store``
action, and the ``why`` verb's sidecar-index join."""

import json
import os
import time

import pytest

from peasoup_tpu.serve import segments as seglib
from peasoup_tpu.serve import supervisor as sup_mod
from peasoup_tpu.serve.compaction import (
    CompactionPolicy,
    Compactor,
    shard_tail_sizes,
)
from peasoup_tpu.serve.health import (
    CRIT,
    OK,
    WARN,
    DEFAULT_SLO,
    HealthContext,
    rule_query_latency,
    rule_shard_backlog,
)
from peasoup_tpu.serve.queue import JobSpool
from peasoup_tpu.serve.store import (
    CandidateStore,
    ShardedCandidateStore,
    _distill_groups,
)
from peasoup_tpu.utils.atomicio import atomic_writer


class _C:
    def __init__(self, freq, snr, dm=10.0):
        self.freq = freq
        self.snr = snr
        self.dm = dm
        self.acc = 0.0
        self.folded_snr = 0.0
        self.nh = 0


def _populate(root, *, hosts=3, jobs=12, per_job=8, seed=7):
    """A deterministic multi-shard survey with coincident signals:
    every 4th job re-detects the same frequencies from a different
    source so ``coincident_groups`` has real work."""
    import random

    rng = random.Random(seed)
    stores = [ShardedCandidateStore(root, host_label=f"host{h}")
              for h in range(hosts)]
    base_freqs = [rng.uniform(1.0, 80.0) for _ in range(4)]
    for j in range(jobs):
        cands = []
        for i in range(per_job):
            if i < len(base_freqs) and j % 4 == 0:
                f = base_freqs[i] * (1.0 + rng.uniform(-3e-5, 3e-5))
            else:
                f = rng.uniform(0.5, 120.0)
            cands.append(_C(f, rng.uniform(7.0, 25.0)))
        stores[j % hosts].ingest(f"job-{j:03d}", f"obs{j:03d}.fil",
                                 cands, utc=1000.0 + j)
    return ShardedCandidateStore(root)


def _snapshot(store):
    # records() order is documented to change at compaction (sealed
    # segments are freq-sorted); the record SET is what must be
    # preserved, so normalise by the canonical total order.  query()
    # and coincident_groups() are canonically ordered on every path
    # and compared exactly.
    return {
        "records": sorted(store.records(),
                          key=seglib.record_sort_key),
        "count": store.count(),
        "sources": store.sources(),
        "shard_counts": store.shard_counts(),
        "q1": store.query(10.0, freq_tol=1e-3, max_harm=4),
        "q2": store.query(40.0, freq_tol=5e-4, max_harm=2),
        "groups": store.coincident_groups(freq_tol=1e-4,
                                          min_sources=2),
    }


# --------------------------------------------------------------------------
# compaction equivalence
# --------------------------------------------------------------------------

def test_compaction_round_trip_identical(tmp_path):
    """Every read verb answers record-identically before and after
    compaction — same records, same ORDER — and the post-compaction
    query touches only indexed spans, never the full store."""
    store = _populate(str(tmp_path), jobs=30, per_job=20)
    before = _snapshot(store)
    # pre-compaction: full scans see all 3 shards
    assert len(store.shard_files()) == 3
    assert before["count"] == 30 * 20

    report = Compactor(str(tmp_path),
                       CompactionPolicy(min_bytes=1)) \
        .compact_once(force=True)
    assert report["compacted"] and report["records"] == 600

    after = _snapshot(store)
    for key in before:
        assert after[key] == before[key], key
    # the equivalence is not vacuous: sealed reads really served it
    man = seglib.load_manifest(str(tmp_path))
    assert len(man["segments"]) == 1
    assert sum(t for t in shard_tail_sizes(str(tmp_path)).values()) \
        == 0
    # a fresh single-window query reads one fence-post stride (fence
    # granularity is 256 records), not the whole segment
    store.query(10.0, freq_tol=1e-3, max_harm=1)
    reads = store.last_read_stats
    assert reads.get("tail_lines", 0) == 0
    assert reads.get("fence_seeks", 0) == 1
    assert 0 < reads.get("range_lines", 0) < 600
    # count() comes from the manifest: no record parsing at all
    store.count()
    assert store.last_read_stats.get("segment_lines", 0) == 0


def test_second_compaction_accretes_and_merged_reads_hold(tmp_path):
    """New ingests after a compaction land in the tail; a second
    compaction seals a second segment; the merged view stays exact
    through every intermediate state."""
    store = _populate(str(tmp_path))
    comp = Compactor(str(tmp_path), CompactionPolicy(min_bytes=1))
    comp.compact_once(force=True)
    frozen = store.records()

    late = ShardedCandidateStore(str(tmp_path), host_label="late")
    late.ingest("job-late", "late.fil", [_C(10.0, 30.0)], utc=5000.0)
    assert store.count() == len(frozen) + 1
    assert [r for r in store.records()
            if r["job_id"] == "job-late"]

    comp.compact_once(force=True)
    man = seglib.load_manifest(str(tmp_path))
    assert [s["name"] for s in man["segments"]] == ["seg-000001",
                                                    "seg-000002"]
    assert store.count() == len(frozen) + 1
    assert len([r for r in store.records()
                if r["job_id"] == "job-late"]) == 1


# --------------------------------------------------------------------------
# pinned merge order
# --------------------------------------------------------------------------

def test_merge_order_legacy_first_then_sorted_shards(tmp_path):
    """The documented total order: legacy ``candidates.jsonl`` FIRST,
    then ``store-*.jsonl`` sorted by basename — covering a shard that
    sorts after the legacy file's name."""
    legacy = CandidateStore(str(tmp_path / "candidates.jsonl"))
    legacy.ingest("j-legacy", "legacy.fil", [_C(5.0, 9.0)], utc=1.0)
    # "store-aaa" < "store-zzz"; both sort AFTER "candidates.jsonl"
    # alphabetically, but the legacy file is pinned first regardless
    for host, utc in (("zzz", 2.0), ("aaa", 3.0)):
        s = ShardedCandidateStore(str(tmp_path), host_label=host)
        s.ingest(f"j-{host}", f"{host}.fil", [_C(6.0 + utc, 9.0)],
                 utc=utc)
    store = ShardedCandidateStore(str(tmp_path))
    names = [os.path.basename(p) for p in store.shard_files()]
    assert names == ["candidates.jsonl", "store-aaa.jsonl",
                     "store-zzz.jsonl"]
    assert [r["job_id"] for r in store.records()] == \
        ["j-legacy", "j-aaa", "j-zzz"]
    # the order survives compaction (segment writes re-sort by freq,
    # but the merged stream stays deterministic and complete)
    Compactor(str(tmp_path),
              CompactionPolicy(min_bytes=1)).compact_once(force=True)
    assert sorted(r["job_id"] for r in store.records()) == \
        ["j-aaa", "j-legacy", "j-zzz"]


# --------------------------------------------------------------------------
# retention / dedup
# --------------------------------------------------------------------------

def test_reingest_same_cand_id_replaces_never_duplicates(tmp_path):
    """A re-run writing the same cand_id replaces the old record in
    every read — across tail-vs-tail, tail-vs-sealed and
    sealed-vs-sealed (``supersedes``) generations."""
    store = ShardedCandidateStore(str(tmp_path), host_label="h0")
    cand = _C(12.345, 10.0)
    store.ingest("run-a", "beam.fil", [cand], utc=100.0)
    n0 = store.count()
    comp = Compactor(str(tmp_path), CompactionPolicy(min_bytes=1))
    comp.compact_once(force=True)

    # same run/candidate identity -> same cand_id, newer utc
    store.ingest("run-a", "beam.fil", [cand], utc=200.0)
    merged = ShardedCandidateStore(str(tmp_path))
    assert merged.count() == n0
    [rec] = [r for r in merged.records() if r["freq"] == cand.freq]
    assert rec["utc"] == 200.0  # tail copy shadows the sealed copy

    comp.compact_once(force=True)  # seals the replacement
    man = seglib.load_manifest(str(tmp_path))
    assert man["segments"][1]["supersedes"] == 1
    assert merged.count() == n0
    [rec] = [r for r in merged.records() if r["freq"] == cand.freq]
    assert rec["utc"] == 200.0  # later segment supersedes earlier
    # the indexed join sees exactly the survivor too
    hits = merged.lookup(rec["cand_id"])
    assert [r["utc"] for r, _origin in hits] == [200.0]


# --------------------------------------------------------------------------
# crash safety: compactor killed at every stage
# --------------------------------------------------------------------------

def test_compactor_kill_all_stages_zero_record_loss(tmp_path):
    """tools/chaos.py ``compactor_kill``: a compaction subprocess is
    ``os._exit``-killed at each fault stage.  After every kill the
    merged read sees exactly one copy of each record, the manifest is
    the old one (or absent), and a subsequent clean compaction (which
    also sweeps orphan files) converges to the identical answer."""
    from peasoup_tpu.tools.chaos import compactor_kill

    store = _populate(str(tmp_path), hosts=2, jobs=6, per_job=5)
    expected = sorted(store.records(), key=seglib.record_sort_key)
    assert len(expected) == 30

    for stage in ("scan", "segment_partial", "segment_done",
                  "index_done", "pre_manifest"):
        rc = compactor_kill(str(tmp_path), stage)
        assert rc == 137, f"fault at {stage} did not fire (rc={rc})"
        # no manifest was ever committed -> reads fall back to the
        # untouched JSONL shards, record-identical
        assert seglib.load_manifest(str(tmp_path))["segments"] == []
        got = sorted(ShardedCandidateStore(str(tmp_path)).records(),
                     key=seglib.record_sort_key)
        assert got == expected, \
            f"record set changed after kill at {stage}"

    report = Compactor(str(tmp_path),
                       CompactionPolicy(min_bytes=1)) \
        .compact_once(force=True)
    assert report["compacted"] and report["records"] == 30
    got = sorted(ShardedCandidateStore(str(tmp_path)).records(),
                 key=seglib.record_sort_key)
    assert got == expected
    # orphans from the killed attempts were swept under the lock
    segdir = seglib.segment_dir(str(tmp_path))
    leftovers = [n for n in os.listdir(segdir)
                 if n.startswith(seglib.SEG_PREFIX)
                 and "seg-000001" not in n]
    assert leftovers == [], leftovers


def test_compactor_lock_excludes_and_steals_stale(tmp_path):
    store = ShardedCandidateStore(str(tmp_path), host_label="h0")
    store.ingest("j", "a.fil", [_C(9.0, 9.0)], utc=1.0)
    segdir = seglib.segment_dir(str(tmp_path))
    os.makedirs(segdir, exist_ok=True)
    lock = os.path.join(segdir, "compact.lock")
    # a live-pid lock (this process) blocks compaction
    with open(lock, "x") as f:
        json.dump({"pid": os.getpid(), "utc": time.time()}, f)
    report = Compactor(str(tmp_path),
                       CompactionPolicy(min_bytes=1)) \
        .compact_once(force=True)
    assert not report["compacted"] and report["reason"] == "locked"
    # a dead-pid lock is stale: stolen, compaction proceeds
    os.unlink(lock)
    with open(lock, "x") as f:
        json.dump({"pid": 2 ** 22 + 1, "utc": 0.0}, f)
    report = Compactor(str(tmp_path),
                       CompactionPolicy(min_bytes=1)) \
        .compact_once(force=True)
    assert report["compacted"]


# --------------------------------------------------------------------------
# seeded coincidence == full distill
# --------------------------------------------------------------------------

def test_seeded_coincidence_equals_full_distill(tmp_path):
    """The bin-seeded ``coincident_groups`` must reproduce the full
    distill exactly — before compaction (tail bins), after (segment
    bins), and with the bins sidecars deleted (gap-scan fallback)."""
    store = _populate(str(tmp_path), hosts=3, jobs=16, per_job=6)
    for tol, nsrc in ((1e-4, 2), (1e-3, 2), (1e-4, 3)):
        expected = _distill_groups(store.records(), tol, nsrc)
        assert store.coincident_groups(tol, nsrc) == expected, \
            (tol, nsrc)

    Compactor(str(tmp_path),
              CompactionPolicy(min_bytes=1)).compact_once(force=True)
    for tol, nsrc in ((1e-4, 2), (1e-3, 2)):
        expected = _distill_groups(store.records(), tol, nsrc)
        assert store.coincident_groups(tol, nsrc) == expected

    # late tail + deleted bins sidecars: the reader's gap scan closes
    # the under-approximation
    late = ShardedCandidateStore(str(tmp_path), host_label="late")
    late.ingest("jl", "late.fil", [_C(10.0, 20.0), _C(10.0003, 19.0)],
                utc=9000.0)
    segdir = seglib.segment_dir(str(tmp_path))
    for name in os.listdir(segdir):
        if name.startswith("bins-"):
            os.unlink(os.path.join(segdir, name))
    expected = _distill_groups(store.records(), 1e-4, 2)
    assert store.coincident_groups(1e-4, 2) == expected


# --------------------------------------------------------------------------
# query service
# --------------------------------------------------------------------------

def test_query_service_inbox_round_trip_and_ledger(tmp_path):
    from peasoup_tpu.serve.query_service import (
        QueryService,
        result_path,
        submit_request,
    )

    store = _populate(str(tmp_path))
    Compactor(str(tmp_path),
              CompactionPolicy(min_bytes=1)).compact_once(force=True)
    rec = store.records()[0]
    ledger = str(tmp_path / "history.jsonl")

    rid_q = submit_request(str(tmp_path), {
        "op": "query", "freq": 10.0, "freq_tol": 1e-3,
        "max_harm": 4})
    rid_w = submit_request(str(tmp_path), {
        "op": "why", "cand_id": rec["cand_id"][:12]})
    rid_bad = submit_request(str(tmp_path), {"op": "nonsense"})

    svc = QueryService(str(tmp_path), ledger_path=ledger)
    assert svc.poll_once() == 3
    with open(result_path(str(tmp_path), rid_q)) as f:
        res_q = json.load(f)
    assert res_q["ok"] and res_q["id"] == rid_q
    assert res_q["records"] == store.query(10.0, freq_tol=1e-3,
                                           max_harm=4)
    with open(result_path(str(tmp_path), rid_w)) as f:
        res_w = json.load(f)
    assert res_w["ok"]
    assert [r["cand_id"] for r in res_w["records"]] \
        == [rec["cand_id"]]
    assert res_w["records"][0]["_origin"].startswith("seg-")
    with open(result_path(str(tmp_path), rid_bad)) as f:
        res_bad = json.load(f)
    assert not res_bad["ok"] and "nonsense" in res_bad["error"]
    # malformed requests were consumed, not left to loop forever
    assert svc.poll_once() == 0

    with open(ledger) as f:
        led = [json.loads(line) for line in f]
    assert [r["kind"] for r in led] == ["query"] * 3
    assert all("query_latency_ms" in r["metrics"] for r in led)
    assert {r["config"]["op"] for r in led} == {"query", "why",
                                                "nonsense"}
    assert [r["config"]["ok"] for r in led].count(False) == 1


# --------------------------------------------------------------------------
# health rules + supervisor action
# --------------------------------------------------------------------------

def _ctx(ledger=(), store_tails=None, now=10_000.0):
    return HealthContext(
        now=now, samples=[], recent=[], latest={},
        queue={"pending": 0, "running": 0, "done": 0, "failed": 0},
        running=[], ledger=list(ledger),
        store_tails=dict(store_tails or {}))


def _qrec(latency_ms, utc):
    return {"kind": "query", "utc": utc,
            "metrics": {"query_latency_ms": latency_ms,
                        "result_records": 1}}


def test_rule_query_latency_tiers():
    now = 10_000.0
    [f] = rule_query_latency(_ctx())
    assert f.severity == OK and f.data["requests"] == 0
    fast = [_qrec(5.0, now - 1.0) for _ in range(20)]
    [f] = rule_query_latency(_ctx(fast))
    assert f.severity == OK and f.data["p50_ms"] == 5.0
    slow_p50 = [_qrec(DEFAULT_SLO["query_p50_ms"] + 50.0, now - 1.0)
                for _ in range(20)]
    [f] = rule_query_latency(_ctx(slow_p50))
    assert f.severity == WARN
    tail = fast + [_qrec(DEFAULT_SLO["query_p95_ms"] * 2, now - 1.0)
                   for _ in range(20)]
    [f] = rule_query_latency(_ctx(tail))
    assert f.severity == CRIT
    # stale traffic outside the window is invisible
    [f] = rule_query_latency(_ctx([_qrec(9e9, now - 9000.0)]))
    assert f.severity == OK and f.data["requests"] == 0


def test_rule_shard_backlog_tiers():
    from peasoup_tpu.serve.compaction import DEFAULT_MIN_BYTES

    [f] = rule_shard_backlog(_ctx())
    assert f.severity == OK
    [f] = rule_shard_backlog(_ctx(store_tails={"a.jsonl": 1024}))
    assert f.severity == OK
    [f] = rule_shard_backlog(
        _ctx(store_tails={"a.jsonl": DEFAULT_MIN_BYTES}))
    assert f.severity == WARN
    [f] = rule_shard_backlog(
        _ctx(store_tails={"a.jsonl": 4 * DEFAULT_MIN_BYTES,
                          "b.jsonl": 10}))
    assert f.severity == CRIT
    assert f.data["worst_shard"] == "a.jsonl"


def test_supervisor_compact_store_action_fires(tmp_path, monkeypatch):
    """A ``shard_backlog`` WARN finding makes the supervisor run a
    real compaction on its spool — the background-compaction trigger
    end to end."""
    spool = JobSpool(str(tmp_path / "jobs"))
    store = ShardedCandidateStore(spool.root, host_label="h0")
    store.ingest("j0", "a.fil", [_C(10.0 + i, 9.0) for i in range(6)],
                 utc=1.0)

    finding = {"rule": "shard_backlog", "severity": WARN,
               "message": "injected", "host": "",
               "data": {"worst_shard": "store-h0.jsonl"}}

    def fake_evaluate(ctx):
        return {"v": 1, "utc": 0.0, "severity": WARN,
                "findings": [dict(finding)], "queue": {}, "hosts": []}

    monkeypatch.setattr(sup_mod, "evaluate", fake_evaluate)
    t = [0.0]
    sup = sup_mod.Supervisor(
        spool, interval_s=0.0,
        history_path=str(tmp_path / "sup.jsonl"),
        ledger_path=str(tmp_path / "ledger.jsonl"),
        clock=lambda: t[0], out=lambda *_: None)
    results = sup.tick()
    assert [r["action"] for r in results] == ["compact_store"]
    assert results[0]["executed"]
    assert results[0]["outcome"]["compacted"]
    man = seglib.load_manifest(spool.root)
    assert man["segments"] and man["segments"][0]["records"] == 6
    # the cooldown holds the action back on an immediate re-fire
    t[0] = 1.0
    results = sup.tick()
    assert results and results[0].get("throttled")
    # after the cooldown, with nothing left to fold, the action is
    # inapplicable (no entry, no cooldown burned) rather than a fake
    # "executed" that would eat the actions-per-window budget
    t[0] = 120.0
    assert sup.tick() == []


# --------------------------------------------------------------------------
# the why verb reads the sidecar index
# --------------------------------------------------------------------------

def test_why_verb_identical_pre_and_post_compaction(tmp_path, capsys):
    from peasoup_tpu.serve.cli import main

    store = _populate(str(tmp_path), hosts=2, jobs=4, per_job=4)
    rec = store.records()[5]
    prefix = rec["cand_id"][:12]

    assert main(["--spool", str(tmp_path), "why", prefix]) == 0
    before = capsys.readouterr().out
    assert rec["cand_id"] in before

    Compactor(str(tmp_path),
              CompactionPolicy(min_bytes=1)).compact_once(force=True)
    assert main(["--spool", str(tmp_path), "why", prefix]) == 0
    after = capsys.readouterr().out
    assert after == before
    # and the join really was indexed: one seek, one line
    merged = ShardedCandidateStore(str(tmp_path))
    merged.lookup(prefix)
    reads = merged.last_read_stats
    assert reads.get("lookup_lines", 0) == 1
    assert reads.get("tail_lines", 0) == 0

    # ambiguity semantics survive the reroute: a prefix matching two
    # distinct cand_ids still errors out
    ids = sorted({r["cand_id"] for r in merged.records()})
    common = os.path.commonprefix([ids[0], ids[1]])
    if not common:  # sha-based ids: first hex chars may differ
        common = ""
    rc = main(["--spool", str(tmp_path), "why", common])
    assert rc == 1
    assert "ambiguous" in capsys.readouterr().err


# --------------------------------------------------------------------------
# loadgen query mix + atomic_writer
# --------------------------------------------------------------------------

def test_loadgen_query_mix_seeded_and_summarised(tmp_path):
    from peasoup_tpu.tools.loadgen import query_mix, run_query_mix

    _populate(str(tmp_path))
    Compactor(str(tmp_path),
              CompactionPolicy(min_bytes=1)).compact_once(force=True)
    ledger = str(tmp_path / "history.jsonl")
    doc = run_query_mix(str(tmp_path), 30, seed=3, history=ledger)
    assert doc["requests"] == 30 and doc["failures"] == 0
    assert set(doc["per_op"]) <= {"query", "coincidence", "why"}
    assert "query" in doc["per_op"]  # 70% weight: always present
    assert doc["query_p50_ms"] > 0
    with open(ledger) as f:
        assert sum(1 for _ in f) == 30  # one kind:"query" per request

    # determinism: same seed -> identical request stream
    import random
    a = query_mix(25, random.Random(5), freqs=[1.0, 2.0],
                  cand_ids=["abc", "def"])
    b = query_mix(25, random.Random(5), freqs=[1.0, 2.0],
                  cand_ids=["abc", "def"])
    assert a == b


def test_atomic_writer_publishes_or_leaves_nothing(tmp_path):
    path = str(tmp_path / "artifact.txt")
    with atomic_writer(path) as f:
        f.write("generation 1\n")
        assert not os.path.exists(path)  # invisible until the rename
    with open(path) as f:
        assert f.read() == "generation 1\n"
    with pytest.raises(RuntimeError):
        with atomic_writer(path) as f:
            f.write("torn garbage")
            raise RuntimeError("writer died")
    with open(path) as f:
        assert f.read() == "generation 1\n"  # old generation intact
    assert [n for n in os.listdir(tmp_path) if ".tmp" in n] == []
