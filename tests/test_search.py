import numpy as np
import pytest

from peasoup_tpu.data import Candidate
from peasoup_tpu.search import (
    AccelerationPlan,
    AccelerationDistiller,
    CandidateScorer,
    DMDistiller,
    HarmonicDistiller,
    SearchConfig,
    prev_power_of_two,
)


def test_prev_power_of_two():
    assert prev_power_of_two(187520) == 131072
    # reference quirk: an exact power of two maps to its half (the loop
    # condition is n*2 < val, utils.hpp:12-18)
    assert prev_power_of_two(131072) == 65536
    assert prev_power_of_two(131073) == 131072
    assert prev_power_of_two(3) == 2


class TestAccelerationPlan:
    # tutorial.fil params: size=131072, tsamp=0.00032, cfreq=1475.12,
    # foff=-1.09
    ARGS = dict(tol=1.10, nsamps=131072, tsamp=0.00032,
                cfreq=1510.0 - 1.09 * 32, bw=-1.09)

    def test_equal_range_gives_zero_only(self):
        plan = AccelerationPlan(acc_lo=0.0, acc_hi=0.0, pulse_width=64.0,
                                **self.ARGS)
        np.testing.assert_array_equal(plan.generate_accel_list(0.0), [0.0])

    def test_golden_grid_with_2014_pulse_width(self):
        # The 2014 golden output (acceleration_trials count=3: 0,-5,5)
        # corresponds to pulse_width=64000 under the current formula
        # (utils.hpp:165 divides by 1e3).
        plan = AccelerationPlan(acc_lo=-5.0, acc_hi=5.0, pulse_width=64000.0,
                                **self.ARGS)
        acc = plan.generate_accel_list(0.0)
        np.testing.assert_allclose(acc, [0.0, -5.0, 5.0])

    def test_zero_always_included_and_ends_capped(self):
        plan = AccelerationPlan(acc_lo=-5.0, acc_hi=5.0, pulse_width=64.0,
                                **self.ARGS)
        acc = plan.generate_accel_list(0.0)
        assert acc[0] == 0.0  # explicitly forced
        assert acc[1] == -5.0
        assert acc[-1] == 5.0
        assert len(acc) > 3  # ~0.24 m/s^2 steps with the current formula
        steps = np.diff(acc[1:])
        assert np.all(steps > 0)

    def test_step_grows_with_dm(self):
        plan = AccelerationPlan(acc_lo=-50.0, acc_hi=50.0, pulse_width=64.0,
                                **self.ARGS)
        assert len(plan.generate_accel_list(0.0)) >= len(
            plan.generate_accel_list(5000.0)
        )


class TestDistillers:
    def mk(self, freq, snr, dm=10.0, dm_idx=3, acc=0.0, nh=0):
        return Candidate(dm=dm, dm_idx=dm_idx, acc=acc, nh=nh, snr=snr,
                         freq=freq)

    def test_harmonic_distiller_absorbs_harmonics(self):
        cands = [
            self.mk(4.0, 50.0),       # fundamental
            self.mk(8.0, 20.0),       # 2nd harmonic
            self.mk(2.0, 10.0, nh=1),  # 1/2 fractional harmonic
            self.mk(5.3, 15.0),       # unrelated
        ]
        out = HarmonicDistiller(1e-4, 16, keep_related=True).distill(cands)
        freqs = sorted(c.freq for c in out)
        assert freqs == [4.0, 5.3]
        fund = [c for c in out if c.freq == 4.0][0]
        assert fund.count_assoc() == 2

    def test_acceleration_distiller(self):
        tobs = 41.94304
        f = 4.0
        drift = 5.0 * f * tobs / 299792458.0  # df for da=5
        cands = [
            self.mk(f, 50.0, acc=0.0),
            self.mk(f + 0.5 * drift, 20.0, acc=5.0),  # inside drift window
            self.mk(f + 1.0, 15.0, acc=5.0),          # far outside
        ]
        out = AccelerationDistiller(tobs, 1e-4, True).distill(cands)
        assert len(out) == 2
        assert out[0].count_assoc() == 1

    def test_dm_distiller(self):
        cands = [
            self.mk(4.0, 50.0, dm=10.0),
            self.mk(4.00001, 20.0, dm=20.0),
            self.mk(4.2, 15.0, dm=20.0),
        ]
        out = DMDistiller(1e-4, True).distill(cands)
        assert len(out) == 2

    def test_distill_sorts_by_snr(self):
        cands = [self.mk(3.0, 10.0), self.mk(7.0, 30.0), self.mk(5.0, 20.0)]
        out = HarmonicDistiller(1e-4, 16, False).distill(cands)
        assert [c.snr for c in out] == [30.0, 20.0, 10.0]

    @pytest.mark.parametrize("seed", range(5))
    def test_native_distill_matches_numpy(self, seed, monkeypatch):
        """The native greedy distill must be bit-identical to the numpy
        matches() path, assoc structure included."""
        rng = np.random.default_rng(seed)
        n = 120
        base = rng.uniform(1.0, 30.0, 8)
        freqs = np.concatenate([
            b * rng.integers(1, 5, 15) * (1 + rng.normal(0, 3e-5, 15))
            for b in base
        ])

        def mk_set():
            return [
                self.mk(float(f), float(s), acc=float(a), nh=int(h))
                for f, s, a, h in zip(
                    freqs,
                    rng.permutation(np.linspace(10, 90, n)),
                    rng.choice([0.0, -5.0, 5.0], n),
                    rng.integers(0, 5, n),
                )
            ]

        rng2 = np.random.default_rng(seed)  # same draws for both sets
        rng, saved = rng2, rng
        a_set = mk_set()
        rng = saved
        b_set = [
            Candidate(dm=c.dm, dm_idx=c.dm_idx, acc=c.acc, nh=c.nh,
                      snr=c.snr, freq=c.freq)
            for c in a_set
        ]

        import peasoup_tpu.search.distill as dst

        for cls, args in [
            (HarmonicDistiller, (1e-4, 16, True)),
            (AccelerationDistiller, (41.94, 1e-4, True)),
            (DMDistiller, (1e-4, True)),
        ]:
            a_in = [c for c in a_set]
            b_in = [c for c in b_set]
            native_out = cls(*args).distill(a_in)
            monkeypatch.setattr(dst, "_native_lib", lambda: None)
            numpy_out = cls(*args).distill(b_in)
            monkeypatch.undo()
            assert len(native_out) == len(numpy_out)
            for x, y in zip(native_out, numpy_out):
                assert x.freq == y.freq and x.snr == y.snr
                assert x.count_assoc() == y.count_assoc()
            for c in a_set + b_set:
                c.assoc = []


class TestScorer:
    def test_scoring(self):
        scorer = CandidateScorer(0.00032, 1475.12, -1.09, 1.09 * 64)
        cand = Candidate(dm=30.0, dm_idx=9, acc=0.0, nh=4, snr=80.0, freq=4.0)
        cand.assoc = [
            Candidate(dm=29.6, dm_idx=9, snr=40.0, freq=4.0),
            Candidate(dm=33.0, dm_idx=10, snr=30.0, freq=4.0),
        ]
        scorer.score(cand)
        assert cand.is_physical  # foff < 0 makes the smear delay negative
        assert cand.is_adjacent  # dm_idx 10 is adjacent to 9
        assert 0 < cand.ddm_count_ratio <= 1
        assert 0 < cand.ddm_snr_ratio <= 1


class TestCustomDMList:
    """User-supplied DM grids (``dedisp_set_dm_list``,
    `dedisperser.hpp:34-48`) via SearchConfig.dm_list / --dm_file."""

    def _base(self, tutorial_fil):
        from peasoup_tpu.io import read_filterbank
        from peasoup_tpu.search.pipeline import PulsarSearch
        from peasoup_tpu.search.plan import SearchConfig

        fil = read_filterbank(tutorial_fil)
        cfg = SearchConfig(
            dm_start=0.0, dm_end=60.0, acc_start=-5.0, acc_end=5.0,
            acc_pulse_width=64000.0, nharmonics=4, npdmp=0, limit=50,
        )
        return fil, cfg, PulsarSearch(fil, cfg)

    def test_explicit_list_overrides_grid(self, tutorial_fil):
        from peasoup_tpu.search.pipeline import PulsarSearch
        from peasoup_tpu.search.plan import SearchConfig

        fil, _, base = self._base(tutorial_fil)
        cfg = SearchConfig(
            dm_list=np.asarray(base.dm_list), acc_start=-5.0, acc_end=5.0,
            acc_pulse_width=64000.0, nharmonics=4, npdmp=0, limit=50,
        )
        search = PulsarSearch(fil, cfg)
        np.testing.assert_array_equal(search.dm_list, base.dm_list)
        a, b = base.run(), search.run()
        assert len(a.candidates) == len(b.candidates)
        for x, y in zip(a.candidates, b.candidates):
            assert x.freq == y.freq and x.snr == y.snr and x.dm == y.dm

    def test_dm_file(self, tutorial_fil, tmp_path):
        from peasoup_tpu.search.pipeline import PulsarSearch, load_dm_file
        from peasoup_tpu.search.plan import SearchConfig

        fil, _, base = self._base(tutorial_fil)
        path = tmp_path / "dms.txt"
        lines = (["# custom grid"]
                 + [f"{float(dm)!r}" for dm in base.dm_list] + [""])
        path.write_text("\n".join(lines))
        np.testing.assert_array_equal(load_dm_file(str(path)), base.dm_list)
        cfg = SearchConfig(dm_file=str(path))
        search = PulsarSearch(fil, cfg)
        np.testing.assert_array_equal(search.dm_list, base.dm_list)

    def test_empty_list_raises(self, tutorial_fil):
        from peasoup_tpu.io import read_filterbank
        from peasoup_tpu.search.pipeline import PulsarSearch
        from peasoup_tpu.search.plan import SearchConfig

        fil = read_filterbank(tutorial_fil)
        with pytest.raises(ValueError):
            PulsarSearch(fil, SearchConfig(dm_list=[]))


class TestDumpDir:
    """--dump_dir debug buffer dumps (`Utils::dump_device_buffer`,
    `include/utils/utils.hpp:62-72`)."""

    def test_dump_whiten_stages(self, tutorial_fil, tmp_path):
        import jax.numpy as jnp

        from peasoup_tpu.io import read_filterbank
        from peasoup_tpu.search.pipeline import PulsarSearch, whiten_trial
        from peasoup_tpu.search.plan import SearchConfig

        fil = read_filterbank(tutorial_fil)
        dump = tmp_path / "dumps"
        cfg = SearchConfig(
            dm_list=[0.0, 30.0], acc_start=0.0, acc_end=0.0, npdmp=0,
            dump_dir=str(dump),
        )
        search = PulsarSearch(fil, cfg)
        search.run()
        for idx in (0, 1):
            files = {
                name: np.load(dump / f"trial{idx:04d}_{name}.npy")
                for name in ("tim", "pspec", "median", "interp_spec",
                             "tim_white")
            }
            nspec = search.size // 2 + 1
            assert files["tim"].shape == (search.size,)
            assert files["pspec"].shape == (nspec,)
            assert files["median"].shape == (nspec,)
            assert files["interp_spec"].shape == (nspec,)
            # the dumped whitened series must match the series the
            # search used (last-ulp differences allowed: the dump path
            # recomputes outside the jitted program, so XLA fusion
            # boundaries differ)
            tim_w, _, _ = whiten_trial(
                jnp.asarray(files["tim"]), jnp.zeros(0, np.float32),
                jnp.zeros(0, np.float32), search.bin_width,
                cfg.boundary_5_freq, cfg.boundary_25_freq, False,
            )
            np.testing.assert_allclose(
                files["tim_white"], np.asarray(tim_w),
                rtol=1e-4, atol=1e-6)


class TestNumericGuards:
    def test_staircase_rejects_extreme_shift(self):
        from peasoup_tpu.ops.resample import _staircase_tables_np

        with pytest.raises(ValueError, match="4\\*max_shift"):
            _staircase_tables_np(np.array([1e-4]), n=1024, max_shift=300,
                                 block=128)

    def test_linear_stretch_falls_back_above_2_24(self):
        import jax.numpy as jnp

        from peasoup_tpu.ops import rednoise

        calls = []
        orig = rednoise._linear_stretch_lanes
        rednoise._linear_stretch_lanes = (
            lambda *a, **k: calls.append(1) or orig(*a, **k))
        try:
            x = np.linspace(0.0, 1.0, 4096).astype(np.float32)
            rednoise.linear_stretch(jnp.asarray(x), 1 << 19)
            assert calls  # lanes path used below the exactness bound
            calls.clear()
            # above 2^24 outputs the (exact-by-construction) gather
            # path must be chosen; just check dispatch, not the 64 MB
            # result
            import unittest.mock as mock

            with mock.patch.object(
                rednoise, "_linear_stretch_lanes",
                side_effect=AssertionError("lanes path above 2^24"),
            ):
                rednoise.linear_stretch(jnp.asarray(x), (1 << 24) + 640)
        finally:
            rednoise._linear_stretch_lanes = orig


class TestFixedAccelGrid:
    """Serial-driver fixed-step acceleration grid
    (`src/pipeline.cpp:287`, VERDICT r2 missing item 2)."""

    def test_grid_matches_c_loop_semantics(self):
        from peasoup_tpu.search.plan import FixedAccelerationPlan

        plan = FixedAccelerationPlan(-5.0, 5.0, 0.5)
        # float32 `for (jj=start; jj<end; jj+=0.5)`: end EXCLUDED,
        # f32 accumulation order
        want = []
        jj = np.float32(-5.0)
        while jj < np.float32(5.0):
            want.append(jj)
            jj = np.float32(jj + np.float32(0.5))
        got = plan.generate_accel_list(123.0)
        np.testing.assert_array_equal(got, np.array(want, np.float32))
        assert got[-1] < 5.0  # acc_end excluded, unlike the multi grid
        assert len(got) == 20
        # DM-independent
        np.testing.assert_array_equal(got, plan.generate_accel_list(0.0))

    def test_empty_grid_raises(self):
        from peasoup_tpu.search.plan import FixedAccelerationPlan

        with pytest.raises(ValueError, match="empty"):
            FixedAccelerationPlan(5.0, -5.0, 0.5)

    def test_e2e_with_fixed_grid(self, tutorial_fil):
        from peasoup_tpu.io import read_filterbank
        from peasoup_tpu.parallel.mesh import MeshPulsarSearch
        from peasoup_tpu.search.plan import SearchConfig

        fil = read_filterbank(tutorial_fil)
        cfg = SearchConfig(
            dm_start=0.0, dm_end=30.0, acc_start=-5.0, acc_end=5.0,
            acc_step=5.0, npdmp=0, limit=20,
        )
        r = MeshPulsarSearch(fil, cfg).run()
        np.testing.assert_array_equal(r.acc_list_dm0, [-5.0, 0.0])
        assert len(r.candidates) > 0

    def test_step_below_f32_epsilon_raises(self):
        from peasoup_tpu.search.plan import FixedAccelerationPlan

        # f32 increment stops advancing partway (the C loop would
        # spin forever) — must raise, not hang
        with pytest.raises(ValueError, match="does not advance"):
            FixedAccelerationPlan(0.0, 5.0, 1e-7)


@pytest.mark.parametrize("seed", range(3))
def test_distill_rows_batch_matches_per_row(seed, tutorial_fil):
    """Fuzz the segmented-native batched distillation against the
    per-row reference path: identical candidates, SNR order, and
    recursive assoc counts."""
    from peasoup_tpu.io import read_filterbank
    from peasoup_tpu.search.pipeline import PulsarSearch
    from peasoup_tpu.search.plan import SearchConfig

    from peasoup_tpu.native import lib as native_lib

    if native_lib is None:
        pytest.skip("native lib unavailable: the batched path would "
                    "fall back to the per-row reference itself")
    rng = np.random.default_rng(seed)
    fil = read_filterbank(tutorial_fil)
    cfg = SearchConfig(dm_start=0.0, dm_end=30.0, acc_start=-5.0,
                       acc_end=5.0, acc_pulse_width=64000.0, npdmp=0)
    s = PulsarSearch(fil, cfg)
    rows = []
    for ii in range(len(s.dm_list)):
        acc_list = s.acc_plan.generate_accel_list(float(s.dm_list[ii]))
        n = rng.integers(0, 60)
        base = rng.uniform(1.0, 30.0, 4)
        freqs = np.concatenate([
            b * rng.integers(1, 4, (n + 3) // 4) for b in base
        ])[:n].astype(np.float64) * (1 + rng.normal(0, 3e-5, n))
        grp = (freqs,
               rng.uniform(9.5, 80.0, n).astype(np.float64),
               rng.integers(0, len(acc_list), n),
               rng.integers(0, 5, n))
        rows.append((ii, grp if n else None, acc_list))
    batched = s._distill_rows_batch(rows)
    for ii, grp, acc_list in rows:
        ref = s._distill_dm_row(ii, grp, acc_list)
        got = batched[ii]
        assert len(got) == len(ref)
        for a, b in zip(got, ref):
            assert a.freq == b.freq and a.snr == b.snr
            assert a.acc == b.acc and a.nh == b.nh
            assert a.count_assoc() == b.count_assoc()


# --------------------------------------------------------------------------
# batched multi-observation dispatch (ISSUE 9)
# --------------------------------------------------------------------------


def _batch_fil(path, seed=0, nchans=16, nsamps=4096):
    """Small synthetic 8-bit observation (same recipe as test_serve)."""
    from peasoup_tpu.io import read_filterbank
    from peasoup_tpu.io.sigproc import (
        Filterbank, SigprocHeader, write_filterbank,
    )

    rng = np.random.default_rng(seed)
    data = rng.integers(0, 32, size=(nsamps, nchans), dtype=np.uint8)
    data[::16] += 60
    hdr = SigprocHeader(nbits=8, nchans=nchans, tsamp=0.000256,
                        fch1=1510.0, foff=-10.0, nsamples=nsamps)
    write_filterbank(str(path), Filterbank(header=hdr, data=data))
    return read_filterbank(str(path))


def _batch_cfg(**kw):
    return SearchConfig(dm_start=0.0, dm_end=20.0, acc_start=-5.0,
                        acc_end=5.0, acc_pulse_width=64000.0, npdmp=0,
                        limit=10, min_snr=6.0, **kw)


def _cand_tuples(result):
    return [(float(c.freq), float(c.snr), float(c.dm), float(c.acc),
             int(c.nh), float(c.folded_snr))
            for c in result.candidates]


class TestBatchedDispatch:
    def test_run_batch_bit_identical_per_beam(self, tmp_path):
        """One B=3 batched dispatch returns exactly the candidates of
        three sequential B=1 runs, beam for beam (the unrolled batch
        body keeps per-beam HLO identical, so equality is EXACT)."""
        from peasoup_tpu.parallel.mesh import MeshPulsarSearch

        fils = [_batch_fil(tmp_path / f"b{i}.fil", seed=i)
                for i in range(3)]
        cfg = _batch_cfg()
        want = [_cand_tuples(MeshPulsarSearch(f, cfg).run())
                for f in fils]

        leader = MeshPulsarSearch(fils[0], cfg)
        results = leader.run_batch(fils)
        assert leader.last_dispatch_batched
        assert [_cand_tuples(r) for r in results] == want

    def test_run_batch_rejects_mismatched_geometry(self, tmp_path):
        """Beams that cannot share one compiled program (different
        nchans here) must be refused up front, not mis-searched."""
        from peasoup_tpu.errors import ConfigError
        from peasoup_tpu.parallel.mesh import MeshPulsarSearch

        a = _batch_fil(tmp_path / "a.fil", seed=0)
        b = _batch_fil(tmp_path / "b.fil", seed=1, nchans=32)
        with pytest.raises(ConfigError, match="batch"):
            MeshPulsarSearch(a, _batch_cfg()).run_batch([a, b])

    def test_tuning_hints_batch_invariant(self, tmp_path):
        """The tune sidecar must record the same high-water marks — and
        therefore pick the same extraction path — whether an
        observation ran solo or inside a batch: extraction cells are
        per-spectrum/per-beam quantities, so the key stays B-free and
        records stay comparable across batch widths."""
        from peasoup_tpu.parallel.mesh import MeshPulsarSearch
        from peasoup_tpu.search.tuning import load_tuning

        src = tmp_path / "obs.fil"
        fil = _batch_fil(src, seed=3)
        t_seq = str(tmp_path / "seq.tune.json")
        t_bat = str(tmp_path / "bat.tune.json")

        cfg_seq = _batch_cfg(infilename=str(src), tune_file=t_seq)
        s_seq = MeshPulsarSearch(fil, cfg_seq)
        s_seq.run()
        key = s_seq._tune_scoped_key("fused")

        # same observation as every beam: max-over-beams == solo marks
        cfg_bat = _batch_cfg(infilename=str(src), tune_file=t_bat)
        beams = [fil,
                 _batch_fil(tmp_path / "copy1.fil", seed=3),
                 _batch_fil(tmp_path / "copy2.fil", seed=3)]
        leader = MeshPulsarSearch(beams[0], cfg_bat)
        leader.run_batch(beams)
        assert leader.last_dispatch_batched
        assert leader._tune_scoped_key("fused") == key  # key is B-free

        seq_rec = load_tuning(t_seq, key)
        bat_rec = load_tuning(t_bat, key)
        assert seq_rec is not None and bat_rec is not None
        assert seq_rec["cap_hw"] == bat_rec["cap_hw"]
        assert seq_rec["ck_hw"] == bat_rec["ck_hw"]

        # identical hints -> identical picked extraction path on the
        # next run, independent of the batch width that recorded them
        cap = max(64, seq_rec["cap_hw"])
        again_seq = MeshPulsarSearch(fil, cfg_seq)
        again_bat = MeshPulsarSearch(fil, cfg_bat)
        assert (again_seq.peaks_methods_for(cap)
                == again_bat.peaks_methods_for(cap))
