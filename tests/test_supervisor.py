"""Self-healing supervisor tests (ISSUE 15): the @supervisor_action
registry, finding->action planning, per-action cooldowns + the global
actions-per-window cap, each built-in action against a real spool (or
a fake worker pool), dry-run, the supervise/admission CLI verbs, the
concurrent-reaper idempotence race, and the fair-share
starvation-freedom property."""

import json
import threading

import pytest

from peasoup_tpu.obs.history import load_history
from peasoup_tpu.obs.metrics import REGISTRY
from peasoup_tpu.serve import (
    ACTIONS,
    LEASE_EXPIRED,
    ActionSpec,
    AdmissionPolicy,
    JobSpool,
    Supervisor,
    TenantPolicy,
    WorkerPool,
    supervisor_action,
)
import peasoup_tpu.serve.supervisor as sup_mod
from peasoup_tpu.serve.health import CRIT, OK, WARN


@pytest.fixture(autouse=True)
def _fresh_registry():
    REGISTRY.reset()
    yield
    REGISTRY.reset()


class _Clock:
    def __init__(self, t=100000.0):
        self.t = t

    def __call__(self):
        return self.t


class _FakeProc:
    """Stands in for a fleet-worker subprocess: alive until
    terminated."""

    _pid = 40000

    def __init__(self, cmd, env=None):
        self.cmd = list(cmd)
        _FakeProc._pid += 1
        self.pid = _FakeProc._pid
        self._rc = None

    def poll(self):
        return self._rc

    def terminate(self):
        self._rc = 0

    def wait(self, timeout=None):
        return self._rc

    def kill(self):
        self._rc = -9


def _finding(rule, severity, data=None, message="injected"):
    return {"rule": rule, "severity": severity, "message": message,
            "host": "", "data": data or {}}


def _fake_evaluate(monkeypatch, reports):
    """Patch the supervisor's health evaluation with a scripted
    sequence of reports (the last one repeats).  Covers both the tick
    evaluation and the after-state re-evaluation."""
    reports = list(reports)

    def fake(ctx):
        rep = reports.pop(0) if len(reports) > 1 else reports[0]
        return {"v": 1, "utc": 0.0,
                "severity": max((f["severity"] for f in rep),
                                default=OK),
                "findings": list(rep), "queue": {}, "hosts": []}

    monkeypatch.setattr(sup_mod, "evaluate", fake)


def _supervisor(tmp_path, clock, *, pool=None, **kw):
    spool = kw.pop("spool", None) or JobSpool(str(tmp_path / "jobs"))
    return Supervisor(
        spool,
        pool=pool or WorkerPool(spool.root, max_workers=2,
                                popen=_FakeProc),
        history_path=str(tmp_path / "supervise.jsonl"),
        ledger_path=str(tmp_path / "ledger.jsonl"),
        clock=clock, out=lambda *_: None, **kw)


# --------------------------------------------------------------------------
# registry + planning
# --------------------------------------------------------------------------

def test_builtin_actions_registered():
    by_name = {a.name: a for a in ACTIONS}
    assert {"reap_expired", "scale_up", "retire_idle",
            "retune_batch"} <= set(by_name)
    assert by_name["reap_expired"].rule == "stale_host"
    assert by_name["reap_expired"].severities == (CRIT,)
    assert by_name["scale_up"].matches(
        _finding("queue_backlog", WARN))
    assert by_name["scale_up"].matches(
        _finding("queue_backlog", CRIT))
    assert not by_name["scale_up"].matches(
        _finding("queue_backlog", OK))
    assert by_name["retire_idle"].matches(
        _finding("queue_backlog", OK))
    assert not by_name["reap_expired"].matches(
        _finding("stale_host", WARN))


def test_plan_fires_each_action_once_per_tick(tmp_path):
    """Two crit stale hosts plan ONE reap (the reaper sweeps every
    lease in one call); unrelated findings plan their own actions."""
    clock = _Clock()
    sup = _supervisor(tmp_path, clock)
    plan = sup.plan({"findings": [
        _finding("stale_host", CRIT),
        _finding("stale_host", CRIT),
        _finding("queue_backlog", WARN),
        _finding("retry_spike", WARN),  # no registered action
    ]})
    assert [(spec.name, f["rule"]) for spec, f in plan] == [
        ("reap_expired", "stale_host"),
        ("scale_up", "queue_backlog"),
    ]


# --------------------------------------------------------------------------
# actions end-to-end through tick()
# --------------------------------------------------------------------------

def test_reap_action_recovers_stale_lease(tmp_path, monkeypatch):
    """A crit stale_host finding makes the supervisor reap the dead
    host's lease: job back to pending with the LEASE_EXPIRED failure
    entry, one typed event, one kind:"supervise" ledger record
    carrying the before/after finding state."""
    spool = JobSpool(str(tmp_path / "jobs"))
    rec = spool.submit("/tmp/x.fil")
    job = spool.claim("w1", host="dead-host")
    assert job.job_id == rec.job_id

    clock = _Clock(job.claimed_utc + 1000.0)
    sup = _supervisor(tmp_path, clock, spool=spool, lease_ttl_s=5.0)
    _fake_evaluate(monkeypatch, [
        [_finding("stale_host", CRIT)],   # tick evaluation
        [_finding("stale_host", OK)],     # after-state re-evaluation
    ])
    with pytest.warns(UserWarning, match="reaped"):
        results = sup.tick()
    assert [r["action"] for r in results] == ["reap_expired"]
    assert results[0]["executed"] is True
    assert results[0]["outcome"]["reaped"] == 1
    assert results[0]["severity_after"] == OK

    counts = spool.counts()
    assert counts["pending"] == 1 and counts["running"] == 0
    back = spool.jobs("pending")[0]
    assert back.attempts == 1
    assert [f["classification"] for f in back.failures] \
        == [LEASE_EXPIRED]

    (led,) = load_history(str(tmp_path / "supervise.jsonl"),
                          kinds=["supervise"])
    assert led["action"]["name"] == "reap_expired"
    assert led["action"]["rule"] == "stale_host"
    assert led["action"]["finding_before"]["severity"] == CRIT
    assert led["action"]["finding_after"]["severity"] == OK
    assert led["metrics"]["queue_pending"] == 1

    counters = REGISTRY.snapshot()["counters"]
    assert counters["supervisor.actions"] == 1
    assert counters["supervisor.action.reap_expired"] == 1
    assert counters["events.supervise_action"] == 1


def test_cooldown_throttles_then_releases(tmp_path, monkeypatch):
    """The same finding two ticks in a row: the second execution is
    refused by the per-action cooldown until the clock passes it."""
    spool = JobSpool(str(tmp_path / "jobs"))
    spool.submit("/tmp/x.fil")
    job = spool.claim("w1", host="dead")
    clock = _Clock(job.claimed_utc + 1000.0)
    sup = _supervisor(tmp_path, clock, spool=spool, lease_ttl_s=5.0,
                      cooldowns={"reap_expired": 30.0})
    _fake_evaluate(monkeypatch, [[_finding("stale_host", CRIT)]])

    with pytest.warns(UserWarning, match="reaped"):
        first = sup.tick()
    assert first[0]["executed"] is True

    second = sup.tick()  # same instant: cooldown refuses
    assert second[0]["executed"] is False
    assert "cooldown" in second[0]["throttled"]

    clock.t += 31.0  # past the override cooldown: clear to fire
    third = sup.tick()
    assert third[0]["executed"] is True  # zero reaped, still executed
    assert third[0]["outcome"]["reaped"] == 0

    counters = REGISTRY.snapshot()["counters"]
    assert counters["supervisor.throttled"] == 1
    assert counters["supervisor.actions"] == 2


def test_global_actions_per_window_cap(tmp_path, monkeypatch):
    """Zeroed cooldowns cannot bypass the global cap: the third
    execution inside the window is refused, and ages out."""
    clock = _Clock(300000.0)
    sup = _supervisor(tmp_path, clock,
                      cooldowns={"scale_up": 0.0},
                      actions_window_s=60.0,
                      max_actions_per_window=2)
    _fake_evaluate(monkeypatch, [[_finding("queue_backlog", WARN)]])

    assert sup.tick()[0]["executed"] is True   # spawn sup-0
    clock.t += 1.0
    assert sup.tick()[0]["executed"] is True   # spawn sup-1
    clock.t += 1.0
    third = sup.tick()  # pool at max_workers would return None, but
    # the global cap refuses BEFORE the action runs
    assert third[0]["executed"] is False
    assert "global cap" in third[0]["throttled"]

    clock.t += 61.0  # both executions age out of the window
    sup.pool.max_workers = 3
    assert sup.tick()[0]["executed"] is True
    assert len(sup.pool.alive()) == 3


def test_scale_up_bounded_and_retire_after_sustained_idle(
        tmp_path, monkeypatch):
    """scale_up adds real workers up to max_workers (at capacity it is
    inapplicable — no cooldown burned, nothing recorded); retire_idle
    needs low_depth_ticks consecutive empty ticks, then SIGTERMs the
    newest worker."""
    clock = _Clock(400000.0)
    sup = _supervisor(tmp_path, clock, cooldowns={"scale_up": 0.0,
                                                  "retire_idle": 0.0},
                      low_depth_ticks=2,
                      max_actions_per_window=100)
    _fake_evaluate(monkeypatch, [[_finding("queue_backlog", WARN)]])
    assert sup.tick()[0]["outcome"]["spawned"] == "sup-0"
    clock.t += 1
    assert sup.tick()[0]["outcome"]["spawned"] == "sup-1"
    clock.t += 1
    assert sup.tick() == []  # at capacity: inapplicable, not throttled
    ledger = load_history(str(tmp_path / "supervise.jsonl"))
    assert len(ledger) == 2  # inapplicable firings never reach it

    _fake_evaluate(monkeypatch, [[_finding("queue_backlog", OK)]])
    clock.t += 1
    assert sup.tick() == []  # idle tick 1 of 2: not yet
    clock.t += 1
    (res,) = sup.tick()      # idle tick 2: newest worker retired
    assert res["outcome"]["retired"] == "sup-1"
    assert res["outcome"]["idle_ticks"] == 2
    assert [w["label"] for w in sup.pool.alive()] == ["sup-0"]
    assert sup.pool.procs[0]["proc"].poll() is None  # oldest untouched


def test_retire_resets_on_pending_work(tmp_path, monkeypatch):
    """A momentary lull must not churn workers: pending work between
    idle ticks resets the counter."""
    clock = _Clock(500000.0)
    sup = _supervisor(tmp_path, clock, low_depth_ticks=2,
                      cooldowns={"retire_idle": 0.0})
    sup.pool.spawn()
    _fake_evaluate(monkeypatch, [[_finding("queue_backlog", OK)]])
    assert sup.tick() == []
    assert sup.idle_ticks == 1
    sup.spool.submit("/tmp/w.fil")  # work arrives mid-lull
    clock.t += 1
    assert sup.tick() == []
    assert sup.idle_ticks == 0  # reset, not retired
    assert len(sup.pool.alive()) == 1


def test_retune_batch_applies_suggestion_to_future_spawns(
        tmp_path, monkeypatch):
    clock = _Clock(600000.0)
    sup = _supervisor(tmp_path, clock, max_batch=8,
                      cooldowns={"retune_batch": 0.0,
                                 "scale_up": 0.0})
    _fake_evaluate(monkeypatch, [[_finding(
        "batch_mix", WARN, data={"suggest_batch": 6})]])
    (res,) = sup.tick()
    assert res["outcome"] == {"batch_old": 1, "batch_new": 6}
    assert sup.pool.batch == 6
    clock.t += 1
    assert sup.tick() == []  # same suggestion again: no-op, no record

    _fake_evaluate(monkeypatch, [[
        _finding("batch_mix", WARN, data={"suggest_batch": 20}),
        _finding("queue_backlog", WARN),
    ]])
    clock.t += 1
    results = sup.tick()
    by_action = {r["action"]: r for r in results}
    # the max_batch ceiling clamps a wild suggestion
    assert by_action["retune_batch"]["outcome"]["batch_new"] == 8
    # and the spawned worker's command line carries the tuned batch
    spawned = sup.pool.procs[-1]["proc"].cmd
    assert spawned[spawned.index("--batch") + 1] == "8"


def test_dry_run_plans_but_never_acts(tmp_path, monkeypatch):
    lines = []
    clock = _Clock(700000.0)
    spool = JobSpool(str(tmp_path / "jobs"))
    spool.submit("/tmp/x.fil")
    spool.claim("w1", host="dead")
    sup = Supervisor(spool, pool=WorkerPool(spool.root,
                                            popen=_FakeProc),
                     dry_run=True, clock=clock, out=lines.append,
                     history_path=str(tmp_path / "supervise.jsonl"))
    _fake_evaluate(monkeypatch, [[
        _finding("stale_host", CRIT),
        _finding("queue_backlog", CRIT),
    ]])
    results = sup.tick()
    assert all(r["dry_run"] for r in results)
    assert all(not r["executed"] for r in results)
    assert any("would run reap_expired" in ln for ln in lines)
    # nothing moved, spawned, or recorded
    assert spool.counts()["running"] == 1
    assert sup.pool.alive() == []
    assert load_history(str(tmp_path / "supervise.jsonl")) == []
    assert "supervisor.actions" not in \
        REGISTRY.snapshot()["counters"]


def test_crashing_action_consumes_cooldown(tmp_path, monkeypatch):
    """An action that raises is executed-with-error: the outcome
    records the exception and the cooldown stops an every-tick retry
    storm."""
    @supervisor_action("explode", rule="test_rule",
                      severities=(CRIT,), cooldown_s=30.0)
    def _explode(sup, finding):
        raise RuntimeError("injected action crash")

    try:
        clock = _Clock(800000.0)
        sup = _supervisor(tmp_path, clock)
        _fake_evaluate(monkeypatch, [[_finding("test_rule", CRIT)]])
        (res,) = sup.tick()
        assert res["executed"] is True
        assert "RuntimeError: injected action crash" \
            in res["outcome"]["error"]
        (nxt,) = sup.tick()  # same instant: cooldown holds
        assert "cooldown" in nxt["throttled"]
        (led,) = load_history(str(tmp_path / "supervise.jsonl"))
        assert "error" in led["action"]["outcome"]
    finally:
        ACTIONS[:] = [a for a in ACTIONS if a.name != "explode"]


def test_status_snapshot_written_each_tick(tmp_path, monkeypatch):
    clock = _Clock(900000.0)
    sup = _supervisor(tmp_path, clock,
                      cooldowns={"scale_up": 0.0})
    _fake_evaluate(monkeypatch, [[_finding("queue_backlog", WARN)]])
    sup.tick()
    doc = json.load(open(sup.status_path()))
    assert doc["tick"] == 1 and doc["actions_total"] == 1
    assert doc["workers"][0]["label"] == "sup-0"
    assert doc["workers"][0]["pid"] > 0
    assert doc["last_results"][0]["action"] == "scale_up"


# --------------------------------------------------------------------------
# concurrent-reaper idempotence (satellite: exactly-once requeue)
# --------------------------------------------------------------------------

def test_two_concurrent_reapers_requeue_exactly_once(tmp_path):
    """The supervisor's reaper racing an operator's `requeue
    --expired` (or a worker's own reap pass): the running->pending
    rename arbitrates, so the job is requeued EXACTLY once and its
    failure log gains one lease_expired entry, not two."""
    spool = JobSpool(str(tmp_path / "jobs"))
    rec = spool.submit("/tmp/x.fil")
    spool.claim("w1", host="doomed")
    stale_now = rec.submitted_utc + 10 * 3600.0

    results = {}
    barrier = threading.Barrier(2)

    def _reap(name):
        barrier.wait()
        results[name] = spool.reap_expired(5.0, now=stale_now)

    with pytest.warns(UserWarning, match="reaped"):
        ts = [threading.Thread(target=_reap, args=(n,))
              for n in ("a", "b")]
        for t in ts:
            t.start()
        for t in ts:
            t.join()

    assert len(results["a"]) + len(results["b"]) == 1
    assert spool.counts() == {"pending": 1, "running": 0, "done": 0,
                              "failed": 0}
    back = spool.jobs("pending")[0]
    assert back.attempts == 1
    assert [f["classification"] for f in back.failures] \
        == [LEASE_EXPIRED]
    counters = REGISTRY.snapshot()["counters"]
    assert counters["scheduler.lease_reaped"] == 1


# --------------------------------------------------------------------------
# fair-share starvation freedom (satellite: property test)
# --------------------------------------------------------------------------

def test_fair_share_starvation_freedom(tmp_path):
    """A light tenant behind a 10x flood: with weights w_light=1,
    w_flood=4 the light tenant's i-th job must be claimed within its
    virtual-finish-time bound — flood jobs can precede light job i
    only while their own virtual time is smaller, so position(light_i)
    <= (i+1) * (1 + w_flood/w_light).  No configuration of the flood
    can push a light job past that bound (starvation-free)."""
    spool = JobSpool(
        str(tmp_path / "jobs"),
        admission=AdmissionPolicy(tenants={
            "light": TenantPolicy(weight=1.0),
            "flood": TenantPolicy(weight=4.0),
        }))
    flood, light = [], []
    for i in range(40):
        flood.append(spool.submit(f"/tmp/f{i}.fil", tenant="flood"))
    for i in range(4):
        light.append(spool.submit(f"/tmp/l{i}.fil", tenant="light"))

    order = [r.job_id for r in spool.claim_order()]
    bound = 1.0 + 4.0 / 1.0
    for i, rec in enumerate(light):
        pos = order.index(rec.job_id)  # 0-based claim position
        assert pos < (i + 1) * bound, (
            f"light job {i} starved to position {pos}")
    # and the flood still gets its weighted majority of early claims
    first_ten = order[:10]
    assert sum(j in {r.job_id for r in flood}
               for j in first_ten) >= 7

    # claims drain in exactly the planned order
    claimed = [spool.claim("w").job_id for _ in range(len(order))]
    assert claimed == order


def test_fair_share_respects_priority_tiers(tmp_path):
    """Weighted interleave happens WITHIN a priority tier; a higher
    tier always drains first regardless of tenant weight."""
    spool = JobSpool(
        str(tmp_path / "jobs"),
        admission=AdmissionPolicy(tenants={
            "heavy": TenantPolicy(weight=8.0),
        }))
    lo = [spool.submit(f"/tmp/h{i}.fil", tenant="heavy")
          for i in range(3)]
    hi = spool.submit("/tmp/urgent.fil", priority=9, tenant="other")
    order = [r.job_id for r in spool.claim_order()]
    assert order[0] == hi.job_id
    assert order[1:] == [r.job_id for r in lo]


# --------------------------------------------------------------------------
# CLI verbs
# --------------------------------------------------------------------------

def test_supervise_verb_dry_run_smoke(tmp_path, capsys):
    from peasoup_tpu.serve.cli import main

    spool_dir = str(tmp_path / "jobs")
    rc = main(["--spool", spool_dir, "supervise", "--ticks", "2",
               "--interval", "0", "--dry-run",
               "--history", str(tmp_path / "h.jsonl"),
               "--ledger", str(tmp_path / "h.jsonl")])
    assert rc == 0
    out = capsys.readouterr().out
    assert "2 tick(s)" in out
    doc = json.load(open(str(tmp_path / "jobs" / "supervisor.json")))
    assert doc["dry_run"] is True and doc["tick"] == 2


def test_admission_verb_configures_policy(tmp_path, capsys):
    from peasoup_tpu.serve.cli import main

    spool_dir = str(tmp_path / "jobs")
    rc = main(["--spool", spool_dir, "admission", "--max-pending",
               "50", "--tenant", "flood", "--rate", "0.5",
               "--burst", "3", "--weight", "2"])
    assert rc == 0
    pol = AdmissionPolicy.load(spool_dir)
    assert pol.max_pending == 50
    ten = pol.for_tenant("flood")
    assert (ten.rate_per_s, ten.burst, ten.weight) == (0.5, 3.0, 2.0)

    rc = main(["--spool", spool_dir, "admission", "--show"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "flood" in out and "50" in out
