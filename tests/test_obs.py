"""Run-telemetry subsystem tests: metrics registry, JSONL event log,
warn_event bridge, run_report.json end-to-end, and the repo lint that
keeps every search/parallel warning routed through telemetry."""

import json
import os
import re
import threading
import warnings

import numpy as np
import pytest

from peasoup_tpu.obs.events import EventLog, warn_event
from peasoup_tpu.obs.metrics import REGISTRY, MetricsRegistry
from peasoup_tpu.obs.report import build_run_report, format_stage_table


# --------------------------------------------------------------------------
# metrics registry
# --------------------------------------------------------------------------

def test_counter_concurrent_increments():
    reg = MetricsRegistry()
    n_threads, per_thread = 8, 500

    def work():
        for _ in range(per_thread):
            reg.inc("hits")

    threads = [threading.Thread(target=work) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert reg.counter("hits") == n_threads * per_thread


def test_gauge_last_write_wins():
    reg = MetricsRegistry()
    reg.gauge("hbm.budget_bytes", 1.0)
    reg.gauge("hbm.budget_bytes", 13e9)
    assert reg.snapshot()["gauges"]["hbm.budget_bytes"] == 13e9


def test_timer_nesting_and_device_host_split():
    import jax.numpy as jnp

    reg = MetricsRegistry()
    with reg.timer("outer") as tm_out:
        with reg.timer("inner") as tm_in:
            arr = jnp.arange(1024) * 2
            tm_in.block(arr)
        tm_out.block(arr)
    snap = reg.snapshot()["timers"]
    assert snap["outer"]["count"] == 1
    assert snap["inner"]["count"] == 1
    # the inner stage is a sub-span of the outer one
    assert snap["outer"]["host_s"] >= snap["inner"]["host_s"]
    # device wait is a sub-span of host wall-clock, for both stages
    for name in ("outer", "inner"):
        assert 0.0 <= snap[name]["device_s"] <= snap[name]["host_s"]


def test_timer_counts_accumulate():
    reg = MetricsRegistry()
    for _ in range(3):
        with reg.timer("stage"):
            pass
    rec = reg.snapshot()["timers"]["stage"]
    assert rec["count"] == 3
    assert rec["host_s"] >= 0.0


# --------------------------------------------------------------------------
# event log
# --------------------------------------------------------------------------

def test_event_log_jsonl_schema(tmp_path):
    path = str(tmp_path / "events.jsonl")
    reg = MetricsRegistry()
    log = EventLog(path, registry=reg)
    log.emit("peak_buffer_overflow", "overflowed",
             count=np.int64(131), capacity=64, dm=np.float32(2.5))
    log.emit("peak_buffer_overflow", "again", count=200, capacity=64)
    log.emit("tune_io_error", "disk on fire", path="/dev/null")
    log.close()
    lines = open(path).read().splitlines()
    assert len(lines) == 3
    recs = [json.loads(ln) for ln in lines]
    for rec in recs:
        assert rec["v"] == 1
        assert isinstance(rec["ts"], float)
        assert isinstance(rec["kind"], str)
        assert isinstance(rec["message"], str)
    # numpy scalars must land as plain JSON numbers
    assert recs[0]["data"] == {"count": 131, "capacity": 64, "dm": 2.5}
    assert log.summary() == {"peak_buffer_overflow": 2, "tune_io_error": 1}
    # every emit also lands in the registry's events.<kind> counters
    assert reg.counter("events.peak_buffer_overflow") == 2
    assert reg.counter("events.tune_io_error") == 1


def test_event_log_without_path_still_counts():
    log = EventLog("", registry=MetricsRegistry())
    log.emit("x", "no sink configured")
    assert log.summary() == {"x": 1}


def test_warn_event_raises_warning_and_records_event(tmp_path):
    from peasoup_tpu.obs import events as ev

    old = ev.get_event_log()
    path = str(tmp_path / "warn_events.jsonl")
    ev.configure_event_log(path)
    before = REGISTRY.counter("events.capacity_escalation")
    try:
        with pytest.warns(UserWarning, match="re-running with capacity"):
            warn_event(
                "capacity_escalation",
                "peak buffer overflow on DM trial 3 (count 99); "
                "re-running with capacity=128",
                dm_trial=3, count=99, capacity=128,
            )
    finally:
        log = ev.get_event_log()
        ev._LOG = old  # restore the process-wide sink for later tests
        log.close()
    assert REGISTRY.counter("events.capacity_escalation") == before + 1
    rec = json.loads(open(path).read().splitlines()[0])
    assert rec["kind"] == "capacity_escalation"
    assert rec["data"] == {"dm_trial": 3, "count": 99, "capacity": 128}


# --------------------------------------------------------------------------
# report assembly
# --------------------------------------------------------------------------

def test_build_report_and_stage_table():
    reg = MetricsRegistry()
    reg.inc("events.peak_buffer_overflow", 2)
    reg.gauge("hbm.data_bytes", 4096)
    with reg.timer("dedispersion"):
        pass
    log = EventLog("", registry=reg)
    log.emit("peak_buffer_overflow", "x")
    report = build_run_report(registry=reg, events=log)
    assert report["version"] == 2  # PR 4: schema bump (adds `perf`)
    assert report["schema_version"] == 2
    assert report["events"] == {"peak_buffer_overflow": 1}
    assert "dedispersion" in report["stage_timers"]
    assert {"count", "host_s", "device_s"} <= set(
        report["stage_timers"]["dedispersion"])
    assert report["device"]["device_count"] >= 1
    table = format_stage_table(report)
    assert "dedispersion" in table
    assert "host_s" in table and "device_s" in table


# --------------------------------------------------------------------------
# end-to-end: CLI run writes run_report.json + events.jsonl
# --------------------------------------------------------------------------

@pytest.fixture(scope="module")
def synth_fil(tmp_path_factory):
    """Tiny 8-bit filterbank with a strong 976 Hz pulse train: loud
    enough that a peak_capacity=2 search must overflow and escalate."""
    from peasoup_tpu.io.sigproc import (
        Filterbank, SigprocHeader, write_filterbank,
    )

    rng = np.random.default_rng(0)
    nsamps, nchans = 4096, 16
    data = rng.integers(0, 32, size=(nsamps, nchans), dtype=np.uint8)
    data[::16] += 60
    hdr = SigprocHeader(nbits=8, nchans=nchans, tsamp=0.000256,
                        fch1=1510.0, foff=-10.0, nsamples=nsamps)
    path = str(tmp_path_factory.mktemp("obs_e2e") / "synth.fil")
    write_filterbank(path, Filterbank(header=hdr, data=data))
    return path


def _run_cli_collecting_warnings(args):
    from peasoup_tpu.cli import main

    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        rc = main(args)
    return rc, [str(w.message) for w in rec]


def _check_report_dir(outdir, warned_msgs):
    report = json.load(open(os.path.join(outdir, "run_report.json")))
    events = [json.loads(ln) for ln in
              open(os.path.join(outdir, "events.jsonl"))]
    # every warning raised during the run is a counted, typed event
    assert sum(report["events"].values()) == len(warned_msgs)
    assert len(events) == len(warned_msgs)
    assert sorted(e["message"] for e in events) == sorted(warned_msgs)
    # nonzero stage timers with a host/device split
    stages = report["stage_timers"]
    assert any(rec["host_s"] > 0 for rec in stages.values())
    for rec in stages.values():
        assert 0.0 <= rec["device_s"] <= max(rec["host_s"], 1e-9) * 1.01
    assert report["jit"]["backend_compiles"] >= 0
    assert report["candidates"]["count"] >= 1
    # the XML mirror is present
    xml = open(os.path.join(outdir, "overview.xml"),
               encoding="latin-1").read()
    assert "<telemetry>" in xml and "<stage_timers>" in xml
    return report


def test_cli_host_loop_run_report(synth_fil, tmp_path):
    """Host-loop driver: a forced-overflow run's escalation warnings
    must appear 1:1 as counted events in run_report.json."""
    REGISTRY.reset()
    outdir = str(tmp_path / "out_host")
    rc, warned = _run_cli_collecting_warnings([
        "-i", synth_fil, "-o", outdir,
        "--dm_start", "0", "--dm_end", "20", "--min_snr", "6",
        "--peak_capacity", "2", "--npdmp", "2", "--limit", "10",
        "--single_device",
    ])
    assert rc == 0
    assert len(warned) > 0, "tiny capacity must force escalations"
    report = _check_report_dir(outdir, warned)
    assert report["events"].get("capacity_escalation", 0) == len(warned)
    assert report["stage_timers"]["dedispersion"]["host_s"] > 0
    assert report["stage_timers"]["accel_search"]["count"] > 0
    assert report["counters"]["runs.host_loop"] == 1
    assert report["gauges"]["hbm.data_bytes"] > 0


def test_cli_mesh_run_report(synth_fil, tmp_path):
    """Mesh (fused) driver through the CLI default path."""
    REGISTRY.reset()
    outdir = str(tmp_path / "out_mesh")
    rc, warned = _run_cli_collecting_warnings([
        "-i", synth_fil, "-o", outdir,
        "--dm_start", "0", "--dm_end", "20", "--min_snr", "6",
        "--npdmp", "2", "--limit", "10",
    ])
    assert rc == 0
    report = _check_report_dir(outdir, warned)
    assert report["stage_timers"]["fused_search"]["host_s"] > 0
    assert report["stage_timers"]["peak_decode"]["count"] >= 1
    assert report["counters"]["runs.mesh_fused"] == 1
    assert report["gauges"]["search.n_devices"] == 8


def test_chunked_driver_phase_timers(synth_fil):
    """Bounded-HBM chunked driver: per-phase breakdown mirrors into
    the registry with a device share on the aggregate stage."""
    from peasoup_tpu.io import read_filterbank
    from peasoup_tpu.parallel.mesh import MeshPulsarSearch
    from peasoup_tpu.search.plan import SearchConfig

    REGISTRY.reset()
    fil = read_filterbank(synth_fil)
    cfg = SearchConfig(dm_start=0.0, dm_end=20.0, min_snr=6.0, npdmp=2,
                       limit=10, dm_chunk=2, accel_block=1)
    result = MeshPulsarSearch(fil, cfg).run()
    assert len(result.candidates) > 0
    snap = REGISTRY.snapshot()
    assert snap["counters"]["runs.mesh_chunked"] == 1
    timers = snap["timers"]
    for phase in ("chunk_prep", "chunk_compile", "chunk_fetch",
                  "chunk_decode", "chunk_distill"):
        assert phase in timers
    agg = timers["chunked_search"]
    assert agg["host_s"] > 0
    assert 0.0 <= agg["device_s"] <= agg["host_s"] * 1.01
    assert snap["gauges"]["chunk.dm_chunk"] == 2


def test_tutorial_run_report(tutorial_fil, tmp_path):
    """ISSUE acceptance: a tutorial-scale CLI run writes a parseable
    run_report.json whose overflow/escalation counters match the
    warnings raised."""
    REGISTRY.reset()
    outdir = str(tmp_path / "out_tut")
    rc, warned = _run_cli_collecting_warnings([
        "-i", tutorial_fil, "-o", outdir,
        "--dm_start", "0", "--dm_end", "60",
        "--acc_start", "-5", "--acc_end", "5",
        "--acc_pulse_width", "64000",
        "--peak_capacity", "8", "--limit", "50",
        "--single_device",
    ])
    assert rc == 0
    report = _check_report_dir(outdir, warned)
    n_escalations = sum(
        1 for m in warned if "re-running with capacity" in m)
    assert report["events"].get(
        "capacity_escalation", 0) == n_escalations


# --------------------------------------------------------------------------
# repo lint: no bare warnings.warn in search/ or parallel/
# --------------------------------------------------------------------------

def test_no_bare_warnings_warn_in_search_and_parallel():
    """Every warning in the drivers must route through
    obs.events.warn_event so it is counted and logged — a bare
    warnings.warn would silently bypass telemetry.

    Since ISSUE 2 this is the PSL001 rule of the
    ``peasoup_tpu.analysis`` engine (which covers the whole package,
    not just the drivers — see tests/test_lint.py); this test pins the
    original driver-scoped guarantee onto that rule."""
    import peasoup_tpu
    from peasoup_tpu.analysis.engine import run_rules
    from peasoup_tpu.analysis.rules import rules_by_id

    pkg_root = os.path.dirname(peasoup_tpu.__file__)
    violations, _suppressed, errors = run_rules(
        rules_by_id(["PSL001"]),
        [os.path.join(pkg_root, "search"),
         os.path.join(pkg_root, "parallel")],
    )
    assert not errors, errors
    offenders = [v.format() for v in violations]
    assert not offenders, (
        "bare warnings.warn found (route through obs.events.warn_event):\n"
        + "\n".join(offenders)
    )


# --------------------------------------------------------------------------
# progress bar satellite
# --------------------------------------------------------------------------

def test_progress_bar_counts_rate_and_summary():
    import io

    from peasoup_tpu.utils import ProgressBar

    buf = io.StringIO()
    p = ProgressBar(10, "DM trials ", stream=buf, width=10)
    p.start()
    p.update(5)
    p.finish()
    text = buf.getvalue()
    assert "5/10" in text          # done/total counts
    assert "/s" in text            # throughput
    assert "ETA" in text
    # final summary line
    assert re.search(r"10 trials in \d+\.\d s, \d+(\.\d)? trials/s", text)
