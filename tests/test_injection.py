"""Sensitivity-observatory tests (ISSUE 14): the injection
synthesizer's determinism and dispersion exactness, recovery matching
(harmonic folds + near-miss rejection), the per-stage SNR budget
probe's monotone taps, canary jobs end-to-end through a worker drain
with store isolation, the canary_recovery health rule's fixtures, the
sensitivity ledger record schema, jerk round-trips through
overview.xml / candidates.peasoup / the parsers, the lattice sidecar's
recovery_delta field, and the load generator's canary mix."""

import importlib
import json
import os
import warnings

import numpy as np
import pytest

from peasoup_tpu.data import Candidate
from peasoup_tpu.obs.injection import (
    amp_for_snr,
    delay_table,
    load_manifest,
    match_candidates,
    noise_sigma,
    save_manifest,
    smoke_observation,
    synthesize,
)
from peasoup_tpu.obs.metrics import REGISTRY

TSAMP = 0.000256

#: fast search overrides shared by the end-to-end tests
FAST = {"dm_end": 20.0, "min_snr": 6.0, "npdmp": 0, "limit": 10}


@pytest.fixture(autouse=True)
def _fresh_registry():
    REGISTRY.reset()
    yield
    REGISTRY.reset()


# --------------------------------------------------------------------------
# synthesizer
# --------------------------------------------------------------------------

def test_synthesize_deterministic(tmp_path):
    a = str(tmp_path / "a.fil")
    b = str(tmp_path / "b.fil")
    c = str(tmp_path / "c.fil")
    man_a = synthesize(a, period=16 * TSAMP, snr=20.0, seed=3)
    man_b = synthesize(b, period=16 * TSAMP, snr=20.0, seed=3)
    man_c = synthesize(c, period=16 * TSAMP, snr=20.0, seed=4)
    assert open(a, "rb").read() == open(b, "rb").read()
    assert open(a, "rb").read() != open(c, "rb").read()
    # manifests identical up to the path they describe
    for k in man_a:
        if k != "path":
            assert man_a[k] == man_b[k], k
    assert man_a["target_snr"] == 20.0 and man_a["amp"] > 0


def test_synthesize_manifest_roundtrip(tmp_path):
    fil = str(tmp_path / "x.fil")
    man = synthesize(fil, freq=50.0, dm=12.5, accel=3.0, jerk=2e5,
                     duty=0.07, snr=15.0, seed=1)
    path = save_manifest(man, fil + ".manifest.json")
    back = load_manifest(path)
    assert back == json.loads(json.dumps(man))  # JSON-faithful
    assert load_manifest(man) is man            # dict passthrough


def test_delay_table_matches_ops():
    dd = importlib.import_module("peasoup_tpu.ops.dedisperse")
    ours = delay_table(64, TSAMP, 1510.0, -10.0)
    theirs = np.asarray(dd.delay_table(64, TSAMP, 1510.0, -10.0))
    np.testing.assert_array_equal(ours, theirs.astype(np.float32))


def test_dispersion_exact(tmp_path):
    """Channel j carries the channel-0 train delayed by exactly the
    dedisperser's integer delay — so DM-trial dedispersion realigns
    the injection losslessly."""
    from peasoup_tpu.io import read_filterbank
    from peasoup_tpu.obs.injection import _delays_in_samples

    dm, nchans, nsamps = 40.0, 8, 2048
    fil = str(tmp_path / "dm.fil")
    # noise_max=1 -> the noise floor is all zeros: the file IS the train
    synthesize(fil, period=64 * TSAMP, dm=dm, duty=0.1, amp=100.0,
               noise_max=1, nsamps=nsamps, nchans=nchans)
    data = np.asarray(read_filterbank(fil).data)
    delays = _delays_in_samples(dm, delay_table(nchans, TSAMP, 1510.0,
                                                -10.0))
    assert delays[-1] > 0  # the injection really is dispersed
    for j in range(1, nchans):
        d = int(delays[j])
        np.testing.assert_array_equal(data[d:, j], data[:nsamps - d, 0])


def test_smoke_observation_is_the_legacy_recipe(tmp_path):
    """The consolidated smoke helper stays byte-identical to the
    historical private ``_write_synthetic`` every smoke tool used."""
    from peasoup_tpu.io.sigproc import SigprocHeader, write_sigproc_header

    for seed, trunc in ((0, 0), (2, 1024)):
        legacy = str(tmp_path / f"legacy{seed}.fil")
        rng = np.random.default_rng(seed)
        data = rng.integers(0, 32, size=(4096, 16), dtype=np.uint8)
        data[::16] += 60
        hdr = SigprocHeader(nbits=8, nchans=16, tsamp=TSAMP,
                            fch1=1510.0, foff=-10.0, nsamples=4096)
        with open(legacy, "wb") as f:
            write_sigproc_header(f, hdr, include_nsamples=True)
            payload = data.tobytes()
            f.write(payload[:-trunc] if trunc else payload)
        ours = str(tmp_path / f"ours{seed}.fil")
        smoke_observation(ours, seed=seed, truncate_bytes=trunc)
        assert open(ours, "rb").read() == open(legacy, "rb").read()


def test_amp_calibration():
    assert noise_sigma(32) == pytest.approx(np.sqrt((32 * 32 - 1) / 12))
    a1 = amp_for_snr(10.0, duty=0.05, nsamps=4096, nchans=16,
                     noise_max=32)
    a2 = amp_for_snr(20.0, duty=0.05, nsamps=4096, nchans=16,
                     noise_max=32)
    assert a2 == pytest.approx(2 * a1)  # linear in target SNR
    with pytest.raises(ValueError):
        synthesize("/tmp/never.fil", period=1.0, freq=1.0, snr=1.0)
    with pytest.raises(ValueError):
        synthesize("/tmp/never.fil", period=1.0)


# --------------------------------------------------------------------------
# recovery matching
# --------------------------------------------------------------------------

def _manifest(freq=50.0, accel=0.0, jerk=0.0, size=4096):
    return {"freq": freq, "period": 1.0 / freq, "dm": 0.0,
            "accel": accel, "jerk": jerk, "size": size, "tsamp": TSAMP}


def test_match_harmonic_folds():
    man = _manifest()
    hits = [
        {"freq": 50.0, "snr": 9.0},    # fundamental
        {"freq": 25.0, "snr": 7.0},    # 1/2 fold
        {"freq": 100.0, "snr": 11.0},  # 2x fold
        {"freq": 61.3, "snr": 50.0},   # unrelated, however bright
    ]
    v = match_candidates(man, hits)
    assert v["recovered"] and v["n_matches"] == 3
    assert v["best"]["freq"] == 100.0 and v["best_snr"] == 11.0
    assert not match_candidates(man, [hits[3]])["recovered"]
    assert match_candidates(man, [])["best_snr"] == 0.0


def test_match_accel_jerk_windows():
    tobs = 4096 * TSAMP
    c = 299792458.0
    man = _manifest(accel=10.0)
    near = {"freq": 50.0, "snr": 5.0,
            "acc": 10.0 + 0.5 * 2e-3 * c / tobs}
    far = {"freq": 50.0, "snr": 5.0,
           "acc": 10.0 + 2.5 * 2e-3 * c / tobs}
    assert match_candidates(man, [near])["recovered"]
    assert not match_candidates(man, [far])["recovered"]
    # sign convention is resampler-relative: magnitudes compare
    assert match_candidates(man, [dict(near, acc=-near["acc"])])[
        "recovered"]
    man_j = _manifest(jerk=1e6)
    near_j = {"freq": 50.0, "snr": 5.0, "jerk": 1e6}
    far_j = {"freq": 50.0, "snr": 5.0,
             "jerk": 1e6 + 2.5 * 2e-3 * 6 * c / tobs ** 2}
    assert match_candidates(man_j, [near_j])["recovered"]
    assert not match_candidates(man_j, [far_j])["recovered"]


def test_match_dm_window_and_objects():
    man = _manifest()
    cand = Candidate(freq=50.0, dm=3.0, snr=8.0)  # attr access path
    assert match_candidates(man, [cand])["recovered"]
    assert not match_candidates(man, [cand], dm_tol=1.0)["recovered"]
    assert match_candidates(man, [cand], dm_tol=5.0)["recovered"]


# --------------------------------------------------------------------------
# per-stage SNR budget probe (one real search)
# --------------------------------------------------------------------------

def test_budget_probe_monotone(tmp_path):
    from peasoup_tpu.io import read_filterbank
    from peasoup_tpu.parallel.mesh import MeshPulsarSearch
    from peasoup_tpu.search.plan import SearchConfig

    fil = str(tmp_path / "inj.fil")
    man = synthesize(fil, period=16 * TSAMP, snr=40.0, duty=0.05,
                     seed=5, size=2048)
    man_path = save_manifest(man, fil + ".manifest.json")
    cfg = SearchConfig(dm_start=0.0, dm_end=20.0, min_snr=6.0, npdmp=0,
                       limit=16, size=2048, injection_manifest=man_path)
    result = MeshPulsarSearch(read_filterbank(fil), cfg).run()

    probe = result.injection
    assert probe is not None and probe["recovered"]
    snr = probe["snr"]
    # bin-centered injection: each later tap can only lose signal
    # (harmonic summing may then lift it again, so only these three
    # are ordered)
    assert snr["whiten"] >= snr["interbin"] >= snr["fourier_bin"] > 0
    assert snr["peak"] > 0 and snr["harmonic_best"] >= snr["interbin"]
    assert probe["loss"]["scalloping"] >= 0
    assert probe["loss"]["interbin_residual"] >= 0
    assert set(probe["trial"]) == {"dm", "dm_idx", "acc", "jerk"}
    gauges = REGISTRY.snapshot()["gauges"]
    assert gauges.get("injection.recovered") == 1
    assert gauges.get("injection.snr_whiten", 0) > 0


# --------------------------------------------------------------------------
# canary jobs end-to-end + store isolation (one in-process drain)
# --------------------------------------------------------------------------

def test_canary_drain_and_store_isolation(tmp_path):
    from peasoup_tpu.serve import (
        BackoffPolicy, CandidateStore, JobSpool, SurveyWorker,
    )

    spool = JobSpool(str(tmp_path / "jobs"))
    good_fil = str(tmp_path / "good.fil")
    good_man = smoke_observation(good_fil, seed=11)
    faint_fil = str(tmp_path / "faint.fil")
    faint_man = synthesize(faint_fil, period=16 * TSAMP, duty=0.05,
                           snr=1.0, seed=13)
    man_path = save_manifest(good_man, good_fil + ".manifest.json")
    spool.submit(good_fil,
                 dict(FAST, injection_manifest=man_path, size=2048),
                 canary=good_man)
    spool.submit(faint_fil, dict(FAST, size=2048), canary=faint_man)
    worker = SurveyWorker(
        spool, single_device=True,
        backoff=BackoffPolicy(max_attempts=2, base_s=0.0),
        history_path=None, sleeper=lambda s: None)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")  # canary_missed warns by design
        summary = worker.drain()

    # a missed canary is a health event, not a job failure
    assert spool.counts()["done"] == 2 and summary["failed"] == 0
    counters = REGISTRY.snapshot()["counters"]
    assert counters.get("canary.recovered") == 1
    assert counters.get("canary.missed") == 1
    assert counters.get("events.canary_missed") == 1

    verdicts = {}
    for rec in spool.jobs("done"):
        verdicts[rec.input] = rec.summary["canary"]
    assert verdicts[good_fil]["recovered"]
    assert verdicts[good_fil]["best_snr"] > 0
    assert not verdicts[faint_fil]["recovered"]

    # canary candidates never reach science reads
    store = CandidateStore(str(tmp_path / "jobs" / "candidates.jsonl"))
    assert store.count() == 0
    assert store.sources() == []
    assert store.query(good_man["freq"], freq_tol=1e-2, max_harm=2) == []
    assert store.coincident_groups(min_sources=1) == []
    tagged = store.records(include_canary=True)
    assert tagged and all(r.get("canary") is True for r in tagged)


def test_store_canary_tagging_direct(tmp_path):
    from peasoup_tpu.serve import CandidateStore

    store = CandidateStore(str(tmp_path / "cands.jsonl"))
    science = Candidate(freq=20.0, dm=5.0, snr=9.0, jerk=1e5)
    probe = Candidate(freq=20.0, dm=5.0, snr=9.0)
    store.ingest("job-a", "/obs/a.fil", [science], utc=1.0)
    store.ingest("job-b", "/obs/b.fil", [probe], utc=2.0, canary=True)
    recs = store.records()
    assert len(recs) == 1 and "canary" not in recs[0]
    assert recs[0]["jerk"] == pytest.approx(1e5)
    both = store.records(include_canary=True)
    assert len(both) == 2
    # the coincidencer must not pair a science hit with its own probe
    assert store.coincident_groups(freq_tol=1e-3, min_sources=2) == []


# --------------------------------------------------------------------------
# canary_recovery health rule (literal-dict fixtures)
# --------------------------------------------------------------------------

NOW = 100000.0


def _ctx(samples, ledger=()):
    from peasoup_tpu.serve.health import DEFAULT_WINDOW_S, HealthContext

    recent = [s for s in samples
              if s.get("ts", 0) >= NOW - DEFAULT_WINDOW_S]
    return HealthContext(now=NOW, samples=samples, recent=recent,
                         latest={}, queue={}, running=[],
                         ledger=list(ledger))


def _sample(ts, recovered=0, missed=0):
    return {"v": 1, "ts": ts, "host": "host-0",
            "counters": {"canary.recovered": recovered,
                         "canary.missed": missed}}


def _sens_rec(fraction):
    return {"kind": "sensitivity",
            "metrics": {"recovery_fraction": fraction}}


def test_canary_rule_fixtures():
    from peasoup_tpu.serve.health import (
        CRIT, OK, WARN, rule_canary_recovery,
    )

    f = rule_canary_recovery(_ctx([_sample(NOW - 10)]))[0]
    assert (f.rule, f.severity) == ("canary_recovery", OK)

    f = rule_canary_recovery(_ctx([_sample(NOW - 10, recovered=2)]))[0]
    assert f.severity == OK

    f = rule_canary_recovery(
        _ctx([_sample(NOW - 10, recovered=1, missed=1)]))[0]
    assert f.severity == CRIT and "MISSED" in f.message

    # a clean re-drain after a miss reports healthy again (last wins)
    f = rule_canary_recovery(_ctx([
        _sample(NOW - 60, missed=1), _sample(NOW - 10, recovered=1),
    ]))[0]
    assert f.severity == OK

    # window recovery below 80% of the sweep-ledger median -> warn
    # (1 of 2 recovered in-window vs median fraction 1.0); the miss is
    # in an OLD sample so the latest-drain check stays clean
    ledger = [_sens_rec(1.0), _sens_rec(1.0), _sens_rec(0.9)]
    f = rule_canary_recovery(_ctx([
        _sample(NOW - 200, missed=1), _sample(NOW - 10, recovered=1),
    ], ledger))[0]
    assert f.severity == WARN and "regressing" in f.message
    # fewer than 3 sweeps: no baseline, same samples stay ok
    f = rule_canary_recovery(_ctx([
        _sample(NOW - 200, missed=1), _sample(NOW - 10, recovered=1),
    ], ledger[:2]))[0]
    assert f.severity == OK


# --------------------------------------------------------------------------
# sensitivity ledger record schema
# --------------------------------------------------------------------------

def test_sensitivity_ledger_record(tmp_path):
    from peasoup_tpu.obs.history import load_history
    from peasoup_tpu.tools.sensitivity import append_sensitivity_record

    doc = {
        "cells": [{"recovered": True}, {"recovered": True},
                  {"recovered": False}],
        "recovery_fraction": 2 / 3,
        "min_detectable_snr": 12.0,
        "elapsed_s": 4.2,
        "transfer": [{"snr_in": 12.0, "fraction": 1.0}],
        "config": {"snrs": [40.0, 12.0, 1.5]},
    }
    history = str(tmp_path / "history.jsonl")
    append_sensitivity_record(doc, history)
    recs = load_history(history, kinds=("sensitivity",))
    assert len(recs) == 1
    m = recs[0]["metrics"]
    assert m["cells"] == 3
    assert m["recovery_fraction"] == pytest.approx(2 / 3)
    assert m["min_detectable_snr"] == 12.0
    # an inconclusive sweep has no min_detectable_snr metric at all
    doc2 = dict(doc, min_detectable_snr=None)
    append_sensitivity_record(doc2, history)
    m2 = load_history(history, kinds=("sensitivity",))[-1]["metrics"]
    assert "min_detectable_snr" not in m2


# --------------------------------------------------------------------------
# jerk round-trips (overview.xml / candidates.peasoup / parsers)
# --------------------------------------------------------------------------

def _jerk_cand(jerk, freq=4.0):
    return Candidate(dm=30.0, dm_idx=9, acc=1.5, jerk=jerk, nh=2,
                     snr=50.0, freq=freq, opt_period=1.0 / freq)


def test_xml_jerk_roundtrip(tmp_path):
    from peasoup_tpu.output import OutputFileWriter, OverviewFile

    w = OutputFileWriter()
    w.add_candidates([_jerk_cand(2.5e6), _jerk_cand(0.0, freq=7.0)],
                     {0: 0, 1: 128})
    path = str(tmp_path / "overview.xml")
    w.to_file(path)
    arr = OverviewFile(path).as_array()
    assert arr["jerk"][0] == pytest.approx(2.5e6)
    assert arr["jerk"][1] == 0.0
    # pre-jerk files (no <jerk> element) parse with a zero column
    legacy = open(path).read().replace(
        "      <jerk>2500000</jerk>\n", "").replace(
        "      <jerk>0</jerk>\n", "")
    assert "<jerk>" not in legacy
    legacy_path = str(tmp_path / "legacy.xml")
    open(legacy_path, "w").write(legacy)
    ov = OverviewFile(legacy_path)
    arr = ov.as_array()
    assert list(arr["jerk"]) == [0.0, 0.0]
    assert ov.get_candidate(0)["jerk"] == 0.0


def test_binary_jerk_roundtrip(tmp_path):
    from peasoup_tpu.output import (
        CandidateFileParser, write_candidate_binary,
    )

    top = _jerk_cand(2.5e6)
    top.append(_jerk_cand(-1.25e6, freq=8.0))
    jerked = str(tmp_path / "jerked.peasoup")
    mapping = write_candidate_binary([top], jerked)
    with CandidateFileParser(jerked) as p:
        _, hits = p.cand_from_offset(mapping[0])
    assert list(hits["jerk"]) == [np.float32(2.5e6), np.float32(-1.25e6)]
    assert hits[0]["snr"] == pytest.approx(50.0)
    assert b"JRK0" in open(jerked, "rb").read()

    # an all-zero-jerk file keeps the reference byte layout exactly
    plain = str(tmp_path / "plain.peasoup")
    write_candidate_binary([_jerk_cand(0.0)], plain)
    blob = open(plain, "rb").read()
    assert b"JRK0" not in blob
    from peasoup_tpu.output.binary import POD_DTYPE

    assert len(blob) == 4 + POD_DTYPE.itemsize  # ndets + one POD
    with CandidateFileParser(plain) as p:
        _, hits = p.cand_from_offset(0)
    assert hits["jerk"][0] == 0.0


# --------------------------------------------------------------------------
# lattice sidecar recovery_delta
# --------------------------------------------------------------------------

def test_update_lattice_recovery_delta(tmp_path):
    from peasoup_tpu.search.tuning import load_lattice, update_lattice

    path = str(tmp_path / "tune.json")
    update_lattice(path, "cpu", "dedisperse", 2048,
                   costs={"f32": 1.0, "u8": 0.5},
                   picked="u8",
                   parity={"u8": {"ok": True, "max_snr_delta": 0.01,
                                  "candidates_moved": 0,
                                  "recovery_delta": 0.0},
                           "bf16": {"ok": True, "max_snr_delta": 0.0,
                                    "candidates_moved": 0}})
    sec = load_lattice(path)
    cell = sec["cpu"]["dedisperse/2048"]
    assert cell["parity"]["u8"]["recovery_delta"] == 0.0
    assert "recovery_delta" not in cell["parity"]["bf16"]


# --------------------------------------------------------------------------
# loadgen canary mix
# --------------------------------------------------------------------------

def test_job_mix_canary_disjoint_from_poison():
    from peasoup_tpu.tools.loadgen import job_mix

    rng = np.random.default_rng(0)
    specs = job_mix(40, rng, poison_fraction=0.25, canary_fraction=0.25)
    poison = {s["i"] for s in specs if s["poison"]}
    canary = {s["i"] for s in specs if s["canary"]}
    assert len(poison) == 10 and len(canary) == 10
    assert not (poison & canary)
    # deterministic for a fixed generator state
    specs2 = job_mix(40, np.random.default_rng(0),
                     poison_fraction=0.25, canary_fraction=0.25)
    assert specs == specs2


def test_write_observations_canary_manifest(tmp_path):
    from peasoup_tpu.tools.loadgen import job_mix, write_observations

    rng = np.random.default_rng(1)
    specs = job_mix(4, rng, canary_fraction=0.5)
    write_observations(specs, str(tmp_path / "obs"))
    canaries = [s for s in specs if s["canary"]]
    assert len(canaries) == 2
    for s in canaries:
        assert os.path.exists(s["path"])
        assert os.path.exists(s["manifest_path"])
        assert load_manifest(s["manifest_path"])["freq"] == \
            s["canary_manifest"]["freq"]
    for s in specs:
        if not s["canary"]:
            assert "canary_manifest" not in s
