import os

# Tests run on a virtual 8-device CPU mesh so multi-chip sharding logic is
# exercised without TPU hardware. Must be set before jax import.
os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# jax may already have been imported by the host's sitecustomize (which
# registers a TPU plugin), making the env vars above too late — force the
# platform through the live config instead.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)

import pytest  # noqa: E402

REFERENCE = "/root/reference"


@pytest.fixture(scope="session")
def tutorial_fil() -> str:
    path = os.path.join(REFERENCE, "example_data", "tutorial.fil")
    if not os.path.exists(path):
        pytest.skip("reference tutorial.fil not available")
    return path


@pytest.fixture(scope="session")
def golden_overview() -> str:
    path = os.path.join(REFERENCE, "example_output", "overview.xml")
    if not os.path.exists(path):
        pytest.skip("reference example output not available")
    return path
