import os
import sys

# Tests run on a virtual 8-device CPU mesh so multi-chip sharding logic is
# exercised without TPU hardware; the forcing recipe (env vars before jax
# import, live-config fallback after, backend reset) is shared with the
# driver's dryrun entry point.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from __graft_entry__ import _force_virtual_cpu_mesh  # noqa: E402

_force_virtual_cpu_mesh(8)

import pytest  # noqa: E402

REFERENCE = "/root/reference"


@pytest.fixture(scope="session")
def pallas_interpret():
    """Skip (with the probe's reason) on jax builds whose pallas
    interpret mode cannot run the package's kernels — e.g. jax
    0.4.37's i64 leak across interpret-mode pjit boundaries under
    jax_enable_x64.  See
    ``peasoup_tpu.ops.dedisperse_pallas.pallas_interpret_supported``."""
    from peasoup_tpu.ops.dedisperse_pallas import (
        pallas_interpret_supported,
    )

    ok, reason = pallas_interpret_supported()
    if not ok:
        pytest.skip(
            f"pallas interpret mode unsupported on this jax build: "
            f"{reason}")


@pytest.fixture(scope="session")
def peaks_pallas_interpret():
    """Skip when the peaks threshold-compaction kernel cannot run in
    interpret mode on this jax build (its own probe: the dedisperse
    probe's jax-0.4.37 failure is specific to those kernels' internal
    pjit/i64 boundary and does not gate this kernel).  See
    ``peasoup_tpu.ops.peaks_pallas.pallas_peaks_supported``."""
    from peasoup_tpu.ops.peaks_pallas import pallas_peaks_supported

    ok, reason = pallas_peaks_supported()
    if not ok:
        pytest.skip(
            f"peaks pallas kernel unsupported on this jax build: "
            f"{reason}")


@pytest.fixture(scope="session")
def tutorial_fil() -> str:
    path = os.path.join(REFERENCE, "example_data", "tutorial.fil")
    if not os.path.exists(path):
        pytest.skip("reference tutorial.fil not available")
    return path


@pytest.fixture(scope="session")
def golden_overview() -> str:
    path = os.path.join(REFERENCE, "example_output", "overview.xml")
    if not os.path.exists(path):
        pytest.skip("reference example output not available")
    return path
