"""Tier-1 tests for the concurrency & contracts prover (ISSUE 17):
PSL010 lock discipline, PSL011 lock ordering, PSL012 atomic-write
discipline, PSL013 stream contracts and PSL014 rename-publication
discipline (ISSUE 20) — plus the engine's parse cache and the
full-tree wall-clock budget."""

import os
import subprocess
import sys
import textwrap
import time

from peasoup_tpu.analysis import engine
from peasoup_tpu.analysis.engine import (
    Baseline,
    SourceFile,
    repo_root,
    run_rules,
)
from peasoup_tpu.analysis.rules import ALL_RULES, rules_by_id

REPO = repo_root()
NEW_RULES = ("PSL010", "PSL011", "PSL012", "PSL013", "PSL014")


def _lint_snippet(tmp_path, code, relpath, rule_ids):
    """Write ``code`` at ``relpath`` under a fixture tree and run the
    named rules exactly as the CLI would."""
    path = tmp_path / relpath
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(code))
    violations, suppressed, errors = run_rules(
        rules_by_id(list(rule_ids)), [str(path)], root=str(tmp_path))
    assert not errors, errors
    return violations, suppressed


# --------------------------------------------------------------------------
# PSL010 — lock discipline
# --------------------------------------------------------------------------

UNGUARDED = """
    import threading

    class Worker:
        def __init__(self):
            self.count = 0
            self._t = threading.Thread(target=self._run, daemon=True)

        def _run(self):
            while True:
                self.count += 1

        def snapshot(self):
            return self.count
"""


def test_psl010_unguarded_shared_attr_flagged(tmp_path):
    vs, _ = _lint_snippet(tmp_path, UNGUARDED,
                          "peasoup_tpu/serve/fixture.py", ["PSL010"])
    assert [v.rule for v in vs] == ["PSL010"]
    assert "self.count" in vs[0].message
    assert "common lock" in vs[0].message


def test_psl010_guarded_attr_clean(tmp_path):
    vs, _ = _lint_snippet(tmp_path, """
        import threading

        class Worker:
            def __init__(self):
                self._lock = threading.Lock()
                self.count = 0
                self._t = threading.Thread(target=self._run,
                                           daemon=True)

            def _run(self):
                while True:
                    with self._lock:
                        self.count += 1

            def snapshot(self):
                with self._lock:
                    return self.count
    """, "peasoup_tpu/serve/fixture.py", ["PSL010"])
    assert vs == []


def test_psl010_queue_handoff_and_event_exempt(tmp_path):
    """Locks, Events, queues and deques are synchronisation primitives
    — internally thread-safe, never flagged as shared state."""
    vs, _ = _lint_snippet(tmp_path, """
        import collections
        import queue
        import threading

        class Pipe:
            def __init__(self):
                self._q = queue.Queue()
                self._buf = collections.deque(maxlen=8)
                self._stop = threading.Event()
                self._t = threading.Thread(target=self._run)

            def _run(self):
                while not self._stop.is_set():
                    self._buf.append(self._q.get())

            def push(self, item):
                self._q.put(item)

            def close(self):
                self._stop.set()
    """, "peasoup_tpu/serve/fixture.py", ["PSL010"])
    assert vs == []


def test_psl010_init_is_happens_before_start(tmp_path):
    """Writes in __init__ precede Thread.start() — a thread-side-only
    attribute initialised in the constructor is not a conflict."""
    vs, _ = _lint_snippet(tmp_path, """
        import threading

        class Counter:
            def __init__(self):
                self._n = 0
                self._t = threading.Thread(target=self._run)

            def _run(self):
                self._n += 1
    """, "peasoup_tpu/serve/fixture.py", ["PSL010"])
    assert vs == []


def test_psl010_event_wait_loop_is_a_thread_entry(tmp_path):
    """The sampler idiom: a daemon loop discovered via its
    ``while ... self._evt.wait()`` shape, not a Thread(target=)."""
    vs, _ = _lint_snippet(tmp_path, """
        import threading

        class Sampler:
            def __init__(self):
                self._stop = threading.Event()
                self._t = threading.Thread(target=self._loop)
                self.seq = 0

            def _loop(self):
                while not self._stop.wait(1.0):
                    self.seq += 1

            def latest(self):
                return self.seq
    """, "peasoup_tpu/serve/fixture.py", ["PSL010"])
    assert [v.rule for v in vs] == ["PSL010"]
    assert "self.seq" in vs[0].message


def test_psl010_pragma_suppresses(tmp_path):
    code = UNGUARDED.replace(
        "self.count += 1",
        "self.count += 1  # psl: disable=PSL010 -- torn reads benign")
    vs, suppressed = _lint_snippet(
        tmp_path, code, "peasoup_tpu/serve/fixture.py", ["PSL010"])
    assert vs == []
    assert suppressed == 1


# --------------------------------------------------------------------------
# PSL011 — lock ordering
# --------------------------------------------------------------------------

AB_BA = """
    import threading

    LOCK_A = threading.Lock()
    LOCK_B = threading.Lock()

    def forward():
        with LOCK_A:
            with LOCK_B:
                pass

    def backward():
        with LOCK_B:
            with LOCK_A:
                pass
"""


def test_psl011_ab_ba_cycle_flagged(tmp_path):
    vs, _ = _lint_snippet(tmp_path, AB_BA,
                          "peasoup_tpu/serve/fixture.py", ["PSL011"])
    assert [v.rule for v in vs] == ["PSL011"]
    assert "lock-order cycle" in vs[0].message
    assert "LOCK_A" in vs[0].message and "LOCK_B" in vs[0].message


def test_psl011_consistent_order_clean(tmp_path):
    vs, _ = _lint_snippet(tmp_path, """
        import threading

        LOCK_A = threading.Lock()
        LOCK_B = threading.Lock()

        def forward():
            with LOCK_A:
                with LOCK_B:
                    pass

        def also_forward():
            with LOCK_A:
                with LOCK_B:
                    pass
    """, "peasoup_tpu/serve/fixture.py", ["PSL011"])
    assert vs == []


def test_psl011_cycle_through_a_call_flagged(tmp_path):
    """The graph is interprocedural: holding A while calling a
    function that takes B closes the cycle against a B->A nesting."""
    vs, _ = _lint_snippet(tmp_path, """
        import threading

        LOCK_A = threading.Lock()
        LOCK_B = threading.Lock()

        def takes_b():
            with LOCK_B:
                pass

        def holds_a():
            with LOCK_A:
                takes_b()

        def backward():
            with LOCK_B:
                with LOCK_A:
                    pass
    """, "peasoup_tpu/serve/fixture.py", ["PSL011"])
    assert [v.rule for v in vs] == ["PSL011"]
    assert "lock-order cycle" in vs[0].message


def test_psl011_pragma_suppresses(tmp_path):
    """A pragma on the witness acquisition (the inner `with` that
    closes the cycle) silences the finding."""
    vs, suppressed = _lint_snippet(tmp_path, """
        import threading

        LOCK_A = threading.Lock()
        LOCK_B = threading.Lock()

        def forward():
            with LOCK_A:
                with LOCK_B:
                    pass

        def backward():
            with LOCK_B:
                with LOCK_A:  # psl: disable=PSL011 -- startup only
                    pass
    """, "peasoup_tpu/serve/fixture.py", ["PSL011"])
    assert vs == []
    assert suppressed == 1


# --------------------------------------------------------------------------
# PSL012 — atomic-write discipline
# --------------------------------------------------------------------------

def test_psl012_raw_truncating_open_flagged(tmp_path):
    vs, _ = _lint_snippet(tmp_path, """
        import json

        def save(path, doc):
            with open(path, "w") as f:
                json.dump(doc, f)
    """, "peasoup_tpu/serve/fixture.py", ["PSL012"])
    assert [v.rule for v in vs] == ["PSL012"]
    assert "atomic" in vs[0].message


def test_psl012_mode_kwarg_flagged(tmp_path):
    vs, _ = _lint_snippet(tmp_path, """
        def save(path):
            f = open(path, mode="w")
            f.close()
    """, "peasoup_tpu/obs/fixture.py", ["PSL012"])
    assert [v.rule for v in vs] == ["PSL012"]


def test_psl012_append_binary_and_reads_exempt(tmp_path):
    """Appends are crash-extending not crash-truncating; "wb"/"x" and
    reads are out of scope."""
    vs, _ = _lint_snippet(tmp_path, """
        def ok(path):
            with open(path, "a") as f:
                f.write("line\\n")
            with open(path, "wb") as f:
                f.write(b"blob")
            with open(path, "x") as f:
                f.write("new")
            with open(path) as f:
                return f.read()
    """, "peasoup_tpu/serve/fixture.py", ["PSL012"])
    assert vs == []


def test_psl012_scoped_to_serve_and_obs(tmp_path):
    vs, _ = _lint_snippet(tmp_path, """
        def save(path, text):
            with open(path, "w") as f:
                f.write(text)
    """, "peasoup_tpu/ops/fixture.py", ["PSL012"])
    assert vs == []


def test_psl012_pragma_suppresses(tmp_path):
    vs, suppressed = _lint_snippet(tmp_path, """
        def save(path, text):
            # psl: disable-file=PSL012 -- fixture writer, not an artifact
            with open(path, "w") as f:
                f.write(text)
    """, "peasoup_tpu/serve/fixture.py", ["PSL012"])
    assert vs == []
    assert suppressed == 1


# --------------------------------------------------------------------------
# PSL014 — rename publication discipline
# --------------------------------------------------------------------------

def test_psl014_hand_rolled_rename_flagged(tmp_path):
    vs, _ = _lint_snippet(tmp_path, """
        import json
        import os

        def publish(path, doc):
            tmp = path + ".tmp"
            with open(tmp, "x") as f:
                json.dump(doc, f)
            os.replace(tmp, path)
    """, "peasoup_tpu/serve/fixture.py", ["PSL014"])
    assert [v.rule for v in vs] == ["PSL014"]
    assert "atomicio" in vs[0].message


def test_psl014_os_rename_flagged(tmp_path):
    vs, _ = _lint_snippet(tmp_path, """
        import os

        def move(src, dst):
            os.rename(src, dst)
    """, "peasoup_tpu/obs/fixture.py", ["PSL014"])
    assert [v.rule for v in vs] == ["PSL014"]


def test_psl014_rotation_idiom_and_queue_exempt(tmp_path):
    """The shard rotation (``path + ".1"``) and the spool state
    machine (serve/queue.py — the rename IS the state transition)
    stay sanctioned."""
    vs, _ = _lint_snippet(tmp_path, """
        import os

        def rotate(path):
            os.replace(path, path + ".1")
    """, "peasoup_tpu/obs/fixture.py", ["PSL014"])
    assert vs == []
    vs, _ = _lint_snippet(tmp_path, """
        import os

        def transition(src, dst):
            os.rename(src, dst)
    """, "peasoup_tpu/serve/queue.py", ["PSL014"])
    assert vs == []


def test_psl014_dynamic_and_binary_update_modes_flagged(tmp_path):
    """The gap PSL012's constant-text check leaves: a runtime mode
    expression and a binary truncate-and-read-back mode."""
    vs, _ = _lint_snippet(tmp_path, """
        def save(path, mode, blob):
            with open(path, mode) as f:
                f.write(blob)
            with open(path, "wb+") as f:
                f.write(blob)
    """, "peasoup_tpu/serve/fixture.py", ["PSL014"])
    assert [v.rule for v in vs] == ["PSL014", "PSL014"]
    assert "runtime expression" in vs[0].message


def test_psl014_plain_binary_reads_appends_exempt(tmp_path):
    vs, _ = _lint_snippet(tmp_path, """
        def ok(path, blob):
            with open(path, "wb") as f:
                f.write(blob)
            with open(path, "rb") as f:
                f.read()
            with open(path, "a") as f:
                f.write("line\\n")
            with open(path) as f:
                f.read()
    """, "peasoup_tpu/obs/fixture.py", ["PSL014"])
    assert vs == []


def test_psl014_scoped_to_serve_and_obs(tmp_path):
    vs, _ = _lint_snippet(tmp_path, """
        import os

        def move(src, dst):
            os.rename(src, dst)
    """, "peasoup_tpu/ops/fixture.py", ["PSL014"])
    assert vs == []


def test_psl014_whole_tree_clean():
    """The shipped serve/obs planes satisfy their own prover rule —
    the segment/index/manifest writers all publish through atomicio."""
    vs, _suppressed, errors = run_rules(rules_by_id(["PSL014"]))
    assert not errors, errors
    assert vs == [], "\n".join(v.format() for v in vs)


# --------------------------------------------------------------------------
# PSL013 — stream contracts
# --------------------------------------------------------------------------

def test_psl013_undeclared_writer_key_flagged(tmp_path):
    """A writer dict literal sneaking in a key the catalog does not
    declare fails the build (fixture impersonates obs/events.py, a
    declared writer site)."""
    vs, _ = _lint_snippet(tmp_path, """
        SCHEMA_VERSION = 1

        class EventLog:
            def emit(self, kind, message):
                rec = {"v": SCHEMA_VERSION, "ts": 0.0,
                       "kind": kind, "message": message,
                       "smuggled": True}
                return rec
    """, "peasoup_tpu/obs/events.py", ["PSL013"])
    assert [v.rule for v in vs] == ["PSL013"]
    assert "smuggled" in vs[0].message


def test_psl013_declared_writer_keys_clean(tmp_path):
    vs, _ = _lint_snippet(tmp_path, """
        SCHEMA_VERSION = 1

        class EventLog:
            def emit(self, kind, message):
                rec = {"v": SCHEMA_VERSION, "ts": 0.0,
                       "kind": kind, "message": message,
                       "data": {}}
                return rec
    """, "peasoup_tpu/obs/events.py", ["PSL013"])
    assert vs == []


def test_psl013_impossible_reader_key_flagged(tmp_path):
    """A reader asking for a key no writer can produce is dead code or
    a typo — the exact shape of the ingest_timeline bug this PR
    fixed."""
    vs, _ = _lint_snippet(tmp_path, """
        TIMELINE_VERSION = 1

        def read_timeline(path):
            out = []
            for rec in []:
                out.append(rec.get("job"))
                out.append(rec["phase"])
            return out
    """, "peasoup_tpu/obs/timeline.py", ["PSL013"])
    assert [v.rule for v in vs] == ["PSL013"]
    assert "job" in vs[0].message


def test_psl013_version_drift_flagged(tmp_path):
    vs, _ = _lint_snippet(tmp_path, """
        SCHEMA_VERSION = 99
    """, "peasoup_tpu/obs/events.py", ["PSL013"])
    assert [v.rule for v in vs] == ["PSL013"]
    assert "99" in vs[0].message


def test_psl013_catalog_sourced_version_exempt(tmp_path):
    """A constant sourced from the catalog cannot drift — the
    WAREHOUSE_VERSION pattern is exempt by construction."""
    vs, _ = _lint_snippet(tmp_path, """
        from .streams import stream_version

        SCHEMA_VERSION = stream_version("events")
    """, "peasoup_tpu/obs/events.py", ["PSL013"])
    assert vs == []


def test_psl013_catalog_matches_live_writers():
    """Every declared version constant must match what the live module
    actually exports — the catalog describes reality."""
    from peasoup_tpu.obs.streams import STREAMS, stream_keys

    from peasoup_tpu.obs.events import SCHEMA_VERSION
    from peasoup_tpu.obs.history import HISTORY_VERSION
    from peasoup_tpu.obs.report import REPORT_VERSION
    from peasoup_tpu.obs.telemetry import TS_SCHEMA_VERSION
    from peasoup_tpu.obs.timeline import TIMELINE_VERSION

    assert STREAMS["events"]["version"] == SCHEMA_VERSION
    assert STREAMS["telemetry"]["version"] == TS_SCHEMA_VERSION
    assert STREAMS["timeline"]["version"] == TIMELINE_VERSION
    assert STREAMS["history"]["version"] == HISTORY_VERSION
    assert STREAMS["run_report"]["version"] == REPORT_VERSION
    # every version_key is itself a declared key
    for name, ent in STREAMS.items():
        assert ent["version_key"] in stream_keys(name), name


# --------------------------------------------------------------------------
# baseline round-trip + repo-clean gates
# --------------------------------------------------------------------------

def test_baseline_roundtrip_for_new_rules(tmp_path):
    """Grandfather a PSL010 finding, confirm split() covers it, then
    fix the code and confirm the entry expires."""
    vs, _ = _lint_snippet(tmp_path, UNGUARDED,
                          "peasoup_tpu/serve/fixture.py", ["PSL010"])
    assert len(vs) == 1
    bl_path = tmp_path / "baseline.json"
    Baseline.from_violations(vs).save(str(bl_path))
    bl = Baseline.load(str(bl_path))
    new, old, expired = bl.split(vs)
    assert (new, len(old), expired) == ([], 1, [])
    # fixed code -> no violations -> entry expires
    new, old, expired = bl.split([])
    assert new == [] and old == [] and len(expired) == 1


def test_repo_clean_under_new_rules():
    """PSL010-013 hold on the real tree with ZERO grandfathered
    entries: every real finding was fixed or pragma'd with a reason."""
    violations, _suppressed, errors = run_rules(
        rules_by_id(list(NEW_RULES)))
    assert not errors, errors
    assert violations == [], "\n".join(v.format() for v in violations)
    bl = Baseline.load(os.path.join(REPO, "lint_baseline.json"))
    assert not [e for e in bl.entries if e["rule"] in NEW_RULES], (
        "new rules must not lean on the baseline")


def test_rules_by_id_subsetting():
    rules = rules_by_id(["PSL010", "PSL011"])
    assert [r.id for r in rules] == ["PSL010", "PSL011"]
    assert all(r.id in {r2.id for r2 in ALL_RULES} for r in rules)


def test_cli_rules_subset_exits_zero_on_repo():
    proc = subprocess.run(
        [sys.executable, "-m", "peasoup_tpu.analysis",
         "--rules", "PSL010,PSL011,PSL012,PSL013", "--no-jaxpr"],
        capture_output=True, text=True, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


# --------------------------------------------------------------------------
# engine: parse cache + wall-clock budget
# --------------------------------------------------------------------------

def test_engine_parse_cache_parses_each_file_once(tmp_path, monkeypatch):
    """Two consecutive run_rules() over an unchanged tree must parse
    zero files the second time (stat-validated cache)."""
    path = tmp_path / "peasoup_tpu" / "serve" / "fixture.py"
    path.parent.mkdir(parents=True)
    path.write_text("X = 1\n")
    calls = []
    real_load = SourceFile.load.__func__

    def counting_load(cls, p, rel):
        calls.append(p)
        return real_load(cls, p, rel)

    monkeypatch.setattr(SourceFile, "load",
                        classmethod(counting_load))
    run_rules(rules_by_id(["PSL010"]), [str(path)], root=str(tmp_path))
    assert len(calls) == 1
    run_rules(rules_by_id(["PSL010"]), [str(path)], root=str(tmp_path))
    assert len(calls) == 1, "unchanged file was re-parsed"
    # an edit (size change) invalidates the entry
    path.write_text("X = 1\nY = 2\n")
    run_rules(rules_by_id(["PSL010"]), [str(path)], root=str(tmp_path))
    assert len(calls) == 2, "changed file was served stale"


def test_engine_cache_is_shared_across_rule_sets(tmp_path, monkeypatch):
    """The cache keys on the file, not the rule set — a --rules subset
    run after a full run re-parses nothing."""
    path = tmp_path / "peasoup_tpu" / "obs" / "fixture.py"
    path.parent.mkdir(parents=True)
    path.write_text("Y = 2\n")
    calls = []
    real_load = SourceFile.load.__func__
    monkeypatch.setattr(
        SourceFile, "load",
        classmethod(lambda cls, p, rel:
                    (calls.append(p), real_load(cls, p, rel))[1]))
    run_rules(ALL_RULES, [str(path)], root=str(tmp_path))
    run_rules(rules_by_id(["PSL012", "PSL013"]), [str(path)],
              root=str(tmp_path))
    assert len(calls) == 1


def test_full_tree_lint_wall_clock_budget():
    """All 13 rules over the whole package must stay interactive.
    Budget is deliberately generous (~8x the dev-box cold run) so it
    only trips on an algorithmic regression — the whole-program rules
    must stay near-linear in repo size, not quadratic."""
    t0 = time.perf_counter()
    violations, _, errors = run_rules(ALL_RULES)
    elapsed = time.perf_counter() - t0
    assert not errors, errors
    assert elapsed < 20.0, (
        f"full-tree lint took {elapsed:.1f}s (budget 20s) — "
        "did a whole-program pass go superlinear?")
