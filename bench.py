"""Benchmark: full DM x accel search on the reference's tutorial.fil.

Prints ONE JSON line {metric, value, unit, vs_baseline, ...}.  The
baseline is the reference's recorded end-to-end wall-clock of 0.770 s
on its 2014-era GPU(s) (`example_output/overview.xml`
<execution_times><total>, see BASELINE.md).  ``vs_baseline`` is the
speedup factor (baseline_seconds / our_seconds; >1 beats the reference).

The run reproduces the golden search exactly (dm 0-250 tol 1.10,
accel -5..+5 over the 3-trial grid, 4 harmonic sums, min_snr 9,
npdmp 10) and asserts parity of ALL TEN golden candidates — period,
spectral SNR (0.5%), folded SNR (1%; the f32 trials measurably agree
with the reference's uint8-trial folds to <= 0.5%), and exact
association counts —
before reporting a number, so the metric can't be gamed by returning
garbage fast.  Per-stage timers are included so a slow capture is
self-diagnosing (dedispersion — fused into the search dispatch — is
clocked by a dedicated dispatch outside the timed loop).

``--trace [path]`` additionally writes a Chrome trace-event JSON of
the benchmark's spans (obs/trace.py), including a parity-checked pass
on the chunked driver for its per-chunk ``Chunked-Search-<i>`` spans;
``--lint`` runs the peasoup-lint gate instead of the benchmark.

``--batch [B]`` (default 4) runs the batched-dispatch throughput
benchmark instead: B synthetic same-geometry observations are drained
twice — once with a ``batch=1`` worker (one device dispatch per job)
and once with ``batch=B`` (ONE dispatch for all B beams) — with
per-beam store records asserted bit-identical between the two drains
before any number is reported.  Both drains append their own
``kind="serve"`` ledger record (batch / batched_dispatches /
batch_fill metrics included), so the B=1 vs B=B ``jobs_per_hour``
pair is trendable from the same history the serve workers feed.

``--pipeline-depth [D]`` (default 2) runs the dispatch-pipeline
throughput benchmark instead: the same synthetic observations are
drained twice through the CHUNKED driver — serial
(``pipeline_depth=1``, the pre-ISSUE-11 dispatch→fetch→decode loop)
and pipelined (depth D) — with per-source store records asserted
bit-identical before any number is reported.  Both drains report
their measured ``device_duty_cycle`` (device seconds per wall second
over the span ledger), the gauge the pipeline exists to raise.

``--jerk [N]`` (default 5 trials) runs the jerk-axis benchmark
instead: an accel-only search vs the same search with an N-trial jerk
grid over one synthetic observation, asserting every accel-only
candidate survives in the jerked run (the grid contains the zero
trial) before reporting the per-trial cost ratio; appends a
``kind="jerk"`` ledger record with per-stage device seconds and the
resolved trial lattice.

``--sensitivity`` runs the sensitivity micro-bench instead: the
default injected-SNR sweep of ``tools/sensitivity.py`` (bright /
marginal / sub-threshold cells) over synthetic observations,
asserting the bright injections are recovered and the sub-threshold
one is not before reporting the recovery fraction; appends the
``kind="sensitivity"`` ledger record the perf gate trends
``recovery_fraction`` from and the ``canary_recovery`` health rule
reads its baseline median from.

``--loadgen [N]`` (default 16 jobs/rate) runs the open-loop
saturation micro-bench instead: a seeded two-rate in-process sweep
(``tools/loadgen.py`` — one rate under the stub workers' capacity,
one far over) reporting the detected saturation knee and per-rate
p50/p95/p99 sojourn, and appending the ``kind="loadgen"`` ledger
record the ``loadgen_saturation`` health rule reads its baseline
from.

``--coldstart [N]`` (default 2) runs the cold-start observatory
micro-bench instead (ISSUE 18): N synthetic same-geometry
observations are drained twice through fresh survey workers — once
COLD (first compiles of this process) and once WARM (programs
replayed from the jit cache) — and the wall time from drain start to
the first finished job is decomposed into read / trace / compile /
execute phases (the worker's ``cold_to_first_candidate_s`` metric).
The cold drain's spool-level ``compiles.jsonl`` must attribute its
compiles to the search geometry and the warm drain must add ZERO new
compile records before any number is reported; appends the
``kind="coldstart"`` ledger record the perf gate trends
``cold_to_first_candidate_s`` from.

``--chaos [budget_s]`` (default 360) runs the chaos-recovery
micro-bench instead: the seeded fault plan of ``tools/chaos.py``
(worker SIGKILL mid-job, one poison input, one over-quota tenant)
against a live supervised fleet, reporting ``chaos_recovery_s`` —
fault injection to ``health`` exit-0 — with zero jobs lost or
double-run asserted before the number is reported; appends the
``kind="chaos"`` ledger record the perf gate trends recovery time
from.

Every successful run appends one structured record (git sha, device,
timers, per-stage device time, roofline utilization, compile counts,
parity verdict) to ``benchmarks/history.jsonl`` through the shared
``obs/history.py`` writer; ``--gate`` then runs the noise-aware
regression gate (``python -m peasoup_tpu.tools.perf_report --gate``)
over the ledger and exits with its status.  ``--no-history`` skips
the append (scratch experiments must not pollute the trend).
"""

from __future__ import annotations

import json
import os
import re
import sys
import time

BASELINE_TOTAL_S = 0.769960045814514  # example_output/overview.xml <total>
TUTORIAL = "/root/reference/example_data/tutorial.fil"
GOLDEN_XML = "/root/reference/example_output/overview.xml"


def load_golden(path: str) -> list[dict]:
    """All ten golden candidates from the reference's shipped output."""
    text = open(path).read()
    out = []
    for block in re.findall(r"<candidate id='\d+'>(.*?)</candidate>", text,
                            re.S):
        def f(tag):
            return float(re.search(rf"<{tag}>([^<]+)</{tag}>", block).group(1))
        out.append(dict(
            period=f("period"), dm=f("dm"), acc=f("acc"), nh=int(f("nh")),
            snr=f("snr"), folded_snr=f("folded_snr"), nassoc=int(f("nassoc")),
        ))
    return out


def check_parity(result, golden: list[dict]) -> list[str]:
    """Compare all golden candidates against the search result; returns
    a list of human-readable failures (empty = parity holds)."""
    fails = []
    cands = list(result.candidates)
    if len(result.dm_list) != 59:
        fails.append(f"dm trials {len(result.dm_list)} != 59")
    if len(cands) < len(golden):
        fails.append(f"only {len(cands)} candidates < {len(golden)}")
    for g in golden:
        c = next(
            (c for c in cands
             if abs(1.0 / c.freq - g["period"]) / g["period"] < 1e-6
             and abs(c.dm - g["dm"]) < 0.01),
            None,
        )
        tag = f"P={g['period']:.6f} dm={g['dm']:.2f}"
        if c is None:
            fails.append(f"missing candidate {tag}")
            continue
        if c.nh != g["nh"]:
            fails.append(f"{tag}: nh {c.nh} != {g['nh']}")
        if abs(c.snr - g["snr"]) / g["snr"] > 5e-3:
            fails.append(f"{tag}: snr {c.snr:.2f} != {g['snr']:.2f}")
        if g["folded_snr"] > 0 and (
            abs(c.folded_snr - g["folded_snr"]) / g["folded_snr"] > 1e-2
        ):
            fails.append(
                f"{tag}: folded_snr {c.folded_snr:.2f} != "
                f"{g['folded_snr']:.2f}"
            )
        if c.count_assoc() != g["nassoc"]:
            fails.append(
                f"{tag}: nassoc {c.count_assoc()} != {g['nassoc']}"
            )
    return fails


def telemetry_delta(before: dict, after: dict) -> dict:
    """Compile-count + device/host stage split between two registry
    snapshots (obs/metrics.py) — the attribution BENCH_*.json lacked:
    how much of the wall-clock was device execution vs host tail, and
    whether any run paid an (unexpected) recompile."""
    stages = {}
    for name, rec in after["timers"].items():
        prev = before["timers"].get(
            name, {"count": 0, "host_s": 0.0, "device_s": 0.0})
        d_host = rec["host_s"] - prev["host_s"]
        if rec["count"] > prev["count"] or d_host > 1e-9:
            stages[name] = {
                "count": rec["count"] - prev["count"],
                "host_s": round(d_host, 4),
                "device_s": round(rec["device_s"] - prev["device_s"], 4),
            }
    return {
        "backend_compiles": (
            after["counters"].get("jit.backend_compiles", 0)
            - before["counters"].get("jit.backend_compiles", 0)
        ),
        "stages": stages,
    }


def run_lint() -> int:
    """``bench.py --lint``: the peasoup-lint gate (AST rules + jaxpr
    program checks) as a bench-entry spelling of ``make lint``, so CI
    that drives everything through bench.py can run the checker in one
    command before tier-1."""
    from peasoup_tpu.analysis.cli import main as lint_main

    return lint_main([])


def batch_arg(argv: list[str]) -> int | None:
    """``--batch [B]``: run the batched-dispatch throughput benchmark
    with B same-geometry beams per dispatch (default 4)."""
    if "--batch" not in argv:
        return None
    i = argv.index("--batch")
    if i + 1 < len(argv) and not argv[i + 1].startswith("-"):
        return max(2, int(argv[i + 1]))
    return 4


def run_batch_bench(b: int) -> int:
    """``bench.py --batch B``: B=1 vs B=b survey drains over the same
    synthetic observations; prints one JSON line with both
    ``jobs_per_hour`` figures and the speedup, after asserting the
    batched drain's per-beam store records are bit-identical to the
    sequential reference (throughput that changes candidates is not
    throughput)."""
    import shutil
    import tempfile

    from peasoup_tpu.obs.metrics import REGISTRY
    from peasoup_tpu.serve import CandidateStore, JobSpool, SurveyWorker
    from peasoup_tpu.tools.batch_smoke import (
        _store_fingerprint, _write_synthetic,
    )

    work = tempfile.mkdtemp(prefix="peasoup-batch-bench-")
    # --no-history: point the workers at a throwaway ledger so scratch
    # experiments stay out of the trend (same contract as the e2e bench)
    history = (os.path.join(work, "history.jsonl")
               if "--no-history" in sys.argv[1:] else None)
    try:
        overrides = {"dm_end": 20.0, "min_snr": 6.0, "npdmp": 0,
                     "limit": 10}
        obs = [
            _write_synthetic(os.path.join(work, f"obs{i}.fil"), seed=i)
            for i in range(b)
        ]
        modes = {}
        fps = {}
        for label, width in (("sequential", 1), ("batched", b)):
            REGISTRY.reset()
            spool = JobSpool(os.path.join(work, f"jobs_{label}"))
            for path in obs:
                spool.submit(path, overrides)
            summary = SurveyWorker(
                spool, batch=width, history_path=history,
                sleeper=lambda s: None,
            ).drain()
            counters = REGISTRY.snapshot()["counters"]
            telem = summary.get("telemetry") or {}
            modes[label] = {
                "jobs_per_hour": summary["jobs_per_hour"],
                "elapsed_s": summary["elapsed_s"],
                "dispatches": counters.get("runs.mesh_fused", 0),
                "batched_dispatches": counters.get(
                    "scheduler.batched_dispatches", 0),
                "batch_fill": counters.get("scheduler.batch_fill", 0),
                "telemetry_overhead_s": telem.get("overhead_s", 0.0),
                "telemetry_overhead_frac": round(
                    telem.get("overhead_s", 0.0)
                    / max(summary["elapsed_s"], 1e-9), 6),
            }
            if summary["succeeded"] != b:
                print(json.dumps({
                    "metric": "batched_dispatch_jobs_per_hour",
                    "value": None, "batch": b,
                    "error": f"{label} drain succeeded "
                             f"{summary['succeeded']}/{b}",
                }))
                return 1
            fps[label] = _store_fingerprint(CandidateStore(os.path.join(
                work, f"jobs_{label}", "candidates.jsonl")), obs)
        parity_ok = fps["sequential"] == fps["batched"]
        out = {
            "metric": "batched_dispatch_jobs_per_hour",
            "value": modes["batched"]["jobs_per_hour"],
            "unit": "jobs/h",
            "batch": b,
            "vs_sequential": round(
                modes["batched"]["jobs_per_hour"]
                / max(modes["sequential"]["jobs_per_hour"], 1e-9), 3),
            "modes": modes,
            "parity": ("per-beam candidates bit-identical"
                       if parity_ok else "PER-BEAM PARITY FAILED"),
        }
        print(json.dumps(out))
        return 0 if parity_ok else 1
    finally:
        shutil.rmtree(work, ignore_errors=True)


def pipeline_depth_arg(argv: list[str]) -> int | None:
    """``--pipeline-depth [D]``: run the dispatch-pipeline throughput
    benchmark at depth D vs the serial depth-1 reference (default 2)."""
    if "--pipeline-depth" not in argv:
        return None
    i = argv.index("--pipeline-depth")
    if i + 1 < len(argv) and not argv[i + 1].startswith("-"):
        return max(2, int(argv[i + 1]))
    return 2


def run_pipeline_bench(depth: int) -> int:
    """``bench.py --pipeline-depth D``: depth-1 vs depth-D survey
    drains through the chunked driver over the same synthetic
    observations; prints one JSON line with both ``jobs_per_hour`` and
    ``device_duty_cycle`` figures plus the speedup, after asserting
    the pipelined drain's per-source store records are bit-identical
    to the serial reference (a pipeline that changes candidates is a
    bug, not a speedup)."""
    import shutil
    import tempfile

    from peasoup_tpu.obs.metrics import REGISTRY
    from peasoup_tpu.serve import CandidateStore, JobSpool, SurveyWorker
    from peasoup_tpu.tools.batch_smoke import (
        _store_fingerprint, _write_synthetic,
    )

    work = tempfile.mkdtemp(prefix="peasoup-pipeline-bench-")
    history = (os.path.join(work, "history.jsonl")
               if "--no-history" in sys.argv[1:] else None)
    try:
        # dm_chunk forces the chunked driver — the pipeline's home turf
        overrides = {"dm_end": 20.0, "min_snr": 6.0, "npdmp": 0,
                     "limit": 10, "dm_chunk": 4, "accel_block": 1}
        obs = [
            _write_synthetic(os.path.join(work, f"obs{i}.fil"), seed=i)
            for i in range(4)
        ]
        modes = {}
        fps = {}
        for label, d in (("serial", 1), ("pipelined", depth)):
            REGISTRY.reset()
            spool = JobSpool(os.path.join(work, f"jobs_{label}"))
            for path in obs:
                spool.submit(path, dict(overrides, pipeline_depth=d))
            summary = SurveyWorker(
                spool, history_path=history, sleeper=lambda s: None,
            ).drain()
            snap = REGISTRY.snapshot()
            modes[label] = {
                "pipeline_depth": d,
                "jobs_per_hour": summary["jobs_per_hour"],
                "elapsed_s": summary["elapsed_s"],
                "device_duty_cycle": snap["gauges"].get(
                    "device_duty_cycle", 0.0),
            }
            if summary["succeeded"] != len(obs):
                print(json.dumps({
                    "metric": "pipelined_dispatch_jobs_per_hour",
                    "value": None, "pipeline_depth": depth,
                    "error": f"{label} drain succeeded "
                             f"{summary['succeeded']}/{len(obs)}",
                }))
                return 1
            fps[label] = _store_fingerprint(CandidateStore(os.path.join(
                work, f"jobs_{label}", "candidates.jsonl")), obs)
        parity_ok = fps["serial"] == fps["pipelined"]
        out = {
            "metric": "pipelined_dispatch_jobs_per_hour",
            "value": modes["pipelined"]["jobs_per_hour"],
            "unit": "jobs/h",
            "pipeline_depth": depth,
            "vs_serial": round(
                modes["pipelined"]["jobs_per_hour"]
                / max(modes["serial"]["jobs_per_hour"], 1e-9), 3),
            "device_duty_cycle": modes["pipelined"]["device_duty_cycle"],
            "modes": modes,
            "parity": ("per-source candidates bit-identical"
                       if parity_ok else "PER-SOURCE PARITY FAILED"),
        }
        print(json.dumps(out))
        return 0 if parity_ok else 1
    finally:
        shutil.rmtree(work, ignore_errors=True)


def loadgen_arg(argv: list[str]) -> int | None:
    """``--loadgen [jobs]``: run the open-loop saturation micro-bench
    (in-process stub workers, two offered rates straddling capacity)
    instead of the e2e search benchmark (default 16 jobs/rate)."""
    if "--loadgen" not in argv:
        return None
    i = argv.index("--loadgen")
    if i + 1 < len(argv) and not argv[i + 1].startswith("-"):
        return max(4, int(argv[i + 1]))
    return 16


def run_loadgen_bench(jobs: int) -> int:
    """``bench.py --loadgen N``: a seeded two-rate in-process
    saturation sweep (tools/loadgen.py) — one rate under capacity, one
    far over — printing one JSON line with the detected knee and the
    per-rate sojourn percentiles.  The sweep appends its own
    ``kind="loadgen"`` ledger record (the ``loadgen_saturation``
    health rule's baseline); ``--no-history`` routes it to a
    throwaway ledger."""
    import shutil
    import tempfile

    from peasoup_tpu.tools.loadgen import sweep

    work = tempfile.mkdtemp(prefix="peasoup-loadgen-bench-")
    history = (os.path.join(work, "history.jsonl")
               if "--no-history" in sys.argv[1:] else None)
    try:
        # service_s 0.03 -> capacity ~33 jobs/s; 10/s keeps up, 80/s
        # saturates, so the sweep always exhibits a knee
        doc = sweep(work, rates=[10.0, 80.0], jobs=jobs, seed=0,
                    history=history, timeout_s=120.0, inprocess=True,
                    service_s=0.03, verbose=False)
        knee = doc["knee"]
        points = doc["points"]
        out = {
            "metric": "loadgen_knee_throughput",
            "value": knee["throughput_per_s"],
            "unit": "jobs/s",
            "knee_rate_per_s": knee["rate_per_s"],
            "saturated": knee["saturated"],
            "jobs_per_rate": jobs,
            "rates": doc["ledger_record"].get("rates", []),
            "timeline_overhead_frac":
                doc["timeline"]["overhead_frac"],
        }
        ok = (len(points) >= 2
              and all(p["done"] == jobs for p in points)
              and knee["throughput_per_s"] > 0)
        if not ok:
            out["error"] = "sweep incomplete: " + "; ".join(
                f"rate {p['offered_rate_per_s']:g}/s -> "
                f"{p['done']}/{p['jobs']} done"
                for p in points)
        print(json.dumps(out))
        return 0 if ok else 1
    finally:
        shutil.rmtree(work, ignore_errors=True)


def jerk_arg(argv: list[str]) -> int | None:
    """``--jerk [N]``: run the jerk-axis benchmark with an N-trial jerk
    grid (default 5; forced odd so the grid contains the exact zero
    trial the parity check relies on)."""
    if "--jerk" not in argv:
        return None
    i = argv.index("--jerk")
    n = 5
    if i + 1 < len(argv) and not argv[i + 1].startswith("-"):
        n = max(3, int(argv[i + 1]))
    return n if n % 2 else n + 1


def run_jerk_bench(njerk: int) -> int:
    """``bench.py --jerk N``: accel-only vs accel x N-jerk searches over
    the same synthetic observation (ISSUE 13).  The jerk grid contains
    the zero trial, so every candidate the accel-only search finds must
    survive in the jerked run (a grid that loses its own zero slice is
    broken, not bigger); that containment is asserted before any number
    is reported.  Prints one JSON line with both wall-clocks, the trial
    multiplier, and the per-trial cost ratio, and appends a
    ``kind="jerk"`` ledger record carrying per-stage device seconds and
    the resolved trial lattice so the tuner's pick is trendable."""
    import shutil
    import tempfile

    from peasoup_tpu.io import read_filterbank
    from peasoup_tpu.obs.costmodel import get_run_costs
    from peasoup_tpu.obs.metrics import REGISTRY
    from peasoup_tpu.search.plan import SearchConfig
    from peasoup_tpu.parallel.mesh import MeshPulsarSearch
    from peasoup_tpu.tools.batch_smoke import _write_synthetic

    work = tempfile.mkdtemp(prefix="peasoup-jerk-bench-")
    history = (os.path.join(work, "history.jsonl")
               if "--no-history" in sys.argv[1:] else None)
    try:
        path = _write_synthetic(os.path.join(work, "obs.fil"), seed=0)
        base = dict(dm_end=20.0, acc_start=-5.0, acc_end=5.0,
                    min_snr=6.0, npdmp=0, limit=32)
        half = (njerk - 1) // 2
        step = 10.0
        modes = {}
        cands = {}
        for label, extra in (
            ("accel_only", {}),
            ("jerk", dict(jerk_start=-half * step,
                          jerk_end=half * step, jerk_step=step)),
        ):
            REGISTRY.reset()
            fil = read_filterbank(path)
            search = MeshPulsarSearch(fil, SearchConfig(**base, **extra))
            search.run()  # warm-up absorbs compilation
            t0 = time.time()
            result = search.run()
            elapsed = time.time() - t0
            snap = REGISTRY.snapshot()
            geom = get_run_costs()["geometry"]
            cands[label] = [(round(c.freq, 9), round(float(c.dm), 3))
                            for c in result.candidates]
            modes[label] = {
                "elapsed_s": round(elapsed, 4),
                "n_trials_total": int(geom.n_trials_total),
                "njerk": int(geom.njerk),
                "trial_lattice": str(search.lattice),
                "s_per_ktrial": round(
                    1e3 * elapsed / max(geom.n_trials_total, 1), 4),
                "stage_device_s": {
                    k: round(rec.get("device_s", 0.0), 6)
                    for k, rec in snap["timers"].items()
                    if rec.get("device_s", 0.0) > 0.0},
            }
        missing = [c for c in cands["accel_only"]
                   if c not in cands["jerk"]]
        parity_ok = not missing
        mult = (modes["jerk"]["n_trials_total"]
                / max(modes["accel_only"]["n_trials_total"], 1))
        out = {
            "metric": "jerk_grid_s_per_ktrial",
            "value": modes["jerk"]["s_per_ktrial"],
            "unit": "s/ktrial",
            "njerk": njerk,
            "trial_multiplier": round(mult, 3),
            "wallclock_ratio": round(
                modes["jerk"]["elapsed_s"]
                / max(modes["accel_only"]["elapsed_s"], 1e-9), 3),
            "trial_lattice": modes["jerk"]["trial_lattice"],
            "modes": modes,
            "parity": ("accel-only candidates all survive the jerk "
                       "grid" if parity_ok else
                       f"JERK GRID LOST {len(missing)} ACCEL-ONLY "
                       f"CANDIDATES"),
        }
        print(json.dumps(out))
        from peasoup_tpu.obs.history import (
            append_history, make_history_record,
        )

        append_history(make_history_record(
            "jerk",
            metrics={"jerk_s_per_ktrial": out["value"],
                     "jerk_wallclock_ratio": out["wallclock_ratio"],
                     "jerk_trial_multiplier": out["trial_multiplier"],
                     "njerk": njerk},
            stage_device_s=modes["jerk"]["stage_device_s"],
            parity=out["parity"],
            extra={"trial_lattice": modes["jerk"]["trial_lattice"]},
        ), path=history)
        return 0 if parity_ok else 1
    finally:
        shutil.rmtree(work, ignore_errors=True)


def run_sensitivity_bench() -> int:
    """``bench.py --sensitivity``: the default injected-SNR sweep
    (ISSUE 14) over synthetic observations — a real search per cell,
    recovery matched against each cell's injection manifest and the
    per-stage SNR budget attached.  The bright (snr_in >= 10) cells
    must be recovered and the sub-threshold cell must not (a sweep
    that "recovers" a snr 1.5 injection is matching noise); both are
    asserted before any number is reported.  Prints one JSON line
    with the recovery fraction, min detectable SNR and transfer
    curve, and appends a ``kind="sensitivity"`` ledger record."""
    import shutil
    import tempfile

    from peasoup_tpu.obs.metrics import REGISTRY
    from peasoup_tpu.tools.sensitivity import run_sweep

    work = tempfile.mkdtemp(prefix="peasoup-sensitivity-bench-")
    history = (os.path.join(work, "history.jsonl")
               if "--no-history" in sys.argv[1:] else None)
    try:
        REGISTRY.reset()
        t0 = time.time()
        doc = run_sweep(
            work,
            overrides=dict(dm_end=20.0, min_snr=6.0, npdmp=0,
                           limit=16),
            history=history, verbose=False)
        elapsed = time.time() - t0
        bright = [c for c in doc["cells"] if c["snr_in"] >= 10.0]
        faint = [c for c in doc["cells"] if c["snr_in"] < 3.0]
        ok = (all(c["recovered"] for c in bright)
              and not any(c["recovered"] for c in faint))
        out = {
            "metric": "sensitivity_recovery_fraction",
            "value": doc["recovery_fraction"],
            "unit": "fraction",
            "min_detectable_snr": doc["min_detectable_snr"],
            "cells": len(doc["cells"]),
            "elapsed_s": round(elapsed, 3),
            "transfer": doc["transfer"],
            "parity": ("bright injections recovered, sub-threshold "
                       "missed" if ok else
                       "SENSITIVITY SWEEP INCONSISTENT: "
                       f"bright={[c['recovered'] for c in bright]} "
                       f"faint={[c['recovered'] for c in faint]}"),
        }
        print(json.dumps(out))
        return 0 if ok else 1
    finally:
        shutil.rmtree(work, ignore_errors=True)


def chaos_arg(argv: list[str]) -> float | None:
    """``--chaos [budget_s]``: run the supervised chaos-recovery
    micro-bench (tools/chaos.py phase A only — no control phase)
    instead of the e2e search benchmark (default 360s budget)."""
    if "--chaos" not in argv:
        return None
    i = argv.index("--chaos")
    if i + 1 < len(argv) and not argv[i + 1].startswith("-"):
        return max(30.0, float(argv[i + 1]))
    return 360.0


def run_chaos_bench(budget_s: float) -> int:
    """``bench.py --chaos``: the seeded fault plan (worker SIGKILL
    mid-job + poison input + over-quota tenant) against a live
    supervised fleet, printing one JSON line whose headline is
    ``chaos_recovery_s`` — fault injection to health exit-0.  The
    harness runs against a hermetic workdir ledger; the ``kind=
    "chaos"`` record lands in benchmarks/history.jsonl (the
    perf-gate trend) unless ``--no-history``."""
    import shutil
    import tempfile

    from peasoup_tpu.tools.chaos import run_smoke

    work = tempfile.mkdtemp(prefix="peasoup-chaos-bench-")
    try:
        rc, report = run_smoke(work, budget_s=budget_s, seed=0,
                               control=False)
        recovery = report.get("recovery_s")
        out = {
            "metric": "chaos_recovery_s",
            "value": recovery,
            "unit": "seconds",
            "jobs_total": report.get("jobs_total"),
            "jobs_done": report.get("jobs_done"),
            "jobs_failed": report.get("jobs_failed"),
            "admission_rejected": report.get("admission_rejected"),
            "supervise_actions": report.get("supervise_actions"),
            "parity": ("recovered" if rc == 0 and recovery is not None
                       else "CHAOS RECOVERY FAILED"),
        }
        print(json.dumps(out))
        if rc == 0 and recovery is not None \
                and "--no-history" not in sys.argv[1:]:
            from peasoup_tpu.obs.history import (
                append_history, make_history_record,
            )
            append_history(make_history_record(
                "chaos",
                {"chaos_recovery_s": recovery,
                 "faults_injected": len(report.get("plan", [])),
                 "jobs_total": report.get("jobs_total", 0),
                 "jobs_done": report.get("jobs_done", 0),
                 "jobs_failed": report.get("jobs_failed", 0),
                 "admission_rejected":
                     report.get("admission_rejected", 0)},
                config={"seed": report.get("seed", 0),
                        "budget_s": float(budget_s),
                        "plan": report.get("plan", [])}))
        return rc
    finally:
        shutil.rmtree(work, ignore_errors=True)


def coldstart_arg(argv: list[str]) -> int | None:
    """``--coldstart [N]``: run the cold-start observatory bench over
    N synthetic observations (default 2)."""
    if "--coldstart" not in argv:
        return None
    i = argv.index("--coldstart")
    if i + 1 < len(argv) and not argv[i + 1].startswith("-"):
        return max(1, int(argv[i + 1]))
    return 2


def run_coldstart_bench(n: int) -> int:
    """``bench.py --coldstart N``: cold vs warm worker drains over the
    same synthetic observations (ISSUE 18).

    The cold drain pays this process's first XLA compiles; the warm
    drain replays them from the in-process jit cache.  Each drain's
    ``cold_to_first_candidate_s`` is decomposed by the worker into
    read / trace / compile / execute phases; the cold spool's compile
    ledger must attribute its compiles to the search geometry and the
    warm spool's ledger must stay EMPTY (a warm worker that recompiles
    has broken program reuse — that is the regression this bench
    exists to catch) before any number is reported."""
    import shutil
    import tempfile

    from peasoup_tpu.obs.compilation import read_compiles
    from peasoup_tpu.obs.metrics import REGISTRY
    from peasoup_tpu.serve import JobSpool, SurveyWorker
    from peasoup_tpu.tools.batch_smoke import _write_synthetic

    work = tempfile.mkdtemp(prefix="peasoup-coldstart-bench-")
    history = (os.path.join(work, "history.jsonl")
               if "--no-history" in sys.argv[1:] else None)
    try:
        overrides = {"dm_end": 20.0, "min_snr": 6.0, "npdmp": 0,
                     "limit": 10}
        obs = [
            _write_synthetic(os.path.join(work, f"obs{i}.fil"), seed=i)
            for i in range(n)
        ]
        modes = {}
        for label in ("cold", "warm"):
            REGISTRY.reset()
            spool = JobSpool(os.path.join(work, f"jobs_{label}"))
            for path in obs:
                spool.submit(path, overrides)
            summary = SurveyWorker(
                spool, history_path=history, sleeper=lambda s: None,
            ).drain()
            if summary["succeeded"] != n:
                print(json.dumps({
                    "metric": "cold_to_first_candidate_s",
                    "value": None,
                    "error": f"{label} drain succeeded "
                             f"{summary['succeeded']}/{n}",
                }))
                return 1
            compiles = read_compiles(
                os.path.join(spool.root, "compiles.jsonl"),
                kinds=("compile",))
            modes[label] = {
                **summary.get("coldstart", {}),
                "jobs_per_hour": summary["jobs_per_hour"],
                "compiles": len(compiles),
                "attributed": sum(1 for r in compiles
                                  if r.get("program")),
            }
        cold, warm = modes["cold"], modes["warm"]
        problems = []
        if cold["compiles"] == 0:
            problems.append("cold drain ledgered zero compiles")
        elif cold["attributed"] != cold["compiles"]:
            problems.append(
                f"{cold['compiles'] - cold['attributed']} cold "
                f"compile(s) unattributed")
        if warm["compiles"] != 0:
            problems.append(
                f"warm drain ledgered {warm['compiles']} new "
                f"compile(s) — program reuse broken")
        out = {
            "metric": "cold_to_first_candidate_s",
            "value": cold.get("cold_to_first_candidate_s"),
            "unit": "s",
            "warm_to_first_candidate_s": warm.get(
                "cold_to_first_candidate_s"),
            "coldstart_overhead_s": round(
                cold.get("cold_to_first_candidate_s", 0.0)
                - warm.get("cold_to_first_candidate_s", 0.0), 4),
            "n_jobs": n,
            "modes": modes,
            "parity": ("; ".join(problems) if problems
                       else "cold compiles attributed, warm drain "
                            "compile-free"),
        }
        print(json.dumps(out))
        from peasoup_tpu.obs.history import (
            append_history, make_history_record,
        )

        append_history(make_history_record(
            "coldstart",
            metrics={
                "cold_to_first_candidate_s": cold.get(
                    "cold_to_first_candidate_s", 0.0),
                "coldstart_read_s": cold.get("read_s", 0.0),
                "coldstart_trace_s": cold.get("trace_s", 0.0),
                "coldstart_compile_s": cold.get("compile_s", 0.0),
                "coldstart_execute_s": cold.get("execute_s", 0.0),
                "warm_to_first_candidate_s": warm.get(
                    "cold_to_first_candidate_s", 0.0),
                "coldstart_compiles": cold["compiles"],
            },
            parity=out["parity"],
        ), path=history)
        return 0 if not problems else 1
    finally:
        shutil.rmtree(work, ignore_errors=True)


def store_arg(argv: list[str]) -> int | None:
    """``--store [records]``: run the log-structured candidate-store
    micro-bench (synthetic survey, full-scan vs indexed query, one
    compaction) instead of the e2e search benchmark (default
    100000 records)."""
    if "--store" not in argv:
        return None
    i = argv.index("--store")
    if i + 1 < len(argv) and not argv[i + 1].startswith("-"):
        return max(1000, int(argv[i + 1]))
    return 100_000


def run_store_bench(n: int) -> int:
    """``bench.py --store N``: ISSUE 20's acceptance measurement.

    Synthesizes an ``N``-record survey across 4 host shards, times a
    seeded set of harmonic ``query()`` calls against the raw JSONL
    tails (full scan), compacts into sealed segments, re-times the
    SAME queries through the frequency fence-post indexes, and checks
    the two answer sets are record-identical.  One ``kind:"store"``
    ledger record carries ``store_query_p50_ms`` (indexed),
    ``store_query_full_scan_p50_ms``, ``store_query_speedup`` and
    ``compaction_s`` — the perf gate's new store metrics.
    ``--no-history`` routes the record to a throwaway ledger."""
    import random
    import shutil
    import statistics
    import tempfile
    import types

    from peasoup_tpu.serve.compaction import (CompactionPolicy,
                                              Compactor)
    from peasoup_tpu.serve.store import ShardedCandidateStore

    work = tempfile.mkdtemp(prefix="peasoup-store-bench-")
    history = (os.path.join(work, "history.jsonl")
               if "--no-history" in sys.argv[1:] else None)
    rng = random.Random(0)
    try:
        n_hosts, per_job = 4, 250
        stores = [ShardedCandidateStore(work, host_label=f"host{h}")
                  for h in range(n_hosts)]
        written, job = 0, 0
        while written < n:
            batch = min(per_job, n - written)
            cands = [types.SimpleNamespace(
                dm=rng.uniform(0.0, 250.0), dm_idx=i,
                acc=rng.uniform(-5.0, 5.0), jerk=0.0,
                freq=rng.uniform(0.5, 500.0),
                snr=rng.uniform(7.0, 30.0), folded_snr=9.0, nh=2)
                for i in range(batch)]
            stores[job % n_hosts].ingest(
                f"job-{job:06d}", f"obs{job:06d}.fil", cands,
                utc=1000.0 + job)
            written += batch
            job += 1
        store = ShardedCandidateStore(work)
        shards = store.shard_files()
        probe_freqs = [rng.uniform(1.0, 400.0) for _ in range(12)]

        def timed_queries() -> tuple[list, float]:
            out, lat = [], []
            for f in probe_freqs:
                t0 = time.perf_counter()
                out.append(store.query(f, freq_tol=1e-4, max_harm=4))
                lat.append((time.perf_counter() - t0) * 1000.0)
            return out, statistics.median(lat)

        full_hits, full_p50_ms = timed_queries()
        t0 = time.perf_counter()
        report = Compactor(work, CompactionPolicy(min_bytes=1)) \
            .compact_once(force=True)
        compaction_s = time.perf_counter() - t0
        idx_hits, idx_p50_ms = timed_queries()
        identical = full_hits == idx_hits
        speedup = (full_p50_ms / idx_p50_ms) if idx_p50_ms > 0 \
            else float("inf")
        reads = dict(store.last_read_stats)

        from peasoup_tpu.obs.history import (append_history,
                                             make_history_record)
        append_history(make_history_record(
            "store",
            {"store_query_p50_ms": round(idx_p50_ms, 3),
             "store_query_full_scan_p50_ms": round(full_p50_ms, 3),
             "store_query_speedup": round(speedup, 2),
             "compaction_s": round(compaction_s, 3),
             "store_records": written},
            config={"shards": len(shards), "queries": len(probe_freqs),
                    "identical": bool(identical)}), history)
        out = {
            "metric": "store_query_p50_ms",
            "value": round(idx_p50_ms, 3), "unit": "ms",
            "full_scan_p50_ms": round(full_p50_ms, 3),
            "speedup": round(speedup, 2),
            "compaction_s": round(compaction_s, 3),
            "records": written, "shards": len(shards),
            "sealed_records": report.get("records"),
            "identical": bool(identical),
            "read_stats": reads,
        }
        ok = identical and report.get("compacted", False)
        if not ok:
            out["error"] = ("indexed query diverged from full scan"
                            if not identical else "compaction failed")
        print(json.dumps(out))
        return 0 if ok else 1
    finally:
        shutil.rmtree(work, ignore_errors=True)


def trace_arg(argv: list[str]) -> str | None:
    """``--trace [path]``: write a Chrome trace-event JSON of the
    benchmark's spans (default ./bench_trace.json)."""
    if "--trace" not in argv:
        return None
    i = argv.index("--trace")
    if i + 1 < len(argv) and not argv[i + 1].startswith("-"):
        return argv[i + 1]
    return "bench_trace.json"


def main() -> None:
    if "--lint" in sys.argv[1:]:
        sys.exit(run_lint())
    b = batch_arg(sys.argv[1:])
    if b is not None:
        sys.exit(run_batch_bench(b))
    d = pipeline_depth_arg(sys.argv[1:])
    if d is not None:
        sys.exit(run_pipeline_bench(d))
    lg = loadgen_arg(sys.argv[1:])
    if lg is not None:
        sys.exit(run_loadgen_bench(lg))
    jk = jerk_arg(sys.argv[1:])
    if jk is not None:
        sys.exit(run_jerk_bench(jk))
    if "--sensitivity" in sys.argv[1:]:
        sys.exit(run_sensitivity_bench())
    ch = chaos_arg(sys.argv[1:])
    if ch is not None:
        sys.exit(run_chaos_bench(ch))
    cs = coldstart_arg(sys.argv[1:])
    if cs is not None:
        sys.exit(run_coldstart_bench(cs))
    st = store_arg(sys.argv[1:])
    if st is not None:
        sys.exit(run_store_bench(st))
    trace_path = trace_arg(sys.argv[1:])
    from peasoup_tpu.io import read_filterbank
    from peasoup_tpu.obs.metrics import REGISTRY, install_compile_hook
    from peasoup_tpu.parallel.mesh import MeshPulsarSearch
    from peasoup_tpu.search.plan import SearchConfig
    from peasoup_tpu.utils import enable_compile_cache

    enable_compile_cache()
    install_compile_hook()

    if not os.path.exists(TUTORIAL):
        print(json.dumps({
            "metric": "tutorial_fil_e2e_wallclock", "value": None,
            "unit": "s", "vs_baseline": None,
            "error": "tutorial.fil not found",
        }))
        return

    golden = load_golden(GOLDEN_XML)
    assert len(golden) == 10, (
        f"parsed {len(golden)} golden candidates (format drift would "
        f"silently disable the parity gate)"
    )
    fil = read_filterbank(TUTORIAL)
    cfg = SearchConfig(
        dm_start=0.0, dm_end=250.0, acc_start=-5.0, acc_end=5.0,
        acc_pulse_width=64000.0, nharmonics=4, npdmp=10, limit=1000,
    )

    # Warm-up run on the same search object: XLA compilation is cached
    # per-process, static inputs (filterbank bytes, delay table, accel
    # grid) stay device-resident, and the run() tail pre-compiles the
    # capacity-auto-tuned program — mirroring how the reference's
    # 0.770 s excludes CUDA context/module setup and counts file
    # reading separately.
    search = MeshPulsarSearch(fil, cfg)
    search.prewarm_tuned = True  # warmup also compiles the auto-tuned program
    snap_cold = REGISTRY.snapshot()
    search.run()
    snap_warm = REGISTRY.snapshot()
    warmup_compiles = telemetry_delta(snap_cold, snap_warm)[
        "backend_compiles"]

    # best of five timed runs: the tunnel to the remote-attached TPU
    # adds 50-100 ms of per-fetch jitter (and occasional multi-second
    # stalls under contention), which a single capture can't separate
    # from real regressions — round 2's driver recorded 5.4 s where a
    # clean rerun gave 1.1 s.  The work is identical each run; min is
    # the standard noise-rejecting statistic.
    runs = []
    for _ in range(5):
        t0 = time.time()
        result = search.run()
        runs.append((time.time() - t0, result))
    snap_timed = REGISTRY.snapshot()
    runs.sort(key=lambda r: r[0])
    elapsed, result = runs[0]
    median_s = runs[len(runs) // 2][0]
    # device/host attribution + compile count across the 5 timed runs:
    # a nonzero timed compile count means the wall-clock includes
    # compilation (it must not — the warmup exists to absorb it)
    telemetry = telemetry_delta(snap_warm, snap_timed)
    telemetry["warmup_backend_compiles"] = warmup_compiles

    timers = {k: round(v, 4) for k, v in result.timers.items()}
    timers["all_runs_s"] = [round(r[0], 4) for r in runs]
    # median alongside best-of-5 so tunnel-latency luck is visible in
    # the recorded artifact (VERDICT r3 weak #6)
    timers["median_s"] = round(median_s, 4)
    # the fused program has no in-run dedispersion boundary, so the
    # mesh driver reports 0.0 (the BENCH_r05 blind spot); clock one
    # dedicated dispatch OUTSIDE the timed loop so the stage figure is
    # real without inflating the e2e number
    timers["dedispersion"] = round(search.measure_dedispersion_stage(), 4)
    fails = check_parity(result, golden)
    if fails:
        print(json.dumps({
            "metric": "tutorial_fil_e2e_wallclock", "value": elapsed,
            "unit": "s", "vs_baseline": None, "timers": timers,
            "error": "candidate parity check failed: " + "; ".join(fails),
        }))
        sys.exit(1)

    trace_info = None
    if trace_path:
        # one extra parity-checked run on the bounded-HBM chunked
        # driver: its per-chunk `Chunked-Search-<i>` spans (chunk id,
        # DM range, trial counts) are the per-chunk attribution the
        # fused single-dispatch path cannot produce.  Runs after the
        # timed section, so the headline number is unaffected.
        cfg_chunked = SearchConfig(
            dm_start=0.0, dm_end=250.0, acc_start=-5.0, acc_end=5.0,
            acc_pulse_width=64000.0, nharmonics=4, npdmp=10, limit=1000,
            dm_chunk=8, accel_block=1,
        )
        chunked_result = MeshPulsarSearch(fil, cfg_chunked).run()
        chunk_fails = check_parity(chunked_result, golden)
        from peasoup_tpu.obs.trace import get_tracer, write_merged_trace

        written = write_merged_trace(trace_path)
        trace_info = {
            "path": written,
            "spans": len(get_tracer().records()),
            "chunked_parity": (
                "ok" if not chunk_fails else "; ".join(chunk_fails)),
        }

    # perf accounting (obs/costmodel.py): join the run's closed-form
    # stage costs with the measured device time into per-stage
    # utilization — the bench's new roofline columns
    perf_cols = None
    utilization = {}
    stage_metrics = {}
    try:
        from peasoup_tpu.obs.costmodel import (
            get_run_costs,
            perf_section,
            utilization_summary,
        )
        from peasoup_tpu.obs.report import device_summary

        run_costs = get_run_costs()
        if run_costs is not None:
            snap_now = REGISTRY.snapshot()
            perf = perf_section(
                run_costs, snap_now["timers"], device_summary(),
                snap_now["gauges"])
            utilization = utilization_summary(perf)
            # device-time columns for the perf gate (ISSUE 6): the
            # peaks stage's (modelled-share) device seconds and the
            # pooled search-dispatch device time — a sort-wall
            # regression must trip the gate even when wall-clock
            # hides it behind tunnel jitter
            peaks_row = perf["stages"].get("peaks", {})
            if isinstance(peaks_row.get("device_s"), (int, float)):
                stage_metrics["peaks_device_s"] = peaks_row["device_s"]
            search_dev = sum(
                rec.get("device_s", 0.0)
                for name, rec in snap_now["timers"].items()
                if name in ("accel_search", "fused_search",
                            "chunked_search")
            )
            if search_dev > 0.0:
                stage_metrics["search_device_s"] = round(search_dev, 6)
            perf_cols = {
                name: {
                    "gflops": round(row["flops"] / 1e9, 2),
                    **({"utilization": row["utilization"]}
                       if "utilization" in row else {}),
                }
                for name, row in perf["stages"].items()
            }
    except Exception as exc:  # perf accounting must never fail a bench
        perf_cols = {"error": repr(exc)}

    out = {
        "metric": "tutorial_fil_e2e_wallclock",
        "value": round(elapsed, 4),
        "unit": "s",
        "vs_baseline": round(BASELINE_TOTAL_S / elapsed, 3),
        "median_s": round(median_s, 4),
        "vs_baseline_median": round(BASELINE_TOTAL_S / median_s, 3),
        "timers": timers,
        "telemetry": telemetry,
        "parity": f"all {len(golden)} golden candidates matched",
    }
    if perf_cols is not None:
        out["perf"] = perf_cols
    if trace_info is not None:
        out["trace"] = trace_info
    print(json.dumps(out))

    if "--no-history" not in sys.argv[1:]:
        from peasoup_tpu.obs.history import (
            append_history,
            make_history_record,
            stage_device_seconds,
        )

        # the last timed run's duty cycle (ISSUE 11): device seconds
        # per wall second over the span ledger — trendable next to the
        # wall-clock so "did the pipeline stop hiding host work" is
        # answerable from the same history
        duty = REGISTRY.snapshot()["gauges"].get("device_duty_cycle")
        append_history(make_history_record(
            "bench",
            metrics={"e2e_s": round(elapsed, 4),
                     "median_s": round(median_s, 4),
                     "vs_baseline": out["vs_baseline"],
                     **({"device_duty_cycle": duty}
                        if isinstance(duty, (int, float)) else {}),
                     **stage_metrics},
            timers={k: v for k, v in timers.items()
                    if isinstance(v, (int, float))},
            stage_device_s=stage_device_seconds(REGISTRY.snapshot()),
            utilization=utilization,
            compile_counts={
                "timed": telemetry["backend_compiles"],
                "warmup": warmup_compiles,
            },
            parity=out["parity"],
        ))
        # flight recorder (ISSUE 16): regenerate the run-over-run
        # trace summary mechanically from the last two bench records —
        # benchmarks/trace_summary_r<N>.md is `peasoup-tpu obs diff`
        # output, never hand-written prose
        try:
            from peasoup_tpu.obs.diff import (
                diff_bench_records,
                write_trace_summary,
            )
            from peasoup_tpu.obs.history import load_history

            recs = [r for r in load_history(kinds=["bench"])
                    if r.get("stage_device_s")]
            if len(recs) >= 2:
                spath = os.path.join(
                    os.path.dirname(os.path.abspath(__file__)),
                    "benchmarks", f"trace_summary_r{len(recs)}.md")
                write_trace_summary(
                    spath, diff_bench_records(
                        recs[-2], recs[-1],
                        label_a=recs[-2].get("ts", "previous"),
                        label_b=recs[-1].get("ts", "latest")))
                print(f"wrote {spath}", file=sys.stderr)
        except Exception as exc:  # a diff must never fail the bench
            print(f"trace summary skipped: {exc!r}", file=sys.stderr)
    if "--gate" in sys.argv[1:]:
        from peasoup_tpu.tools.perf_report import main as gate_main

        sys.exit(gate_main(["--gate"]))


if __name__ == "__main__":
    main()
