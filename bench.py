"""Benchmark: full DM x accel search on the reference's tutorial.fil.

Prints ONE JSON line {metric, value, unit, vs_baseline}.  The baseline
is the reference's recorded end-to-end wall-clock of 0.770 s on its
2014-era GPU(s) (`example_output/overview.xml` <execution_times><total>,
see BASELINE.md).  ``vs_baseline`` is the speedup factor
(baseline_seconds / our_seconds; >1 means we beat the reference).

The run reproduces the golden search exactly (dm 0-250 tol 1.10,
accel -5..+5 over the 3-trial grid, 4 harmonic sums, min_snr 9,
npdmp 10) and asserts candidate parity before reporting a number, so
the metric can't be gamed by returning garbage fast.
"""

from __future__ import annotations

import json
import os
import sys
import time

BASELINE_TOTAL_S = 0.769960045814514  # example_output/overview.xml <total>
TUTORIAL = "/root/reference/example_data/tutorial.fil"


def main() -> None:
    from peasoup_tpu.io import read_filterbank
    from peasoup_tpu.parallel.mesh import MeshPulsarSearch
    from peasoup_tpu.search.plan import SearchConfig

    if not os.path.exists(TUTORIAL):
        print(json.dumps({
            "metric": "tutorial_fil_e2e_wallclock", "value": None,
            "unit": "s", "vs_baseline": None,
            "error": "tutorial.fil not found",
        }))
        return

    fil = read_filterbank(TUTORIAL)
    cfg = SearchConfig(
        dm_start=0.0, dm_end=250.0, acc_start=-5.0, acc_end=5.0,
        acc_pulse_width=64000.0, nharmonics=4, npdmp=10, limit=1000,
    )

    # Warm-up run on the same search object: XLA compilation is cached
    # per-process and the static inputs (filterbank bytes, delay table,
    # accel grid) stay device-resident, mirroring how the reference's
    # 0.770 s excludes CUDA context/module setup and counts file
    # reading separately.
    search = MeshPulsarSearch(fil, cfg)
    search.run()

    t0 = time.time()
    result = search.run()
    elapsed = time.time() - t0

    # Parity gate: the golden fundamental family must be recovered.
    top = result.candidates[0]
    period = 1.0 / top.freq
    ok = (
        len(result.dm_list) == 59
        and len(result.candidates) >= 10
        and abs(period - 0.24994) / 0.24994 < 1e-3
        and abs(top.snr - 86.9626) / 86.9626 < 5e-3
    )
    if not ok:
        print(json.dumps({
            "metric": "tutorial_fil_e2e_wallclock", "value": elapsed,
            "unit": "s", "vs_baseline": None,
            "error": "candidate parity check failed",
        }))
        sys.exit(1)

    print(json.dumps({
        "metric": "tutorial_fil_e2e_wallclock",
        "value": round(elapsed, 4),
        "unit": "s",
        "vs_baseline": round(BASELINE_TOTAL_S / elapsed, 3),
    }))


if __name__ == "__main__":
    main()
