"""Concurrency analyses: lock discipline (PSL010), lock order (PSL011).

The serve and obs planes run ~10 thread entry points (lease
heartbeats, telemetry samplers, observation prefetchers, timeout
workers) against state also touched by the main thread.  These two
rules are the lint engine's Eraser-flavoured lockset pass over that
surface — static, conservative, and tuned to this repo's sanctioned
patterns (CONTRIBUTING.md "Adding a thread entry point"):

**PSL010 — lock discipline.**  Per class that *creates* threads
(``threading.Thread(target=...)`` or an ``Event.wait``-loop daemon
method), compute the set of methods reachable from each thread entry
(intra-class ``self.m()`` fixpoint) and the ``self._x`` attributes
each side reads/writes.  An attribute written on one side and
accessed on the other must share a lock across *all* those accesses.
The lockset of an access is its lexical ``with self._lock:`` nesting
**plus the method's entry lockset**: a private (``_``-prefixed)
method inherits the intersection of locks held at every intra-class
call site — so ``TelemetrySampler._append``, lexically lock-free but
only ever called under ``sample_now``'s ``with self._lock:``, is
correctly seen as guarded.  Recognized-safe and therefore exempt:
``threading.Event`` attributes (its wait/set handshake is the
synchronization), ``queue.Queue``/``deque`` handoffs, lock objects
themselves, ``threading.Thread`` handles, and attributes whose only
out-of-thread write is in ``__init__`` (the read-only-after-
``start()`` pattern — construction happens-before the thread).
Classes that never create a thread (``EventLog``, ``Tracer``,
``DispatchPipeline``) are skipped entirely: their "caller holds the
lock" helpers are single-threaded contracts, not data races.

**PSL011 — lock order.**  A whole-program rule (the engine hands it
every file at once): every ``threading.Lock``/``RLock`` — module
global or ``self._x`` instance attribute — becomes a node; acquiring
``B`` while holding ``A`` (lexically nested ``with``, or a ``with``
body calling a function that acquires, transitively, across modules
via import resolution) adds edge ``A -> B``.  A cycle is a potential
deadlock; the finding prints the offending chain.  Instance locks are
keyed per *class*, the usual lockset abstraction: two instances of
one class share a node, so an ``A -> B -> A`` report may be a
self-deadlock or a cross-instance inversion — either deserves the
failure.

Both rules are best-effort by construction (dynamic dispatch,
``getattr``, cross-class aliasing are out of reach), so they are
tuned to report only what they can witness in the AST — every finding
carries the witnessing chain or access pair.
"""

from __future__ import annotations

import ast

from .engine import SourceFile
from .rules import Rule, _dotted

#: constructors classifying a ``self._x = ...`` attribute
_LOCK_CTORS = {"threading.Lock", "threading.RLock", "Lock", "RLock"}
_EVENT_CTORS = {"threading.Event", "Event"}
_QUEUE_CTORS = {"queue.Queue", "queue.SimpleQueue", "Queue",
                "SimpleQueue", "collections.deque", "deque"}
_THREAD_CTORS = {"threading.Thread", "Thread"}

#: method calls that mutate a container in place — a write for
#: lock-discipline purposes
_MUTATORS = {"append", "extend", "add", "update", "pop", "popitem",
             "clear", "setdefault", "remove", "discard", "insert",
             "appendleft", "popleft", "sort"}


def _self_attr(node: ast.AST) -> str | None:
    """``'_x'`` for ``self._x`` attribute nodes, else None."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _ctor_of(call: ast.AST) -> str:
    return _dotted(call.func) if isinstance(call, ast.Call) else ""


class _Access:
    """One attribute access: read or write, with its lockset."""

    __slots__ = ("attr", "write", "locks", "node", "method")

    def __init__(self, attr, write, locks, node, method):
        self.attr = attr
        self.write = write
        self.locks = locks
        self.node = node
        self.method = method


class _ClassModel:
    """Everything PSL010 needs about one class."""

    def __init__(self, cdef: ast.ClassDef, module_locks: set[str]):
        self.cdef = cdef
        self.module_locks = module_locks
        self.methods: dict[str, ast.AST] = {}
        for item in cdef.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.methods[item.name] = item
        self.lock_attrs: set[str] = set()
        self.exempt_attrs: set[str] = set()
        self._classify_attrs()
        #: thread entries: method names + nested thread-target defs
        self.entry_methods: set[str] = set()
        self.entry_funcs: list[tuple[str, ast.AST]] = []
        self._find_entries()
        #: per-method accesses and intra-class call sites
        self.accesses: dict[str, list[_Access]] = {}
        self.calls: dict[str, list[tuple[str, frozenset]]] = {}
        self._thread_target_defs = {id(n) for _, n in self.entry_funcs}
        for name, node in self.methods.items():
            acc: list[_Access] = []
            sites: list[tuple[str, frozenset]] = []
            self._walk(node, frozenset(), name, acc, sites, skip_def=node)
            self.accesses[name] = acc
            self.calls[name] = sites

    # -- attribute classification ------------------------------------------

    def _classify_attrs(self) -> None:
        for node in ast.walk(self.cdef):
            if not isinstance(node, ast.Assign):
                continue
            ctor = _ctor_of(node.value)
            if not ctor:
                continue
            for tgt in node.targets:
                attr = _self_attr(tgt)
                if attr is None:
                    continue
                if ctor in _LOCK_CTORS:
                    self.lock_attrs.add(attr)
                    self.exempt_attrs.add(attr)
                elif ctor in _EVENT_CTORS | _QUEUE_CTORS | _THREAD_CTORS:
                    self.exempt_attrs.add(attr)

    # -- thread-entry discovery --------------------------------------------

    def _find_entries(self) -> None:
        for mname, mnode in self.methods.items():
            nested = {
                n.name: n for n in ast.walk(mnode)
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                and n is not mnode
            }
            for node in ast.walk(mnode):
                if (isinstance(node, ast.Call)
                        and _ctor_of(node) in _THREAD_CTORS):
                    for kw in node.keywords:
                        if kw.arg != "target":
                            continue
                        attr = _self_attr(kw.value)
                        if attr is not None and attr in self.methods:
                            self.entry_methods.add(attr)
                        elif (isinstance(kw.value, ast.Name)
                                and kw.value.id in nested):
                            self.entry_funcs.append(
                                (f"{mname}.<{kw.value.id}>",
                                 nested[kw.value.id]))
            # Event.wait-loop daemon: while ... self._ev.wait(...)
            for node in ast.walk(mnode):
                if not isinstance(node, ast.While):
                    continue
                for sub in ast.walk(node.test):
                    if (isinstance(sub, ast.Call)
                            and isinstance(sub.func, ast.Attribute)
                            and sub.func.attr == "wait"
                            and _self_attr(sub.func.value) is not None):
                        self.entry_methods.add(mname)

    @property
    def is_threaded(self) -> bool:
        return bool(self.entry_methods or self.entry_funcs)

    # -- access/lockset walker ---------------------------------------------

    def _lock_name(self, expr: ast.AST) -> str | None:
        attr = _self_attr(expr)
        if attr is not None and attr in self.lock_attrs:
            return attr
        if isinstance(expr, ast.Name) and expr.id in self.module_locks:
            return f"::{expr.id}"
        return None

    def _walk(self, node, held, method, acc, sites, skip_def=None):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node is not skip_def:
                # nested def: runs later, outside this lock scope; a
                # nested thread target is walked as its own entry
                if id(node) in self._thread_target_defs:
                    return
                held = frozenset()
        elif isinstance(node, ast.Lambda):
            held = frozenset()
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            inner = set(held)
            for item in node.items:
                name = self._lock_name(item.context_expr)
                if name is not None:
                    inner.add(name)
                self._walk(item.context_expr, held, method, acc, sites)
            inner = frozenset(inner)
            for stmt in node.body:
                self._walk(stmt, inner, method, acc, sites)
            return
        elif isinstance(node, ast.Call):
            attr = _self_attr(node.func)
            if attr is not None and attr in self.methods:
                sites.append((attr, held))
            if isinstance(node.func, ast.Attribute):
                owner = _self_attr(node.func.value)
                if (owner is not None and node.func.attr in _MUTATORS
                        and self._tracked(owner)):
                    acc.append(_Access(owner, True, held, node, method))
        elif (isinstance(node, (ast.Subscript,))
                and isinstance(node.ctx, (ast.Store, ast.Del))):
            owner = _self_attr(node.value)
            if owner is not None and self._tracked(owner):
                acc.append(_Access(owner, True, held, node, method))
        elif isinstance(node, ast.Attribute):
            attr = _self_attr(node)
            if attr is not None and self._tracked(attr):
                write = isinstance(node.ctx, (ast.Store, ast.Del))
                acc.append(_Access(attr, write, held, node, method))
        for child in ast.iter_child_nodes(node):
            self._walk(child, held, method, acc, sites)

    def _tracked(self, attr: str) -> bool:
        return (attr not in self.exempt_attrs
                and attr not in self.methods)

    # -- reachability + entry locksets -------------------------------------

    def thread_reachable(self) -> set[str]:
        seen = set(self.entry_methods)
        frontier = list(seen)
        while frontier:
            m = frontier.pop()
            for callee, _held in self.calls.get(m, ()):
                if callee not in seen:
                    seen.add(callee)
                    frontier.append(callee)
        return seen

    def entry_locksets(self) -> dict[str, frozenset]:
        """Per-method lockset guaranteed held on entry.  Public
        methods, thread entries, and methods with no intra-class call
        site get the empty set (callers are unconstrained); private
        methods get the intersection over every call site of (locks
        lexically held there + the caller's own entry lockset),
        iterated to a fixpoint."""
        callsites: dict[str, list[tuple[str, frozenset]]] = {}
        for caller, sites in self.calls.items():
            for callee, held in sites:
                callsites.setdefault(callee, []).append((caller, held))
        all_locks = frozenset(self.lock_attrs)
        constrained = {
            name for name in self.methods
            if name.startswith("_") and not name.startswith("__")
            and name not in self.entry_methods and callsites.get(name)
        }
        entry: dict[str, frozenset] = {
            name: (all_locks if name in constrained else frozenset())
            for name in self.methods
        }
        for _ in range(len(self.methods) + 1):
            changed = False
            for name in constrained:
                new: frozenset | None = None
                for caller, held in callsites[name]:
                    site = held | entry.get(caller, frozenset())
                    new = site if new is None else (new & site)
                new = new if new is not None else frozenset()
                if new != entry[name]:
                    entry[name] = new
                    changed = True
            if not changed:
                break
        return entry


class LockDisciplineRule(Rule):
    """Attributes shared between a thread target's reach and the main
    side must have a common lock over every conflicting access (see
    module docstring for the full lattice of exemptions)."""

    id = "PSL010"
    title = "shared attribute lacks a common lock"

    def run(self, sf: SourceFile):
        module_locks = {
            tgt.id
            for node in sf.tree.body if isinstance(node, ast.Assign)
            if _ctor_of(node.value) in _LOCK_CTORS
            for tgt in node.targets if isinstance(tgt, ast.Name)
        }
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ClassDef):
                yield from self._check_class(sf, node, module_locks)

    def _check_class(self, sf, cdef, module_locks):
        model = _ClassModel(cdef, module_locks)
        if not model.is_threaded:
            return
        entry = model.entry_locksets()
        reach = model.thread_reachable()
        thread_acc: dict[str, list[_Access]] = {}
        main_acc: dict[str, list[_Access]] = {}

        def add(table, access, extra):
            a = _Access(access.attr, access.write,
                        access.locks | extra, access.node, access.method)
            table.setdefault(a.attr, []).append(a)

        for name, accs in model.accesses.items():
            extra = entry.get(name, frozenset())
            on_thread = name in reach
            # a public thread-reachable non-entry method is also
            # externally callable -> both sides (sample_now pattern)
            on_main = (not on_thread) or (
                not name.startswith("_")
                and name not in model.entry_methods)
            if name == "__init__":
                on_main = False  # happens-before thread start
            for a in accs:
                if on_thread:
                    add(thread_acc, a, extra)
                if on_main:
                    add(main_acc, a, extra)
        for _fname, fnode in model.entry_funcs:
            accs: list[_Access] = []
            sites: list = []
            model._walk(fnode, frozenset(), _fname, accs, sites,
                        skip_def=fnode)
            for a in accs:
                add(thread_acc, a, frozenset())

        for attr in sorted(set(thread_acc) | set(main_acc)):
            t_side = thread_acc.get(attr, [])
            m_side = main_acc.get(attr, [])
            if not t_side or not m_side:
                continue
            if not (any(a.write for a in t_side)
                    or any(a.write for a in m_side)):
                continue  # read-only on both sides
            common = None
            for a in t_side + m_side:
                common = (a.locks if common is None
                          else common & a.locks)
            if common:
                continue
            bad = next((a for a in t_side + m_side
                        if a.write and not a.locks),
                       next(a for a in t_side + m_side if a.write))
            t_where = sorted({a.method for a in t_side})
            m_where = sorted({a.method for a in m_side})
            yield sf.violation(
                self.id, bad.node,
                f"class {cdef.name}: self.{attr} is written without a "
                f"common lock — thread side {t_where} vs main side "
                f"{m_where}; guard every access with the same "
                f"'with self._lock:', hand off via queue/Event, or "
                f"make it read-only after start()")


# --------------------------------------------------------------------------
# PSL011 — lock-order cycles
# --------------------------------------------------------------------------

def _module_name(relpath: str) -> str:
    name = relpath[:-3] if relpath.endswith(".py") else relpath
    name = name.replace("/", ".")
    for prefix in ("peasoup_tpu.",):
        if name.startswith(prefix):
            name = name[len(prefix):]
    return name


class _ModuleFacts:
    """Per-file lock/function/import inventory for PSL011."""

    def __init__(self, sf: SourceFile):
        self.sf = sf
        self.mod = _module_name(sf.relpath)
        #: lock node id -> True; module locks are "mod:NAME",
        #: instance locks "mod:Class.ATTR"
        self.locks: set[str] = set()
        #: function qualname ("f" or "C.m") -> node
        self.funcs: dict[str, ast.AST] = {}
        #: class name -> {lock attr names}
        self.class_locks: dict[str, set[str]] = {}
        #: module-global name -> class name (X = C() singletons)
        self.instance_of: dict[str, str] = {}
        #: imported name -> ("func"|"module", target module name, attr)
        self.imports: dict[str, tuple[str, str]] = {}
        self._scan()

    def _scan(self) -> None:
        tree = self.sf.tree
        for node in tree.body:
            if isinstance(node, ast.Assign):
                ctor = _ctor_of(node.value)
                for tgt in node.targets:
                    if not isinstance(tgt, ast.Name):
                        continue
                    if ctor in _LOCK_CTORS:
                        self.locks.add(f"{self.mod}:{tgt.id}")
                    elif ctor:
                        self.instance_of[tgt.id] = ctor.split(".")[-1]
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                self.funcs[node.name] = node
            elif isinstance(node, ast.ClassDef):
                attrs: set[str] = set()
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Assign) \
                            and _ctor_of(sub.value) in _LOCK_CTORS:
                        for tgt in sub.targets:
                            a = _self_attr(tgt)
                            if a is not None:
                                attrs.add(a)
                if attrs:
                    self.class_locks[node.name] = attrs
                    for a in attrs:
                        self.locks.add(f"{self.mod}:{node.name}.{a}")
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        self.funcs[f"{node.name}.{item.name}"] = item
            elif isinstance(node, ast.ImportFrom) and node.level >= 0:
                target = self._resolve_from(node)
                if target is None:
                    continue
                for alias in node.names:
                    key = alias.asname or alias.name
                    if node.module is None:
                        # ``from . import mod`` binds a module name
                        sub = (f"{target}.{alias.name}" if target
                               else alias.name)
                        self.imports[key] = (sub, "")
                    else:
                        self.imports[key] = (target, alias.name)
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name.startswith("peasoup_tpu."):
                        mod = alias.name[len("peasoup_tpu."):]
                        self.imports[alias.asname
                                     or alias.name.split(".")[-1]] = \
                            (mod, "")

    def _resolve_from(self, node: ast.ImportFrom) -> str | None:
        """Target module name (package-relative) of a from-import."""
        if node.level == 0:
            if node.module and node.module.startswith("peasoup_tpu"):
                rest = node.module[len("peasoup_tpu"):].lstrip(".")
                return rest or ""
            return None
        parts = self.mod.split(".")
        base = parts[:len(parts) - node.level]
        if node.module:
            base.append(node.module)
        return ".".join(base)


class LockOrderRule(Rule):
    """Global lock-acquisition order must be acyclic; a cycle in the
    acquired-while-holding graph is a potential deadlock."""

    id = "PSL011"
    title = "lock-order cycle (potential deadlock)"
    whole_program = True

    def run(self, sf):  # pragma: no cover - engine uses run_program
        return iter(())

    def run_program(self, sfs):
        facts = {f.mod: f for f in (_ModuleFacts(sf) for sf in sfs)}
        # per-function direct acquisitions / edges / call sites
        direct_acq: dict[tuple, set[str]] = {}
        edges: dict[str, dict[str, tuple]] = {}
        calls: dict[tuple, list[tuple]] = {}

        for mf in facts.values():
            for qual, node in mf.funcs.items():
                key = (mf.mod, qual)
                acq: set[str] = set()
                sites: list[tuple] = []
                self._walk(mf, facts, qual, node, frozenset(), acq,
                           sites, edges)
                direct_acq[key] = acq
                calls[key] = sites

        # transitive acquires per function (fixpoint)
        trans = {k: set(v) for k, v in direct_acq.items()}
        changed = True
        while changed:
            changed = False
            for key, sites in calls.items():
                for callee, _held, _node, _mf in sites:
                    extra = trans.get(callee)
                    if extra and not extra <= trans[key]:
                        trans[key] |= extra
                        changed = True
        # cross-call edges: held locks at a call site order-before
        # everything the callee (transitively) acquires
        for key, sites in calls.items():
            for callee, held, node, mf in sites:
                for a in held:
                    for b in trans.get(callee, ()):
                        if a != b:
                            edges.setdefault(a, {}).setdefault(
                                b, (mf.sf, node))

        yield from self._find_cycles(edges)

    # -- traversal ----------------------------------------------------------

    def _lock_id(self, mf, facts, qual, expr) -> str | None:
        attr = _self_attr(expr)
        if attr is not None and "." in qual:
            cls = qual.split(".")[0]
            if attr in mf.class_locks.get(cls, ()):
                return f"{mf.mod}:{cls}.{attr}"
            return None
        if isinstance(expr, ast.Name):
            if f"{mf.mod}:{expr.id}" in mf.locks:
                return f"{mf.mod}:{expr.id}"
            imp = mf.imports.get(expr.id)
            if imp:
                tmod, tname = imp
                tf = facts.get(tmod)
                if tf and f"{tmod}:{tname}" in tf.locks:
                    return f"{tmod}:{tname}"
            return None
        if isinstance(expr, ast.Attribute):
            # _SINGLETON._lock / mod.GLOBAL_LOCK
            if isinstance(expr.value, ast.Name):
                owner = expr.value.id
                cls = mf.instance_of.get(owner)
                if cls and expr.attr in mf.class_locks.get(cls, ()):
                    return f"{mf.mod}:{cls}.{expr.attr}"
                imp = mf.imports.get(owner)
                if imp and not imp[1]:  # owner names a module
                    tf = facts.get(imp[0])
                    if tf and f"{imp[0]}:{expr.attr}" in tf.locks:
                        return f"{imp[0]}:{expr.attr}"
        return None

    def _resolve_call(self, mf, facts, qual, func) -> tuple | None:
        """(module, qualname) of a statically resolvable callee."""
        attr = _self_attr(func)
        if attr is not None and "." in qual:
            cls = qual.split(".")[0]
            if f"{cls}.{attr}" in mf.funcs:
                return (mf.mod, f"{cls}.{attr}")
            return None
        if isinstance(func, ast.Name):
            if func.id in mf.funcs:
                return (mf.mod, func.id)
            imp = mf.imports.get(func.id)
            if imp:
                tmod, tname = imp
                tf = facts.get(tmod)
                if tf and tname in tf.funcs:
                    return (tmod, tname)
            return None
        if isinstance(func, ast.Attribute) \
                and isinstance(func.value, ast.Name):
            owner = func.value.id
            cls = mf.instance_of.get(owner)
            if cls and f"{cls}.{func.attr}" in mf.funcs:
                return (mf.mod, f"{cls}.{func.attr}")
            imp = mf.imports.get(owner)
            if imp and not imp[1]:
                tf = facts.get(imp[0])
                if tf and func.attr in tf.funcs:
                    return (imp[0], func.attr)
        return None

    def _walk(self, mf, facts, qual, node, held, acq, sites, edges,
              root=None):
        if root is None:
            root = node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node is not root:
            # nested def: runs later (often on another thread); its
            # acquisitions are not ordered under the enclosing locks
            # and must not count as this function's acquires
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = set(held)
            for item in node.items:
                lock = self._lock_id(mf, facts, qual, item.context_expr)
                if lock is not None:
                    acq.add(lock)
                    for h in held:
                        if h != lock:
                            edges.setdefault(h, {}).setdefault(
                                lock, (mf.sf, node))
                    inner.add(lock)
            inner = frozenset(inner)
            for stmt in node.body:
                self._walk(mf, facts, qual, stmt, inner, acq, sites,
                           edges, root)
            return
        if isinstance(node, ast.Call):
            callee = self._resolve_call(mf, facts, qual, node.func)
            if callee is not None:
                sites.append((callee, held, node, mf))
        for child in ast.iter_child_nodes(node):
            self._walk(mf, facts, qual, child, held, acq, sites,
                       edges, root)

    # -- cycle detection -----------------------------------------------------

    def _find_cycles(self, edges):
        seen_cycles: set[frozenset] = set()
        WHITE, GREY, BLACK = 0, 1, 2
        color = {n: WHITE for n in edges}

        def dfs(n, stack):
            color[n] = GREY
            stack.append(n)
            for m, witness in sorted(edges.get(n, {}).items()):
                if color.get(m, WHITE) == GREY:
                    cycle = stack[stack.index(m):] + [m]
                    key = frozenset(cycle)
                    if key not in seen_cycles:
                        seen_cycles.add(key)
                        yield cycle, witness
                elif color.get(m, WHITE) == WHITE and m in edges:
                    yield from dfs(m, stack)
            stack.pop()
            color[n] = BLACK

        for n in sorted(edges):
            if color.get(n, WHITE) == WHITE:
                for cycle, (sf, node) in dfs(n, []):
                    chain = " -> ".join(cycle)
                    yield sf.violation(
                        self.id, node,
                        f"lock-order cycle: {chain}; every code path "
                        f"must acquire these locks in one global "
                        f"order (or drop the nesting)")
