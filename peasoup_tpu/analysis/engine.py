"""AST rule engine: source loading, suppressions, baseline, runner.

The engine is rule-agnostic: a rule is any object with an ``id`` (the
``PSL0xx`` code), a ``title``, an ``applies(relpath)`` predicate and a
``run(sf)`` generator yielding :class:`Violation`.  The concrete TPU
rules live in :mod:`peasoup_tpu.analysis.rules`.

Suppressions
------------

A violation is suppressed by a ``psl`` pragma comment on the flagged
line (or on the line of the enclosing statement for multi-line
expressions)::

    x = float(count)  # psl: disable=PSL002 -- static shape probe

File-wide suppression (use sparingly; prefer line pragmas)::

    # psl: disable-file=PSL003 -- emulated-f64 legacy resample path

Several IDs may be given comma-separated, and everything after ``--``
is a free-form reason (required by convention, not enforced by the
parser).

Baseline
--------

``lint_baseline.json`` (repo root) grandfathers pre-existing
violations so new rules can land strict without a flag-day fixup of
every historical site.  Entries are keyed by (rule, path, source
snippet) — deliberately *line-number free*, so unrelated edits in the
same file do not churn the baseline.  An entry whose violation has
been fixed is reported as *expired* and removed on the next
``--write-baseline``.
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field


@dataclass(frozen=True)
class Violation:
    """One finding: ``rule`` is the PSL id, ``path`` the repo-relative
    posix path, ``snippet`` the stripped source line (the stable part
    of the baseline key)."""

    rule: str
    path: str
    line: int
    message: str
    snippet: str = ""

    def key(self) -> tuple[str, str, str]:
        return (self.rule, self.path, self.snippet)

    def format(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"

    def to_json(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "message": self.message,
            "snippet": self.snippet,
        }


_PRAGMA = re.compile(
    r"#\s*psl:\s*(disable(?:-file)?)\s*=\s*"
    r"(?P<ids>[A-Za-z0-9*]+(?:\s*,\s*[A-Za-z0-9*]+)*)"
    r"(?:\s*--\s*(?P<reason>.*))?"
)


@dataclass
class SourceFile:
    """A parsed source file plus its suppression pragmas."""

    path: str       # absolute
    relpath: str    # repo-relative, posix separators
    source: str
    tree: ast.AST
    lines: list[str] = field(default_factory=list)
    line_disables: dict[int, set[str]] = field(default_factory=dict)
    file_disables: set[str] = field(default_factory=set)
    _parents: dict | None = field(default=None, repr=False)

    @classmethod
    def load(cls, path: str, relpath: str) -> "SourceFile":
        with open(path, encoding="utf-8") as f:
            source = f.read()
        tree = ast.parse(source, filename=path)
        sf = cls(path=path, relpath=relpath, source=source, tree=tree,
                 lines=source.splitlines())
        for lineno, line in enumerate(sf.lines, start=1):
            if "psl:" not in line:
                continue
            m = _PRAGMA.search(line)
            if not m:
                continue
            ids = {s.strip().upper() for s in m.group("ids").split(",")}
            if m.group(1) == "disable-file":
                sf.file_disables |= ids
            else:
                sf.line_disables.setdefault(lineno, set()).update(ids)
        return sf

    def parents(self) -> dict:
        """Child -> parent map over the whole tree, built once per
        file and shared by every rule that needs enclosing-scope
        context (the 13-rule run must parse AND walk each file once,
        not once per rule)."""
        if self._parents is None:
            parents: dict = {}
            for node in ast.walk(self.tree):
                for child in ast.iter_child_nodes(node):
                    parents[child] = node
            self._parents = parents
        return self._parents

    def snippet_at(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    def is_suppressed(self, rule_id: str, line: int,
                      end_line: int | None = None) -> bool:
        """True if ``rule_id`` is disabled on any line of the flagged
        statement's span (so the pragma may sit on the opening or the
        closing line of a multi-line call)."""
        if rule_id in self.file_disables or "*" in self.file_disables:
            return True
        for ln in range(line, (end_line or line) + 1):
            ids = self.line_disables.get(ln)
            if ids and (rule_id in ids or "*" in ids):
                return True
        return False

    def violation(self, rule_id: str, node: ast.AST, message: str
                  ) -> Violation:
        line = getattr(node, "lineno", 1)
        return Violation(
            rule=rule_id, path=self.relpath, line=line, message=message,
            snippet=self.snippet_at(line),
        )


#: per-process parse cache: abs path -> (mtime_ns, size, SourceFile).
#: Repeated ``run_rules`` calls (the CLI after a test run, per-file
#: gates in tests, ``bench.py --lint``) reuse the parsed tree as long
#: as the file on disk is unchanged; a stat is the only cost.
_SF_CACHE: dict[str, tuple[int, int, "SourceFile"]] = {}


def load_source_file(path: str, relpath: str) -> "SourceFile":
    """Cached :meth:`SourceFile.load` keyed by (mtime_ns, size)."""
    st = os.stat(path)
    hit = _SF_CACHE.get(path)
    if (hit is not None and hit[0] == st.st_mtime_ns
            and hit[1] == st.st_size and hit[2].relpath == relpath):
        return hit[2]
    sf = SourceFile.load(path, relpath)
    _SF_CACHE[path] = (st.st_mtime_ns, st.st_size, sf)
    return sf


def package_root() -> str:
    """Absolute path of the installed ``peasoup_tpu`` package."""
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def repo_root() -> str:
    """The directory holding the package (where the baseline lives)."""
    return os.path.dirname(package_root())


def iter_source_files(paths: list[str] | None = None,
                      root: str | None = None):
    """Yield :class:`SourceFile` for every ``.py`` under ``paths``
    (default: the ``peasoup_tpu`` package).  Files that fail to parse
    are yielded as ``(path, exception)`` tuples so the caller can
    report rather than crash."""
    root = root or repo_root()
    if not paths:
        # the package plus the repo-root bench entry points: PSL007
        # (cost-model authority) polices FLOP/byte constants in bench
        # code too, and rules path-filter themselves so the package
        # -only rules simply skip these files
        paths = [package_root()]
        for extra in ("bench.py", "benchmarks"):
            p = os.path.join(repo_root(), extra)
            if os.path.exists(p):
                paths.append(p)
    seen: set[str] = set()
    for p in paths:
        p = os.path.abspath(p)
        if os.path.isfile(p):
            files = [p]
        else:
            files = sorted(
                os.path.join(dirpath, name)
                for dirpath, dirnames, names in os.walk(p)
                for name in names
                if name.endswith(".py")
                and "__pycache__" not in dirpath
            )
        for fp in files:
            if fp in seen:
                continue
            seen.add(fp)
            rel = os.path.relpath(fp, root).replace(os.sep, "/")
            try:
                yield load_source_file(fp, rel)
            except (SyntaxError, UnicodeDecodeError, OSError) as exc:
                yield (fp, exc)


def run_rules(rules, paths: list[str] | None = None,
              root: str | None = None):
    """Apply ``rules`` to the sources; returns
    ``(violations, suppressed, errors)`` where ``suppressed`` counts
    pragma-silenced findings and ``errors`` is a list of
    ``(path, message)`` for unparseable files.

    Rules come in two shapes: per-file rules (``run(sf)``, the PR 2
    protocol) see one file at a time; rules with a truthy
    ``whole_program`` attribute implement ``run_program(sfs)`` instead
    and see every (applicable) file at once — the lock-order analysis
    (PSL011) needs the cross-module acquisition graph.  Both yield
    :class:`Violation` and go through the same pragma filter.
    """
    violations: list[Violation] = []
    suppressed = 0
    errors: list[tuple[str, str]] = []
    sources: list[SourceFile] = []
    for sf in iter_source_files(paths, root=root):
        if isinstance(sf, tuple):
            path, exc = sf
            errors.append((path, f"{type(exc).__name__}: {exc}"))
            continue
        sources.append(sf)
    per_file = [r for r in rules
                if not getattr(r, "whole_program", False)]
    program = [r for r in rules if getattr(r, "whole_program", False)]
    for sf in sources:
        for rule in per_file:
            if not rule.applies(sf.relpath):
                continue
            for v in rule.run(sf):
                end = v.line
                # widen the pragma window to the statement the engine
                # reported (ast end_lineno travels on the node; the
                # rule already folded it into the Violation line when
                # it mattered) — a trailing pragma on the same line is
                # the common case either way
                if sf.is_suppressed(v.rule, v.line, end):
                    suppressed += 1
                else:
                    violations.append(v)
    if program:
        by_rel = {sf.relpath: sf for sf in sources}
        for rule in program:
            scoped = [sf for sf in sources if rule.applies(sf.relpath)]
            for v in rule.run_program(scoped):
                sf = by_rel.get(v.path)
                if sf is not None and sf.is_suppressed(
                        v.rule, v.line, v.line):
                    suppressed += 1
                else:
                    violations.append(v)
    violations.sort(key=lambda v: (v.path, v.line, v.rule))
    return violations, suppressed, errors


# --------------------------------------------------------------------------
# baseline
# --------------------------------------------------------------------------

class Baseline:
    """Committed grandfather list for pre-existing violations."""

    VERSION = 1

    def __init__(self, entries: list[dict] | None = None):
        self.entries = entries or []

    @staticmethod
    def _key(entry: dict) -> tuple[str, str, str]:
        return (entry["rule"], entry["path"], entry.get("snippet", ""))

    @classmethod
    def load(cls, path: str) -> "Baseline":
        if not os.path.exists(path):
            return cls()
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
        if data.get("version") != cls.VERSION:
            raise ValueError(
                f"unsupported baseline version {data.get('version')!r} "
                f"in {path} (expected {cls.VERSION})"
            )
        return cls(data.get("entries", []))

    def save(self, path: str) -> None:
        data = {
            "version": self.VERSION,
            "entries": sorted(
                self.entries,
                key=lambda e: (e["path"], e["rule"], e.get("snippet", "")),
            ),
        }
        with open(path, "w", encoding="utf-8") as f:
            json.dump(data, f, indent=2, sort_keys=True)
            f.write("\n")

    def split(self, violations: list[Violation]):
        """Partition into ``(new, grandfathered, expired_entries)``:
        findings not in the baseline, findings it covers, and entries
        whose violation no longer exists (fixed code — drop them)."""
        keys = {self._key(e) for e in self.entries}
        new = [v for v in violations if v.key() not in keys]
        old = [v for v in violations if v.key() in keys]
        live = {v.key() for v in violations}
        expired = [e for e in self.entries if self._key(e) not in live]
        return new, old, expired

    @classmethod
    def from_violations(cls, violations: list[Violation],
                        reason: str = "grandfathered") -> "Baseline":
        return cls([
            {"rule": v.rule, "path": v.path, "snippet": v.snippet,
             "reason": reason}
            for v in violations
        ])
