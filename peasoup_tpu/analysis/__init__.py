"""peasoup-lint: static analysis for the TPU search pipeline.

Two complementary layers keep the pipeline's TPU invariants enforced
on every PR (the generalisation of PR 1's one-off no-bare-warnings
test):

* an AST rule engine (:mod:`.engine`, :mod:`.rules`) that walks the
  package sources and flags the Python-level mistakes that silently
  cost a device->host stall or a recompile per DM trial — bare
  ``warnings.warn`` bypassing telemetry (PSL001), host syncs inside
  jitted programs (PSL002), device float64 leaks under ``ops/``
  (PSL003), Python branching on traced values (PSL004) and untyped
  ``ValueError``/``RuntimeError`` raises in the drivers (PSL005) —
  plus, since ISSUE 17, the concurrency-and-contracts prover
  (:mod:`.concurrency`, :mod:`.contracts`): Eraser-style lock
  discipline over every thread entry point (PSL010), lock-order
  cycle detection across modules (PSL011, the engine's first
  whole-program rule), atomic-write discipline for serve/obs
  artifacts (PSL012) and artifact-stream schema contracts against
  ``obs/streams.py`` (PSL013);
* a jaxpr-level checker (:mod:`.jaxpr_check`) that traces the five
  registered pipeline programs (dedisperse, spectrum, harmonics,
  peaks, fold) at representative shapes and asserts no f64
  intermediates (outside documented allowances), no host-callback or
  transfer primitives, and a bounded distinct-compiled-signature
  count via the compile tracking in ``obs/metrics.py``.

Run ``python -m peasoup_tpu.analysis`` (or ``make lint``); see the
README's "Static analysis" section for rule IDs, the
``# psl: disable=PSL0xx`` suppression syntax and the committed
baseline (``lint_baseline.json``) for grandfathered violations.
"""

from .engine import (  # noqa: F401
    Baseline,
    SourceFile,
    Violation,
    iter_source_files,
    run_rules,
)
from .rules import ALL_RULES, rules_by_id  # noqa: F401
from .concurrency import (  # noqa: F401
    LockDisciplineRule,
    LockOrderRule,
)
from .contracts import (  # noqa: F401
    AtomicWriteRule,
    StreamContractRule,
)
from .jaxpr_check import (  # noqa: F401
    JaxprFinding,
    ProgramSpec,
    check_program,
    check_registered_programs,
    registered_programs,
)
