"""Jaxpr-level invariant checks over the registered pipeline programs.

The AST rules catch what the *source* says; this module checks what the
traced programs actually *contain*.  Each registered program —
dedisperse, spectrum (the whitening chain), harmonics, peaks, fold —
is traced with :func:`jax.make_jaxpr` at a small representative shape
and its (recursively flattened) equations are checked for:

* **f64/complex128 intermediates** — software-emulated on TPU; a leak
  multiplies the op's cost silently.  The fold program's phase/index
  math is *deliberately* f64 (reference-exact ``__double2int_rd``
  semantics, see ``ops/fold.py:phase_bins``) and carries a documented
  allowance; everything else must be clean.
* **host-callback / transfer primitives** — ``pure_callback``,
  ``io_callback``, ``infeed``/``outfeed``, ``device_put`` and friends
  inside a jitted program mean a host round-trip per call.
* **compiled-signature stability** — each program is executed twice at
  identical shapes through a jitted entry; a second compile on the
  repeat call means the signature churns (weak types, python scalars
  re-hashing) and a production run would recompile per DM trial.  The
  per-program counts are additionally read through the PR-1 compile
  tracking (``obs.metrics.jit_program_cache_sizes``) and bounded.

Everything here is lazy: jax is imported only when a check runs, so
``import peasoup_tpu.analysis`` stays cheap for the AST-only path.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import Callable

#: primitives that imply a host round-trip inside a device program.
#: Any primitive whose name contains "callback" is also rejected.
HOST_PRIMITIVES = frozenset({
    "pure_callback", "io_callback", "outside_call", "host_callback",
    "infeed", "outfeed", "device_put", "copy_to_host_async",
})

#: dtypes that are software-emulated (f64) or unsupported (c128) on TPU
_BANNED_DTYPES = frozenset({"float64", "complex128"})


@dataclass(frozen=True)
class JaxprFinding:
    program: str
    check: str      # "f64-intermediate" | "host-primitive" |
                    # "signature-churn" | "signature-bound" | "trace-error"
    detail: str

    def format(self) -> str:
        return f"jaxpr:{self.program}: {self.check}: {self.detail}"

    def to_json(self) -> dict:
        return {"program": self.program, "check": self.check,
                "detail": self.detail}


@dataclass
class ProgramSpec:
    """One registered pipeline program.

    ``build()`` returns ``(fn, args)`` — statics already bound, args
    small representative arrays — used both for :func:`jax.make_jaxpr`
    and (wrapped in ``jax.jit``) for the signature-stability check.
    """

    name: str
    build: Callable[[], tuple[Callable, tuple]]
    allow_f64: bool = False
    allow_reason: str = ""
    #: names this program populates in jit_program_cache_sizes()
    tracked_programs: tuple[str, ...] = field(default_factory=tuple)


def registered_programs() -> list[ProgramSpec]:
    """The five pipeline programs the checker runs over (ISSUE 2)."""

    def _dedisperse():
        import importlib

        import jax.numpy as jnp

        dd = importlib.import_module("peasoup_tpu.ops.dedisperse")
        data = jnp.zeros((16, 2048), jnp.float32)
        delays = jnp.zeros((4, 16), jnp.int32)
        return partial(dd.dedisperse, out_nsamps=1024), (data, delays)

    def _spectrum():
        import jax.numpy as jnp

        from ..search import pipeline as pl

        tim = jnp.zeros((2048,), jnp.float32)
        none = jnp.zeros((0,), jnp.float32)
        fn = partial(pl.whiten_core, bin_width=1.0 / 2048.0,
                     b5=0.05, b25=0.5, use_zap=False)
        return fn, (tim, none, none)

    def _harmonics():
        import jax.numpy as jnp

        from ..ops.harmonics import harmonic_sums

        spec = jnp.zeros((1025,), jnp.float32)
        return partial(harmonic_sums, nharms=4), (spec,)

    def _peaks():
        import jax.numpy as jnp

        from ..ops.peaks import extract_top_peaks

        spec = jnp.zeros((1025,), jnp.float32)
        fn = partial(extract_top_peaks, thresh=6.0, start_idx=1,
                     stop_idx=1000, capacity=32)
        return fn, (spec,)

    def _fold():
        import jax.numpy as jnp

        from ..ops.fold import fold_time_series_core, optimise_device

        def fold_and_optimise(tim):
            return optimise_device(
                fold_time_series_core(tim, 0.007, 6.4e-5, 64, 16))

        return fold_and_optimise, (jnp.zeros((16384,), jnp.float32),)

    return [
        ProgramSpec("dedisperse", _dedisperse),
        ProgramSpec("spectrum", _spectrum,
                    tracked_programs=("whiten_trial",)),
        ProgramSpec("harmonics", _harmonics),
        ProgramSpec("peaks", _peaks),
        ProgramSpec(
            "fold", _fold, allow_f64=True,
            allow_reason=(
                "reference-exact f64 phase/index math "
                "(__double2int_rd parity, ops/fold.py:phase_bins) — "
                "3 flops/element of emulated f64 by design"
            ),
        ),
    ]


# --------------------------------------------------------------------------
# jaxpr traversal
# --------------------------------------------------------------------------

def _iter_eqns(jaxpr):
    """All equations of ``jaxpr`` and every sub-jaxpr (scan/while/cond
    bodies, pjit calls), recursively.  Sub-jaxprs are discovered
    duck-typed through eqn params so the walk survives jax moving
    Jaxpr/ClosedJaxpr between modules."""
    stack = [jaxpr]
    while stack:
        jx = stack.pop()
        if hasattr(jx, "jaxpr"):  # ClosedJaxpr
            jx = jx.jaxpr
        for eqn in jx.eqns:
            yield eqn
            for val in eqn.params.values():
                vals = val if isinstance(val, (tuple, list)) else (val,)
                for sub in vals:
                    if hasattr(sub, "eqns") or hasattr(sub, "jaxpr"):
                        stack.append(sub)


def check_jaxpr(jaxpr, program: str, allow_f64: bool = False
                ) -> list[JaxprFinding]:
    """f64-intermediate + host-primitive checks on one (Closed)Jaxpr."""
    findings: list[JaxprFinding] = []
    f64_prims: dict[str, str] = {}
    for eqn in _iter_eqns(jaxpr):
        pname = eqn.primitive.name
        if pname in HOST_PRIMITIVES or "callback" in pname:
            findings.append(JaxprFinding(
                program, "host-primitive",
                f"primitive `{pname}` implies a host round-trip "
                f"inside the device program",
            ))
        if allow_f64:
            continue
        for var in eqn.outvars:
            aval = getattr(var, "aval", None)
            dtype = getattr(aval, "dtype", None)
            if dtype is not None and str(dtype) in _BANNED_DTYPES:
                f64_prims.setdefault(pname, str(dtype))
    for pname, dtype in sorted(f64_prims.items()):
        findings.append(JaxprFinding(
            program, "f64-intermediate",
            f"primitive `{pname}` produces {dtype} (software-emulated "
            f"on TPU) — keep device math f32/c64 or move it host-side",
        ))
    return findings


def check_program(spec: ProgramSpec) -> list[JaxprFinding]:
    """Trace one program and run the jaxpr checks."""
    import jax

    try:
        fn, args = spec.build()
        jaxpr = jax.make_jaxpr(fn)(*args)
    except Exception as exc:  # noqa: BLE001 - reported, not raised
        return [JaxprFinding(
            spec.name, "trace-error",
            f"{type(exc).__name__}: {str(exc).splitlines()[0]}",
        )]
    return check_jaxpr(jaxpr, spec.name, allow_f64=spec.allow_f64)


def check_signatures(specs=None, bound: int = 8) -> list[JaxprFinding]:
    """Execute each program twice at identical shapes and bound its
    distinct-compiled-signature count.

    The repeat call must be a cache hit — a second compile means the
    jitted signature is unstable (weak types, python-scalar hashing)
    and production runs would recompile per trial.  Afterwards the
    pipeline-registered programs are read through
    ``obs.metrics.jit_program_cache_sizes`` and bounded by ``bound``.
    """
    import jax

    findings: list[JaxprFinding] = []
    specs = registered_programs() if specs is None else specs
    for spec in specs:
        try:
            fn, args = spec.build()
            jfn = jax.jit(fn)
            jax.block_until_ready(jfn(*args))
            first = jfn._cache_size()
            jax.block_until_ready(jfn(*args))
            second = jfn._cache_size()
        except Exception as exc:  # noqa: BLE001 - reported, not raised
            findings.append(JaxprFinding(
                spec.name, "trace-error",
                f"{type(exc).__name__}: {str(exc).splitlines()[0]}",
            ))
            continue
        if second > first:
            findings.append(JaxprFinding(
                spec.name, "signature-churn",
                f"repeat call at identical shapes compiled a new "
                f"signature ({first} -> {second})",
            ))
        if second > bound:
            findings.append(JaxprFinding(
                spec.name, "signature-bound",
                f"{second} compiled signatures > bound {bound}",
            ))

    from ..obs.metrics import jit_program_cache_sizes

    for name, size in sorted(jit_program_cache_sizes().items()):
        if size > bound:
            findings.append(JaxprFinding(
                name, "signature-bound",
                f"jit program cache holds {size} distinct compiled "
                f"signatures > bound {bound} (recompile storm)",
            ))
    return findings


def check_registered_programs(names=None, signature_bound: int = 8,
                              signatures: bool = True
                              ) -> list[JaxprFinding]:
    """Run every jaxpr check over the registered programs; the CLI and
    ``tests/test_lint.py`` entry point."""
    specs = registered_programs()
    if names:
        wanted = set(names)
        unknown = wanted - {s.name for s in specs}
        if unknown:
            raise ValueError(f"unknown program(s): {sorted(unknown)}")
        specs = [s for s in specs if s.name in wanted]
    findings: list[JaxprFinding] = []
    for spec in specs:
        findings.extend(check_program(spec))
    if signatures:
        findings.extend(check_signatures(specs, bound=signature_bound))
    return findings
