"""Artifact contracts: atomic writes (PSL012/PSL014), stream schemas
(PSL013).

**PSL012 — atomic-write discipline.**  OBSERVABILITY.md's first
shared design rule is rename atomicity: a killed writer leaves the
old artifact or the new one, never a torn half-write.  The sanctioned
spelling is :mod:`peasoup_tpu.utils.atomicio` (tmp + ``os.replace``,
opt-in fsync), which lives *outside* the scanned packages — so inside
``serve/`` and ``obs/`` any truncating text ``open(path, "w")`` is a
violation, the same single-sanctioned-site scheme PSL008 applies to
``time.sleep``.  Append-mode JSONL streams (``"a"``: torn *tails* are
tolerated by every reader) and binary payload streaming (``"wb"``:
the injection harness's ``.fil`` writer) are exempt; this rule is
about truncate-in-place races on spool records, leases, reports,
sidecars and indexes.

**PSL014 — rename publication discipline.**  PSL012 proves nobody
truncates in place, but only for *constant text* modes — a dynamic
``mode=`` expression or a binary update mode (``"wb+"``) slips
through, and a hand-rolled ``tmp + os.replace`` inside ``serve/`` /
``obs/`` re-implements atomicio minus its unlink-on-error and opt-in
fsync (exactly the gap a killed segment/index writer would fall
into — ISSUE 20's compactor is why this rule exists).  So in the
scanned planes: ``open`` modes must be string literals and must not
be binary update modes, and ``os.replace`` / ``os.rename`` may
appear only in ``serve/queue.py`` (the spool's state machine — the
rename IS the state transition) or as the sanctioned shard-rotation
idiom ``os.replace(path, path + ".1")``.  Everything else publishes
through :mod:`peasoup_tpu.utils.atomicio`.

**PSL013 — stream contracts.**  :mod:`peasoup_tpu.obs.streams`
declares each artifact stream's schema (version, required/optional
keys) and its binding sites.  In a declared *writer* function, every
dict literal carrying the stream's version key is a record: a string
key outside the declaration is flagged (missing keys are not — many
record keys are conditional by design).  ``var["k"] = ...`` stores on
the declared record variable are held to the same contract.  In a
declared *reader*, every ``var["k"]`` / ``var.get("k")`` on the
declared variable must name a declared key — a key no writer can
produce reads as dead code or a typo (this rule found
``ingest_timeline`` polling a ``"ts"`` key timeline marks never
carry).  Module version constants bound in the catalog must equal
the declared version when written as an int literal; constants
*sourced from the catalog* are non-literal and exempt.
"""

from __future__ import annotations

import ast

from .engine import SourceFile
from .rules import Rule, _in_pkg

#: truncating text modes; "wb"/"ab"/"a"/"x" stay legal
_TRUNCATING = {"w", "wt", "tw", "w+", "+w", "wt+", "w+t"}


class AtomicWriteRule(Rule):
    """Truncating ``open(path, "w")`` in the serve/obs planes must go
    through ``peasoup_tpu.utils.atomicio`` (tmp + ``os.replace``)."""

    id = "PSL012"
    title = "raw truncating write (use utils.atomicio)"

    def applies(self, relpath: str) -> bool:
        return _in_pkg(relpath, "serve", "obs")

    def run(self, sf: SourceFile):
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            if not (isinstance(node.func, ast.Name)
                    and node.func.id == "open"):
                continue
            mode = None
            if len(node.args) >= 2:
                mode = node.args[1]
            for kw in node.keywords:
                if kw.arg == "mode":
                    mode = kw.value
            if not (isinstance(mode, ast.Constant)
                    and isinstance(mode.value, str)):
                continue
            if mode.value not in _TRUNCATING:
                continue
            yield sf.violation(
                self.id, node,
                f"open(..., {mode.value!r}) truncates in place; write "
                f"through peasoup_tpu.utils.atomicio "
                f"(atomic_write_text/json: tmp + os.replace, opt-in "
                f"fsync) so a killed writer never leaves a torn file")


# --------------------------------------------------------------------------
# PSL014 — rename publication discipline
# --------------------------------------------------------------------------

#: binary truncate-and-read-back modes: in-place update of a payload
#: file (plain ``"wb"`` payload streaming stays legal, as in PSL012)
_BINARY_UPDATE = {"wb+", "w+b", "bw+", "+wb", "b+w", "+bw"}

#: the one module whose renames ARE the product: the spool state
#: machine (a job changes state by os.rename of its record file)
_RENAME_SANCTIONED = ("serve/queue.py",)


def _is_rotation_dst(node: ast.AST) -> bool:
    """The sanctioned shard-rotation spelling: destination is
    ``<expr> + ".1"`` (telemetry/compilation/warehouse/lineage/events
    all rotate their JSONL shard this way)."""
    return (isinstance(node, ast.BinOp)
            and isinstance(node.op, ast.Add)
            and isinstance(node.right, ast.Constant)
            and node.right.value == ".1")


class RenameDisciplineRule(Rule):
    """Dynamic/binary-update ``open`` modes and hand-rolled
    ``os.replace``/``os.rename`` publication in the serve/obs planes
    must go through ``peasoup_tpu.utils.atomicio``."""

    id = "PSL014"
    title = "non-atomicio rename publication / unprovable open mode"

    def applies(self, relpath: str) -> bool:
        return _in_pkg(relpath, "serve", "obs")

    def run(self, sf: SourceFile):
        sanctioned = any(sf.relpath.endswith(s)
                         for s in _RENAME_SANCTIONED)
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            if (isinstance(node.func, ast.Name)
                    and node.func.id == "open"):
                yield from self._check_open(sf, node)
            elif (isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("replace", "rename")
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == "os"
                    and not sanctioned):
                yield from self._check_rename(sf, node)

    def _check_open(self, sf, node):
        mode = None
        if len(node.args) >= 2:
            mode = node.args[1]
        for kw in node.keywords:
            if kw.arg == "mode":
                mode = kw.value
        if mode is None:
            return  # default "r": provably non-truncating
        if not (isinstance(mode, ast.Constant)
                and isinstance(mode.value, str)):
            yield sf.violation(
                self.id, node,
                "open() mode is a runtime expression — PSL012 can "
                "only prove atomic-write discipline for literal "
                "modes; spell the mode as a string constant (or "
                "write through peasoup_tpu.utils.atomicio)")
        elif mode.value in _BINARY_UPDATE:
            yield sf.violation(
                self.id, node,
                f"open(..., {mode.value!r}) truncates a binary "
                f"artifact in place; stage the new payload through "
                f"peasoup_tpu.utils.atomicio (tmp + os.replace) "
                f"instead of updating it under readers")

    def _check_rename(self, sf, node):
        if len(node.args) >= 2 and _is_rotation_dst(node.args[1]):
            return  # shard rotation: os.replace(path, path + ".1")
        yield sf.violation(
            self.id, node,
            "hand-rolled os.replace/os.rename publication — use "
            "peasoup_tpu.utils.atomicio (atomic_write_text/json or "
            "the atomic_writer context manager: tmp naming, "
            "unlink-on-error, opt-in fsync) so every artifact "
            "publication shares one proven spelling; only "
            "serve/queue.py's state machine and the "
            "`os.replace(path, path + \".1\")` shard rotation are "
            "sanctioned")


# --------------------------------------------------------------------------
# PSL013 — stream schema contracts
# --------------------------------------------------------------------------

def _qualname(sf: SourceFile, node: ast.AST) -> str:
    """``Class.method`` / ``func`` for a def node (one class level —
    matching the catalog's binding convention)."""
    parents = sf.parents()
    names = [node.name]
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, ast.ClassDef):
            names.append(cur.name)
        elif isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            names.append(cur.name)
        cur = parents.get(cur)
    return ".".join(reversed(names))


class StreamContractRule(Rule):
    """Writer dict-literal keys, reader subscript/.get keys and
    version constants must agree with ``obs/streams.py``."""

    id = "PSL013"
    title = "artifact-stream key/version outside the declared contract"

    def run(self, sf: SourceFile):
        # late import, PSL009-style: rules must not drag obs into
        # every engine import
        from ..obs.streams import (STREAMS, reader_bindings,
                                   stream_keys, version_bindings,
                                   writer_bindings)

        writers = {q: b for (rel, q), b in writer_bindings().items()
                   if rel == sf.relpath}
        readers = {q: b for (rel, q), b in reader_bindings().items()
                   if rel == sf.relpath}
        versions = {c: b for (rel, c), b in version_bindings().items()
                    if rel == sf.relpath}
        if not (writers or readers or versions):
            return

        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Assign) and versions:
                yield from self._check_version(sf, node, versions)
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            qual = _qualname(sf, node)
            if qual in writers:
                stream, varname = writers[qual]
                allowed = stream_keys(stream) | {
                    STREAMS[stream]["version_key"]}
                yield from self._check_writer(
                    sf, node, stream, varname, allowed,
                    STREAMS[stream]["version_key"])
            for stream, varname in readers.get(qual, ()):
                allowed = stream_keys(stream) | {
                    STREAMS[stream]["version_key"]}
                yield from self._check_reader(
                    sf, node, stream, varname, allowed)

    # -- checks --------------------------------------------------------------

    def _check_version(self, sf, node, versions):
        for tgt in node.targets:
            if not isinstance(tgt, ast.Name) or tgt.id not in versions:
                continue
            stream, want = versions[tgt.id]
            if (isinstance(node.value, ast.Constant)
                    and isinstance(node.value.value, int)
                    and node.value.value != want):
                yield sf.violation(
                    self.id, node,
                    f"{tgt.id} = {node.value.value} but stream "
                    f"{stream!r} declares version {want} in "
                    f"obs/streams.py — bump both together")

    def _check_writer(self, sf, fnode, stream, varname, allowed,
                      version_key):
        for node in ast.walk(fnode):
            if isinstance(node, ast.Dict):
                keys = [k for k in node.keys
                        if isinstance(k, ast.Constant)
                        and isinstance(k.value, str)]
                if not any(k.value == version_key for k in keys):
                    continue  # not a record literal of this stream
                for k in keys:
                    if k.value not in allowed:
                        yield sf.violation(
                            self.id, k,
                            f"writer emits undeclared key "
                            f"{k.value!r} for stream {stream!r}; "
                            f"declare it in obs/streams.py (readers "
                            f"and the warehouse flatteners key off "
                            f"the contract)")
            elif (varname is not None
                    and isinstance(node, ast.Subscript)
                    and isinstance(node.ctx, ast.Store)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == varname
                    and isinstance(node.slice, ast.Constant)
                    and isinstance(node.slice.value, str)
                    and node.slice.value not in allowed):
                yield sf.violation(
                    self.id, node,
                    f"writer stores undeclared key "
                    f"{node.slice.value!r} on stream {stream!r} "
                    f"record; declare it in obs/streams.py")

    def _check_reader(self, sf, fnode, stream, varname, allowed):
        for node in ast.walk(fnode):
            key = None
            if (isinstance(node, ast.Subscript)
                    and isinstance(node.ctx, ast.Load)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == varname
                    and isinstance(node.slice, ast.Constant)
                    and isinstance(node.slice.value, str)):
                key = node.slice.value
            elif (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "get"
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == varname
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                key = node.args[0].value
            if key is not None and key not in allowed:
                yield sf.violation(
                    self.id, node,
                    f"reader asks for key {key!r} which no "
                    f"stream-{stream!r} writer can produce (see "
                    f"obs/streams.py) — dead code or a typo")
