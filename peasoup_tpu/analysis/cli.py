"""``python -m peasoup_tpu.analysis`` — run the linter + jaxpr checks.

Exit status: 0 when the tree is clean (every finding fixed, suppressed
with a pragma, or grandfathered in the committed baseline) and the
jaxpr invariants hold; 1 when there is anything new to fix; 2 on usage
errors.  ``--json`` emits one machine-readable object for CI.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from .engine import Baseline, repo_root, run_rules
from .rules import ALL_RULES, rules_by_id

DEFAULT_BASELINE = "lint_baseline.json"


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m peasoup_tpu.analysis",
        description="peasoup-lint: AST + jaxpr invariant checker for "
                    "the TPU search pipeline",
    )
    p.add_argument("paths", nargs="*",
                   help="files/directories to lint (default: the "
                        "peasoup_tpu package)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit one JSON object instead of text")
    p.add_argument("--baseline", default=None,
                   help=f"baseline file (default: <repo>/"
                        f"{DEFAULT_BASELINE})")
    p.add_argument("--write-baseline", action="store_true",
                   help="grandfather all current violations into the "
                        "baseline (and drop expired entries)")
    p.add_argument("--root", default=None,
                   help="directory violations are reported relative "
                        "to (default: the repo root); rule path "
                        "filters match against these relative paths")
    p.add_argument("--rules", default=None,
                   help="comma-separated rule ids to run "
                        "(default: all)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule table and exit")
    p.add_argument("--no-jaxpr", action="store_true",
                   help="skip the jaxpr-level program checks "
                        "(no jax import; fast)")
    p.add_argument("--jaxpr-only", action="store_true",
                   help="run only the jaxpr-level program checks")
    p.add_argument("--signature-bound", type=int, default=8,
                   help="max distinct compiled signatures per program "
                        "(default: 8)")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    if args.list_rules:
        for rule in ALL_RULES:
            print(f"{rule.id}  {rule.title}")
        return 0

    try:
        rules = rules_by_id(
            args.rules.split(",") if args.rules else None)
    except ValueError as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 2

    root = args.root or repo_root()
    baseline_path = args.baseline or os.path.join(
        root, DEFAULT_BASELINE)

    new, grandfathered, expired = [], [], []
    suppressed, errors = 0, []
    if not args.jaxpr_only:
        violations, suppressed, errors = run_rules(
            rules, args.paths or None, root=root)
        try:
            baseline = Baseline.load(baseline_path)
        except ValueError as exc:
            print(f"error: {exc}", file=sys.stderr)
            return 2
        new, grandfathered, expired = baseline.split(violations)
        if args.write_baseline:
            Baseline.from_violations(violations).save(baseline_path)

    jaxpr_findings = []
    if not args.no_jaxpr:
        from .jaxpr_check import check_registered_programs

        jaxpr_findings = check_registered_programs(
            signature_bound=args.signature_bound)

    ok = not new and not jaxpr_findings and not errors

    if args.as_json:
        print(json.dumps({
            "ok": ok,
            "violations": [v.to_json() for v in new],
            "grandfathered": len(grandfathered),
            "suppressed": suppressed,
            "expired_baseline": expired,
            "errors": [{"path": p, "message": m} for p, m in errors],
            "jaxpr": [f.to_json() for f in jaxpr_findings],
        }, indent=2))
        return 0 if ok else 1

    for path, message in errors:
        print(f"{path}: parse error: {message}")
    for v in new:
        print(v.format())
    for f in jaxpr_findings:
        print(f.format())
    if expired:
        print(f"note: {len(expired)} baseline entr"
              f"{'y is' if len(expired) == 1 else 'ies are'} expired "
              f"(violation fixed) — run --write-baseline to drop:")
        for e in expired:
            print(f"  {e['rule']} {e['path']}: {e.get('snippet', '')}")
    summary = (
        f"{len(new)} new violation(s), {len(grandfathered)} "
        f"grandfathered, {suppressed} suppressed"
    )
    if not args.no_jaxpr:
        summary += f", {len(jaxpr_findings)} jaxpr finding(s)"
    print(("OK — " if ok else "FAIL — ") + summary)
    return 0 if ok else 1
