"""The PSL rules: TPU invariants of the search pipeline, as AST checks.

=======  ==========================================================
PSL001   bare ``warnings.warn`` outside ``obs/`` (bypasses telemetry)
PSL002   host-sync call inside a jitted function (device->host stall)
PSL003   device float64/complex128 under ``ops/`` (emulated on TPU)
PSL004   Python ``if``/``while`` on a traced value in a jitted
         function (TracerBoolConversionError, or a silent recompile
         when the branch folds on a concrete weak type)
PSL005   raw ``ValueError``/``RuntimeError`` raise in ``search/`` or
         ``parallel/`` (use the typed ``peasoup_tpu.errors`` classes)
PSL006   raw ``METRICS.timer(...)`` / ``trace_range(...)`` call
         outside ``obs/`` (stage timing must go through the
         ``obs.trace.span`` API so every stage is span-traced)
PSL007   hand-written FLOP/byte/bandwidth constant outside
         ``obs/costmodel.py`` (the analytical cost model is the
         single source of truth for perf accounting figures)
PSL008   bare ``time.sleep`` outside ``serve/retry.py`` (scheduler
         waits must be bounded, classified and injectable — route
         them through the retry layer's BackoffPolicy/pause)
PSL009   literal ``METRICS.inc``/``METRICS.gauge`` name missing from
         ``obs/catalog.py`` (every metric name is a queryable
         contract — an uncatalogued name is a dangling wire)
PSL010   attribute shared between a thread target's reach and the
         main thread without a common ``with self._lock:`` guard
         (Eraser-style lockset check; Event/queue/read-only-after-
         ``start()`` handoffs recognized — see ``concurrency.py``)
PSL011   cycle in the global lock-acquisition order graph (potential
         deadlock; the finding prints the offending chain)
PSL012   truncating ``open(path, "w")`` under ``serve/``/``obs/``
         instead of the sanctioned ``utils.atomicio`` tmp +
         ``os.replace`` helpers (see ``contracts.py``)
PSL013   artifact-stream record key or schema version outside the
         declared contract in ``obs/streams.py`` (undeclared writer
         key, impossible reader key, drifted version constant)
PSL014   non-atomicio rename publication under ``serve/``/``obs/``:
         dynamic/binary-update ``open`` modes PSL012 cannot prove,
         and direct ``os.replace``/``os.rename`` outside the spool
         state machine and the ``path + ".1"`` shard rotation
=======  ==========================================================

Jit detection is syntactic and intra-module: a function is "known
jitted" when it is decorated with ``jax.jit`` / ``partial(jax.jit,
...)`` or wrapped by a module-level ``name = jax.jit(fn, ...)``
assignment.  Static argnames are honoured — a parameter listed in
``static_argnames`` is a Python value, not a tracer, so branching on
it or ``float()``-ing it is fine.

Taint is a forward syntactic pass: non-static parameters are traced;
an assignment whose right-hand side *value-depends* on a traced name
taints its targets.  Structure probes (``x.shape``, ``x.dtype``,
``x.ndim``, ``len(x)``, ``isinstance(x, ...)``, ``x is None``) do NOT
value-depend on the tracer — they are static under jit — so shapes
derived from traced arrays stay untainted and do not false-positive
PSL002/PSL004.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from .engine import SourceFile, Violation

# attribute probes on a tracer that yield static Python values
_SAFE_ATTRS = {"shape", "dtype", "ndim", "size", "itemsize", "weak_type"}
# builtins whose result does not depend on traced *values*
_SAFE_CALLS = {"isinstance", "len", "callable", "hasattr", "getattr",
               "type", "id", "repr"}


# --------------------------------------------------------------------------
# jit detection
# --------------------------------------------------------------------------

@dataclass
class JitInfo:
    node: ast.FunctionDef
    static: set[str] = field(default_factory=set)
    via: str = ""


def _dotted(node: ast.AST) -> str:
    """'jax.jit' for Attribute chains, 'jit' for Names, '' otherwise."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _is_jax_jit(node: ast.AST) -> bool:
    return _dotted(node) in ("jax.jit", "jit")


def _const_strs(node: ast.AST) -> set[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return {node.value}
    if isinstance(node, (ast.Tuple, ast.List)):
        return {
            e.value for e in node.elts
            if isinstance(e, ast.Constant) and isinstance(e.value, str)
        }
    return set()


def _const_ints(node: ast.AST) -> set[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return {node.value}
    if isinstance(node, (ast.Tuple, ast.List)):
        return {
            e.value for e in node.elts
            if isinstance(e, ast.Constant) and isinstance(e.value, int)
        }
    return set()


def _jit_call_statics(call: ast.Call) -> tuple[set[str], set[int]]:
    names: set[str] = set()
    nums: set[int] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            names |= _const_strs(kw.value)
        elif kw.arg == "static_argnums":
            nums |= _const_ints(kw.value)
    return names, nums


def _jit_spec_of_expr(expr: ast.AST):
    """``(static_argnames, static_argnums)`` if ``expr`` denotes a
    jax.jit wrapping, else None.  Handles ``jax.jit``, ``jax.jit(...)``
    and ``partial(jax.jit, ...)`` (the decorator spelling used by the
    pipeline's chunk programs)."""
    if _is_jax_jit(expr):
        return set(), set()
    if isinstance(expr, ast.Call):
        if _is_jax_jit(expr.func):
            return _jit_call_statics(expr)
        if _dotted(expr.func) in ("partial", "functools.partial") and \
                expr.args and _is_jax_jit(expr.args[0]):
            return _jit_call_statics(expr)
    return None


def _argnum_names(fn: ast.FunctionDef, nums: set[int]) -> set[str]:
    pos = [a.arg for a in fn.args.posonlyargs + fn.args.args]
    return {pos[i] for i in nums if 0 <= i < len(pos)}


def collect_jitted(tree: ast.AST) -> list[JitInfo]:
    """Every function in ``tree`` that is known-jitted (see module
    docstring), with its static argnames resolved."""
    defs: dict[str, ast.FunctionDef] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(node.name, node)

    out: dict[int, JitInfo] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                spec = _jit_spec_of_expr(dec)
                if spec is not None:
                    names, nums = spec
                    out[id(node)] = JitInfo(
                        node, names | _argnum_names(node, nums),
                        via="decorator")
        elif isinstance(node, ast.Call):
            # any jax.jit(fn, ...) call — module-level `name = jax.jit
            # (fn)` wrappers, `return jax.jit(mapped)` in the mesh
            # program builders, inline jax.jit(...)(...) dispatches
            if _is_jax_jit(node.func) and node.args and \
                    isinstance(node.args[0], ast.Name):
                fn = defs.get(node.args[0].id)
                if fn is not None:
                    names, nums = _jit_call_statics(node)
                    out.setdefault(id(fn), JitInfo(
                        fn, names | _argnum_names(fn, nums),
                        via="jax.jit() wrapper"))
    return list(out.values())


# --------------------------------------------------------------------------
# value-dependence + taint
# --------------------------------------------------------------------------

def _parent_map(root: ast.AST) -> dict[int, ast.AST]:
    parents: dict[int, ast.AST] = {}
    for node in ast.walk(root):
        for child in ast.iter_child_nodes(node):
            parents[id(child)] = node
    return parents


def _use_is_safe(name: ast.Name, parents: dict[int, ast.AST]) -> bool:
    """True when this occurrence of a traced name cannot leak a traced
    *value* into Python control flow: shape/dtype probes, isinstance,
    len, identity comparisons."""
    node: ast.AST = name
    while True:
        parent = parents.get(id(node))
        if parent is None:
            return False
        if isinstance(parent, ast.Attribute) and parent.value is node:
            if parent.attr in _SAFE_ATTRS:
                return True
            return False  # method/attr that may carry the value
        if isinstance(parent, ast.Subscript) and parent.value is node:
            node = parent  # x[0].shape is still a structure probe path
            continue
        if isinstance(parent, ast.Call):
            if node in parent.args or any(
                    kw.value is node for kw in parent.keywords):
                return _dotted(parent.func) in _SAFE_CALLS
            return False
        if isinstance(parent, ast.Compare):
            if all(isinstance(op, (ast.Is, ast.IsNot))
                   for op in parent.ops):
                return True
            return False
        if isinstance(parent, (ast.Tuple, ast.List)):
            node = parent
            continue
        return False


def value_dependent(expr: ast.AST, traced: set[str],
                    parents: dict[int, ast.AST]) -> bool:
    """Does ``expr`` depend on the *value* (not just the structure) of
    any traced name?"""
    for node in ast.walk(expr):
        if isinstance(node, ast.Name) and node.id in traced:
            if not _use_is_safe(node, parents):
                return True
    return False


def _target_names(target: ast.AST):
    for node in ast.walk(target):
        if isinstance(node, ast.Name):
            yield node.id


def traced_names(info: JitInfo, parents: dict[int, ast.AST]) -> set[str]:
    """Non-static parameters of the jitted function, plus locals
    assigned from value-dependent expressions (forward fixpoint)."""
    fn = info.node
    a = fn.args
    params = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
    if a.vararg:
        params.append(a.vararg.arg)
    traced = {p for p in params if p not in info.static and p != "self"}
    for _ in range(16):  # fixpoint; depth bounded by assignment chains
        changed = False
        for node in ast.walk(fn):
            value, targets = None, []
            if isinstance(node, ast.Assign):
                value, targets = node.value, node.targets
            elif isinstance(node, (ast.AnnAssign, ast.AugAssign,
                                   ast.NamedExpr)):
                value, targets = node.value, [node.target]
            elif isinstance(node, ast.For):
                value, targets = node.iter, [node.target]
            if value is None:
                continue
            if value_dependent(value, traced, parents):
                for target in targets:
                    for name in _target_names(target):
                        if name not in traced:
                            traced.add(name)
                            changed = True
        if not changed:
            break
    return traced


# --------------------------------------------------------------------------
# rule framework
# --------------------------------------------------------------------------

class Rule:
    id: str = "PSL000"
    title: str = ""

    def applies(self, relpath: str) -> bool:
        return relpath.endswith(".py")

    def run(self, sf: SourceFile):
        raise NotImplementedError


def _in_pkg(relpath: str, *subdirs: str) -> bool:
    return any(relpath.startswith(f"peasoup_tpu/{d}/") for d in subdirs)


# --------------------------------------------------------------------------
# PSL001 — bare warnings.warn outside obs/
# --------------------------------------------------------------------------

class NoBareWarningsRule(Rule):
    """Every warning must route through ``obs.events.warn_event`` so it
    is counted and JSONL-logged; a bare ``warnings.warn`` silently
    bypasses run telemetry.  ``obs/`` itself is exempt (warn_event's
    own implementation raises the Python warning there)."""

    id = "PSL001"
    title = "bare warnings.warn bypasses telemetry"

    def applies(self, relpath: str) -> bool:
        return (relpath.startswith("peasoup_tpu/")
                and not relpath.startswith("peasoup_tpu/obs/")
                and relpath.endswith(".py"))

    def run(self, sf: SourceFile):
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ImportFrom) and \
                    node.module == "warnings":
                yield sf.violation(
                    self.id, node,
                    "import from `warnings` — route warnings through "
                    "peasoup_tpu.obs.events.warn_event so they are "
                    "counted and logged",
                )
            elif isinstance(node, ast.Call) and \
                    _dotted(node.func) == "warnings.warn":
                yield sf.violation(
                    self.id, node,
                    "bare warnings.warn() — use "
                    "peasoup_tpu.obs.events.warn_event(kind, message, "
                    "**data) so the warning lands in run telemetry",
                )


# --------------------------------------------------------------------------
# PSL002 — host syncs inside jitted functions
# --------------------------------------------------------------------------

_HOST_SYNC_METHODS = {"block_until_ready", "item", "tolist", "to_py"}
_HOST_CAST_BUILTINS = {"float", "int", "bool", "complex"}
_HOST_NP_FUNCS = {"np.asarray", "np.array", "numpy.asarray",
                  "numpy.array", "onp.asarray", "onp.array"}


class NoHostSyncInJitRule(Rule):
    """A ``.block_until_ready()``, ``.item()``, ``float()``/``int()``
    on a tracer, ``np.asarray`` or ``jax.device_get`` inside a jitted
    program either fails at trace time or — worse — silently pins a
    device->host transfer (and a potential recompile) into the hot
    path of every DM trial."""

    id = "PSL002"
    title = "host sync inside a jitted function"

    def applies(self, relpath: str) -> bool:
        return relpath.startswith("peasoup_tpu/") and \
            relpath.endswith(".py")

    def run(self, sf: SourceFile):
        for info in collect_jitted(sf.tree):
            parents = _parent_map(info.node)
            traced = traced_names(info, parents)
            for node in ast.walk(info.node):
                if not isinstance(node, ast.Call):
                    continue
                fn = node.func
                name = _dotted(fn)
                if isinstance(fn, ast.Attribute) and \
                        fn.attr in _HOST_SYNC_METHODS:
                    recv_dep = value_dependent(fn.value, traced, parents)
                    if fn.attr == "block_until_ready" or recv_dep:
                        yield sf.violation(
                            self.id, node,
                            f".{fn.attr}() inside jitted "
                            f"`{info.node.name}` forces a device->host "
                            f"sync per call — return the array and "
                            f"sync outside the program",
                        )
                elif name in _HOST_CAST_BUILTINS:
                    if node.args and value_dependent(
                            node.args[0], traced, parents):
                        yield sf.violation(
                            self.id, node,
                            f"{name}() on a traced value inside jitted "
                            f"`{info.node.name}` concretises the "
                            f"tracer (host sync / TracerConversion"
                            f"Error) — keep it a jnp array",
                        )
                elif name in _HOST_NP_FUNCS:
                    if node.args and value_dependent(
                            node.args[0], traced, parents):
                        yield sf.violation(
                            self.id, node,
                            f"{name}() on a traced value inside jitted "
                            f"`{info.node.name}` pulls the array to "
                            f"host — use jnp.asarray or restructure",
                        )
                elif name in ("jax.device_get", "device_get"):
                    yield sf.violation(
                        self.id, node,
                        f"jax.device_get inside jitted "
                        f"`{info.node.name}` is a host transfer — "
                        f"fetch after the program returns",
                    )


# --------------------------------------------------------------------------
# PSL003 — device float64 under ops/
# --------------------------------------------------------------------------

_F64_ATTRS = {"float64", "complex128", "double", "float_"}
_F64_STRINGS = {"float64", "complex128", "double"}


class NoDeviceF64Rule(Rule):
    """float64 is software-emulated on TPU (and complex128 unsupported)
    — a stray ``jnp.float64`` in a kernel silently multiplies its cost.
    Host-side ``np.float64`` table math is exempt: only the jax/jnp
    namespaces are device dtypes.  The deliberate f64 index-math sites
    (``ops/resample.py`` legacy path, ``ops/fold.py`` phase_bins) carry
    ``psl: disable`` pragmas with their reasons."""

    id = "PSL003"
    title = "device float64/complex128 under ops/"

    def applies(self, relpath: str) -> bool:
        return _in_pkg(relpath, "ops")

    def _jnp_aliases(self, tree: ast.AST) -> set[str]:
        aliases = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "jax.numpy":
                        aliases.add(a.asname or "jax.numpy")
            elif isinstance(node, ast.ImportFrom) and node.module == "jax":
                for a in node.names:
                    if a.name == "numpy":
                        aliases.add(a.asname or "numpy")
        return aliases or {"jnp"}

    def run(self, sf: SourceFile):
        aliases = self._jnp_aliases(sf.tree)
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Attribute) and \
                    node.attr in _F64_ATTRS and \
                    _dotted(node.value) in aliases | {"jax.numpy"}:
                yield sf.violation(
                    self.id, node,
                    f"device dtype {_dotted(node.value)}.{node.attr} — "
                    f"f64 is emulated on TPU; use f32 (or do the f64 "
                    f"math host-side in numpy)",
                )
            elif isinstance(node, ast.Call):
                root = _dotted(node.func).split(".")[0]
                if root not in aliases:
                    continue
                for kw in node.keywords:
                    if kw.arg == "dtype" and \
                            isinstance(kw.value, ast.Constant) and \
                            kw.value.value in _F64_STRINGS:
                        yield sf.violation(
                            self.id, node,
                            f'dtype="{kw.value.value}" in a '
                            f"{root}.* call — f64 is emulated on TPU",
                        )


# --------------------------------------------------------------------------
# PSL004 — Python branching on traced values
# --------------------------------------------------------------------------

class NoTracedBranchRule(Rule):
    """``if``/``while`` on a traced value inside a jitted function is
    either a TracerBoolConversionError at trace time or, when the
    value happens to be concrete (weak types, shape-dependent consts),
    a per-value recompile.  Use ``lax.cond`` / ``lax.select`` /
    ``jnp.where``.  Branching on static argnames and on structure
    probes (``x.shape``, ``x is None``, ``isinstance``) is fine and
    not flagged."""

    id = "PSL004"
    title = "Python branch on traced value in jitted function"

    def applies(self, relpath: str) -> bool:
        return relpath.startswith("peasoup_tpu/") and \
            relpath.endswith(".py")

    def run(self, sf: SourceFile):
        for info in collect_jitted(sf.tree):
            parents = _parent_map(info.node)
            traced = traced_names(info, parents)
            for node in ast.walk(info.node):
                if isinstance(node, (ast.If, ast.While, ast.IfExp)):
                    if value_dependent(node.test, traced, parents):
                        kind = {"If": "if", "While": "while",
                                "IfExp": "conditional expression"}[
                                    type(node).__name__]
                        yield sf.violation(
                            self.id, node,
                            f"Python `{kind}` on a traced value inside "
                            f"jitted `{info.node.name}` — use lax.cond"
                            f"/lax.select/jnp.where (or mark the "
                            f"argument static)",
                        )


# --------------------------------------------------------------------------
# PSL005 — untyped raises in the drivers
# --------------------------------------------------------------------------

_RAW_EXCS = {"ValueError", "RuntimeError"}


class TypedErrorsRule(Rule):
    """``search/`` and ``parallel/`` raise the typed
    ``peasoup_tpu.errors`` hierarchy (ConfigError, InputFileError,
    HBMBudgetError, DomainError, CheckpointError) so callers catch a
    *class* of failure instead of string-matching ValueErrors.  Every
    typed class still subclasses the builtin it replaces, so this is
    always a safe upgrade."""

    id = "PSL005"
    title = "raw ValueError/RuntimeError in search/ or parallel/"

    def applies(self, relpath: str) -> bool:
        return _in_pkg(relpath, "search", "parallel")

    def run(self, sf: SourceFile):
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Raise) or node.exc is None:
                continue
            exc = node.exc
            name = ""
            if isinstance(exc, ast.Call):
                name = _dotted(exc.func)
            elif isinstance(exc, ast.Name):
                name = exc.id
            if name in _RAW_EXCS:
                yield sf.violation(
                    self.id, node,
                    f"raise {name} in a driver — raise the matching "
                    f"typed peasoup_tpu.errors class (ConfigError, "
                    f"InputFileError, HBMBudgetError, DomainError, "
                    f"CheckpointError) instead",
                )


# --------------------------------------------------------------------------
# PSL006 — raw stage timing/tracing outside obs/
# --------------------------------------------------------------------------

#: receivers whose ``.timer(...)`` is the raw registry API (the
#: process-wide aliases the drivers import)
_TIMER_RECEIVERS = {"METRICS", "REGISTRY"}


class SpanApiRule(Rule):
    """Pipeline stages time themselves through ``obs.trace.span`` —
    one call that opens a hierarchical span (Chrome-trace exportable,
    HBM-sampled, per-trial attributable), feeds the stage-timer
    registry via ``metric=``, and forwards the name to the jax
    profiler.  A raw ``METRICS.timer(...)`` or ``trace_range(...)``
    call outside ``obs/`` produces a stage the trace cannot see (or a
    profiler range the report cannot count) — the split telemetry this
    rule exists to prevent.  Deliberate exceptions carry a
    ``# psl: disable=PSL006 -- reason`` pragma."""

    id = "PSL006"
    title = "raw METRICS.timer/trace_range outside obs/ (use span())"

    def applies(self, relpath: str) -> bool:
        return (relpath.startswith("peasoup_tpu/")
                and not relpath.startswith("peasoup_tpu/obs/")
                and relpath.endswith(".py"))

    def run(self, sf: SourceFile):
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            name = _dotted(node.func)
            if name == "trace_range" or name.endswith(".trace_range"):
                yield sf.violation(
                    self.id, node,
                    "trace_range() outside obs/ — open an "
                    "obs.trace.span(...) instead (it still forwards "
                    "to jax.profiler.TraceAnnotation, and the span "
                    "lands in the exported trace + run report)",
                )
                continue
            parts = name.split(".")
            if (len(parts) >= 2 and parts[-1] == "timer"
                    and parts[-2] in _TIMER_RECEIVERS):
                yield sf.violation(
                    self.id, node,
                    f"{name}() outside obs/ — use obs.trace.span("
                    f"name, metric=...) so the stage is span-traced "
                    f"AND registry-timed in one call",
                )


# --------------------------------------------------------------------------
# PSL007 — hand-written FLOP/byte constants outside obs/costmodel.py
# --------------------------------------------------------------------------

import re as _re

#: CONSTANT_CASE names that smell like perf-accounting figures: peak
#: flops, bandwidths, per-element byte/flop coefficients.  Matched
#: against whole underscore-separated tokens so e.g. MAX_SPANS or
#: N_BYTES_READ_IDX (an index, not a coefficient) stay clean.
_PERF_CONST_TOKENS = _re.compile(
    r"(?:^|_)(FLOPS?|[GT]FLOPS?|GBPS|GIBPS|BANDWIDTH|BYTES_PER|PEAK_BW)"
    r"(?:_|$)"
)


def _numeric_literal(node: ast.AST) -> bool:
    """True for a numeric constant or simple arithmetic of numeric
    constants (``819.0``, ``1 << 30``, ``96 + 32``, ``8.3e9``)."""
    if isinstance(node, ast.Constant):
        return isinstance(node.value, (int, float))
    if isinstance(node, ast.BinOp):
        return _numeric_literal(node.left) and _numeric_literal(node.right)
    if isinstance(node, ast.UnaryOp):
        return _numeric_literal(node.operand)
    return False


class CostModelAuthorityRule(Rule):
    """Perf-accounting figures — peak FLOP/s, HBM bandwidths,
    per-element byte/flop coefficients — live in ``obs/costmodel.py``
    (its peak table and unit-cost functions) and NOWHERE else: a
    hand-written ``V5E_HBM_GBPS = 819.0`` in a benchmark silently
    diverges the moment the table is corrected, and two disagreeing
    "peaks" make every utilization number untrustworthy.  Deliberate
    exceptions (e.g. a constant describing a non-device quantity that
    happens to match the name pattern) carry a
    ``# psl: disable=PSL007 -- reason`` pragma."""

    id = "PSL007"
    title = "hand-written FLOP/byte constant outside obs/costmodel.py"

    def applies(self, relpath: str) -> bool:
        if relpath == "peasoup_tpu/obs/costmodel.py":
            return False
        return relpath.endswith(".py") and (
            relpath.startswith("peasoup_tpu/")
            or relpath == "bench.py"
            or relpath.startswith("benchmarks/")
        )

    def run(self, sf: SourceFile):
        for node in ast.walk(sf.tree):
            targets: list[ast.AST] = []
            value = None
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets, value = [node.target], node.value
            if value is None or not _numeric_literal(value):
                continue
            for target in targets:
                if not isinstance(target, ast.Name):
                    continue
                name = target.id
                if name != name.upper():
                    continue  # CONSTANT_CASE only: locals stay free
                if _PERF_CONST_TOKENS.search(name):
                    yield sf.violation(
                        self.id, node,
                        f"hand-written perf constant `{name}` — import "
                        f"it from peasoup_tpu.obs.costmodel (peak "
                        f"table / unit-cost functions) so the cost "
                        f"model stays the single source of truth",
                    )


# --------------------------------------------------------------------------
# PSL008 — bare time.sleep outside serve/retry.py
# --------------------------------------------------------------------------

class NoBareSleepRule(Rule):
    """An ad-hoc ``time.sleep`` retry/poll loop is an unbounded,
    unclassified, untestable wait: the survey scheduler's backoff
    policy (``serve/retry.py``) is the one place waits are allowed to
    happen, because there they are bounded by ``BackoffPolicy``,
    attributed to a failure classification, and injectable in tests
    (``pause(seconds, sleeper=...)``).  A sleep anywhere else either
    belongs behind that API or carries a
    ``# psl: disable=PSL008 -- reason`` pragma."""

    id = "PSL008"
    title = "bare time.sleep outside serve/retry.py"

    def applies(self, relpath: str) -> bool:
        if relpath == "peasoup_tpu/serve/retry.py":
            return False
        return relpath.endswith(".py") and (
            relpath.startswith("peasoup_tpu/")
            or relpath == "bench.py"
            or relpath.startswith("benchmarks/")
        )

    def run(self, sf: SourceFile):
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ImportFrom) and \
                    node.module == "time" and \
                    any(a.name == "sleep" for a in node.names):
                yield sf.violation(
                    self.id, node,
                    "`from time import sleep` — scheduler waits go "
                    "through peasoup_tpu.serve.retry (BackoffPolicy."
                    "delay_for + pause) so they are bounded, "
                    "classified and injectable in tests",
                )
            elif isinstance(node, ast.Call) and \
                    _dotted(node.func) == "time.sleep":
                yield sf.violation(
                    self.id, node,
                    "bare time.sleep() — use peasoup_tpu.serve.retry."
                    "pause / BackoffPolicy so the wait is bounded, "
                    "classified and injectable in tests",
                )


# --------------------------------------------------------------------------
# PSL009 — uncatalogued metric names
# --------------------------------------------------------------------------

#: receiver spellings of the metrics registry whose ``.inc``/``.gauge``
#: this rule audits: the process-wide aliases plus any attribute or
#: local that *is* a registry (``self._registry``, ``reg``)
_METRIC_RECEIVERS = {"METRICS", "REGISTRY", "reg"}


class MetricsCatalogRule(Rule):
    """Every literal counter/gauge name must appear in
    ``obs/catalog.py`` (:data:`~peasoup_tpu.obs.catalog.CATALOG`, or
    match a documented :data:`~peasoup_tpu.obs.catalog.DYNAMIC_PREFIXES`
    family).  The warehouse, the health rules and every dashboard
    join on metric *names*; a name emitted in code but absent from
    the catalog is a dangling wire nobody will ever query — and a
    typo'd name is a silent fork of an existing series.  Dynamically
    built names (f-strings) are exempt per call site but their prefix
    must be catalogued as a family.  Deliberate exceptions carry a
    ``# psl: disable=PSL009 -- reason`` pragma."""

    id = "PSL009"
    title = "metric name missing from obs/catalog.py"

    def applies(self, relpath: str) -> bool:
        if relpath == "peasoup_tpu/obs/catalog.py":
            return False
        return (relpath.startswith("peasoup_tpu/")
                and relpath.endswith(".py"))

    def run(self, sf: SourceFile):
        from ..obs.catalog import is_cataloged

        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            name = _dotted(node.func)
            parts = name.split(".")
            if len(parts) < 2 or parts[-1] not in {"inc", "gauge"}:
                continue
            recv = parts[-2]
            if recv not in _METRIC_RECEIVERS \
                    and not recv.endswith("registry"):
                continue
            arg = node.args[0]
            if not (isinstance(arg, ast.Constant)
                    and isinstance(arg.value, str)):
                continue  # dynamic name: the prefix is the contract
            if not is_cataloged(arg.value):
                yield sf.violation(
                    self.id, node,
                    f"metric name {arg.value!r} is not in "
                    f"peasoup_tpu/obs/catalog.py — add it to CATALOG "
                    f"(or a DYNAMIC_PREFIXES family) so the name is "
                    f"a queryable, documented contract",
                )


# imported at the tail so concurrency/contracts can subclass Rule
# (defined above) without a cycle at module-init time
from .concurrency import LockDisciplineRule, LockOrderRule  # noqa: E402
from .contracts import (AtomicWriteRule, RenameDisciplineRule,  # noqa: E402
                        StreamContractRule)

ALL_RULES: tuple[Rule, ...] = (
    NoBareWarningsRule(),
    NoHostSyncInJitRule(),
    NoDeviceF64Rule(),
    NoTracedBranchRule(),
    TypedErrorsRule(),
    SpanApiRule(),
    CostModelAuthorityRule(),
    NoBareSleepRule(),
    MetricsCatalogRule(),
    LockDisciplineRule(),
    LockOrderRule(),
    AtomicWriteRule(),
    StreamContractRule(),
    RenameDisciplineRule(),
)


def rules_by_id(ids=None) -> list[Rule]:
    if not ids:
        return list(ALL_RULES)
    wanted = {i.strip().upper() for i in ids}
    unknown = wanted - {r.id for r in ALL_RULES}
    if unknown:
        raise ValueError(f"unknown rule id(s): {sorted(unknown)}")
    return [r for r in ALL_RULES if r.id in wanted]
