from .sigproc import (
    SigprocHeader,
    read_sigproc_header,
    write_sigproc_header,
    Filterbank,
    TimeSeries,
    read_filterbank,
    write_filterbank,
    read_tim,
    write_tim,
)
from .unpack import unpack_bits, pack_bits
