"""Bit unpacking/packing for 1/2/4/8-bit SIGPROC data.

Sub-byte samples are packed little-endian within each byte (first sample
in the lowest-order bits), matching the unpack convention of the
``dedisp`` library the reference links against
(`include/transforms/dedisperser.hpp:104-112` feeds raw 1/2/4/8-bit
words straight to ``dedisp_execute``).

A C++ fast path (``peasoup_tpu/native``) is used when available; the
NumPy lookup-table fallback below is always correct.
"""

from __future__ import annotations

import numpy as np

_LUTS: dict[int, np.ndarray] = {}


def _lut(nbits: int) -> np.ndarray:
    lut = _LUTS.get(nbits)
    if lut is None:
        spb = 8 // nbits  # samples per byte
        mask = (1 << nbits) - 1
        byte = np.arange(256, dtype=np.uint16)
        lut = np.empty((256, spb), dtype=np.uint8)
        for k in range(spb):
            lut[:, k] = (byte >> (k * nbits)) & mask
        _LUTS[nbits] = lut
    return lut


def unpack_bits(raw: np.ndarray, nbits: int) -> np.ndarray:
    """Unpack a uint8 byte buffer into one uint8 value per sample."""
    raw = np.asarray(raw, dtype=np.uint8)
    if nbits == 8:
        return raw
    if nbits not in (1, 2, 4):
        raise ValueError(f"unsupported nbits: {nbits}")
    try:
        from ..native import lib as _native
    except Exception:
        _native = None
    if _native is not None:
        return _native.unpack_bits(raw, nbits)
    return _lut(nbits)[raw].ravel()


def pack_bits(samples: np.ndarray, nbits: int) -> np.ndarray:
    """Pack uint8 samples (values < 2**nbits) into a byte buffer."""
    samples = np.asarray(samples, dtype=np.uint8)
    if nbits == 8:
        return samples
    if nbits not in (1, 2, 4):
        raise ValueError(f"unsupported nbits: {nbits}")
    spb = 8 // nbits
    n = samples.shape[0]
    if n % spb:
        samples = np.pad(samples, (0, spb - n % spb))
    groups = samples.reshape(-1, spb).astype(np.uint16)
    out = np.zeros(groups.shape[0], dtype=np.uint16)
    for k in range(spb):
        out |= (groups[:, k] & ((1 << nbits) - 1)) << (k * nbits)
    return out.astype(np.uint8)
