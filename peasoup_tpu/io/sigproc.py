"""SIGPROC filterbank / time-series I/O.

Implements the SIGPROC keyword-length-prefixed binary header format and
whole-file data loading, with the same semantics as the reference
(`include/data_types/header.hpp:222-308,339-403` and
`include/data_types/filterbank.hpp:207-250` in xiaobotianxie/peasoup):

* header keys are length-prefixed ASCII strings followed by a binary
  value; parsing stops at ``HEADER_END``;
* when ``nsamples`` is absent (0) it is inferred from the file size:
  ``(total_size - header_size) / nchans * 8 / nbits``;
* data are stored time-major (time slowest), ``nchans`` values per
  sample, 1/2/4/8/32 bits each.
"""

from __future__ import annotations

import os
import struct
from dataclasses import dataclass, field, asdict

import numpy as np

from ..errors import InputFileError
from .unpack import unpack_bits, pack_bits

# SIGPROC header keys -> struct format. Matches the reference parser's
# accepted keyword set (header.hpp:265-296).
_INT_KEYS = {
    "nchans", "telescope_id", "machine_id", "data_type", "ibeam",
    "nbeams", "nbits", "barycentric", "pulsarcentric", "nbins",
    "nsamples", "nifs", "npuls",
}
_DOUBLE_KEYS = {
    "az_start", "za_start", "src_raj", "src_dej", "tstart", "tsamp",
    "period", "fch1", "foff", "refdm",
}
_CHAR_KEYS = {"signed"}
_STRING_KEYS = {"source_name", "rawdatafile"}


@dataclass
class SigprocHeader:
    """SIGPROC header values (defaults all zero, as in the reference)."""

    source_name: str = ""
    rawdatafile: str = ""
    az_start: float = 0.0
    za_start: float = 0.0
    src_raj: float = 0.0
    src_dej: float = 0.0
    tstart: float = 0.0
    tsamp: float = 0.0
    period: float = 0.0
    fch1: float = 0.0
    foff: float = 0.0
    nchans: int = 0
    telescope_id: int = 0
    machine_id: int = 0
    data_type: int = 0
    ibeam: int = 0
    nbeams: int = 0
    nbits: int = 0
    barycentric: int = 0
    pulsarcentric: int = 0
    nbins: int = 0
    nsamples: int = 0
    nifs: int = 0
    npuls: int = 0
    refdm: float = 0.0
    signed_data: int = 0
    size: int = 0  # header size in bytes (set on read)

    @property
    def cfreq(self) -> float:
        """Centre frequency in MHz (filterbank.hpp:190-196)."""
        if self.foff < 0:
            return self.fch1 + self.foff * self.nchans / 2.0
        return self.fch1 - self.foff * self.nchans / 2.0

    def to_dict(self) -> dict:
        return asdict(self)


def _read_string(f) -> str | None:
    raw = f.read(4)
    if len(raw) < 4:
        return None
    (length,) = struct.unpack("<i", raw)
    if length <= 0 or length >= 80:
        return None
    return f.read(length).decode("latin-1")


def read_sigproc_header(f) -> SigprocHeader:
    """Parse a SIGPROC header from an open binary file object."""
    hdr = SigprocHeader()
    start = f.tell()
    s = _read_string(f)
    if s != "HEADER_START":
        f.seek(start)
        raise InputFileError("not a SIGPROC file (missing HEADER_START)")
    while True:
        key = _read_string(f)
        if key is None:
            raise InputFileError("unexpected EOF inside SIGPROC header")
        if key == "HEADER_END":
            break
        if key in _INT_KEYS:
            (val,) = struct.unpack("<i", f.read(4))
            setattr(hdr, key, val)
        elif key in _DOUBLE_KEYS:
            (val,) = struct.unpack("<d", f.read(8))
            setattr(hdr, key, val)
        elif key in _CHAR_KEYS:
            hdr.signed_data = f.read(1)[0]
        elif key in _STRING_KEYS:
            val = _read_string(f)
            setattr(hdr, key, val if val is not None else "")
        else:
            # The reference warns and continues; with no length knowledge we
            # cannot skip an unknown binary value, so fail loudly instead.
            raise ValueError(f"unknown SIGPROC header parameter: {key!r}")
    hdr.size = f.tell() - start
    if hdr.nchans <= 0 or hdr.nbits <= 0:
        raise InputFileError(
            f"invalid SIGPROC header: nchans={hdr.nchans}, "
            f"nbits={hdr.nbits} (both must be positive)")
    if hdr.nsamples == 0:
        # Infer from file size (header.hpp:394-401)
        pos = f.tell()
        f.seek(0, os.SEEK_END)
        total = f.tell()
        f.seek(pos)
        hdr.nsamples = (total - hdr.size) * 8 // hdr.nchans // hdr.nbits
    return hdr


def _write_string(f, s: str) -> None:
    b = s.encode("latin-1")
    f.write(struct.pack("<i", len(b)))
    f.write(b)


def write_sigproc_header(f, hdr: SigprocHeader, include_nsamples: bool = False) -> None:
    """Write a SIGPROC header (header.hpp:339-403 semantics)."""
    _write_string(f, "HEADER_START")
    for key in _STRING_KEYS:
        val = getattr(hdr, key)
        if val:
            _write_string(f, key)
            _write_string(f, val)
    for key in sorted(_DOUBLE_KEYS):
        _write_string(f, key)
        f.write(struct.pack("<d", float(getattr(hdr, key))))
    for key in sorted(_INT_KEYS):
        if key == "nsamples" and not include_nsamples:
            continue
        _write_string(f, key)
        f.write(struct.pack("<i", int(getattr(hdr, key))))
    _write_string(f, "signed")
    f.write(struct.pack("<B", hdr.signed_data))
    _write_string(f, "HEADER_END")


@dataclass
class Filterbank:
    """A time x frequency data block plus metadata.

    ``data`` is a (nsamps, nchans) uint8 array for nbits<=8 input or
    float32 for 32-bit input; time is the slow axis, channel 0 = fch1.
    """

    header: SigprocHeader
    data: np.ndarray  # (nsamps, nchans)

    @property
    def nsamps(self) -> int:
        return self.data.shape[0]

    @property
    def nchans(self) -> int:
        return self.data.shape[1]

    @property
    def tsamp(self) -> float:
        return self.header.tsamp

    @property
    def fch1(self) -> float:
        return self.header.fch1

    @property
    def foff(self) -> float:
        return self.header.foff

    @property
    def cfreq(self) -> float:
        return self.header.cfreq


@dataclass
class TimeSeries:
    """A 1-D time series with metadata (timeseries.hpp:50-161)."""

    data: np.ndarray
    tsamp: float
    dm: float = 0.0

    @property
    def nsamps(self) -> int:
        return self.data.shape[0]


def read_filterbank(filename: str) -> Filterbank:
    """Load a whole SIGPROC filterbank into RAM (filterbank.hpp:218-240).

    A truncated file — the header promises more samples than the bytes
    that follow — raises :class:`InputFileError` WITH the byte counts,
    instead of surfacing as a numpy reshape error deep inside unpack.
    The survey scheduler's retry layer (serve/retry.py) classifies
    exactly this error as quarantine-immediately.
    """
    with open(filename, "rb") as f:
        hdr = read_sigproc_header(f)
        nbytes = hdr.nsamples * hdr.nbits * hdr.nchans // 8
        f.seek(hdr.size)
        buf = f.read(nbytes)
        if len(buf) < nbytes:
            raise InputFileError(
                f"truncated filterbank {filename!r}: header promises "
                f"{hdr.nsamples} samples x {hdr.nchans} chans at "
                f"{hdr.nbits}-bit = {nbytes} data bytes, but only "
                f"{len(buf)} bytes follow the {hdr.size}-byte header")
        raw = np.frombuffer(buf, dtype=np.uint8)
    if hdr.nbits == 32:
        data = raw.view(np.float32).reshape(hdr.nsamples, hdr.nchans)
    else:
        data = unpack_bits(raw, hdr.nbits)[: hdr.nsamples * hdr.nchans]
        data = data.reshape(hdr.nsamples, hdr.nchans)
    return Filterbank(header=hdr, data=data)


def write_filterbank(filename: str, fil: Filterbank) -> None:
    hdr = fil.header
    with open(filename, "wb") as f:
        write_sigproc_header(f, hdr)
        if hdr.nbits == 32:
            f.write(np.ascontiguousarray(fil.data, dtype=np.float32).tobytes())
        else:
            flat = np.ascontiguousarray(fil.data, dtype=np.uint8).ravel()
            f.write(pack_bits(flat, hdr.nbits).tobytes())


def read_tim(filename: str) -> TimeSeries:
    """Read a SIGPROC .tim file (float32 payload; timeseries.hpp:137-160)."""
    with open(filename, "rb") as f:
        hdr = read_sigproc_header(f)
        raw = np.frombuffer(f.read(), dtype=np.float32)
    return TimeSeries(data=raw.copy(), tsamp=hdr.tsamp, dm=hdr.refdm)


def write_tim(filename: str, tim: TimeSeries, header: SigprocHeader | None = None) -> None:
    hdr = header or SigprocHeader()
    hdr.tsamp = tim.tsamp
    hdr.refdm = tim.dm
    hdr.nbits = 32
    hdr.nchans = 1
    hdr.nifs = 1
    hdr.data_type = 2  # sigproc time series
    with open(filename, "wb") as f:
        write_sigproc_header(f, hdr)
        f.write(np.ascontiguousarray(tim.data, dtype=np.float32).tobytes())
