"""Background compaction: fold live JSONL shard tails into sealed,
indexed segments (ISSUE 20).

Lifecycle of one ``compact_once``::

    lock        segments/compact.lock (O_EXCL; stale/dead-pid steal)
    plan        per shard: size - folded_offset tail, keep tails past
                the size/age threshold (all of them under ``force``)
    scan        parse each eligible tail's COMPLETE lines only — a
                torn last line stays live, exactly as readers treat it
    dedup       same cand_id ingested twice -> newest record wins
                (utc, then pinned shard order); ids already sealed in
                older segments go to the new segment's ``supersedes``
    seal        write seg-<seq>.jsonl + seg-<seq>.idx.json, each via
                write-temp-then-atomic-rename (segments.write_segment)
    publish     write MANIFEST.json (fsync'd atomic replace) — THE
                commit point: folded offsets advance and the segment
                becomes visible in the same rename
    rebuild     reset each folded shard's live-tail coincidence bins
                to start at the new folded offset (the sealed bins now
                live in the segment's sidecar)

A compactor killed anywhere before ``publish`` changes nothing a
reader can see: orphan ``seg-*`` / temp files are ignored (the
manifest is the only source of truth) and removed by the next run.
Shard files are never truncated or rewritten — they are append-only
for live writers; folding only advances the manifest offset at which
merged readers start the tail.  ``fault`` is the chaos hook
(tools/chaos.py ``compactor_kill``): stages named in
:func:`segments.write_segment` plus ``"scan"`` and ``"pre_manifest"``
let a drill die at every syscall boundary a SIGKILL could hit.
"""

from __future__ import annotations

import json
import os
import time

from ..obs.metrics import REGISTRY as METRICS
from .segments import (SEG_PREFIX, SegmentSet, _noop_fault,
                       load_manifest, segment_dir, update_bins_file,
                       write_manifest, write_segment)
from .store import LEGACY_BASENAME, SHARD_PREFIX

#: default size threshold: a shard tail at/above this many bytes is
#: eligible for folding
DEFAULT_MIN_BYTES = 1 << 20

#: a compact.lock older than this whose owner pid is gone is stolen
DEFAULT_LOCK_STALE_S = 600.0

LOCK_BASENAME = "compact.lock"


class CompactionPolicy:
    """When is a shard tail sealed?  ``min_bytes`` (size pressure) OR
    ``min_age_s`` since last append (quiet shards drain eventually);
    ``min_age_s=None`` disables the age trigger."""

    def __init__(self, min_bytes: int = DEFAULT_MIN_BYTES,
                 min_age_s: float | None = None):
        self.min_bytes = int(min_bytes)
        self.min_age_s = (None if min_age_s is None
                          else float(min_age_s))

    def eligible(self, tail_bytes: int, age_s: float) -> bool:
        if tail_bytes <= 0:
            return False
        if tail_bytes >= self.min_bytes:
            return True
        return (self.min_age_s is not None
                and age_s >= self.min_age_s)


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(int(pid), 0)
    except (OSError, ValueError, TypeError):
        return False
    return True


class CompactionLocked(RuntimeError):
    """Another compactor holds the store's compaction lock."""


class Compactor:
    """One store's compactor.  Safe to run concurrently with live
    ingests and merged reads; NOT safe to run two of per store, which
    the lock file enforces."""

    def __init__(self, root: str, policy: CompactionPolicy | None = None,
                 *, fault=_noop_fault, clock=time.time,
                 lock_stale_s: float = DEFAULT_LOCK_STALE_S):
        self.root = os.path.abspath(root)
        self.policy = policy or CompactionPolicy()
        self.fault = fault
        self.clock = clock
        self.lock_stale_s = float(lock_stale_s)

    # -- lock --------------------------------------------------------------

    def _lock_path(self) -> str:
        return os.path.join(segment_dir(self.root), LOCK_BASENAME)

    def _acquire_lock(self) -> None:
        os.makedirs(segment_dir(self.root), exist_ok=True)
        path = self._lock_path()
        for attempt in (0, 1):
            try:
                fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            except FileExistsError:
                if attempt or not self._lock_is_stale(path):
                    raise CompactionLocked(path)
                try:
                    os.unlink(path)  # dead owner: steal
                except OSError:
                    pass
                continue
            with os.fdopen(fd, "w", encoding="utf-8") as f:
                json.dump({"pid": os.getpid(),
                           "utc": float(self.clock())}, f)
            return

    def _lock_is_stale(self, path: str) -> bool:
        try:
            with open(path, encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError):
            return True  # unreadable lock: treat as crashed owner
        age = float(self.clock()) - float(doc.get("utc", 0.0))
        if not _pid_alive(doc.get("pid", -1)):
            return True  # owner died (a SIGKILL'd drill, a crash)
        # owner looks alive — could be a recycled pid or another
        # host's compactor that wedged; steal only past the deadline
        return age >= self.lock_stale_s

    def _release_lock(self) -> None:
        try:
            os.unlink(self._lock_path())
        except OSError:
            pass

    # -- planning ----------------------------------------------------------

    def _live_files(self) -> list[str]:
        """Live JSONL files in the store's pinned merge order
        (store.ShardedCandidateStore.shard_files: legacy first, then
        shards by basename)."""
        out = []
        legacy = os.path.join(self.root, LEGACY_BASENAME)
        if os.path.exists(legacy):
            out.append(legacy)
        try:
            names = sorted(
                n for n in os.listdir(self.root)
                if n.startswith(SHARD_PREFIX) and n.endswith(".jsonl"))
        except OSError:
            names = []
        out.extend(os.path.join(self.root, n) for n in names)
        return out

    def plan(self, *, force: bool = False) -> list[dict]:
        """Eligible shard tails: ``[{path, basename, start, end}]``
        with ``end`` clamped to the last complete line later, at scan
        time."""
        man = load_manifest(self.root)
        now = float(self.clock())
        out = []
        for path in self._live_files():
            try:
                st = os.stat(path)
            except OSError:
                continue
            base = os.path.basename(path)
            start = int((man.get("folded") or {}).get(base, {})
                        .get("bytes", 0))
            tail = int(st.st_size) - start
            age = max(0.0, now - float(st.st_mtime))
            if force and tail > 0:
                out.append({"path": path, "basename": base,
                            "start": start, "end": int(st.st_size)})
            elif self.policy.eligible(tail, age):
                out.append({"path": path, "basename": base,
                            "start": start, "end": int(st.st_size)})
        return out

    # -- scan --------------------------------------------------------------

    @staticmethod
    def _scan_tail(path: str, start: int, end: int):
        """Parse the complete lines of ``path[start:end]``; returns
        ``(records, consumed_bytes, science_count)``.  Records keep
        canary tags (segments store everything); lines that no reader
        would ever surface (corrupt JSON, non-dict, missing ``freq``)
        are folded away — they were already invisible."""
        try:
            with open(path, "rb") as f:
                f.seek(start)
                data = f.read(max(0, end - start))
        except OSError:
            return [], 0, 0
        cut = data.rfind(b"\n")
        if cut < 0:
            return [], 0, 0
        data = data[:cut + 1]
        recs = []
        science = 0
        for raw in data.splitlines():
            raw = raw.strip()
            if not raw:
                continue
            try:
                rec = json.loads(raw)
            except ValueError:
                continue
            if not isinstance(rec, dict) or "freq" not in rec:
                continue
            recs.append(rec)
            if not rec.get("canary"):
                science += 1
        return recs, len(data), science

    # -- the fold ----------------------------------------------------------

    def compact_once(self, *, force: bool = False) -> dict:
        """One full fold; returns a report dict.  ``compacted`` is
        False (with a ``reason``) when there is nothing to do or the
        lock is held elsewhere."""
        t0 = float(self.clock())
        try:
            self._acquire_lock()
        except CompactionLocked:
            return {"compacted": False, "reason": "locked"}
        try:
            return self._compact_locked(force=force, t0=t0)
        finally:
            self._release_lock()

    def _compact_locked(self, *, force: bool, t0: float) -> dict:
        man = load_manifest(self.root)
        self._clean_orphans(man)
        plan = self.plan(force=force)
        if not plan:
            return {"compacted": False, "reason": "no eligible tails"}
        self.fault("scan")

        folded: list[tuple[int, int, dict]] = []  # (shard#, line#, rec)
        per_shard: dict[str, dict] = {}
        for si, item in enumerate(plan):
            recs, consumed, science = self._scan_tail(
                item["path"], item["start"], item["end"])
            if consumed <= 0:
                continue
            per_shard[item["basename"]] = {
                "bytes": item["start"] + consumed,
                "records": science,
            }
            for li, rec in enumerate(recs):
                folded.append((si, li, rec))
        if not per_shard:
            return {"compacted": False, "reason": "no complete lines"}

        # dedup: newest (utc, shard order, line order) wins per cand_id
        keep: dict[str, tuple] = {}
        anonymous: list[dict] = []
        for si, li, rec in folded:
            cid = rec.get("cand_id")
            if not cid:
                anonymous.append(rec)
                continue
            key = (float(rec.get("utc", 0.0)), si, li)
            prev = keep.get(str(cid))
            if prev is None or key > prev[0]:
                keep[str(cid)] = (key, rec)
        records = anonymous + [rec for _, rec in keep.values()]
        duplicates = len(folded) - len(records)

        # ids re-ingested after an earlier seal: the old sealed copy
        # is superseded by this segment
        segs = SegmentSet(self.root)
        supersedes = [cid for cid in keep if segs.contains_cand(cid)]

        report = {
            "compacted": True,
            "records": len(records),
            "duplicates_dropped": duplicates,
            "supersedes": len(supersedes),
            "shards": sorted(per_shard),
        }
        new_man = {
            "v": man.get("v", 1),
            "seq": int(man.get("seq", 0)),
            "segments": list(man.get("segments") or []),
            "folded": dict(man.get("folded") or {}),
        }
        if records:
            seq = int(man.get("seq", 0)) + 1
            entry = write_segment(self.root, seq, records,
                                  supersedes=supersedes,
                                  fault=self.fault)
            entry["canary"] = sum(
                1 for r in records if r.get("canary"))
            new_man["seq"] = seq
            new_man["segments"].append(entry)
            report["segment"] = entry["name"]
        for base, info in per_shard.items():
            prev = new_man["folded"].get(base) or {}
            new_man["folded"][base] = {
                "bytes": int(info["bytes"]),
                "records": int(prev.get("records", 0))
                + int(info["records"]),
            }
        self.fault("pre_manifest")
        write_manifest(self.root, new_man)

        # live-tail coincidence bins restart at the new folded offset
        # (sealed bins now come from the segment sidecars)
        for base, info in per_shard.items():
            update_bins_file(self.root, base, [],
                             covered=int(info["bytes"]),
                             rebuild_from=int(info["bytes"]))

        METRICS.inc("store.compactions")
        METRICS.inc("store.compacted_records", len(records))
        report["duration_s"] = round(float(self.clock()) - t0, 6)
        return report

    def _clean_orphans(self, man: dict) -> None:
        """Remove seg files a crashed run left unpublished.  Safe
        under the lock: nothing outside the manifest is ever opened by
        readers, and only seg-prefixed temp files are touched (bins
        files have live single writers)."""
        d = segment_dir(self.root)
        known = {e.get("name") for e in man.get("segments") or []}
        try:
            names = os.listdir(d)
        except OSError:
            return
        for n in names:
            if not n.startswith(SEG_PREFIX):
                continue
            stem = n.split(".", 1)[0]
            if ".tmp" in n or stem not in known:
                try:
                    os.unlink(os.path.join(d, n))
                except OSError:
                    pass


def shard_tail_sizes(root: str) -> dict[str, int]:
    """Unsealed tail bytes per live shard basename — the health
    plane's shard-size signal (serve/health.py rule_shard_backlog)."""
    man = load_manifest(root)
    out: dict[str, int] = {}
    legacy = os.path.join(root, LEGACY_BASENAME)
    paths = []
    if os.path.exists(legacy):
        paths.append(legacy)
    try:
        paths.extend(
            os.path.join(root, n) for n in sorted(os.listdir(root))
            if n.startswith(SHARD_PREFIX) and n.endswith(".jsonl"))
    except OSError:
        pass
    for path in paths:
        try:
            size = os.path.getsize(path)
        except OSError:
            continue
        base = os.path.basename(path)
        start = int((man.get("folded") or {}).get(base, {})
                    .get("bytes", 0))
        out[base] = max(0, int(size) - start)
    return out
