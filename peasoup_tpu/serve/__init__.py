"""Survey scheduler: multi-observation job queue + worker + store.

The reference pipeline is one-shot — ``src/pipeline_multi.cu:33-81``
dispenses DM trials to GPU workers inside one process, which handles
exactly one filterbank and exits.  A real survey queues thousands of
observations against a fixed device slice and has to survive corrupt
beams, flaky runs and worker crashes.  This package is that layer:

* :mod:`~peasoup_tpu.serve.queue` — durable on-disk job spool
  (``pending/running/done/failed`` with atomic-rename claims, safe
  for multiple worker processes on one machine);
* :mod:`~peasoup_tpu.serve.worker` — long-running driver that claims
  jobs by priority, runs the existing search pipeline on each,
  overlaps the next observation's read+unpack with the current
  search, and buckets filterbank geometry so jitted programs are
  reused across jobs;
* :mod:`~peasoup_tpu.serve.retry` — failure classification, bounded
  exponential backoff, per-job timeout (and the ONE sanctioned
  ``time.sleep`` site — lint rule PSL008);
* :mod:`~peasoup_tpu.serve.store` — append-only cross-run candidate
  store with survey-level dedup/coincidence queries (fleet mode
  shards it per host: ``ShardedCandidateStore``);
* :mod:`~peasoup_tpu.serve.fleet` — the fleet control plane: one
  worker per host of a multi-host slice, heartbeat leases on claims,
  automatic dead-host recovery, per-host store shards and the
  aggregated fleet report;
* :mod:`~peasoup_tpu.serve.health` — the fleet health plane: typed
  ok/warn/crit rules + an SLO summary evaluated over the live
  per-host telemetry time-series (obs/telemetry.py shards), embedded
  in ``fleet_report.json`` v2 and surfaced by the ``health`` verb;
* :mod:`~peasoup_tpu.serve.supervisor` — the self-healing plane: a
  control loop that maps health findings to typed, rate-limited
  actions (reap dead hosts' leases, spawn/retire real fleet-worker
  subprocesses, retune ``--batch``) via the ``@supervisor_action``
  registry, with per-action cooldowns and a global actions-per-window
  cap;
* :mod:`~peasoup_tpu.serve.cli` — ``python -m peasoup_tpu.serve``
  with ``submit`` / ``worker`` / ``fleet-worker`` / ``supervise`` /
  ``admission`` / ``status`` (``--watch`` live dashboard) /
  ``health`` / ``timeline`` (per-job lifecycle waterfall from
  obs/timeline.py marks) / ``coincidence`` / ``requeue`` verbs.

Admission control lives in :mod:`~peasoup_tpu.serve.queue`: per-tenant
submits, token-bucket rate limits and weighted fair-share claim
ordering, gated by a backlog knee that raises a typed
:class:`~peasoup_tpu.errors.AdmissionError`.
"""

from .fleet import (
    FleetMembership,
    FleetWorker,
    LeaseHeartbeat,
    fleet_report,
    write_fleet_report,
)
from .health import (
    HealthContext,
    HealthFinding,
    build_context,
    evaluate,
    evaluate_spool,
    health_rule,
    slo_summary,
)
from .queue import (
    DEFAULT_TENANT,
    LEASE_EXPIRED,
    AdmissionPolicy,
    JobRecord,
    JobSpool,
    TenantPolicy,
)
from .retry import (
    QUARANTINE,
    RETRY,
    BackoffPolicy,
    JobTimeoutError,
    abandoned_count,
    classify_failure,
)
from .store import CandidateStore, ShardedCandidateStore
from .supervisor import (
    ACTIONS,
    ActionSpec,
    Supervisor,
    WorkerPool,
    supervisor_action,
)
from .worker import SurveyWorker
from ..errors import AdmissionError

__all__ = [
    "JobRecord",
    "JobSpool",
    "LEASE_EXPIRED",
    "DEFAULT_TENANT",
    "AdmissionError",
    "AdmissionPolicy",
    "TenantPolicy",
    "abandoned_count",
    "ACTIONS",
    "ActionSpec",
    "Supervisor",
    "WorkerPool",
    "supervisor_action",
    "BackoffPolicy",
    "JobTimeoutError",
    "classify_failure",
    "QUARANTINE",
    "RETRY",
    "CandidateStore",
    "ShardedCandidateStore",
    "SurveyWorker",
    "FleetMembership",
    "FleetWorker",
    "LeaseHeartbeat",
    "fleet_report",
    "write_fleet_report",
    "HealthContext",
    "HealthFinding",
    "build_context",
    "evaluate",
    "evaluate_spool",
    "health_rule",
    "slo_summary",
]
