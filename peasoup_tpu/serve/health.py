"""Fleet health plane: typed rules + SLO summary over live telemetry.

``obs/telemetry.py`` gives every host a continuous time-series shard;
this module is the judgement layer on top — the part that turns raw
samples into "is the fleet healthy, and if not, what do I run".  The
shape follows the repo's analysis engine (``analysis/rules.py``):

* :class:`HealthFinding` — one typed verdict (``rule``, ``severity``
  ok/warn/crit, human message, machine ``data``), JSON-serialisable;
* :class:`HealthContext` — everything a rule may look at, assembled
  once by :func:`build_context`: the merged time-series (all samples
  + the recent evaluation window), latest sample per host, queue
  depths, running-job lease holders, and the bench-history ledger's
  ``kind:"serve"`` (plus ``"anomaly"`` — ISSUE 16) records;
* each rule is a small **pure function** ``rule(ctx) ->
  [HealthFinding]`` registered via the :func:`health_rule` decorator —
  adding a rule is writing one function (see CONTRIBUTING.md);
* :func:`slo_summary` — queue-wait and job-duration p50/p95 (weighted
  by per-sample observation counts) against configurable targets;
* :func:`evaluate` — run every rule, fold in the SLO verdict, and
  return the health report dict the ``health`` CLI verb prints (and
  ``fleet_report.json`` v2 embeds).

Severity semantics (what the operator should do):

* **ok** — nothing to do;
* **warn** — worth a look, the fleet is still making progress;
* **crit** — jobs are at risk or stalled; the ``health`` verb exits
  nonzero so CI/cron can page on it.

The stale-host rule encodes the fleet's lease model: a silent host is
only *critical* while it still holds running-job leases (those jobs
are going nowhere until ``requeue --expired`` reaps them); silent
with pending work waiting is a warning (capacity loss); silent with
an empty queue and no leases is a clean departure — drained workers
exit, that's normal, and the fleet reports healthy again after
recovery.
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import asdict, dataclass, field

from ..obs.history import default_ledger_path, load_history
from ..utils.atomicio import atomic_write_json
from ..obs.telemetry import (
    DEFAULT_INTERVAL_S,
    latest_by_host,
    read_samples,
)
from .compaction import DEFAULT_MIN_BYTES, shard_tail_sizes
from .queue import JobSpool

OK = "ok"
WARN = "warn"
CRIT = "crit"

_SEVERITY_RANK = {OK: 0, WARN: 1, CRIT: 2}

#: default evaluation window (seconds of recent samples rules sum over)
DEFAULT_WINDOW_S = 300.0

#: a host is stale after this many missed sampling intervals
DEFAULT_STALE_AFTER = 5.0

#: default SLO targets (seconds); override per-key via ``--slo`` or the
#: ``slo=`` argument of :func:`build_context`
DEFAULT_SLO = {
    "queue_wait_p50_s": 60.0,
    "queue_wait_p95_s": 600.0,
    "job_p50_s": 900.0,
    "job_p95_s": 3600.0,
    # end-to-end sojourn (submit->done, timeline-derived): roughly
    # queue-wait + job targets — the latency a SUBMITTER experiences
    "sojourn_p50_s": 960.0,
    "sojourn_p95_s": 4200.0,
    # science-query latency over the survey store (ISSUE 20): the
    # query service's per-request kind:"query" ledger records; in
    # MILLISECONDS — an indexed read is three orders of magnitude
    # below the job-latency scale and its SLO should say so
    "query_p50_ms": 250.0,
    "query_p95_ms": 2000.0,
}

#: retry/quarantine/reap thresholds for the spike rules (per window)
RETRY_WARN = 3
RETRY_CRIT = 10
QUARANTINE_CRIT = 3
REAP_CRIT = 3


@dataclass(frozen=True)
class HealthFinding:
    """One rule's verdict on one subject (a host, or the fleet)."""

    rule: str
    severity: str
    message: str
    host: str = ""
    data: dict = field(default_factory=dict)

    def to_obj(self) -> dict:
        return asdict(self)


def worst_severity(severities) -> str:
    worst = OK
    for sev in severities:
        if _SEVERITY_RANK.get(sev, 0) > _SEVERITY_RANK[worst]:
            worst = sev
    return worst


@dataclass
class HealthContext:
    """Everything the rules see — plain data, so every rule is a pure
    function and its fixtures are literal dicts."""

    now: float
    samples: list[dict]           # full merged time-series (ts-sorted)
    recent: list[dict]            # samples within the window
    latest: dict[str, dict]       # newest sample per host
    queue: dict[str, int]         # spool state counts
    running: list[dict]           # [{"job_id", "host"}] lease holders
    ledger: list[dict]            # serve/loadgen/sensitivity history recs
    window_s: float = DEFAULT_WINDOW_S
    stale_after: float = DEFAULT_STALE_AFTER
    slo: dict = field(default_factory=lambda: dict(DEFAULT_SLO))
    #: pending-queue geometry mix: overrides-fingerprint -> job count
    #: (cheap, header-free proxy for the batcher's bucket key), capped
    #: at _BUCKET_SCAN_CAP records so health stays O(small)
    pending_buckets: dict = field(default_factory=dict)
    #: candidate-store unsealed tail bytes per shard basename
    #: (serve/compaction.shard_tail_sizes) — the shard-size signal the
    #: compaction rule and supervisor action key on (ISSUE 20)
    store_tails: dict = field(default_factory=dict)


def default_ts_dir(spool: JobSpool) -> str:
    """The telemetry shard directory — the spool's ``fleet/`` dir
    (same place as the per-host status snapshots; the ``ts-`` prefix
    and ``.jsonl`` suffix keep the two namespaces disjoint)."""
    return os.path.join(spool.root, "fleet")


#: at most this many pending records are read for the bucket mix
_BUCKET_SCAN_CAP = 256


def pending_bucket_mix(spool: JobSpool,
                       cap: int = _BUCKET_SCAN_CAP) -> dict:
    """Count pending jobs per overrides-fingerprint (sorted key=value
    repr).  Jobs with identical overrides are *candidates* for one
    batched dispatch (the worker's bucket key adds the data header,
    which health deliberately does not read — no I/O amplification);
    a dominant fingerprint therefore bounds the achievable batch."""
    mix: dict = {}
    for rec in spool.pending_jobs()[:max(int(cap), 0)]:
        key = ",".join(f"{k}={v!r}" for k, v in
                       sorted((rec.overrides or {}).items())) or "-"
        mix[key] = mix.get(key, 0) + 1
    return mix


def build_context(spool: JobSpool, *, ts_dir: str | None = None,
                  ledger_path: str | None = None,
                  now: float | None = None,
                  window_s: float = DEFAULT_WINDOW_S,
                  stale_after: float = DEFAULT_STALE_AFTER,
                  slo: dict | None = None) -> HealthContext:
    """Assemble the rules' world view from the spool + shards +
    ledger.  ``now`` is injectable for tests; every reader involved is
    torn-tail tolerant, so a half-dead fleet still evaluates."""
    now = time.time() if now is None else float(now)
    ts_dir = ts_dir or default_ts_dir(spool)
    samples = read_samples(ts_dir)
    recent = [s for s in samples if s.get("ts", 0) >= now - window_s]
    running = []
    for rec in spool.jobs("running"):
        lease = spool.lease_info(rec.job_id) or {}
        running.append({"job_id": rec.job_id,
                        "host": rec.host or lease.get("host", "")})
    targets = dict(DEFAULT_SLO)
    targets.update(slo or {})
    return HealthContext(
        now=now,
        samples=samples,
        recent=recent,
        latest=latest_by_host(ts_dir),
        queue=spool.counts(),
        running=running,
        ledger=load_history(ledger_path or default_ledger_path(),
                            kinds=("serve", "loadgen", "sensitivity",
                                   "anomaly", "query")),
        window_s=float(window_s),
        stale_after=float(stale_after),
        slo=targets,
        pending_buckets=pending_bucket_mix(spool),
        store_tails=shard_tail_sizes(spool.root),
    )


# -- rule registry ---------------------------------------------------------

RULES: list = []


def health_rule(fn):
    """Register a health rule: ``fn(ctx) -> list[HealthFinding]``.
    Rules run in registration order; a crashing rule becomes a warn
    finding, never an evaluation failure."""
    RULES.append(fn)
    return fn


def _recent_counter(ctx: HealthContext, name: str) -> int:
    """Sum of a counter's per-sample deltas across the window."""
    return sum(int(s.get("counters", {}).get(name, 0))
               for s in ctx.recent)


# -- rules -----------------------------------------------------------------

@health_rule
def rule_stale_host(ctx: HealthContext) -> list[HealthFinding]:
    """A host that stopped sampling: crit while it holds running-job
    leases, warn if pending work is waiting for capacity, ok when it
    departed cleanly (drained workers exit — that is normal)."""
    leases: dict[str, int] = {}
    for job in ctx.running:
        host = job.get("host") or "?"
        leases[host] = leases.get(host, 0) + 1
    hosts = set(ctx.latest) | {h for h in leases if h != "?"}
    if not hosts:
        return [HealthFinding(
            "stale_host", OK, "no telemetry shards yet",
            data={"hosts": 0})]
    pending = int(ctx.queue.get("pending", 0))
    out = []
    for host in sorted(hosts):
        sample = ctx.latest.get(host)
        interval = (float(sample.get("interval_s", DEFAULT_INTERVAL_S))
                    if sample else DEFAULT_INTERVAL_S)
        age = (ctx.now - float(sample.get("ts", 0.0))
               if sample else float("inf"))
        threshold = ctx.stale_after * interval
        held = leases.get(host, 0)
        data = {"age_s": round(age, 3) if sample else None,
                "threshold_s": round(threshold, 3), "leases": held}
        if age <= threshold:
            out.append(HealthFinding(
                "stale_host", OK,
                f"sampled {age:.1f}s ago", host=host, data=data))
        elif held:
            out.append(HealthFinding(
                "stale_host", CRIT,
                f"silent for {age:.1f}s (> {threshold:.1f}s) while "
                f"holding {held} running-job lease(s) — run "
                f"'requeue --expired' to recover them",
                host=host, data=data))
        elif pending:
            out.append(HealthFinding(
                "stale_host", WARN,
                f"silent for {age:.1f}s with {pending} pending "
                f"job(s) waiting for capacity", host=host, data=data))
        else:
            out.append(HealthFinding(
                "stale_host", OK,
                "silent, but holds no leases and the queue is empty "
                "(departed cleanly)", host=host, data=data))
    return out


@health_rule
def rule_queue_backlog(ctx: HealthContext) -> list[HealthFinding]:
    """Pending depth trending up across the window: warn while jobs
    still complete, crit when the backlog grows and nothing drains."""
    series = [int(s["queue"]["pending"]) for s in ctx.recent
              if isinstance(s.get("queue"), dict)
              and "pending" in s["queue"]]
    if len(series) < 3:
        return [HealthFinding(
            "queue_backlog", OK,
            f"insufficient queue samples in window ({len(series)})",
            data={"samples": len(series)})]
    first, last = series[0], series[-1]
    grew = last - first
    data = {"first": first, "last": last, "grew": grew,
            "samples": len(series)}
    if grew >= 2 and last > 0:
        drained = _recent_counter(ctx, "scheduler.succeeded")
        data["drained_in_window"] = drained
        if drained == 0:
            return [HealthFinding(
                "queue_backlog", CRIT,
                f"backlog grew {first} -> {last} with ZERO jobs "
                f"completed in the window — workers stalled or absent",
                data=data)]
        return [HealthFinding(
            "queue_backlog", WARN,
            f"backlog grew {first} -> {last} in the window "
            f"(submissions outpacing {drained} completion(s))",
            data=data)]
    return [HealthFinding(
        "queue_backlog", OK,
        f"backlog stable ({first} -> {last})", data=data)]


@health_rule
def rule_retry_spike(ctx: HealthContext) -> list[HealthFinding]:
    """Quarantine/retry-rate spikes in the window: bad inputs or a
    systematically failing fleet."""
    retried = _recent_counter(ctx, "scheduler.retried")
    quarantined = _recent_counter(ctx, "scheduler.quarantined")
    exhausted = _recent_counter(ctx, "scheduler.exhausted")
    terminal = quarantined + exhausted
    data = {"retried": retried, "quarantined": quarantined,
            "exhausted": exhausted}
    if terminal >= QUARANTINE_CRIT or retried >= RETRY_CRIT:
        return [HealthFinding(
            "retry_spike", CRIT,
            f"{terminal} job(s) quarantined/exhausted and {retried} "
            f"retried in the window — inputs or workers are "
            f"systematically failing", data=data)]
    if terminal > 0 or retried >= RETRY_WARN:
        return [HealthFinding(
            "retry_spike", WARN,
            f"{terminal} terminal failure(s), {retried} retry(ies) "
            f"in the window", data=data)]
    return [HealthFinding(
        "retry_spike", OK, "no failure spike in the window",
        data=data)]


@health_rule
def rule_throughput_regression(ctx: HealthContext) -> list[HealthFinding]:
    """Live fleet ``jobs_per_hour`` against the ledger's serve-record
    median — the survey-throughput regression check, evaluated on the
    running fleet instead of post-hoc."""
    baseline_vals = sorted(
        float(r.get("metrics", {}).get("jobs_per_hour", 0.0))
        for r in ctx.ledger
        if r.get("metrics", {}).get("jobs_per_hour", 0.0) > 0)
    if len(baseline_vals) < 3:
        return [HealthFinding(
            "throughput_regression", OK,
            f"not enough serve ledger records for a baseline "
            f"({len(baseline_vals)} < 3)",
            data={"records": len(baseline_vals)})]
    mid = len(baseline_vals) // 2
    median = (baseline_vals[mid] if len(baseline_vals) % 2
              else 0.5 * (baseline_vals[mid - 1] + baseline_vals[mid]))
    current = 0.0
    seen = False
    for sample in ctx.latest.values():
        jph = sample.get("gauges", {}).get("scheduler.jobs_per_hour")
        if jph is not None:
            current += float(jph)
            seen = True
    data = {"median_jobs_per_hour": round(median, 3),
            "current_jobs_per_hour": round(current, 3),
            "records": len(baseline_vals)}
    if not seen:
        return [HealthFinding(
            "throughput_regression", OK,
            "no live jobs_per_hour gauge yet (fleet idle or starting)",
            data=data)]
    if current < 0.25 * median:
        return [HealthFinding(
            "throughput_regression", CRIT,
            f"fleet at {current:.1f} jobs/h vs ledger median "
            f"{median:.1f} (<25%)", data=data)]
    if current < 0.5 * median:
        return [HealthFinding(
            "throughput_regression", WARN,
            f"fleet at {current:.1f} jobs/h vs ledger median "
            f"{median:.1f} (<50%)", data=data)]
    return [HealthFinding(
        "throughput_regression", OK,
        f"fleet at {current:.1f} jobs/h vs ledger median "
        f"{median:.1f}", data=data)]


@health_rule
def rule_hbm_watermark(ctx: HealthContext) -> list[HealthFinding]:
    """Per-host HBM high-water against the plan's budget: >90% warn,
    >98% crit.  Hosts without a budget gauge (CPU runs, old samples)
    evaluate ok — unknown is not unhealthy."""
    out = []
    for host in sorted(ctx.latest):
        gauges = ctx.latest[host].get("gauges", {})
        high = gauges.get("hbm.high_water_bytes")
        budget = gauges.get("hbm.budget_bytes")
        if not budget or high is None:
            continue
        frac = float(high) / float(budget)
        data = {"high_water_bytes": high, "budget_bytes": budget,
                "fraction": round(frac, 4)}
        if frac >= 0.98:
            sev, note = CRIT, "next escalation will OOM"
        elif frac >= 0.90:
            sev, note = WARN, "approaching the HBM budget"
        else:
            sev, note = OK, "within budget"
        out.append(HealthFinding(
            "hbm_watermark", sev,
            f"HBM high-water at {100 * frac:.1f}% of budget ({note})",
            host=host, data=data))
    if not out:
        return [HealthFinding(
            "hbm_watermark", OK, "no HBM budget gauges reported",
            data={})]
    return out


@health_rule
def rule_lease_reap_burst(ctx: HealthContext) -> list[HealthFinding]:
    """Lease reaps in the window mean hosts died mid-job: one is a
    warning, a burst means the fleet is losing machines."""
    reaped = _recent_counter(ctx, "scheduler.lease_reaped")
    data = {"reaped": reaped}
    if reaped >= REAP_CRIT:
        return [HealthFinding(
            "lease_reap_burst", CRIT,
            f"{reaped} lease(s) reaped in the window — multiple hosts "
            f"dying mid-job", data=data)]
    if reaped > 0:
        return [HealthFinding(
            "lease_reap_burst", WARN,
            f"{reaped} lease(s) reaped in the window (a host died; "
            f"its jobs were recovered)", data=data)]
    return [HealthFinding(
        "lease_reap_burst", OK, "no lease reaps in the window",
        data=data)]


@health_rule
def rule_device_duty_cycle(ctx: HealthContext) -> list[HealthFinding]:
    """Per-host device duty cycle (ISSUE 11): device seconds per wall
    second from the span ledger.  A LOW duty cycle while jobs are
    still queued means the accelerators are idling behind host work —
    the round-trip wall the dispatch pipeline exists to remove: <50%
    warn, <20% crit.  With an empty queue the hosts are expected to
    idle, so the rule reports ok regardless of the gauge; hosts
    without the gauge (old samples, non-search workers) are skipped —
    unknown is not unhealthy."""
    pending = int(ctx.queue.get("pending", 0) or 0)
    out = []
    for host in sorted(ctx.latest):
        gauges = ctx.latest[host].get("gauges", {})
        duty = gauges.get("device_duty_cycle")
        if duty is None:
            continue
        duty = float(duty)
        data = {"device_duty_cycle": round(duty, 4),
                "queue_pending": pending}
        if pending <= 0:
            out.append(HealthFinding(
                "device_duty_cycle", OK,
                f"duty cycle {duty:.2f} with an empty queue (idle by "
                f"design)", host=host, data=data))
        elif duty < 0.2:
            out.append(HealthFinding(
                "device_duty_cycle", CRIT,
                f"duty cycle {duty:.2f} with {pending} job(s) queued "
                f"— devices starved behind host work", host=host,
                data=data))
        elif duty < 0.5:
            out.append(HealthFinding(
                "device_duty_cycle", WARN,
                f"duty cycle {duty:.2f} with {pending} job(s) queued "
                f"— dispatch pipeline not keeping devices fed",
                host=host, data=data))
        else:
            out.append(HealthFinding(
                "device_duty_cycle", OK,
                f"duty cycle {duty:.2f} with {pending} job(s) queued",
                host=host, data=data))
    if not out:
        return [HealthFinding(
            "device_duty_cycle", OK,
            "no device_duty_cycle gauges reported", data={})]
    return out


@health_rule
def rule_loadgen_saturation(ctx: HealthContext) -> list[HealthFinding]:
    """Live arrival rate vs the measured saturation knee (ISSUE 12).

    ``tools/loadgen.py`` sweeps offered rates against a real fleet and
    records the knee — the highest rate the fleet still kept up with —
    as a ``kind:"loadgen"`` ledger record.  When live submissions
    (``scheduler.submitted`` deltas over the telemetry window) arrive
    FASTER than that measured capacity, the queue is growing without
    bound by construction: warn above the knee, crit at 1.5x.  No
    loadgen record means no baseline — ok, not unknown-unhealthy.
    """
    knee = None
    for r in ctx.ledger:
        if r.get("kind") != "loadgen":
            continue
        val = r.get("metrics", {}).get("knee_throughput_per_s")
        if isinstance(val, (int, float)):
            knee = float(val)  # last record wins (newest sweep)
    if knee is None:
        return [HealthFinding(
            "loadgen_saturation", OK,
            "no loadgen saturation baseline in the ledger (run "
            "'make loadgen-smoke' or tools/loadgen.py to measure one)",
            data={"knee_throughput_per_s": None})]
    if knee <= 0:
        return [HealthFinding(
            "loadgen_saturation", OK,
            "loadgen record carries no positive knee throughput",
            data={"knee_throughput_per_s": knee})]
    submits = _recent_counter(ctx, "scheduler.submitted")
    ts = [float(s.get("ts", 0.0)) for s in ctx.recent]
    span = max(ts) - min(ts) if len(ts) >= 2 else ctx.window_s
    if span <= 0:
        span = ctx.window_s
    rate = submits / span if span > 0 else 0.0
    ratio = rate / knee
    data = {"arrival_rate_per_s": round(rate, 6),
            "knee_throughput_per_s": round(knee, 6),
            "ratio": round(ratio, 4), "submits": submits,
            "span_s": round(span, 3)}
    if ratio >= 1.5:
        return [HealthFinding(
            "loadgen_saturation", CRIT,
            f"arrival rate {rate:.3f}/s is {ratio:.2f}x the measured "
            f"saturation knee ({knee:.3f}/s) — queue growth is "
            f"unbounded, shed load or add workers", data=data)]
    if ratio > 1.0:
        return [HealthFinding(
            "loadgen_saturation", WARN,
            f"arrival rate {rate:.3f}/s exceeds the measured "
            f"saturation knee ({knee:.3f}/s)", data=data)]
    return [HealthFinding(
        "loadgen_saturation", OK,
        f"arrival rate {rate:.3f}/s within the measured knee "
        f"({knee:.3f}/s)", data=data)]


@health_rule
def rule_canary_recovery(ctx: HealthContext) -> list[HealthFinding]:
    """Known-answer canary jobs (ISSUE 14): a missed canary means the
    pipeline is NOT recovering a signal it is known to contain — a
    sensitivity outage no throughput metric can see.

    The verdict keys off the NEWEST telemetry sample that carries any
    canary counter delta, so one missed canary goes crit and STAYS
    crit until a later drain recovers a canary again (the operator's
    clean re-run produces a newer recovered-only sample and the fleet
    reports healthy).  Secondary check: the window's live recovery
    fraction against the ledger median of ``kind:"sensitivity"``
    sweeps — a soft regression warns before canaries start missing
    outright.  No canary traffic at all is ok, not unknown-unhealthy
    (canaries are opt-in via ``submit --canary`` / loadgen
    ``canary_fraction``).
    """
    last = None
    for s in ctx.samples:  # ts-sorted; last hit wins
        counters = s.get("counters", {})
        rec = int(counters.get("canary.recovered", 0))
        mis = int(counters.get("canary.missed", 0))
        if rec + mis > 0:
            last = {"ts": float(s.get("ts", 0.0)), "recovered": rec,
                    "missed": mis, "host": str(s.get("host", ""))}
    if last is None:
        return [HealthFinding(
            "canary_recovery", OK,
            "no canary activity in the telemetry (submit known-answer "
            "jobs with 'submit --canary' to probe sensitivity)",
            data={"canaries": 0})]
    if last["missed"] > 0:
        return [HealthFinding(
            "canary_recovery", CRIT,
            f"latest canary drain MISSED {last['missed']} injected "
            f"pulsar(s) (recovered {last['recovered']}) — the search "
            f"is not finding signals it is known to contain",
            host=last["host"], data=last)]
    recovered = _recent_counter(ctx, "canary.recovered")
    missed = _recent_counter(ctx, "canary.missed")
    total = recovered + missed
    fraction = recovered / total if total else 1.0
    data = dict(last)
    data.update({"window_recovered": recovered,
                 "window_missed": missed,
                 "window_recovery_fraction": round(fraction, 4)})
    baseline_vals = sorted(
        float(r.get("metrics", {}).get("recovery_fraction", -1.0))
        for r in ctx.ledger
        if r.get("kind") == "sensitivity"
        and r.get("metrics", {}).get("recovery_fraction", -1.0) >= 0)
    if len(baseline_vals) >= 3 and total > 0:
        mid = len(baseline_vals) // 2
        median = (baseline_vals[mid] if len(baseline_vals) % 2
                  else 0.5 * (baseline_vals[mid - 1]
                              + baseline_vals[mid]))
        data["median_recovery_fraction"] = round(median, 4)
        if fraction < 0.8 * median:
            return [HealthFinding(
                "canary_recovery", WARN,
                f"window canary recovery {fraction:.2f} below 80% of "
                f"the sensitivity-sweep ledger median ({median:.2f}) "
                f"— sensitivity regressing", data=data)]
    return [HealthFinding(
        "canary_recovery", OK,
        f"latest canary drain recovered {last['recovered']} "
        f"injected pulsar(s), none missed", data=data)]


@health_rule
def rule_batch_mix(ctx: HealthContext) -> list[HealthFinding]:
    """Bucket-mix drift: the pending queue's geometry mix no longer
    matches the workers' configured ``--batch``.

    Warn-only (a mis-sized batch wastes throughput, it does not lose
    jobs): (1) a dominant same-overrides bucket much deeper than the
    dispatch batch means batching upside is being left on the table;
    (2) a batch > 1 whose windowed mean fill is under half the batch
    means the mix fragmented and the batch wait is pure overhead.
    ``data.suggest_batch`` carries the retune hint the supervisor's
    ``retune_batch`` action applies to respawned workers."""
    pending = sum(int(n) for n in ctx.pending_buckets.values())
    if pending <= 0:
        return [HealthFinding(
            "batch_mix", OK, "no pending jobs to batch", data={})]
    dominant = max(ctx.pending_buckets.values())
    batches = [s.get("gauges", {}).get("search.batch")
               for s in ctx.latest.values()]
    batches = [int(b) for b in batches if b]
    batch = max(batches) if batches else 1
    dispatches = _recent_counter(ctx, "scheduler.batched_dispatches")
    fill = _recent_counter(ctx, "scheduler.batch_fill")
    data = {"pending": pending, "dominant_bucket": int(dominant),
            "buckets": len(ctx.pending_buckets), "batch": batch,
            "dispatches_in_window": dispatches,
            "fill_in_window": fill}
    if dominant >= max(2 * batch, 4):
        data["suggest_batch"] = int(min(dominant, 8))
        return [HealthFinding(
            "batch_mix", WARN,
            f"dominant pending bucket holds {dominant} same-geometry "
            f"job(s) but workers dispatch batch={batch} — retune "
            f"--batch toward {data['suggest_batch']}", data=data)]
    if batch > 1 and dispatches >= 3 and fill < 0.5 * batch * dispatches:
        mean_fill = fill / dispatches
        data["suggest_batch"] = max(1, round(mean_fill))
        return [HealthFinding(
            "batch_mix", WARN,
            f"batch={batch} but windowed mean fill is "
            f"{mean_fill:.1f} — the mix fragmented; retune --batch "
            f"toward {data['suggest_batch']}", data=data)]
    return [HealthFinding(
        "batch_mix", OK,
        f"dominant bucket {dominant} vs batch {batch}", data=data)]


#: windowed recompiles on an ALREADY-SEEN (program, geometry, device)
#: key before the fleet is re-paying compiles it should replay from
#: cache: a couple may be legitimate (donor programs evicted, an
#: escalated re-search), a storm means the program-reuse bucketing or
#: the persistent compile cache is broken (ISSUE 18)
COMPILE_STORM_WARN = 3
COMPILE_STORM_CRIT = 10


@health_rule
def rule_compile_storm(ctx: HealthContext) -> list[HealthFinding]:
    """Recompile storm (ISSUE 18): the compile ledger's attribution
    counters ride the telemetry stream, so a fleet re-paying XLA
    compiles for geometry fingerprints it has ALREADY compiled this
    process is visible here without reading compiles.jsonl.  A warm
    worker should replay cached programs — recompiles on a seen key
    mean the geometry bucketing regressed, the jit cache is thrashing,
    or the persistent compile cache silently disengaged.  No samples /
    no counter = ok (unknown is not unhealthy); ``obs compiles``
    answers WHICH geometry paid."""
    recompiles = _recent_counter(ctx, "jit.recompiles_seen_geometry")
    attributed = _recent_counter(ctx, "jit.compiles_attributed")
    data = {"recompiles_seen_geometry": recompiles,
            "compiles_attributed": attributed}
    if recompiles >= COMPILE_STORM_CRIT:
        return [HealthFinding(
            "compile_storm", CRIT,
            f"{recompiles} recompile(s) of already-seen geometry in "
            f"the window — program reuse is broken; see `obs "
            f"compiles` for the paying geometry", data=data)]
    if recompiles >= COMPILE_STORM_WARN:
        return [HealthFinding(
            "compile_storm", WARN,
            f"{recompiles} recompile(s) of already-seen geometry in "
            f"the window (cache miss or bucketing drift)", data=data)]
    return [HealthFinding(
        "compile_storm", OK,
        f"{recompiles} recompile(s) of seen geometry in the window "
        f"({attributed} attributed compile(s))", data=data)]


#: recent anomaly records meaning "the fleet is drifting" vs "on fire"
ANOMALY_CRIT_COUNT = 3


@health_rule
def rule_anomaly(ctx: HealthContext) -> list[HealthFinding]:
    """Typed ``kind:"anomaly"`` ledger records (ISSUE 16): the
    baseline plane (``obs/baseline.py``) appends one per statistical
    departure — a stage outside its median/MAD band, a fleet-presence
    drop.  A *recent* anomaly (ts inside the window) is a warning the
    supervisor can observe exactly like duty-cycle collapse; several,
    or one already rated ``crit``, is critical.  Historical anomalies
    age out as ``now`` moves on — the finding clears after recovery
    without anyone deleting records."""
    from ..obs.warehouse import _iso_to_epoch

    anomalies = [r for r in ctx.ledger if r.get("kind") == "anomaly"]
    recent = []
    for rec in anomalies:
        ts = _iso_to_epoch(rec.get("ts"))
        if ts is not None and ts >= ctx.now - ctx.window_s:
            recent.append(rec)
    data = {"recent": len(recent), "total": len(anomalies)}
    if recent:
        keys = sorted({
            f"{r.get('key', {}).get('stage', '?')}"
            f"@{r.get('key', {}).get('host', '') or 'fleet'}"
            for r in recent})
        data["keys"] = keys
        crit = (len(recent) >= ANOMALY_CRIT_COUNT
                or any(r.get("severity") == "crit" for r in recent))
        return [HealthFinding(
            "anomaly", CRIT if crit else WARN,
            f"{len(recent)} baseline anomaly record(s) in the window "
            f"({', '.join(keys[:4])})", data=data)]
    return [HealthFinding(
        "anomaly", OK,
        f"no baseline anomalies in the window "
        f"({len(anomalies)} historical)", data=data)]


#: distillation-funnel collapse (ISSUE 19): how far the newest drain's
#: absorbed fraction may sit from the ledger median before it counts
#: as a behaviour shift, and the pass-fraction floor/baseline for the
#: hard-collapse verdict
DISTILL_ABSORBED_BAND = 0.15
DISTILL_PASS_CRIT = 0.01
DISTILL_PASS_BASELINE = 0.10


def _median(values: list[float]) -> float:
    values = sorted(values)
    mid = len(values) // 2
    return (values[mid] if len(values) % 2
            else 0.5 * (values[mid - 1] + values[mid]))


@health_rule
def rule_distill_collapse(ctx: HealthContext) -> list[HealthFinding]:
    """Distillation-funnel collapse (ISSUE 19): the lineage ledger's
    exact selection-funnel rates ride each drain's serve record
    (``lineage_pass_frac`` = emitted/decoded, ``lineage_absorbed_frac``
    = absorbed/decoded), so a *distillation behaviour shift* — a
    mistuned harmonic tolerance silently eating real candidates, or a
    broken distiller passing everything through — is a ledger
    comparison, not a post-mortem.  Warn when the newest drain's
    absorbed fraction departs the baseline band around the ledger
    median; crit when the funnel hard-collapses: almost nothing
    (<1%) survives where the baseline passes >10%.  Fewer than 3
    funnel-bearing records = no baseline = ok."""
    recs = [r for r in ctx.ledger
            if r.get("kind") == "serve"
            and float(r.get("metrics", {})
                      .get("lineage_decoded", 0) or 0) > 0]
    if len(recs) < 3:
        return [HealthFinding(
            "distill_collapse", OK,
            f"not enough funnel-bearing serve records for a baseline "
            f"({len(recs)} < 3)", data={"records": len(recs)})]
    head = recs[-1]["metrics"]
    base = [r["metrics"] for r in recs[:-1]]
    head_pass = float(head.get("lineage_pass_frac", 0.0) or 0.0)
    head_abs = float(head.get("lineage_absorbed_frac", 0.0) or 0.0)
    med_pass = _median([float(m.get("lineage_pass_frac", 0.0) or 0.0)
                        for m in base])
    med_abs = _median([float(m.get("lineage_absorbed_frac", 0.0) or 0.0)
                       for m in base])
    data = {"pass_frac": round(head_pass, 4),
            "absorbed_frac": round(head_abs, 4),
            "median_pass_frac": round(med_pass, 4),
            "median_absorbed_frac": round(med_abs, 4),
            "band": DISTILL_ABSORBED_BAND,
            "records": len(recs)}
    if head_pass < DISTILL_PASS_CRIT and med_pass > DISTILL_PASS_BASELINE:
        return [HealthFinding(
            "distill_collapse", CRIT,
            f"selection funnel collapsed: {100 * head_pass:.2f}% of "
            f"decoded peaks survive distillation where the ledger "
            f"baseline passes {100 * med_pass:.1f}% — a distiller "
            f"tolerance is eating the science; run `why` on a known "
            f"candidate to see which rule absorbs it", data=data)]
    if abs(head_abs - med_abs) > DISTILL_ABSORBED_BAND:
        return [HealthFinding(
            "distill_collapse", WARN,
            f"absorbed fraction {head_abs:.2f} departed the baseline "
            f"band ({med_abs:.2f} +/- {DISTILL_ABSORBED_BAND:.2f}) — "
            f"distillation behaviour shifted since the ledger "
            f"baseline", data=data)]
    return [HealthFinding(
        "distill_collapse", OK,
        f"funnel pass {head_pass:.2f} / absorbed {head_abs:.2f} vs "
        f"baseline medians {med_pass:.2f} / {med_abs:.2f}",
        data=data)]


@health_rule
def rule_query_latency(ctx: HealthContext) -> list[HealthFinding]:
    """Science-query latency SLO (ISSUE 20): the query service
    appends one ``kind:"query"`` ledger record per request
    (serve/query_service.py).  Compare the window's p50/p95 against
    the ``query_p50_ms``/``query_p95_ms`` targets: warn when p50
    breaches its target, crit when p95 breaches (tail latency is what
    an interactive science session feels) or p50 blows through the
    p95 budget.  No query traffic in the window = ok — an idle
    service is not an unhealthy one."""
    lat = [
        float(r["metrics"]["query_latency_ms"])
        for r in ctx.ledger
        if r.get("kind") == "query"
        and isinstance(r.get("metrics", {}).get("query_latency_ms"),
                       (int, float))
        and float(r.get("utc", 0.0)) >= ctx.now - ctx.window_s
    ]
    if not lat:
        return [HealthFinding(
            "query_latency", OK,
            "no query traffic in the window", data={"requests": 0})]
    p50 = percentile(lat, 0.50)
    p95 = percentile(lat, 0.95)
    t50 = float(ctx.slo.get("query_p50_ms",
                            DEFAULT_SLO["query_p50_ms"]))
    t95 = float(ctx.slo.get("query_p95_ms",
                            DEFAULT_SLO["query_p95_ms"]))
    data = {"requests": len(lat), "p50_ms": round(p50, 3),
            "p95_ms": round(p95, 3), "target_p50_ms": t50,
            "target_p95_ms": t95}
    if p95 > t95 or p50 > t95:
        return [HealthFinding(
            "query_latency", CRIT,
            f"query p95 {p95:.0f}ms / p50 {p50:.0f}ms breach the "
            f"{t95:.0f}ms tail budget over {len(lat)} requests — "
            f"check shard tails (is compaction keeping up?)",
            data=data)]
    if p50 > t50:
        return [HealthFinding(
            "query_latency", WARN,
            f"query p50 {p50:.0f}ms above the {t50:.0f}ms target "
            f"over {len(lat)} requests", data=data)]
    return [HealthFinding(
        "query_latency", OK,
        f"query p50 {p50:.0f}ms / p95 {p95:.0f}ms within targets "
        f"over {len(lat)} requests", data=data)]


#: shard-tail crit multiple: a tail this many times the compaction
#: threshold means the compactor has fallen badly behind
SHARD_TAIL_CRIT_X = 4.0


@health_rule
def rule_shard_backlog(ctx: HealthContext) -> list[HealthFinding]:
    """Unsealed candidate-shard backlog (ISSUE 20): every byte past
    the compaction threshold is a byte every query re-scans.  Warn
    when any shard's unsealed tail reaches the compactor's size
    threshold (``compaction.DEFAULT_MIN_BYTES``), crit at
    :data:`SHARD_TAIL_CRIT_X` times it — the trigger the supervisor's
    rate-limited ``compact_store`` action fires on.  No store or no
    tails = ok."""
    tails = {k: int(v) for k, v in (ctx.store_tails or {}).items()
             if int(v) > 0}
    if not tails:
        return [HealthFinding(
            "shard_backlog", OK, "no unsealed store tails",
            data={"shards": 0, "tail_bytes": 0})]
    worst_shard = max(tails, key=tails.get)
    worst = tails[worst_shard]
    total = sum(tails.values())
    data = {"shards": len(tails), "tail_bytes": total,
            "worst_shard": worst_shard, "worst_bytes": worst,
            "threshold_bytes": int(DEFAULT_MIN_BYTES)}
    if worst >= SHARD_TAIL_CRIT_X * DEFAULT_MIN_BYTES:
        return [HealthFinding(
            "shard_backlog", CRIT,
            f"shard {worst_shard} has {worst} unsealed bytes "
            f"(>= {SHARD_TAIL_CRIT_X:.0f}x the "
            f"{DEFAULT_MIN_BYTES} compaction threshold) — the "
            f"compactor is not keeping up", data=data)]
    if worst >= DEFAULT_MIN_BYTES:
        return [HealthFinding(
            "shard_backlog", WARN,
            f"shard {worst_shard} has {worst} unsealed bytes past "
            f"the {DEFAULT_MIN_BYTES} compaction threshold",
            data=data)]
    return [HealthFinding(
        "shard_backlog", OK,
        f"{len(tails)} live tail(s), worst {worst} bytes — under "
        f"the compaction threshold", data=data)]


# -- SLO summary -----------------------------------------------------------

def _weighted_percentile(pairs: list[tuple[float, float]],
                         q: float) -> float | None:
    """Percentile of (value, weight) pairs; None on no data."""
    if not pairs:
        return None
    pairs = sorted(pairs)
    total = sum(w for _, w in pairs)
    if total <= 0:
        return None
    acc = 0.0
    for value, weight in pairs:
        acc += weight
        if acc >= q * total:
            return value
    return pairs[-1][0]


def percentile(values, q: float) -> float:
    """Unweighted percentile of a value list (0.0 on no data) — the
    worker's per-drain sojourn/queue-wait ledger columns and the
    loadgen report use this so every table quotes one definition."""
    result = _weighted_percentile([(float(v), 1.0) for v in values], q)
    return 0.0 if result is None else float(result)


def slo_summary(ctx: HealthContext) -> dict:
    """Queue-wait, job-duration and end-to-end sojourn p50/p95 vs
    targets.

    Each telemetry sample carries timer *deltas* (count + host
    seconds), so the per-sample mean weighted by its count is an
    unbiased estimate over the window — good enough for SLO banding
    without shipping every observation off-host.  Over target = warn,
    over 2x target = crit, no data = ``no_data`` (counts as ok: an
    idle fleet meets its SLOs vacuously).
    """
    metrics = {}
    statuses = []
    # (report name, telemetry timer key): sojourn is the end-to-end
    # submit->done latency the scheduler.sojourn timer carries
    for name, timer_key in (("queue_wait", "queue_wait"),
                            ("job", "job"),
                            ("sojourn", "scheduler.sojourn")):
        pairs = []
        n = 0
        for sample in ctx.recent:
            delta = sample.get("timers", {}).get(timer_key)
            if not isinstance(delta, dict):
                continue
            count = float(delta.get("count", 0))
            if count > 0:
                pairs.append((float(delta.get("host_s", 0.0)) / count,
                              count))
                n += int(count)
        p50 = _weighted_percentile(pairs, 0.50)
        p95 = _weighted_percentile(pairs, 0.95)
        t50 = float(ctx.slo.get(f"{name}_p50_s", float("inf")))
        t95 = float(ctx.slo.get(f"{name}_p95_s", float("inf")))
        if p50 is None:
            status = "no_data"
        elif p50 > 2 * t50 or (p95 or 0.0) > 2 * t95:
            status = CRIT
        elif p50 > t50 or (p95 or 0.0) > t95:
            status = WARN
        else:
            status = OK
        statuses.append(status if status in _SEVERITY_RANK else OK)
        metrics[name] = {
            "p50_s": round(p50, 6) if p50 is not None else None,
            "p95_s": round(p95, 6) if p95 is not None else None,
            "n": n,
            "target_p50_s": t50,
            "target_p95_s": t95,
            "status": status,
        }
    return {"metrics": metrics, "status": worst_severity(statuses)}


# -- evaluation ------------------------------------------------------------

def evaluate(ctx: HealthContext) -> dict:
    """Run every registered rule + the SLO summary; returns the health
    report (schema below).  A crashing rule degrades to a warn finding
    so one bad rule can never mask the others.

    Report schema::

        {"v": 1, "utc": <s>, "severity": "ok"|"warn"|"crit",
         "findings": [HealthFinding...], "slo": {...},
         "queue": {...}, "hosts": [...], "window_s": ..., }
    """
    findings: list[HealthFinding] = []
    for rule in RULES:
        try:
            findings.extend(rule(ctx))
        except Exception as exc:
            findings.append(HealthFinding(
                "rule_error", WARN,
                f"health rule {getattr(rule, '__name__', rule)!r} "
                f"crashed: {exc}",
                data={"rule": str(getattr(rule, "__name__", rule))}))
    slo = slo_summary(ctx)
    if slo["status"] in (WARN, CRIT):
        breached = [f"{name} p50={m['p50_s']}s/p95={m['p95_s']}s vs "
                    f"{m['target_p50_s']}/{m['target_p95_s']}s"
                    for name, m in slo["metrics"].items()
                    if m["status"] in (WARN, CRIT)]
        findings.append(HealthFinding(
            "slo_breach", slo["status"],
            "SLO breach: " + "; ".join(breached),
            data={"metrics": {k: m for k, m in slo["metrics"].items()
                              if m["status"] in (WARN, CRIT)}}))
    return {
        "v": 1,
        "utc": round(ctx.now, 3),
        "severity": worst_severity(f.severity for f in findings),
        "findings": [f.to_obj() for f in findings],
        "slo": slo,
        "queue": dict(ctx.queue),
        "hosts": sorted(ctx.latest),
        "window_s": ctx.window_s,
        "stale_after": ctx.stale_after,
    }


def evaluate_spool(spool: JobSpool, **kwargs) -> dict:
    """One-call health evaluation (what the CLI verb runs)."""
    return evaluate(build_context(spool, **kwargs))


def format_findings(report: dict) -> str:
    """Human-readable finding lines (the ``health`` verb's output)."""
    lines = []
    for f in report["findings"]:
        tag = f["severity"].upper()
        subject = f" {f['host']}" if f.get("host") else ""
        lines.append(f"[{tag:<4}] {f['rule']}{subject}: "
                     f"{f['message']}")
    slo = report.get("slo", {})
    for name, m in slo.get("metrics", {}).items():
        if m["status"] == "no_data":
            lines.append(f"[SLO ] {name}: no data in window")
        else:
            lines.append(
                f"[SLO ] {name}: p50={m['p50_s']}s p95={m['p95_s']}s "
                f"(targets {m['target_p50_s']}/{m['target_p95_s']}s) "
                f"-> {m['status']}")
    lines.append(f"fleet severity: {report['severity']}")
    return "\n".join(lines)


def write_health_report(report: dict, path: str) -> str:
    """Serialise a health report atomically (``--json PATH``)."""
    atomic_write_json(path, report, sort_keys=True, indent=1)
    return path
