"""Sealed store segments: the log-structured layer under the survey
store (ISSUE 20).

The live store is append-only JSONL shards (serve/store.py).  That is
the right *write* path — one atomic line append, no locks — and the
wrong *read* path at survey scale: every query re-parses every line.
This module adds the LSM-style read side:

* **Sealed segments** (``segments/seg-<seq>.jsonl``): immutable,
  frequency-sorted record files folded out of shard prefixes by the
  compactor (serve/compaction.py).  Same record schema as the shards
  (STORE.md "Record schema compatibility"), so a segment is readable
  by any JSONL consumer.
* **Sidecar indexes** (``seg-<seq>.idx.json``): frequency fence posts
  (byte offset every :data:`FENCE_EVERY` records) for range reads, a
  ``cand_id -> byte offset`` map for the ``why`` verb's record join,
  per-frequency-bin source lists for incremental coincidence, bloom
  summaries over sources and cand ids, and min/max summaries that let
  a query skip whole segments.
* **Manifest** (``segments/MANIFEST.json``): the single source of
  truth.  It names the sealed segments (in seal order) and records,
  per shard, how many bytes/records have been folded.  A merged read
  is ``segments ∪ unsealed shard tails``; a segment or index file not
  named by the manifest does not exist as far as readers are
  concerned, which is the whole crash-safety story: the compactor
  publishes segment, then index, then manifest (each
  write-temp-then-atomic-rename via ``utils/atomicio``), so a
  compactor killed at ANY point leaves the previous manifest — and
  therefore the previous, complete view — intact.
* **Live-tail coincidence bins** (``segments/bins-<shard>.json``):
  per-frequency-bin source lists for the *unsealed* tail of each
  shard, rewritten atomically by that shard's single writer on every
  ingest.  Together with the per-segment bin summaries they make
  ``coincident_groups()`` a seeded lookup over hot bins (the
  reference coincidencer's per-bin beam-count masks, SURVEY.md §3.4,
  transplanted to survey scale) instead of an O(survey) distill.
  Bin data may safely OVER-approximate (stale files, folded overlap):
  extra occupied/hot bins only enlarge the seed set.  Readers close
  the under-approximation hole by scanning any shard bytes past the
  file's ``covered`` offset inline.

Retention / dedup policy: a record's identity is its ``cand_id``.
Re-ingesting the same candidate (a re-run) REPLACES: the compactor
drops older duplicates when sealing and records cross-segment
replacements in the newer segment's ``supersedes`` list; merged reads
suppress segment records whose id reappears later (a later segment's
``supersedes`` or a live tail line).  Duplicates are therefore never
visible through a sealed read and disappear from the physical store
no later than the segment seal that folds them.
"""

from __future__ import annotations

import bisect
import hashlib
import json
import math
import os

from ..utils.atomicio import (atomic_write_json, atomic_writer,
                              fsync_dir)

SEGMENTS_VERSION = 1

#: subdirectory of the store root holding segments, sidecars, manifest
SEGMENT_DIRNAME = "segments"

MANIFEST_BASENAME = "MANIFEST.json"

SEG_PREFIX = "seg-"

#: fence-post stride: one (freq, byte offset) post per this many
#: records — a range read over-reads at most one stride
FENCE_EVERY = 256

#: fractional width of one coincidence frequency bin; query tolerances
#: map to a neighbour radius in bins (:func:`neighbor_radius`), so the
#: bin grid never constrains the tolerance a caller may use
BIN_TOL = 1e-4

#: natural-log width of one bin
_BIN_W = math.log1p(BIN_TOL)

BLOOM_BITS = 1024
BLOOM_HASHES = 3


# -- frequency bins ---------------------------------------------------------

def freq_bin(freq: float) -> int | None:
    """Log-spaced bin index of a frequency; None for non-positive
    frequencies (they can never satisfy a ratio-tolerance match and
    are excluded from the bin structure)."""
    f = float(freq)
    if not f > 0.0 or not math.isfinite(f):
        return None
    return int(math.floor(math.log(f) / _BIN_W))


def neighbor_radius(freq_tol: float) -> int:
    """Bin radius guaranteeing: two frequencies whose ratio lies
    within ``1 ± freq_tol`` are at most this many bins apart."""
    return int(math.floor(math.log1p(float(freq_tol)) / _BIN_W)) + 1


def bin_freq_range(bin_lo: int, bin_hi: int) -> tuple[float, float]:
    """Closed frequency interval covering bins ``bin_lo..bin_hi``
    (with slack so edge records are never missed by a range read;
    membership is always re-checked via :func:`freq_bin`)."""
    lo = math.exp(_BIN_W * bin_lo) * (1.0 - 1e-9)
    hi = math.exp(_BIN_W * (bin_hi + 1)) * (1.0 + 1e-9)
    return lo, hi


# -- bloom summaries --------------------------------------------------------

def _bloom_positions(item: str):
    h = hashlib.sha1(str(item).encode("utf-8")).digest()
    for i in range(BLOOM_HASHES):
        yield int.from_bytes(h[4 * i:4 * i + 4], "big") % BLOOM_BITS


def bloom_make(items) -> str:
    """Hex-encoded bloom filter over ``items`` (sources, cand ids)."""
    bits = bytearray(BLOOM_BITS // 8)
    for item in items:
        for pos in _bloom_positions(item):
            bits[pos // 8] |= 1 << (pos % 8)
    return bytes(bits).hex()


def bloom_may_contain(hexbits: str, item: str) -> bool:
    """False means definitely absent; True means 'check the index'."""
    try:
        bits = bytes.fromhex(hexbits or "")
    except ValueError:
        return True
    if len(bits) != BLOOM_BITS // 8:
        return True  # unknown bloom geometry: never rule out
    return all(bits[p // 8] & (1 << (p % 8))
               for p in _bloom_positions(item))


# -- paths / manifest -------------------------------------------------------

def segment_dir(root: str) -> str:
    return os.path.join(os.path.abspath(root), SEGMENT_DIRNAME)


def manifest_path(root: str) -> str:
    return os.path.join(segment_dir(root), MANIFEST_BASENAME)


def segment_name(seq: int) -> str:
    return f"{SEG_PREFIX}{int(seq):06d}"


def empty_manifest() -> dict:
    return {"v": SEGMENTS_VERSION, "seq": 0, "segments": [],
            "folded": {}}


def load_manifest(root: str) -> dict:
    """The current manifest, or an empty one when the store has never
    been compacted (or the manifest is unreadable — readers then see
    the full shards, which is always a complete view)."""
    try:
        with open(manifest_path(root), encoding="utf-8") as f:
            man = json.load(f)
    except (OSError, ValueError):
        return empty_manifest()
    if not isinstance(man, dict) or man.get("v") != SEGMENTS_VERSION:
        return empty_manifest()
    man.setdefault("seq", 0)
    man.setdefault("segments", [])
    man.setdefault("folded", {})
    return man


def write_manifest(root: str, man: dict) -> None:
    """Publish a new manifest — THE commit point of a compaction.
    fsync'd: once a reader has seen records only via segments, losing
    the manifest to power loss must not lose the records with it."""
    atomic_write_json(manifest_path(root), man, fsync=True, indent=1,
                      sort_keys=True, trailing_newline=True)
    fsync_dir(manifest_path(root))


def folded_offset(man: dict, shard_basename: str) -> int:
    """Bytes of ``shard_basename`` already folded into segments; the
    shard's live tail begins here."""
    info = (man.get("folded") or {}).get(shard_basename) or {}
    return int(info.get("bytes", 0))


# -- record canonical order -------------------------------------------------

def record_sort_key(rec: dict):
    """Total order of records inside a segment (and of canonicalised
    query results): frequency first — the index dimension — then
    enough identity fields that the order is deterministic for any
    record set."""
    return (float(rec.get("freq", 0.0)), float(rec.get("utc", 0.0)),
            str(rec.get("cand_id", "")), str(rec.get("source", "")),
            str(rec.get("job_id", "")))


# -- segment writer ---------------------------------------------------------

def _noop_fault(stage: str) -> None:
    return None


def write_segment(root: str, seq: int, records: list[dict], *,
                  supersedes=(), fault=_noop_fault) -> dict:
    """Seal ``records`` (already deduped) as segment ``seq``: write
    the frequency-sorted record file, then its sidecar index, each via
    write-temp-then-atomic-rename.  Returns the manifest entry; the
    CALLER publishes it by writing the manifest (the commit point).

    ``fault(stage)`` is the chaos hook (tools/chaos.py): stages
    ``"segment_partial"`` (half the records written to the temp
    file), ``"segment_done"`` (temp complete, not yet renamed) and
    ``"index_done"`` (segment + index on disk, manifest not yet
    written) let a drill die at exactly the syscall boundaries a
    SIGKILL could hit.
    """
    d = segment_dir(root)
    os.makedirs(d, exist_ok=True)
    name = segment_name(seq)
    seg_path = os.path.join(d, name + ".jsonl")
    idx_path = os.path.join(d, name + ".idx.json")

    recs = sorted(records, key=record_sort_key)
    fence: list[list] = []
    cands: dict[str, int] = {}
    bins: dict[str, list] = {}
    bin_sources: dict[int, set] = {}
    sources: set = set()
    utc_min = utc_max = None
    half = len(recs) // 2
    offset = 0
    with atomic_writer(seg_path, fsync=True) as f:
        for i, rec in enumerate(recs):
            if i == half:
                fault("segment_partial")
            line = json.dumps(rec, sort_keys=True)
            if i % FENCE_EVERY == 0:
                fence.append([float(rec.get("freq", 0.0)), offset])
            cid = rec.get("cand_id")
            if cid:
                cands[str(cid)] = offset
            if not rec.get("canary"):
                src = str(rec.get("source", ""))
                sources.add(src)
                b = freq_bin(rec.get("freq", 0.0))
                if b is not None:
                    bin_sources.setdefault(b, set()).add(src)
            utc = rec.get("utc")
            if isinstance(utc, (int, float)):
                utc_min = utc if utc_min is None else min(utc_min, utc)
                utc_max = utc if utc_max is None else max(utc_max, utc)
            f.write(line + "\n")
            offset += len(line.encode("utf-8")) + 1
        fault("segment_done")

    for b, srcs in bin_sources.items():
        bins[str(b)] = sorted(srcs)
    idx = {
        "v": SEGMENTS_VERSION,
        "name": name,
        "records": len(recs),
        "bytes": offset,
        "freq_min": float(recs[0].get("freq", 0.0)) if recs else 0.0,
        "freq_max": float(recs[-1].get("freq", 0.0)) if recs else 0.0,
        "utc_min": utc_min,
        "utc_max": utc_max,
        "sources": sorted(sources),
        "source_bloom": bloom_make(sources),
        "cand_bloom": bloom_make(cands),
        "fence": fence,
        "cands": cands,
        "bins": bins,
        "supersedes": sorted(str(s) for s in supersedes),
    }
    atomic_write_json(idx_path, idx, fsync=True, sort_keys=True,
                      trailing_newline=True)
    fault("index_done")
    return {
        "name": name,
        "records": len(recs),
        "bytes": offset,
        "freq_min": idx["freq_min"],
        "freq_max": idx["freq_max"],
        "supersedes": len(idx["supersedes"]),
    }


# -- segment reader ---------------------------------------------------------

#: process-wide sidecar-index cache: segments are immutable once the
#: manifest names them, so an idx keyed by (path, size, mtime_ns) can
#: never go stale — it only falls out when a new segment replaces the
#: path (never happens: seq numbers are monotonic) or the cache fills
_IDX_CACHE: dict[str, tuple[tuple, dict]] = {}
_IDX_CACHE_MAX = 64


def _cached_idx(path: str) -> dict | None:
    """Load a sidecar index through the immutability cache; None when
    the file is unreadable (caller degrades to index-less reads)."""
    try:
        st = os.stat(path)
        sig = (st.st_size, st.st_mtime_ns)
    except OSError:
        return None
    hit = _IDX_CACHE.get(path)
    if hit is not None and hit[0] == sig:
        return hit[1]
    try:
        with open(path, encoding="utf-8") as f:
            idx = json.load(f)
    except (OSError, ValueError):
        return None
    if len(_IDX_CACHE) >= _IDX_CACHE_MAX:
        _IDX_CACHE.pop(next(iter(_IDX_CACHE)))
    _IDX_CACHE[path] = (sig, idx)
    return idx


class Segment:
    """One sealed segment: lazy sidecar index, streamed record file.
    All read paths count parsed lines into ``reads`` (the shared
    :class:`SegmentSet` counter dict) so tests can assert a query
    touched only indexed spans."""

    def __init__(self, dirpath: str, entry: dict, reads: dict):
        self.dir = dirpath
        self.name = str(entry.get("name", ""))
        self.entry = entry
        self.path = os.path.join(dirpath, self.name + ".jsonl")
        self.idx_path = os.path.join(dirpath, self.name + ".idx.json")
        self._idx: dict | None = None
        self.reads = reads

    @property
    def idx(self) -> dict:
        if self._idx is None:
            self._idx = _cached_idx(self.idx_path)
            if self._idx is None:
                # index lost: degrade to an index-less segment (full
                # streams still work; range reads scan)
                self._idx = {"fence": [], "cands": {}, "bins": {},
                             "sources": [], "supersedes": []}
        return self._idx

    @property
    def records_count(self) -> int:
        return int(self.entry.get("records", 0))

    @property
    def supersedes(self) -> set:
        return set(self.idx.get("supersedes") or ())

    def contains_cand(self, cand_id: str) -> bool:
        idx = self.idx
        bloom = idx.get("cand_bloom")
        if bloom and not bloom_may_contain(bloom, cand_id):
            return False
        return str(cand_id) in (idx.get("cands") or {})

    def may_contain_source(self, source: str) -> bool:
        bloom = self.idx.get("source_bloom")
        if bloom and not bloom_may_contain(bloom, str(source)):
            return False
        srcs = self.idx.get("sources")
        return (str(source) in srcs) if srcs else True

    def bin_sources(self) -> dict[int, set]:
        out: dict[int, set] = {}
        for key, srcs in (self.idx.get("bins") or {}).items():
            try:
                out[int(key)] = set(srcs)
            except (TypeError, ValueError):
                continue
        return out

    def _iter_lines(self, start: int = 0, counter: str = "segment_lines"):
        try:
            f = open(self.path, "rb")
        except OSError:
            return
        with f:
            if start:
                f.seek(start)
            for raw in f:
                if not raw.endswith(b"\n"):
                    return  # torn tail can't exist in a sealed file,
                    # but never yield a partial line regardless
                try:
                    rec = json.loads(raw)
                except ValueError:
                    continue
                if not isinstance(rec, dict):
                    continue
                self.reads[counter] = self.reads.get(counter, 0) + 1
                yield rec

    def iter_records(self):
        """All records, segment (frequency) order."""
        return self._iter_lines()

    def lookup(self, cand_id: str) -> dict | None:
        """Index-read one record by exact cand id: one seek + one
        line, never a scan."""
        off = (self.idx.get("cands") or {}).get(str(cand_id))
        if off is None:
            return None
        for rec in self._iter_lines(int(off), counter="lookup_lines"):
            return rec
        return None

    def iter_freq_range(self, lo: float, hi: float):
        """Records with ``lo <= freq <= hi`` via fence-post seek: jump
        to the last post at or before ``lo``, stop at the first record
        past ``hi`` (the file is frequency-sorted)."""
        entry_lo = self.entry.get("freq_min", self.idx.get("freq_min"))
        entry_hi = self.entry.get("freq_max", self.idx.get("freq_max"))
        if entry_lo is not None and float(entry_hi) < lo:
            self.reads["segments_skipped"] = \
                self.reads.get("segments_skipped", 0) + 1
            return
        if entry_lo is not None and float(entry_lo) > hi:
            self.reads["segments_skipped"] = \
                self.reads.get("segments_skipped", 0) + 1
            return
        fence = self.idx.get("fence") or []
        start = 0
        if fence:
            freqs = [p[0] for p in fence]
            i = bisect.bisect_right(freqs, lo) - 1
            if i >= 0:
                start = int(fence[i][1])
            self.reads["fence_seeks"] = \
                self.reads.get("fence_seeks", 0) + 1
        for rec in self._iter_lines(start, counter="range_lines"):
            f = float(rec.get("freq", 0.0))
            if f > hi:
                return
            if f >= lo:
                yield rec


class SegmentSet:
    """The sealed half of a store: manifest + segments, loaded fresh
    per logical read so concurrent compactions are seen atomically
    (a reader holds ONE manifest for the whole read — either the old
    complete view or the new one, never a mix)."""

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        self.dir = segment_dir(self.root)
        self.manifest = load_manifest(self.root)
        self.reads: dict[str, int] = {}
        self.segments = [
            Segment(self.dir, entry, self.reads)
            for entry in self.manifest.get("segments") or []
        ]

    def __bool__(self) -> bool:
        return bool(self.segments)

    def folded_offset(self, shard_basename: str) -> int:
        return folded_offset(self.manifest, shard_basename)

    def folded_records(self, shard_basename: str) -> int:
        info = (self.manifest.get("folded") or {}).get(
            shard_basename) or {}
        return int(info.get("records", 0))

    def total_records(self) -> int:
        return sum(s.records_count for s in self.segments)

    def suppressed_for(self, i: int) -> set:
        """cand ids that later segments supersede — records in
        segment ``i`` carrying one of these ids are replaced."""
        out: set = set()
        for later in self.segments[i + 1:]:
            out |= later.supersedes
        return out

    def contains_cand(self, cand_id: str) -> bool:
        return any(s.contains_cand(cand_id) for s in self.segments)

    def lookup(self, cand_id: str):
        """Newest sealed record for an exact cand id, plus the segment
        name it lives in: ``(record, segment_name)`` or None."""
        for i in range(len(self.segments) - 1, -1, -1):
            seg = self.segments[i]
            if not seg.contains_cand(cand_id):
                continue
            if cand_id in self.suppressed_for(i):
                continue
            rec = seg.lookup(cand_id)
            if rec is not None:
                return rec, seg.name
        return None

    def lookup_prefix(self, prefix: str):
        """All sealed (record, segment_name) pairs whose cand id
        starts with ``prefix`` — an index-key scan, never a record
        scan."""
        out = []
        for i, seg in enumerate(self.segments):
            suppressed = self.suppressed_for(i)
            for cid in (seg.idx.get("cands") or {}):
                if cid.startswith(prefix) and cid not in suppressed:
                    rec = seg.lookup(cid)
                    if rec is not None:
                        out.append((rec, seg.name))
        return out

    def bin_sources(self) -> dict[int, set]:
        """Union of per-segment frequency-bin source lists."""
        out: dict[int, set] = {}
        for seg in self.segments:
            for b, srcs in seg.bin_sources().items():
                out.setdefault(b, set()).update(srcs)
        return out


# -- live-tail coincidence bins --------------------------------------------

def bins_path(root: str, shard_basename: str) -> str:
    return os.path.join(segment_dir(root),
                        f"bins-{shard_basename}.json")


def load_bins_file(root: str, shard_basename: str) -> dict:
    try:
        with open(bins_path(root, shard_basename),
                  encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return {"v": SEGMENTS_VERSION, "start": 0, "covered": 0,
                "bins": {}}
    if not isinstance(doc, dict) or doc.get("v") != SEGMENTS_VERSION:
        return {"v": SEGMENTS_VERSION, "start": 0, "covered": 0,
                "bins": {}}
    doc.setdefault("start", 0)
    doc.setdefault("covered", 0)
    doc.setdefault("bins", {})
    return doc


def update_bins_file(root: str, shard_basename: str, records,
                     covered: int, *, rebuild_from: int | None = None,
                     start: int | None = None) -> None:
    """Merge ``records``' (bin, source) pairs into the shard's live
    bin file and advance its ``covered`` byte offset (atomic replace;
    the shard's single writer is the only caller).  With
    ``rebuild_from`` the file is reset to cover ``[rebuild_from,
    covered)`` — the post-compaction shrink that drops bins the
    sealed segments now carry."""
    doc = load_bins_file(root, shard_basename)
    if rebuild_from is not None:
        doc = {"v": SEGMENTS_VERSION, "start": int(rebuild_from),
               "covered": int(rebuild_from), "bins": {}}
    if start is not None:
        doc["start"] = int(start)
    bins = doc["bins"]
    for rec in records:
        if rec.get("canary"):
            continue
        b = freq_bin(rec.get("freq", 0.0))
        if b is None:
            continue
        srcs = bins.setdefault(str(b), [])
        src = str(rec.get("source", ""))
        if src not in srcs:
            srcs.append(src)
            srcs.sort()
    doc["covered"] = max(int(doc.get("covered", 0)), int(covered))
    d = segment_dir(root)
    os.makedirs(d, exist_ok=True)
    atomic_write_json(bins_path(root, shard_basename), doc,
                      sort_keys=True, trailing_newline=True)


# -- seeded coincidence planning -------------------------------------------

def hot_components(bin_sources: dict[int, set], freq_tol: float,
                   min_sources: int) -> list[tuple[int, int]]:
    """Plan a seeded coincidence pass: from per-bin source sets,
    return the ``(bin_lo, bin_hi)`` spans of every connected component
    (occupied bins chained by gaps <= the tolerance's neighbour
    radius) that contains at least one HOT bin — a bin whose ±radius
    window unions >= ``min_sources`` distinct sources.

    Components are closed under the within-tolerance relation, so
    distilling only their records provably reproduces the full
    distill's qualifying groups: no record outside a returned span can
    match any record inside one (it would be bin-adjacent, hence in
    the same component), and any qualifying group's fundamental is a
    hot bin by construction.
    """
    if not bin_sources:
        return []
    radius = neighbor_radius(freq_tol)
    occupied = sorted(bin_sources)

    # components: consecutive occupied bins chained by gap <= radius
    comps: list[list[int]] = [[occupied[0]]]
    for b in occupied[1:]:
        if b - comps[-1][-1] <= radius:
            comps[-1].append(b)
        else:
            comps.append([b])

    # hot test per component via a sliding window over its bins
    spans: list[tuple[int, int]] = []
    for comp in comps:
        hot = False
        j0 = 0
        for i, b in enumerate(comp):
            # union sources over comp bins within [b-radius, b+radius]
            while comp[j0] < b - radius:
                j0 += 1
            srcs: set = set()
            j = j0
            while j < len(comp) and comp[j] <= b + radius:
                srcs |= bin_sources[comp[j]]
                if len(srcs) >= min_sources:
                    hot = True
                    break
                j += 1
            if hot:
                break
        if hot:
            spans.append((comp[0], comp[-1]))
    return spans


def spans_to_freq_windows(spans) -> list[tuple[float, float]]:
    """Frequency intervals (with edge slack) covering the bin spans;
    callers re-check membership with :func:`freq_bin` so slack can
    only over-fetch, never mis-classify."""
    return [bin_freq_range(lo, hi) for lo, hi in spans]


def bins_in_spans(b: int | None, spans) -> bool:
    """Span membership via bisect — spans are sorted and disjoint
    (hot_components emits them in bin order)."""
    if b is None or not spans:
        return False
    i = bisect.bisect_right(spans, (b, float("inf"))) - 1
    return i >= 0 and spans[i][0] <= b <= spans[i][1]
