"""Fleet control plane: one survey worker per host of a slice.

The reference pipeline's scale-out story stops at one host — a
pthread pool dispensing DM trials to local GPUs
(`src/pipeline_multi.cu:33-81`) — and the serve layer so far was the
same shape: a single-filesystem spool drained by workers on one
machine.  This module makes the whole service layer fleet-safe, so a
multi-host TPU slice (or just N machines sharing a filesystem) drains
ONE spool:

* **membership + identity** — :class:`FleetMembership` derives each
  host's (id, count, label) from ``parallel/multihost.py`` /
  ``jax.distributed`` (:meth:`FleetMembership.detect`), with an
  injectable fake (:meth:`FleetMembership.fake`) so tier-1 tests
  simulate N hosts in one process — the same pattern as
  ``gather_host_payloads``'s single-process fast path;
* **distributed spool** — claims stay ``os.rename``-atomic across
  hosts on a shared filesystem; each claim drops a lease that a
  :class:`LeaseHeartbeat` daemon thread keeps fresh while the job
  runs, and every idle fleet worker runs the spool's lease-expiry
  reaper so a dead host's jobs return to ``pending/`` without
  operator action (serve/queue.py);
* **sharded candidate store** — each host ingests into its own
  append-only ``store-<host>.jsonl`` shard
  (``serve/store.ShardedCandidateStore``): single-writer appends need
  no cross-host locking, and queries/coincidence merge all shards;
* **fleet verbs** — ``python -m peasoup_tpu.serve fleet-worker`` runs
  :class:`FleetWorker` (the per-host loop, with all the existing
  retry/quarantine/checkpoint machinery); ``status --fleet`` renders
  :func:`fleet_report` — per-host scheduler gauges, queue depths and
  ``jobs_per_hour`` in one table — and writes ``fleet_report.json``.

Observability: each host's drain writes a status snapshot to
``<spool>/fleet/<host>.json`` (what ``status --fleet`` aggregates)
and appends its ``kind="serve"`` throughput record to the bench
history ledger with ``config.host`` set (obs/history.py).
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass

from ..errors import ConfigError
from ..obs.metrics import REGISTRY as METRICS
from ..utils.atomicio import atomic_write_json
from .queue import DEFAULT_LEASE_TTL_S, JobRecord, JobSpool
from .retry import abandoned_count
from .store import ShardedCandidateStore, safe_label
from .worker import SurveyWorker

#: spool subdirectory holding per-host status snapshots
FLEET_DIR = "fleet"

#: aggregated report written by ``status --fleet``
REPORT_BASENAME = "fleet_report.json"


@dataclass(frozen=True)
class FleetMembership:
    """This process's place in the fleet: host index, host count and
    the label that names its worker identity, store shard and status
    file."""

    host_id: int
    host_count: int
    label: str

    @classmethod
    def make(cls, host_id: int, host_count: int,
             label: str | None = None) -> "FleetMembership":
        host_id, host_count = int(host_id), int(host_count)
        if host_count < 1 or not 0 <= host_id < host_count:
            raise ConfigError(
                f"fleet membership host_id={host_id} host_count="
                f"{host_count}: need 0 <= host_id < host_count")
        return cls(host_id, host_count,
                   safe_label(label or f"host-{host_id}"))

    @classmethod
    def detect(cls, coordinator_address: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None,
               label: str | None = None) -> "FleetMembership":
        """Real membership: bring up jax.distributed (a no-op off-pod)
        and read this process's slice identity.  A plain single-process
        run detects as the 1-host fleet — every fleet verb works,
        unchanged, on a laptop."""
        from ..parallel.multihost import initialize, process_identity

        initialize(coordinator_address, num_processes, process_id)
        idx, n = process_identity()
        return cls.make(idx, n, label)

    @classmethod
    def fake(cls, host_id: int, host_count: int,
             label: str | None = None) -> "FleetMembership":
        """Injectable membership: simulate host ``host_id`` of
        ``host_count`` WITHOUT jax.distributed — how tier-1 tests (and
        ``make fleet-smoke``'s subprocesses) run an N-host fleet on
        one machine, following ``gather_host_payloads``'s fake-gather
        pattern."""
        return cls.make(host_id, host_count, label)


class LeaseHeartbeat:
    """Daemon thread refreshing a claimed job's lease every
    ``interval_s`` while the job runs, so the fleet's reapers can tell
    a live long search from a dead host (serve/queue.py lease rules:
    heartbeat ~ TTL/3, several consecutive missed beats expire).

    A context manager wrapping exactly one job's execution.  Waits on
    a ``threading.Event`` — not ``time.sleep``, which lint rule
    PSL008 reserves for serve/retry.py — so :meth:`stop` interrupts
    the wait immediately and job teardown never blocks on the beat
    interval.  Beat I/O errors are swallowed: a torn write on a
    flaky shared filesystem is indistinguishable from a late beat,
    and the next beat retries.
    """

    def __init__(self, spool: JobSpool, rec: JobRecord,
                 interval_s: float):
        self.spool = spool
        self.rec = rec
        self.interval_s = max(float(interval_s), 0.05)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.beats = 0

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.spool.heartbeat(self.rec)
                self.beats += 1
                METRICS.inc("scheduler.heartbeats")
            except OSError:
                pass  # torn/raced beat; the next one retries

    def start(self) -> "LeaseHeartbeat":
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"lease-{self.rec.job_id[:12]}")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def __enter__(self) -> "LeaseHeartbeat":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False


class FleetWorker(SurveyWorker):
    """One host's member of the fleet.

    A :class:`~peasoup_tpu.serve.worker.SurveyWorker` — same claim /
    classify / retry / quarantine / checkpoint / prefetch machinery —
    that additionally (1) stamps claims with its host label, (2) keeps
    a :class:`LeaseHeartbeat` alive around every job, (3) reaps
    expired leases when idle (and once per drain up front, so a
    restarted fleet adopts a dead host's jobs immediately), (4)
    ingests candidates into its own store shard, and (5) writes the
    per-host status snapshot that ``status --fleet`` aggregates.
    """

    def __init__(self, spool: JobSpool, membership: FleetMembership,
                 *, lease_ttl_s: float = DEFAULT_LEASE_TTL_S,
                 heartbeat_s: float | None = None, store=None, **kw):
        if store is None:
            store = ShardedCandidateStore(spool.root, membership.label)
        kw.setdefault(
            "worker_id", f"{membership.label}:pid{os.getpid()}")
        super().__init__(spool, store, **kw)
        self.membership = membership
        self.host_label = membership.label
        self.lease_ttl_s = float(lease_ttl_s)
        self.heartbeat_s = (float(heartbeat_s) if heartbeat_s
                            else max(self.lease_ttl_s / 3.0, 0.5))

    # -- fleet hooks -------------------------------------------------------

    def run_one(self, job: JobRecord) -> bool:
        with LeaseHeartbeat(self.spool, job, self.heartbeat_s):
            return super().run_one(job)

    def _run_batch_jobs(self, jobs: list[JobRecord]) -> int:
        # every beam of a batched dispatch keeps its own lease fresh,
        # so a long batch never looks dead to the reaper
        import contextlib

        with contextlib.ExitStack() as stack:
            for job in jobs:
                stack.enter_context(
                    LeaseHeartbeat(self.spool, job, self.heartbeat_s))
            return super()._run_batch_jobs(jobs)

    def _idle_poll(self) -> None:
        self.spool.reap_expired(self.lease_ttl_s)

    def drain(self, max_jobs: int | None = None, wait: bool = False,
              poll_s: float = 5.0) -> dict:
        # adopt any dead host's jobs before the first claim
        self.spool.reap_expired(self.lease_ttl_s)
        summary = super().drain(max_jobs=max_jobs, wait=wait,
                                poll_s=poll_s)
        summary["host"] = self.membership.label
        summary["host_id"] = self.membership.host_id
        summary["host_count"] = self.membership.host_count
        self.write_host_status(summary)
        return summary

    # -- per-host status ---------------------------------------------------

    def write_host_status(self, summary: dict) -> str:
        """Atomic per-host snapshot (``<spool>/fleet/<host>.json``):
        the drain summary plus this process's scheduler counters and
        gauges — the raw material of :func:`fleet_report`."""
        snap = METRICS.snapshot()
        sched = lambda d: {
            k.split(".", 1)[1]: v for k, v in d.items()
            if k.startswith("scheduler.")
        }
        doc = {
            "v": 1,
            "utc": round(time.time(), 3),
            "host": self.membership.label,
            "host_id": self.membership.host_id,
            "host_count": self.membership.host_count,
            "worker": self.worker_id,
            "pid": os.getpid(),
            "lease_ttl_s": self.lease_ttl_s,
            "heartbeat_s": self.heartbeat_s,
            "summary": {k: summary[k] for k in (
                "claimed", "succeeded", "failed", "elapsed_s",
                "jobs_per_hour", "geometry_buckets",
                "telemetry") if k in summary},
            "scheduler": sched(snap["counters"]),
            "gauges": sched(snap["gauges"]),
            #: timed-out job threads still running in this process
            #: (serve/retry.py run_with_timeout abandons them; each
            #: may hold a device until its dispatch returns)
            "abandoned_threads": abandoned_count(),
            "shard": os.path.basename(self.store.path),
        }
        d = os.path.join(self.spool.root, FLEET_DIR)
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, f"{self.membership.label}.json")
        atomic_write_json(path, doc, sort_keys=True)
        return path


# -- fleet-wide aggregation ------------------------------------------------

def load_host_statuses(spool: JobSpool) -> dict[str, dict]:
    """Every host's latest status snapshot, keyed by host label;
    corrupt/partial snapshots are skipped (ledger rules)."""
    out: dict[str, dict] = {}
    d = os.path.join(spool.root, FLEET_DIR)
    if not os.path.isdir(d):
        return out
    for name in sorted(os.listdir(d)):
        if not name.endswith(".json"):
            continue
        try:
            with open(os.path.join(d, name)) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        if isinstance(doc, dict) and doc.get("host"):
            out[str(doc["host"])] = doc
    return out


def fleet_report(spool: JobSpool,
                 lease_ttl_s: float = DEFAULT_LEASE_TTL_S) -> dict:
    """One aggregated view of the fleet: queue depths, merged-store
    shard counts, per-host scheduler gauges and the cross-host
    throughput totals (``status --fleet``'s table source, serialised
    to ``fleet_report.json`` by :func:`write_fleet_report`)."""
    hosts = load_host_statuses(spool)
    store = ShardedCandidateStore(spool.root)
    now = time.time()
    stale = 0
    leases = 0
    for rec in spool.jobs("running"):
        leases += 1
        lease = spool.lease_info(rec.job_id)
        beat = (lease or {}).get("utc") or rec.claimed_utc
        if now - float(beat or 0.0) > float(lease_ttl_s):
            stale += 1

    def _tot(path, *keys):
        vals = []
        for h in hosts.values():
            v = h
            for k in keys:
                v = v.get(k, {}) if isinstance(v, dict) else {}
            if isinstance(v, (int, float)):
                vals.append(v)
        return vals

    totals = {
        "hosts": len(hosts),
        "claimed": int(sum(_tot(None, "summary", "claimed"))),
        "succeeded": int(sum(_tot(None, "summary", "succeeded"))),
        "failed": int(sum(_tot(None, "summary", "failed"))),
        "jobs_per_hour": round(
            sum(_tot(None, "summary", "jobs_per_hour")), 3),
        "lease_reaped": int(sum(_tot(None, "scheduler",
                                     "lease_reaped"))),
        "quarantined": int(sum(_tot(None, "scheduler",
                                    "quarantined"))),
    }
    report = {
        "v": 2,
        "utc": round(now, 3),
        "spool": spool.root,
        "queue": spool.counts(),
        "leases": {"running": leases, "stale": stale,
                   "ttl_s": float(lease_ttl_s)},
        "store": {
            "candidates": store.count(),
            "sources": len(store.sources()),
            "shards": store.shard_counts(),
        },
        "hosts": hosts,
        "totals": totals,
    }
    # v2: embed the live health evaluation (findings + SLO summary)
    # so fleet_report.json alone answers "is the fleet ok".  Best
    # effort — a broken shard must not take the status verb down.
    try:
        from .health import evaluate_spool

        hp = evaluate_spool(spool, now=now)
        report["health"] = {
            "severity": hp["severity"],
            "findings": hp["findings"],
            "slo": hp["slo"],
        }
    except Exception:
        report["v"] = 1
    return report


def write_fleet_report(spool: JobSpool, report: dict | None = None,
                       path: str | None = None) -> str:
    """Serialise :func:`fleet_report` next to the spool (atomic)."""
    report = report if report is not None else fleet_report(spool)
    path = path or os.path.join(spool.root, REPORT_BASENAME)
    atomic_write_json(path, report, sort_keys=True, indent=1)
    return path
