"""Cross-run candidate store: append-only JSONL + survey queries.

Each job's *distilled* candidates (the per-observation dedup already
done by the search's distiller chain) are appended here as flat JSON
records, one line each, so a survey accumulates one queryable ledger
across thousands of observations — the role PRESTO-style survey
processing fills downstream of each beam.  Queries reuse the search's
own matching machinery (``search/distill.py``, the same fractional-
harmonic and frequency-ratio predicates ``search/coincidence.py``
builds its beam matching on): :meth:`CandidateStore.query` finds
records harmonically related to a frequency, and
:meth:`CandidateStore.coincident_groups` groups detections of the
same signal across *different* observations — the survey-level
coincidence pass (a pulsar repeats across epochs; RFI repeats across
everything).

Record schema (``v`` = 1; consumers tolerate additions)::

    v          int    record schema version
    job_id     str    spool job that produced the record
    source     str    input filterbank path (the observation)
    utc        float  ingest time (unix seconds)
    cand_id    str    stable content-derived candidate id
                      (obs/lineage.py, ISSUE 19) — the ``why`` verb's
                      join key into the lineage ledger
    dm_idx     int    DM trial index (part of the id's preimage)
    dm, acc, jerk, freq, snr, folded_snr, nh, period  candidate fields
    prov       dict   producing run's provenance block (run id, git
                      sha, geometry fingerprint, lattice, host)
    canary     bool   present (true) only on canary-job records
                      (obs/injection.py, ISSUE 14) — excluded from
                      every science read unless ``include_canary=True``

Store I/O follows the ledger rules (obs/history.py): appends are one
atomic line write; corrupt/torn lines are skipped on load so a killed
worker cannot poison the survey.

Fleet mode shards the ledger per host
(:class:`ShardedCandidateStore`): each host APPENDS only to its own
``store-<host>.jsonl`` — append-only single-writer files need no
cross-host locking on a shared filesystem — while every query
(:meth:`~CandidateStore.query`, the coincidencer
:meth:`~CandidateStore.coincident_groups`) reads the MERGE of all
shards plus the legacy single-store file.  A torn tail on one shard
(its host died mid-append) skips that line only; the merge is
unaffected.
"""

from __future__ import annotations

import glob
import json
import os
import re
import time

import numpy as np

STORE_VERSION = 1

#: fleet store shards: <spool>/store-<host_label>.jsonl
SHARD_PREFIX = "store-"

#: the pre-fleet single-store file, still merged by the sharded reader
LEGACY_BASENAME = "candidates.jsonl"


def safe_label(label: str) -> str:
    """Host label sanitised for use in file names (shards, per-host
    status files): anything outside [A-Za-z0-9_.-] becomes '_'."""
    return re.sub(r"[^A-Za-z0-9_.-]", "_", str(label)) or "host"


def _iter_records(path: str, source: str | None = None,
                  min_snr: float | None = None,
                  include_canary: bool = False):
    """Yield one file's records in file order; corrupt/torn lines and
    a missing file are skipped (ledger rules).  Canary-job records are
    skipped unless ``include_canary`` — known-answer probes must never
    pollute science reads."""
    if not os.path.exists(path):
        return
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # torn tail from a killed worker
            if not isinstance(rec, dict) or "freq" not in rec:
                continue
            if rec.get("canary") and not include_canary:
                continue
            if source is not None and rec.get("source") != source:
                continue
            if min_snr is not None and \
                    rec.get("snr", 0.0) < min_snr:
                continue
            yield rec


#: provenance fields copied from ``SearchResult.provenance`` onto each
#: store record (obs/lineage.py, ISSUE 19) — enough for ``why`` to
#: relocate the run's lineage ledger and pin the producing build
PROV_FIELDS = ("run", "git_sha", "geometry", "lattice", "host")


def _record_from_candidate(job_id: str, source: str, cand,
                           utc: float, canary: bool = False,
                           prov: dict | None = None) -> dict:
    from ..obs.lineage import candidate_uid

    run = (prov or {}).get("run") or str(job_id)
    rec = {
        "v": STORE_VERSION,
        "job_id": str(job_id),
        "source": str(source),
        "utc": round(float(utc), 3),
        "cand_id": candidate_uid(run, cand),
        "dm": round(float(cand.dm), 6),
        "dm_idx": int(getattr(cand, "dm_idx", 0)),
        "acc": round(float(cand.acc), 6),
        "jerk": round(float(getattr(cand, "jerk", 0.0)), 6),
        "freq": float(cand.freq),
        "snr": round(float(cand.snr), 4),
        "folded_snr": round(float(cand.folded_snr), 4),
        "nh": int(cand.nh),
        "period": (1.0 / float(cand.freq)) if cand.freq else 0.0,
    }
    if prov:
        rec["prov"] = {k: prov[k] for k in PROV_FIELDS if k in prov}
    if canary:
        # tag-only-when-true keeps science records byte-identical to
        # the pre-canary schema
        rec["canary"] = True
    return rec


class CandidateStore:
    """Append-only JSONL candidate ledger with survey-level queries."""

    def __init__(self, path: str):
        self.path = path

    # -- ingest ------------------------------------------------------------

    def ingest(self, job_id: str, source: str, candidates,
               utc: float | None = None, canary: bool = False,
               provenance: dict | None = None) -> int:
        """Append one job's distilled candidates; returns the count.

        ``canary=True`` tags every record so the default read side
        excludes them from science queries and coincidence.
        ``provenance`` (``SearchResult.provenance``) stamps each record
        with the producing run's identity block (ISSUE 19) so ``why``
        can reconstruct the decision chain from the record alone."""
        utc = time.time() if utc is None else utc
        recs = [
            _record_from_candidate(job_id, source, c, utc, canary,
                                   provenance)
            for c in candidates
        ]
        if not recs:
            return 0
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(self.path, "a", encoding="utf-8") as f:
            for rec in recs:
                f.write(json.dumps(rec, sort_keys=True) + "\n")
        return len(recs)

    # -- load / filter -----------------------------------------------------

    def records(self, source: str | None = None,
                min_snr: float | None = None,
                include_canary: bool = False) -> list[dict]:
        """All SCIENCE records in file order; corrupt lines skipped.
        ``include_canary=True`` adds the canary-tagged records (the
        canary drain's own bookkeeping reads)."""
        return list(_iter_records(self.path, source, min_snr,
                                  include_canary))

    def count(self) -> int:
        return len(self.records())

    def sources(self) -> list[str]:
        """Distinct observations that contributed records."""
        return sorted({r.get("source", "") for r in self.records()})

    # -- survey queries ----------------------------------------------------

    def query(self, freq: float, freq_tol: float = 1e-4,
              max_harm: int = 1) -> list[dict]:
        """Records harmonically related to ``freq`` across the survey.

        The same fractional-ratio predicate as the search's
        ``HarmonicDistiller``: a record at ``f`` matches when
        ``k*f / (j*freq)`` lies within ``1 ± freq_tol`` for some
        integer ``j, k <= max_harm`` (``max_harm=1`` is a plain
        frequency-ratio match).
        """
        recs = self.records()
        if not recs:
            return []
        freqs = np.array([r["freq"] for r in recs], np.float64)
        # numerator and denominator harmonics both range 1..max_harm
        hh = np.arange(1, int(max_harm) + 1, dtype=np.float64)
        # ratio[i, k, j] = hh[k] * f_i / (hh[j] * freq)
        ratio = (hh[None, :, None] * freqs[:, None, None]
                 / (hh[None, None, :] * float(freq)))
        ok = ((ratio > 1 - freq_tol) & (ratio < 1 + freq_tol)).any(
            axis=(1, 2))
        return [r for r, hit in zip(recs, ok) if hit]

    def coincident_groups(self, freq_tol: float = 1e-4,
                          min_sources: int = 2) -> list[list[dict]]:
        """Groups of records matching in frequency across at least
        ``min_sources`` DISTINCT observations, strongest first.

        Reuses ``search/distill.py``'s ``DMDistiller`` greedy
        SNR-sorted matching (frequency ratio within tolerance
        regardless of DM) — the candidate-level analogue of the beam
        coincidencer — so store matching can never drift from the
        in-run distillation semantics.
        """
        from ..data.candidates import Candidate
        from ..search.distill import DMDistiller

        recs = self.records()
        if not recs:
            return []
        cands = [
            Candidate(dm=r.get("dm", 0.0), snr=r.get("snr", 0.0),
                      freq=r["freq"])
            for r in recs
        ]
        by_id = {id(c): r for c, r in zip(cands, recs)}
        fundamentals = DMDistiller(freq_tol, True).distill(cands)
        groups: list[list[dict]] = []
        for fund in fundamentals:
            family = [by_id[id(c)] for c in fund.collect()]
            if len({r["source"] for r in family}) >= min_sources:
                groups.append(family)
        return groups


def shard_path(root: str, host_label: str) -> str:
    """One host's append-only shard file under the spool root."""
    return os.path.join(root, f"{SHARD_PREFIX}{safe_label(host_label)}"
                              f".jsonl")


class ShardedCandidateStore(CandidateStore):
    """Fleet store: per-host append-only shards, merged reads.

    ``host_label`` names the shard THIS process appends to
    (``store-<host>.jsonl``); without one the store is a pure merged
    reader (the ``status --fleet`` / ``coincidence`` verbs) and
    ingests fall through to the legacy single-store file so nothing is
    ever dropped.  Every read-side method — :meth:`records` and
    therefore :meth:`count`, :meth:`sources`, :meth:`query` and the
    coincidencer :meth:`coincident_groups` — sees the merge of ALL
    shards plus the legacy file, in (shard name, file order): a
    deterministic order, so merged queries equal the single-store
    answer on the same record set (tests/test_fleet.py asserts this).
    """

    def __init__(self, root: str, host_label: str | None = None):
        self.root = os.path.abspath(root)
        self.host_label = (safe_label(host_label)
                           if host_label is not None else None)
        super().__init__(
            shard_path(self.root, self.host_label)
            if self.host_label is not None
            else os.path.join(self.root, LEGACY_BASENAME))

    def shard_files(self) -> list[str]:
        """All shard files plus the legacy store, merge order."""
        shards = sorted(
            glob.glob(os.path.join(self.root, f"{SHARD_PREFIX}*.jsonl")))
        legacy = os.path.join(self.root, LEGACY_BASENAME)
        if os.path.exists(legacy):
            shards.append(legacy)
        return shards

    def records(self, source: str | None = None,
                min_snr: float | None = None,
                include_canary: bool = False) -> list[dict]:
        out: list[dict] = []
        for path in self.shard_files():
            out.extend(_iter_records(path, source, min_snr,
                                     include_canary))
        return out

    def shard_counts(self) -> dict[str, int]:
        """Readable records per shard basename (fleet status table)."""
        return {
            os.path.basename(p): sum(1 for _ in _iter_records(p))
            for p in self.shard_files()
        }
