"""Cross-run candidate store: append-only JSONL + survey queries.

Each job's *distilled* candidates (the per-observation dedup already
done by the search's distiller chain) are appended here as flat JSON
records, one line each, so a survey accumulates one queryable ledger
across thousands of observations — the role PRESTO-style survey
processing fills downstream of each beam.  Queries reuse the search's
own matching machinery (``search/distill.py``, the same fractional-
harmonic and frequency-ratio predicates ``search/coincidence.py``
builds its beam matching on): :meth:`CandidateStore.query` finds
records harmonically related to a frequency, and
:meth:`CandidateStore.coincident_groups` groups detections of the
same signal across *different* observations — the survey-level
coincidence pass (a pulsar repeats across epochs; RFI repeats across
everything).

Record schema (``v`` = 1; consumers tolerate additions)::

    v          int    record schema version
    job_id     str    spool job that produced the record
    source     str    input filterbank path (the observation)
    utc        float  ingest time (unix seconds)
    cand_id    str    stable content-derived candidate id
                      (obs/lineage.py, ISSUE 19) — the ``why`` verb's
                      join key into the lineage ledger, and the
                      record's IDENTITY for the store's retention
                      policy (a re-run replaces, never duplicates)
    dm_idx     int    DM trial index (part of the id's preimage)
    dm, acc, jerk, freq, snr, folded_snr, nh, period  candidate fields
    prov       dict   producing run's provenance block (run id, git
                      sha, geometry fingerprint, lattice, host)
    canary     bool   present (true) only on canary-job records
                      (obs/injection.py, ISSUE 14) — excluded from
                      every science read unless ``include_canary=True``

Store I/O follows the ledger rules (obs/history.py): appends are one
atomic line write; corrupt/torn lines are skipped on load so a killed
worker cannot poison the survey.

Fleet mode shards the ledger per host
(:class:`ShardedCandidateStore`): each host APPENDS only to its own
``store-<host>.jsonl`` — append-only single-writer files need no
cross-host locking on a shared filesystem — while every query reads
the MERGE of all shards.  **Pinned total merge order** (ISSUE 20):
the legacy single-store file ``candidates.jsonl`` first (it predates
every shard), then shards sorted by basename; within a file, line
order.  The order is a property of the NAMES alone — never of glob or
directory enumeration order — so merged reads are deterministic
across hosts and filesystems.  A torn tail on one shard (its host
died mid-append) skips that line only; the merge is unaffected.

At survey scale the sharded store is *log-structured* (ISSUE 20,
serve/segments.py): a background compactor (serve/compaction.py)
folds shard prefixes into immutable frequency-sorted segments with
sidecar indexes, and every read-side method here sees
``sealed segments ∪ unsealed shard tails`` — record-identical to the
full scan, while :meth:`query`, :meth:`coincident_groups` and
:meth:`lookup` touch only indexed spans.  A store that has never been
compacted behaves exactly as before (the segment set is empty and
every tail starts at byte 0).
"""

from __future__ import annotations

import glob
import json
import os
import re
import time

import numpy as np

from . import segments as seglib

STORE_VERSION = 1

#: fleet store shards: <spool>/store-<host_label>.jsonl
SHARD_PREFIX = "store-"

#: the pre-fleet single-store file, still merged by the sharded reader
LEGACY_BASENAME = "candidates.jsonl"

#: batch size for the streaming numpy ratio test in :meth:`query` —
#: bounds peak memory at O(batch), not O(survey)
QUERY_BATCH = 4096


def safe_label(label: str) -> str:
    """Host label sanitised for use in file names (shards, per-host
    status files): anything outside [A-Za-z0-9_.-] becomes '_'."""
    return re.sub(r"[^A-Za-z0-9_.-]", "_", str(label)) or "host"


def _iter_records(path: str, source: str | None = None,
                  min_snr: float | None = None,
                  include_canary: bool = False, start: int = 0):
    """Yield one file's records in file order; corrupt/torn lines and
    a missing file are skipped (ledger rules).  Canary-job records are
    skipped unless ``include_canary`` — known-answer probes must never
    pollute science reads.  ``start`` seeks to a byte offset first
    (always a line boundary: the segment manifest's folded offsets are
    produced from complete lines only)."""
    try:
        f = open(path, "rb")
    except OSError:
        return
    with f:
        if start:
            f.seek(int(start))
        for raw in f:
            raw = raw.strip()
            if not raw:
                continue
            try:
                rec = json.loads(raw)
            except ValueError:
                continue  # torn tail from a killed worker
            if not isinstance(rec, dict) or "freq" not in rec:
                continue
            if rec.get("canary") and not include_canary:
                continue
            if source is not None and rec.get("source") != source:
                continue
            if min_snr is not None and \
                    rec.get("snr", 0.0) < min_snr:
                continue
            yield rec


def _passes(rec: dict, source, min_snr, include_canary) -> bool:
    """The read-side filter, factored out so segment reads apply the
    exact predicate `_iter_records` applies to shard reads."""
    if rec.get("canary") and not include_canary:
        return False
    if source is not None and rec.get("source") != source:
        return False
    if min_snr is not None and rec.get("snr", 0.0) < min_snr:
        return False
    return True


#: provenance fields copied from ``SearchResult.provenance`` onto each
#: store record (obs/lineage.py, ISSUE 19) — enough for ``why`` to
#: relocate the run's lineage ledger and pin the producing build
PROV_FIELDS = ("run", "git_sha", "geometry", "lattice", "host")


def _record_from_candidate(job_id: str, source: str, cand,
                           utc: float, canary: bool = False,
                           prov: dict | None = None) -> dict:
    from ..obs.lineage import candidate_uid

    run = (prov or {}).get("run") or str(job_id)
    rec = {
        "v": STORE_VERSION,
        "job_id": str(job_id),
        "source": str(source),
        "utc": round(float(utc), 3),
        "cand_id": candidate_uid(run, cand),
        "dm": round(float(cand.dm), 6),
        "dm_idx": int(getattr(cand, "dm_idx", 0)),
        "acc": round(float(cand.acc), 6),
        "jerk": round(float(getattr(cand, "jerk", 0.0)), 6),
        "freq": float(cand.freq),
        "snr": round(float(cand.snr), 4),
        "folded_snr": round(float(cand.folded_snr), 4),
        "nh": int(cand.nh),
        "period": (1.0 / float(cand.freq)) if cand.freq else 0.0,
    }
    if prov:
        rec["prov"] = {k: prov[k] for k in PROV_FIELDS if k in prov}
    if canary:
        # tag-only-when-true keeps science records byte-identical to
        # the pre-canary schema
        rec["canary"] = True
    return rec


# -- shared query predicates ------------------------------------------------

def _harmonic_windows(freq: float, freq_tol: float,
                      max_harm: int) -> list[tuple[float, float]]:
    """Merged frequency intervals that contain every f satisfying the
    harmonic-ratio predicate — the index prefilter.  Matching is
    always re-decided by :func:`_harmonic_hits`, so windows only need
    to be a superset."""
    raw = []
    for j in range(1, int(max_harm) + 1):
        for k in range(1, int(max_harm) + 1):
            center = j * float(freq) / k
            raw.append((center * (1.0 - freq_tol),
                        center * (1.0 + freq_tol)))
    raw.sort()
    merged = [list(raw[0])]
    for lo, hi in raw[1:]:
        if lo <= merged[-1][1]:
            merged[-1][1] = max(merged[-1][1], hi)
        else:
            merged.append([lo, hi])
    return [(lo, hi) for lo, hi in merged]


def _harmonic_hits(freqs, freq: float, freq_tol: float,
                   max_harm: int):
    """Boolean mask over ``freqs``: the search's fractional-ratio
    predicate ``k*f / (j*freq) in (1 ± freq_tol)`` for some integer
    ``j, k <= max_harm`` — identical arithmetic on every path (full
    scan, batch stream, segment range read)."""
    freqs = np.asarray(freqs, np.float64)
    hh = np.arange(1, int(max_harm) + 1, dtype=np.float64)
    # ratio[i, k, j] = hh[k] * f_i / (hh[j] * freq)
    ratio = (hh[None, :, None] * freqs[:, None, None]
             / (hh[None, None, :] * float(freq)))
    return ((ratio > 1 - freq_tol) & (ratio < 1 + freq_tol)).any(
        axis=(1, 2))


def _query_stream(rec_iter, freq: float, freq_tol: float,
                  max_harm: int, batch: int = QUERY_BATCH):
    """Run the ratio test over a record stream in fixed-size batches —
    O(batch) peak memory however large the survey is."""
    hits: list[dict] = []
    buf: list[dict] = []

    def _flush():
        if not buf:
            return
        ok = _harmonic_hits([r["freq"] for r in buf], freq, freq_tol,
                            max_harm)
        hits.extend(r for r, h in zip(buf, ok) if h)
        buf.clear()

    for rec in rec_iter:
        buf.append(rec)
        if len(buf) >= batch:
            _flush()
    _flush()
    return hits


def _distill_groups(recs: list[dict], freq_tol: float,
                    min_sources: int) -> list[list[dict]]:
    """The coincidence core shared by the full-scan and seeded paths:
    canonical pre-sort (strongest first, then the segment record
    order — deterministic whatever order the records arrived in),
    DMDistiller greedy matching, group by family, keep groups spanning
    >= ``min_sources`` distinct observations."""
    from ..data.candidates import Candidate
    from ..search.distill import DMDistiller

    if not recs:
        return []
    recs = sorted(recs, key=lambda r: (-float(r.get("snr", 0.0)),
                                       seglib.record_sort_key(r)))
    cands = [
        Candidate(dm=r.get("dm", 0.0), snr=r.get("snr", 0.0),
                  freq=r["freq"])
        for r in recs
    ]
    by_id = {id(c): r for c, r in zip(cands, recs)}
    fundamentals = DMDistiller(freq_tol, True).distill(cands)
    groups: list[list[dict]] = []
    for fund in fundamentals:
        family = [by_id[id(c)] for c in fund.collect()]
        if len({r["source"] for r in family}) >= min_sources:
            groups.append(family)
    return groups


class CandidateStore:
    """Append-only JSONL candidate ledger with survey-level queries."""

    def __init__(self, path: str):
        self.path = path

    # -- ingest ------------------------------------------------------------

    def ingest(self, job_id: str, source: str, candidates,
               utc: float | None = None, canary: bool = False,
               provenance: dict | None = None) -> int:
        """Append one job's distilled candidates; returns the count.

        ``canary=True`` tags every record so the default read side
        excludes them from science queries and coincidence.
        ``provenance`` (``SearchResult.provenance``) stamps each record
        with the producing run's identity block (ISSUE 19) so ``why``
        can reconstruct the decision chain from the record alone."""
        utc = time.time() if utc is None else utc
        recs = [
            _record_from_candidate(job_id, source, c, utc, canary,
                                   provenance)
            for c in candidates
        ]
        if not recs:
            return 0
        self._append(recs)
        return len(recs)

    def _append(self, recs: list[dict]) -> None:
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(self.path, "a", encoding="utf-8") as f:
            for rec in recs:
                f.write(json.dumps(rec, sort_keys=True) + "\n")

    # -- load / filter -----------------------------------------------------

    def iter_records(self, source: str | None = None,
                     min_snr: float | None = None,
                     include_canary: bool = False):
        """Streaming :meth:`records` — O(1) memory, file order."""
        return _iter_records(self.path, source, min_snr,
                             include_canary)

    def records(self, source: str | None = None,
                min_snr: float | None = None,
                include_canary: bool = False) -> list[dict]:
        """All SCIENCE records in file order; corrupt lines skipped.
        ``include_canary=True`` adds the canary-tagged records (the
        canary drain's own bookkeeping reads)."""
        return list(self.iter_records(source, min_snr,
                                      include_canary))

    def count(self) -> int:
        """Science-record count, streamed (never materialises the
        records)."""
        return sum(1 for _ in self.iter_records())

    def sources(self) -> list[str]:
        """Distinct observations that contributed records, streamed."""
        out: set[str] = set()
        for rec in self.iter_records():
            out.add(rec.get("source", ""))
        return sorted(out)

    # -- survey queries ----------------------------------------------------

    def query(self, freq: float, freq_tol: float = 1e-4,
              max_harm: int = 1) -> list[dict]:
        """Records harmonically related to ``freq`` across the survey.

        The same fractional-ratio predicate as the search's
        ``HarmonicDistiller``: a record at ``f`` matches when
        ``k*f / (j*freq)`` lies within ``1 ± freq_tol`` for some
        integer ``j, k <= max_harm`` (``max_harm=1`` is a plain
        frequency-ratio match).  The scan streams in
        :data:`QUERY_BATCH`-record batches, so memory stays bounded
        at any survey size.
        """
        return _query_stream(self.iter_records(), freq, freq_tol,
                             max_harm)

    def coincident_groups(self, freq_tol: float = 1e-4,
                          min_sources: int = 2) -> list[list[dict]]:
        """Groups of records matching in frequency across at least
        ``min_sources`` DISTINCT observations, strongest first.

        Reuses ``search/distill.py``'s ``DMDistiller`` greedy
        SNR-sorted matching (frequency ratio within tolerance
        regardless of DM) — the candidate-level analogue of the beam
        coincidencer — so store matching can never drift from the
        in-run distillation semantics.  Records are canonically
        pre-sorted (snr desc, then frequency/identity) so the result
        is deterministic for a given record SET, independent of file
        or shard order.
        """
        return _distill_groups(self.records(), freq_tol, min_sources)


def shard_path(root: str, host_label: str) -> str:
    """One host's append-only shard file under the spool root."""
    return os.path.join(root, f"{SHARD_PREFIX}{safe_label(host_label)}"
                              f".jsonl")


class ShardedCandidateStore(CandidateStore):
    """Fleet store: per-host append-only shards, merged log-structured
    reads.

    ``host_label`` names the shard THIS process appends to
    (``store-<host>.jsonl``); without one the store is a pure merged
    reader (the ``status --fleet`` / ``coincidence`` verbs) and
    ingests fall through to the legacy single-store file so nothing is
    ever dropped.

    Every read-side method sees ``sealed segments ∪ unsealed shard
    tails`` under the **pinned total merge order**: sealed segments in
    seal sequence first, then the legacy ``candidates.jsonl`` tail,
    then shard tails sorted by basename (a pure function of the file
    NAMES — deterministic on every host and filesystem; the glob-order
    fragility of the pre-ISSUE-20 reader is gone).  Retention: a
    ``cand_id`` appearing more than once (a re-run) resolves to the
    newest copy — a live tail line beats any sealed copy, a later
    segment's ``supersedes`` beats an earlier segment — so merged
    reads never show a duplicate that compaction has had a chance to
    see, and :meth:`count` matches ``len(records())`` exactly.
    """

    def __init__(self, root: str, host_label: str | None = None):
        self.root = os.path.abspath(root)
        self.host_label = (safe_label(host_label)
                           if host_label is not None else None)
        super().__init__(
            shard_path(self.root, self.host_label)
            if self.host_label is not None
            else os.path.join(self.root, LEGACY_BASENAME))
        #: read-volume counters of the most recent segment-aware read
        #: (tests assert queries touch only indexed spans)
        self.last_read_stats: dict[str, int] = {}

    # -- ingest (bins upkeep) ----------------------------------------------

    def _append(self, recs: list[dict]) -> None:
        """Shard append + live-tail coincidence-bin upkeep: after the
        line append, fold the new records' (frequency bin, source)
        pairs into this shard's ``segments/bins-*.json`` so
        :meth:`coincident_groups` stays a seeded lookup without
        rescanning the tail (ISSUE 20).  The bins file is advisory —
        readers close any coverage gap by scanning uncovered tail
        bytes — so a crash between the two writes loses nothing."""
        super()._append(recs)
        base = os.path.basename(self.path)
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return
        folded = seglib.folded_offset(seglib.load_manifest(self.root),
                                      base)
        doc = seglib.load_bins_file(self.root, base)
        if int(doc.get("start", 0)) < folded:
            # a compaction sealed part of our coverage: rebuild the
            # live bins from the new folded offset (the sealed part
            # now lives in the segment sidecars)
            tail = list(_iter_records(self.path, start=folded))
            seglib.update_bins_file(self.root, base, tail,
                                    covered=size,
                                    rebuild_from=folded)
        else:
            seglib.update_bins_file(self.root, base, recs,
                                    covered=size)

    # -- merge plumbing ----------------------------------------------------

    def shard_files(self) -> list[str]:
        """Live JSONL files in pinned merge order: legacy store first
        (it predates every shard), then shards sorted by basename.
        Deterministic by construction — derived from names, never from
        enumeration order."""
        out: list[str] = []
        legacy = os.path.join(self.root, LEGACY_BASENAME)
        if os.path.exists(legacy):
            out.append(legacy)
        shards = sorted(
            glob.glob(os.path.join(self.root, f"{SHARD_PREFIX}*.jsonl")),
            key=os.path.basename)
        out.extend(shards)
        return out

    def _segments(self) -> "seglib.SegmentSet":
        segs = seglib.SegmentSet(self.root)
        self.last_read_stats = segs.reads
        return segs

    def _tails(self, segs):
        """Buffer every unsealed tail record (unfiltered — identity
        resolution must see canaries and all sources) plus the
        last-occurrence index per cand_id and each record's origin
        basename.  Post-compaction tails are small; pre-compaction
        this is the whole store, i.e. exactly the legacy read."""
        tails: list[dict] = []
        origins: list[str] = []
        last: dict[str, int] = {}
        for path in self.shard_files():
            base = os.path.basename(path)
            start = segs.folded_offset(base)
            for rec in _iter_records(path, include_canary=True,
                                     start=start):
                cid = rec.get("cand_id")
                if cid:
                    last[str(cid)] = len(tails)
                tails.append(rec)
                origins.append(base)
        segs.reads["tail_lines"] = (segs.reads.get("tail_lines", 0)
                                    + len(tails))
        return tails, last, origins

    def _iter_merged(self, source=None, min_snr=None,
                     include_canary=False, segs=None, tails=None,
                     last=None):
        """Segments ∪ tails with retention applied, pinned order."""
        segs = self._segments() if segs is None else segs
        if tails is None:
            tails, last, _ = self._tails(segs)
        for i, seg in enumerate(segs.segments):
            suppressed = segs.suppressed_for(i)
            for rec in seg.iter_records():
                cid = rec.get("cand_id")
                if cid and (cid in suppressed or cid in last):
                    continue  # replaced by a newer copy
                if _passes(rec, source, min_snr, include_canary):
                    yield rec
        for idx, rec in enumerate(tails):
            cid = rec.get("cand_id")
            if cid and last.get(str(cid)) != idx:
                continue  # an older duplicate within the tails
            if _passes(rec, source, min_snr, include_canary):
                yield rec

    def iter_records(self, source: str | None = None,
                     min_snr: float | None = None,
                     include_canary: bool = False):
        return self._iter_merged(source, min_snr, include_canary)

    def records(self, source: str | None = None,
                min_snr: float | None = None,
                include_canary: bool = False) -> list[dict]:
        return list(self._iter_merged(source, min_snr,
                                      include_canary))

    # -- counters (index fast paths) ---------------------------------------

    def count(self) -> int:
        """``len(records())`` without reading segment bodies when the
        index allows: segment record counts come from the manifest;
        only retention collisions (tail ids also sealed, cross-segment
        supersessions) and canary exclusions force index lookups, and
        only a canary count forces nothing — the common no-collision
        survey is O(tails + #segments)."""
        segs = self._segments()
        if not segs:
            return sum(
                1 for path in self.shard_files()
                for _ in _iter_records(path))
        tails, last, _ = self._tails(segs)
        total = 0
        for i, seg in enumerate(segs.segments):
            n = seg.records_count - int(seg.entry.get("canary", 0))
            suspects = segs.suppressed_for(i) | set(last)
            if suspects:
                hidden = 0
                for cid in suspects:
                    if not seg.contains_cand(cid):
                        continue
                    rec = seg.lookup(cid)
                    if rec is not None and not rec.get("canary"):
                        hidden += 1
                n -= hidden
            total += n
        for idx, rec in enumerate(tails):
            cid = rec.get("cand_id")
            if cid and last.get(str(cid)) != idx:
                continue
            if rec.get("canary"):
                continue
            total += 1
        return total

    def sources(self) -> list[str]:
        """Distinct science observations — per-segment source
        summaries plus a streamed tail scan; segment bodies are never
        read."""
        segs = self._segments()
        out: set[str] = set()
        for seg in segs.segments:
            out.update(seg.idx.get("sources") or ())
        for path in self.shard_files():
            start = segs.folded_offset(os.path.basename(path))
            for rec in _iter_records(path, start=start):
                out.add(rec.get("source", ""))
        return sorted(out)

    def shard_counts(self) -> dict[str, int]:
        """Science records ingested per shard basename (fleet status
        table): the manifest's folded-record count plus a streamed
        count of the unsealed tail — ingest attribution, before
        cross-shard retention."""
        segs = self._segments()
        out: dict[str, int] = {}
        for path in self.shard_files():
            base = os.path.basename(path)
            start = segs.folded_offset(base)
            out[base] = segs.folded_records(base) + sum(
                1 for _ in _iter_records(path, start=start))
        return out

    # -- indexed survey queries --------------------------------------------

    def query(self, freq: float, freq_tol: float = 1e-4,
              max_harm: int = 1) -> list[dict]:
        """Harmonically related records (see
        :meth:`CandidateStore.query`) via the segment indexes: each
        sealed segment contributes only fence-post range reads over
        the harmonic windows (or is skipped outright by its min/max
        summary); only the unsealed tails are scanned.  Results are
        canonically ordered (frequency, then identity) so the answer
        is a pure function of the record set — identical before and
        after any compaction."""
        segs = self._segments()
        tails, last, _ = self._tails(segs)
        windows = _harmonic_windows(float(freq), float(freq_tol),
                                    int(max_harm))
        hits: list[dict] = []
        for i, seg in enumerate(segs.segments):
            suppressed = segs.suppressed_for(i)
            cand_rows: list[dict] = []
            for lo, hi in windows:
                for rec in seg.iter_freq_range(lo, hi):
                    cid = rec.get("cand_id")
                    if cid and (cid in suppressed or cid in last):
                        continue
                    if _passes(rec, None, None, False):
                        cand_rows.append(rec)
            if cand_rows:
                ok = _harmonic_hits([r["freq"] for r in cand_rows],
                                    freq, freq_tol, max_harm)
                hits.extend(r for r, h in zip(cand_rows, ok) if h)
        tail_rows = [
            rec for idx, rec in enumerate(tails)
            if (not rec.get("cand_id")
                or last.get(str(rec.get("cand_id"))) == idx)
            and _passes(rec, None, None, False)
        ]
        hits.extend(_query_stream(iter(tail_rows), freq, freq_tol,
                                  max_harm))
        hits.sort(key=seglib.record_sort_key)
        return hits

    def coincident_groups(self, freq_tol: float = 1e-4,
                          min_sources: int = 2) -> list[list[dict]]:
        """Cross-observation groups (see
        :meth:`CandidateStore.coincident_groups`) as a SEEDED distill:
        per-frequency-bin source masks (segment sidecars + live-tail
        bins files, the reference coincidencer's per-bin beam counts
        at survey scale) select the connected bin components that
        could possibly qualify; only their records are fetched (fence
        ranges in segments, bin filter over tails) and distilled.
        Component closure under the ratio tolerance makes this
        provably record-identical to distilling the whole survey."""
        segs = self._segments()
        tails, last, _ = self._tails(segs)

        # per-bin source masks: sealed (sidecars) ∪ live (bins files,
        # gap-scanned where coverage lags the shard)
        bins = segs.bin_sources()
        for path in self.shard_files():
            base = os.path.basename(path)
            folded = segs.folded_offset(base)
            doc = seglib.load_bins_file(self.root, base)
            for key, srcs in (doc.get("bins") or {}).items():
                try:
                    b = int(key)
                except (TypeError, ValueError):
                    continue
                bins.setdefault(b, set()).update(srcs)
            gap = max(int(doc.get("covered", 0)), folded)
            for rec in _iter_records(path, start=gap):
                b = seglib.freq_bin(rec.get("freq", 0.0))
                if b is not None:
                    bins.setdefault(b, set()).add(
                        str(rec.get("source", "")))
        spans = seglib.hot_components(bins, float(freq_tol),
                                      int(min_sources))
        if not spans:
            return []
        # dense surveys (most occupied bins selected) degrade to one
        # sequential stream per segment — seeking span-by-span would
        # re-read overlapping fence strides many times over
        selected = sum(
            1 for b in bins
            if seglib.bins_in_spans(b, spans))
        dense = bins and selected >= 0.5 * len(bins)

        seed: list[dict] = []
        for i, seg in enumerate(segs.segments):
            suppressed = segs.suppressed_for(i)
            if dense:
                span_recs = seg.iter_records()
            else:
                span_recs = (
                    rec
                    for lo, hi in seglib.spans_to_freq_windows(spans)
                    for rec in seg.iter_freq_range(lo, hi))
            for rec in span_recs:
                if not seglib.bins_in_spans(
                        seglib.freq_bin(rec.get("freq", 0.0)),
                        spans):
                    continue
                cid = rec.get("cand_id")
                if cid and (cid in suppressed or cid in last):
                    continue
                if _passes(rec, None, None, False):
                    seed.append(rec)
        for idx, rec in enumerate(tails):
            cid = rec.get("cand_id")
            if cid and last.get(str(cid)) != idx:
                continue
            if not _passes(rec, None, None, False):
                continue
            if seglib.bins_in_spans(
                    seglib.freq_bin(rec.get("freq", 0.0)), spans):
                seed.append(rec)
        return _distill_groups(seed, freq_tol, min_sources)

    # -- indexed identity lookup (the ``why`` join) ------------------------

    def lookup(self, cand_id_prefix: str) -> list[tuple[dict, str]]:
        """Records whose ``cand_id`` starts with the prefix, newest
        copy only, as ``(record, origin)`` pairs — origin is the
        sealed segment's name or the live file's basename.  On a
        compacted store this is an index-key lookup (the sidecar
        ``cand_id → offset`` maps), never a shard scan; only unsealed
        tails are streamed."""
        prefix = str(cand_id_prefix)
        segs = self._segments()
        tails, last, origins = self._tails(segs)
        out: list[tuple[dict, str]] = []
        for rec, seg_name in segs.lookup_prefix(prefix):
            cid = str(rec.get("cand_id", ""))
            if cid in last:
                continue  # a live tail copy is newer
            out.append((rec, seg_name))
        for idx, rec in enumerate(tails):
            cid = str(rec.get("cand_id", ""))
            if not cid or not cid.startswith(prefix):
                continue
            if last.get(cid) != idx:
                continue  # an older duplicate within the tails
            out.append((rec, origins[idx]))
        return out
