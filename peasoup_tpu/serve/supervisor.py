"""Self-healing supervisor: the loop that ACTS on health findings.

PRs 7/10/12/14 built the sensing plane — leases, health rules, SLO
summaries, saturation knees, canaries — but a human still had to read
the findings and type ``requeue --expired`` or start another worker.
This module closes the loop: a long-running control process evaluates
the :mod:`serve.health` rules every tick and maps findings to typed,
rate-limited **actions** through an ``@supervisor_action`` registry
that mirrors ``@health_rule``:

    finding (rule, severity)  ->  action         ->  effect
    stale_host        crit    ->  reap_expired   ->  dead host's leases
                                                     reaped, jobs back
                                                     to pending/
    queue_backlog  warn/crit  ->  scale_up       ->  one more real
                                                     fleet-worker
                                                     subprocess (up to
                                                     --max-workers)
    queue_backlog     ok      ->  retire_idle    ->  newest worker
                                                     retired after
                                                     sustained empty
                                                     queue
    batch_mix      warn/crit  ->  retune_batch   ->  respawned workers
                                                     get the suggested
                                                     --batch

Safety over liveness: every action has a per-action cooldown and the
loop has a global actions-per-window cap, so a flapping rule can slow
the fleet's healing but can never thrash it.  Every EXECUTED action is
recorded three ways — a typed ``supervise_action`` event, a
``kind:"supervise"`` ledger record carrying the before/after finding
state (did the action actually clear the finding?), and the
``supervisor.json`` status snapshot under the spool root.  Dry-run
mode plans and prints but never executes.

The loop is PSL008-clean (waits via ``threading.Event.wait``) and
fully injectable — clock, sleeper-equivalent (the Event), subprocess
spawner — so the unit tests drive ticks synchronously with a fake
clock while ``tools/chaos.py`` exercises the real thing against
SIGKILLed workers.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
from dataclasses import dataclass

from ..obs.events import get_event_log
from ..obs.history import append_history, make_history_record
from ..obs.metrics import REGISTRY as METRICS
from ..obs.telemetry import TelemetrySampler, shard_path
from ..utils.atomicio import atomic_write_json
from .health import (
    CRIT,
    DEFAULT_STALE_AFTER,
    DEFAULT_WINDOW_S,
    OK,
    WARN,
    build_context,
    evaluate,
)
from .queue import DEFAULT_LEASE_TTL_S, JobSpool

#: default per-action cooldowns live on the specs; these two bound the
#: loop globally — at most MAX_ACTIONS executed in any WINDOW seconds
DEFAULT_ACTIONS_WINDOW_S = 120.0
DEFAULT_MAX_ACTIONS_PER_WINDOW = 6


# -- action registry (mirrors serve/health.py's @health_rule) --------------

@dataclass(frozen=True)
class ActionSpec:
    """One registered supervisor action: which rule/severities it
    answers, its cooldown, and the callable that does the work."""

    name: str
    rule: str
    severities: tuple
    cooldown_s: float
    fn: object

    def matches(self, finding: dict) -> bool:
        return (finding.get("rule") == self.rule
                and finding.get("severity") in self.severities)


ACTIONS: list[ActionSpec] = []


def supervisor_action(name: str, *, rule: str, severities=(CRIT,),
                      cooldown_s: float = 30.0):
    """Register an action against a health rule's findings.

    The decorated function runs as ``fn(sup, finding)`` where ``sup``
    is the :class:`Supervisor` and ``finding`` the triggering finding
    as a dict.  Return a JSON-able dict describing what was done, or
    ``None`` to declare the action inapplicable this tick (no
    cooldown consumed, nothing recorded).  Raising marks the action
    executed-with-error (cooldown consumed — a crashing action must
    not retry every tick).  See CONTRIBUTING "Adding a supervisor
    action".
    """
    def deco(fn):
        ACTIONS.append(ActionSpec(
            name=str(name), rule=str(rule),
            severities=tuple(severities),
            cooldown_s=float(cooldown_s), fn=fn))
        return fn
    return deco


# -- worker pool -----------------------------------------------------------

class WorkerPool:
    """Real ``fleet-worker`` subprocesses owned by the supervisor.

    Workers are spawned as ``sup-<n>`` with ``--host-id 0
    --host-count 1`` (fair single-host claim arbitration is the
    spool's rename, not the id) and poll forever until retired
    (SIGTERM).  ``popen`` is injectable so tests can count spawns
    without forking; ``batch`` is mutable — retune_batch changes it
    and the next spawn picks it up (running workers keep theirs)."""

    def __init__(self, spool_root: str, *, max_workers: int = 2,
                 batch: int = 1, worker_args=None, popen=None,
                 env=None):
        self.spool_root = str(spool_root)
        self.max_workers = int(max_workers)
        self.batch = int(batch)
        self.worker_args = list(worker_args or [])
        self._popen = popen or subprocess.Popen
        self.env = env
        self.spawned = 0
        self.procs: list[dict] = []

    def _cmd(self, label: str) -> list[str]:
        return [sys.executable, "-m", "peasoup_tpu.serve",
                "--spool", self.spool_root, "fleet-worker",
                "--host-id", "0", "--host-count", "1",
                "--label", label,
                "--batch", str(self.batch)] + self.worker_args

    def reap(self) -> None:
        """Forget workers whose process exited (crashed or killed —
        the lease reaper recovers their jobs; the pool just frees the
        slot so scale_up can replace them)."""
        self.procs = [w for w in self.procs
                      if w["proc"].poll() is None]

    def alive(self) -> list[dict]:
        self.reap()
        return list(self.procs)

    def spawn(self) -> dict | None:
        """Start one more worker, or None at ``max_workers``."""
        if len(self.alive()) >= self.max_workers:
            return None
        label = f"sup-{self.spawned}"
        self.spawned += 1
        proc = self._popen(self._cmd(label), env=self.env)
        info = {"label": label, "pid": int(getattr(proc, "pid", 0)),
                "batch": self.batch, "proc": proc}
        self.procs.append(info)
        return info

    def retire(self) -> dict | None:
        """SIGTERM the newest worker (LIFO keeps the longest-running
        worker's warm compile cache alive), or None if the pool is
        empty."""
        live = self.alive()
        if not live:
            return None
        info = live[-1]
        try:
            info["proc"].terminate()
        except OSError:
            pass
        self.procs.remove(info)
        return info

    def stop_all(self, timeout_s: float = 10.0) -> None:
        for info in list(self.procs):
            try:
                info["proc"].terminate()
            except OSError:
                pass
        for info in list(self.procs):
            try:
                info["proc"].wait(timeout=timeout_s)
            except Exception:
                try:
                    info["proc"].kill()
                except OSError:
                    pass
        self.procs = []

    def describe(self) -> list[dict]:
        return [{"label": w["label"], "pid": w["pid"],
                 "batch": w["batch"]} for w in self.alive()]


# -- the built-in actions --------------------------------------------------

@supervisor_action("reap_expired", rule="stale_host",
                   severities=(CRIT,), cooldown_s=10.0)
def action_reap_expired(sup: "Supervisor", finding: dict) -> dict:
    """A silent host holds running-job leases: run the reaper the
    operator would have run.  Reaping zero jobs is still an executed
    action (the lease may simply not have aged past the TTL yet; the
    cooldown paces the retries)."""
    reaped = sup.spool.reap_expired(sup.lease_ttl_s, now=sup.clock())
    return {"reaped": len(reaped),
            "job_ids": [r.job_id for r in reaped][:16]}


@supervisor_action("scale_up", rule="queue_backlog",
                   severities=(WARN, CRIT), cooldown_s=15.0)
def action_scale_up(sup: "Supervisor", finding: dict) -> dict | None:
    """Backlog trending up: add one real fleet-worker, bounded by the
    pool's ``max_workers``.  One worker per firing — the cooldown
    spaces spawns so the backlog trend can react before the next."""
    sup.idle_ticks = 0
    info = sup.pool.spawn()
    if info is None:
        return None  # already at capacity — nothing to do
    return {"spawned": info["label"], "pid": info["pid"],
            "batch": info["batch"],
            "workers_alive": len(sup.pool.alive())}


@supervisor_action("retire_idle", rule="queue_backlog",
                   severities=(OK,), cooldown_s=30.0)
def action_retire_idle(sup: "Supervisor", finding: dict) -> dict | None:
    """Sustained empty queue: retire the newest worker.  Requires
    ``low_depth_ticks`` consecutive idle ticks (queue AND running
    empty) so a momentary lull between submit waves doesn't churn
    workers."""
    counts = sup.spool.counts()
    if counts.get("pending", 0) or counts.get("running", 0):
        sup.idle_ticks = 0
        return None
    sup.idle_ticks += 1
    if sup.idle_ticks < sup.low_depth_ticks or not sup.pool.alive():
        return None
    info = sup.pool.retire()
    if info is None:
        return None
    return {"retired": info["label"], "pid": info["pid"],
            "idle_ticks": sup.idle_ticks,
            "workers_alive": len(sup.pool.alive())}


@supervisor_action("retune_batch", rule="batch_mix",
                   severities=(WARN, CRIT), cooldown_s=60.0)
def action_retune_batch(sup: "Supervisor", finding: dict) -> dict | None:
    """Bucket-mix drift: adopt the rule's ``suggest_batch`` for future
    spawns (running workers keep their batch; the pool applies the new
    value when scale_up next fires or a crashed worker is replaced)."""
    suggest = int((finding.get("data") or {}).get("suggest_batch")
                  or 0)
    if suggest < 1:
        return None
    new = min(suggest, sup.max_batch)
    if new == sup.pool.batch:
        return None
    old = sup.pool.batch
    sup.pool.batch = new
    return {"batch_old": old, "batch_new": new}


@supervisor_action("compact_store", rule="shard_backlog",
                   severities=(WARN, CRIT), cooldown_s=60.0)
def action_compact_store(sup: "Supervisor", finding: dict) -> dict | None:
    """Unsealed shard tails past the compaction threshold
    (rule_shard_backlog, ISSUE 20): fold them into sealed, indexed
    segments so science queries stop paying for the backlog.  Runs
    the compactor in-process under its own store-level lock; the
    action cooldown rate-limits the supervisor side, the lock
    serialises against any operator-run ``compact`` verb.  A lost
    lock race is inapplicable (None), not an error — someone else is
    already folding."""
    from .compaction import Compactor, CompactionPolicy

    report = Compactor(
        sup.spool.root,
        CompactionPolicy(min_bytes=1),  # the RULE decided pressure;
        # fold every live tail rather than re-litigating thresholds
        clock=sup.clock,
    ).compact_once()
    if not report.get("compacted"):
        # locked (another compactor is folding) or nothing left to
        # fold (the finding raced a compaction): inapplicable, keep
        # the cooldown and actions budget for real work
        return None
    return report


# -- the control loop ------------------------------------------------------

class Supervisor:
    """Evaluate health each tick; map findings to rate-limited actions.

    Injectables: ``clock`` (token buckets, cooldowns, ledger stamps),
    ``pool`` (a :class:`WorkerPool` or a test double), and the tick
    wait runs on a ``threading.Event`` so ``stop()`` — e.g. from a
    SIGTERM handler — interrupts a sleeping loop immediately.
    ``telemetry_interval_s > 0`` runs the supervisor's own
    :class:`TelemetrySampler` (host label ``supervisor``) carrying
    queue depths, so the backlog trend stays observable even when
    every worker is dead — exactly the moment scale_up is needed.
    """

    def __init__(self, spool: JobSpool, *, pool: WorkerPool | None = None,
                 interval_s: float = 10.0,
                 lease_ttl_s: float = DEFAULT_LEASE_TTL_S,
                 max_workers: int = 2, dry_run: bool = False,
                 actions_window_s: float = DEFAULT_ACTIONS_WINDOW_S,
                 max_actions_per_window: int =
                 DEFAULT_MAX_ACTIONS_PER_WINDOW,
                 cooldowns: dict | None = None,
                 history_path: str | None = None,
                 ledger_path: str | None = None,
                 window_s: float = DEFAULT_WINDOW_S,
                 stale_after: float = DEFAULT_STALE_AFTER,
                 slo: dict | None = None,
                 low_depth_ticks: int = 3, max_batch: int = 8,
                 telemetry_interval_s: float = 0.0,
                 clock=None, out=print):
        self.spool = spool
        self.pool = pool if pool is not None else WorkerPool(
            spool.root, max_workers=max_workers)
        self.interval_s = float(interval_s)
        self.lease_ttl_s = float(lease_ttl_s)
        self.dry_run = bool(dry_run)
        self.actions_window_s = float(actions_window_s)
        self.max_actions_per_window = int(max_actions_per_window)
        self.cooldowns = dict(cooldowns or {})
        self.history_path = history_path
        self.ledger_path = ledger_path
        self.window_s = float(window_s)
        self.stale_after = float(stale_after)
        self.slo = slo
        self.low_depth_ticks = int(low_depth_ticks)
        self.max_batch = int(max_batch)
        self.telemetry_interval_s = float(telemetry_interval_s)
        self.clock = clock or time.time
        self.out = out
        self.idle_ticks = 0
        self.tick_count = 0
        self.actions_taken: list[dict] = []
        self._last_fired: dict[str, float] = {}
        self._exec_times: list[float] = []
        self._stop = threading.Event()

    # -- planning ----------------------------------------------------------

    def _context(self, now: float):
        return build_context(
            self.spool, now=now, window_s=self.window_s,
            stale_after=self.stale_after, slo=self.slo,
            ledger_path=self.ledger_path)

    def plan(self, report: dict) -> list[tuple[ActionSpec, dict]]:
        """Match findings to registered actions; one firing per action
        per tick (reap_expired covers every stale host in one call, so
        N crit hosts still plan a single reap)."""
        out = []
        fired = set()
        for finding in report.get("findings", []):
            for spec in ACTIONS:
                if spec.name in fired or not spec.matches(finding):
                    continue
                fired.add(spec.name)
                out.append((spec, dict(finding)))
        return out

    def _throttled(self, spec: ActionSpec, now: float) -> str | None:
        """Cooldown / global-cap gate; returns the refusal reason or
        None (clear to execute)."""
        cooldown = float(self.cooldowns.get(spec.name,
                                            spec.cooldown_s))
        last = self._last_fired.get(spec.name)
        if last is not None and now - last < cooldown:
            return (f"cooldown: {now - last:.1f}s since last "
                    f"{spec.name} < {cooldown:.1f}s")
        self._exec_times = [t for t in self._exec_times
                            if now - t <= self.actions_window_s]
        if len(self._exec_times) >= self.max_actions_per_window:
            return (f"global cap: {len(self._exec_times)} action(s) "
                    f"in the last {self.actions_window_s:.0f}s "
                    f"(max {self.max_actions_per_window})")
        return None

    # -- execution ---------------------------------------------------------

    def _finding_for_rule(self, rule: str, now: float) -> dict | None:
        """Re-evaluate and return the worst finding for one rule (the
        'after' state recorded with each action)."""
        report = evaluate(self._context(now))
        best = None
        for finding in report.get("findings", []):
            if finding.get("rule") != rule:
                continue
            if best is None or (finding.get("severity") != OK
                                and best.get("severity") == OK):
                best = finding
        return best

    def _record(self, spec: ActionSpec, before: dict, after,
                outcome: dict, now: float) -> None:
        severity_before = before.get("severity", "")
        severity_after = (after or {}).get("severity", "")
        get_event_log().emit(
            "supervise_action",
            f"supervisor action {spec.name} for rule {spec.rule} "
            f"({severity_before} -> {severity_after or '?'})",
            action=spec.name, rule=spec.rule,
            severity_before=severity_before,
            severity_after=severity_after, outcome=outcome)
        counts = self.spool.counts()
        rec = make_history_record(
            "supervise",
            {"tick": self.tick_count,
             "workers_alive": len(self.pool.alive()),
             "queue_pending": counts.get("pending", 0),
             "queue_running": counts.get("running", 0)},
            config={"spool": self.spool.root, "action": spec.name,
                    "dry_run": self.dry_run},
            extra={"action": {
                "name": spec.name, "rule": spec.rule,
                "cooldown_s": float(self.cooldowns.get(
                    spec.name, spec.cooldown_s)),
                "outcome": outcome,
                "finding_before": before,
                "finding_after": after,
            }})
        append_history(rec, self.history_path)

    def tick(self) -> list[dict]:
        """One control cycle: evaluate -> plan -> gate -> execute ->
        record.  Returns one result dict per planned action."""
        now = float(self.clock())
        self.tick_count += 1
        report = evaluate(self._context(now))
        results = []
        for spec, finding in self.plan(report):
            entry = {"action": spec.name, "rule": spec.rule,
                     "severity": finding.get("severity", ""),
                     "executed": False}
            if self.dry_run:
                entry["dry_run"] = True
                self.out(f"supervise[dry-run]: would run {spec.name} "
                         f"for {spec.rule} "
                         f"({finding.get('severity')}): "
                         f"{finding.get('message', '')}")
                results.append(entry)
                continue
            reason = self._throttled(spec, now)
            if reason is not None:
                entry["throttled"] = reason
                METRICS.inc("supervisor.throttled")
                results.append(entry)
                continue
            try:
                outcome = spec.fn(self, finding)
            except Exception as exc:  # a crashing action is an outcome
                outcome = {"error": f"{type(exc).__name__}: {exc}"}
            if outcome is None:
                continue  # inapplicable — no cooldown, no record
            self._last_fired[spec.name] = now
            self._exec_times.append(now)
            METRICS.inc("supervisor.actions")
            METRICS.inc(f"supervisor.action.{spec.name}")
            after = self._finding_for_rule(spec.rule, self.clock())
            self._record(spec, finding, after, outcome, now)
            entry.update(executed=True, outcome=outcome,
                         severity_after=(after or {}).get(
                             "severity", ""))
            self.actions_taken.append(entry)
            self.out(f"supervise: {spec.name} for {spec.rule} "
                     f"({finding.get('severity')}) -> {outcome}")
            results.append(entry)
        self.write_status(report, results)
        return results

    # -- status / lifecycle ------------------------------------------------

    def status_path(self) -> str:
        return os.path.join(self.spool.root, "supervisor.json")

    def write_status(self, report: dict, results: list[dict]) -> None:
        """Atomic ``supervisor.json`` snapshot (NOT under fleet/ — it
        is not a worker host status).  The chaos harness reads worker
        pids from here."""
        doc = {
            "v": 1,
            "utc": round(float(self.clock()), 3),
            "pid": os.getpid(),
            "tick": self.tick_count,
            "dry_run": self.dry_run,
            "interval_s": self.interval_s,
            "severity": report.get("severity", ""),
            "queue": report.get("queue", {}),
            "workers": self.pool.describe(),
            "batch": self.pool.batch,
            "actions_total": len(self.actions_taken),
            "last_results": results[-8:],
        }
        try:
            atomic_write_json(self.status_path(), doc, sort_keys=True,
                              trailing_newline=True)
        except OSError:
            pass  # status is advisory; the loop must not die for it

    def stop(self) -> None:
        self._stop.set()

    def run(self, ticks: int = 0) -> int:
        """Run the loop: forever (``ticks=0``) or a fixed tick count.
        Returns ticks executed.  The caller owns pool shutdown (the
        CLI stops it; tests may want the workers to outlive a run)."""
        sampler = None
        if self.telemetry_interval_s > 0:
            fleet_dir = os.path.join(self.spool.root, "fleet")
            sampler = TelemetrySampler(
                shard_path(fleet_dir, "supervisor"), "supervisor",
                self.telemetry_interval_s,
                extras=lambda: {"queue": self.spool.counts()})
            sampler.start()
        done = 0
        try:
            while not self._stop.is_set():
                self.tick()
                done += 1
                if ticks and done >= ticks:
                    break
                if self._stop.wait(self.interval_s):
                    break
        finally:
            if sampler is not None:
                sampler.stop()
        return done
