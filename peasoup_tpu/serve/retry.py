"""Failure classification, bounded backoff, per-job timeout.

One corrupt beam must never stall a survey queue: a failure is either
**quarantine** (deterministic — the same input + config will fail the
same way every time: malformed filterbank, bad overrides, out-of-
domain parameters, un-fittable HBM budget) and goes straight to
``failed/``, or **retry** (possibly transient — a flaky device, a
preempted slice, an interrupted fetch) and goes back to ``pending/``
after an exponential-backoff delay, up to ``max_attempts``.

This module is also the ONE place in the codebase allowed to call
``time.sleep`` (lint rule PSL008): every scheduler wait routes through
:func:`pause` / :class:`BackoffPolicy`, so waits are bounded,
classified, and injectable in tests (pass a fake ``sleeper``).
"""

from __future__ import annotations

import os
import random
import threading
import time
from dataclasses import dataclass

from ..errors import (
    ConfigError,
    DomainError,
    HBMBudgetError,
    InputFileError,
    PeasoupError,
)
from ..obs.events import warn_event
from ..obs.metrics import REGISTRY as METRICS

#: classification labels stored on the job's failure log
QUARANTINE = "quarantine"
RETRY = "retry"


class JobTimeoutError(PeasoupError, RuntimeError):
    """A job exceeded its per-job wall-clock budget."""


def classify_failure(exc: BaseException) -> str:
    """``QUARANTINE`` for deterministic failures, ``RETRY`` otherwise.

    Deterministic means re-running the identical job cannot succeed:
    * :class:`InputFileError` — truncated/malformed filterbank or
      sidecar (io/sigproc.py raises this with the byte counts);
    * :class:`ConfigError` / :class:`DomainError` — the job's
      overrides are invalid or numerically out of domain;
    * :class:`HBMBudgetError` — the search cannot fit the configured
      budget even after chunking;
    * a missing/unreadable input path.

    Everything else — including :class:`JobTimeoutError` and raw
    ``RuntimeError`` from a flaky device — is worth a bounded retry.
    New failure classes: add the mapping here WITH a test in
    ``tests/test_serve.py`` (see CONTRIBUTING "Failure
    classification").
    """
    if isinstance(exc, (InputFileError, ConfigError, DomainError,
                        HBMBudgetError)):
        return QUARANTINE
    if isinstance(exc, (FileNotFoundError, IsADirectoryError,
                        NotADirectoryError, PermissionError)):
        return QUARANTINE
    return RETRY


#: process-wide jitter source for workers that enable ``jitter`` but
#: don't inject their own rng — seeded from the pid so N fleet-worker
#: processes retrying the same transient fault draw DIFFERENT delay
#: sequences (decorrelated), yet each process is deterministic.
_JITTER_RNG = random.Random(os.getpid())


@dataclass(frozen=True)
class BackoffPolicy:
    """Bounded exponential backoff: attempt ``k`` (1-based) waits
    ``min(base_s * factor**(k-1), max_s)`` before re-queueing.

    ``jitter`` (fraction in [0, 1)) decorrelates the herd: the delay is
    drawn uniformly from ``[d*(1-jitter), d*(1+jitter)]`` (still capped
    at ``max_s``), so N workers that hit the same transient fault at
    the same instant do not hammer the spool in lock-step on every
    retry wave.  Default 0.0 keeps delays exact for schedulers/tests
    that assert on them; pass ``rng`` (a ``random.Random``) to make the
    jittered sequence reproducible."""

    max_attempts: int = 3
    base_s: float = 1.0
    factor: float = 2.0
    max_s: float = 60.0
    jitter: float = 0.0
    rng: random.Random | None = None

    def delay_for(self, attempt: int) -> float:
        k = max(int(attempt), 1)
        d = float(min(self.base_s * self.factor ** (k - 1), self.max_s))
        j = float(self.jitter)
        if j > 0.0 and d > 0.0:
            rng = self.rng if self.rng is not None else _JITTER_RNG
            d *= 1.0 - j + 2.0 * j * rng.random()
            d = float(min(d, self.max_s))
        return d

    def exhausted(self, attempt: int) -> bool:
        return int(attempt) >= self.max_attempts


def pause(seconds: float, sleeper=None) -> None:
    """The one sanctioned wait (PSL008).  ``sleeper`` is injectable so
    tests assert on delays instead of serving them."""
    if seconds and seconds > 0:
        (sleeper or time.sleep)(float(seconds))


#: daemon threads abandoned by :func:`run_with_timeout` — they cannot
#: be cancelled, but they must not be *invisible*: `abandoned_count()`
#: prunes the dead and reports how many are still burning a device,
#: and the host status snapshot surfaces the number per host.
_ABANDONED: list = []
_ABANDONED_LOCK = threading.Lock()


def abandoned_count() -> int:
    """Live count of timed-out job threads still running in this
    process (each may still hold a device until its dispatch returns).
    Finished threads are pruned on every call."""
    with _ABANDONED_LOCK:
        _ABANDONED[:] = [t for t in _ABANDONED if t.is_alive()]
        return len(_ABANDONED)


def run_with_timeout(fn, timeout_s: float, label: str = "job"):
    """Run ``fn()`` with a wall-clock budget.

    ``timeout_s <= 0`` runs inline (no thread).  On timeout a
    :class:`JobTimeoutError` is raised — classified as RETRY — and the
    worker thread is abandoned as a daemon (a blocked XLA dispatch
    cannot be interrupted from Python; the abandoned attempt finishes
    or dies with the process, and the job record has already moved
    on).  Every abandonment is accounted: ``scheduler.timeout_abandoned``
    counter + ``job_timeout_abandoned`` event + the live count from
    :func:`abandoned_count` in the host status snapshot, so a worker
    quietly accumulating zombie dispatches is visible to `health`.
    Exceptions from ``fn`` propagate unchanged.
    """
    if not timeout_s or timeout_s <= 0:
        return fn()
    box: dict = {}

    def _target():
        try:
            box["result"] = fn()
        except BaseException as exc:  # propagated to the caller below
            box["error"] = exc

    t = threading.Thread(target=_target, daemon=True,
                         name=f"serve-{label}")
    t.start()
    t.join(float(timeout_s))
    if t.is_alive():
        with _ABANDONED_LOCK:
            _ABANDONED.append(t)
        METRICS.inc("scheduler.timeout_abandoned")
        live = abandoned_count()
        warn_event(
            "job_timeout_abandoned",
            f"{label} timed out after {timeout_s:.1f}s; its attempt "
            f"thread keeps running detached ({live} live abandoned "
            f"thread(s) in this process)",
            label=str(label), timeout_s=float(timeout_s),
            live_abandoned=int(live))
        raise JobTimeoutError(
            f"{label} exceeded its {timeout_s:.1f}s budget (the "
            f"attempt thread is abandoned; the job is eligible for "
            f"retry)")
    if "error" in box:
        raise box["error"]
    return box.get("result")
