"""Durable on-disk job spool for survey scheduling.

One job = one observation: an input filterbank path plus its
``SearchConfig`` overrides, a priority and an attempt count.  Layout
(one JSON record file per job under the spool root)::

    <spool>/pending/<job_id>.json    submitted, claimable
    <spool>/running/<job_id>.json    claimed by a worker
    <spool>/done/<job_id>.json       finished, result summary attached
    <spool>/failed/<job_id>.json     quarantined or retry-exhausted
    <spool>/work/<job_id>/           per-job scratch: checkpoint file,
                                     output directory, failure reports,
                                     lifecycle timeline.jsonl
                                     (obs/timeline.py)
    <spool>/leases/<job_id>.json     claim lease: host + worker +
                                     heartbeat time of the claimer
    <spool>/fleet/<host>.json        per-host status snapshot
                                     (serve/fleet.py)
    <spool>/candidates.jsonl         cross-run candidate store
                                     (serve/store.py default path)
    <spool>/store-<host>.jsonl       per-host store shards in fleet
                                     mode (serve/store.py)

A job changes state by ``os.rename`` of its record file — atomic on
POSIX — so any number of worker processes on one machine can claim
from the same spool with no lock service: exactly one rename wins,
the losers get ``FileNotFoundError`` and try the next candidate.
This is the reference's pthread-mutex trial dispenser
(`pipeline_multi.cu:33-46`) lifted to observation granularity, with
the queue surviving process death.  Record *contents* are always
rewritten in place (tmp + ``os.replace``) BEFORE the state rename, so
a reader never sees a torn or stale record in the new state.

Fleet hardening (multi-HOST spools on a shared filesystem): a claim
additionally stamps the record with the claimer's ``host`` and drops
a lease file that the owner's heartbeat keeps fresh while the job
runs.  A host that dies mid-job stops heartbeating, and ANY surviving
host's :meth:`JobSpool.reap_expired` — run by every fleet worker when
idle — returns the job to ``pending/`` with a ``lease_expired`` entry
appended to its failure log (attempt history intact), generalising
the operator-driven ``requeue`` to automatic dead-host recovery.
``os.rename`` atomicity is the arbiter for reapers exactly as for
claimers, so concurrent reapers converge on one pending record.
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import asdict, dataclass, field

from ..errors import ConfigError
from ..obs import timeline
from ..obs.events import warn_event
from ..obs.metrics import REGISTRY as METRICS

#: spool subdirectories, in lifecycle order
STATES = ("pending", "running", "done", "failed")

#: failure-log classification stamped by the lease reaper (alongside
#: serve/retry.py's QUARANTINE / RETRY, which classify exceptions)
LEASE_EXPIRED = "lease_expired"

#: default lease time-to-live; owners heartbeat at ~TTL/3, so a lease
#: only expires after several consecutive missed beats
DEFAULT_LEASE_TTL_S = 120.0

_RECORD_VERSION = 1


@dataclass
class JobRecord:
    """One observation job (the JSON record's in-memory face)."""

    job_id: str
    input: str
    priority: int = 0
    overrides: dict = field(default_factory=dict)
    attempts: int = 0
    submitted_utc: float = 0.0
    claimed_utc: float = 0.0
    finished_utc: float = 0.0
    worker: str = ""
    #: fleet host label of the claimer ("" pre-fleet / single host)
    host: str = ""
    #: one entry per failed attempt: {utc, t_mono, attempt,
    #: classification, error, traceback, run_report}
    failures: list = field(default_factory=list)
    #: submit->claim wait of the LAST claim, from timeline marks when
    #: available (monotonic within a process, wall-clamped across
    #: processes — never negative even across clock steps)
    queue_wait_s: float = 0.0
    #: success summary (candidate counts, outdir) set by mark_done
    summary: dict = field(default_factory=dict)
    #: injection manifest for canary jobs (obs/injection.py, ISSUE 14):
    #: a known synthetic pulsar the worker must recover on completion.
    #: Empty dict = a normal science job; pre-canary records load
    #: unchanged through from_obj's known-field filter
    canary: dict = field(default_factory=dict)
    v: int = _RECORD_VERSION

    def to_json(self) -> str:
        return json.dumps(asdict(self), sort_keys=True)

    @classmethod
    def from_obj(cls, obj: dict) -> "JobRecord":
        known = {f for f in cls.__dataclass_fields__}
        return cls(**{k: v for k, v in obj.items() if k in known})


def _new_job_id() -> str:
    """Unique, roughly submit-ordered id (ns timestamp + random tail:
    two submits in the same nanosecond still cannot collide)."""
    return f"{time.time_ns():016x}-{os.urandom(3).hex()}"


class JobSpool:
    """Priority job queue over the directory layout above."""

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        for state in STATES:
            os.makedirs(os.path.join(self.root, state), exist_ok=True)
        os.makedirs(os.path.join(self.root, "work"), exist_ok=True)
        os.makedirs(os.path.join(self.root, "leases"), exist_ok=True)

    # -- paths -------------------------------------------------------------

    def _path(self, state: str, job_id: str) -> str:
        return os.path.join(self.root, state, f"{job_id}.json")

    def _lease_path(self, job_id: str) -> str:
        return os.path.join(self.root, "leases", f"{job_id}.json")

    def work_dir(self, job_id: str) -> str:
        """Per-job scratch directory (checkpoint, outputs, reports)."""
        d = os.path.join(self.root, "work", job_id)
        os.makedirs(d, exist_ok=True)
        return d

    def _mark(self, rec: JobRecord, phase: str, **attrs) -> None:
        """Best-effort lifecycle mark in the job's timeline
        (obs/timeline.py) — every spool transition leaves one, so the
        ``timeline`` verb can reconstruct the job's waterfall across
        submitter/worker/reaper processes."""
        timeline.mark(
            os.path.join(self.root, "work", rec.job_id), phase,
            host=rec.host, attempt=rec.attempts, **attrs)

    def _observe_queue_wait(self, rec: JobRecord) -> None:
        """Record submit->claim wait, preferring timeline marks: same
        process uses the monotonic clock (exact across wall steps),
        cross-process uses a wall delta clamped at >= 0.  Only the
        pre-timeline fallback still subtracts raw wall stamps."""
        wait = timeline.queue_wait_from(
            os.path.join(self.root, "work", rec.job_id),
            host=rec.host, t_wall=rec.claimed_utc)
        if wait is None:
            wait = max(0.0, rec.claimed_utc - rec.submitted_utc)
        rec.queue_wait_s = round(wait, 6)
        METRICS.observe("queue_wait", wait)

    # -- record I/O --------------------------------------------------------

    def _write(self, path: str, rec: JobRecord) -> None:
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(rec.to_json() + "\n")
        os.replace(tmp, path)

    def _read(self, path: str) -> JobRecord | None:
        try:
            with open(path) as f:
                obj = json.load(f)
            return JobRecord.from_obj(obj)
        except FileNotFoundError:
            return None
        except (OSError, ValueError, TypeError) as exc:
            warn_event(
                "job_record_corrupt",
                f"unreadable job record {path!r}: {exc}",
                path=path, error=str(exc),
            )
            return None

    # -- submit / claim ----------------------------------------------------

    def submit(self, input_path: str, overrides: dict | None = None,
               priority: int = 0,
               canary: dict | None = None) -> JobRecord:
        """Enqueue one observation; returns the pending record.

        ``canary``: injection manifest dict for a known-answer canary
        job — the worker matches the result against it on completion
        and the store tags its candidates out of science queries.
        """
        rec = JobRecord(
            job_id=_new_job_id(),
            input=os.path.abspath(input_path),
            priority=int(priority),
            overrides=dict(overrides or {}),
            canary=dict(canary or {}),
            submitted_utc=time.time(),
        )
        self._write(self._path("pending", rec.job_id), rec)
        self._mark(rec, "submit", t_wall=rec.submitted_utc,
                   priority=rec.priority)
        METRICS.inc("scheduler.submitted")
        return rec

    def pending_jobs(self) -> list[JobRecord]:
        """Claimable jobs, best-first: priority descending, then
        submit time (FIFO within a priority band)."""
        out = []
        pend = os.path.join(self.root, "pending")
        for name in os.listdir(pend):
            if not name.endswith(".json"):
                continue
            rec = self._read(os.path.join(pend, name))
            if rec is not None:
                out.append(rec)
        out.sort(key=lambda r: (-r.priority, r.submitted_utc, r.job_id))
        return out

    def peek(self) -> JobRecord | None:
        """Best pending job WITHOUT claiming it (the worker's prefetch
        hint; another worker may still win the claim)."""
        jobs = self.pending_jobs()
        return jobs[0] if jobs else None

    def claim(self, worker: str = "", host: str = "") -> JobRecord | None:
        """Claim the best pending job via atomic rename, or None.

        Safe against concurrent claimers — on one machine or across
        hosts sharing the spool filesystem: the rename is the arbiter,
        a lost race just moves on to the next candidate.  The winner's
        record carries ``worker`` and ``host``, and a lease file is
        dropped for the reaper (kept fresh via :meth:`heartbeat`).
        """
        for rec in self.pending_jobs():
            src = self._path("pending", rec.job_id)
            dst = self._path("running", rec.job_id)
            try:
                os.rename(src, dst)
            except FileNotFoundError:
                continue  # another worker won this one
            rec.worker = worker
            rec.host = host
            rec.claimed_utc = time.time()
            rec.attempts += 1
            self._observe_queue_wait(rec)
            self._write(dst, rec)
            self.heartbeat(rec)
            self._mark(rec, "claim", t_wall=rec.claimed_utc,
                       worker=worker)
            METRICS.inc("scheduler.claimed")
            return rec
        return None

    def claim_job(self, job_id: str, worker: str = "",
                  host: str = "") -> JobRecord | None:
        """Claim one SPECIFIC pending job, or None (gone / lost race).

        The batched worker uses this to pull same-geometry batch-mates
        out of queue order once it holds a leader job: the same atomic
        pending->running rename arbitrates against concurrent
        claimers, so a lost race simply means a smaller batch.
        """
        src = self._path("pending", job_id)
        rec = self._read(src)
        if rec is None:
            return None
        dst = self._path("running", job_id)
        try:
            os.rename(src, dst)
        except FileNotFoundError:
            return None  # another worker won this one
        rec.worker = worker
        rec.host = host
        rec.claimed_utc = time.time()
        rec.attempts += 1
        self._observe_queue_wait(rec)
        self._write(dst, rec)
        self.heartbeat(rec)
        self._mark(rec, "claim", t_wall=rec.claimed_utc,
                   worker=worker)
        METRICS.inc("scheduler.claimed")
        return rec

    # -- leases (fleet hardening) ------------------------------------------

    def heartbeat(self, rec: JobRecord) -> None:
        """Refresh the claimer's lease on a running job (atomic
        rewrite).  Written on claim and then every ~TTL/3 by the
        owner's heartbeat thread (serve/fleet.py LeaseHeartbeat), so a
        fresh lease means the owning host is demonstrably alive."""
        path = self._lease_path(rec.job_id)
        tmp = path + f".tmp{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({
                "v": 1,
                "job_id": rec.job_id,
                "worker": rec.worker,
                "host": rec.host,
                "attempt": rec.attempts,
                "utc": round(time.time(), 3),
            }, f)
        os.replace(tmp, path)

    def lease_info(self, job_id: str) -> dict | None:
        """The job's lease record, or None (missing/corrupt — a torn
        lease reads as 'no heartbeat', never as an error)."""
        try:
            with open(self._lease_path(job_id)) as f:
                obj = json.load(f)
            return obj if isinstance(obj, dict) else None
        except (OSError, ValueError):
            return None

    def _clear_lease(self, job_id: str) -> None:
        try:
            os.remove(self._lease_path(job_id))
        except OSError:
            pass

    def reap_expired(self, ttl_s: float = DEFAULT_LEASE_TTL_S,
                     now: float | None = None) -> list[JobRecord]:
        """Return every running job whose lease went stale to
        ``pending/`` — automatic dead-host recovery.

        A job is reaped when its last heartbeat (falling back to the
        claim time for pre-lease records) is more than ``ttl_s``
        seconds old.  The reaped record keeps its attempt count and
        gains a :data:`LEASE_EXPIRED` entry in the failure log, so the
        next claimer sees the full history; the worker/host stamps are
        cleared.  Concurrent reapers race on the running->pending
        rename exactly like claimers race on pending->running: losers
        skip.  ``now`` is injectable for tests.
        """
        now = time.time() if now is None else float(now)
        reaped = []
        for rec in self.jobs("running"):
            lease = self.lease_info(rec.job_id)
            beat = (lease or {}).get("utc") or rec.claimed_utc \
                or rec.submitted_utc
            age = now - float(beat)
            if age <= float(ttl_s):
                continue
            dead_host = rec.host or (lease or {}).get("host") or "?"
            rec.failures.append({
                "utc": round(now, 3),
                "t_mono": round(time.perf_counter(), 6),
                "attempt": rec.attempts,
                "classification": LEASE_EXPIRED,
                "error": (f"lease expired after {age:.1f}s "
                          f"(ttl {float(ttl_s):.1f}s; last owner "
                          f"{rec.worker or '?'} on host {dead_host})"),
            })
            rec.worker = ""
            rec.host = ""
            try:
                self._transition(rec, "running", "pending")
            except (ConfigError, OSError):
                continue  # another reaper won this one
            self._clear_lease(rec.job_id)
            self._mark(rec, "reap", dead_host=dead_host)
            warn_event(
                "job_lease_expired",
                f"job {rec.job_id} reaped after {age:.1f}s without a "
                f"heartbeat from host {dead_host}; re-queued with "
                f"attempt history intact",
                job_id=rec.job_id, host=dead_host, age_s=round(age, 1),
                ttl_s=float(ttl_s), attempt=rec.attempts,
            )
            METRICS.inc("scheduler.lease_reaped")
            reaped.append(rec)
        return reaped

    # -- state transitions (record rewritten BEFORE the rename) ------------

    def _transition(self, rec: JobRecord, src_state: str,
                    dst_state: str) -> None:
        src = self._path(src_state, rec.job_id)
        if not os.path.exists(src):
            raise ConfigError(
                f"job {rec.job_id} is not in {src_state}/ (spool "
                f"{self.root})")
        self._write(src, rec)
        os.rename(src, self._path(dst_state, rec.job_id))

    def update(self, rec: JobRecord, state: str = "running") -> None:
        """Rewrite a record in place (attempt metadata, failure log)."""
        self._write(self._path(state, rec.job_id), rec)

    def mark_done(self, rec: JobRecord, summary: dict | None = None) -> None:
        rec.finished_utc = time.time()
        if summary:
            rec.summary = dict(summary)
        self._transition(rec, "running", "done")
        self._clear_lease(rec.job_id)
        self._mark(rec, "done", t_wall=rec.finished_utc)

    def mark_failed(self, rec: JobRecord) -> None:
        """running -> failed (the failure log on the record says why:
        quarantined input vs exhausted retries)."""
        rec.finished_utc = time.time()
        self._transition(rec, "running", "failed")
        self._clear_lease(rec.job_id)
        self._mark(rec, "failed", t_wall=rec.finished_utc)

    def release(self, rec: JobRecord) -> None:
        """running -> pending for a bounded retry (attempt count and
        failure log travel with the record)."""
        self._transition(rec, "running", "pending")
        self._clear_lease(rec.job_id)
        self._mark(rec, "release")

    def requeue(self, job_id: str) -> JobRecord:
        """Recover a job from ``running/`` (crashed worker) or
        ``failed/`` (operator retry) back to ``pending/``."""
        for state in ("running", "failed"):
            path = self._path(state, job_id)
            rec = self._read(path)
            if rec is not None:
                rec.worker = ""
                rec.host = ""
                self._transition(rec, state, "pending")
                self._clear_lease(rec.job_id)
                self._mark(rec, "requeue", from_state=state)
                METRICS.inc("scheduler.requeued")
                return rec
        raise ConfigError(
            f"job {job_id!r} not found in running/ or failed/ "
            f"(spool {self.root})")

    # -- inspection --------------------------------------------------------

    def get(self, job_id: str) -> tuple[str, JobRecord] | None:
        for state in STATES:
            rec = self._read(self._path(state, job_id))
            if rec is not None:
                return state, rec
        return None

    def jobs(self, state: str) -> list[JobRecord]:
        if state not in STATES:
            raise ConfigError(
                f"unknown spool state {state!r}; use one of {STATES}")
        d = os.path.join(self.root, state)
        out = []
        for name in sorted(os.listdir(d)):
            if name.endswith(".json"):
                rec = self._read(os.path.join(d, name))
                if rec is not None:
                    out.append(rec)
        return out

    def counts(self) -> dict[str, int]:
        return {
            state: sum(
                1 for n in os.listdir(os.path.join(self.root, state))
                if n.endswith(".json"))
            for state in STATES
        }
