"""Durable on-disk job spool for survey scheduling.

One job = one observation: an input filterbank path plus its
``SearchConfig`` overrides, a priority and an attempt count.  Layout
(one JSON record file per job under the spool root)::

    <spool>/pending/<job_id>.json    submitted, claimable
    <spool>/running/<job_id>.json    claimed by a worker
    <spool>/done/<job_id>.json       finished, result summary attached
    <spool>/failed/<job_id>.json     quarantined or retry-exhausted
    <spool>/work/<job_id>/           per-job scratch: checkpoint file,
                                     output directory, failure reports,
                                     lifecycle timeline.jsonl
                                     (obs/timeline.py)
    <spool>/leases/<job_id>.json     claim lease: host + worker +
                                     heartbeat time of the claimer
    <spool>/fleet/<host>.json        per-host status snapshot
                                     (serve/fleet.py)
    <spool>/candidates.jsonl         cross-run candidate store
                                     (serve/store.py default path)
    <spool>/store-<host>.jsonl       per-host store shards in fleet
                                     mode (serve/store.py)

A job changes state by ``os.rename`` of its record file — atomic on
POSIX — so any number of worker processes on one machine can claim
from the same spool with no lock service: exactly one rename wins,
the losers get ``FileNotFoundError`` and try the next candidate.
This is the reference's pthread-mutex trial dispenser
(`pipeline_multi.cu:33-46`) lifted to observation granularity, with
the queue surviving process death.  Record *contents* are always
rewritten in place (tmp + ``os.replace``) BEFORE the state rename, so
a reader never sees a torn or stale record in the new state.

Fleet hardening (multi-HOST spools on a shared filesystem): a claim
additionally stamps the record with the claimer's ``host`` and drops
a lease file that the owner's heartbeat keeps fresh while the job
runs.  A host that dies mid-job stops heartbeating, and ANY surviving
host's :meth:`JobSpool.reap_expired` — run by every fleet worker when
idle — returns the job to ``pending/`` with a ``lease_expired`` entry
appended to its failure log (attempt history intact), generalising
the operator-driven ``requeue`` to automatic dead-host recovery.
``os.rename`` atomicity is the arbiter for reapers exactly as for
claimers, so concurrent reapers converge on one pending record.

Admission control (multi-tenant spools): every record carries a
``tenant`` (legacy records load as :data:`DEFAULT_TENANT`), and an
:class:`AdmissionPolicy` — persisted at ``<spool>/admission.json`` so
submitters, workers and the supervisor share one config — gates
submits with a queue-depth knee plus per-tenant token buckets (typed
:class:`~peasoup_tpu.errors.AdmissionError` on refusal, the job is NOT
enqueued) and orders claims by weighted fair share: within a priority
tier, tenants' FIFOs are interleaved by weighted virtual finish time
(deficit-round-robin equivalent: a weight-2 tenant drains twice as
fast as a weight-1 tenant), so one tenant's million jobs cannot
starve the rest.  A single-tenant tier reduces exactly to the
historical priority-FIFO order.

Crash consistency: with ``durable=True`` (the default; env
``PEASOUP_SPOOL_FSYNC=0`` opts out) record writes fsync the tmp file
before ``os.replace`` and the durability-critical transitions
(submit / claim / done / failed / release / requeue / reap) fsync the
affected state directories after the rename, so a host power-cut
cannot tear a record or lose a rename that a peer already observed.
High-frequency lease heartbeats stay un-fsynced: a lost beat is
recoverable by design (the reaper just sees an older one).
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import asdict, dataclass, field

from ..errors import AdmissionError, ConfigError
from ..obs import timeline
from ..obs.events import warn_event
from ..obs.metrics import REGISTRY as METRICS
from ..utils.atomicio import atomic_write_json, atomic_write_text

#: spool subdirectories, in lifecycle order
STATES = ("pending", "running", "done", "failed")

#: tenant stamped on submits that don't name one; legacy (pre-tenant)
#: records load as this through from_obj's known-field filter
DEFAULT_TENANT = "default"

#: shared admission-policy file under the spool root
ADMISSION_BASENAME = "admission.json"

#: failure-log classification stamped by the lease reaper (alongside
#: serve/retry.py's QUARANTINE / RETRY, which classify exceptions)
LEASE_EXPIRED = "lease_expired"

#: default lease time-to-live; owners heartbeat at ~TTL/3, so a lease
#: only expires after several consecutive missed beats
DEFAULT_LEASE_TTL_S = 120.0

_RECORD_VERSION = 1


@dataclass
class JobRecord:
    """One observation job (the JSON record's in-memory face)."""

    job_id: str
    input: str
    priority: int = 0
    overrides: dict = field(default_factory=dict)
    attempts: int = 0
    submitted_utc: float = 0.0
    claimed_utc: float = 0.0
    finished_utc: float = 0.0
    worker: str = ""
    #: fleet host label of the claimer ("" pre-fleet / single host)
    host: str = ""
    #: one entry per failed attempt: {utc, t_mono, attempt,
    #: classification, error, traceback, run_report}
    failures: list = field(default_factory=list)
    #: submit->claim wait of the LAST claim, from timeline marks when
    #: available (monotonic within a process, wall-clamped across
    #: processes — never negative even across clock steps)
    queue_wait_s: float = 0.0
    #: success summary (candidate counts, outdir) set by mark_done
    summary: dict = field(default_factory=dict)
    #: injection manifest for canary jobs (obs/injection.py, ISSUE 14):
    #: a known synthetic pulsar the worker must recover on completion.
    #: Empty dict = a normal science job; pre-canary records load
    #: unchanged through from_obj's known-field filter
    canary: dict = field(default_factory=dict)
    #: submitting tenant for admission control / fair share; legacy
    #: records (no field in the JSON) load as DEFAULT_TENANT
    tenant: str = DEFAULT_TENANT
    v: int = _RECORD_VERSION

    def to_json(self) -> str:
        return json.dumps(asdict(self), sort_keys=True)

    @classmethod
    def from_obj(cls, obj: dict) -> "JobRecord":
        known = {f for f in cls.__dataclass_fields__}
        return cls(**{k: v for k, v in obj.items() if k in known})


def _new_job_id() -> str:
    """Unique, roughly submit-ordered id (ns timestamp + random tail:
    two submits in the same nanosecond still cannot collide)."""
    return f"{time.time_ns():016x}-{os.urandom(3).hex()}"


@dataclass(frozen=True)
class TenantPolicy:
    """Per-tenant admission knobs.

    ``rate_per_s`` > 0 enables a token bucket: a submit spends one
    token, tokens refill at ``rate_per_s`` up to ``burst`` capacity;
    an empty bucket raises :class:`AdmissionError` with a
    ``retry_after_s`` hint.  ``weight`` sets the tenant's fair share
    of claims within a priority tier (relative to the other tenants'
    weights).  The zero-value policy (rate 0) is unlimited."""

    rate_per_s: float = 0.0
    burst: float = 1.0
    weight: float = 1.0


@dataclass
class AdmissionPolicy:
    """Spool-wide admission config: backlog knee + per-tenant limits.

    ``max_pending`` > 0 rejects every submit (any tenant) while the
    pending backlog is at or past the knee — overload degrades into
    typed, retryable refusals instead of an unbounded spool.
    Persisted at ``<spool>/admission.json`` (see :meth:`save`) so
    submitters, workers and the supervisor share one config; a spool
    with no file runs the permissive default (everything admitted,
    equal weights)."""

    max_pending: int = 0
    tenants: dict = field(default_factory=dict)

    def for_tenant(self, tenant: str) -> TenantPolicy:
        pol = self.tenants.get(str(tenant or DEFAULT_TENANT))
        return pol if pol is not None else TenantPolicy()

    def weight(self, tenant: str) -> float:
        w = float(self.for_tenant(tenant).weight)
        return w if w > 0 else 1.0

    def to_obj(self) -> dict:
        return {
            "v": 1,
            "max_pending": int(self.max_pending),
            "tenants": {name: asdict(pol)
                        for name, pol in sorted(self.tenants.items())},
        }

    @classmethod
    def from_obj(cls, obj: dict) -> "AdmissionPolicy":
        tenants = {}
        for name, pol in (obj.get("tenants") or {}).items():
            known = {f for f in TenantPolicy.__dataclass_fields__}
            tenants[str(name)] = TenantPolicy(
                **{k: v for k, v in dict(pol).items() if k in known})
        return cls(max_pending=int(obj.get("max_pending", 0) or 0),
                   tenants=tenants)

    @classmethod
    def load(cls, root: str) -> "AdmissionPolicy":
        """Policy from ``<root>/admission.json``; missing or corrupt
        reads as the permissive default (admission must never brick
        the spool)."""
        path = os.path.join(root, ADMISSION_BASENAME)
        try:
            with open(path) as f:
                obj = json.load(f)
        except (OSError, ValueError):
            return cls()
        try:
            return cls.from_obj(obj if isinstance(obj, dict) else {})
        except (TypeError, ValueError) as exc:
            warn_event("admission_policy_corrupt",
                       f"unreadable admission policy {path!r}: {exc}",
                       path=path, error=str(exc))
            return cls()

    def save(self, root: str) -> str:
        path = os.path.join(root, ADMISSION_BASENAME)
        atomic_write_json(path, self.to_obj(), sort_keys=True,
                          indent=1, trailing_newline=True)
        return path


class JobSpool:
    """Priority job queue over the directory layout above."""

    def __init__(self, root: str, *,
                 admission: AdmissionPolicy | None = None,
                 durable: bool | None = None, clock=None):
        self.root = os.path.abspath(root)
        for state in STATES:
            os.makedirs(os.path.join(self.root, state), exist_ok=True)
        os.makedirs(os.path.join(self.root, "work"), exist_ok=True)
        os.makedirs(os.path.join(self.root, "leases"), exist_ok=True)
        #: admission policy snapshot (loaded once per JobSpool; CLI
        #: verbs build a fresh spool per invocation, so edits to
        #: admission.json take effect on the next command)
        self.admission = (AdmissionPolicy.load(self.root)
                          if admission is None else admission)
        #: fsync records + state dirs on durability-critical
        #: transitions (env PEASOUP_SPOOL_FSYNC=0 opts out fleet-wide)
        self.durable = (
            os.environ.get("PEASOUP_SPOOL_FSYNC", "1") != "0"
            if durable is None else bool(durable))
        #: injectable wall clock for token buckets (tests)
        self._clock = clock or time.time
        #: per-tenant token buckets: tenant -> (tokens, last_refill).
        #: In-memory per spool instance — rate limiting is a
        #: per-submitter-process courtesy throttle, the shared
        #: max_pending knee is the cross-process backstop.
        self._buckets: dict = {}

    # -- paths -------------------------------------------------------------

    def _path(self, state: str, job_id: str) -> str:
        return os.path.join(self.root, state, f"{job_id}.json")

    def _lease_path(self, job_id: str) -> str:
        return os.path.join(self.root, "leases", f"{job_id}.json")

    def work_dir(self, job_id: str) -> str:
        """Per-job scratch directory (checkpoint, outputs, reports)."""
        d = os.path.join(self.root, "work", job_id)
        os.makedirs(d, exist_ok=True)
        return d

    def _mark(self, rec: JobRecord, phase: str, **attrs) -> None:
        """Best-effort lifecycle mark in the job's timeline
        (obs/timeline.py) — every spool transition leaves one, so the
        ``timeline`` verb can reconstruct the job's waterfall across
        submitter/worker/reaper processes."""
        timeline.mark(
            os.path.join(self.root, "work", rec.job_id), phase,
            host=rec.host, attempt=rec.attempts, **attrs)

    def _observe_queue_wait(self, rec: JobRecord) -> None:
        """Record submit->claim wait, preferring timeline marks: same
        process uses the monotonic clock (exact across wall steps),
        cross-process uses a wall delta clamped at >= 0.  Only the
        pre-timeline fallback still subtracts raw wall stamps."""
        wait = timeline.queue_wait_from(
            os.path.join(self.root, "work", rec.job_id),
            host=rec.host, t_wall=rec.claimed_utc)
        if wait is None:
            wait = max(0.0, rec.claimed_utc - rec.submitted_utc)
        rec.queue_wait_s = round(wait, 6)
        METRICS.observe("queue_wait", wait)

    # -- record I/O --------------------------------------------------------

    def _fsync_dir(self, path: str) -> None:
        """Flush a directory's metadata (the rename itself) to disk.
        Best-effort: some filesystems refuse O_RDONLY dir fsync —
        durability degrades, correctness does not."""
        try:
            fd = os.open(path, os.O_RDONLY)
        except OSError:
            return
        try:
            os.fsync(fd)
        except OSError:
            pass
        finally:
            os.close(fd)

    def _write(self, path: str, rec: JobRecord) -> None:
        atomic_write_text(path, rec.to_json() + "\n",
                          fsync=self.durable)

    def _read(self, path: str) -> JobRecord | None:
        try:
            with open(path) as f:
                obj = json.load(f)
            return JobRecord.from_obj(obj)
        except FileNotFoundError:
            return None
        except (OSError, ValueError, TypeError) as exc:
            warn_event(
                "job_record_corrupt",
                f"unreadable job record {path!r}: {exc}",
                path=path, error=str(exc),
            )
            return None

    # -- submit / claim ----------------------------------------------------

    def _admit(self, tenant: str) -> None:
        """Admission gate for one submit: spool-wide backlog knee
        first, then the tenant's token bucket.  Raises
        :class:`AdmissionError` (the submit never happens) and counts
        ``scheduler.admission_deferred`` / ``..._rejected``."""
        pol = self.admission
        if pol is None:
            return
        knee = int(pol.max_pending or 0)
        if knee > 0:
            backlog = self.counts()["pending"]
            if backlog >= knee:
                METRICS.inc("scheduler.admission_deferred")
                warn_event(
                    "admission_deferred",
                    f"submit deferred for tenant {tenant!r}: pending "
                    f"backlog {backlog} is at the knee ({knee})",
                    tenant=tenant, backlog=backlog, max_pending=knee)
                raise AdmissionError(
                    f"queue backlog {backlog} >= knee {knee}; "
                    f"resubmit after the fleet drains",
                    tenant=tenant, reason="backlog")
        tp = pol.for_tenant(tenant)
        rate = float(tp.rate_per_s or 0.0)
        if rate <= 0:
            return
        cap = max(1.0, float(tp.burst))
        now = float(self._clock())
        tokens, last = self._buckets.get(tenant, (cap, now))
        tokens = min(cap, tokens + max(0.0, now - last) * rate)
        if tokens < 1.0:
            self._buckets[tenant] = (tokens, now)
            retry_after = (1.0 - tokens) / rate
            METRICS.inc("scheduler.admission_rejected")
            warn_event(
                "admission_rejected",
                f"submit rejected for tenant {tenant!r}: token bucket "
                f"empty (rate {rate:g}/s, burst {cap:g}); retry in "
                f"{retry_after:.2f}s",
                tenant=tenant, rate_per_s=rate, burst=cap,
                retry_after_s=round(retry_after, 3))
            raise AdmissionError(
                f"tenant {tenant!r} over rate limit "
                f"({rate:g} submits/s, burst {cap:g})",
                tenant=tenant, reason="rate_limit",
                retry_after_s=retry_after)
        self._buckets[tenant] = (tokens - 1.0, now)

    def submit(self, input_path: str, overrides: dict | None = None,
               priority: int = 0, canary: dict | None = None,
               tenant: str = DEFAULT_TENANT) -> JobRecord:
        """Enqueue one observation; returns the pending record.

        ``canary``: injection manifest dict for a known-answer canary
        job — the worker matches the result against it on completion
        and the store tags its candidates out of science queries.
        ``tenant``: accounting identity for admission control and
        fair-share claims; may raise :class:`AdmissionError` when the
        spool's policy refuses the submit (job NOT enqueued).
        """
        tenant = str(tenant or DEFAULT_TENANT)
        self._admit(tenant)
        rec = JobRecord(
            job_id=_new_job_id(),
            input=os.path.abspath(input_path),
            priority=int(priority),
            overrides=dict(overrides or {}),
            canary=dict(canary or {}),
            tenant=tenant,
            submitted_utc=time.time(),
        )
        self._write(self._path("pending", rec.job_id), rec)
        if self.durable:
            self._fsync_dir(os.path.join(self.root, "pending"))
        self._mark(rec, "submit", t_wall=rec.submitted_utc,
                   priority=rec.priority, tenant=tenant)
        METRICS.inc("scheduler.submitted")
        return rec

    def pending_jobs(self) -> list[JobRecord]:
        """Claimable jobs, best-first: priority descending, then
        submit time (FIFO within a priority band)."""
        out = []
        pend = os.path.join(self.root, "pending")
        for name in os.listdir(pend):
            if not name.endswith(".json"):
                continue
            rec = self._read(os.path.join(pend, name))
            if rec is not None:
                out.append(rec)
        out.sort(key=lambda r: (-r.priority, r.submitted_utc, r.job_id))
        return out

    def claim_order(self) -> list[JobRecord]:
        """Pending jobs in fair-share claim order.

        Priority tiers stay strict (a higher tier always drains
        first).  WITHIN a tier, each tenant's jobs form a FIFO and the
        FIFOs are interleaved by weighted virtual finish time — job
        index ``i`` (0-based) of a weight-``w`` tenant is ranked at
        ``(inflight + i + 1) / w``, ties broken by submit time, where
        ``inflight`` is the tenant's current running-job count.  The
        inflight anchor makes the order the stateless equivalent of
        deficit round-robin ACROSS consecutive claims, not just within
        one snapshot: each claim a tenant wins raises its next job's
        virtual time, so a weight-2 tenant receives two claims for
        every one a weight-1 tenant gets, and every tenant with
        pending work is served within one full round (starvation-free)
        instead of the heaviest tenant re-winning a freshly recomputed
        rank on every claim.  A single-tenant tier reduces exactly to
        the historical priority-FIFO order.
        """
        jobs = self.pending_jobs()
        pol = self.admission
        out: list[JobRecord] = []
        tier: list[JobRecord] = []
        inflight: dict | None = None

        def _inflight(name: str) -> int:
            nonlocal inflight
            if inflight is None:
                inflight = {}
                for r in self.jobs("running"):
                    t = r.tenant or DEFAULT_TENANT
                    inflight[t] = inflight.get(t, 0) + 1
            return inflight.get(name, 0)

        def _flush() -> None:
            if not tier:
                return
            tenants: dict = {}
            for r in tier:
                tenants.setdefault(r.tenant or DEFAULT_TENANT,
                                   []).append(r)
            if len(tenants) == 1:
                out.extend(tier)
            else:
                keyed = []
                for name, recs in tenants.items():
                    w = pol.weight(name) if pol is not None else 1.0
                    base = _inflight(name)
                    for i, r in enumerate(recs):
                        keyed.append(((base + i + 1) / w,
                                      r.submitted_utc, r.job_id, r))
                keyed.sort(key=lambda kv: kv[:3])
                out.extend(r for _, _, _, r in keyed)
            tier.clear()

        prio = None
        for r in jobs:
            if prio is not None and r.priority != prio:
                _flush()
            prio = r.priority
            tier.append(r)
        _flush()
        return out

    def peek(self) -> JobRecord | None:
        """Next claimable job WITHOUT claiming it (the worker's
        prefetch hint; another worker may still win the claim)."""
        jobs = self.claim_order()
        return jobs[0] if jobs else None

    def claim(self, worker: str = "", host: str = "") -> JobRecord | None:
        """Claim the next job in fair-share order via atomic rename,
        or None.

        Safe against concurrent claimers — on one machine or across
        hosts sharing the spool filesystem: the rename is the arbiter,
        a lost race just moves on to the next candidate.  The winner's
        record carries ``worker`` and ``host``, and a lease file is
        dropped for the reaper (kept fresh via :meth:`heartbeat`).
        """
        for rec in self.claim_order():
            src = self._path("pending", rec.job_id)
            dst = self._path("running", rec.job_id)
            try:
                os.rename(src, dst)
            except FileNotFoundError:
                continue  # another worker won this one
            rec.worker = worker
            rec.host = host
            rec.claimed_utc = time.time()
            rec.attempts += 1
            self._observe_queue_wait(rec)
            self._write(dst, rec)
            if self.durable:
                self._fsync_dir(os.path.join(self.root, "pending"))
                self._fsync_dir(os.path.join(self.root, "running"))
            self.heartbeat(rec)
            self._mark(rec, "claim", t_wall=rec.claimed_utc,
                       worker=worker)
            METRICS.inc("scheduler.claimed")
            return rec
        return None

    def claim_job(self, job_id: str, worker: str = "",
                  host: str = "") -> JobRecord | None:
        """Claim one SPECIFIC pending job, or None (gone / lost race).

        The batched worker uses this to pull same-geometry batch-mates
        out of queue order once it holds a leader job: the same atomic
        pending->running rename arbitrates against concurrent
        claimers, so a lost race simply means a smaller batch.
        """
        src = self._path("pending", job_id)
        rec = self._read(src)
        if rec is None:
            return None
        dst = self._path("running", job_id)
        try:
            os.rename(src, dst)
        except FileNotFoundError:
            return None  # another worker won this one
        rec.worker = worker
        rec.host = host
        rec.claimed_utc = time.time()
        rec.attempts += 1
        self._observe_queue_wait(rec)
        self._write(dst, rec)
        if self.durable:
            self._fsync_dir(os.path.join(self.root, "pending"))
            self._fsync_dir(os.path.join(self.root, "running"))
        self.heartbeat(rec)
        self._mark(rec, "claim", t_wall=rec.claimed_utc,
                   worker=worker)
        METRICS.inc("scheduler.claimed")
        return rec

    # -- leases (fleet hardening) ------------------------------------------

    def heartbeat(self, rec: JobRecord) -> None:
        """Refresh the claimer's lease on a running job (atomic
        rewrite).  Written on claim and then every ~TTL/3 by the
        owner's heartbeat thread (serve/fleet.py LeaseHeartbeat), so a
        fresh lease means the owning host is demonstrably alive."""
        # deliberately never fsynced: rename atomicity alone is the
        # lease contract, and this runs every ~TTL/3 per running job
        atomic_write_json(self._lease_path(rec.job_id), {
            "v": 1,
            "job_id": rec.job_id,
            "worker": rec.worker,
            "host": rec.host,
            "attempt": rec.attempts,
            "utc": round(time.time(), 3),
        })

    def lease_info(self, job_id: str) -> dict | None:
        """The job's lease record, or None (missing/corrupt — a torn
        lease reads as 'no heartbeat', never as an error)."""
        try:
            with open(self._lease_path(job_id)) as f:
                obj = json.load(f)
            return obj if isinstance(obj, dict) else None
        except (OSError, ValueError):
            return None

    def _clear_lease(self, job_id: str) -> None:
        try:
            os.remove(self._lease_path(job_id))
        except OSError:
            pass

    def reap_expired(self, ttl_s: float = DEFAULT_LEASE_TTL_S,
                     now: float | None = None) -> list[JobRecord]:
        """Return every running job whose lease went stale to
        ``pending/`` — automatic dead-host recovery.

        A job is reaped when its last heartbeat (falling back to the
        claim time for pre-lease records) is more than ``ttl_s``
        seconds old.  The reaped record keeps its attempt count and
        gains a :data:`LEASE_EXPIRED` entry in the failure log, so the
        next claimer sees the full history; the worker/host stamps are
        cleared.  Concurrent reapers race on the running->pending
        rename exactly like claimers race on pending->running: losers
        skip.  ``now`` is injectable for tests.
        """
        now = time.time() if now is None else float(now)
        reaped = []
        for rec in self.jobs("running"):
            lease = self.lease_info(rec.job_id)
            beat = (lease or {}).get("utc") or rec.claimed_utc \
                or rec.submitted_utc
            age = now - float(beat)
            if age <= float(ttl_s):
                continue
            dead_host = rec.host or (lease or {}).get("host") or "?"
            rec.failures.append({
                "utc": round(now, 3),
                "t_mono": round(time.perf_counter(), 6),
                "attempt": rec.attempts,
                "classification": LEASE_EXPIRED,
                "error": (f"lease expired after {age:.1f}s "
                          f"(ttl {float(ttl_s):.1f}s; last owner "
                          f"{rec.worker or '?'} on host {dead_host})"),
            })
            rec.worker = ""
            rec.host = ""
            try:
                self._transition(rec, "running", "pending")
            except (ConfigError, OSError):
                continue  # another reaper won this one
            self._clear_lease(rec.job_id)
            self._mark(rec, "reap", dead_host=dead_host)
            warn_event(
                "job_lease_expired",
                f"job {rec.job_id} reaped after {age:.1f}s without a "
                f"heartbeat from host {dead_host}; re-queued with "
                f"attempt history intact",
                job_id=rec.job_id, host=dead_host, age_s=round(age, 1),
                ttl_s=float(ttl_s), attempt=rec.attempts,
            )
            METRICS.inc("scheduler.lease_reaped")
            reaped.append(rec)
        return reaped

    # -- state transitions (record rewritten BEFORE the rename) ------------

    def _transition(self, rec: JobRecord, src_state: str,
                    dst_state: str) -> None:
        src = self._path(src_state, rec.job_id)
        if not os.path.exists(src):
            raise ConfigError(
                f"job {rec.job_id} is not in {src_state}/ (spool "
                f"{self.root})")
        self._write(src, rec)
        os.rename(src, self._path(dst_state, rec.job_id))
        if self.durable:
            self._fsync_dir(os.path.join(self.root, src_state))
            self._fsync_dir(os.path.join(self.root, dst_state))

    def update(self, rec: JobRecord, state: str = "running") -> None:
        """Rewrite a record in place (attempt metadata, failure log)."""
        self._write(self._path(state, rec.job_id), rec)

    def mark_done(self, rec: JobRecord, summary: dict | None = None) -> None:
        rec.finished_utc = time.time()
        if summary:
            rec.summary = dict(summary)
        self._transition(rec, "running", "done")
        self._clear_lease(rec.job_id)
        self._mark(rec, "done", t_wall=rec.finished_utc)

    def mark_failed(self, rec: JobRecord) -> None:
        """running -> failed (the failure log on the record says why:
        quarantined input vs exhausted retries)."""
        rec.finished_utc = time.time()
        self._transition(rec, "running", "failed")
        self._clear_lease(rec.job_id)
        self._mark(rec, "failed", t_wall=rec.finished_utc)

    def release(self, rec: JobRecord) -> None:
        """running -> pending for a bounded retry (attempt count and
        failure log travel with the record)."""
        self._transition(rec, "running", "pending")
        self._clear_lease(rec.job_id)
        self._mark(rec, "release")

    def requeue(self, job_id: str) -> JobRecord:
        """Recover a job from ``running/`` (crashed worker) or
        ``failed/`` (operator retry) back to ``pending/``."""
        for state in ("running", "failed"):
            path = self._path(state, job_id)
            rec = self._read(path)
            if rec is not None:
                rec.worker = ""
                rec.host = ""
                self._transition(rec, state, "pending")
                self._clear_lease(rec.job_id)
                self._mark(rec, "requeue", from_state=state)
                METRICS.inc("scheduler.requeued")
                return rec
        raise ConfigError(
            f"job {job_id!r} not found in running/ or failed/ "
            f"(spool {self.root})")

    # -- inspection --------------------------------------------------------

    def get(self, job_id: str) -> tuple[str, JobRecord] | None:
        for state in STATES:
            rec = self._read(self._path(state, job_id))
            if rec is not None:
                return state, rec
        return None

    def jobs(self, state: str) -> list[JobRecord]:
        if state not in STATES:
            raise ConfigError(
                f"unknown spool state {state!r}; use one of {STATES}")
        d = os.path.join(self.root, state)
        out = []
        for name in sorted(os.listdir(d)):
            if name.endswith(".json"):
                rec = self._read(os.path.join(d, name))
                if rec is not None:
                    out.append(rec)
        return out

    def counts(self) -> dict[str, int]:
        return {
            state: sum(
                1 for n in os.listdir(os.path.join(self.root, state))
                if n.endswith(".json"))
            for state in STATES
        }

    def tenant_counts(self) -> dict[str, dict[str, int]]:
        """Per-tenant state counts (reads every record — an
        inspection/CLI surface, not a hot path)."""
        out: dict[str, dict[str, int]] = {}
        for state in STATES:
            for rec in self.jobs(state):
                name = rec.tenant or DEFAULT_TENANT
                per = out.setdefault(name,
                                     {s: 0 for s in STATES})
                per[state] += 1
        return out
